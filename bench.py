"""Benchmark: multi-phase Louvain TEPS on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric follows the reference's TEPS accounting (main.cpp:448, :509):
    TEPS = sum over phases (phase_edges * phase_iterations) / clustering time
i.e. traversed-edges-per-second across the whole clustering run.

Baseline (BASELINE.json): >= 1B edges/sec aggregate on a v5p-64, i.e.
15.625M edges/sec/chip.  vs_baseline = value / 15.625e6.

Env knobs: BENCH_SCALE (R-MAT scale; default 20 on the TPU chip, 18 on the
cpu fallback), BENCH_EF (edge factor, default 16), BENCH_GRAPH=rmat|rgg,
BENCH_REPEATS (steady-state timed runs, default 3; value = best-of-N).
The JSON line also carries "platform" and "scale" so a cpu-fallback number
can never be misattributed to TPU hardware, plus per-run TEPS, spread, and
loadavg samples so a contended run (1-core host) is visible in the record.
"""

import json
import os
import sys
import time

_T_PROC = time.perf_counter()  # budget accounting starts at process start

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_EDGES_PER_SEC_PER_CHIP = 1.0e9 / 64.0

# Persistent XLA compilation cache (opt out with CUVITE_NO_COMPILE_CACHE=1).
from cuvite_tpu.utils.compile_cache import enable_compile_cache

enable_compile_cache()


def _init_backend(max_tries: int = 2, timeout_s: int = 75) -> str:
    """Decide which jax backend this process will use, with a hang guard.

    The axon TPU plugin's backend init is flaky in this image: it can raise
    (RuntimeError: Unable to initialize backend 'axon') or hang outright
    inside a native call (where SIGALRM-based timeouts never fire).  The
    probe therefore runs in a SUBPROCESS with a hard timeout; only when it
    proves the default backend healthy does this process touch it.  After
    exhausting retries, fall back to the cpu backend so the bench always
    emits a numeric result (the JSON line then carries "platform": "cpu" so
    the number cannot be misattributed to TPU hardware).
    """
    import subprocess

    import jax

    # The probe must report the backend's REGISTRY name (e.g. 'axon' for
    # the TPU tunnel plugin), not Device.platform (which says 'tpu'):
    # jax_platforms is matched against registry names, and pinning 'tpu'
    # would select the built-in libtpu plugin that has no device here.
    probe = ("import jax; from jax._src import xla_bridge as xb; "
             "d = jax.devices(); "
             "n = [k for k, b in xb.backends().items() if b is d[0].client]; "
             "print(n[0] if n else d[0].platform, len(d))")
    for attempt in range(1, max_tries + 1):
        try:
            out = subprocess.run(
                [sys.executable, "-c", probe],
                capture_output=True, text=True, timeout=timeout_s,
            )
            if out.returncode == 0 and out.stdout.strip():
                plat, n = out.stdout.split()
                print(f"# backend: {plat} x{n} (probe attempt {attempt})",
                      file=sys.stderr)
                # Pin the parent to exactly what the probe proved healthy:
                # without this, a child whose default-backend init raised and
                # fell back to cpu would report "cpu" while the parent still
                # tries (and possibly hangs on) the default TPU plugin.
                jax.config.update("jax_platforms", plat)
                return plat
            err = (out.stderr or "").strip().splitlines()
            print(f"# backend probe attempt {attempt}/{max_tries} failed "
                  f"(rc={out.returncode}): {err[-1] if err else '?'}",
                  file=sys.stderr)
        except subprocess.TimeoutExpired:
            print(f"# backend probe attempt {attempt}/{max_tries} hung "
                  f">{timeout_s}s, killed", file=sys.stderr)
        if attempt < max_tries:
            time.sleep(3 * attempt)
    print("# WARNING: default (TPU) backend unavailable after retries; "
          "falling back to cpu", file=sys.stderr)
    jax.config.update("jax_platforms", "cpu")
    return jax.devices()[0].platform


def main():
    platform = _init_backend()
    # The real chip's platform name is "axon" (TPU v5 lite plugin), not
    # "tpu": treat anything that isn't the cpu fallback as TPU-class.
    # The cpu-fallback scale matches the scale every recorded CPU number
    # and the persistent compile cache were built at (README benchmarks).
    default_scale = "18" if platform == "cpu" else "20"
    scale = int(os.environ.get("BENCH_SCALE", default_scale))
    ef = int(os.environ.get("BENCH_EF", "16"))
    kind = os.environ.get("BENCH_GRAPH", "rmat")
    engine = os.environ.get("BENCH_ENGINE", "auto")

    from cuvite_tpu.io.generate import generate_rgg, generate_rmat
    from cuvite_tpu.louvain.driver import louvain_phases

    t0 = time.perf_counter()
    if kind == "rgg":
        graph = generate_rgg(1 << scale, seed=1)
    else:
        graph = generate_rmat(scale, edge_factor=ef, seed=1)
    gen_s = time.perf_counter() - t0
    print(f"# graph: {kind} scale={scale} nv={graph.num_vertices} "
          f"ne={graph.num_edges} gen={gen_s:.1f}s", file=sys.stderr)

    # Warm-up: a full multi-phase run on the same graph.  The run is
    # deterministic, so every coarsened phase of the timed run hits the
    # in-memory jit cache and TEPS measures steady-state execution, not
    # XLA compilation (the reference likewise excludes one-time costs from
    # its clustering-time metric, main.cpp:499-518).
    #
    # Wall-clock budget (BENCH_TIME_BUDGET seconds, default 420): the
    # harness running this script enforces its own timeout, and a killed
    # bench reports NOTHING.  If the warm-up (which eats all compilation)
    # already used too much of the budget, report the warm-up's own TEPS —
    # compile-included, flagged as such — instead of risking the timed run
    # being killed mid-flight.
    budget_s = float(os.environ.get("BENCH_TIME_BUDGET", "420"))
    t1 = time.perf_counter()
    res = louvain_phases(graph, engine=engine)
    warm_wall = time.perf_counter() - t1
    # Elapsed since PROCESS start: backend probes against a wedged TPU
    # tunnel can eat 150s before main() even begins, and the external
    # timeout covers all of it.
    elapsed = time.perf_counter() - _T_PROC

    def one_teps(res, wall):
        traversed = sum(p.num_edges * p.iterations for p in res.phases)
        clustering_s = sum(p.seconds for p in res.phases) or wall
        return traversed / clustering_s, clustering_s

    def loadavg():
        try:
            with open("/proc/loadavg") as f:
                return float(f.read().split()[0])
        except OSError:  # non-Linux
            return -1.0

    def emit(res, wall, compile_included, all_teps=(), load=()):
        teps, clustering_s = one_teps(res, wall)
        best = max((teps, *all_teps))
        print(f"# Q={res.modularity:.5f} phases={len(res.phases)} "
              f"iters={res.total_iterations} clustering={clustering_s:.2f}s "
              f"wall={wall:.2f}s compile_included={compile_included}",
              file=sys.stderr)
        out = {
            "metric": "louvain_teps_per_chip",
            "value": round(best, 1),
            "unit": "traversed_edges/sec",
            "vs_baseline": round(best / BASELINE_EDGES_PER_SEC_PER_CHIP, 4),
            "platform": platform,
            "scale": scale,
        }
        if compile_included:
            out["compile_included"] = True
        if all_teps:
            # Contention telemetry (1-core host: any concurrent work halves
            # a timed run).  value is best-of-N steady-state; the full list
            # + loadavg samples let a reader spot a contended run at sight.
            out["runs"] = len(all_teps)
            out["teps_runs"] = [round(t, 1) for t in all_teps]
            out["spread"] = round(max(all_teps) / min(all_teps), 3)
        if load:
            out["loadavg"] = [round(x, 2) for x in load]
        print(json.dumps(out))

    if elapsed + 1.5 * warm_wall > budget_s:
        print(f"# budget: {elapsed:.0f}s elapsed of {budget_s:.0f}s — "
              f"skipping the steady-state rerun", file=sys.stderr)
        emit(res, warm_wall, compile_included=True, load=[loadavg()])
        return
    del res  # free the warm-up labels (O(nv)) before the timed run

    # Steady-state best-of-N (default 3, budget-bounded): on a 1-core host
    # a single timed run is hostage to whatever else the machine is doing;
    # best-of-N + the per-run list + loadavg samples make the number
    # reproducible across driver/builder invocations (VERDICT r3 weak #1:
    # a 23% driver-vs-builder discrepancy from exactly this).
    max_runs = max(1, int(os.environ.get("BENCH_REPEATS", "3")))
    all_teps, loads = [], [loadavg()]
    last_res, last_wall = None, warm_wall
    while len(all_teps) < max_runs:
        elapsed = time.perf_counter() - _T_PROC
        if all_teps and elapsed + 1.2 * last_wall > budget_s:
            print(f"# budget: stopping after {len(all_teps)} timed runs "
                  f"({elapsed:.0f}s of {budget_s:.0f}s)", file=sys.stderr)
            break
        t1 = time.perf_counter()
        last_res = louvain_phases(graph, engine=engine, verbose=False)
        last_wall = time.perf_counter() - t1
        teps, _ = one_teps(last_res, last_wall)
        all_teps.append(teps)
        loads.append(loadavg())
        print(f"# run {len(all_teps)}: {teps/1e6:.2f}M TEPS "
              f"(wall {last_wall:.1f}s, load {loads[-1]:.2f})",
              file=sys.stderr)
    emit(last_res, last_wall, compile_included=False,
         all_teps=all_teps, load=loads)


if __name__ == "__main__":
    main()
