"""Benchmark entry point: multi-phase Louvain TEPS on one chip.

The harness logic lives in cuvite_tpu.workloads.bench (warm-up,
compile-count==0 guard on the first timed run, best-of-N, budget
handling, shared JSON schema); this shim keeps the historical
`python bench.py` invocation and BENCH_* env knobs working for the
driver and the TPU ladder.  Prints ONE JSON line on success; exits 3
WITHOUT a JSON when the compile guard trips.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Persistent XLA compilation cache (opt out with CUVITE_NO_COMPILE_CACHE=1).
from cuvite_tpu.utils.compile_cache import enable_compile_cache

enable_compile_cache()

from cuvite_tpu.workloads.bench import main

if __name__ == "__main__":
    sys.exit(main())
