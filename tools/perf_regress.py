#!/usr/bin/env python
"""Perf-regression gate over the BENCH_*.json trajectory (ISSUE 6).

The repo's bench trajectory is the checked-in ``BENCH_r*.json`` round
logs: ``{"n": <round>, "cmd": ..., "rc": ..., "tail": ..., "parsed":
<bench record or null>}``.  Early rounds parsed minimal records; round 6+
records are schema-v4 self-describing (``schema`` field, enforced by
``cuvite_tpu.workloads.bench.validate_record``).  This tool turns that
trajectory into a gate:

    # compare a fresh bench record against the trajectory
    python tools/perf_regress.py --record fresh.json [--threshold 0.30]

    # structural self-check of every checked-in round log (tier-1,
    # tests/test_obs.py): a malformed record can never land silently
    python tools/perf_regress.py --self-check

Comparison model: the fresh record is matched against trajectory records
of the SAME platform (and scale, when both carry one).  The gate trips
(exit 1) when the fresh TEPS falls more than ``--threshold`` below the
trajectory best, or any canonical stage time (coarsen_s/upload_s/
iterate_s) grows more than ``--threshold`` over the most recent
comparable record that carries stages — wall-noise floors exempt stages
under ``--stage-floor-s`` (default 0.5 s).  Exit codes: 0 ok, 1
regression, 2 usage/parse error.

Stdlib-only (no jax import): the tier-1 self-check must stay cheap, and
a gate that needs a healthy accelerator to *parse JSON* would be useless
exactly when a broken image is the thing being caught.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:  # validate_v4's lazy cuvite_tpu import
    sys.path.insert(0, REPO_ROOT)

TEPS_METRIC = "louvain_teps_per_chip"
# coalesce_s (ISSUE 8) is the device relabel+coalesce slice nested
# inside coarsen_s — gating it separately catches a sort-tax regression
# that a constant-ish coarsen_s total would mask.  rebin_s (ISSUE 19)
# is the device plan re-bin of coarse bucketed phases, nested inside
# plan_s the same way.
STAGE_KEYS = ("coarsen_s", "coalesce_s", "rebin_s", "upload_s",
              "iterate_s")


def load_trajectory(pattern: str) -> list:
    """(path, round, record) for every round log whose ``parsed`` field
    holds a bench record; raises ValueError on a structurally malformed
    round log (the self-check's failure signal)."""
    out = []
    paths = sorted(glob.glob(pattern))
    if not paths:
        raise ValueError(f"no round logs match {pattern!r}")
    for path in paths:
        with open(path, encoding="utf-8") as f:
            try:
                log = json.load(f)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}: not valid JSON: {e}") from e
        for key in ("n", "cmd", "rc"):
            if key not in log:
                raise ValueError(f"{path}: round log missing {key!r}")
        rec = log.get("parsed")
        if rec is None:
            continue
        if not isinstance(rec, dict):
            raise ValueError(f"{path}: parsed must be a record or null")
        for key in ("metric", "value", "unit"):
            if key not in rec:
                raise ValueError(f"{path}: parsed record missing {key!r}")
        if rec["metric"] == TEPS_METRIC and not (
                isinstance(rec["value"], (int, float)) and rec["value"] > 0):
            raise ValueError(
                f"{path}: non-positive TEPS value {rec['value']!r}")
        out.append((path, log["n"], rec))
    return out


def validate_v4(path: str, rec: dict) -> list:
    """Full schema validation for self-describing (v4+) records; pre-v4
    trajectory records predate the schema field and get the structural
    checks in load_trajectory only."""
    if not isinstance(rec.get("schema"), int):
        return []
    from cuvite_tpu.workloads.bench import validate_record

    return [f"{path}: {p}" for p in validate_record(rec)]


def comparable(fresh: dict, rec: dict) -> bool:
    if rec.get("platform") != fresh.get("platform"):
        return False
    if ("scale" in fresh and "scale" in rec
            and fresh["scale"] != rec["scale"]):
        return False
    # Different input graphs / engines have different intrinsic TEPS —
    # only gate like against like.  Pre-v4 trajectory rounds carry no
    # 'graph' or 'engine' (all rmat, default engine), so each check
    # engages only when both sides are identified.
    if ("graph" in fresh and "graph" in rec
            and fresh["graph"] != rec["graph"]):
        return False
    if ("engine" in fresh and "engine" in rec
            and fresh["engine"] != rec["engine"]):
        return False
    # Batched serving records (ISSUE 9) gate like-for-like only: same
    # padded batch size AND same slab class — jobs/sec at B=64 on the
    # (4096, 16384) class says nothing about B=8 or a bigger class —
    # AND same batched engine (ISSUE 10): the bucketed trajectory runs
    # several-x above the fused one by design, so letting them gate
    # each other would either mask a bucketed regression behind the
    # fused floor or flag every fused record against the bucketed best.
    fb, rb = fresh.get("batch"), rec.get("batch")
    if (fb is None) != (rb is None):
        return False
    if fb is not None:
        if fb.get("B") != rb.get("B"):
            return False
        if fb.get("class") != rb.get("class"):
            return False
        # Pre-ISSUE-10 batch records carry no engine tag, but every one
        # of them ran the fused loop (the only engine that existed) —
        # defaulting the missing side keeps the fused trajectory gating
        # fresh fused records instead of silently resetting to "no
        # comparable peers".
        if (fb.get("engine") or "fused") != (rb.get("engine") or "fused"):
            return False
    # Open-loop serving records (ISSUE 11) gate like-for-like only:
    # same batch cap, same admission arm (on/off are DIFFERENT
    # experiments — the off arm exists to show unbounded wait growth),
    # same SLO, same job shape, same engine, and — since ISSUE 14 —
    # same dispatcher architecture: serial and pipelined serve records
    # never gate each other (the pipelined goodput sits above the
    # serial one BY DESIGN, so mixing them would either mask a
    # pipeline regression behind the serial floor or flag every serial
    # record against the pipelined best).  A record with no
    # `pipelined` tag predates ISSUE 14 and ran the serial dispatcher
    # — default it so the historical trajectory keeps gating fresh
    # serial records.  Arrival rate is NOT matched: each round offers
    # its own (saturation-derived) rate and goodput is the gated
    # capacity number.
    fs, rs = fresh.get("serve"), rec.get("serve")
    if (fs is None) != (rs is None):
        return False
    if fs is not None:
        for k in ("b_max", "admission", "slo_ms", "edges_each", "engine"):
            if fs.get(k) != rs.get(k):
                return False
        if bool(fs.get("pipelined", False)) != bool(rs.get("pipelined",
                                                           False)):
            return False
        # Sub-row merge packing (ISSUE 20): the packed arm's goodput
        # sits above the per-class-queue arm's BY DESIGN on a skewed
        # mix — the two are different experiments, never peers.  A
        # record with no tag predates ISSUE 20 and ran per-class.
        if bool(fs.get("merge_packing", False)) != bool(
                rs.get("merge_packing", False)):
            return False
    # Skewed-mix records (ISSUE 20) gate like-for-like only: same A/B
    # arm (merge_packing, already pinned on the serve block above),
    # same small:big ratio and the same class pair — a 90:10 mix's
    # small-class wait profile says nothing about 50:50, and a mix
    # record never compares against a single-class serve record.
    fm, rm = fresh.get("mix"), rec.get("mix")
    if (fm is None) != (rm is None):
        return False
    if fm is not None:
        for k in ("ratio", "small_class", "big_class"):
            if fm.get(k) != rm.get(k):
                return False
    # Streaming churn records (ISSUE 17) gate like-for-like only: a
    # stream record never compares against a batch/serve/plain-TEPS
    # record (its cold arm re-clusters a resident slab, not the bench's
    # graph pipeline), and within the stream trajectory the warm arm
    # and the churn size must match — the 'labels' speedup sits above
    # 'plp' by design, and a 10% churn's frontier dwarfs a 1% one's.
    ft, rt = fresh.get("stream"), rec.get("stream")
    if (ft is None) != (rt is None):
        return False
    if ft is not None:
        for k in ("warm", "churn_frac"):
            if ft.get(k) != rt.get(k):
                return False
    # Exchange arms (ISSUE 18): a two-level record never gates a flat
    # one (or vice versa) — shrinking the per-chip table window by
    # |dcn| changes the exchange cost model, not just a constant — and
    # within the two-level arm the (dcn, ici) factorization must match
    # (2x4 and 4x2 pay different ICI/DCN splits by design).  A record
    # with no `exchange` block predates ISSUE 18 or ran single-shard;
    # it compares only against other block-less records.
    fx, rx = fresh.get("exchange"), rec.get("exchange")
    if (fx is None) != (rx is None):
        return False
    if fx is not None:
        if fx.get("mode") != rx.get("mode"):
            return False
        if fx.get("mode") == "twolevel":
            for k in ("dcn", "ici"):
                if fx.get(k) != rx.get(k):
                    return False
    # Re-bin arms (ISSUE 19): a device-rebin record (rebin_device > 0)
    # never gates a host-rebin one or vice versa — the device arm moves
    # per-phase plan cost from host BucketPlan.build + upload into
    # rebin_s by design, so cross-arm stage deltas are architecture,
    # not regression.  Records predating the field (or non-bucketed
    # engines, which never re-bin) compare only against each other.
    frd, rrd = fresh.get("rebin_device"), rec.get("rebin_device")
    if (frd is not None and frd > 0) != (rrd is not None and rrd > 0):
        return False
    return True


def check_regression(fresh: dict, trajectory: list, threshold: float,
                     stage_floor_s: float = 0.5) -> list:
    """Regression strings (empty = gate passes) for a fresh record vs
    the trajectory."""
    problems = []
    if fresh.get("metric") != TEPS_METRIC:
        return [f"fresh record has metric {fresh.get('metric')!r}, "
                f"expected {TEPS_METRIC!r}"]
    peers = [(n, rec) for _, n, rec in trajectory
             if rec.get("metric") == TEPS_METRIC and comparable(fresh, rec)]
    if not peers:
        # Nothing comparable (new platform/scale): first record of a new
        # config is a baseline, not a regression.
        return []
    # Open-loop serve records are exempt from the top-level TEPS gate:
    # below saturation the wall is dominated by arrival pacing
    # (n_jobs/rate), so value scales with the OFFERED rate — which
    # comparable() deliberately does not match (each round offers its
    # own saturation-derived rate).  Their capacity gate is the
    # saturated-goodput check below.
    if not isinstance(fresh.get("serve"), dict):
        best_n, best = max(peers, key=lambda p: p[1]["value"])
        floor = best["value"] * (1.0 - threshold)
        if fresh["value"] < floor:
            problems.append(
                f"TEPS {fresh['value']:.3g} is "
                f"{1.0 - fresh['value'] / best['value']:.0%} below the "
                f"trajectory best {best['value']:.3g} (round {best_n}); "
                f"gate allows {threshold:.0%}")
    # Serving-throughput gate (ISSUE 9): jobs_per_s of a batched record
    # against the best comparable batched record (comparable() already
    # pinned class and B).
    if isinstance(fresh.get("batch"), dict):
        bpeers = [(n, rec) for n, rec in peers
                  if isinstance(rec.get("batch"), dict)
                  and isinstance(rec["batch"].get("jobs_per_s"),
                                 (int, float))]
        if bpeers and isinstance(fresh["batch"].get("jobs_per_s"),
                                 (int, float)):
            bn, bbest = max(bpeers,
                            key=lambda p: p[1]["batch"]["jobs_per_s"])
            old_jps = bbest["batch"]["jobs_per_s"]
            new_jps = fresh["batch"]["jobs_per_s"]
            if new_jps < old_jps * (1.0 - threshold):
                problems.append(
                    f"batch jobs_per_s {new_jps:.3g} is "
                    f"{1.0 - new_jps / old_jps:.0%} below the trajectory "
                    f"best {old_jps:.3g} (round {bn}, B="
                    f"{fresh['batch'].get('B')}); gate allows "
                    f"{threshold:.0%}")
    # Serving-goodput gate (ISSUE 11): goodput of an open-loop serve
    # record against the best comparable one (comparable() already
    # pinned b_max, admission arm, SLO, job shape and engine).  Only
    # SATURATED runs gate: below saturation goodput tracks the offered
    # rate, not the server's capacity — a conservative low-rate run
    # must not trip against a saturated round's number (and cannot
    # prove a regression either way).
    def _saturated(s) -> bool:
        gp, ar = s.get("goodput_jobs_per_s"), s.get("arrival_jobs_per_s")
        if not isinstance(gp, (int, float)) \
                or not isinstance(ar, (int, float)):
            return False
        return gp < 0.9 * ar

    if isinstance(fresh.get("serve"), dict) and _saturated(fresh["serve"]):
        speers = [(n, rec) for n, rec in peers
                  if isinstance(rec.get("serve"), dict)
                  and _saturated(rec["serve"])
                  and isinstance(rec["serve"].get("goodput_jobs_per_s"),
                                 (int, float))]
        if speers and isinstance(fresh["serve"].get("goodput_jobs_per_s"),
                                 (int, float)):
            sn, sbest = max(
                speers, key=lambda p: p[1]["serve"]["goodput_jobs_per_s"])
            old_gp = sbest["serve"]["goodput_jobs_per_s"]
            new_gp = fresh["serve"]["goodput_jobs_per_s"]
            if new_gp < old_gp * (1.0 - threshold):
                problems.append(
                    f"serve goodput_jobs_per_s {new_gp:.3g} is "
                    f"{1.0 - new_gp / old_gp:.0%} below the trajectory "
                    f"best {old_gp:.3g} (round {sn}, b_max="
                    f"{fresh['serve'].get('b_max')}, admission="
                    f"{fresh['serve'].get('admission')}); gate allows "
                    f"{threshold:.0%}")
    # Skewed-mix gate (ISSUE 20): the SMALL class's goodput of a mix
    # record against the best comparable mix record — comparable()
    # already pinned the merge_packing arm, the ratio and the class
    # pair, so packed and per-class-queue trajectories never gate each
    # other.  Saturation-conditioned like the serve gate: below
    # saturation the per-class goodput tracks the offered mix, not the
    # packer.
    if isinstance(fresh.get("mix"), dict) and _saturated(
            fresh.get("serve") or {}):
        mpeers = [(n, rec) for n, rec in peers
                  if isinstance(rec.get("mix"), dict)
                  and _saturated(rec.get("serve") or {})
                  and isinstance(
                      rec["mix"].get("small_goodput_jobs_per_s"),
                      (int, float))]
        if mpeers and isinstance(
                fresh["mix"].get("small_goodput_jobs_per_s"),
                (int, float)):
            mn, mbest = max(
                mpeers,
                key=lambda p: p[1]["mix"]["small_goodput_jobs_per_s"])
            old_mg = mbest["mix"]["small_goodput_jobs_per_s"]
            new_mg = fresh["mix"]["small_goodput_jobs_per_s"]
            if new_mg < old_mg * (1.0 - threshold):
                problems.append(
                    f"mix small_goodput_jobs_per_s {new_mg:.3g} is "
                    f"{1.0 - new_mg / old_mg:.0%} below the trajectory "
                    f"best {old_mg:.3g} (round {mn}, merge_packing="
                    f"{fresh['mix'].get('merge_packing')}); gate allows "
                    f"{threshold:.0%}")
    # Streaming-speedup gate (ISSUE 17): cold/delta wall ratio of a
    # churn record against the best comparable stream record
    # (comparable() already pinned the warm arm and churn_frac, and
    # keeps stream records out of every batch/serve/TEPS comparison).
    # The ratio is the gated number — walls alone drift with the host,
    # but cold and delta share one machine state by construction.
    if isinstance(fresh.get("stream"), dict):
        tpeers = [(n, rec) for n, rec in peers
                  if isinstance(rec.get("stream"), dict)
                  and isinstance(rec["stream"].get("speedup"),
                                 (int, float))]
        if tpeers and isinstance(fresh["stream"].get("speedup"),
                                 (int, float)):
            tn, tbest = max(tpeers,
                            key=lambda p: p[1]["stream"]["speedup"])
            old_sp = tbest["stream"]["speedup"]
            new_sp = fresh["stream"]["speedup"]
            if new_sp < old_sp * (1.0 - threshold):
                problems.append(
                    f"stream speedup {new_sp:.3g}x is "
                    f"{1.0 - new_sp / old_sp:.0%} below the trajectory "
                    f"best {old_sp:.3g}x (round {tn}, warm="
                    f"{fresh['stream'].get('warm')}, churn_frac="
                    f"{fresh['stream'].get('churn_frac')}); gate allows "
                    f"{threshold:.0%}")
    # Stage-level gate: against the most recent comparable record that
    # carries stages (schema v2+ — early rounds predate the breakdown).
    # Serve records are exempt for the same reason as their TEPS gate:
    # their cumulative stage seconds scale with the job count, which
    # comparable() does not match (a 512-job A/B round vs a 32-job
    # default round would show every stage "grown" ~16x).
    staged = [] if isinstance(fresh.get("serve"), dict) else \
        [(n, rec) for n, rec in peers
         if isinstance(rec.get("stages"), dict)]
    if staged and isinstance(fresh.get("stages"), dict):
        ref_n, ref = max(staged, key=lambda p: p[0])
        for key in STAGE_KEYS:
            old = ref["stages"].get(key)
            new = fresh["stages"].get(key)
            if not isinstance(old, (int, float)) \
                    or not isinstance(new, (int, float)):
                continue
            if max(old, new) < stage_floor_s:
                continue  # sub-floor stages are wall-clock noise
            if old > 0 and new > old * (1.0 + threshold):
                problems.append(
                    f"stage {key} grew {new / old - 1.0:.0%} over round "
                    f"{ref_n} ({old:.3g}s -> {new:.3g}s); gate allows "
                    f"{threshold:.0%}")
    return problems


def self_check(pattern: str) -> list:
    """Structural + (v4) schema problems across every checked-in round
    log; also proves at least one parsed record exists."""
    try:
        trajectory = load_trajectory(pattern)
    except ValueError as e:
        return [str(e)]
    problems = []
    parsed = 0
    for path, _, rec in trajectory:
        parsed += 1
        problems.extend(validate_v4(path, rec))
    if not parsed:
        problems.append(f"no round log under {pattern!r} carries a "
                        "parsed bench record")
    return problems


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python tools/perf_regress.py",
        description="bench-trajectory perf-regression gate")
    p.add_argument("--record", metavar="FILE.json",
                   help="fresh bench record to gate (a bare record, or a "
                        "round log with a 'parsed' field)")
    p.add_argument("--threshold", type=float, default=0.30,
                   help="allowed fractional drop in TEPS / growth in a "
                        "stage time (default 0.30)")
    p.add_argument("--stage-floor-s", type=float, default=0.5,
                   help="ignore stages under this many seconds (wall "
                        "noise; default 0.5)")
    p.add_argument("--bench-glob",
                   default=os.path.join(REPO_ROOT, "BENCH_*.json"),
                   help="trajectory round logs (default: repo root)")
    p.add_argument("--self-check", action="store_true",
                   help="validate the checked-in trajectory itself "
                        "(tier-1 gate) instead of comparing a record")
    args = p.parse_args(argv)

    if args.self_check:
        problems = self_check(args.bench_glob)
        if problems:
            for prob in problems:
                print(f"SELF-CHECK FAIL: {prob}", file=sys.stderr)
            return 1
        print("self-check ok: trajectory parses and validates")
        return 0

    if not args.record:
        p.error("--record FILE.json or --self-check required")
    try:
        with open(args.record, encoding="utf-8") as f:
            fresh = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"ERROR: cannot read {args.record}: {e}", file=sys.stderr)
        return 2
    if isinstance(fresh, dict) and isinstance(fresh.get("parsed"), dict):
        fresh = fresh["parsed"]  # a round log wraps the record
    if not isinstance(fresh, dict) \
            or not isinstance(fresh.get("schema"), int):
        # Pre-v4 leniency covers only the checked-in trajectory: a FRESH
        # record comes from today's run_bench, which always stamps
        # schema=4 — a missing field means record emission regressed,
        # exactly what this gate must not wave through.
        print(f"SCHEMA FAIL: {args.record}: fresh record carries no int "
              "'schema' field (self-describing v4+ required; only "
              "checked-in pre-v4 trajectory rounds are read leniently)",
              file=sys.stderr)
        return 2
    problems = validate_v4(args.record, fresh)
    if problems:
        for prob in problems:
            print(f"SCHEMA FAIL: {prob}", file=sys.stderr)
        return 2
    try:
        trajectory = load_trajectory(args.bench_glob)
    except ValueError as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 2
    problems = check_regression(fresh, trajectory, args.threshold,
                                args.stage_floor_s)
    if problems:
        for prob in problems:
            print(f"REGRESSION: {prob}", file=sys.stderr)
        return 1
    peers = sum(1 for _, _, rec in trajectory if comparable(fresh, rec))
    print(f"ok: no regression vs {peers} comparable trajectory "
          f"record(s) at threshold {args.threshold:.0%}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
