#!/bin/bash
# Background watcher: probe the axon tunnel every ~10 min; on an alive
# window run the full measurement ladder (tools/tpu_ladder.py).  Stops
# when the ladder completes (tools/TPU_LADDER_DONE) or when
# tools/TPU_WATCH_STOP exists.
cd "$(dirname "$0")/.."
while true; do
  [ -f tools/TPU_LADDER_DONE ] && exit 0
  [ -f tools/TPU_WATCH_STOP ] && exit 0
  python tools/tpu_ladder.py >> tools/tpu_watch.out 2>&1
  [ -f tools/TPU_LADDER_DONE ] && exit 0
  sleep 600
done
