"""THE TPU measurement ladder (one ladder, one watcher: this file, run
by tools/tpu_watch3.sh).  Earlier generations (tpu_ladder.py, round-4
stages A-C; tpu_ladder2.py, stages A2/D/E) are folded in here and
deleted — VERDICT r5 weak #5.

Bench-first priority (a mid-ladder tunnel wedge preserves the most
valuable result first):

  C'. bench at scales 20 then 18 (the hardened harness in
      cuvite_tpu/workloads/bench.py: warm-up, compile-count==0 guard on
      the first timed run, shared JSON schema), stderr preserved per
      scale, JSON checkpointed to disk the moment it exists;
  A2. compiled Pallas row_argmax parity + min-of-5 timing for EVERY
      staged ladder width in (QUADRATIC_MAX_WIDTH..PALLAS_MAX_WIDTH]
      vs the XLA sorted-dedup twin — the widths that have only ever run
      in interpret mode (the SPMD engine routes all of them, ISSUE 4);
  D.  full clustering A/B on chip: bucketed vs pallas vs fused engines,
      rmat-18 and rmat-20 (--json lines logged); on a multi-chip slice
      also bucketed vs pallas SPMD over all devices;
  E.  bench at scale 22;
  then tools/heavy_ab.py (heavy-class kernel decision measurement),
  stage F (seg-coalesce fullrun A/B, ISSUE 8), stage G (batched
  multi-tenant serving at B in {1, 8, 64} — jobs/sec + pack_util,
  ISSUE 9), stage H (load generator vs the async daemon at
  B in {8, 64} — on-chip SLO row + SIGTERM drain check, ISSUE 11),
  and stage I (tools/mesh_audit.py across the slice's pow2 mesh
  shapes — the first on-chip M00x evidence: collective sequences,
  cross-shape label bit-identity, per-chip HBM scaling laws;
  ISSUE 15), stage J (width audit on the TPU lowering, ISSUE 16),
  stage K (streaming churn A/B, ISSUE 17), stage L (flat 8x1 vs
  two-level 2x4/4x2 exchange A/B + the per-axis ICI-vs-DCN collective
  microbench, ISSUE 18), and stage M (packed vs per-class serving A/B
  under the 90/10 skewed open-loop mix — mixed-class sub-row packing's
  on-chip goodput + wait_p95 verdict, ISSUE 20).

Success marker: tools/TPU_LADDER3_DONE (platform!=cpu bench JSON
landed).  Every result appends to tools/logs/tpu_ladder_r4.log immediately.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
LOG = os.path.join(REPO, "tools", "logs", "tpu_ladder_r4.log")
DONE = os.path.join(REPO, "tools", "TPU_LADDER3_DONE")


def log(msg):
    line = f"[{time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())}] {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def probe(timeout_s=75):
    code = ("import jax; from jax._src import xla_bridge as xb; "
            "d = jax.devices(); "
            "n = [k for k, b in xb.backends().items() if b is d[0].client]; "
            "print(n[0] if n else d[0].platform, len(d), d[0].device_kind)")
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None
    if out.returncode != 0 or not out.stdout.strip():
        return None
    return out.stdout.strip().split(None, 2)


def stage_c_retry():
    """Scale 20 first (the TPU default BASELINE tracks), then 18
    (comparable with every recorded CPU number).  Each scale checkpoints
    its JSON immediately so a tunnel wedge cannot lose it.  The bench's
    own compile guard aborts (rc=3, no JSON) on a recompiling run —
    which this log then shows instead of a silently-poisoned number."""
    got = False
    for scale, budget in (("20", "1400"), ("18", "700")):
        env = dict(os.environ, BENCH_SCALE=scale, BENCH_TIME_BUDGET=budget,
                   BENCH_REPEATS="3")
        t0 = time.perf_counter()
        errpath = os.path.join(REPO, "tools",
                               f"bench{scale}_tpu_stderr.log")
        try:
            with open(errpath, "w") as errf:
                out = subprocess.run(
                    [sys.executable, os.path.join(REPO, "bench.py")],
                    stdout=subprocess.PIPE, stderr=errf, text=True,
                    timeout=int(budget) + 400, env=env)
        except subprocess.TimeoutExpired:
            log(f"C': bench scale={scale} TIMEOUT")
            continue
        last = out.stdout.strip().splitlines()
        log(f"C': bench scale={scale} rc={out.returncode} "
            f"wall={time.perf_counter()-t0:.0f}s "
            f"json={last[-1] if last else '?'} "
            f"(stderr: {errpath})")
        if out.returncode == 3:
            log("C': compile guard tripped — no JSON by design; see the "
                "stderr log for the compile list")
        if out.returncode == 0 and last:
            try:
                j = json.loads(last[-1])
                from cuvite_tpu.workloads.bench import validate_record

                problems = validate_record(j)
                if problems:
                    log(f"C': record rejected by schema: {problems}")
                elif j.get("platform") != "cpu":
                    with open(os.path.join(
                            REPO, f"tools/bench_tpu_s{scale}.json"),
                            "w") as f:
                        f.write(last[-1] + "\n")
                    got = True
            except json.JSONDecodeError:
                pass
    return got


def stage_a2(jnp, np):
    """Compiled Pallas parity + min-of-5 timing vs the XLA sorted twin
    for EVERY staged ladder width in (64..PALLAS_MAX_WIDTH] — the widths
    that have only ever run in interpret mode (ISSUE 4: the SPMD engine
    now routes all of them through the kernel, so the next chip window
    must prove the whole staged set, not the 64/256/2048 samples).
    Widths and the cap come from the ladder constants, never literals
    (graftlint R011's contract)."""
    from cuvite_tpu.kernels.row_argmax import row_argmax_pallas
    from cuvite_tpu.louvain.bucketed import (
        DEFAULT_BUCKETS,
        PALLAS_MAX_WIDTH,
        QUADRATIC_MAX_WIDTH,
        _row_argmax_sorted,
    )

    SENT = np.iinfo(np.int32).max
    rng = np.random.default_rng(0)
    staged = [w for w in DEFAULT_BUCKETS
              if QUADRATIC_MAX_WIDTH < w <= PALLAS_MAX_WIDTH]

    def rows_for(width):
        # ~2^20 elements per case, pow2 rows in [2^9, 2^14] (the kernel
        # needs >= 128 rows; pow2 keeps its tile math exact).
        r = (1 << 20) // width
        r = 1 << (max(r, 1).bit_length() - 1)
        return min(max(r, 1 << 9), 1 << 14)

    for width in staged:
        n_rows = rows_for(width)
        nv = 50000
        cmat = rng.integers(0, nv, size=(n_rows, width)).astype(np.int32)
        wmat = (rng.integers(1, 32, size=(n_rows, width)) / 16.0
                ).astype(np.float32)
        curr = rng.integers(0, nv, size=n_rows).astype(np.int32)
        cmat[: n_rows // 2, 0] = curr[: n_rows // 2]
        vdeg = (rng.integers(1, 64, size=n_rows) / 4.0).astype(np.float32)
        sl = np.where(cmat[:, 0] == curr, wmat[:, 0] / 2.0, 0.0
                      ).astype(np.float32)
        comm_deg = (rng.integers(1, 256, size=nv) / 8.0).astype(np.float32)
        const = np.float32(1.0 / 64.0)
        ay = comm_deg[cmat]
        ax = comm_deg[curr] - vdeg
        args_p = (jnp.asarray(np.ascontiguousarray(cmat.T)),
                  jnp.asarray(np.ascontiguousarray(wmat.T)),
                  jnp.asarray(np.ascontiguousarray(ay.T)),
                  jnp.asarray(curr), jnp.asarray(vdeg), jnp.asarray(sl),
                  jnp.asarray(ax), jnp.asarray(const))
        args_x = (jnp.asarray(cmat), jnp.asarray(wmat), jnp.asarray(ay),
                  None, jnp.asarray(curr), jnp.asarray(vdeg),
                  jnp.asarray(sl), jnp.asarray(ax), jnp.asarray(const),
                  SENT)

        t0 = time.perf_counter()
        bc, bg, c0 = row_argmax_pallas(*args_p, sentinel=SENT,
                                       interpret=False)
        bc_h = np.asarray(bc)
        log(f"A2: width={width} pallas COMPILED ok "
            f"(first call {time.perf_counter()-t0:.1f}s)")
        ref = _row_argmax_sorted(*args_x, id_bound=nv)
        # best_c/counter0 agree exactly; best_gain may differ in f32
        # summation order for duplicate aggregation -> epsilon compare.
        ok_c = (np.array_equal(bc_h, np.asarray(ref.best_c))
                and np.array_equal(np.asarray(c0), np.asarray(ref.counter0)))
        gmax = float(np.max(np.abs(
            np.where(np.isfinite(np.asarray(bg)),
                     np.asarray(bg) - np.asarray(ref.best_gain), 0.0))))
        log(f"A2: width={width} vs XLA-sorted: best_c/counter0 "
            f"{'PASS' if ok_c else 'FAIL'}, |dgain|max={gmax:.3g}")

        def t5(fn):
            ts = []
            for _ in range(5):
                t0 = time.perf_counter()
                out = fn()
                _ = float(np.asarray(out[0]).ravel()[0])
                ts.append(time.perf_counter() - t0)
            return min(ts)

        tp = t5(lambda: row_argmax_pallas(*args_p, sentinel=SENT,
                                          interpret=False))
        tx = t5(lambda: _row_argmax_sorted(*args_x, id_bound=nv))
        log(f"A2: width={width} rows={n_rows}: pallas {tp*1e3:.2f} ms vs "
            f"XLA-sorted {tx*1e3:.2f} ms ({tx/max(tp,1e-9):.2f}x)")


def stage_d(platform, ndev=1):
    """Full clustering engine A/B on chip (folded from tpu_ladder2.py);
    fused = one host sync per RUN (vs per phase): over a ~1s-rtt tunnel
    per-phase syncs alone are a visible share of a scale-18 run.  On a
    multi-chip slice the SPMD rows additionally A/B bucketed vs pallas
    over all devices (ISSUE 4: the kernel now runs inside shard_map)."""
    configs = [(engine, 1) for engine in ("bucketed", "pallas", "fused")]
    if ndev > 1:
        configs += [(engine, ndev) for engine in ("bucketed", "pallas")]
    for scale in (18, 20):
        for engine, shards in configs:
            cmd = [sys.executable, "-m", "cuvite_tpu.cli",
                   "--rmat", str(scale), "--engine", engine,
                   "--platform", platform, "--json", "--quiet"]
            if shards > 1:
                cmd += ["--shards", str(shards)]
            t0 = time.perf_counter()
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=2400, cwd=REPO)
            wall = time.perf_counter() - t0
            line = ""
            for ln in reversed(out.stdout.strip().splitlines() or [""]):
                if ln.startswith("{"):
                    line = ln
                    break
            log(f"D: scale={scale} engine={engine} shards={shards} "
                f"rc={out.returncode} wall={wall:.0f}s "
                f"json={line or out.stderr[-200:]}")


def stage_e():
    """Scale-22 bench (folded from tpu_ladder2.py)."""
    env = dict(os.environ, BENCH_SCALE="22", BENCH_TIME_BUDGET="1500",
               BENCH_REPEATS="2")
    t0 = time.perf_counter()
    out = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                         capture_output=True, text=True, timeout=3600,
                         env=env)
    last = out.stdout.strip().splitlines()
    log(f"E: bench scale=22 rc={out.returncode} "
        f"wall={time.perf_counter()-t0:.0f}s "
        f"json={last[-1] if last else '?'}")
    if out.returncode == 0 and last:
        try:
            j = json.loads(last[-1])
            if j.get("platform") != "cpu":
                with open(os.path.join(REPO, "tools/bench_tpu_s22.json"),
                          "w") as f:
                    f.write(last[-1] + "\n")
        except json.JSONDecodeError:
            pass


def stage_g():
    """Batched multi-tenant serving bench at B in {1, 8, 64} (ISSUE 9),
    A/B'd fused-vs-bucketed (ISSUE 10): jobs/sec + pack_util through
    the batched driver on-chip, staged next to the seg-coalesce A/B so
    the first platform=tpu record can cover both.  On a TPU slice the
    batch axis shards over the chips (louvain/batched.py BATCH_AXIS);
    each (B, engine) cell writes its own JSON the moment it exists, so
    a timeout mid-sweep loses nothing already measured."""
    for b in (1, 8, 64):
        for eng in ("fused", "bucketed"):
            out_path = os.path.join(
                REPO, f"tools/bench_tpu_batch_{eng}_b{b}.json")
            t0 = time.perf_counter()
            try:
                out = subprocess.run(
                    [sys.executable, "-m", "cuvite_tpu.workloads",
                     "bench", "--batch", str(b), "--batch-engine", eng,
                     "--repeats", "3", "--out", out_path],
                    capture_output=True, text=True, timeout=1800,
                    cwd=REPO)
            except subprocess.TimeoutExpired:
                log(f"G: batch B={b} engine={eng} TIMEOUT (1800s)")
                continue
            last = out.stdout.strip().splitlines()
            log(f"G: batch B={b} engine={eng} rc={out.returncode} "
                f"wall={time.perf_counter()-t0:.0f}s "
                f"json={last[-1] if last else out.stderr[-200:]}")
            if out.returncode == 3:
                log("G: compile guard tripped — a timed batch "
                    "recompiled; no JSON by design")


def stage_h():
    """Staged on-chip saturation run (ISSUE 11, extended to the
    pipeline A/B by ISSUE 14): the open-loop load generator drives the
    async daemon over its socket at B in {8, 64} with the PIPELINED
    and the SERIAL dispatcher on the SAME seeded job set, SIGTERMs
    each, and verifies the graceful drain — so the first platform=tpu
    serving record includes an SLO row per arm (goodput at an offered
    rate, wait_p95 vs the 500 ms SLO, reject/shed counts, daemon exit
    code) and the on-chip pack-vs-execute overlap verdict.  Each
    (B, arm) writes its own JSON the moment it exists; rates start
    conservative (the CPU saturation numbers in BASELINE.md round-13)
    — the point is the SLO row and the clean drain on chip, not a
    chip-side sweep."""
    for b, rate in ((8, 20.0), (64, 60.0)):
        for pipe in ("on", "off"):
            out_path = os.path.join(
                REPO, f"tools/serve_tpu_daemon_pipe{pipe}_b{b}.json")
            t0 = time.perf_counter()
            try:
                out = subprocess.run(
                    [sys.executable,
                     os.path.join(REPO, "tools", "serve_load.py"),
                     "daemon",
                     "--b-max", str(b), "--rate", str(rate),
                     "--jobs", "128", "--edges", "4096",
                     "--slo-ms", "500", "--tenants", "4",
                     "--pipeline", pipe,
                     "--out", out_path],
                    capture_output=True, text=True, timeout=1800,
                    cwd=REPO)
            except subprocess.TimeoutExpired:
                log(f"H: daemon B={b} pipeline={pipe} TIMEOUT (1800s)")
                continue
            last = out.stdout.strip().splitlines()
            log(f"H: daemon B={b} pipeline={pipe} rate={rate} "
                f"rc={out.returncode} "
                f"wall={time.perf_counter()-t0:.0f}s "
                f"json={last[-1] if last else out.stderr[-200:]}")


def stage_i(platform, ndev):
    """Mesh audit on the real chips (ISSUE 15): the first on-chip
    M00x evidence.  tools/mesh_audit.py runs the sharded entries (both
    exchanges + the batched engines) across the pow2 mesh shapes the
    slice supports and grades M001 collective sequences, M002
    cross-shape label bit-identity, and M003 per-device HBM scaling vs
    tools/replication_budget.json — per-shape ledger rows checkpointed
    as JSON the moment the audit returns.  On a multi-chip slice this
    is the first time the scaling laws are measured against REAL
    per-chip HBM placements instead of virtual host devices."""
    shapes = [f"{s}x{ndev // s}" for s in (8, 4, 2)
              if s <= ndev and ndev % s == 0]
    # Cross-shape M001/M002 need >= 2 shapes: on a small slice add the
    # unsharded 1xN factorization instead of silently grading nothing.
    if len(shapes) < 2 and ndev > 1:
        shapes.append(f"1x{ndev}")
    shapes = shapes or ["1x1"]
    note = "" if len(shapes) >= 2 else \
        " (single shape: cross-shape M001/M002 NOT graded)"
    out_path = os.path.join(REPO, "tools", "mesh_audit_tpu.json")
    t0 = time.perf_counter()
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "mesh_audit.py"),
             "--shapes", *shapes, "--out", out_path],
            capture_output=True, text=True, timeout=1800, cwd=REPO,
            env=dict(os.environ, CUVITE_PLATFORM=platform))
    except subprocess.TimeoutExpired:
        log("I: mesh_audit TIMEOUT (1800s)")
        return
    tail = out.stdout.strip().splitlines()
    log(f"I: mesh_audit shapes={','.join(shapes)} rc={out.returncode} "
        f"wall={time.perf_counter()-t0:.0f}s "
        f"verdict={tail[-1] if tail else out.stderr[-200:]}{note} "
        f"(json: {out_path})")


def stage_j(platform):
    """Width audit on the real chips (ISSUE 16): the zero-allocation
    scale-28 certification re-run against the TPU backend's own
    lowering.  tools/width_audit.py traces the billion-edge-path
    entries at the Friendster-class and scale-28 shard shapes (no
    device bytes allocated — the trace is abstract even on chip) and
    grades W001 index-carrying buffer widths, W002 fallback selection
    at the bit-budget boundaries, and W003 manifest drift vs
    tools/width_budget.json.  On-chip this certifies the width laws
    against the REAL platform's dtype promotion and sort lowering, not
    the CPU stand-in's."""
    out_path = os.path.join(REPO, "tools", "width_audit_tpu.json")
    t0 = time.perf_counter()
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "width_audit.py"),
             "--out", out_path],
            capture_output=True, text=True, timeout=1800, cwd=REPO,
            env=dict(os.environ, CUVITE_PLATFORM=platform))
    except subprocess.TimeoutExpired:
        log("J: width_audit TIMEOUT (1800s)")
        return
    tail = out.stdout.strip().splitlines()
    log(f"J: width_audit rc={out.returncode} "
        f"wall={time.perf_counter()-t0:.0f}s "
        f"verdict={tail[-1] if tail else out.stderr[-200:]} "
        f"(json: {out_path})")


def stage_k():
    """Streaming churn A/B on chip (ISSUE 17): cold full re-cluster vs
    resident-slab delta + warm re-cluster at 1% churn on rmat-20,
    across the three warm arms (labels / plp prepass / cold control).
    On a TPU the cold arm pays the full upload + pipeline while the
    delta arm touches only the resident slab — the speedup this stage
    measures is the one the CPU baseline understates (host arrays make
    'resident' nearly free).  Each arm writes its own compile-guarded
    schema-v4 JSON with a `stream` block the moment it exists; rc=3
    means a timed window recompiled (no JSON by design)."""
    for warm in ("labels", "plp", "cold"):
        out_path = os.path.join(
            REPO, f"tools/bench_tpu_stream_{warm}.json")
        t0 = time.perf_counter()
        try:
            out = subprocess.run(
                [sys.executable, "-m", "cuvite_tpu.workloads",
                 "bench", "--churn-frac", "0.01", "--scale", "20",
                 "--warm-start", warm, "--out", out_path],
                capture_output=True, text=True, timeout=1800,
                cwd=REPO)
        except subprocess.TimeoutExpired:
            log(f"K: churn warm={warm} TIMEOUT (1800s)")
            continue
        last = out.stdout.strip().splitlines()
        log(f"K: churn warm={warm} rc={out.returncode} "
            f"wall={time.perf_counter()-t0:.0f}s "
            f"json={last[-1] if last else out.stderr[-200:]}")
        if out.returncode == 3:
            log("K: compile guard tripped — a timed stream window "
                "recompiled; no JSON by design")


def stage_l(platform, ndev):
    """Two-level exchange A/B on chip (ISSUE 18): the SAME rmat-20
    clustering over the flat 1-D mesh (8x1: sparse exchange, tables at
    the full nv_total window) vs the hybrid factorizations (2x4, 4x2:
    community tables replicated only inside the ICI submesh, sparse
    ghost routing on the DCN axis).  Labels are bit-identical by the
    M002 gate — the number this stage adds is the WALL/exchange cost of
    shrinking the per-chip table window by |dcn|, on real ICI vs DCN
    links instead of tier-1's uniform virtual host axes.  Each shape
    writes its own JSON line the moment it exists."""
    if ndev < 8:
        log(f"L: skipped (ndev={ndev} < 8; the A/B needs the 8-chip "
            "factorizations)")
        return
    for shape in ("8x1", "2x4", "4x2"):
        out_path = os.path.join(REPO, f"tools/cli_tpu_twolevel_{shape}.json")
        cmd = [sys.executable, "-m", "cuvite_tpu.cli",
               "--rmat", "20", "--engine", "bucketed",
               "--platform", platform, "--json", "--quiet"]
        d, _, i = shape.partition("x")
        if d == "1" or i == "1":
            cmd += ["--shards", "8", "--exchange", "sparse"]
        else:
            cmd += ["--mesh", shape]
        t0 = time.perf_counter()
        try:
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=2400, cwd=REPO)
        except subprocess.TimeoutExpired:
            log(f"L: twolevel shape={shape} TIMEOUT (2400s)")
            continue
        line = ""
        for ln in reversed(out.stdout.strip().splitlines() or [""]):
            if ln.startswith("{"):
                line = ln
                break
        log(f"L: twolevel shape={shape} rc={out.returncode} "
            f"wall={time.perf_counter()-t0:.0f}s "
            f"json={line or out.stderr[-200:]}")
        if out.returncode == 0 and line:
            with open(out_path, "w") as f:
                f.write(line + "\n")
    # The per-axis collective microbench: intra-ICI all_gather vs
    # cross-DCN all_to_all launch + payload cost at the table scales the
    # A/B above exercises (tools/exchange_latency.py --mesh mode).
    out_path = os.path.join(REPO, "tools", "exchange_latency_tpu_2axis.json")
    try:
        out = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "exchange_latency.py"),
             "--mesh", "2x4", "--out", out_path],
            capture_output=True, text=True, timeout=1200, cwd=REPO,
            env=dict(os.environ, CUVITE_PLATFORM=platform))
        tail = out.stdout.strip().splitlines()
        log(f"L: exchange_latency --mesh 2x4 rc={out.returncode} "
            f"tail={tail[-1] if tail else out.stderr[-200:]} "
            f"(json: {out_path})")
    except subprocess.TimeoutExpired:
        log("L: exchange_latency --mesh TIMEOUT (1200s)")


def stage_m(platform):
    """Stage M (ISSUE 20): packed-vs-per-class serving A/B under the
    90/10 skewed open-loop mix on chip.  tools/serve_load.py mix runs
    both arms (merge_packing off then on) at the same offered rate,
    compile-guarded with the sub-row rungs pre-warmed, and writes one
    schema-v5 bench record per arm (the `mix` block: per-class goodput
    + wait_p95, pack_util, subrow_util, merged_batches).  The verdict
    line is the on-chip analog of the BASELINE round-20 CPU acceptance
    row — packed must beat per-class queues on goodput AND small-class
    wait_p95 with merged_batches > 0."""
    prefix = os.path.join(REPO, "tools", "logs", "serve_mix_tpu")
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "serve_load.py"),
             "mix", "--rate", "20", "--platform", platform,
             "--budget", "900", "--out-prefix", prefix],
            capture_output=True, text=True, timeout=2400, cwd=REPO)
        tail = out.stdout.strip().splitlines()
        log(f"M: mix 90:10 rc={out.returncode} "
            f"tail={tail[-1] if tail else out.stderr[-200:]} "
            f"(json: {prefix}_packed.json / {prefix}_perclass.json)")
    except subprocess.TimeoutExpired:
        log("M: serve_load mix TIMEOUT (2400s)")


def main():
    parts = probe()
    if parts is None:
        print("probe: tunnel not answering", flush=True)
        return 2
    if parts[0] == "cpu":
        print("probe resolved to cpu; nothing to measure", flush=True)
        return 2
    ts = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(os.path.join(REPO, "tools", "tpu_probe_log.md"), "a") as f:
        f.write(f"- {ts} ladder3 probe: rc=0 {' '.join(parts)}\n")
    log(f"LADDER3 start: {' '.join(parts)}")
    # stage_c_retry handles its own per-scale timeouts.
    got_tpu_json = stage_c_retry()

    # In-process stages need the proven backend pinned here too.
    import jax

    jax.config.update("jax_platforms", parts[0])
    from cuvite_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    import jax.numpy as jnp
    import numpy as np

    try:
        stage_a2(jnp, np)
    except Exception as e:
        log(f"A2: FAILED {type(e).__name__}: {e}")
    try:
        stage_d(parts[0], ndev=int(parts[1]))
    except Exception as e:
        log(f"D: FAILED {type(e).__name__}: {e}")
    try:
        stage_e()
    except Exception as e:
        log(f"E: FAILED {type(e).__name__}: {e}")
    # Heavy-class decision measurement (heavy_kernel_design.md): tile
    # kernel vs XLA sorted path over (D, nv_ceil); its own dated log.
    # `both` also runs the seg-coalesce sweep (ISSUE 8 dense dst-tile
    # engines + the ISSUE 19 msd/hash big-class engines vs the
    # packed-sort chokepoint, per slab class —
    # tools/logs/seg_coalesce_ab_r19.log): the on-chip numbers that
    # decide the CUVITE_SEG_COALESCE per-backend defaults.
    try:
        subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "heavy_ab.py"),
                        "both"],
                       timeout=1800)
    except subprocess.TimeoutExpired:
        log("heavy_ab: TIMEOUT (1800s)")
    # Stage F (ISSUE 8, extended by ISSUE 19): round-7 config
    # end-to-end with each coalesce engine forced — the fullrun side of
    # the seg-coalesce A/B, on-chip.  'xla' is the dense dst-tile arm;
    # 'msd' and 'hash' are the big-class sort-free arms (at scale 20
    # the nv_pad >= 2^16 coarse slabs are where they differ from sort).
    for seg_eng in ("xla", "msd", "hash"):
        try:
            env = dict(os.environ, AB_SCALE="20", AB_ENGINE="sort",
                       CUVITE_SEG_COALESCE=seg_eng)
            subprocess.run([sys.executable,
                            os.path.join(REPO, "tools", "fullrun_ab.py")],
                           timeout=3600, env=env)
        except subprocess.TimeoutExpired:
            log(f"fullrun_ab (seg-coalesce stage F, {seg_eng}): "
                "TIMEOUT (3600s)")
    # Stage G (ISSUE 9): batched serving at B in {1, 8, 64}.
    try:
        stage_g()
    except Exception as e:
        log(f"G: FAILED {type(e).__name__}: {e}")
    # Stage H (ISSUE 11): load generator vs the async daemon on chip —
    # the first platform=tpu serving SLO row + SIGTERM drain check.
    try:
        stage_h()
    except Exception as e:
        log(f"H: FAILED {type(e).__name__}: {e}")
    # Stage I (ISSUE 15): the tier-5 mesh audit on real chips — first
    # on-chip M00x evidence, per-shape ledger JSON checkpointed.
    try:
        stage_i(parts[0], int(parts[1]))
    except Exception as e:
        log(f"I: FAILED {type(e).__name__}: {e}")
    # Stage J (ISSUE 16): the tier-6 width audit on real chips — the
    # scale-28 certification against the TPU's own lowering.
    try:
        stage_j(parts[0])
    except Exception as e:
        log(f"J: FAILED {type(e).__name__}: {e}")
    # Stage K (ISSUE 17): the streaming churn A/B on chip — cold vs
    # delta + warm re-cluster across the three warm arms at rmat-20.
    try:
        stage_k()
    except Exception as e:
        log(f"K: FAILED {type(e).__name__}: {e}")
    # Stage L (ISSUE 18): flat vs two-level exchange A/B across the
    # 8-chip mesh factorizations + the two-axis collective microbench.
    try:
        stage_l(parts[0], int(parts[1]))
    except Exception as e:
        log(f"L: FAILED {type(e).__name__}: {e}")
    # Stage M (ISSUE 20): packed-vs-per-class serving A/B under the
    # 90/10 skewed mix — sub-row packing's on-chip goodput/wait_p95 row.
    try:
        stage_m(parts[0])
    except Exception as e:
        log(f"M: FAILED {type(e).__name__}: {e}")
    if got_tpu_json:
        with open(DONE, "w") as f:
            f.write(time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()) + "\n")
    log("LADDER3 pass complete "
        f"(tpu bench json: {'yes' if got_tpu_json else 'no'})")
    return 0 if got_tpu_json else 1


if __name__ == "__main__":
    sys.exit(main())
