"""Third-stage TPU ladder (round 4): bench-first retry for the missing
platform=tpu BENCH artifact.

The 03:48-04:19Z alive window landed stages A (compiled Pallas parity +
1.41x/1.79x vs XLA) and B (910 ms scale-18 step incl. tunnel rtt), but
the stage-C bench crashed rc=1 with its stderr captured-and-lost, and
the tunnel wedged.  On the NEXT alive window the priority flips:

  C'. bench.py scale 18 with a generous in-process budget, stderr saved
      to tools/bench18_tpu_stderr.log (so a repeat failure is
      diagnosable), JSON saved to tools/bench_tpu_s18_r4.json when the
      platform is not the cpu fallback;
  then tools/tpu_ladder2.py (wide-width Pallas parity A2, engine A/B D,
      scale-22 bench E) inline.

Run via tools/tpu_watch3.sh.  Success marker: tools/TPU_LADDER3_DONE.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "tools", "tpu_ladder_r4.log")
DONE = os.path.join(REPO, "tools", "TPU_LADDER3_DONE")


def log(msg):
    line = f"[{time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())}] {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def probe(timeout_s=75):
    code = ("import jax; from jax._src import xla_bridge as xb; "
            "d = jax.devices(); "
            "n = [k for k, b in xb.backends().items() if b is d[0].client]; "
            "print(n[0] if n else d[0].platform, len(d), d[0].device_kind)")
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None
    if out.returncode != 0 or not out.stdout.strip():
        return None
    return out.stdout.strip().split(None, 2)


def stage_c_retry():
    """Round-5 bench-first order (VERDICT r4 item 2): scale 20 first
    (bench.py's TPU default, the number BASELINE tracks), then scale 18
    (comparable with every recorded CPU number).  Each stage checkpoints
    its JSON to disk immediately, so a tunnel wedge mid-ladder cannot
    lose an earlier stage's result; stderr is preserved per scale."""
    got = False
    for scale, budget in (("20", "1400"), ("18", "700")):
        env = dict(os.environ, BENCH_SCALE=scale, BENCH_TIME_BUDGET=budget,
                   BENCH_REPEATS="3")
        t0 = time.perf_counter()
        errpath = os.path.join(REPO, "tools",
                               f"bench{scale}_tpu_stderr.log")
        try:
            with open(errpath, "w") as errf:
                out = subprocess.run(
                    [sys.executable, os.path.join(REPO, "bench.py")],
                    stdout=subprocess.PIPE, stderr=errf, text=True,
                    timeout=int(budget) + 400, env=env)
        except subprocess.TimeoutExpired:
            log(f"C': bench scale={scale} TIMEOUT")
            continue
        last = out.stdout.strip().splitlines()
        log(f"C': bench scale={scale} rc={out.returncode} "
            f"wall={time.perf_counter()-t0:.0f}s "
            f"json={last[-1] if last else '?'} "
            f"(stderr: {errpath})")
        if out.returncode == 0 and last:
            try:
                j = json.loads(last[-1])
                if j.get("platform") != "cpu":
                    with open(os.path.join(
                            REPO, f"tools/bench_tpu_s{scale}_r5.json"),
                            "w") as f:
                        f.write(last[-1] + "\n")
                    got = True
            except json.JSONDecodeError:
                pass
    return got


def main():
    parts = probe()
    if parts is None:
        print("probe: tunnel not answering", flush=True)
        return 2
    if parts[0] == "cpu":
        print("probe resolved to cpu; nothing to measure", flush=True)
        return 2
    ts = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(os.path.join(REPO, "tools", "tpu_probe_log.md"), "a") as f:
        f.write(f"- {ts} ladder3 probe: rc=0 {' '.join(parts)}\n")
    log(f"LADDER3 start: {' '.join(parts)}")
    # stage_c_retry handles its own per-scale timeouts.
    got_tpu_json = stage_c_retry()
    try:
        subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "tpu_ladder2.py")],
                       timeout=7200)
    except subprocess.TimeoutExpired:
        log("ladder2: TIMEOUT (7200s)")
    # Heavy-class decision measurement (heavy_kernel_design.md): tile
    # kernel vs XLA sorted path over (D, nv_ceil); its own dated log.
    try:
        subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "heavy_ab.py")],
                       timeout=1800)
    except subprocess.TimeoutExpired:
        log("heavy_ab: TIMEOUT (1800s)")
    if got_tpu_json:
        with open(DONE, "w") as f:
            f.write(time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()) + "\n")
    log("LADDER3 pass complete "
        f"(tpu bench json: {'yes' if got_tpu_json else 'no'})")
    return 0 if got_tpu_json else 1


if __name__ == "__main__":
    sys.exit(main())
