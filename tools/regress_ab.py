"""A/B the e65cc15 mechanisms at step level (judge-bisected 7x regression).

Driver-identical single-shard bucketed phase-0 build at AB_SCALE (default
18), honoring the two kill switches added for this investigation:

  CUVITE_NO_ALIAS_UPLOAD=1   to_device() always copies (no DLPack alias)
  CUVITE_NO_SLABLESS=1       driver uses the padded slab layout again

Run one config per process (compile caches shared via tools/_common).
Prints plan time, compile time, and min/median of N step walls.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _common  # noqa: F401

import numpy as np

from cuvite_tpu.core.distgraph import DistGraph
from cuvite_tpu.io.generate import generate_rmat
from cuvite_tpu.louvain.driver import PhaseRunner


def main():
    import jax
    scale = int(os.environ.get("AB_SCALE", "18"))
    slabless = not os.environ.get("CUVITE_NO_SLABLESS")
    alias = not os.environ.get("CUVITE_NO_ALIAS_UPLOAD")
    print(f"# backend={jax.default_backend()} scale={scale} "
          f"slabless={slabless} alias={alias}", flush=True)
    g = generate_rmat(scale, edge_factor=16, seed=1)
    t0 = time.perf_counter()
    dg = DistGraph.build(g, 1, min_nv_pad=4096, min_ne_pad=16384,
                         pad_edges=not slabless)
    runner = PhaseRunner(dg, engine="bucketed", release_slabs=slabless)
    _ = np.asarray(runner.comm0[0:1])
    print(f"# plan+upload {time.perf_counter() - t0:.2f}s", flush=True)

    def step(c):
        return runner._step(None, None, None, c, runner.vdeg,
                            runner.constant)

    t0 = time.perf_counter()
    out = step(runner.comm0)
    _ = float(out[1])
    print(f"# first call (compile) {time.perf_counter() - t0:.1f}s",
          flush=True)

    c = runner.comm0
    times = []
    for _ in range(6):
        t0 = time.perf_counter()
        tgt, mod, _, _ = step(c)
        _ = float(mod)
        times.append(time.perf_counter() - t0)
        c = tgt
    times.sort()
    print(f"step min {times[0]*1e3:.0f} ms  med {times[3]*1e3:.0f} ms  "
          f"all {[f'{t*1e3:.0f}' for t in times]}", flush=True)


if __name__ == "__main__":
    main()
