"""Capture an xprof trace of the bucketed step and print top ops.

Runs 3 chained steps through PhaseRunner under jax.profiler.trace, then
parses the xplane with xprof (framework_op_stats) and prints the top
device ops by self time.  Note: the XLA:CPU backend does not emit per-op
device rows — this tool is for the TPU.

Usage:  python tools/trace_step.py      (AB_SCALE to change the graph)
NEVER run under a tight external timeout on the TPU (wedge hazard).
"""

import glob
import json
import os
import shutil
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _common  # noqa: F401  (backend pin + compile cache, must be first)

import jax

# Fail fast on a missing profiler dependency BEFORE any device work.
from xprof.convert import raw_to_tool_data as rtd

from cuvite_tpu.core.distgraph import DistGraph
from cuvite_tpu.io.generate import generate_rmat
from cuvite_tpu.louvain.driver import PhaseRunner


def main():
    scale = int(os.environ.get("AB_SCALE", "18"))
    g = generate_rmat(scale, edge_factor=16, seed=1)
    runner = PhaseRunner(DistGraph.build(g, 1), engine="bucketed")

    def step(c):
        return runner._step(None, None, None, c, runner.vdeg,
                            runner.constant)

    out = step(runner.comm0)
    _ = float(out[1])   # warm (compile)

    trace_dir = os.environ.get("TRACE_DIR", "/tmp/cuvite_trace")
    shutil.rmtree(trace_dir, ignore_errors=True)
    t0 = time.perf_counter()
    with jax.profiler.trace(trace_dir):
        c = runner.comm0
        for _ in range(3):
            tgt, mod, _, _ = step(c)
            c = tgt
        _ = float(mod)
    print(f"# traced 3 steps in {time.perf_counter()-t0:.2f}s -> {trace_dir}",
          flush=True)

    pbs = glob.glob(trace_dir + "/**/*.xplane.pb", recursive=True)
    data, _ctype = rtd.xspace_to_tool_data(pbs, "framework_op_stats",
                                           {"tqx": "out:csv"})
    if isinstance(data, bytes):
        data = data.decode()
    tbl = json.loads(data)
    tbl = tbl[0] if isinstance(tbl, list) else tbl
    cols = [cc["label"] for cc in tbl["cols"]]
    ix = {label: i for i, label in enumerate(cols)}
    rows = [[cc.get("v") for cc in r["c"]] for r in tbl["rows"]]
    dev = [r for r in rows if r[ix["Host/device"]] == "Device"]
    key = "Total self-time (us)"
    dev.sort(key=lambda r: -(r[ix[key]] or 0))
    total = sum(r[ix[key]] or 0 for r in dev)
    print(f"# device self time over 3 steps: {total/1e6:.3f}s")
    for r in dev[:20]:
        print(f"{(r[ix[key]] or 0)/1e3:9.1f} ms  "
              f"{str(r[ix['Operation Type']])[:24]:24} "
              f"{str(r[ix['Operation Name']])[:70]}")


if __name__ == "__main__":
    main()
