#!/usr/bin/env python
"""Serving saturation load tool (ISSUE 11; `mix` added by ISSUE 20).

Four subcommands around the open-loop generator (serve/loadgen.py):

    # geometric arrival-rate ramp: find max sustainable jobs/s at the SLO
    python tools/serve_load.py sweep --b-max 8 --edges 1024 --slo-ms 500

    # THE acceptance A/B: 2x the measured saturation rate, admission on
    # (wait_p95 holds, excess rejected with retry_after_s) vs admission
    # off (unbounded wait growth); two schema-v4 bench records emitted
    python tools/serve_load.py ab --b-max 8 --out-prefix tools/logs/serve_r13

    # drive a SPAWNED `python -m cuvite_tpu.serve daemon` over its
    # socket at a fixed rate, then SIGTERM it and check the clean drain
    # (the TPU ladder's stage H path)
    python tools/serve_load.py daemon --b-max 8 --rate 20 --jobs 64

    # skewed-mix packing A/B (ISSUE 20): 90:10 small:big open-loop mix,
    # per-class queues (merge_packing off) vs sub-row packing (on);
    # two schema-v5 records with a `mix` block; acceptance = packed
    # wins goodput AND small-class wait_p95 with merged_batches > 0
    python tools/serve_load.py mix --rate 20 --out-prefix tools/logs/mix_r20

`sweep`/`ab` run in-process (records via workloads.bench.run_serve_bench,
gated like-for-like by tools/perf_regress.py); `daemon` exercises the
full socket intake + dispatcher + SIGTERM drain path and emits a
compact JSON row (goodput, wait_p95 vs SLO, reject/shed counts, daemon
exit code) — the SLO row the first platform=tpu serving record needs.
"""

from __future__ import annotations

import argparse
import json
import os
import select
import signal
import socket
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _setup_jax(host_devices: int) -> None:
    from cuvite_tpu.utils.envknob import request_host_devices

    request_host_devices(host_devices)
    from cuvite_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()


def _warm_rungs(graphs, b_max: int, engine: str):
    """Compile every rung <= b_max once (open-loop partials can pad to
    any of them) with the job-set-pinned geometry; returns (cls, shape).
    The policy lives in ONE place — workloads.bench.warm_serve_rungs —
    shared with run_serve_bench so the two paths cannot drift."""
    from cuvite_tpu.workloads.bench import warm_serve_rungs

    return warm_serve_rungs(graphs, b_max, engine)


def _sweep_run(args):
    """Shared sweep machinery for `sweep`/`ab` (one copy so the
    setup/warm/pin policy cannot drift): synthesize the job set, warm
    the rungs, ramp rates printing a row per round.  Returns
    ``(graphs, make_server, reports, best)``; ``best is None`` means
    even the start rate overloads (callers bail with rc=1)."""
    _setup_jax(args.host_devices)
    from cuvite_tpu.serve import AdmissionConfig, LouvainServer, ServeConfig
    from cuvite_tpu.serve.loadgen import saturation_sweep
    from cuvite_tpu.workloads.synth import many_seed, synthesize_graph

    graphs = [synthesize_graph(args.edges, seed=many_seed(args.seed, k))
              for k in range(args.jobs)]
    cls, shape = _warm_rungs(graphs, args.b_max, args.engine)

    def make_server():
        srv = LouvainServer(ServeConfig(
            b_max=args.b_max, linger_s=args.linger_ms / 1e3,
            engine=args.engine,
            admission=AdmissionConfig(wait_slo_s=args.slo_ms / 1e3)))
        if shape is not None:
            srv.pin_shape(cls, shape)
        return srv

    reports, best = saturation_sweep(
        make_server, lambda: graphs, start_rate=args.start_rate,
        slo_s=args.slo_ms / 1e3, growth=args.growth,
        max_rounds=args.max_rounds,
        pipelined=getattr(args, "pipeline", "off") == "on")
    for rep in reports:
        print(json.dumps(rep.row()))
    if best is None:
        print(f"# even {args.start_rate} jobs/s overloads; lower "
              "--start-rate", file=sys.stderr)
    return graphs, make_server, reports, best


def cmd_sweep(args) -> int:
    _graphs, _mk, _reports, best = _sweep_run(args)
    if best is None:
        return 1
    print(json.dumps({"saturation_jobs_per_s": round(best.rate, 3),
                      "wait_p95_ms": round(best.wait_p95_s * 1e3, 3),
                      "slo_ms": args.slo_ms}))
    return 0


def cmd_ab(args) -> int:
    """Sweep, then 2x saturation with admission on vs off; both records
    written (BASELINE.md round-13 wants exactly this pair)."""
    from cuvite_tpu.workloads.bench import run_serve_bench, validate_record

    _graphs, _mk, reports, best = _sweep_run(args)
    if best is None:
        return 1
    # Measured saturation = the highest GOODPUT any sweep round
    # demonstrated, not the last sustainable offered rate: short sweep
    # bursts carry a fixed linger/drain tail that inflates wall and
    # biases the offered-rate knee low, so 2x the knee can land under
    # the queue's true capacity and never actually overload it.
    sat = max(best.rate, *(r.goodput_jobs_per_s for r in reports))
    rate2x = 2.0 * sat
    print(json.dumps({"saturation_jobs_per_s": round(sat, 3),
                      "sustainable_offered_rate": round(best.rate, 3),
                      "overload_rate": round(rate2x, 3)}))
    out = {}
    for arm in (True, False):
        rec = run_serve_bench(
            rate=rate2x, b_max=args.b_max, edges=args.edges,
            n_jobs=args.ab_jobs, seed=args.seed, slo_ms=args.slo_ms,
            admission=arm, linger_ms=args.linger_ms,
            engine=args.engine, platform=args.platform,
            budget_s=args.budget,
            pipelined=args.pipeline == "on")
        problems = validate_record(rec)
        if problems:
            print(f"# invalid record ({arm=}): {problems}",
                  file=sys.stderr)
            return 2
        out[arm] = rec
        line = json.dumps(rec)
        print(line)
        if args.out_prefix:
            suffix = "admit" if arm else "noadmit"
            path = f"{args.out_prefix}_{suffix}.json"
            with open(path, "w", encoding="utf-8") as f:
                f.write(line + "\n")
            print(f"# wrote {path}", file=sys.stderr)
    on, off = out[True]["serve"], out[False]["serve"]
    verdict = {
        "overload_rate": round(rate2x, 3),
        "admit_wait_p95_ms": on["wait_p95_ms"],
        "admit_slo_met": on["slo_met"],
        "admit_reject_rate": on["reject_rate"],
        "noadmit_wait_p95_ms": off["wait_p95_ms"],
        "noadmit_slo_met": off["slo_met"],
        "acceptance": bool(on["slo_met"] and on["reject_rate"] > 0
                           and not off["slo_met"]),
    }
    print(json.dumps({"verdict": verdict}))
    return 0 if verdict["acceptance"] else 1


def cmd_pipeab(args) -> int:
    """THE ISSUE-14 acceptance A/B: pipelined vs serial dispatcher on
    the SAME seeded job set at the same saturating offered rate
    (admission off, so goodput == measured capacity, not an intake
    policy).  Emits one schema-v4 serve record per arm (separated by
    serve.pipelined in perf_regress) and a verdict line with the
    speedup + the measured pack_s/device_s ratio the acceptance
    criterion is conditioned on (overlap can only buy up to
    (pack+device)/max(pack, device))."""
    from cuvite_tpu.workloads.bench import run_serve_bench, validate_record

    _graphs, _mk, reports, best = _sweep_run(args)
    if best is None:
        return 1
    sat = max(best.rate, *(r.goodput_jobs_per_s for r in reports))
    rate = args.overload_factor * sat
    print(json.dumps({"serial_saturation_jobs_per_s": round(sat, 3),
                      "ab_rate": round(rate, 3)}))
    out = {}
    for pipe in (False, True):
        rec = run_serve_bench(
            rate=rate, b_max=args.b_max, edges=args.edges,
            n_jobs=args.ab_jobs, seed=args.seed, slo_ms=args.slo_ms,
            admission=False, linger_ms=args.linger_ms,
            engine=args.engine, platform=args.platform,
            budget_s=args.budget, pipelined=pipe)
        problems = validate_record(rec)
        if problems:
            print(f"# invalid record (pipelined={pipe}): {problems}",
                  file=sys.stderr)
            return 2
        out[pipe] = rec
        line = json.dumps(rec)
        print(line)
        if args.out_prefix:
            suffix = "pipelined" if pipe else "serial"
            path = f"{args.out_prefix}_{suffix}.json"
            with open(path, "w", encoding="utf-8") as f:
                f.write(line + "\n")
            print(f"# wrote {path}", file=sys.stderr)
    ser, pip = out[False]["serve"], out[True]["serve"]
    speedup = pip["goodput_jobs_per_s"] / max(ser["goodput_jobs_per_s"],
                                              1e-9)
    ratio = ser["pack_s"] / max(ser["device_s"], 1e-9)
    verdict = {
        "serial_goodput_jobs_per_s": ser["goodput_jobs_per_s"],
        "pipelined_goodput_jobs_per_s": pip["goodput_jobs_per_s"],
        "speedup": round(speedup, 3),
        "pack_over_device": round(ratio, 3),
        "overlap_frac": pip.get("overlap_frac"),
        # The conditional acceptance form (ISSUE 14): >= 1.25x is
        # demanded only when pack is at least half of device — below
        # that, perfect overlap cannot reach 1.25x arithmetically.
        "acceptance": bool(speedup >= 1.25 or ratio < 0.5),
    }
    print(json.dumps({"verdict": verdict}))
    return 0 if verdict["acceptance"] else 1


def cmd_mix(args) -> int:
    """THE ISSUE-20 acceptance A/B: one 90:10 skewed small:big arrival
    mix at the same offered rate, served twice — merge_packing on
    (small bins pack as fenced sub-rows of the big class's program) vs
    off (strict per-class queues).  Two schema-v5 records with the
    ``mix`` block; the verdict demands the packed arm beat the
    per-class arm on BOTH total goodput and small-class wait_p95 at
    the equal SLO."""
    _setup_jax(args.host_devices)
    from cuvite_tpu.workloads.bench import (
        run_mixed_serve_bench,
        validate_record,
    )

    out = {}
    for packed in (False, True):
        rec = run_mixed_serve_bench(
            rate=args.rate, merge_packing=packed, b_max=args.b_max,
            small_edges=args.edges, big_scale=args.big_scale,
            big_edge_factor=args.big_edge_factor,
            n_small=args.n_small, n_big=args.n_big, seed=args.seed,
            slo_ms=args.slo_ms, linger_ms=args.linger_ms,
            engine=args.engine, platform=args.platform,
            budget_s=args.budget, pipelined=args.pipeline == "on")
        problems = validate_record(rec)
        if problems:
            print(f"# invalid record (merge_packing={packed}): {problems}",
                  file=sys.stderr)
            return 2
        out[packed] = rec
        line = json.dumps(rec)
        print(line)
        if args.out_prefix:
            suffix = "packed" if packed else "perclass"
            path = f"{args.out_prefix}_{suffix}.json"
            with open(path, "w", encoding="utf-8") as f:
                f.write(line + "\n")
            print(f"# wrote {path}", file=sys.stderr)
    plain, packed = out[False], out[True]
    ps, pl = packed["serve"], plain["serve"]
    pm, lm = packed["mix"], plain["mix"]
    verdict = {
        "rate_jobs_per_s": round(args.rate, 3),
        "perclass_goodput_jobs_per_s": pl["goodput_jobs_per_s"],
        "packed_goodput_jobs_per_s": ps["goodput_jobs_per_s"],
        "perclass_small_wait_p95_ms": lm["small_wait_p95_ms"],
        "packed_small_wait_p95_ms": pm["small_wait_p95_ms"],
        "merged_batches": pm["merged_batches"],
        "packed_subrow_util": pm["subrow_util"],
        "acceptance": bool(
            ps["goodput_jobs_per_s"] >= pl["goodput_jobs_per_s"]
            and pm["small_wait_p95_ms"] <= lm["small_wait_p95_ms"]
            and pm["merged_batches"] > 0),
    }
    print(json.dumps({"verdict": verdict}))
    return 0 if verdict["acceptance"] else 1


def _read_ready(proc, timeout_s: float) -> dict:
    """The daemon's readiness line, with a hard deadline (a wedged
    backend init must fail this tool, not hang it)."""
    deadline = time.monotonic() + timeout_s
    buf = ""
    while time.monotonic() < deadline:
        r, _, _ = select.select([proc.stdout], [], [], 0.5)
        if not r:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"daemon exited rc={proc.returncode} before ready")
            continue
        chunk = proc.stdout.readline()
        if not chunk:
            raise RuntimeError("daemon stdout closed before ready")
        buf = chunk.strip()
        if buf.startswith("{"):
            msg = json.loads(buf)
            if "ready" in msg:
                return msg["ready"]
    raise RuntimeError(f"daemon not ready within {timeout_s}s")


def cmd_daemon(args) -> int:
    """Spawn the daemon, drive an open-loop synth load over its socket,
    SIGTERM it, and verify the graceful drain (exit 0 + summary)."""
    cmd = [sys.executable, "-m", "cuvite_tpu.serve", "daemon",
           "--port", "0", "--b-max", str(args.b_max),
           "--linger-ms", str(args.linger_ms),
           "--engine", args.engine,
           "--pipeline", args.pipeline,
           "--host-devices", str(args.host_devices)]
    if args.slo_ms > 0:
        cmd += ["--wait-slo-ms", str(args.slo_ms)]
    if args.fault_plan:
        cmd += ["--fault-plan", args.fault_plan]
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True,
                            cwd=REPO)
    try:
        ready = _read_ready(proc, args.ready_timeout)
        port = ready["port"]
        # Loopback to the daemon this tool just spawned, not a fetch.
        conn = socket.create_connection(  # graftlint: disable=R009 — localhost control channel to our own child process
            ("127.0.0.1", port), timeout=30.0)
        lines = conn.makefile("r", encoding="utf-8")
        events = {"result": 0, "failed": 0, "shed": 0, "rejected": 0,
                  "acked": 0, "refused": 0, "summary": None}
        done_evt = threading.Event()

        def reader():
            for line in lines:
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "serve_summary" in msg:
                    events["summary"] = msg["serve_summary"]
                    done_evt.set()
                elif "result" in msg:
                    events["result"] += 1
                elif "failed" in msg:
                    events["failed"] += 1
                elif "shed" in msg:
                    events["shed"] += 1
                elif msg.get("rejected"):
                    events["rejected"] += 1
                elif "ok" in msg:
                    events["acked" if msg["ok"] else "refused"] += 1
            done_evt.set()

        threading.Thread(target=reader, daemon=True).start()
        t0 = time.perf_counter()
        wlock = threading.Lock()
        for k in range(args.jobs):
            target = t0 + k / args.rate
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            req = {"op": "submit", "synth": {"edges": args.edges,
                                             "seed": 1000 + k},
                   "tenant": f"t{k % max(args.tenants, 1)}"}
            if args.deadline_ms:
                req["deadline_s"] = args.deadline_ms / 1e3
            with wlock:
                conn.sendall((json.dumps(req) + "\n").encode())
        # Submits are pipelined (no per-request round trip); wait until
        # the daemon has ANSWERED every one before pulling the trigger,
        # or the SIGTERM would drain-refuse intake it never saw.
        ack_deadline = time.monotonic() + args.ready_timeout
        while time.monotonic() < ack_deadline:
            if (events["acked"] + events["rejected"]
                    + events["refused"]) >= args.jobs:
                break
            time.sleep(0.05)
        # Graceful shutdown via the signal path (the acceptance check).
        proc.send_signal(signal.SIGTERM)
        done_evt.wait(timeout=args.drain_timeout)
        rc = proc.wait(timeout=60)
        wall = time.perf_counter() - t0
        summary = events["summary"] or {}
        stats = summary if "jobs_done" in summary else {}
        row = {
            "daemon": True,
            "b_max": args.b_max,
            "engine": args.engine,
            "pipelined": args.pipeline == "on",
            "arrival_jobs_per_s": round(args.rate, 3),
            "offered": args.jobs,
            "done": stats.get("jobs_done", events["result"]),
            "failed": stats.get("jobs_failed", events["failed"]),
            "shed": stats.get("jobs_shed", events["shed"]),
            "rejected": stats.get("jobs_rejected", events["rejected"]),
            "goodput_jobs_per_s": round(
                stats.get("jobs_done", events["result"]) / max(wall, 1e-9),
                3),
            "wait_p95_ms": stats.get("wait_p95_ms"),
            "slo_ms": args.slo_ms,
            "conservation": summary.get("conservation"),
            "daemon_rc": rc,
            "clean_drain": bool(rc == 0 and summary),
        }
        print(json.dumps(row))
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                f.write(json.dumps(row) + "\n")
        return 0 if row["clean_drain"] else 1
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python tools/serve_load.py",
        description="serving saturation load generator")
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(q):
        q.add_argument("--b-max", type=int, default=8)
        q.add_argument("--edges", type=int, default=1024)
        q.add_argument("--jobs", type=int, default=64)
        q.add_argument("--seed", type=int, default=1)
        q.add_argument("--slo-ms", type=float, default=500.0)
        q.add_argument("--linger-ms", type=float, default=20.0)
        q.add_argument("--engine", default="bucketed",
                       choices=["bucketed", "fused"])
        q.add_argument("--host-devices", type=int, default=8)
        q.add_argument("--pipeline", default="off", choices=["on", "off"],
                       help="two-stage pipelined dispatch (ISSUE 14): "
                            "sweep/ab run the in-process dispatcher in "
                            "this mode; daemon forwards it to the "
                            "spawned daemon CLI")

    sw = sub.add_parser("sweep", help="find max sustainable jobs/s")
    common(sw)
    sw.add_argument("--start-rate", type=float, default=4.0)
    sw.add_argument("--growth", type=float, default=1.6)
    sw.add_argument("--max-rounds", type=int, default=8)

    ab = sub.add_parser("ab", help="2x-saturation admission on/off A/B")
    common(ab)
    ab.add_argument("--start-rate", type=float, default=4.0)
    ab.add_argument("--growth", type=float, default=1.5)
    ab.add_argument("--max-rounds", type=int, default=12)
    ab.add_argument("--ab-jobs", type=int, default=512,
                    help="job count for the two 2x-overload runs: must "
                         "offer enough WORK that the backlog a 2x rate "
                         "builds can push queue waits past the SLO "
                         "(64 jobs drain before the wait integral shows)")
    ab.add_argument("--platform", default="cpu")
    ab.add_argument("--budget", type=float, default=600.0)
    ab.add_argument("--out-prefix", default=None,
                    help="write <prefix>_admit.json / <prefix>_noadmit.json")

    pab = sub.add_parser("pipeab",
                         help="pipelined-vs-serial dispatcher A/B at a "
                              "saturating rate (ISSUE 14 acceptance)")
    common(pab)
    pab.add_argument("--start-rate", type=float, default=4.0)
    pab.add_argument("--growth", type=float, default=1.5)
    pab.add_argument("--max-rounds", type=int, default=12)
    pab.add_argument("--overload-factor", type=float, default=1.5,
                     help="offered rate = factor * measured serial "
                          "saturation (must exceed BOTH arms' capacity "
                          "so goodput reads capacity, not arrival)")
    pab.add_argument("--ab-jobs", type=int, default=256)
    pab.add_argument("--platform", default="cpu")
    pab.add_argument("--budget", type=float, default=600.0)
    pab.add_argument("--out-prefix", default=None,
                     help="write <prefix>_serial.json / "
                          "<prefix>_pipelined.json")

    mx = sub.add_parser("mix",
                        help="90:10 skewed-mix packed-vs-per-class A/B "
                             "(ISSUE 20 acceptance)")
    common(mx)
    mx.add_argument("--mix", default="90:10",
                    help="small:big arrival ratio by count (informational"
                         " — pool sizes come from --n-small/--n-big; the "
                         "default pools realize 90:10)")
    mx.add_argument("--rate", type=float, default=20.0,
                    help="offered arrival rate over the WHOLE mix")
    mx.add_argument("--big-scale", type=int, default=13,
                    help="R-MAT scale of the big pool (default 13 with "
                         "--big-edge-factor 2 lands in (8192, 32768), an "
                         "n_sub=2 row class for 1024-edge smalls)")
    mx.add_argument("--big-edge-factor", type=int, default=2)
    mx.add_argument("--n-small", type=int, default=None)
    mx.add_argument("--n-big", type=int, default=None)
    mx.add_argument("--platform", default="cpu")
    mx.add_argument("--budget", type=float, default=600.0)
    mx.add_argument("--out-prefix", default=None,
                    help="write <prefix>_packed.json / "
                         "<prefix>_perclass.json")
    # The packed program is plan-free (fused-style specs); defaulting
    # the PLAIN arm to bucketed would measure the ISSUE-10 engine gap,
    # not the packing policy — the A/B runs fused on both arms unless
    # explicitly overridden.
    mx.set_defaults(engine="fused")

    dm = sub.add_parser("daemon",
                        help="drive a spawned serve daemon over its socket")
    common(dm)
    dm.add_argument("--rate", type=float, default=10.0)
    dm.add_argument("--tenants", type=int, default=4)
    dm.add_argument("--deadline-ms", type=float, default=None)
    dm.add_argument("--fault-plan", default=None)
    dm.add_argument("--ready-timeout", type=float, default=180.0)
    dm.add_argument("--drain-timeout", type=float, default=600.0)
    dm.add_argument("--out", default=None)
    return p


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    if args.cmd == "sweep":
        return cmd_sweep(args)
    if args.cmd == "ab":
        return cmd_ab(args)
    if args.cmd == "pipeab":
        return cmd_pipeab(args)
    if args.cmd == "mix":
        return cmd_mix(args)
    return cmd_daemon(args)


if __name__ == "__main__":
    sys.exit(main())
