"""Measured weighted edge-list -> CSR ingest at benchmark scale.

VERDICT r3 item 8: the r3 generic weighted path OOM-killed a scale-26
coalesce at 131 GB.  This records the r4 `cv_build_csr_w32` path
(int32-index-payload radix; cuvite_tpu/core/graph.py dispatch) on a
weighted R-MAT edge list: wall, coalesced edges, and RSS high-water.

Usage: python tools/weighted_ingest_bench.py [scale] [edge_factor]
Appends one line to tools/logs/weighted_ingest.log.
"""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def hwm_mb():
    with open("/proc/self/status") as f:
        s = f.read()
    return int(s.split("VmHWM:")[1].split()[0]) // 1024


def main():
    scale = int(sys.argv[1]) if len(sys.argv) > 1 else 25
    ef = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    from cuvite_tpu.core.graph import Graph
    from cuvite_tpu import native

    nv = 1 << scale
    ne = ef * nv
    t0 = time.perf_counter()
    src, dst = native.rmat_edges(scale, ne, 1, 0.57, 0.19, 0.19)
    # Deterministic synthetic weights (the R-MAT family is unweighted;
    # weights here only exercise the weighted coalesce at scale).
    w = ((src ^ dst) % 97).astype(np.float64) / 13.0 + 0.5
    gen_s = time.perf_counter() - t0
    gen_hwm = hwm_mb()

    # Record which builder the dispatch gate actually selects (the w32
    # path needs expanded count < 2^31: at scale 26 ef=16, symmetrize
    # doubles 2^30 edges to exactly 2^31 and the GENERIC path runs —
    # don't let that number masquerade as a w32 measurement).
    w32_gate = (len(src) >= native.MIN_NATIVE_EDGES and native.available()
                and (1 << 22) < nv <= (1 << 31)
                and 2 * len(src) < (1 << 31))
    t1 = time.perf_counter()
    g = Graph.from_edges(nv, src, dst, weights=w, symmetrize=True)
    build_s = time.perf_counter() - t1
    line = (f"weighted scale-{scale} ef={ef}: gen {gen_s:.0f}s "
            f"(hwm {gen_hwm} MB), from_edges {build_s:.0f}s "
            f"path={'w32' if w32_gate else 'generic'}, "
            f"nv={g.num_vertices} ne={g.num_edges} "
            f"wdtype={g.weights.dtype} total_hwm={hwm_mb()} MB")
    print(line)
    with open(os.path.join(REPO, "tools", "logs", "weighted_ingest.log"), "a") as f:
        f.write(line + "\n")


if __name__ == "__main__":
    main()
