#!/bin/bash
# Background watcher (round 4, pass 3): probe the axon tunnel every
# ~10 min; on an alive window run tools/tpu_ladder3.py (bench-first).
# Stops when tools/TPU_LADDER3_DONE or tools/TPU_WATCH_STOP exists.
cd "$(dirname "$0")/.."
while true; do
  [ -f tools/TPU_LADDER3_DONE ] && exit 0
  [ -f tools/TPU_WATCH_STOP ] && exit 0
  python tools/tpu_ladder3.py >> tools/tpu_watch.out 2>&1
  [ -f tools/TPU_LADDER3_DONE ] && exit 0
  sleep 600
done
