"""Compile-budget + jaxpr audit CLI (graftlint tier 3).

Runs the real serving-path entries at ONE representative small slab
class ((4096, 16384) — the floor every tiny graph canonicalizes to) on
CPU, watches what XLA actually compiles (obs/compile_watch.py), and
grades the observed compile set against the checked-in closed manifest
``tools/compile_budget.json``:

  * B001 — a module compiled that matches nothing in the manifest
    (a NEW program appeared on the serving path);
  * B002 — rerunning an entry with different batch CONTENT (same slab
    class, B, engine; only the weights change) compiled anything:
    content has entered a compile key, the exact regression PR 10 could
    only catch by hand measurement;
  * B003 — compile count over the entry's budget;
  * J001/J002/J003 — the traced per-phase jaxprs contain 64-bit ops,
    host callbacks, or in-graph transfers (analysis/jaxpr_audit.py).

Usage:
    python tools/compile_audit.py                 # audit, exit 1 on FAIL
    python tools/compile_audit.py --write-manifest  # regenerate budget
    python tools/compile_audit.py --json            # machine-readable
    python tools/compile_audit.py --entries batched_fused_B2 ...

The audit is deterministic: graph structure is fixed, only weights vary
with the content seed, and everything runs on the forced-CPU 8-virtual-
device backend tier-1 uses (the same programs either way).  The tier-1
test (tests/test_analysis.py) runs the same scenarios in-process, plus
a sabotage fixture asserting B002 actually fires when content is
threaded into a static argument.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

MANIFEST = os.path.join(REPO_ROOT, "tools", "compile_budget.json")

# Tier-1's backend shape, replicated for standalone runs: 8 virtual CPU
# devices so the batch-axis mesh (and therefore the compiled module
# set) matches what the in-suite audit and the manifest record.
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms",
                  os.environ.get("CUVITE_PLATFORM", "cpu"))

from cuvite_tpu.analysis.jaxpr_audit import (  # noqa: E402
    audit_entry,
    audit_jaxprs,
    load_manifest,
    tiny_graphs,
    write_manifest,
)

MAX_PHASES = 2  # enough to cover the coarse-class programs


def _run_batched(engine):
    def run(seed):
        from cuvite_tpu.louvain.batched import cluster_many

        cluster_many(tiny_graphs(b=2, content_seed=seed),
                     threshold=1.0e-6, max_phases=MAX_PHASES,
                     engine=engine)
    return run


def _run_solo(engine):
    def run(seed):
        from cuvite_tpu.louvain.driver import louvain_phases

        # Phase 0 only: the per-graph driver's COARSE classes are
        # content-dependent by design (maybe_shrink_to_class follows the
        # coarsened sizes), so a multi-phase solo run recompiles
        # legitimately when content changes; the batched entries cover
        # the multi-phase budget instead.
        louvain_phases(tiny_graphs(b=1, content_seed=seed)[0],
                       engine=engine, max_phases=1)
    return run


def _run_serve(seed):
    from cuvite_tpu.serve.queue import LouvainServer, ServeConfig

    server = LouvainServer(ServeConfig(
        b_max=2, linger_s=0.0, engine="bucketed", max_phases=MAX_PHASES))
    for g in tiny_graphs(b=2, content_seed=seed):
        server.submit(g)
    server.step(force=True)


def _run_subrow(seed):
    """Packed sub-row batch (ISSUE 20): three tiny small-class graphs
    merged as fenced sub-rows of (8192, 32768) rows.  The compile key
    is (row class, B, n_sub, engine) — batch CONTENT and sub-row
    OCCUPANCY are runtime operands, so the content-seed rerun must
    compile nothing (B002 otherwise)."""
    from cuvite_tpu.core.batch import subrow_layout_for
    from cuvite_tpu.louvain.batched import cluster_packed

    layout = subrow_layout_for((4096, 16384), (8192, 32768))
    cluster_packed(tiny_graphs(b=3, content_seed=seed), layout,
                   threshold=1.0e-6, max_phases=MAX_PHASES)


# Entry registry: name -> run(content_seed).  Names match the manifest.
ENTRIES = {
    "solo_fused_sort": _run_solo("sort"),
    "solo_bucketed": _run_solo("auto"),
    "batched_fused_B2": _run_batched("fused"),
    "batched_bucketed_B2": _run_batched("bucketed"),
    "serve_pack_bucketed_B2": _run_serve,
    "packed_subrow_B2": _run_subrow,
}


def run_audit(entry_names=None, manifest_path: str = MANIFEST,
              with_jaxprs: bool = True):
    """(results, jaxpr_findings).  Shared by the CLI and the tier-1
    test — one implementation, one behavior."""
    try:
        manifest = load_manifest(manifest_path)
    except (OSError, ValueError):
        manifest = {"entries": {}}
    # Match against the UNION of every entry's modules: which entry a
    # shared program's compile lands on depends on jit-cache warmth and
    # run order (audited alone, the serve path compiles the batched
    # entries' programs itself).  Closedness holds at manifest level.
    union = sorted({p for e in manifest["entries"].values()
                    for p in e.get("modules", ())})
    results = []
    for name in (entry_names or ENTRIES):
        results.append(audit_entry(
            name, ENTRIES[name], manifest["entries"].get(name),
            extra_patterns=union))
    jaxpr_findings = audit_jaxprs() if with_jaxprs else []
    return results, jaxpr_findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python tools/compile_audit.py",
        description="cuvite_tpu compile-budget + jaxpr audit (tier 3)")
    ap.add_argument("--entries", nargs="*", default=None,
                    choices=sorted(ENTRIES), help="subset of entries")
    ap.add_argument("--manifest", default=MANIFEST)
    ap.add_argument("--write-manifest", action="store_true",
                    help="record the observed compile sets as the new "
                         "closed manifest (review the diff!)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    if args.write_manifest:
        entries = {}
        for name in (args.entries or ENTRIES):
            res = audit_entry(name, ENTRIES[name], manifest_entry={
                "modules": ["*"], "content_independent": False})
            mods = sorted(set(res.observed))
            entries[name] = {
                "modules": mods,
                # slack for jax-version drift in helper-jit names
                "max_compiles": len(res.observed) + 4,
                "content_independent": not res.recompiled,
            }
            print(f"{name}: {len(res.observed)} compile(s), "
                  f"{len(res.recompiled)} on content change")
        env = {
            "platform": jax.default_backend(),
            "device_count": jax.device_count(),
            "max_phases": MAX_PHASES,
            "slab_class": [4096, 16384],
        }
        write_manifest(args.manifest, entries, env)
        print(f"wrote {args.manifest}")
        return 0

    results, jaxpr_findings = run_audit(args.entries, args.manifest)
    findings = [f for r in results for f in r.findings] + jaxpr_findings
    if args.json:
        print(json.dumps({
            "entries": [{
                "entry": r.entry, "observed": r.observed,
                "recompiled": r.recompiled,
                "findings": [f.to_dict() for f in r.findings],
            } for r in results],
            "jaxpr_findings": [f.to_dict() for f in jaxpr_findings],
            "ok": not findings,
        }, indent=2))
    else:
        for r in results:
            state = "ok" if r.ok else "FAIL"
            print(f"{r.entry}: {len(r.observed)} compile(s), "
                  f"{len(r.recompiled)} on content change [{state}]")
        for f in findings:
            print(f.format())
        print(f"compile_audit: {len(findings)} finding(s); "
              f"{'FAIL' if findings else 'ok'}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
