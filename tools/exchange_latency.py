"""all_to_all launch-latency microbenchmark: bracket the exchange cutover.

The exchange='auto' cutover (driver.AUTO_SPARSE_MIN_VERTICES) decides when
the sparse ghost plan replaces the replicated exchange.  Its comment keeps
making a LAUNCH-LATENCY argument ("per-launch latency charges per
collective on real ICI") that no tool of this repo had ever measured
(VERDICT r5 weak #3 / next #9).  This microbenchmark measures the three
collective patterns the two exchanges are made of, on the mesh it is run
on, and prints the honest bracket:

  all_gather(n)  — the replicated exchange's community pull (plus two
                   psum'd tables of the same extent => ~3 launches of
                   O(nv_total) bytes per chip per iteration);
  psum(n)        — the replicated tables' reduction;
  all_to_all(b)  — the sparse exchange's transport (3 launches per
                   iteration after the round-3 packing, pinned by
                   test_sparse_step_lowers_to_three_all_to_all; each moves
                   O(ghosts + S*budget) elements, ~ghost_frac * nv).

Per size: jitted shard_map'd op, warm-up call, then min-of-R wall times
(min, not mean: scheduler noise only ever ADDS).  The launch latency is
the time of the smallest size (bandwidth term ~0); the crossover bracket
is the nv span where 3 modeled sparse launches become cheaper than 3
modeled replicated launches.  On a virtual CPU mesh the numbers describe
THIS host (shared-memory "collectives", compute-bound — see the
BASELINE.md round-7 note); on a real TPU slice they describe ICI, which
is the measurement the cutover comment actually wants.  Either way the
tool prints a machine-readable JSON line so the bracket can be cited.

Two-axis mode (``--mesh DCNxICI``, ISSUE 18): the same ladder measured
per axis of the hybrid mesh the two-level exchange runs on — the
intra-ICI all_gather/psum that materializes the group community tables
vs the cross-DCN all_to_all that moves the sparse ghosts.  On a real
slice the ICI axis is the fast fabric and the DCN axis the slow one, so
the per-axis launch latencies are the two constants the two-level
design trades against each other; on a virtual CPU mesh both axes are
the same host and the split only proves the harness.

Usage:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/exchange_latency.py --devices 8
    python tools/exchange_latency.py --devices 8 --ghost-frac 0.1 --json
    python tools/exchange_latency.py --mesh 2x4 --json --out lat.json
"""

import argparse
import functools
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def build_argparser():
    ap = argparse.ArgumentParser(
        description="all_to_all / all_gather launch-latency microbenchmark")
    ap.add_argument("--devices", type=int, default=8,
                    help="mesh size (virtual CPU devices are forced when "
                         "the backend is cpu and XLA_FLAGS doesn't already "
                         "ask for them)")
    ap.add_argument("--repeats", type=int, default=30,
                    help="timed calls per size (min is reported)")
    ap.add_argument("--min-log2", type=int, default=7,
                    help="smallest per-chip element count, log2")
    ap.add_argument("--max-log2", type=int, default=22,
                    help="largest per-chip element count, log2")
    ap.add_argument("--ghost-frac", type=float, default=0.10,
                    help="modeled ghost+budget fraction of nv for the "
                         "sparse side (scale-free; rmat partitions measure "
                         "0.05-0.2 per shard)")
    ap.add_argument("--mesh", metavar="DCNxICI", default=None,
                    help="two-axis mode: measure each collective per "
                         "hybrid-mesh axis (intra-ICI table gather vs "
                         "cross-DCN ghost all_to_all) instead of the flat "
                         "1-D ladder")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON line at the end")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="also write the JSON verdict to FILE (the ladder's "
                         "stage L checkpoints through this)")
    return ap


def _emit(verdict, args):
    if args.out:
        with open(args.out, "w") as f:
            json.dump(verdict, f, indent=2, sort_keys=True)
            f.write("\n")
    if args.json:
        print(json.dumps(verdict))


def _two_axis(args, shape, plat) -> int:
    """Per-axis ladder on the hybrid (dcn, ici) mesh: the intra-ICI
    collectives that materialize the two-level exchange's group tables
    (all_gather + psum over the fast submesh) vs the cross-DCN
    all_to_all that moves its sparse ghosts, plus the both-axes global
    gather the scheme exists to avoid.  The per-axis launch latencies
    are the constants the two-level trade rests on."""
    import functools
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from cuvite_tpu.comm.mesh import (
        DCN_AXIS,
        ICI_AXIS,
        make_hybrid_mesh,
        shard_map,
    )

    n_dcn, n_ici = shape
    S = n_dcn * n_ici
    mesh = make_hybrid_mesh(n_dcn, n_ici)
    spec = P((DCN_AXIS, ICI_AXIS))

    def timed(fn, arr):
        jax.block_until_ready(fn(arr))
        best = float("inf")
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(arr))
            best = min(best, time.perf_counter() - t0)
        return best

    def wrap(body, out=P()):
        return jax.jit(functools.partial(
            shard_map, mesh=mesh, in_specs=spec, out_specs=out,
            check_vma=False)(body))

    @functools.lru_cache(maxsize=None)
    def ops():
        def ag_ici(x):
            return jax.lax.all_gather(x, ICI_AXIS, tiled=True)  # graftlint: replicated-ok=scope=bench; launch-latency microbenchmark measuring the ICI table gather itself

        def ps_ici(x):
            return jax.lax.psum(x, ICI_AXIS)  # graftlint: replicated-ok=scope=bench; same microbenchmark, psum arm

        def ag_glob(x):
            return jax.lax.all_gather(x, (DCN_AXIS, ICI_AXIS), tiled=True)  # graftlint: replicated-ok=scope=bench; the global gather the two-level exchange avoids — measured to cite the cost

        def a2a_dcn(x):
            return jax.lax.all_to_all(x, DCN_AXIS, 0, 0, tiled=True)

        return (wrap(ag_ici), wrap(ps_ici), wrap(ag_glob),
                wrap(a2a_dcn, out=spec))

    ag_i, ps_i, ag_g, a2a_d = ops()
    rows = []
    print(f"# hybrid mesh: {n_dcn}x{n_ici} {plat} (dcn x ici); per-chip "
          f"elements n; times are min-of-{args.repeats} wall seconds",
          flush=True)
    print(f"# {'n/chip':>10} {'ag(ici)':>12} {'psum(ici)':>12} "
          f"{'ag(global)':>12} {'a2a(dcn)':>12}")
    for k in range(args.min_log2, args.max_log2 + 1):
        n = 1 << k
        x = jnp.asarray(np.ones(S * n, dtype=np.float32))
        t_agi = timed(ag_i, x)
        t_psi = timed(ps_i, x)
        t_agg = timed(ag_g, x)
        b = max(n // n_dcn, 1)
        y = jnp.asarray(np.ones(S * n_dcn * b, dtype=np.float32))
        t_aad = timed(a2a_d, y)
        rows.append({"n_per_chip": n, "all_gather_ici_s": t_agi,
                     "psum_ici_s": t_psi, "all_gather_global_s": t_agg,
                     "all_to_all_dcn_s": t_aad})
        print(f"  {n:>10} {t_agi:>12.3e} {t_psi:>12.3e} {t_agg:>12.3e} "
              f"{t_aad:>12.3e}", flush=True)

    lat = {k: rows[0][k] for k in ("all_gather_ici_s", "psum_ici_s",
                                   "all_gather_global_s",
                                   "all_to_all_dcn_s")}
    print(f"# per-axis launch latency (smallest size): "
          f"ag(ici) {lat['all_gather_ici_s']*1e6:.0f}us, "
          f"psum(ici) {lat['psum_ici_s']*1e6:.0f}us, "
          f"ag(global) {lat['all_gather_global_s']*1e6:.0f}us, "
          f"a2a(dcn) {lat['all_to_all_dcn_s']*1e6:.0f}us")
    # The two-level per-iteration transport at the largest measured
    # per-chip count: 2 ICI gathers build the group tables (comm +
    # vdeg at the nv/|dcn| window) + 3 DCN all_to_alls move the ghosts
    # (~ghost_frac of the window); the flat alternative pays the global
    # gather + 2 global psums at the full nv window.
    last = rows[-1]
    t_two = (2.0 * last["all_gather_ici_s"]
             + 3.0 * last["all_to_all_dcn_s"] * args.ghost_frac)
    t_flat = (last["all_gather_global_s"] + 2.0 * last["psum_ici_s"]
              * n_dcn)
    print(f"# modeled per-iteration transport at n/chip="
          f"{last['n_per_chip']} (ghost_frac={args.ghost_frac}): "
          f"two-level {t_two:.3e}s vs flat-replicated {t_flat:.3e}s")
    verdict = {
        "platform": plat, "mesh": f"{n_dcn}x{n_ici}", "devices": S,
        "ghost_frac": args.ghost_frac,
        "launch_latency_s": lat,
        "rows": rows,
        "modeled_iteration_s": {"twolevel": t_two,
                                "flat_replicated": t_flat},
        "note": ("per-axis collective ladder on the hybrid mesh; on a "
                 "virtual CPU mesh both axes are the same host — the "
                 "split is meaningful on real ICI/DCN fabric only"),
    }
    _emit(verdict, args)
    return 0


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    shape = None
    if args.mesh:
        d_s, _, i_s = args.mesh.lower().replace("×", "x").partition("x")
        try:
            shape = (int(d_s), int(i_s or 1))
        except ValueError:
            raise SystemExit(f"--mesh must be DCNxICI (e.g. 2x4), "
                             f"got {args.mesh!r}")
        if shape[0] < 1 or shape[1] < 1:
            raise SystemExit("--mesh factors must be >= 1")
        args.devices = shape[0] * shape[1]
    if "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from cuvite_tpu.comm.mesh import VERTEX_AXIS, make_mesh, shard_map

    S = args.devices
    plat = jax.devices()[0].platform

    if shape is not None:
        return _two_axis(args, shape, plat)

    mesh = make_mesh(S)

    def timed(fn, arr):
        out = fn(arr)
        jax.block_until_ready(out)
        best = float("inf")
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(arr))
            best = min(best, time.perf_counter() - t0)
        return best

    @functools.lru_cache(maxsize=None)
    def ag_fn():
        @jax.jit
        @functools.partial(shard_map, mesh=mesh, in_specs=P(VERTEX_AXIS),
                           out_specs=P(), check_vma=False)
        def ag(x):
            return jax.lax.all_gather(x, VERTEX_AXIS, tiled=True)  # graftlint: replicated-ok=scope=bench; launch-latency microbenchmark measuring this collective itself, not a product table
        return ag

    @functools.lru_cache(maxsize=None)
    def psum_fn():
        @jax.jit
        @functools.partial(shard_map, mesh=mesh, in_specs=P(VERTEX_AXIS),
                           out_specs=P(), check_vma=False)
        def ps(x):
            return jax.lax.psum(x, VERTEX_AXIS)
        return ps

    @functools.lru_cache(maxsize=None)
    def a2a_fn():
        @jax.jit
        @functools.partial(shard_map, mesh=mesh, in_specs=P(VERTEX_AXIS),
                           out_specs=P(VERTEX_AXIS), check_vma=False)
        def a2a(x):
            return jax.lax.all_to_all(x, VERTEX_AXIS, 0, 0, tiled=True)
        return a2a

    rows = []
    print(f"# mesh: {S}x {plat}; per-chip elements n; times are "
          f"min-of-{args.repeats} wall seconds", flush=True)
    print(f"# {'n/chip':>10} {'all_gather':>12} {'psum':>12} "
          f"{'all_to_all':>12}")
    for k in range(args.min_log2, args.max_log2 + 1):
        n = 1 << k
        x = jnp.asarray(np.ones(S * n, dtype=np.float32))
        t_ag = timed(ag_fn(), x)
        t_ps = timed(psum_fn(), x)
        # all_to_all: same per-chip byte count, [S, n/S]-blocked transport
        # (pad so every pair block is nonempty).
        b = max(n // S, 1)
        y = jnp.asarray(np.ones((S * S, b), dtype=np.float32))
        t_aa = timed(a2a_fn(), y)
        rows.append({"n_per_chip": n, "all_gather_s": t_ag,
                     "psum_s": t_ps, "all_to_all_s": t_aa})
        print(f"  {n:>10} {t_ag:>12.3e} {t_ps:>12.3e} {t_aa:>12.3e}",
              flush=True)

    # Launch latency: the smallest size's time, where the bandwidth term
    # is negligible (a few hundred bytes/chip).
    lat = {k: rows[0][k] for k in ("all_gather_s", "psum_s",
                                   "all_to_all_s")}

    def interp(series, n):
        """Piecewise-linear read of a measured curve at per-chip count n
        (clamped; log-domain interpolation between the pow2 samples)."""
        pts = [(r["n_per_chip"], r[series]) for r in rows]
        if n <= pts[0][0]:
            return pts[0][1]
        for (n0, t0), (n1, t1) in zip(pts, pts[1:]):
            if n <= n1:
                f = (np.log2(n) - np.log2(n0)) / (np.log2(n1) - np.log2(n0))
                return t0 + f * (t1 - t0)
        return pts[-1][1]

    # Per-iteration exchange COLLECTIVE model over padded total vertex
    # count nv (transport only — the sparse env's extra per-iteration
    # sort/route compute is deliberately out of scope, it is what
    # tools/exchange_bench.py end-to-ends):
    #   replicated: 3 launches of nv elements per chip
    #     (all_gather(comm) + psum(comm_deg) + psum(comm_size))
    #   sparse:     3 all_to_all launches of ~ghost_frac * nv per chip
    #     (the packed ghost pull + owner-route fwd + reply; ghost_frac is
    #     per-shard ghosts+budget over TOTAL nv)
    print(f"# modeled per-iteration exchange transport "
          f"(ghost_frac={args.ghost_frac}):")
    print(f"# {'nv_total':>12} {'replicated':>12} {'sparse':>12}")
    model = []
    for k in range(args.min_log2 + 3, args.max_log2 + int(np.log2(S)) + 1):
        nv = 1 << k
        t_rep = (interp("all_gather_s", nv)
                 + 2.0 * interp("psum_s", nv))
        t_sp = 3.0 * interp("all_to_all_s",
                            max(int(args.ghost_frac * nv), 1))
        model.append((nv, t_rep, t_sp))
        print(f"  {nv:>12} {t_rep:>12.3e} {t_sp:>12.3e}")
    first_win = next((i for i, (_, tr, ts) in enumerate(model) if ts < tr),
                     None)
    if first_win is None:
        lo = hi = None
    elif first_win == 0:
        lo, hi = None, model[0][0]   # sparse wins at/below the range floor
    else:
        lo, hi = model[first_win - 1][0], model[first_win][0]
    verdict = {
        "platform": plat, "devices": S, "ghost_frac": args.ghost_frac,
        "launch_latency_s": lat,
        "crossover_bracket_nv": [lo, hi],
        "note": ("transport-only model; launch latencies from the "
                 "smallest measured size"),
    }
    print(f"# launch latency (smallest size): "
          f"all_gather {lat['all_gather_s']*1e6:.0f}us, "
          f"psum {lat['psum_s']*1e6:.0f}us, "
          f"all_to_all {lat['all_to_all_s']*1e6:.0f}us")
    if first_win is None:
        print("# crossover: NOT reached — the 3 replicated launches stay "
              "cheaper over the whole modeled range; the cutover remains "
              "the MEMORY bound (driver.AUTO_SPARSE_MIN_VERTICES)")
    elif first_win == 0:
        print(f"# crossover: at or below nv={hi} (sparse transport already "
              f"cheaper at the range floor) — the collective model does "
              f"NOT bind the cutover; the HBM bound does")
    else:
        print(f"# crossover bracket: nv in [{lo}, {hi}]")
    _emit(verdict, args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
