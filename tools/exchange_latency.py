"""all_to_all launch-latency microbenchmark: bracket the exchange cutover.

The exchange='auto' cutover (driver.AUTO_SPARSE_MIN_VERTICES) decides when
the sparse ghost plan replaces the replicated exchange.  Its comment keeps
making a LAUNCH-LATENCY argument ("per-launch latency charges per
collective on real ICI") that no tool of this repo had ever measured
(VERDICT r5 weak #3 / next #9).  This microbenchmark measures the three
collective patterns the two exchanges are made of, on the mesh it is run
on, and prints the honest bracket:

  all_gather(n)  — the replicated exchange's community pull (plus two
                   psum'd tables of the same extent => ~3 launches of
                   O(nv_total) bytes per chip per iteration);
  psum(n)        — the replicated tables' reduction;
  all_to_all(b)  — the sparse exchange's transport (3 launches per
                   iteration after the round-3 packing, pinned by
                   test_sparse_step_lowers_to_three_all_to_all; each moves
                   O(ghosts + S*budget) elements, ~ghost_frac * nv).

Per size: jitted shard_map'd op, warm-up call, then min-of-R wall times
(min, not mean: scheduler noise only ever ADDS).  The launch latency is
the time of the smallest size (bandwidth term ~0); the crossover bracket
is the nv span where 3 modeled sparse launches become cheaper than 3
modeled replicated launches.  On a virtual CPU mesh the numbers describe
THIS host (shared-memory "collectives", compute-bound — see the
BASELINE.md round-7 note); on a real TPU slice they describe ICI, which
is the measurement the cutover comment actually wants.  Either way the
tool prints a machine-readable JSON line so the bracket can be cited.

Usage:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/exchange_latency.py --devices 8
    python tools/exchange_latency.py --devices 8 --ghost-frac 0.1 --json
"""

import argparse
import functools
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def build_argparser():
    ap = argparse.ArgumentParser(
        description="all_to_all / all_gather launch-latency microbenchmark")
    ap.add_argument("--devices", type=int, default=8,
                    help="mesh size (virtual CPU devices are forced when "
                         "the backend is cpu and XLA_FLAGS doesn't already "
                         "ask for them)")
    ap.add_argument("--repeats", type=int, default=30,
                    help="timed calls per size (min is reported)")
    ap.add_argument("--min-log2", type=int, default=7,
                    help="smallest per-chip element count, log2")
    ap.add_argument("--max-log2", type=int, default=22,
                    help="largest per-chip element count, log2")
    ap.add_argument("--ghost-frac", type=float, default=0.10,
                    help="modeled ghost+budget fraction of nv for the "
                         "sparse side (scale-free; rmat partitions measure "
                         "0.05-0.2 per shard)")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON line at the end")
    return ap


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    if "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from cuvite_tpu.comm.mesh import VERTEX_AXIS, make_mesh, shard_map

    S = args.devices
    mesh = make_mesh(S)
    plat = jax.devices()[0].platform

    def timed(fn, arr):
        out = fn(arr)
        jax.block_until_ready(out)
        best = float("inf")
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(arr))
            best = min(best, time.perf_counter() - t0)
        return best

    @functools.lru_cache(maxsize=None)
    def ag_fn():
        @jax.jit
        @functools.partial(shard_map, mesh=mesh, in_specs=P(VERTEX_AXIS),
                           out_specs=P(), check_vma=False)
        def ag(x):
            return jax.lax.all_gather(x, VERTEX_AXIS, tiled=True)  # graftlint: replicated-ok=launch-latency microbenchmark measuring this collective itself
        return ag

    @functools.lru_cache(maxsize=None)
    def psum_fn():
        @jax.jit
        @functools.partial(shard_map, mesh=mesh, in_specs=P(VERTEX_AXIS),
                           out_specs=P(), check_vma=False)
        def ps(x):
            return jax.lax.psum(x, VERTEX_AXIS)
        return ps

    @functools.lru_cache(maxsize=None)
    def a2a_fn():
        @jax.jit
        @functools.partial(shard_map, mesh=mesh, in_specs=P(VERTEX_AXIS),
                           out_specs=P(VERTEX_AXIS), check_vma=False)
        def a2a(x):
            return jax.lax.all_to_all(x, VERTEX_AXIS, 0, 0, tiled=True)
        return a2a

    rows = []
    print(f"# mesh: {S}x {plat}; per-chip elements n; times are "
          f"min-of-{args.repeats} wall seconds", flush=True)
    print(f"# {'n/chip':>10} {'all_gather':>12} {'psum':>12} "
          f"{'all_to_all':>12}")
    for k in range(args.min_log2, args.max_log2 + 1):
        n = 1 << k
        x = jnp.asarray(np.ones(S * n, dtype=np.float32))
        t_ag = timed(ag_fn(), x)
        t_ps = timed(psum_fn(), x)
        # all_to_all: same per-chip byte count, [S, n/S]-blocked transport
        # (pad so every pair block is nonempty).
        b = max(n // S, 1)
        y = jnp.asarray(np.ones((S * S, b), dtype=np.float32))
        t_aa = timed(a2a_fn(), y)
        rows.append({"n_per_chip": n, "all_gather_s": t_ag,
                     "psum_s": t_ps, "all_to_all_s": t_aa})
        print(f"  {n:>10} {t_ag:>12.3e} {t_ps:>12.3e} {t_aa:>12.3e}",
              flush=True)

    # Launch latency: the smallest size's time, where the bandwidth term
    # is negligible (a few hundred bytes/chip).
    lat = {k: rows[0][k] for k in ("all_gather_s", "psum_s",
                                   "all_to_all_s")}

    def interp(series, n):
        """Piecewise-linear read of a measured curve at per-chip count n
        (clamped; log-domain interpolation between the pow2 samples)."""
        pts = [(r["n_per_chip"], r[series]) for r in rows]
        if n <= pts[0][0]:
            return pts[0][1]
        for (n0, t0), (n1, t1) in zip(pts, pts[1:]):
            if n <= n1:
                f = (np.log2(n) - np.log2(n0)) / (np.log2(n1) - np.log2(n0))
                return t0 + f * (t1 - t0)
        return pts[-1][1]

    # Per-iteration exchange COLLECTIVE model over padded total vertex
    # count nv (transport only — the sparse env's extra per-iteration
    # sort/route compute is deliberately out of scope, it is what
    # tools/exchange_bench.py end-to-ends):
    #   replicated: 3 launches of nv elements per chip
    #     (all_gather(comm) + psum(comm_deg) + psum(comm_size))
    #   sparse:     3 all_to_all launches of ~ghost_frac * nv per chip
    #     (the packed ghost pull + owner-route fwd + reply; ghost_frac is
    #     per-shard ghosts+budget over TOTAL nv)
    print(f"# modeled per-iteration exchange transport "
          f"(ghost_frac={args.ghost_frac}):")
    print(f"# {'nv_total':>12} {'replicated':>12} {'sparse':>12}")
    model = []
    for k in range(args.min_log2 + 3, args.max_log2 + int(np.log2(S)) + 1):
        nv = 1 << k
        t_rep = (interp("all_gather_s", nv)
                 + 2.0 * interp("psum_s", nv))
        t_sp = 3.0 * interp("all_to_all_s",
                            max(int(args.ghost_frac * nv), 1))
        model.append((nv, t_rep, t_sp))
        print(f"  {nv:>12} {t_rep:>12.3e} {t_sp:>12.3e}")
    first_win = next((i for i, (_, tr, ts) in enumerate(model) if ts < tr),
                     None)
    if first_win is None:
        lo = hi = None
    elif first_win == 0:
        lo, hi = None, model[0][0]   # sparse wins at/below the range floor
    else:
        lo, hi = model[first_win - 1][0], model[first_win][0]
    verdict = {
        "platform": plat, "devices": S, "ghost_frac": args.ghost_frac,
        "launch_latency_s": lat,
        "crossover_bracket_nv": [lo, hi],
        "note": ("transport-only model; launch latencies from the "
                 "smallest measured size"),
    }
    print(f"# launch latency (smallest size): "
          f"all_gather {lat['all_gather_s']*1e6:.0f}us, "
          f"psum {lat['psum_s']*1e6:.0f}us, "
          f"all_to_all {lat['all_to_all_s']*1e6:.0f}us")
    if first_win is None:
        print("# crossover: NOT reached — the 3 replicated launches stay "
              "cheaper over the whole modeled range; the cutover remains "
              "the MEMORY bound (driver.AUTO_SPARSE_MIN_VERTICES)")
    elif first_win == 0:
        print(f"# crossover: at or below nv={hi} (sparse transport already "
              f"cheaper at the range floor) — the collective model does "
              f"NOT bind the cutover; the HBM bound does")
    else:
        print(f"# crossover bracket: nv in [{lo}, {hi}]")
    if args.json:
        print(json.dumps(verdict))
    return 0


if __name__ == "__main__":
    sys.exit(main())
