"""Sparse-vs-replicated exchange A/B on the virtual 8-device CPU mesh.

Re-measures the gap after the round-3 collective packing (7 all_to_all
per iteration -> 3, comm/exchange.py) — VERDICT r2 item 5.  The sparse
plan is a MEMORY play (O(owned+ghosts) per-chip state vs O(nv_total)); a
shrinking time gap is what makes the 2^26 auto-cutover
(driver.AUTO_SPARSE_MIN_VERTICES) safe.

Usage:
    python tools/exchange_bench.py            # scales 18 20
    AB_SCALES="18" python tools/exchange_bench.py
"""

import os
import subprocess
import sys
import time

# Virtual 8-device mesh: must precede jax backend init (see conftest.py).
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("CUVITE_PLATFORM", "cpu")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _common  # noqa: F401,E402  (backend pin + compile cache)

import jax  # noqa: E402

from cuvite_tpu.io.generate import generate_rmat  # noqa: E402
from cuvite_tpu.louvain.driver import louvain_phases  # noqa: E402


def _vm_hwm_mib():
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmHWM:"):
                    return int(line.split()[1]) // 1024
    except OSError:
        pass
    return -1


def run_one(scale: int, nsh: int, exchange: str):
    g = generate_rmat(scale, edge_factor=16, seed=1)
    # warm-up run eats compiles; timed run is steady-state
    louvain_phases(g, nshards=nsh, exchange=exchange)
    t0 = time.perf_counter()
    res = louvain_phases(g, nshards=nsh, exchange=exchange)
    wall = time.perf_counter() - t0
    print(f"scale={scale} exchange={exchange:10s} wall={wall:8.1f}s "
          f"Q={res.modularity:.5f} iters={res.total_iterations} "
          f"rss_hwm={_vm_hwm_mib()}MiB",
          flush=True)
    return wall


def main():
    scales = [int(s) for s in os.environ.get("AB_SCALES", "18 20").split()]
    nsh = int(os.environ.get("AB_SHARDS", "8"))
    # Parse ONCE, up front: a malformed value is reported and replaced
    # by the default BEFORE any child launches — not discovered as a
    # ValueError partway through a multi-hour sweep.
    try:
        child_timeout = float(os.environ.get("AB_CHILD_TIMEOUT") or 7200)
    except ValueError:
        print(f"# ignoring malformed AB_CHILD_TIMEOUT="
              f"{os.environ.get('AB_CHILD_TIMEOUT')!r}; using 7200s",
              flush=True)
        child_timeout = 7200.0
    one = os.environ.get("AB_EXCHANGE")  # subprocess mode: one config
    print(f"# backend={jax.default_backend()} "
          f"devices={len(jax.devices())} shards={nsh}", flush=True)
    if one:
        for scale in scales:
            run_one(scale, nsh, one)
        return
    for scale in scales:
        row = {}
        for exchange in ("replicated", "sparse"):
            # Per-config SUBPROCESS: independent RSS high-water (the
            # sparse plan's whole point is the memory footprint) and no
            # shared jit caches between the two configs.
            env = dict(os.environ, AB_SCALES=str(scale), AB_EXCHANGE=exchange,
                       AB_SHARDS=str(nsh))
            try:
                # Generous ceiling: the slowest measured config (sparse,
                # scale 22) ran ~16 min; the 2h default covers every
                # scale this host can hold plus cold-compile headroom,
                # while still unwedging an A/B run whose child hit a
                # pathological stall (TPU client handshake, OOM thrash).
                out = subprocess.run(
                    [sys.executable, os.path.abspath(__file__)],
                    env=env, capture_output=True, text=True,
                    timeout=child_timeout)
            except subprocess.TimeoutExpired as e:
                # Mirror the rc != 0 branch: a killed child must be LOUD,
                # not a silently missing row in the A/B table.
                tail = (e.stderr or b"")
                tail = tail.decode(errors="replace") \
                    if isinstance(tail, bytes) else tail
                print(f"scale={scale} exchange={exchange}: TIMEOUT after "
                      f"{e.timeout:.0f}s (child killed) {tail[-400:]}",
                      flush=True)
                continue
            if out.returncode != 0:
                # A child that OOMs/crashes after printing its header must
                # be LOUD, not reduced to its last stdout line.
                print(f"scale={scale} exchange={exchange}: "
                      f"rc={out.returncode} "
                      f"{(out.stderr or '')[-400:]}", flush=True)
            elif out.stdout.strip():
                print(out.stdout.strip().splitlines()[-1], flush=True)
            for line in out.stdout.splitlines():
                if line.startswith(f"scale={scale} exchange={exchange}"):
                    row[exchange] = float(line.split("wall=")[1].split("s")[0])
        if "replicated" in row and "sparse" in row:
            print(f"scale={scale} sparse/replicated = "
                  f"{row['sparse'] / row['replicated']:.2f}x", flush=True)


if __name__ == "__main__":
    main()
