"""Single-step microbenchmark on the current default backend.

Times one bucketed sweep on a phase-0 R-MAT slab through the SAME
PhaseRunner the driver uses (no duplicated upload recipe), with an honest
readback (block_until_ready does not reliably block over the axon tunnel —
a scalar fetch does), and reports the dispatch round-trip latency
separately so device time can be read off the difference.

Usage:
    python tools/step_bench.py            # scale 18, default backend
    AB_SCALE=20 python tools/step_bench.py
    CUVITE_QUAD_MAX=256 python tools/step_bench.py   # dedup-cutover A/B
    CUVITE_PLATFORM=cpu python tools/step_bench.py   # pin cpu backend

NEVER run this under a tight external timeout on the TPU: a client killed
mid-compile can wedge the axon tunnel for hours.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _common  # noqa: F401  (backend pin + compile cache, must be first)

import jax

import jax.numpy as jnp
import numpy as np

from cuvite_tpu.core.distgraph import DistGraph
from cuvite_tpu.io.generate import generate_rmat
from cuvite_tpu.louvain.bucketed import QUADRATIC_MAX_WIDTH
from cuvite_tpu.louvain.driver import PhaseRunner


def main():
    scale = int(os.environ.get("AB_SCALE", "18"))
    print(f"# backend={jax.default_backend()} scale={scale} "
          f"QUAD_MAX={QUADRATIC_MAX_WIDTH}", flush=True)
    g = generate_rmat(scale, edge_factor=16, seed=1)
    t0 = time.perf_counter()
    dg = DistGraph.build(g, 1)
    runner = PhaseRunner(dg, engine="bucketed")
    # Force upload completion with a real readback (not block_until_ready).
    _ = np.asarray(runner.comm0[0:1])
    print(f"# plan+upload {time.perf_counter() - t0:.2f}s", flush=True)

    comm = runner.comm0

    def step(c):
        return runner._step(None, None, None, c, runner.vdeg,
                            runner.constant)

    t0 = time.perf_counter()
    out = step(comm)
    _ = float(out[1])
    print(f"# first call (compile) {time.perf_counter() - t0:.1f}s",
          flush=True)

    # Dispatch round-trip latency baseline: warm the exact timed
    # expression first, then take min-of-5 like the step timing.
    x = jnp.zeros(())
    _ = float(jnp.add(x, 1.0))
    rtts = []
    for _ in range(5):
        t0 = time.perf_counter()
        _ = float(jnp.add(x, 1.0))
        rtts.append(time.perf_counter() - t0)
    rtt = min(rtts)
    print(f"# scalar round-trip {rtt*1e3:.1f} ms", flush=True)

    c = comm
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        tgt, mod, _, _ = step(c)
        _ = float(mod)
        times.append(time.perf_counter() - t0)
        c = tgt
    best = min(times)
    print(f"step+fetch {best*1e3:.1f} ms  (~device {max(best-rtt,0)*1e3:.1f} "
          f"ms, {g.num_edges/max(best-rtt,1e-9)/1e6:.1f} M edges/s)")


if __name__ == "__main__":
    main()
