"""Single-step microbenchmark on the current default backend.

Times one bucketed sweep on a phase-0 R-MAT slab with an honest readback
(block_until_ready does not reliably block over the axon tunnel — a
scalar fetch does), and reports the tunnel round-trip latency separately
so device time can be read off the difference.

Usage:
    python tools/step_bench.py            # scale 18, default backend
    AB_SCALE=20 python tools/step_bench.py
    CUVITE_QUAD_MAX=256 python tools/step_bench.py   # dedup-cutover A/B

NEVER run this under a tight external timeout on the TPU: a client killed
mid-compile can wedge the axon tunnel for hours.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# CUVITE_PLATFORM=cpu forces the cpu backend BEFORE any device call (the
# axon plugin wins over a JAX_PLATFORMS env var, and its init hangs
# indefinitely while the tunnel is wedged).
if os.environ.get("CUVITE_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["CUVITE_PLATFORM"])

jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(os.path.dirname(
                      os.path.abspath(__file__))), ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import jax.numpy as jnp
import numpy as np

from cuvite_tpu.core.distgraph import DistGraph
from cuvite_tpu.io.generate import generate_rmat
from cuvite_tpu.louvain import driver as drv
from cuvite_tpu.louvain.bucketed import (
    QUADRATIC_MAX_WIDTH,
    BucketPlan,
    build_assemble_perm,
    compress_unit_weights,
)


def main():
    scale = int(os.environ.get("AB_SCALE", "18"))
    print(f"# backend={jax.default_backend()} scale={scale} "
          f"QUAD_MAX={QUADRATIC_MAX_WIDTH}", flush=True)
    g = generate_rmat(scale, edge_factor=16, seed=1)
    dg = DistGraph.build(g, 1)
    sh = dg.shards[0]
    plan = BucketPlan.build(np.asarray(sh.src), np.asarray(sh.dst),
                            np.asarray(sh.w), dg.nv_pad, 0)
    nvt = dg.total_padded_vertices
    vdt, wdt = np.int32, np.float32
    sentinel = int(np.iinfo(vdt).max)
    vdeg = jnp.asarray(dg.padded_weighted_degrees(), dtype=wdt)
    comm = jnp.arange(nvt, dtype=vdt)
    constant = jnp.asarray(1.0 / g.total_edge_weight_twice(), dtype=wdt)
    t0 = time.perf_counter()
    buckets = tuple(
        (jnp.asarray(b.verts.astype(vdt)), jnp.asarray(b.dst.astype(vdt)),
         jnp.asarray(compress_unit_weights(b.w, wdt)))
        for b in plan.buckets)
    heavy = (jnp.asarray(plan.heavy_src.astype(vdt)),
             jnp.asarray(plan.heavy_dst.astype(vdt)),
             jnp.asarray(plan.heavy_w.astype(wdt)))
    self_loop = jnp.asarray(plan.self_loop.astype(wdt))
    perm = jnp.asarray(build_assemble_perm(
        [b.verts for b in plan.buckets], nvt))
    jax.block_until_ready(buckets[-1])
    print(f"# upload {time.perf_counter() - t0:.2f}s "
          f"({sum(b.dst.size for b in plan.buckets)/1e6:.1f}M slots)",
          flush=True)

    def step(c):
        return drv._bucketed_jit(
            buckets, heavy, self_loop, c, vdeg, constant, perm,
            nv_total=nvt, sentinel=sentinel, accum_dtype="float32",
            pallas_flags=tuple([False] * len(buckets)),
            pallas_interpret=jax.default_backend() != "tpu")

    t0 = time.perf_counter()
    out = step(comm)
    _ = float(out[1])
    print(f"# first call (compile) {time.perf_counter() - t0:.1f}s",
          flush=True)

    # Tunnel/dispatch round-trip latency baseline.
    x = jnp.zeros(())
    _ = float(x)
    t0 = time.perf_counter()
    for _ in range(5):
        _ = float(jnp.add(x, 1.0))
    rtt = (time.perf_counter() - t0) / 5
    print(f"# scalar round-trip {rtt*1e3:.1f} ms", flush=True)

    c = comm
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        tgt, mod, _, _ = step(c)
        _ = float(mod)
        times.append(time.perf_counter() - t0)
        c = tgt
    best = min(times)
    print(f"step+fetch {best*1e3:.1f} ms  (~device {max(best-rtt,0)*1e3:.1f} "
          f"ms, {g.num_edges/max(best-rtt,1e-9)/1e6:.1f} M edges/s)")


if __name__ == "__main__":
    main()
