"""Width audit CLI (graftlint tier 6, dynamic half).

Traces the real device-path entries — the solo sort/bucketed/fused
phase programs, the batched execute, and the device coarsen+coalesce —
at the Friendster-class and R-MAT scale-28 slab shapes with ZERO
device bytes allocated (everything stages abstractly; a live-buffer
spy pins the invariant), and grades:

  * W001 — index-carrying jaxpr buffers (iota / cumsum run ids) wide
    enough for the extent they index at that shape;
  * W002 — every eligibility predicate actually selecting its
    fallback at the boundary: the packed int32 sort at
    kbits+sbits == 31 vs the lexicographic comparator one past (and
    the int64 pack under forced x64), coalesce_engine's nv ceiling
    and ds32 degrade, the SLAB_NE_MAX / FLAT_NV_MAX raise-guards, the
    DS_MIN_TOTAL_WEIGHT ds32 cutover;
  * W003 — audit integrity: crashed entries, a budget manifest that
    drifted from the code constants or the registry's declared max
    workload, or a nonzero live-buffer delta all fail CLOSED.

Usage:
    python tools/width_audit.py                   # full audit, exit 1 on FAIL
    python tools/width_audit.py --smoke           # fast self-check
    python tools/width_audit.py --entries solo_sort_step ...
    python tools/width_audit.py --workloads rmat_s28
    python tools/width_audit.py --json            # machine-readable
    python tools/width_audit.py --inventory       # width-ok annotated sites
    python tools/width_audit.py --out FILE.json   # checkpoint the report
                                                  # (ladder stage J)
    python tools/width_audit.py --write-budget    # regenerate the manifest

Dynamic results are never cached; the audit re-runs the traces every
time.  The tier-1 test (tests/test_widthcheck.py) runs the same audit
in-process plus sabotage fixtures proving R026-R028/W001-W002 convict
seeded overflows.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

BUDGET = os.path.join(REPO_ROOT, "tools", "width_budget.json")

import jax  # noqa: E402

jax.config.update("jax_platforms",
                  os.environ.get("CUVITE_PLATFORM", "cpu"))

from cuvite_tpu.analysis.widthaudit import (  # noqa: E402
    ENTRIES,
    audit_workloads,
    code_laws,
    run_width_audit,
    write_budget,
)

# --smoke: the packed-sort slab entry plus the boundary probes at ONE
# workload — the fast pre-commit self-check lint.sh --width-smoke
# runs (the probes carry most of W002's teeth; the full two-workload
# sweep runs in tier-1 and on the ladder).
SMOKE_ENTRIES = ("solo_sort_step", "coarsen_coalesce")
SMOKE_WORKLOADS = ("rmat_s28",)


def _inventory() -> list:
    """The width-ok inventory, rebuilt from the live tree (static
    tier; no jax involved)."""
    from cuvite_tpu.analysis.callgraph import summarize
    from cuvite_tpu.analysis.engine import SourceFile, iter_py_files
    from cuvite_tpu.analysis.widthcheck import width_inventory

    summaries = []
    for path in iter_py_files([os.path.join(REPO_ROOT, "cuvite_tpu"),
                               os.path.join(REPO_ROOT, "tools")]):
        rel = os.path.relpath(path, REPO_ROOT).replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as fh:
                summaries.append(summarize(SourceFile(fh.read(),
                                                      path=path, rel=rel)))
        except (OSError, SyntaxError, ValueError):
            continue
    return width_inventory(summaries)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python tools/width_audit.py",
        description="cuvite_tpu index-width audit (tier 6, W001-W003)")
    ap.add_argument("--entries", nargs="*", default=None,
                    choices=sorted(ENTRIES), help="subset of entries")
    ap.add_argument("--workloads", nargs="*", default=None,
                    metavar="NAME", help="subset of workloads "
                    "(default: " + " ".join(sorted(audit_workloads()))
                    + ")")
    ap.add_argument("--smoke", action="store_true",
                    help="fast self-check "
                         f"({', '.join(SMOKE_ENTRIES)} at "
                         f"{'/'.join(SMOKE_WORKLOADS)} + all probes)")
    ap.add_argument("--budget", default=BUDGET)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="write the JSON report to FILE (per-workload "
                         "sort facts + findings; ladder stage J "
                         "checkpoints these)")
    ap.add_argument("--inventory", action="store_true",
                    help="print the closed width-ok inventory and "
                         "exit (static tier only)")
    ap.add_argument("--write-budget", action="store_true",
                    help="regenerate the width manifest from the code "
                         "constants, the registry's max workload, and "
                         "the derived certification shapes — review "
                         "the diff before committing")
    args = ap.parse_args(argv)

    if args.inventory:
        inv = _inventory()
        if args.json:
            print(json.dumps(inv, indent=2))
        else:
            for ent in inv:
                print(f"{ent['rel']}:{ent['line']}: {ent['kind']} "
                      f"[{ent['bound']}] — {ent['reason']}")
            print(f"width_audit: {len(inv)} justified 32-bit site(s) "
                  "in the inventory")
        return 0

    if args.write_budget:
        from cuvite_tpu.workloads import registry

        write_budget(args.budget, {
            "laws": code_laws(),
            "max_workload": registry.max_workload(),
            "workloads": audit_workloads(),
        })
        print(f"width_audit: wrote {args.budget} (laws + max workload "
              "+ certification shapes; review the diff)")
        return 0

    # nargs="*" admits a bare `--entries` (an empty $ENTRIES in a
    # script): treat it as "all entries", never as a vacuous zero-entry
    # audit that greens without auditing anything.
    entries = args.entries or None
    workloads = args.workloads or None
    if args.smoke:
        entries = entries or list(SMOKE_ENTRIES)
        workloads = workloads or list(SMOKE_WORKLOADS)

    findings, reports = run_width_audit(entries, workloads=workloads,
                                        budget_path=args.budget)
    doc = {
        "platform": jax.default_backend(),
        "reports": reports,
        "findings": [f.to_dict() for f in findings],
        "ok": not findings,
    }
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=2, sort_keys=True)
            fh.write("\n")
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        spy = reports.get("spy", {})
        for wname in sorted(k for k in reports
                            if k not in ("probes", "spy")):
            per = reports[wname]
            state = "ok" if not any(
                f.path == f"<width:{e}>" for e in per
                for f in findings) else "FAIL"
            ents = ", ".join(sorted(per))
            print(f"{wname}: entries [{ents}] [{state}]")
        print(f"width_audit: spy delta "
              f"{spy.get('delta_bytes', '?')} byte(s)")
        for f in findings:
            print(f.format())
        print(f"width_audit: {len(findings)} finding(s); "
              f"{'FAIL' if findings else 'ok'}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
