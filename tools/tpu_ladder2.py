"""Second-stage TPU ladder (round 4) — run AFTER tools/tpu_ladder.py.

The first ladder proves the narrow-width compiled Pallas kernel and lands
BENCH-ready platform=tpu JSON at scales 18/20.  This one spends the same
alive window on the remaining chip-gated claims, cheapest-first so a
mid-ladder wedge preserves the most valuable results:

  A2. compiled Pallas parity + min-of-5 timing for the WIDE classes
      (64/256/2048 — the lax.fori_loop + shrunken-tile path that has only
      ever run in interpret mode) vs the XLA sorted-dedup twin;
  D.  full clustering A/B on chip: engine=bucketed (XLA) vs
      engine=pallas, rmat-18 and rmat-20, modularity + wall from --json;
  E.  bench.py at scale 22 (platform=tpu JSON line for the record).

Every result appends to tools/tpu_ladder_r4.log immediately.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
LOG = os.path.join(REPO, "tools", "tpu_ladder_r4.log")


def log(msg):
    line = f"[{time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())}] {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def stage_a2(jnp, np):
    from cuvite_tpu.kernels.row_argmax import row_argmax_pallas
    from cuvite_tpu.louvain.bucketed import _row_argmax_sorted

    SENT = np.iinfo(np.int32).max
    rng = np.random.default_rng(0)
    for width, n_rows in ((64, 1 << 14), (256, 1 << 13), (2048, 1 << 11)):
        nv = 50000
        cmat = rng.integers(0, nv, size=(n_rows, width)).astype(np.int32)
        wmat = (rng.integers(1, 32, size=(n_rows, width)) / 16.0
                ).astype(np.float32)
        curr = rng.integers(0, nv, size=n_rows).astype(np.int32)
        cmat[: n_rows // 2, 0] = curr[: n_rows // 2]
        vdeg = (rng.integers(1, 64, size=n_rows) / 4.0).astype(np.float32)
        sl = np.where(cmat[:, 0] == curr, wmat[:, 0] / 2.0, 0.0
                      ).astype(np.float32)
        comm_deg = (rng.integers(1, 256, size=nv) / 8.0).astype(np.float32)
        const = np.float32(1.0 / 64.0)
        ay = comm_deg[cmat]
        ax = comm_deg[curr] - vdeg
        args_p = (jnp.asarray(np.ascontiguousarray(cmat.T)),
                  jnp.asarray(np.ascontiguousarray(wmat.T)),
                  jnp.asarray(np.ascontiguousarray(ay.T)),
                  jnp.asarray(curr), jnp.asarray(vdeg), jnp.asarray(sl),
                  jnp.asarray(ax), jnp.asarray(const))
        args_x = (jnp.asarray(cmat), jnp.asarray(wmat), jnp.asarray(ay),
                  None, jnp.asarray(curr), jnp.asarray(vdeg),
                  jnp.asarray(sl), jnp.asarray(ax), jnp.asarray(const),
                  SENT)

        t0 = time.perf_counter()
        bc, bg, c0 = row_argmax_pallas(*args_p, sentinel=SENT,
                                       interpret=False)
        bc_h = np.asarray(bc)
        log(f"A2: width={width} pallas COMPILED ok "
            f"(first call {time.perf_counter()-t0:.1f}s)")
        ref = _row_argmax_sorted(*args_x, id_bound=nv)
        # The sorted XLA twin and the kernel agree exactly on best_c and
        # counter0; best_gain may differ in f32 summation order for
        # duplicate aggregation, so compare it with an epsilon.
        ok_c = (np.array_equal(bc_h, np.asarray(ref.best_c))
                and np.array_equal(np.asarray(c0), np.asarray(ref.counter0)))
        gmax = float(np.max(np.abs(
            np.where(np.isfinite(np.asarray(bg)),
                     np.asarray(bg) - np.asarray(ref.best_gain), 0.0))))
        log(f"A2: width={width} vs XLA-sorted: best_c/counter0 "
            f"{'PASS' if ok_c else 'FAIL'}, |dgain|max={gmax:.3g}")

        def t5(fn):
            ts = []
            for _ in range(5):
                t0 = time.perf_counter()
                out = fn()
                _ = float(np.asarray(out[0]).ravel()[0])
                ts.append(time.perf_counter() - t0)
            return min(ts)

        tp = t5(lambda: row_argmax_pallas(*args_p, sentinel=SENT,
                                          interpret=False))
        tx = t5(lambda: _row_argmax_sorted(*args_x, id_bound=nv))
        log(f"A2: width={width} rows={n_rows}: pallas {tp*1e3:.2f} ms vs "
            f"XLA-sorted {tx*1e3:.2f} ms ({tx/max(tp,1e-9):.2f}x)")


def stage_d(platform):
    # fused = one host sync per RUN (vs per phase): over a ~1s-rtt tunnel
    # the per-phase syncs alone are a visible share of a scale-18 run.
    for scale in (18, 20):
        for engine in ("bucketed", "pallas", "fused"):
            cmd = [sys.executable, "-m", "cuvite_tpu.cli",
                   "--rmat", str(scale), "--engine", engine,
                   "--platform", platform, "--json", "--quiet"]
            t0 = time.perf_counter()
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=2400, cwd=REPO)
            wall = time.perf_counter() - t0
            line = ""
            for ln in reversed(out.stdout.strip().splitlines() or [""]):
                if ln.startswith("{"):
                    line = ln
                    break
            log(f"D: scale={scale} engine={engine} rc={out.returncode} "
                f"wall={wall:.0f}s json={line or out.stderr[-200:]}")


def stage_e():
    env = dict(os.environ, BENCH_SCALE="22", BENCH_TIME_BUDGET="1500",
               BENCH_REPEATS="2")
    t0 = time.perf_counter()
    out = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                         capture_output=True, text=True, timeout=3600,
                         env=env)
    last = out.stdout.strip().splitlines()
    log(f"E: bench scale=22 rc={out.returncode} "
        f"wall={time.perf_counter()-t0:.0f}s "
        f"json={last[-1] if last else '?'}")
    if out.returncode == 0 and last:
        try:
            j = json.loads(last[-1])
            if j.get("platform") != "cpu":
                with open(os.path.join(REPO, "tools/bench_tpu_s22_r4.json"),
                          "w") as f:
                    f.write(last[-1] + "\n")
        except json.JSONDecodeError:
            pass


def main():
    import jax

    try:
        d = jax.devices()
    except Exception as e:
        print(f"no devices: {e}", flush=True)
        return 2
    from jax._src import xla_bridge as xb

    names = [k for k, b in xb.backends().items() if b is d[0].client]
    plat = names[0] if names else d[0].platform
    if plat == "cpu":
        log("ladder2: backend is cpu; nothing to measure")
        return 2
    jax.config.update("jax_platforms", plat)
    from cuvite_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    import jax.numpy as jnp
    import numpy as np

    log(f"LADDER2 start: backend={plat} devices={jax.devices()}")
    try:
        stage_a2(jnp, np)
    except Exception as e:
        log(f"A2: FAILED {type(e).__name__}: {e}")
    try:
        stage_d(plat)
    except Exception as e:
        log(f"D: FAILED {type(e).__name__}: {e}")
    try:
        stage_e()
    except Exception as e:
        log(f"E: FAILED {type(e).__name__}: {e}")
    log("LADDER2 COMPLETE")
    return 0


if __name__ == "__main__":
    sys.exit(main())
