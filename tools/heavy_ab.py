"""Heavy-class A/B: community-range-tile Pallas kernel vs the XLA sorted
path, on hub rows (the decision measurement of heavy_kernel_design.md).

The kernel's cost is O(D * nv_ceil / C) matmul passes per row — linear in
the COMMUNITY-SPACE size — while the sort path is O(D log^2 D) per row
regardless of nv.  The sweep therefore times both over (D, nv_ceil) so
the log records where (if anywhere) the tile kernel wins: the design
note predicts only small nv_ceil (late coarsened phases) can favor it.

Usage:
    python tools/heavy_ab.py                   # default backend (chip)
    CUVITE_PLATFORM=cpu python tools/heavy_ab.py   # interpret-mode smoke

Appends a dated block to tools/logs/heavy_ab_r5.log.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _common  # noqa: F401

import jax
import jax.numpy as jnp
import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "tools", "logs", "heavy_ab_r5.log")


def log(msg):
    line = f"[{time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())}] {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def time_best(fn, n=5):
    fn()  # compile + warm
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    from cuvite_tpu.kernels.heavy_bincount import heavy_argmax_pallas
    from cuvite_tpu.louvain.bucketed import _row_argmax_sorted

    interpret = jax.default_backend() != "tpu"
    plat = jax.default_backend()
    log(f"heavy A/B start backend={plat} interpret={interpret}")
    H = 32  # hub rows per case (hubs are <0.1% of vertices)
    rng = np.random.default_rng(7)
    for D in (4096, 16384):
        for nv_ceil in (8192, 65536, 1 << 20):
            if interpret and (D, nv_ceil) != (4096, 8192):
                # Interpret mode executes the grid in Python — the big
                # cases would take hours; cpu is a correctness smoke only.
                continue
            nv = nv_ceil - 7
            cmat = rng.integers(0, nv, size=(H, D)).astype(np.int32)
            wmat = (rng.integers(1, 32, size=(H, D)) / 16.0).astype(
                np.float32)
            curr = rng.integers(0, nv, size=H).astype(np.int32)
            vdeg = wmat.sum(axis=1)
            sl = np.zeros(H, dtype=np.float32)
            comm_deg = (rng.integers(1, 256, size=nv_ceil) / 8.0).astype(
                np.float32)
            ax = comm_deg[curr] - vdeg
            const = np.float32(1.0 / vdeg.sum())
            cT = jnp.asarray(np.ascontiguousarray(cmat.T))
            wT = jnp.asarray(np.ascontiguousarray(wmat.T))
            cd = jnp.asarray(comm_deg)
            cu, vd, slj, axj = map(jnp.asarray, (curr, vdeg, sl, ax))

            def run_kernel():
                bc, bg, c0 = heavy_argmax_pallas(
                    cT, wT, cd, cu, vd, slj, axj, jnp.asarray(const),
                    interpret=interpret)
                return float(bg[0])

            # XLA twin: the per-row packed single-key sort path the heavy
            # residual rides today, on identical rows.
            cm = jnp.asarray(cmat)
            wm = jnp.asarray(wmat)
            ay = jnp.asarray(comm_deg[cmat])

            def run_sorted():
                res = _row_argmax_sorted(
                    cm, wm, ay, None, cu, vd, slj, axj,
                    jnp.asarray(const), np.iinfo(np.int32).max,
                    id_bound=nv_ceil)
                return float(res.best_gain[0])

            try:
                tk = time_best(run_kernel)
            except Exception as e:  # mosaic lowering can reject shapes
                log(f"D={D} nv_ceil={nv_ceil}: kernel FAILED {e!r:.200}")
                continue
            ts = time_best(run_sorted)
            # Semantic identity on the A/B inputs: best_c/counter0 must be
            # bitwise equal.  best_gain is compared to 1-2 ulp: const here
            # is 1/sum(w) (not a power of two like the unit tests use), so
            # XLA's FMA contraction rounds the gain's second term once
            # where the non-contracted form rounds twice — measured 1 ulp
            # on ~half the rows, never changing the argmax.
            bk = heavy_argmax_pallas(cT, wT, cd, cu, vd, slj, axj,
                                     jnp.asarray(const),
                                     interpret=interpret)
            br = _row_argmax_sorted(cm, wm, ay, None, cu, vd, slj, axj,
                                    jnp.asarray(const),
                                    np.iinfo(np.int32).max,
                                    id_bound=nv_ceil)
            gk, gr = np.asarray(bk[1]), np.asarray(br.best_gain)
            fin = np.isfinite(gk) & np.isfinite(gr)
            same = (np.array_equal(np.asarray(bk[0]),
                                   np.asarray(br.best_c))
                    and np.array_equal(fin, np.isfinite(gr))
                    and np.allclose(gk[fin], gr[fin], rtol=3e-7, atol=0))
            log(f"D={D} nv_ceil={nv_ceil} H={H}: kernel {tk*1e3:.1f} ms  "
                f"sorted {ts*1e3:.1f} ms  ratio {tk/ts:.2f}x  "
                f"semantically_identical={same}")
    log("heavy A/B done")


if __name__ == "__main__":
    main()
