"""Heavy-class + segmented-coalesce A/B: the two kernel-vs-sort decision
measurements of ISSUE 8 (cf. heavy_kernel_design.md's decision rule).

Sweep 1 (heavy rows): community-range-tile Pallas kernel vs the XLA
sorted path on hub rows.  The kernel's cost is O(D * nv_ceil / C)
matmul passes per row — linear in the COMMUNITY-SPACE size — while the
sort path is O(D log^2 D) per row regardless of nv.  The sweep times
both over (D, nv_ceil) so the log records where the tile kernel wins.

Sweep 2 (seg-coalesce, `python tools/heavy_ab.py seg`): the coalesce
engines vs the packed-sort chokepoint on relabeled-slab workloads, per
slab class — the dense dst-tile pair (kernels/seg_coalesce.py, 'xla'
twin + 'pallas' kernel) on its budget-eligible classes, plus the
ISSUE-19 big-class arms on every class: 'msd' (two-pass int32 MSD
src-partition sort) and 'hash' (hash-slot accumulate with device-side
collision detection + sort retry).  The nv_pad >= 2^16 classes are the
ones the round-10 baseline showed paying the 64-bit variadic
comparator tax — the msd/hash cells there are the ISSUE-19 acceptance
measurement.  Every cell asserts bit-identity vs the sort oracle
before timing.  Appends to tools/logs/seg_coalesce_ab_r19.log.

Usage:
    python tools/heavy_ab.py                   # both sweeps (chip)
    python tools/heavy_ab.py heavy|seg         # one sweep
    CUVITE_PLATFORM=cpu python tools/heavy_ab.py   # interpret-mode smoke

Appends dated blocks to tools/logs/heavy_ab_r5.log (heavy) and
tools/logs/seg_coalesce_ab_r10.log (coalesce).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _common  # noqa: F401

import jax
import jax.numpy as jnp
import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "tools", "logs", "heavy_ab_r5.log")
SEG_LOG = os.path.join(REPO, "tools", "logs", "seg_coalesce_ab_r19.log")


def _log_to(path, msg):
    line = f"[{time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())}] {msg}"
    print(line, flush=True)
    with open(path, "a") as f:
        f.write(line + "\n")


def log(msg):
    _log_to(LOG, msg)


def time_best(fn, n=5):
    fn()  # compile + warm
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def seg_coalesce_ab():
    """Sweep 2: dense coalesce engines vs the packed-sort chokepoint on
    synthetic relabeled slabs (dense ids < nv_pad, 20% tail padding,
    dyadic weights), per slab class.  Every cell also asserts the
    engines' outputs are bit-identical before timing them."""
    from cuvite_tpu.ops.segment import coalesced_runs

    plat = jax.default_backend()
    interpret = plat != "tpu"
    _log_to(SEG_LOG, f"seg-coalesce A/B start backend={plat} "
                     f"interpret={interpret}")
    rng = np.random.default_rng(11)
    for nv_pad, ne_pad in ((1024, 1 << 17), (4096, 1 << 18),
                           (4096, 1 << 20), (1 << 16, 1 << 20),
                           (1 << 18, 1 << 20)):
        # msd/hash run on EVERY class: on the small classes msd
        # delegates to the packed sort (expect ~1.0x, a delegation
        # check), on the nv_pad >= 2^16 classes they are the ISSUE-19
        # candidates against the 64-bit comparator tax.
        engines = ["sort", "msd", "hash"]
        if nv_pad <= 4096:
            # Dense dst-tile classes (within the accumulator budget).
            engines.insert(1, "xla")
            if not (interpret and ne_pad > (1 << 18)):
                # Interpret mode unrolls the kernel grid at trace time;
                # the big slabs are chip cases.  The XLA twin still
                # measures.
                engines.insert(2, "pallas")
        engines = tuple(engines)
        n_real = ne_pad - ne_pad // 5
        src = np.full(ne_pad, nv_pad, np.int32)
        dst = np.zeros(ne_pad, np.int32)
        w = np.zeros(ne_pad, np.float32)
        src[:n_real] = rng.integers(0, nv_pad, n_real)
        dst[:n_real] = rng.integers(0, nv_pad, n_real)
        w[:n_real] = rng.integers(1, 64, n_real) / 8.0
        arrs = tuple(jnp.asarray(x) for x in (src, dst, w))

        # One jitted callable per engine (engine/nv_pad static via the
        # closure): every cell times a compiled program, none pays
        # eager per-op dispatch — apples-to-apples.
        def _jitted(eng):
            return jax.jit(lambda s, d, ww: coalesced_runs(
                s, d, ww, nv_pad=nv_pad, engine=eng))

        # One jitted callable per engine, reused for the parity check
        # AND the timing (a fresh jit wrapper would recompile sort for
        # the reference and again for its timed cell).
        runs = {eng: _jitted(eng) for eng in engines}
        ref = jax.device_get(runs["sort"](*arrs))
        times = {}
        for eng in engines:
            run = runs[eng]
            got = jax.device_get(run(*arrs))
            if not all(np.array_equal(r, g) for r, g in zip(ref, got)):
                # A wrong-result engine must never contribute a timing
                # the promotion decision could read: loud, and skipped.
                _log_to(SEG_LOG,
                        f"nv_pad={nv_pad} ne_pad={ne_pad}: {eng} "
                        f"FAILED bit-identity vs sort — NOT timed")
                continue
            t = time_best(lambda r=run: jax.block_until_ready(r(*arrs)))
            times[eng] = t
            _log_to(SEG_LOG,
                    f"nv_pad={nv_pad} ne_pad={ne_pad}: {eng} "
                    f"{t * 1e3:.1f} ms  vs sort "
                    f"{times[eng] / times['sort']:.2f}x")
    _log_to(SEG_LOG, "seg-coalesce A/B done")


def main():
    from cuvite_tpu.kernels.heavy_bincount import heavy_argmax_pallas
    from cuvite_tpu.louvain.bucketed import _row_argmax_sorted

    interpret = jax.default_backend() != "tpu"
    plat = jax.default_backend()
    log(f"heavy A/B start backend={plat} interpret={interpret}")
    H = 32  # hub rows per case (hubs are <0.1% of vertices)
    rng = np.random.default_rng(7)
    for D in (4096, 16384):
        for nv_ceil in (8192, 65536, 1 << 20):
            if interpret and (D, nv_ceil) != (4096, 8192):
                # Interpret mode executes the grid in Python — the big
                # cases would take hours; cpu is a correctness smoke only.
                continue
            nv = nv_ceil - 7
            cmat = rng.integers(0, nv, size=(H, D)).astype(np.int32)
            wmat = (rng.integers(1, 32, size=(H, D)) / 16.0).astype(
                np.float32)
            curr = rng.integers(0, nv, size=H).astype(np.int32)
            vdeg = wmat.sum(axis=1)
            sl = np.zeros(H, dtype=np.float32)
            comm_deg = (rng.integers(1, 256, size=nv_ceil) / 8.0).astype(
                np.float32)
            ax = comm_deg[curr] - vdeg
            const = np.float32(1.0 / vdeg.sum())
            cT = jnp.asarray(np.ascontiguousarray(cmat.T))
            wT = jnp.asarray(np.ascontiguousarray(wmat.T))
            cd = jnp.asarray(comm_deg)
            cu, vd, slj, axj = map(jnp.asarray, (curr, vdeg, sl, ax))

            def run_kernel():
                bc, bg, c0 = heavy_argmax_pallas(
                    cT, wT, cd, cu, vd, slj, axj, jnp.asarray(const),
                    interpret=interpret)
                return float(bg[0])

            # XLA twin: the per-row packed single-key sort path the heavy
            # residual rides today, on identical rows.
            cm = jnp.asarray(cmat)
            wm = jnp.asarray(wmat)
            ay = jnp.asarray(comm_deg[cmat])

            def run_sorted():
                res = _row_argmax_sorted(
                    cm, wm, ay, None, cu, vd, slj, axj,
                    jnp.asarray(const), np.iinfo(np.int32).max,
                    id_bound=nv_ceil)
                return float(res.best_gain[0])

            try:
                tk = time_best(run_kernel)
            except Exception as e:  # mosaic lowering can reject shapes
                log(f"D={D} nv_ceil={nv_ceil}: kernel FAILED {e!r:.200}")
                continue
            ts = time_best(run_sorted)
            # Semantic identity on the A/B inputs: best_c/counter0 must be
            # bitwise equal.  best_gain is compared to 1-2 ulp: const here
            # is 1/sum(w) (not a power of two like the unit tests use), so
            # XLA's FMA contraction rounds the gain's second term once
            # where the non-contracted form rounds twice — measured 1 ulp
            # on ~half the rows, never changing the argmax.
            bk = heavy_argmax_pallas(cT, wT, cd, cu, vd, slj, axj,
                                     jnp.asarray(const),
                                     interpret=interpret)
            br = _row_argmax_sorted(cm, wm, ay, None, cu, vd, slj, axj,
                                    jnp.asarray(const),
                                    np.iinfo(np.int32).max,
                                    id_bound=nv_ceil)
            gk, gr = np.asarray(bk[1]), np.asarray(br.best_gain)
            fin = np.isfinite(gk) & np.isfinite(gr)
            same = (np.array_equal(np.asarray(bk[0]),
                                   np.asarray(br.best_c))
                    and np.array_equal(fin, np.isfinite(gr))
                    and np.allclose(gk[fin], gr[fin], rtol=3e-7, atol=0))
            log(f"D={D} nv_ceil={nv_ceil} H={H}: kernel {tk*1e3:.1f} ms  "
                f"sorted {ts*1e3:.1f} ms  ratio {tk/ts:.2f}x  "
                f"semantically_identical={same}")
    log("heavy A/B done")


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "both"
    if which in ("heavy", "both"):
        main()
    if which in ("seg", "both"):
        seg_coalesce_ab()
