#!/bin/sh
# graftlint pre-commit one-liner: the EXACT gate tests/test_analysis.py
# enforces in tier-1 (new high-severity finding anywhere in cuvite_tpu/,
# tools/, or tests/ => exit 1).  Extra args pass through, e.g.:
#   tools/lint.sh --fail-on medium        # stricter local run
#   tools/lint.sh --format json           # machine-readable findings
# See ANALYSIS.md for the rule catalogue and suppression/baseline flow.
cd "$(dirname "$0")/.." && exec python -m cuvite_tpu.analysis \
    cuvite_tpu tools tests --baseline tools/graftlint_baseline.json "$@"
