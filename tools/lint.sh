#!/bin/sh
# graftlint pre-commit one-liner: the EXACT gate tests/test_analysis.py
# enforces in tier-1 (new high-severity finding anywhere in cuvite_tpu/,
# tools/, or tests/ => exit 1), warm-started from the incremental cache
# (tools/.graftlint_cache.json — bit-identical to a cold run; delete it
# any time).  Extra args pass through, e.g.:
#   tools/lint.sh --fail-on medium        # stricter local run
#   tools/lint.sh --format json|sarif     # machine-readable findings
#   tools/lint.sh --prune-baseline        # drop dead baseline entries
#   tools/lint.sh --changed               # only files touched vs HEAD
#                                         # (+ untracked) — the fast
#                                         # pre-commit loop; a subset
#                                         # run loses the cross-module
#                                         # tier's full context, so run
#                                         # the full gate before pushing
#   tools/lint.sh --sched-smoke           # tier-4 concheck self-check:
#                                         # a small FIXED-seed schedule
#                                         # budget over the daemon
#                                         # scenarios (clean ones must
#                                         # explore clean, the known-bug
#                                         # fixtures must be convicted).
#                                         # CUVITE_SCHED_BUDGET raises
#                                         # the budget; extra args pass
#                                         # through (--scenario, --seed,
#                                         # --format json).  Dynamic
#                                         # results are never cached.
#   tools/lint.sh --mesh-smoke            # tier-5 mesh-audit self-check:
#                                         # the bucketed SPMD step (both
#                                         # exchanges) at two fixed mesh
#                                         # shapes — M001 collective
#                                         # sequences, M002 label
#                                         # neutrality, M003 replication
#                                         # scaling vs tools/
#                                         # replication_budget.json.
#                                         # Extra args pass through
#                                         # (--entries, --shapes,
#                                         # --json).  Dynamic results
#                                         # are never cached; the full
#                                         # audit runs in tier-1 and as
#                                         # ladder stage I.
#   tools/lint.sh --width-smoke           # tier-6 width-audit self-check:
#                                         # the packed-sort slab entries
#                                         # traced at the scale-28 shard
#                                         # shape (zero bytes allocated)
#                                         # + every boundary probe —
#                                         # W001 index-carrying buffer
#                                         # widths, W002 fallback
#                                         # selection at the bit edges,
#                                         # W003 manifest drift vs
#                                         # tools/width_budget.json.
#                                         # Extra args pass through
#                                         # (--entries, --workloads,
#                                         # --json, --inventory).
#                                         # Dynamic results are never
#                                         # cached; the full audit runs
#                                         # in tier-1 and as ladder
#                                         # stage J.
# See ANALYSIS.md for the rule catalogue and suppression/baseline flow.
cd "$(dirname "$0")/.." || exit 2
if [ "$1" = "--width-smoke" ]; then
    shift
    # Same platform-knob forwarding as --mesh-smoke below.
    CUVITE_PLATFORM="${CUVITE_PLATFORM:-${JAX_PLATFORMS:-cpu}}"
    export CUVITE_PLATFORM
    exec python tools/width_audit.py --smoke "$@"
fi
if [ "$1" = "--mesh-smoke" ]; then
    shift
    # mesh_audit.py pins the jax platform from CUVITE_PLATFORM (the
    # axon plugin overrides a bare JAX_PLATFORMS env var, see
    # tools/compile_audit.py) — honor an exported JAX_PLATFORMS by
    # forwarding it into the knob the audit actually reads.
    CUVITE_PLATFORM="${CUVITE_PLATFORM:-${JAX_PLATFORMS:-cpu}}"
    export CUVITE_PLATFORM
    exec python tools/mesh_audit.py --smoke "$@"
fi
if [ "$1" = "--sched-smoke" ]; then
    shift
    # Forced-CPU like tier-1: the harness stubs the batch runner, but
    # the serve import chain initializes a jax backend.
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" export JAX_PLATFORMS
    exec python -m cuvite_tpu.analysis.concheck \
        --budget "${CUVITE_SCHED_BUDGET:-8}" --seed 0 "$@"
fi
if [ "$1" = "--changed" ]; then
    shift
    # --diff-filter=d: a DELETED file must not reach the linter (its
    # path would fail closed with a high E000 'no Python files').
    changed=$( { git diff --name-only --diff-filter=d HEAD -- \
                     'cuvite_tpu/*.py' 'tools/*.py' 'tests/*.py'; \
                 git ls-files --others --exclude-standard \
                     'cuvite_tpu/*.py' 'tools/*.py' 'tests/*.py'; } \
               | sort -u)
    if [ -z "$changed" ]; then
        echo "graftlint: no changed Python files under the gate paths; ok"
        exit 0
    fi
    # shellcheck disable=SC2086 — word-splitting the file list is the point
    exec python -m cuvite_tpu.analysis $changed \
        --baseline tools/graftlint_baseline.json \
        --cache tools/.graftlint_cache.json "$@"
fi
exec python -m cuvite_tpu.analysis cuvite_tpu tools tests \
    --baseline tools/graftlint_baseline.json \
    --cache tools/.graftlint_cache.json "$@"
