"""Shared bootstrap for the tools/ scripts.

Importing this module (FIRST, before anything touches a jax backend):
- puts the repo root on sys.path;
- pins the backend from $CUVITE_PLATFORM if set — this must happen before
  any device call, because a sitecustomize-registered PJRT plugin (the
  axon TPU tunnel) wins over a JAX_PLATFORMS env var, and a wedged tunnel
  hangs backend init indefinitely;
- points jax at the repo's persistent compile cache.
"""

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

import jax  # noqa: E402

if os.environ.get("CUVITE_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["CUVITE_PLATFORM"])

jax.config.update("jax_compilation_cache_dir",
                  os.path.join(REPO_ROOT, ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
