"""Shared bootstrap for the tools/ scripts.

Importing this module (FIRST, before anything touches a jax backend):
- puts the repo root on sys.path;
- pins the backend from $CUVITE_PLATFORM if set — this must happen before
  any device call, because a sitecustomize-registered PJRT plugin (the
  axon TPU tunnel) wins over a JAX_PLATFORMS env var, and a wedged tunnel
  hangs backend init indefinitely;
- points jax at the repo's persistent compile cache.
"""

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

import jax  # noqa: E402

if os.environ.get("CUVITE_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["CUVITE_PLATFORM"])

from cuvite_tpu.utils.compile_cache import enable_compile_cache  # noqa: E402

enable_compile_cache(REPO_ROOT)
