"""Mesh audit CLI (graftlint tier 5, dynamic half).

Runs the real sharded entries — the per-graph bucketed SPMD step under
the replicated, sparse, auto-cutover, and two-level (hybrid dcn/ici
mesh) exchanges, and the batched fused/bucketed phase programs —
across the virtual mesh shapes {8x1, 4x2, 2x4} of tier-1's forced-CPU
8-device pool (the two-level entry reads each shape as its (dcn, ici)
factorization), and grades:

  * M001 — per-shard collective sequences: extracted from the traced
    jaxprs; a cond whose branches issue different collective
    subsequences, or a sequence that changes structure across mesh
    shapes, is a conviction;
  * M002 — labels + modularity bit-identical across every mesh shape
    (the generalized mesh-neutrality gate);
  * M003 — per-device HBM-ledger bytes vs the per-category scaling law
    declared in ``tools/replication_budget.json`` (the closed
    replication inventory: 'sharded' must shrink ~1/S, 'replicated'
    must be listed);
  * M000 — audit infrastructure failures (an entry crashed, the budget
    manifest is unreadable) fail CLOSED.

Usage:
    python tools/mesh_audit.py                    # full audit, exit 1 on FAIL
    python tools/mesh_audit.py --smoke            # fixed-shape fast self-check
    python tools/mesh_audit.py --entries bucketed_sparse ...
    python tools/mesh_audit.py --shapes 8x1 4x2   # subset of shapes
    python tools/mesh_audit.py --json             # machine-readable
    python tools/mesh_audit.py --inventory        # R025 replicated-ok sites
    python tools/mesh_audit.py --out FILE.json    # checkpoint the report
                                                  # (ladder stage I)

Dynamic results are never cached; the audit re-runs the entries every
time.  The tier-1 test (tests/test_meshcheck.py) runs the same audit
in-process plus sabotage fixtures proving M001/M003 convict seeded
bugs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

BUDGET = os.path.join(REPO_ROOT, "tools", "replication_budget.json")

# Tier-1's backend shape, replicated for standalone runs (the
# compile_audit precedent): the mesh shapes need 8 devices.  On a real
# TPU slice (ladder stage I) the flag is a no-op — the chips are real.
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms",
                  os.environ.get("CUVITE_PLATFORM", "cpu"))

from cuvite_tpu.analysis.meshcheck import (  # noqa: E402
    ENTRIES,
    MESH_SHAPES,
    load_budget,
    run_mesh_audit,
    write_budget,
)

# --smoke: one exchange per engine family at a fixed pair of shapes —
# the fast pre-commit self-check lint.sh --mesh-smoke runs (still
# cross-shape, so M001/M002/M003 all have teeth; the full gate runs in
# tier-1 and on the ladder).
SMOKE_ENTRIES = ("bucketed_replicated", "bucketed_sparse")
SMOKE_SHAPES = ((4, 2), (2, 4))


def _parse_shapes(tokens):
    shapes = []
    for t in tokens:
        a, _, b = t.partition("x")
        shapes.append((int(a), int(b or 1)))
    return tuple(shapes)


def _inventory() -> list:
    """The R025 replicated-ok inventory, rebuilt from the live tree
    (static tier; no jax involved)."""
    from cuvite_tpu.analysis.callgraph import summarize
    from cuvite_tpu.analysis.engine import SourceFile, iter_py_files
    from cuvite_tpu.analysis.meshspec import replicated_inventory

    summaries = []
    for path in iter_py_files([os.path.join(REPO_ROOT, "cuvite_tpu"),
                               os.path.join(REPO_ROOT, "tools")]):
        rel = os.path.relpath(path, REPO_ROOT).replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as fh:
                summaries.append(summarize(SourceFile(fh.read(),
                                                      path=path, rel=rel)))
        except (OSError, SyntaxError, ValueError):
            continue
    return replicated_inventory(summaries)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python tools/mesh_audit.py",
        description="cuvite_tpu SPMD mesh audit (tier 5, M001-M003)")
    ap.add_argument("--entries", nargs="*", default=None,
                    choices=sorted(ENTRIES), help="subset of entries")
    ap.add_argument("--shapes", nargs="*", default=None,
                    metavar="SxT", help="mesh shapes (default: "
                    + " ".join(f"{a}x{b}" for a, b in MESH_SHAPES))
    ap.add_argument("--smoke", action="store_true",
                    help="fast fixed-shape self-check "
                         f"({', '.join(SMOKE_ENTRIES)} at "
                         f"{'/'.join(f'{a}x{b}' for a, b in SMOKE_SHAPES)})")
    ap.add_argument("--budget", default=BUDGET)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="write the JSON report to FILE (per-shape "
                         "ledger rows + findings; ladder stage I "
                         "checkpoints these)")
    ap.add_argument("--inventory", action="store_true",
                    help="print the R025 replicated-ok inventory and "
                         "exit (static tier only)")
    ap.add_argument("--write-budget", action="store_true",
                    help="regenerate the scaling-law manifest from the "
                         "observed ledger categories (existing entries "
                         "kept; NEW categories default to law='sharded' "
                         "— the failing-closed default — edit the "
                         "reasons before committing)")
    args = ap.parse_args(argv)

    if args.inventory:
        inv = _inventory()
        if args.json:
            print(json.dumps(inv, indent=2))
        else:
            for ent in inv:
                print(f"{ent['rel']}:{ent['line']}: {ent['call']} "
                      f"[{ent['size']}] [scope={ent['scope']}] — "
                      f"{ent['reason']}")
            n_global = sum(1 for ent in inv if ent["scope"] == "global")
            print(f"mesh_audit: {len(inv)} justified replicated "
                  f"buffer(s) in the inventory; {n_global} with global "
                  "scope (two-level contract: 0)")
        return 0

    # nargs="*" admits a bare `--entries` (e.g. an empty $ENTRIES in a
    # script): treat it as "all entries", never as a vacuous zero-entry
    # audit that greens without auditing anything.
    entries = args.entries or None
    shapes = _parse_shapes(args.shapes) if args.shapes else None
    if args.smoke:
        entries = entries or list(SMOKE_ENTRIES)
        shapes = shapes or SMOKE_SHAPES
    shapes = shapes or MESH_SHAPES

    if args.write_budget:
        _findings, reports = run_mesh_audit(entries, shapes=shapes,
                                            budget_path=args.budget)
        try:
            cats = dict(load_budget(args.budget).get("categories", {}))
        except (OSError, ValueError):
            cats = {}
        observed = sorted({cat for by_shape in reports.values()
                           for rep in by_shape.values()
                           for cat in rep.categories})
        fresh = [cat for cat in observed if cat not in cats]
        for cat in fresh:
            cats[cat] = {
                "law": "sharded",
                "reason": "autogenerated by --write-budget — declare "
                          "the law (sharded/replicated) deliberately",
            }
        write_budget(args.budget, cats, {
            "device_count": jax.device_count(),
            "platform": jax.default_backend(),
            "shapes": [f"{a}x{b}" for a, b in shapes],
        })
        print(f"mesh_audit: wrote {len(cats)} categories to "
              f"{args.budget} ({len(fresh)} new, defaulted to "
              "law='sharded'; edit the reasons before committing)")
        return 0

    findings, reports = run_mesh_audit(entries, shapes=shapes,
                                       budget_path=args.budget)
    doc = {
        "platform": jax.default_backend(),
        "device_count": jax.device_count(),
        "shapes": [f"{a}x{b}" for a, b in shapes],
        "entries": {
            name: {
                tag: {
                    "devices": rep.devices,
                    "n_results": len(rep.labels),
                    "collectives": len(rep.seq),
                    "ledger": rep.categories,
                }
                for tag, rep in by_shape.items()
            }
            for name, by_shape in reports.items()
        },
        "findings": [f.to_dict() for f in findings],
        "ok": not findings,
    }
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=2, sort_keys=True)
            fh.write("\n")
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        for name, by_shape in reports.items():
            tags = ", ".join(sorted(by_shape))
            state = "ok" if not any(
                f.path == f"<mesh:{name}>" for f in findings) else "FAIL"
            print(f"{name}: shapes [{tags}] [{state}]")
        for f in findings:
            print(f.format())
        print(f"mesh_audit: {len(findings)} finding(s); "
              f"{'FAIL' if findings else 'ok'}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
