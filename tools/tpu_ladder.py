"""Self-triggering TPU measurement ladder (round 4).

The axon tunnel answers intermittently (probe log: one rc=0 at
2026-07-31T01:04Z among ~20 hangs).  Waiting for a human to notice an
alive window wastes it, so this script is the whole reaction: probe the
backend in a guarded subprocess, and the moment the probe succeeds run
the prepared ladder (tools/tpu_tuning.md) in strict priority order,
appending each result to tools/tpu_ladder_r4.log IMMEDIATELY so a
mid-ladder wedge still preserves everything measured before it.

Priority order (VERDICT r3 item 1):
  A. compiled (non-interpret) Pallas row_argmax vs its XLA twin —
     bit-identity + min-of-5 timing, widths 8/32;
  B. one bucketed phase-0 step wall at scale 18 (PhaseRunner, honest
     scalar readback);
  C. full bench.py at scale 18 then 20 (subprocess; BENCH_r04-ready
     JSON lines land in the log).

Run via tools/tpu_watch.sh (background loop, ~10 min cadence); a full
success writes tools/TPU_LADDER_DONE and the watcher stops.

NEVER run stages A/B under a tight external timeout: killing a client
mid-compile wedges the tunnel for hours.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
LOG = os.path.join(REPO, "tools", "tpu_ladder_r4.log")
PROBE_LOG = os.path.join(REPO, "tools", "tpu_probe_log.md")
DONE = os.path.join(REPO, "tools", "TPU_LADDER_DONE")


def log(msg):
    line = f"[{time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())}] {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def probe(timeout_s=75):
    """Subprocess probe; returns the healthy registry platform or None."""
    code = ("import jax; from jax._src import xla_bridge as xb; "
            "d = jax.devices(); "
            "n = [k for k, b in xb.backends().items() if b is d[0].client]; "
            "print(n[0] if n else d[0].platform, len(d), d[0].device_kind)")
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None
    if out.returncode != 0 or not out.stdout.strip():
        return None
    parts = out.stdout.strip().split(None, 2)
    ts = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(PROBE_LOG, "a") as f:
        f.write(f"- {ts} ladder probe: rc=0 {out.stdout.strip()}\n")
    return parts


def stage_a_pallas(jnp, np):
    """Compiled Pallas row_argmax vs XLA twin: parity + min-of-5 timing."""
    from cuvite_tpu.kernels.row_argmax import row_argmax_pallas
    from cuvite_tpu.louvain.bucketed import _row_argmax

    SENT = np.iinfo(np.int32).max
    rng = np.random.default_rng(0)
    for width in (8, 32):
        n_rows, nv = 1 << 16, 50000
        cmat = rng.integers(0, nv, size=(n_rows, width)).astype(np.int32)
        wmat = (rng.integers(1, 32, size=(n_rows, width)) / 16.0
                ).astype(np.float32)
        curr = rng.integers(0, nv, size=n_rows).astype(np.int32)
        cmat[: n_rows // 2, 0] = curr[: n_rows // 2]
        vdeg = (rng.integers(1, 64, size=n_rows) / 4.0).astype(np.float32)
        sl = np.where(cmat[:, 0] == curr, wmat[:, 0] / 2.0, 0.0
                      ).astype(np.float32)
        comm_deg = (rng.integers(1, 256, size=nv) / 8.0).astype(np.float32)
        const = np.float32(1.0 / 64.0)
        ay = comm_deg[cmat]
        ax = comm_deg[curr] - vdeg
        args_p = (jnp.asarray(np.ascontiguousarray(cmat.T)),
                  jnp.asarray(np.ascontiguousarray(wmat.T)),
                  jnp.asarray(np.ascontiguousarray(ay.T)),
                  jnp.asarray(curr), jnp.asarray(vdeg), jnp.asarray(sl),
                  jnp.asarray(ax), jnp.asarray(const))
        args_x = (jnp.asarray(cmat), jnp.asarray(wmat), jnp.asarray(ay),
                  None, jnp.asarray(curr), jnp.asarray(vdeg),
                  jnp.asarray(sl), jnp.asarray(ax), jnp.asarray(const),
                  SENT)

        t0 = time.perf_counter()
        bc, bg, c0 = row_argmax_pallas(*args_p, sentinel=SENT,
                                       interpret=False)
        bc_h = np.asarray(bc)
        log(f"A: width={width} pallas COMPILED ok "
            f"(first call {time.perf_counter()-t0:.1f}s)")
        ref = _row_argmax(*args_x)
        ok = (np.array_equal(bc_h, np.asarray(ref.best_c))
              and np.array_equal(np.asarray(bg), np.asarray(ref.best_gain))
              and np.array_equal(np.asarray(c0), np.asarray(ref.counter0)))
        log(f"A: width={width} bit-identity vs XLA: "
            f"{'PASS' if ok else 'FAIL'}")

        def t5(fn):
            ts = []
            for _ in range(5):
                t0 = time.perf_counter()
                out = fn()
                _ = float(np.asarray(out[0 if isinstance(out, tuple)
                                          else 0]).ravel()[0])
                ts.append(time.perf_counter() - t0)
            return min(ts)

        tp = t5(lambda: row_argmax_pallas(*args_p, sentinel=SENT,
                                          interpret=False))
        tx = t5(lambda: _row_argmax(*args_x))
        log(f"A: width={width} rows={n_rows}: pallas {tp*1e3:.2f} ms vs "
            f"XLA {tx*1e3:.2f} ms ({tx/max(tp,1e-9):.2f}x)")


def stage_b_step(np):
    from cuvite_tpu.core.distgraph import DistGraph
    from cuvite_tpu.io.generate import generate_rmat
    from cuvite_tpu.louvain.driver import PhaseRunner

    g = generate_rmat(18, edge_factor=16, seed=1)
    t0 = time.perf_counter()
    dg = DistGraph.build(g, 1)
    runner = PhaseRunner(dg, engine="bucketed")
    _ = np.asarray(runner.comm0[0:1])
    log(f"B: plan+upload {time.perf_counter()-t0:.2f}s (scale 18, "
        f"{g.num_edges} edges)")

    def step(c):
        return runner._step(None, None, None, c, runner.vdeg,
                            runner.constant)

    t0 = time.perf_counter()
    out = step(runner.comm0)
    _ = float(out[1])
    log(f"B: first step (compile) {time.perf_counter()-t0:.1f}s")
    c = runner.comm0
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        tgt, mod, _, _ = step(c)
        _ = float(mod)
        times.append(time.perf_counter() - t0)
        c = tgt
    best = min(times)
    log(f"B: step+fetch {best*1e3:.1f} ms "
        f"({g.num_edges/max(best,1e-9)/1e6:.1f} M edges/s incl. rtt); "
        f"round-2 pre-batch baseline was ~630 ms")


def stage_c_bench(platform):
    for scale in (18, 20):
        env = dict(os.environ, BENCH_SCALE=str(scale),
                   BENCH_TIME_BUDGET="900")
        t0 = time.perf_counter()
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=1800, env=env)
        last = out.stdout.strip().splitlines()
        log(f"C: bench scale={scale} rc={out.returncode} "
            f"wall={time.perf_counter()-t0:.0f}s "
            f"json={last[-1] if last else '?'}")
        if out.returncode == 0 and last:
            try:
                j = json.loads(last[-1])
                if j.get("platform") != "cpu":
                    with open(os.path.join(
                            REPO, f"tools/bench_tpu_s{scale}_r4.json"),
                            "w") as f:
                        f.write(last[-1] + "\n")
            except json.JSONDecodeError:
                pass


def main():
    parts = probe()
    if parts is None:
        print("probe: tunnel not answering", flush=True)
        return 2
    plat = parts[0]
    log(f"PROBE OK: {' '.join(parts)}")
    if plat == "cpu":
        log("probe resolved to cpu (no TPU registered); nothing to measure")
        return 2
    import jax

    jax.config.update("jax_platforms", plat)
    from cuvite_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    import jax.numpy as jnp
    import numpy as np

    log(f"backend pinned: {plat}; devices={jax.devices()}")
    try:
        stage_a_pallas(jnp, np)
    except Exception as e:  # keep going: B/C are subprocess-independent
        log(f"A: FAILED {type(e).__name__}: {e}")
    try:
        stage_b_step(np)
    except Exception as e:
        log(f"B: FAILED {type(e).__name__}: {e}")
    stage_c_bench(plat)
    with open(DONE, "w") as f:
        f.write(time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()) + "\n")
    log("LADDER COMPLETE")
    return 0


if __name__ == "__main__":
    sys.exit(main())
