"""Full louvain_phases A/B (bench.py's timed body, minus the probe).

One warm-up + one timed run at AB_SCALE (default 18) on the backend pinned
by CUVITE_PLATFORM.  Prints phase breakdown and TEPS for the timed run.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _common  # noqa: F401

import jax

from cuvite_tpu.io.generate import generate_rmat
from cuvite_tpu.louvain.driver import louvain_phases


def teps(res):
    trav = sum(p.num_edges * p.iterations for p in res.phases)
    clus = sum(p.seconds for p in res.phases)
    return trav / max(clus, 1e-9), clus


def main():
    scale = int(os.environ.get("AB_SCALE", "18"))
    engine = os.environ.get("AB_ENGINE", "auto")
    print(f"# backend={jax.default_backend()} scale={scale} engine={engine}",
          flush=True)
    g = generate_rmat(scale, edge_factor=16, seed=1)
    t0 = time.perf_counter()
    res = louvain_phases(g, engine=engine)
    print(f"# warmup wall {time.perf_counter() - t0:.1f}s", flush=True)
    from cuvite_tpu.utils.trace import Tracer

    tr = Tracer()  # stage breakdown incl. coalesce_s (ISSUE 8)
    t0 = time.perf_counter()
    res = louvain_phases(g, engine=engine, verbose=False, tracer=tr)
    wall = time.perf_counter() - t0
    v, clus = teps(res)
    iters = sum(p.iterations for p in res.phases)
    print(f"Q={res.modularity:.5f} phases={len(res.phases)} iters={iters} "
          f"clustering={clus:.2f}s wall={wall:.1f}s "
          f"TEPS={v/1e6:.2f}M", flush=True)
    bd = tr.breakdown()
    stages = " ".join(f"{k}={bd[k]:.2f}" for k in sorted(bd))
    co_tot = tr.counters.get("coalesce_edges", 0)
    co_dense = tr.counters.get("coalesce_dense_edges", 0)
    print(f"# stages: {stages}", flush=True)
    if co_tot:
        print(f"# coalesce_kernel={co_dense / co_tot:.4f} "
              f"({co_dense:g}/{co_tot:g} edges dense)", flush=True)
    for p in res.phases:
        print(f"#   phase ne={p.num_edges} it={p.iterations} "
              f"t={p.seconds:.2f}s", flush=True)


if __name__ == "__main__":
    main()
