// cuvite_tpu native host runtime: graph ingest, CSR construction and
// synthetic-graph generation.
//
// This is the TPU framework's equivalent of the reference's native host
// layer (the MPI-IO loader /root/reference/distgraph.cpp:69-337, the CSR
// assembly in send_newEdges /root/reference/rebuild.cpp:379-427, and the
// in-memory generator /root/reference/distgraph.cpp:341-933).  The device
// compute path is JAX/XLA/Pallas; everything here runs on the host CPU,
// feeding device-ready struct-of-arrays buffers.
//
// Design constraints:
//  * bit-deterministic: every routine produces output identical to the
//    pure-numpy fallback in cuvite_tpu (tested in tests/test_native.py),
//    so a run is reproducible with or without the native library.
//  * OpenMP where it pays (per-row sorts, deinterleaving); serial where
//    determinism of float accumulation order matters.
//  * C ABI only — bound from Python via ctypes, no pybind11.

#ifndef _FILE_OFFSET_BITS
#define _FILE_OFFSET_BITS 64  // 64-bit off_t for fseeko on 32-bit-long ABIs
#endif

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <numeric>
#include <type_traits>
#include <sys/types.h>
#include <vector>

#if defined(_OPENMP)
#include <omp.h>
#endif

// ---------------------------------------------------------------------------
// CSR construction from an edge list (one template, two entry points).
//
// Matches cuvite_tpu.core.graph.Graph.from_edges exactly:
//   - symmetrize: append (dst,src,w) for every non-self edge, after the
//     originals (same virtual concatenation order as the numpy path);
//   - sort by (src, dst) with duplicates kept in input order (stable);
//   - coalesce duplicates by summing weights in double, in input order
//     (numpy's np.add.at order after a stable argsort).
//
// UNIT=true is the R-MAT / unweighted-input specialization: every edge
// weighs exactly 1, so coalescing is duplicate COUNTING and ids ride
// int32 end to end — no 8-byte array exists at any point, which is what
// took single-host scale-26 ingest from an OOM at 131 GB to a measured
// 56 GB peak (tools/scale_model.md).  weights_out[k] = (float)count is
// bit-identical to the generic path's f64 sum-of-ones cast to f32 (both
// round the exact integer once); callers therefore gate the unit path on
// a float32 weight policy.
//
// Sort scheme: small-nv dense-accumulator fast path (counting-sort by
// src + generation-stamped per-row scratch, ~4x for coarsened community
// graphs), else byte-wise LSD radix on the composite key src*nv + dst.
// Measured A/Bs on this host (60 M random edges, 1 core): 16-bit digits
// are ~2x SLOWER (64 K per-bucket write streams thrash L1/TLB; 256 stay
// cache-resident), and a 3-stream u32 dst-radix + counting-by-src
// variant is ~1.6x slower (the nv-bucket scatter costs a cache miss per
// element).  Allocation order keeps the radix peak at ~32 B/slot
// (~16 B/slot for UNIT): the expanded edge list is freed/moved before
// the ping-pong buffers are allocated.

struct NoPayload {};

// Byte-wise LSD radix on uint64 keys with an optional ping-pong payload
// (P = NoPayload sorts keys alone).  Stable, so duplicates keep input
// order.  8-bit digits (16-bit digits measured ~2x slower on this host:
// 64 K per-bucket write streams thrash L1/TLB; 256 stay cache-resident).
// The histogram/scatter loops run over BLOCK ids, not thread ids, so
// correctness holds for any actual OpenMP team size (OMP_DYNAMIC,
// thread limits, nested regions) — every block is processed exactly
// once, whoever runs it.  The exclusive scan is digit-major then
// block-minor: block t's digit-b slots start after every block's
// smaller digits and after earlier blocks' digit-b entries — preserving
// LSD stability.  Shared by the three O(E) sorts (both CSR builders'
// radix branches and the large-nc coarsen); transient = one key + one
// payload ping-pong buffer, allocated here.
template <typename P>
static void radix_sort_pairs(std::vector<uint64_t>& key, std::vector<P>& pay,
                             int key_bits) {
  constexpr bool HAS_P = !std::is_same<P, NoPayload>::value;
  const int64_t m = (int64_t)key.size();
  std::vector<uint64_t> key2(m);
  std::vector<P> pay2;
  if constexpr (HAS_P) pay2.resize(m);
#if defined(_OPENMP)
  const int nt = omp_get_max_threads();
#else
  const int nt = 1;
#endif
  constexpr int DIGIT_BITS = 8;
  constexpr int NB = 1 << DIGIT_BITS;
  constexpr uint64_t DMASK = NB - 1;
  std::vector<int64_t> hist((size_t)nt * NB);
  const int64_t blk = (m + nt - 1) / (nt > 0 ? nt : 1);
  for (int shift = 0; shift < key_bits; shift += DIGIT_BITS) {
    std::fill(hist.begin(), hist.end(), 0);
#pragma omp parallel for schedule(static)
    for (int t = 0; t < nt; ++t) {
      int64_t* h = hist.data() + (size_t)t * NB;
      const int64_t lo = t * blk, hi = std::min<int64_t>(m, lo + blk);
      for (int64_t j = lo; j < hi; ++j) h[(key[j] >> shift) & DMASK]++;
    }
    int64_t run = 0;
    for (int b = 0; b < NB; ++b) {
      for (int t = 0; t < nt; ++t) {
        int64_t c = hist[(size_t)t * NB + b];
        hist[(size_t)t * NB + b] = run;
        run += c;
      }
    }
#pragma omp parallel for schedule(static)
    for (int t = 0; t < nt; ++t) {
      int64_t* h = hist.data() + (size_t)t * NB;
      const int64_t lo = t * blk, hi = std::min<int64_t>(m, lo + blk);
      for (int64_t j = lo; j < hi; ++j) {
        int64_t slot = h[(key[j] >> shift) & DMASK]++;
        key2[slot] = key[j];
        if constexpr (HAS_P) pay2[slot] = pay[j];
      }
    }
    key.swap(key2);
    if constexpr (HAS_P) pay.swap(pay2);
  }
}

// Key width for a composite key a*nv + b, a,b < nv: max key is
// nv*nv - 1 < 2^(2*ceil(log2 nv)); computing from bits(nv-1) avoids
// evaluating nv*nv, which wraps at nv == 2^32.
static int composite_key_bits(uint64_t nv) {
  int vb = 0;
  for (uint64_t x = nv > 0 ? nv - 1 : 0; x; x >>= 1) ++vb;
  return 2 * vb;
}

template <typename IdT, bool UNIT>
static int64_t build_csr_impl(
    int64_t nv, int64_t ne, const IdT* src, const IdT* dst, const double* w,
    int symmetrize, int64_t* offsets_out, IdT* tails_out,
    typename std::conditional<UNIT, float, double>::type* weights_out) {
  using UId = typename std::make_unsigned<IdT>::type;
  using WOut = typename std::conditional<UNIT, float, double>::type;
  // The composite radix key src*nv+dst must fit uint64; UNIT ids int32.
  const int64_t nv_cap =
      UNIT ? ((int64_t)1 << 31) : ((int64_t)1 << 32);
  if (nv < 0 || nv > nv_cap) return -1;
  for (int64_t j = 0; j < ne; ++j) {
    if (src[j] < 0 || src[j] >= nv || dst[j] < 0 || dst[j] >= nv) return -1;
  }
  // Expanded (virtually concatenated) edge list.
  int64_t m = ne;
  std::vector<UId> xs, xd;
  std::vector<double> xw;
  if (symmetrize) {
    int64_t nself = 0;
    for (int64_t j = 0; j < ne; ++j) nself += (src[j] == dst[j]);
    m = 2 * ne - nself;
    xs.resize(m);
    xd.resize(m);
    if (!UNIT) xw.resize(m);
    for (int64_t j = 0; j < ne; ++j) {
      xs[j] = (UId)src[j];
      xd[j] = (UId)dst[j];
      if (!UNIT) xw[j] = w[j];
    }
    int64_t k = ne;
    for (int64_t j = 0; j < ne; ++j) {
      if (src[j] != dst[j]) {
        xs[k] = (UId)dst[j];
        xd[k] = (UId)src[j];
        if (!UNIT) xw[k] = w[j];
        ++k;
      }
    }
  } else {
    xs.resize(m);
    xd.resize(m);
    if (!UNIT) xw.resize(m);
    for (int64_t j = 0; j < ne; ++j) {
      xs[j] = (UId)src[j];
      xd[j] = (UId)dst[j];
      if (!UNIT) xw[j] = w[j];
    }
  }

  // Small-nv fast path: counting-sort by src (stable), then per-row dense
  // accumulation with a generation-stamped scratch.  Bit-identical to the
  // sort path: within a row, duplicate (src, dst) pairs accumulate in
  // input order (exactly the grouping a stable sort produces), and each
  // row's unique tails are emitted sorted ascending.
  if ((uint64_t)nv <= (1ull << 22)) {
    std::vector<int64_t> row_start(nv + 1, 0);
    for (int64_t j = 0; j < m; ++j) row_start[(int64_t)xs[j] + 1]++;
    for (int64_t v = 0; v < nv; ++v) row_start[v + 1] += row_start[v];
    std::vector<UId> rd(m);
    std::vector<double> rw;
    if (!UNIT) rw.resize(m);
    {
      std::vector<int64_t> pos(row_start.begin(), row_start.end() - 1);
      for (int64_t j = 0; j < m; ++j) {
        const int64_t p = pos[xs[j]]++;
        rd[p] = xd[j];
        if (!UNIT) rw[p] = xw[j];
      }
    }
    using Acc = typename std::conditional<UNIT, int64_t, double>::type;
    std::vector<Acc> acc(nv, (Acc)0);
    std::vector<int64_t> seen(nv, -1);
    std::vector<int64_t> uniq;
    std::memset(offsets_out, 0, (nv + 1) * sizeof(int64_t));
    int64_t n_out = 0;
    for (int64_t r = 0; r < nv; ++r) {
      uniq.clear();
      for (int64_t k = row_start[r]; k < row_start[r + 1]; ++k) {
        const int64_t d = (int64_t)rd[k];
        if (seen[d] != r) {
          seen[d] = r;
          if constexpr (UNIT) acc[d] = 1; else acc[d] = rw[k];
          uniq.push_back(d);
        } else {
          if constexpr (UNIT) acc[d] += 1; else acc[d] += rw[k];
        }
      }
      std::sort(uniq.begin(), uniq.end());
      offsets_out[r + 1] = (int64_t)uniq.size();
      for (int64_t d : uniq) {
        tails_out[n_out] = (IdT)d;
        weights_out[n_out] = (WOut)acc[d];
        ++n_out;
      }
    }
    for (int64_t v = 0; v < nv; ++v) offsets_out[v + 1] += offsets_out[v];
    return n_out;
  }

  // Byte-wise LSD radix on the composite key (radix_sort_pairs).
  const uint64_t unv = (uint64_t)nv;
  std::vector<uint64_t> key(m);
  for (int64_t j = 0; j < m; ++j)
    key[j] = (uint64_t)xs[j] * unv + (uint64_t)xd[j];
  xs.clear(); xs.shrink_to_fit();
  xd.clear(); xd.shrink_to_fit();
  std::vector<double> pw(std::move(xw));
  if constexpr (UNIT) {
    std::vector<NoPayload> none;
    radix_sort_pairs(key, none, composite_key_bits(unv));
  } else {
    radix_sort_pairs(key, pw, composite_key_bits(unv));
  }

  // Linear coalesce of the sorted stream into the CSR.
  std::memset(offsets_out, 0, (nv + 1) * sizeof(int64_t));
  int64_t n_out = 0;
  uint64_t prev_key = ~0ull;
  if constexpr (UNIT) {
    int64_t run_count = 0;
    for (int64_t j = 0; j < m; ++j) {
      if (key[j] == prev_key) {
        ++run_count;
      } else {
        if (n_out) weights_out[n_out - 1] = (float)run_count;
        prev_key = key[j];
        run_count = 1;
        tails_out[n_out] = (IdT)(key[j] % unv);
        offsets_out[key[j] / unv + 1]++;
        ++n_out;
      }
    }
    if (n_out) weights_out[n_out - 1] = (float)run_count;
  } else {
    for (int64_t j = 0; j < m; ++j) {
      if (key[j] == prev_key) {
        weights_out[n_out - 1] += pw[j];
      } else {
        prev_key = key[j];
        tails_out[n_out] = (IdT)(key[j] % unv);
        weights_out[n_out] = pw[j];
        offsets_out[key[j] / unv + 1]++;
        ++n_out;
      }
    }
  }
  for (int64_t v = 0; v < nv; ++v) offsets_out[v + 1] += offsets_out[v];
  return n_out;
}

// Weighted low-footprint CSR builder (int32 ids, f32 output weights).
//
// The generic cv_build_csr carries an f64 payload through every radix
// pass (key+payload ping-pong = 32 B/slot) and emits int64/f64 outputs —
// ~65 B/slot end to end, which OOM-killed a weighted scale-26 ingest at
// 131 GB (tools/scale_model.md).  This variant sorts an int32 ORIGINAL-
// EDGE-INDEX payload instead (key 8x2 + idx 4x2 = 24 B/slot transient)
// and gathers w[idx] only at the linear coalesce, accumulating in double
// and casting to f32 once per unique edge — the exact value the generic
// path produces after its policy cast, because a stable sort of indices
// visits duplicates in the same input order the f64-payload sort does.
// Requires nv <= 2^31 and expanded edge count < 2^31 (int32 index).
template <typename IdT>
static int64_t build_csr_w32_impl(int64_t nv, int64_t ne, const IdT* src,
                                  const IdT* dst, const double* w,
                                  int symmetrize, int64_t* offsets_out,
                                  int32_t* tails_out, float* weights_out) {
  if (nv < 0 || nv > ((int64_t)1 << 31)) return -1;
  for (int64_t j = 0; j < ne; ++j) {
    if (src[j] < 0 || src[j] >= nv || dst[j] < 0 || dst[j] >= nv) return -1;
  }
  int64_t m = ne;
  int64_t nself = 0;
  if (symmetrize) {
    for (int64_t j = 0; j < ne; ++j) nself += (src[j] == dst[j]);
    m = 2 * ne - nself;
  }
  if (m >= ((int64_t)1 << 31)) return -1;  // int32 index payload bound
  const uint64_t unv = (uint64_t)nv;

  // Expanded key + original-edge-index payload.  Mirrored entries point
  // at the ORIGINAL edge's weight; expansion order (originals first,
  // mirrors after) matches the numpy concatenation, so stable sorting
  // reproduces the generic accumulation order exactly.
  std::vector<uint64_t> key(m);
  std::vector<int32_t> idx(m);
  for (int64_t j = 0; j < ne; ++j) {
    key[j] = (uint64_t)src[j] * unv + (uint64_t)dst[j];
    idx[j] = (int32_t)j;
  }
  if (symmetrize) {
    int64_t k = ne;
    for (int64_t j = 0; j < ne; ++j) {
      if (src[j] != dst[j]) {
        key[k] = (uint64_t)dst[j] * unv + (uint64_t)src[j];
        idx[k] = (int32_t)j;
        ++k;
      }
    }
  }

  // Byte-wise LSD radix (radix_sort_pairs), payload = int32 index.
  radix_sort_pairs(key, idx, composite_key_bits(unv));

  // Linear coalesce: gather w[idx] in sorted order, accumulate in double
  // per run, cast once at emission.
  std::memset(offsets_out, 0, (nv + 1) * sizeof(int64_t));
  int64_t n_out = 0;
  uint64_t prev_key = ~0ull;
  double acc = 0.0;
  for (int64_t j = 0; j < m; ++j) {
    if (key[j] == prev_key) {
      acc += w[idx[j]];
    } else {
      if (n_out) weights_out[n_out - 1] = (float)acc;
      prev_key = key[j];
      acc = w[idx[j]];
      tails_out[n_out] = (int32_t)(key[j] % unv);
      offsets_out[key[j] / unv + 1]++;
      ++n_out;
    }
  }
  if (n_out) weights_out[n_out - 1] = (float)acc;
  for (int64_t v = 0; v < nv; ++v) offsets_out[v + 1] += offsets_out[v];
  return n_out;
}

extern "C" {

// offsets_out must hold nv+1 entries; tails_out/weights_out must hold
// (symmetrize ? 2*ne : ne) entries.  Returns the number of unique CSR
// entries written, or -1 on bad input (src/dst out of range).
int64_t cv_build_csr(int64_t nv, int64_t ne, const int64_t* src,
                     const int64_t* dst, const double* w, int symmetrize,
                     int64_t* offsets_out, int64_t* tails_out,
                     double* weights_out) {
  return build_csr_impl<int64_t, false>(nv, ne, src, dst, w, symmetrize,
                                        offsets_out, tails_out, weights_out);
}

// Unit-weight int32 variant (see the template header).  Requires
// nv <= 2^31; weights_out holds f32 duplicate counts.
int64_t cv_build_csr_unit(int64_t nv, int64_t ne, const int32_t* src,
                          const int32_t* dst, int symmetrize,
                          int64_t* offsets_out, int32_t* tails_out,
                          float* weights_out) {
  return build_csr_impl<int32_t, true>(nv, ne, src, dst, nullptr, symmetrize,
                                       offsets_out, tails_out, weights_out);
}

// Weighted low-footprint builder (see build_csr_w32_impl); src/dst may be
// int32 or int64 (id64 flag) — no width conversion is ever materialized.
int64_t cv_build_csr_w32(int64_t nv, int64_t ne, const void* src,
                         const void* dst, const double* w, int id64,
                         int symmetrize, int64_t* offsets_out,
                         int32_t* tails_out, float* weights_out) {
  if (id64)
    return build_csr_w32_impl(nv, ne, (const int64_t*)src,
                              (const int64_t*)dst, w, symmetrize,
                              offsets_out, tails_out, weights_out);
  return build_csr_w32_impl(nv, ne, (const int32_t*)src,
                            (const int32_t*)dst, w, symmetrize,
                            offsets_out, tails_out, weights_out);
}

// ---------------------------------------------------------------------------
// Fused inter-phase coarsening: relabel + coalesce straight from the CSR.
//
// Equivalent computation to cuvite_tpu.coarsen.rebuild.coarsen_graph's
// relabel + Graph.from_edges(symmetrize=False) (itself the analog of
// distbuildNextLevelGraph, /root/reference/rebuild.cpp:430-454), but with
// no expanded numpy edge list: the (labels[src], labels[dst]) composite
// key is generated row-by-row from the CSR, so the only O(E) transients
// are the radix key/payload ping-pong buffers (~32 B/slot; the numpy
// route peaked at ~3x that in int64/f64 temporaries and dominated the
// host share of benchmark-scale runs — VERDICT r3 weak #2).
//
// Bit-identity with the fallback path: the key sequence equals the numpy
// path's (stable LSD radix = stable argsort; duplicate (s,d) pairs keep
// CSR order), weights accumulate in double in that order, and the result
// is cast to f32 once — exactly Graph.from_edges' contract.

}  // extern "C" — the coarsen template needs C++ linkage

template <typename IdT, typename WT>
static int64_t coarsen_impl(int64_t nv, int64_t nc, const int64_t* offsets,
                            const IdT* tails, const WT* w,
                            const int32_t* labels, int64_t* offsets_out,
                            int32_t* tails_out, float* weights_out,
                            int force_dense) {
  if (nc < 0 || nc > ((int64_t)1 << 31)) return -1;
  const int64_t m = offsets[nv];
  for (int64_t v = 0; v < nv; ++v)
    if (labels[v] < 0 || labels[v] >= nc) return -1;

  // Counting-sort path: rows by coarse src, then dense per-row
  // accumulation (generation-stamped scratch).  Same output as the sort
  // path: duplicates accumulate in CSR order, unique tails emitted
  // ascending.  Default for small nc (the O(nc) scratch is hot); also
  // selected by the caller via ``force_dense`` for benchmark-scale
  // graphs where the radix path's 32 B/slot ping-pong transient exceeds
  // host RAM — this path peaks at 12 B/slot + O(nc)
  // (tools/scale_model.md).
  if (force_dense || nc <= ((int64_t)1 << 22)) {
    std::vector<int64_t> row_start(nc + 1, 0);
    for (int64_t v = 0; v < nv; ++v)
      row_start[(int64_t)labels[v] + 1] += offsets[v + 1] - offsets[v];
    for (int64_t r = 0; r < nc; ++r) row_start[r + 1] += row_start[r];
    std::vector<int32_t> rd(m);
    std::vector<double> rw(m);
    {
      std::vector<int64_t> pos(row_start.begin(), row_start.end() - 1);
      for (int64_t v = 0; v < nv; ++v) {
        const int32_t s = labels[v];
        int64_t p = pos[s];
        for (int64_t k = offsets[v]; k < offsets[v + 1]; ++k) {
          rd[p] = labels[(int64_t)tails[k]];
          rw[p] = (double)w[k];
          ++p;
        }
        pos[s] = p;
      }
    }
    std::vector<double> acc(nc, 0.0);
    std::vector<int64_t> seen(nc, -1);
    std::vector<int64_t> uniq;
    std::memset(offsets_out, 0, (nc + 1) * sizeof(int64_t));
    int64_t n_out = 0;
    for (int64_t r = 0; r < nc; ++r) {
      uniq.clear();
      for (int64_t k = row_start[r]; k < row_start[r + 1]; ++k) {
        const int64_t d = (int64_t)rd[k];
        if (seen[d] != r) {
          seen[d] = r;
          acc[d] = rw[k];
          uniq.push_back(d);
        } else {
          acc[d] += rw[k];
        }
      }
      std::sort(uniq.begin(), uniq.end());
      offsets_out[r + 1] = (int64_t)uniq.size();
      for (int64_t d : uniq) {
        tails_out[n_out] = (int32_t)d;
        weights_out[n_out] = (float)acc[d];
        ++n_out;
      }
    }
    for (int64_t r = 0; r < nc; ++r) offsets_out[r + 1] += offsets_out[r];
    return n_out;
  }

  // Large-nc: byte-wise LSD radix on labels[s]*nc + labels[d]
  // (radix_sort_pairs — same stability argument as build_csr_impl).
  const uint64_t unc = (uint64_t)nc;
  std::vector<uint64_t> key(m);
  std::vector<double> pw(m);
  for (int64_t v = 0; v < nv; ++v) {
    const uint64_t s = (uint64_t)labels[v] * unc;
    for (int64_t k = offsets[v]; k < offsets[v + 1]; ++k) {
      key[k] = s + (uint64_t)labels[(int64_t)tails[k]];
      pw[k] = (double)w[k];
    }
  }
  radix_sort_pairs(key, pw, composite_key_bits(unc));
  std::memset(offsets_out, 0, (nc + 1) * sizeof(int64_t));
  int64_t n_out = 0;
  uint64_t prev_key = ~0ull;
  std::vector<double> wacc;
  wacc.reserve(1 << 20);
  // Accumulate runs in double, cast once at emission (stream the cast to
  // avoid holding a full f64 copy of the output).
  for (int64_t j = 0; j < m; ++j) {
    if (key[j] == prev_key) {
      wacc[n_out - 1] += pw[j];
    } else {
      prev_key = key[j];
      tails_out[n_out] = (int32_t)(key[j] % unc);
      offsets_out[key[j] / unc + 1]++;
      wacc.push_back(pw[j]);
      ++n_out;
    }
  }
  for (int64_t j = 0; j < n_out; ++j) weights_out[j] = (float)wacc[j];
  for (int64_t r = 0; r < nc; ++r) offsets_out[r + 1] += offsets_out[r];
  return n_out;
}

extern "C" int64_t cv_coarsen(int64_t nv, int64_t nc, const int64_t* offsets,
                              const void* tails, const void* w, int id64,
                              int w64, const int32_t* labels,
                              int64_t* offsets_out, int32_t* tails_out,
                              float* weights_out, int force_dense) {
  if (id64) {
    if (w64)
      return coarsen_impl(nv, nc, offsets, (const int64_t*)tails,
                          (const double*)w, labels, offsets_out, tails_out,
                          weights_out, force_dense);
    return coarsen_impl(nv, nc, offsets, (const int64_t*)tails,
                        (const float*)w, labels, offsets_out, tails_out,
                        weights_out, force_dense);
  }
  if (w64)
    return coarsen_impl(nv, nc, offsets, (const int32_t*)tails,
                        (const double*)w, labels, offsets_out, tails_out,
                        weights_out, force_dense);
  return coarsen_impl(nv, nc, offsets, (const int32_t*)tails,
                      (const float*)w, labels, offsets_out, tails_out,
                      weights_out, force_dense);
}

// Per-vertex weighted degree straight off the CSR: one sequential f64
// accumulation in slab order — bit-identical to
// np.bincount(sources, weights=w.astype(f64)) without the O(E) expanded
// source array (Graph.weighted_degrees' numpy route).
extern "C" void cv_weighted_degrees(int64_t nv, const int64_t* offsets,
                                    const void* w, int w64, double* out) {
  for (int64_t v = 0; v < nv; ++v) {
    double a = 0.0;
    if (w64) {
      const double* ww = (const double*)w;
      for (int64_t k = offsets[v]; k < offsets[v + 1]; ++k) a += ww[k];
    } else {
      const float* ww = (const float*)w;
      for (int64_t k = offsets[v]; k < offsets[v + 1]; ++k) a += (double)ww[k];
    }
    out[v] = a;
  }
}

extern "C" {

// ---------------------------------------------------------------------------
// Counter-based RNG (SplitMix64): stateless, trivially parallel, and
// reproduced verbatim by the numpy fallback (cuvite_tpu/utils/rng.py).
static inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x ^= x >> 30;
  x *= 0xBF58476D1CE4E5B9ull;
  x ^= x >> 27;
  x *= 0x94D049BB133111EBull;
  x ^= x >> 31;
  return x;
}

static inline double u01(uint64_t x) {
  return (double)(x >> 11) * (1.0 / 9007199254740992.0); /* 2^-53 */
}

// Deterministic bijective scramble of [0, 2^bits): rounds of
// (multiply by odd constant mod 2^bits, xor with own high half).  Replaces
// the numpy path's rng.permutation for breaking the R-MAT id/degree
// correlation; identical formula in cuvite_tpu/utils/rng.py:scramble_ids.
static inline uint64_t scramble(uint64_t x, int bits, uint64_t seed) {
  const uint64_t mask = (bits >= 64) ? ~0ull : ((1ull << bits) - 1);
  const uint64_t odd1 = (splitmix64(seed ^ 0xA5A5A5A5ull) | 1ull);
  const uint64_t odd2 = (splitmix64(seed ^ 0x5A5A5A5Aull) | 1ull);
  int h = bits / 2 > 0 ? bits / 2 : 1;
  x = (x * odd1) & mask;
  x ^= x >> h;
  x = (x * odd2) & mask;
  x ^= x >> h;
  return x & mask;
}

// Graph500-style R-MAT edge generator: ne edges over 2^scale vertices with
// recursive quadrant probabilities (a, b, c, 1-a-b-c).  Equivalent in role
// to the reference's in-memory generator entry point
// (/root/reference/distgraph.cpp:341-357); the RGG variant lives in Python
// (KD-tree based) — this native path serves the large benchmark graphs.
void cv_rmat(int scale, int64_t ne, uint64_t seed, double a, double b,
             double c, int64_t* src_out, int64_t* dst_out) {
  const double ab = a + b;
  const double a_norm = a / ab;
  const double c_norm = c / (1.0 - ab);
#pragma omp parallel for schedule(static)
  for (int64_t e = 0; e < ne; ++e) {
    uint64_t s = 0, d = 0;
    const uint64_t base = seed + (uint64_t)e * (uint64_t)(2 * scale);
    for (int l = 0; l < scale; ++l) {
      double r1 = u01(splitmix64(base + (uint64_t)(2 * l)));
      double r2 = u01(splitmix64(base + (uint64_t)(2 * l + 1)));
      uint64_t sbit = r1 > ab;
      uint64_t dbit = sbit ? (r2 > c_norm) : (r2 > a_norm);
      s = (s << 1) | sbit;
      d = (d << 1) | dbit;
    }
    src_out[e] = (int64_t)scramble(s, scale, seed);
    dst_out[e] = (int64_t)scramble(d, scale, seed);
  }
}

// ---------------------------------------------------------------------------
// Vite binary graph format (layout: cuvite_tpu/io/vite.py and the
// reference loader /root/reference/distgraph.cpp:99-197):
//   [nv][ne] [offsets (nv+1)] [edges ne x {tail, weight}]
// with 64-bit (i8/f8) or 32-bit (i4/f4) element widths.

int cv_vite_header(const char* path, int bits64, int64_t* nv_out,
                   int64_t* ne_out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  int rc = 0;
  if (bits64) {
    int64_t h[2];
    rc = std::fread(h, sizeof(int64_t), 2, f) == 2 ? 0 : -2;
    if (rc == 0) { *nv_out = h[0]; *ne_out = h[1]; }
  } else {
    int32_t h[2];
    rc = std::fread(h, sizeof(int32_t), 2, f) == 2 ? 0 : -2;
    if (rc == 0) { *nv_out = h[0]; *ne_out = h[1]; }
  }
  std::fclose(f);
  return rc;
}

// Reads edge records [e0, e1) and deinterleaves them to struct-of-arrays
// (the caller reads + validates the offsets itself, via memmap in
// cuvite_tpu/io/vite.py).  Returns 0 on success.
int cv_vite_edges(const char* path, int bits64, int64_t nv, int64_t e0,
                  int64_t e1, int64_t* tails_out, double* weights_out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  const int64_t esz = bits64 ? 8 : 4;
  const int64_t rec = bits64 ? 16 : 8;
  const int64_t base = 2 * esz + (nv + 1) * esz + e0 * rec;
  // fseeko takes off_t (64-bit with _FILE_OFFSET_BITS=64), so offsets past
  // 2 GiB work even where long is 32-bit; the read streams in bounded
  // chunks so a billion-edge shard never needs a matching heap buffer.
  if (fseeko(f, (off_t)base, SEEK_SET) != 0) { std::fclose(f); return -3; }
  const int64_t n = e1 - e0;
  const int64_t chunk = 4 << 20;  // records per read (<= 64 MiB buffer)
  std::vector<char> buf((size_t)(n < chunk ? (n > 0 ? n : 1) : chunk) * rec);
  for (int64_t done = 0; done < n; ) {
    const int64_t c = n - done < chunk ? n - done : chunk;
    if ((int64_t)std::fread(buf.data(), rec, c, f) != c) {
      std::fclose(f);
      return -2;
    }
    if (bits64) {
      struct E { int64_t t; double w; };
      const E* e = (const E*)buf.data();
#pragma omp parallel for schedule(static)
      for (int64_t i = 0; i < c; ++i) {
        tails_out[done + i] = e[i].t;
        weights_out[done + i] = e[i].w;
      }
    } else {
      struct E { int32_t t; float w; };
      const E* e = (const E*)buf.data();
#pragma omp parallel for schedule(static)
      for (int64_t i = 0; i < c; ++i) {
        tails_out[done + i] = e[i].t;
        weights_out[done + i] = e[i].w;
      }
    }
    done += c;
  }
  std::fclose(f);
  return 0;
}

int cv_vite_write(const char* path, int bits64, int64_t nv, int64_t ne,
                  const int64_t* offsets, const int64_t* tails,
                  const double* weights) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  int rc = 0;
  if (bits64) {
    int64_t h[2] = {nv, ne};
    if (std::fwrite(h, 8, 2, f) != 2) rc = -2;
    if (!rc && (int64_t)std::fwrite(offsets, 8, nv + 1, f) != nv + 1) rc = -2;
    if (!rc) {
      struct E { int64_t t; double w; };
      std::vector<E> buf(ne);
#pragma omp parallel for schedule(static)
      for (int64_t i = 0; i < ne; ++i) buf[i] = {tails[i], weights[i]};
      if ((int64_t)std::fwrite(buf.data(), 16, ne, f) != ne) rc = -2;
    }
  } else {
    int32_t h[2] = {(int32_t)nv, (int32_t)ne};
    if (std::fwrite(h, 4, 2, f) != 2) rc = -2;
    if (!rc) {
      std::vector<int32_t> o32(nv + 1);
      for (int64_t i = 0; i <= nv; ++i) o32[i] = (int32_t)offsets[i];
      if ((int64_t)std::fwrite(o32.data(), 4, nv + 1, f) != nv + 1) rc = -2;
    }
    if (!rc) {
      struct E { int32_t t; float w; };
      std::vector<E> buf(ne);
#pragma omp parallel for schedule(static)
      for (int64_t i = 0; i < ne; ++i)
        buf[i] = {(int32_t)tails[i], (float)weights[i]};
      if ((int64_t)std::fwrite(buf.data(), 8, ne, f) != ne) rc = -2;
    }
  }
  std::fclose(f);
  return rc;
}

// ---------------------------------------------------------------------------
// Edge-balanced partition: greedy scan of the offset array assigning
// contiguous vertex ranges of ~ne/nparts edges each (role of balanceEdges,
// /root/reference/distgraph.cpp:22-66, reached via the -b flag).
void cv_balanced_parts(int64_t nv, const int64_t* offsets, int64_t nparts,
                       int64_t* parts_out) {
  const int64_t ne = offsets[nv];
  parts_out[0] = 0;
  // Cuts start at 1 (shard 0 is never empty), matching the Python
  // balanced_parts searchsorted-over-offsets[1:] semantics even when a
  // target is 0 (ne < nparts).
  int64_t v = 1;
  for (int64_t p = 1; p < nparts; ++p) {
    const int64_t target = (ne * p) / nparts;
    while (v < nv && offsets[v] < target) ++v;
    parts_out[p] = v;
  }
  parts_out[nparts] = nv;
}

// ---------------------------------------------------------------------------
// Bucket-plan construction (the host side of the degree-bucketed TPU engine,
// cuvite_tpu/louvain/bucketed.py BucketPlan.build).
//
// The numpy builder materializes O(E) int64/float64 transients per phase
// (real-mask copies, per-class [nb, width] index/gather matrices) — tens of
// GB at benchmark scales.  These two entry points stream the slab once each
// and write ONLY the output matrices, with no transient larger than O(nv):
//
//   cv_plan_scan  — one fused pass: per-vertex self-loop accumulation (f64,
//                   slab order, bit-identical to np.add.at), the unit-weight
//                   predicate, the src-sortedness check, and the
//                   padding-only-at-tail check that gates cv_bucket_fill.
//   cv_bucket_fill — one pass over CSR rows writing each vertex's padded
//                   bucket row (dst matrix + weight-or-mask matrix) and the
//                   heavy-vertex edge triples, exactly as the numpy path
//                   lays them out (pad columns carry the vertex's own
//                   global id with weight 0).
//
// The cheap O(nv) planning arithmetic (degree bincount, width-class
// assignment, row counters, pow2 padding) stays in numpy — it never touches
// O(E) memory.  Role analog: the reference's device bucketing + clmap setup
// (/root/reference/louvain_cuda.cu:1426-1592), which likewise builds its
// degree-class layout outside the iteration hot path.

}  // extern "C" — template helpers need C++ linkage

template <typename I, typename W>
static int plan_scan_impl(int64_t ne, int64_t nv, int64_t base, const I* src,
                          const I* dst, const W* w, double* self_loop,
                          int* flags_out) {
  int sorted = 1, unit = 1, tail_ok = 1;
  int64_t prev = -1;
  int seen_pad = 0;
  for (int64_t j = 0; j < ne; ++j) {
    const int64_t s = (int64_t)src[j];
    if (s >= nv) {
      seen_pad = 1;
      continue;
    }
    if (s < 0) {  // malformed slab: force the caller's numpy fallback
      *flags_out = 0;
      return 0;
    }
    if (seen_pad) tail_ok = 0;
    if (s < prev) sorted = 0;
    if (!sorted || !tail_ok) {
      // The caller is guaranteed to decline the plan; don't stream the
      // rest of an O(E) slab computing discarded self-loops (color-class
      // masked plans hit this every phase).
      *flags_out = 0;
      return 0;
    }
    prev = s;
    const double wj = (double)w[j];
    if (wj != 1.0) unit = 0;
    if ((int64_t)dst[j] == s + base) self_loop[s] += wj;
  }
  *flags_out = sorted | (unit << 1) | (tail_ok << 2);
  return 0;
}

extern "C" int cv_plan_scan(int64_t ne, int64_t nv, int64_t base,
                            const void* src, const void* dst, const void* w,
                            int id64, int w64, double* self_loop,
                            int* flags_out) {
  if (id64) {
    if (w64)
      return plan_scan_impl(ne, nv, base, (const int64_t*)src,
                            (const int64_t*)dst, (const double*)w, self_loop,
                            flags_out);
    return plan_scan_impl(ne, nv, base, (const int64_t*)src,
                          (const int64_t*)dst, (const float*)w, self_loop,
                          flags_out);
  }
  if (w64)
    return plan_scan_impl(ne, nv, base, (const int32_t*)src,
                          (const int32_t*)dst, (const double*)w, self_loop,
                          flags_out);
  return plan_scan_impl(ne, nv, base, (const int32_t*)src,
                        (const int32_t*)dst, (const float*)w, self_loop,
                        flags_out);
}

// cls codes: kept-class index, 254 = heavy, 255 = no bucket (degree 0).
// Caller pre-fills verts with nv (padding), zero-fills dmat/wmat, and
// pre-pads the heavy arrays; this routine writes only real entries.
// Requires the slab CSR-sorted with padding at the tail (cv_plan_scan
// flags); returns -1 on a counter overrun (corrupt cls/deg inputs).
template <typename I, typename W, typename WM>
static int bucket_fill_impl(int64_t nv, int64_t base, const I* dst,
                            const W* w, const int64_t* row_start,
                            const int64_t* deg, const uint8_t* cls,
                            int nclasses, const int64_t* widths,
                            const int64_t* nb_pad, int64_t** verts_ptrs,
                            I** dmat_ptrs, WM** wmat_ptrs, int unit,
                            int64_t heavy_pad, I* hsrc, I* hdst, W* hw) {
  std::vector<int64_t> counter(nclasses, 0);
  int64_t hk = 0;
  for (int64_t v = 0; v < nv; ++v) {
    const uint8_t c = cls[v];
    if (c == 255) continue;
    const int64_t rs = row_start[v];
    const int64_t d = deg[v];
    if (c == 254) {
      if (hk + d > heavy_pad) return -1;
      for (int64_t k = 0; k < d; ++k) {
        hsrc[hk] = (I)v;
        hdst[hk] = dst[rs + k];
        hw[hk] = w[rs + k];
        ++hk;
      }
      continue;
    }
    if (c >= nclasses) return -1;
    const int64_t width = widths[c];
    const int64_t row = counter[c]++;
    if (row >= nb_pad[c]) return -1;
    verts_ptrs[c][row] = v;
    I* drow = dmat_ptrs[c] + row * width;
    WM* wrow = wmat_ptrs[c] + row * width;
    for (int64_t k = 0; k < d; ++k) {
      drow[k] = dst[rs + k];
      wrow[k] = unit ? (WM)1 : (WM)w[rs + k];
    }
    const I self_id = (I)(v + base);
    for (int64_t k = d; k < width; ++k) drow[k] = self_id;
  }
  return 0;
}

extern "C" int cv_bucket_fill(
    int64_t nv, int64_t base, const void* dst, const void* w, int id64,
    int w64, const int64_t* row_start, const int64_t* deg,
    const uint8_t* cls, int nclasses, const int64_t* widths,
    const int64_t* nb_pad, void** verts_ptrs, void** dmat_ptrs,
    void** wmat_ptrs, int unit, int64_t heavy_pad, void* hsrc, void* hdst,
    void* hw) {
  // unit=1 writes uint8 {0,1} masks; otherwise wmat shares w's dtype.
#define CV_FILL(I_, W_, WM_)                                                  \
  bucket_fill_impl<I_, W_, WM_>(                                              \
      nv, base, (const I_*)dst, (const W_*)w, row_start, deg, cls, nclasses, \
      widths, nb_pad, (int64_t**)verts_ptrs, (I_**)dmat_ptrs,                \
      (WM_**)wmat_ptrs, unit, heavy_pad, (I_*)hsrc, (I_*)hdst, (W_*)hw)
  if (id64) {
    if (w64) return unit ? CV_FILL(int64_t, double, uint8_t)
                         : CV_FILL(int64_t, double, double);
    return unit ? CV_FILL(int64_t, float, uint8_t)
                : CV_FILL(int64_t, float, float);
  }
  if (w64) return unit ? CV_FILL(int32_t, double, uint8_t)
                       : CV_FILL(int32_t, double, double);
  return unit ? CV_FILL(int32_t, float, uint8_t)
              : CV_FILL(int32_t, float, float);
#undef CV_FILL
}

extern "C" int cv_openmp_threads(void) {
#if defined(_OPENMP)
  return omp_get_max_threads();
#else
  return 1;
#endif
}
