"""Scale-safe in-loop convergence (VERDICT r2 item 4): the device loop's
`(mod - prev_mod) < threshold` decision must run on double-single
accumulation above DS_MIN_TOTAL_WEIGHT, where plain f32 reductions lose
more than the threshold."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cuvite_tpu.comm.mesh import shard_map
from cuvite_tpu.ops import segment as seg
from cuvite_tpu.ops.exactsum import ds_psum, ds_tree_sum


def _adversarial_counter0(k: int = 64) -> np.ndarray:
    """[2^25, 1, 1, ... (k ones), 0-pad to 128]: XLA:CPU's f32 reduction of
    this array loses 16.0 absolute (measured, deterministic for the pinned
    jaxlib) while the f64 total is exact — the small-magnitude mass a big
    leading term absorbs, the miniature of the scale-28 failure mode."""
    a = np.zeros(128, dtype=np.float32)
    a[0] = 2.0 ** 25
    a[1:1 + k] = 1.0
    return a


def test_ds_modularity_terms_matches_f64_where_f32_loses():
    c0 = _adversarial_counter0()
    exact = float(np.sum(c0.astype(np.float64)))  # 2^25 + 64, f32-exact
    cd = np.zeros(4, dtype=np.float32)
    const = jnp.float32(1.0)

    def run(accum):
        f = jax.jit(lambda x, d: seg.modularity_terms(
            x, d, const, lambda v: v, accum))
        return float(f(jnp.asarray(c0), jnp.asarray(cd)))

    q32 = run("float32")
    qds = run(seg.DS_ACCUM)
    assert qds == exact, (qds, exact)
    # Canary: if XLA's f32 reduction ever becomes exact on this input, the
    # adversarial construction (and DS_MIN_TOTAL_WEIGHT) needs revisiting.
    assert q32 != exact, "f32 reduction unexpectedly exact; rebuild the test"


def test_threshold_decision_follows_ds():
    """The miniature of the scale-28 bug: with threshold between the f32 and
    ds modularity gains, the f32 loop stops a phase the ds loop continues —
    the driver must follow ds."""
    from cuvite_tpu.louvain.driver import _run_phase_loop

    c0 = jnp.asarray(_adversarial_counter0())
    cd = jnp.zeros(4, dtype=jnp.float32)
    const = jnp.float32(1.0)
    exact = float(np.sum(np.asarray(c0).astype(np.float64)))

    def make_call(accum):
        def call(comm, extra):
            mod = seg.modularity_terms(c0, cd, const, lambda v: v, accum)
            return comm, mod, jnp.int32(0), jnp.zeros((), bool)

        return call

    q32 = float(jax.jit(lambda: make_call("float32")(
        jnp.zeros(4, jnp.int32), ())[1])())
    assert q32 < exact
    # threshold strictly between the two gains over `lower`
    lower = np.float32(exact - 32.0)
    th = np.float32(exact - q32)  # ds gain = 32 >= th > f32 gain

    def iters(accum):
        _, _, it, _, _conv = _run_phase_loop(
            (), jnp.zeros(4, jnp.int32), th, lower,
            call=make_call(accum), max_iters=5)
        return int(it)

    assert iters("float32") == 1   # f32 sees no gain, stops immediately
    assert iters(seg.DS_ACCUM) == 2  # ds sees the real gain, iterates on


def test_ds_psum_exact_across_shards():
    """Cross-shard pair reduction must not re-lose the low words."""
    from jax.sharding import Mesh, PartitionSpec as P

    devs = jax.devices("cpu")[:8]
    mesh = Mesh(np.array(devs), ("x",))
    # per-shard values whose hi parts alone would lose the +1s
    vals = np.tile(np.array([2.0 ** 25, 1.0], np.float32), 4)  # 8 shards

    @jax.jit
    @shard_map(mesh=mesh, in_specs=P("x"), out_specs=P(),
               check_vma=False)
    def f(x):
        pair = ds_tree_sum(x)   # per-shard scalar pair
        hi, lo = ds_psum(pair, "x")
        return hi + lo, hi, lo

    tot, hi, lo = f(jnp.asarray(vals))
    exact = np.sum(vals.astype(np.float64))
    assert float(np.float64(hi) + np.float64(lo)) == float(exact)


@pytest.fixture(scope="module")
def weighted_karate():
    from tests.conftest import karate_edges

    from cuvite_tpu.core.graph import Graph

    nv, s, d = karate_edges()
    w = np.full(len(s), 2.0 ** 18, dtype=np.float64)
    return Graph.from_edges(nv, s, d, weights=w)


def test_runner_selects_ds_above_cutover(weighted_karate):
    from cuvite_tpu.core.distgraph import DistGraph
    from cuvite_tpu.louvain.driver import DS_MIN_TOTAL_WEIGHT, PhaseRunner

    assert weighted_karate.total_edge_weight_twice() >= DS_MIN_TOTAL_WEIGHT
    r = PhaseRunner(DistGraph.build(weighted_karate, 1), engine="bucketed")
    assert r.accum_name == seg.DS_ACCUM


def test_ds_driver_end_to_end(weighted_karate, karate):
    """Q is invariant under uniform weight scaling, so the ds-accum run on
    2^18-weighted karate must reproduce the unweighted golden value — on
    one shard, on a replicated mesh, and on the sparse exchange."""
    from cuvite_tpu.louvain.driver import louvain_phases

    q_ref = louvain_phases(karate).modularity
    for kw in ({}, {"nshards": 4, "exchange": "replicated"},
               {"nshards": 4, "exchange": "sparse"}):
        res = louvain_phases(weighted_karate, **kw)
        assert res.modularity == pytest.approx(q_ref, abs=2e-5), kw
