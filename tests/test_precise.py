"""Double-single accumulation: unit tests for the ds primitives and the
1e-9-class accuracy gate for per-phase modularity (VERDICT round-1 item 4;
analog of the reference's double accumulation, louvain.cpp:2433-2481)."""

import numpy as np
import pytest

import jax.numpy as jnp

from cuvite_tpu.core.distgraph import DistGraph
from cuvite_tpu.evaluate.modularity import modularity as host_mod
from cuvite_tpu.io.generate import generate_rmat
from cuvite_tpu.louvain.driver import louvain_phases
from cuvite_tpu.louvain.precise import phase_modularity
from cuvite_tpu.ops import exactsum as ds


def test_ds_tree_sum_beats_f32():
    """Adversarial mix of magnitudes: ds must track the f64 oracle far
    beyond f32's 2^-24."""
    rng = np.random.default_rng(0)
    x = np.concatenate([
        rng.uniform(1e6, 1e7, 4096),
        rng.uniform(1e-3, 1e-2, 4096),
    ]).astype(np.float32)
    rng.shuffle(x)
    hi, lo = ds.ds_tree_sum(jnp.asarray(x))
    got = ds.ds_to_f64((hi, lo))
    want = float(np.sum(x.astype(np.float64)))
    f32 = float(np.sum(x))
    assert abs(got - want) <= 1e-9 * abs(want)
    assert abs(got - want) < abs(f32 - want)  # strictly better than f32


def test_ds_mul_exactness():
    a, b = np.float32(16777217.0 / 16.0), np.float32(3.0000001)
    hi, lo = ds.ds_mul(ds.ds_from_f32(jnp.float32(a)),
                       ds.ds_from_f32(jnp.float32(b)))
    want = float(np.float64(a) * np.float64(b))
    assert abs(ds.ds_to_f64((hi, lo)) - want) <= 1e-14 * abs(want)


def test_ds_segment_sums_sorted_matches_f64():
    rng = np.random.default_rng(1)
    keys = np.sort(rng.integers(0, 50, 8192)).astype(np.int32)
    vals = rng.uniform(1e-3, 1e5, 8192).astype(np.float32)
    run_hi, run_lo, last = ds.ds_segment_sums_sorted(
        jnp.asarray(keys), jnp.asarray(vals))
    run_hi, run_lo, last = map(np.asarray, (run_hi, run_lo, last))
    want = np.zeros(50)
    np.add.at(want, keys, vals.astype(np.float64))
    got = {}
    for i in np.nonzero(last)[0]:
        got[keys[i]] = np.float64(run_hi[i]) + np.float64(run_lo[i])
    for k, w in got.items():
        assert abs(w - want[k]) <= 1e-9 * max(abs(want[k]), 1.0)


@pytest.mark.parametrize(
    "scale", [16, pytest.param(20, marks=pytest.mark.slow)])
def test_phase_modularity_matches_f64_oracle(scale):
    """Device ds modularity vs host f64 oracle within 1e-9*|Q| — scale-20
    R-MAT with f32 (unit) weights is the VERDICT acceptance case."""
    g = generate_rmat(scale, edge_factor=16, seed=1)
    dg = DistGraph.build(g, 1)
    # Non-trivial synthetic assignment with big skewed communities: maps
    # every vertex to one of ~1000 communities (padded space).
    rng = np.random.default_rng(2)
    labels = rng.integers(0, 1000, g.num_vertices)
    comm_pad = np.arange(dg.total_padded_vertices, dtype=np.int64)
    comm_pad[dg.old_to_pad] = dg.old_to_pad[labels]
    got = phase_modularity(dg, comm_pad)
    want = host_mod(g, labels)
    assert abs(got - want) <= 1e-9 * abs(want), (got, want)


def test_reported_modularity_is_precise_end_to_end():
    g = generate_rmat(13, edge_factor=8, seed=3)
    res = louvain_phases(g, engine="bucketed")
    want = host_mod(g, res.communities)
    assert abs(res.modularity - want) <= 1e-9 * abs(want)


def test_multishard_reported_modularity_is_precise():
    g = generate_rmat(11, edge_factor=8, seed=4)
    res = louvain_phases(g, nshards=4)
    want = host_mod(g, res.communities)
    assert abs(res.modularity - want) <= 1e-9 * abs(want)
