"""Device-resident inter-phase coarsening (cuvite_tpu/coarsen/device.py).

``coarsen/rebuild.py`` is the bit-parity oracle: the device renumber must
reproduce np.unique's sorted-order dense ids (rebuild.cpp:167-197), and
the device relabel+coalesce must reproduce the host CSR coalesce
(offsets, tails, weights) bit-for-bit wherever the run sums are exactly
representable — unit and dyadic weights here, which is the documented
exactness domain (the host accumulates f64 and casts once; the device
accumulates in the weight dtype, or ds32 pairs in the scale-safe mode).

The transfer/compile guards pin the tentpole property: a phase
transition within the same pow2 slab class performs zero host transfers
of O(E) arrays and zero fresh XLA compiles from phase 2 on.
"""

import contextlib
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuvite_tpu.coarsen.device import (
    device_coarsen_slab,
    device_renumber,
    shrink_slab,
)
from cuvite_tpu.coarsen.rebuild import coarsen_graph, renumber_communities
from cuvite_tpu.core.distgraph import DistGraph
from cuvite_tpu.core.graph import Graph
from cuvite_tpu.core.types import Policy, wide_policy
from cuvite_tpu.io.generate import generate_rmat
from cuvite_tpu.louvain.driver import louvain_phases
from cuvite_tpu.utils.trace import Tracer


@pytest.fixture(scope="module")
def rmat10():
    g = generate_rmat(10, edge_factor=8, seed=3)
    # Precondition for the class-stability tests below: the whole run fits
    # the floor class (nv_pad 4096 / ne_pad 16384), so EVERY phase shares
    # one compiled-step cache entry.
    assert g.num_vertices <= 4096 and g.num_edges <= 16384
    return g


def _device_coarse(graph, labels_pad, accum=None):
    """Run the device pipeline on graph's single-shard slab and return the
    coarse CSR (offsets, tails, weights), nc, and the dense map."""
    dg = DistGraph.build(graph, 1)
    sh = dg.shards[0]
    src = jnp.asarray(np.asarray(sh.src))
    dst = jnp.asarray(np.asarray(sh.dst))
    w = jnp.asarray(np.asarray(sh.w))
    comm = jnp.asarray(np.asarray(labels_pad).astype(np.asarray(src).dtype))
    mask = jnp.asarray(dg.vertex_mask())
    out = device_coarsen_slab(src, dst, w, comm, mask, nv_pad=dg.nv_pad,
                              accum_dtype=accum)
    src2, dst2, w2, dmap, nc, ne2 = jax.device_get(out)
    nc, ne2 = int(nc), int(ne2)
    offsets = np.zeros(nc + 1, dtype=np.int64)
    np.cumsum(np.bincount(src2[:ne2], minlength=nc), out=offsets[1:])
    # Padding contract: everything past ne2 is sentinel/zero.
    assert (src2[ne2:] == dg.nv_pad).all()
    assert (w2[ne2:] == 0).all()
    return offsets, dst2[:ne2], w2[:ne2], nc, dmap, dg


def _host_coarse(graph, labels_pad):
    dg = DistGraph.build(graph, 1)
    comm_old = np.asarray(labels_pad)[dg.old_to_pad]
    dense, nc = renumber_communities(comm_old)
    gh = coarsen_graph(graph, dense, nc)
    return gh, dense, nc


def _random_padded_labels(graph, nv_pad, rng, gapped=False):
    """A labeling in padded space: every real vertex points at some real
    vertex id.  ``gapped``: only a sparse subset of ids survive, leaving
    large gaps in the label space (the renumber's hard case)."""
    nv = graph.num_vertices
    if gapped:
        pool = rng.choice(nv, size=max(nv // 13, 2), replace=False)
    else:
        pool = np.arange(nv)
    lab = np.full(nv_pad, nv_pad - 1, dtype=np.int64)
    lab[:nv] = rng.choice(pool, size=nv)
    return lab


@pytest.mark.parametrize("gapped", [False, True],
                         ids=["dense-ish", "gapped-labels"])
@pytest.mark.parametrize("accum", [None, "ds32"])
def test_device_matches_host_bitwise_unit_weights(rmat10, gapped, accum):
    dg = DistGraph.build(rmat10, 1)
    rng = np.random.default_rng(7)
    lab = _random_padded_labels(rmat10, dg.nv_pad, rng, gapped=gapped)
    off_d, tails_d, w_d, nc_d, dmap, _ = _device_coarse(
        rmat10, lab, accum=accum)
    gh, dense, nc_h = _host_coarse(rmat10, lab)
    assert nc_d == nc_h
    assert np.array_equal(off_d, gh.offsets)
    assert np.array_equal(tails_d, gh.tails)
    # Unit weights: every run sum is an exact small integer in f32 — the
    # host's f64-accumulate-then-cast is bit-identical.
    assert np.array_equal(w_d, gh.weights)
    # The device dense map agrees with np.unique's sorted-order ids.
    comm_old = lab[dg.old_to_pad]
    assert np.array_equal(np.asarray(dmap)[comm_old], dense)


def test_device_renumber_matches_np_unique_on_gaps(rmat10):
    dg = DistGraph.build(rmat10, 1)
    rng = np.random.default_rng(11)
    lab = _random_padded_labels(rmat10, dg.nv_pad, rng, gapped=True)
    dmap, nc = jax.device_get(device_renumber(
        jnp.asarray(lab.astype(np.int32)), jnp.asarray(dg.vertex_mask()),
        nv_pad=dg.nv_pad))
    dense, nc_h = renumber_communities(lab[dg.old_to_pad])
    assert int(nc) == nc_h
    assert np.array_equal(dmap[lab[dg.old_to_pad]], dense)


def test_self_loop_accumulation_collapses_cliques(two_cliques):
    """Both K5 cliques collapse to single vertices: ALL intra-community
    weight must land on the diagonal (rebuild.cpp:244-279), and the
    bridge edge survives off-diagonal — compared bit-wise vs the host."""
    dg = DistGraph.build(two_cliques, 1)
    lab = np.arange(dg.nv_pad, dtype=np.int64)
    lab[:5] = 0
    lab[5:10] = 5
    off_d, tails_d, w_d, nc_d, _, _ = _device_coarse(two_cliques, lab)
    gh, _, nc_h = _host_coarse(two_cliques, lab)
    assert nc_d == nc_h == 2
    assert np.array_equal(off_d, gh.offsets)
    assert np.array_equal(tails_d, gh.tails)
    assert np.array_equal(w_d, gh.weights)
    # Diagonal of community 0 = both directions of the 10 K5 edges.
    sl = w_d[(np.repeat(np.arange(2), np.diff(off_d)) == 0) & (tails_d == 0)]
    assert sl.sum() == 20.0


@pytest.mark.parametrize("accum", [None, "ds32"])
def test_dyadic_f32_weights_bitwise(accum):
    """Non-unit weights: dyadic values (multiples of 1/8) keep every run
    sum exact in f32, so device == host remains BIT equality, in both
    accumulation modes."""
    rng = np.random.default_rng(3)
    nv = 96
    src = rng.integers(0, nv, 600)
    dst = rng.integers(0, nv, 600)
    w = rng.integers(1, 64, 600).astype(np.float64) / 8.0
    g = Graph.from_edges(nv, src, dst, weights=w)
    dgp = DistGraph.build(g, 1)
    lab = _random_padded_labels(g, dgp.nv_pad, rng)
    off_d, tails_d, w_d, nc_d, _, _ = _device_coarse(g, lab, accum=accum)
    gh, _, nc_h = _host_coarse(g, lab)
    assert nc_d == nc_h
    assert np.array_equal(off_d, gh.offsets)
    assert np.array_equal(tails_d, gh.tails)
    assert np.array_equal(w_d, gh.weights)


def test_wide_policy_weights_match_after_device_cast():
    """bits64 graphs: the device clamps to f32/int32 (no x64 here), so the
    host f64 oracle is compared after one lossless cast (dyadic weights,
    bounded sums) — value equality at the device dtype."""
    rng = np.random.default_rng(5)
    nv = 64
    src = rng.integers(0, nv, 400)
    dst = rng.integers(0, nv, 400)
    w = rng.integers(1, 16, 400).astype(np.float64) / 4.0
    g = Graph.from_edges(nv, src, dst, weights=w, policy=wide_policy())
    assert g.weights.dtype == np.float64
    dgp = DistGraph.build(g, 1)
    lab = _random_padded_labels(g, dgp.nv_pad, rng, gapped=True)
    off_d, tails_d, w_d, nc_d, _, _ = _device_coarse(g, lab)
    gh, _, nc_h = _host_coarse(g, lab)
    assert nc_d == nc_h
    assert np.array_equal(off_d, gh.offsets)
    assert np.array_equal(tails_d, np.asarray(gh.tails).astype(tails_d.dtype))
    assert np.array_equal(w_d, np.asarray(gh.weights).astype(np.float32))


def test_shrink_slab_prefix_and_sentinel():
    src = jnp.asarray(np.array([0, 1, 2, 64, 64, 64, 64, 64], np.int32))
    dst = jnp.asarray(np.array([1, 2, 0, 0, 0, 0, 0, 0], np.int32))
    w = jnp.asarray(np.ones(8, np.float32))
    s, d, ww = shrink_slab(src, dst, w, new_nv_pad=4, new_ne_pad=4)
    assert s.shape == d.shape == ww.shape == (4,)
    # Real ids survive; old sentinels (64) rewrite to the new class's.
    assert np.array_equal(np.asarray(s), [0, 1, 2, 4])


# ---------------------------------------------------------------------------
# End-to-end: device transition == host transition, and the guards


def test_sort_engine_device_vs_host_full_run(rmat10, monkeypatch):
    monkeypatch.setenv("CUVITE_DEVICE_COARSEN", "0")
    r0 = louvain_phases(rmat10, engine="sort")
    monkeypatch.delenv("CUVITE_DEVICE_COARSEN")
    r1 = louvain_phases(rmat10, engine="sort")
    assert len(r0.phases) == len(r1.phases) >= 3
    assert r0.total_iterations == r1.total_iterations
    assert r0.modularity == r1.modularity  # both use the device ds pass
    assert np.array_equal(r0.communities, r1.communities)


def test_fused_device_vs_host_full_run(rmat10, monkeypatch):
    import cuvite_tpu.louvain.driver as drv

    # Force the multilevel (one-call-per-phase) path on this small graph.
    monkeypatch.setattr(drv, "FUSED_SHRINK_EDGES", 1 << 10)
    monkeypatch.setenv("CUVITE_DEVICE_COARSEN", "0")
    r0 = louvain_phases(rmat10, engine="fused", threshold_cycling=True)
    monkeypatch.delenv("CUVITE_DEVICE_COARSEN")
    r1 = louvain_phases(rmat10, engine="fused", threshold_cycling=True)
    assert len(r0.phases) == len(r1.phases) >= 3
    assert r0.total_iterations == r1.total_iterations
    assert np.array_equal(r0.communities, r1.communities)
    # Final Q: device ds pass vs host f64 oracle — f64-class agreement.
    assert r1.modularity == pytest.approx(r0.modularity, abs=1e-12)


def _no_big_fetch_guard(monkeypatch, cap):
    """Reject any device->host fetch above ``cap`` elements: O(V)=nv_pad
    stays legal, an O(E)=ne_pad slab pull trips.  BOTH spellings are
    guarded — ``jax.device_get(x)`` and the ``np.asarray(x)`` route
    (jax.Array.__array__ does not go through device_get), so a regression
    that re-materializes the slab via numpy is caught too."""
    orig = jax.device_get

    def guarded(x):
        for leaf in jax.tree_util.tree_leaves(x):
            size = int(getattr(leaf, "size", 0) or 0)
            assert size <= cap, \
                f"O(E)-sized device->host fetch ({size} > {cap} elements)"
        return orig(x)

    monkeypatch.setattr(jax, "device_get", guarded)
    from jax._src import array as _jarray

    orig_arr = _jarray.ArrayImpl.__array__

    def guarded_arr(self, *a, **k):
        assert int(self.size) <= cap, \
            f"O(E)-sized np.asarray of a device array ({int(self.size)} " \
            f"> {cap} elements)"
        return orig_arr(self, *a, **k)

    monkeypatch.setattr(_jarray.ArrayImpl, "__array__", guarded_arr)


def test_sort_engine_transition_zero_host_rebuild(rmat10, monkeypatch):
    """The tentpole transfer guard: across a multi-phase sort-engine run,
    the host builds the DistGraph ONCE (phase 0), never runs the host
    coarsener, and never fetches an O(E) array from the device."""
    import cuvite_tpu.louvain.driver as drv

    builds = []
    orig_build = DistGraph.build

    def counting_build(*a, **k):
        builds.append(1)
        return orig_build(*a, **k)

    monkeypatch.setattr(DistGraph, "build", staticmethod(counting_build))

    def boom(*a, **k):
        raise AssertionError("host coarsen_graph on the device path")

    monkeypatch.setattr(drv, "coarsen_graph", boom)
    _no_big_fetch_guard(monkeypatch, cap=4096)  # nv_pad; ne_pad is 16384
    res = louvain_phases(rmat10, engine="sort")
    assert len(builds) == 1
    assert len(res.phases) >= 3
    assert res.modularity > 0


def test_fused_transition_zero_host_rebuild(rmat10, monkeypatch):
    import cuvite_tpu.louvain.driver as drv

    monkeypatch.setattr(drv, "FUSED_SHRINK_EDGES", 1 << 10)
    builds = []
    orig_build = DistGraph.build

    def counting_build(*a, **k):
        builds.append(1)
        return orig_build(*a, **k)

    monkeypatch.setattr(DistGraph, "build", staticmethod(counting_build))

    def boom(*a, **k):
        raise AssertionError("host coarsen_graph on the device path")

    monkeypatch.setattr(drv, "coarsen_graph", boom)
    _no_big_fetch_guard(monkeypatch, cap=4096)
    res = louvain_phases(rmat10, engine="fused")
    assert len(builds) == 1
    assert len(res.phases) >= 3
    assert res.modularity > 0


class _PhaseCompileProbe(Tracer):
    """Tracer that snapshots the compile-log length at every iterate-stage
    ENTRY, so the test can pin which phase triggered which compiles."""

    def __init__(self, compile_log):
        super().__init__(enabled=True)
        self._log = compile_log
        self.marks = []

    @contextlib.contextmanager
    def stage(self, name):
        if name == "iterate":
            self.marks.append(len(self._log))
        with super().stage(name):
            yield


@pytest.mark.parametrize("engine", ["sort", "fused"])
def test_three_phase_run_zero_fresh_compiles_after_phase1(
        rmat10, engine, monkeypatch):
    """Same pow2 class across every phase (floors 4096/16384) => the
    compiled-step cache must serve phases 2+ entirely: all XLA compiles
    happen in phases 0-1 (step + coarsen pipelines), none after."""
    import logging

    import cuvite_tpu.louvain.driver as drv

    if engine == "fused":
        # Force the one-call-per-phase multilevel path (the small-graph
        # default runs everything in ONE call — nothing to probe).
        monkeypatch.setattr(drv, "FUSED_SHRINK_EDGES", 1 << 10)
    compiles = []

    class _Grab(logging.Handler):
        def emit(self, record):
            if "Compiling" in record.getMessage():
                compiles.append(record.getMessage())

    probe = _PhaseCompileProbe(compiles)
    handler = _Grab(level=logging.WARNING)
    logger = logging.getLogger("jax")
    logger.addHandler(handler)
    jax.config.update("jax_log_compiles", True)
    try:
        res = louvain_phases(rmat10, engine=engine, tracer=probe)
    finally:
        jax.config.update("jax_log_compiles", False)
        logger.removeHandler(handler)
    n_calls = len(probe.marks)
    assert len(res.phases) >= 3 and n_calls >= 3
    fresh_after_phase1 = len(compiles) - probe.marks[2]
    assert fresh_after_phase1 == 0, (
        f"phase 2+ recompiled {fresh_after_phase1}x in the same slab "
        f"class: {compiles[probe.marks[2]:][:4]}")


def test_from_device_slab_metadata(rmat10):
    dg = DistGraph.build(rmat10, 1)
    sh = dg.shards[0]
    src = jnp.asarray(np.asarray(sh.src))
    dst = jnp.asarray(np.asarray(sh.dst))
    w = jnp.asarray(np.asarray(sh.w))
    ddg = DistGraph.from_device_slab(
        src, dst, w, num_vertices=rmat10.num_vertices,
        num_edges=rmat10.num_edges, nv_pad=dg.nv_pad, ne_pad=dg.ne_pad,
        policy=Policy(), total_weight_twice=rmat10.total_edge_weight_twice())
    assert ddg.device_resident and ddg.nshards == 1
    assert ddg.graph.num_vertices == rmat10.num_vertices
    assert ddg.graph.total_edge_weight_twice() \
        == rmat10.total_edge_weight_twice()
    # stacked_edges hands the jax arrays back without a host round-trip.
    s2, d2, w2 = ddg.stacked_edges()
    assert s2 is src and d2 is dst and w2 is w
    # padded degrees come from a device segment sum and match the host's.
    vdeg_dev = np.asarray(ddg.padded_weighted_degrees())
    vdeg_host = dg.padded_weighted_degrees()
    assert np.array_equal(vdeg_dev, vdeg_host)
    assert np.array_equal(ddg.vertex_mask(), dg.vertex_mask())
