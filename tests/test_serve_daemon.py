"""Async daemon tests (ISSUE 11): socket intake, result routing,
stats polling, rejection/shed notifications, graceful drain — and the
subprocess SIGTERM acceptance check (daemon exits 0 with a clean drain
and a serve_summary under an injected fault plan).

In-process daemons run a STUB runner over a unix socket (jax never
dispatches), so the protocol/threading machinery is tested in
milliseconds; the one subprocess test exercises the real CLI + signal
path end to end on tiny graphs.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import types

import numpy as np
import pytest

from cuvite_tpu.serve import (
    AdmissionConfig,
    FaultPlan,
    LouvainServer,
    ServeConfig,
    ServeDaemon,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def stub_runner(graphs, **kw):
    results = []
    for g in graphs:
        nv = g.num_vertices
        key = int(np.sum(g.tails)) % 997
        results.append(types.SimpleNamespace(
            communities=(np.arange(nv) + key) % max(nv, 1),
            modularity=key / 997.0, phases=[1], total_iterations=3,
            num_communities=nv))
    return types.SimpleNamespace(results=results, n_phases=1)


class DaemonClient:
    """Minimal line-protocol client for the tests."""

    def __init__(self, sock_path):
        self.conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.conn.connect(sock_path)
        self.conn.settimeout(30.0)
        self.lines = self.conn.makefile("r", encoding="utf-8")
        self.pending: list = []

    def send(self, req: dict) -> None:
        self.conn.sendall((json.dumps(req) + "\n").encode())

    def _raw(self) -> dict:
        line = self.lines.readline()
        assert line, "daemon closed the connection unexpectedly"
        return json.loads(line)

    def recv(self) -> dict:
        """Next ASYNC message (result/failed/shed/summary); request
        replies interleave on the same stream and are buffered by
        call()."""
        if self.pending:
            return self.pending.pop(0)
        return self._raw()

    def call(self, req: dict) -> dict:
        """Send a request and return ITS reply (an 'ok'-keyed line),
        buffering any async result lines that arrive first."""
        self.send(req)
        while True:
            msg = self._raw()
            if "ok" in msg:
                return msg
            self.pending.append(msg)

    def close(self) -> None:
        try:
            self.conn.close()
        except OSError:
            pass


def graph_req(seed: int, nv: int = 12, ne: int = 24, **extra) -> dict:
    rng = np.random.default_rng(seed)
    return dict({"op": "submit", "graph": {
        "nv": nv,
        "src": [int(x) for x in rng.integers(0, nv, ne)],
        "dst": [int(x) for x in rng.integers(0, nv, ne)],
        "w": None}}, **extra)


@pytest.fixture
def daemon(tmp_path):
    srv = LouvainServer(
        ServeConfig(b_max=2, linger_s=0.01, engine="fused"),
        runner=stub_runner)
    d = ServeDaemon(srv, sock_path=str(tmp_path / "serve.sock"),
                    poll_s=0.005)
    d.start()
    yield d
    if not d._done.is_set():
        d.request_drain()
        d.serve_forever(timeout=30.0)


def test_daemon_submit_and_result_roundtrip(daemon, tmp_path):
    c = DaemonClient(str(tmp_path / "serve.sock"))
    try:
        ack = c.call(graph_req(1, labels=True))
        assert ack["ok"] and ack["job_id"]
        ack2 = c.call(graph_req(2))
        assert ack2["ok"]
        got = {}
        for _ in range(2):
            msg = c.recv()
            assert "result" in msg, msg
            got[msg["result"]["job_id"]] = msg["result"]
        assert set(got) == {ack["job_id"], ack2["job_id"]}
        # labels only where asked for
        assert "labels" in got[ack["job_id"]]
        assert "labels" not in got[ack2["job_id"]]
        assert len(got[ack["job_id"]]["labels"]) == 12
        # stats poll from the reader thread while the dispatcher lives
        st = c.call({"op": "stats"})
        assert st["ok"] and st["stats"]["jobs_done"] == 2
        assert st["conservation"]["ok"]
    finally:
        c.close()


def test_daemon_bad_requests_answered_not_fatal(daemon, tmp_path):
    c = DaemonClient(str(tmp_path / "serve.sock"))
    try:
        assert not c.call({"op": "explode"})["ok"]
        assert not c.call({"op": "submit"})["ok"]       # no graph spec
        c.conn.sendall(b"this is not json\n")
        assert "bad json" in c.recv()["error"]
        # the server-generated id namespace is reserved (a client
        # squatting on 'job-N' would collide with a future auto id and
        # overwrite its route)
        r = c.call(dict(graph_req(9), id="job-7"))
        assert not r["ok"] and "reserved" in r["error"]
        ack = c.call(graph_req(3))                      # still serving
        assert ack["ok"]
        assert "result" in c.recv()
    finally:
        c.close()


def test_daemon_line_cap_drops_flooder(tmp_path):
    """A newline-free byte flood must drop THAT connection (error +
    close), not grow the read buffer until the daemon OOMs."""
    srv = LouvainServer(ServeConfig(b_max=2, linger_s=0.01,
                                    engine="fused"), runner=stub_runner)
    d = ServeDaemon(srv, sock_path=str(tmp_path / "f.sock"),
                    poll_s=0.005, max_line_bytes=1024)
    d.start()
    c = DaemonClient(str(tmp_path / "f.sock"))
    try:
        c.conn.sendall(b"x" * 5000)          # no newline, over the cap
        line = c.lines.readline()
        assert "exceeds" in json.loads(line)["error"]
        assert c.lines.readline() == ""       # connection closed
        # the daemon itself is unharmed: a new client still serves
        c2 = DaemonClient(str(tmp_path / "f.sock"))
        try:
            assert c2.call(graph_req(5))["ok"]
            assert "result" in c2.recv()
        finally:
            c2.close()
    finally:
        c.close()
        d.request_drain()
        d.serve_forever(timeout=30.0)


def test_daemon_rejection_and_shed_notifications(tmp_path):
    srv = LouvainServer(
        ServeConfig(b_max=2, linger_s=10.0, engine="fused",
                    admission=AdmissionConfig(wait_slo_s=0.01)),
        runner=stub_runner)
    d = ServeDaemon(srv, sock_path=str(tmp_path / "s.sock"), poll_s=0.005)
    # Pre-seed a fat service-time estimate so the projection rejects
    # as soon as anything queues.
    d.start()
    c = DaemonClient(str(tmp_path / "s.sock"))
    try:
        ack = c.call(graph_req(1))
        assert ack["ok"]
        # Force a rejection decision (queue-level projection arithmetic
        # is pinned in test_serve_robust; here the target is the wire
        # mapping): decide() returning a retry_after_s rejects.
        orig_decide = srv.admission.decide
        with d.lock:
            srv.admission.decide = lambda *a, **kw: 0.8
        rej = c.call(graph_req(2))
        assert rej["ok"] is False and rej["rejected"] is True
        assert rej["retry_after_s"] == pytest.approx(0.8)
        with d.lock:   # back to normal so the next submit admits
            srv.admission.decide = orig_decide
        # a job with an already-hopeless deadline sheds, with a notice
        ack3 = c.call(dict(graph_req(3), deadline_s=-0.001))
        assert ack3["ok"]
        msgs = [c.recv() for _ in range(2)]
        kinds = {next(iter(m)) for m in msgs}
        assert kinds == {"result", "shed"}
    finally:
        c.close()
        d.request_drain()
        d.serve_forever(timeout=30.0)


def test_daemon_graceful_drain_summary(daemon, tmp_path):
    c = DaemonClient(str(tmp_path / "serve.sock"))
    try:
        acks = [c.call(graph_req(10 + s)) for s in range(5)]
        assert all(a["ok"] for a in acks)
        r = c.call({"op": "drain"})
        assert r["ok"] and r["draining"]
        msgs = []
        while True:
            msg = c.recv()
            msgs.append(msg)
            if "serve_summary" in msg:
                break
        summary = msgs[-1]["serve_summary"]
        results = [m for m in msgs if "result" in m]
        assert len(results) == 5, msgs
        assert summary["jobs_done"] == 5
        assert summary["conservation"]["ok"]
        # post-drain submits are refused
        final = daemon.serve_forever(timeout=30.0)
        assert final["jobs_done"] == 5
    finally:
        c.close()


def test_daemon_refuses_submit_while_draining(daemon, tmp_path):
    c = DaemonClient(str(tmp_path / "serve.sock"))
    try:
        daemon.request_drain()
        daemon.serve_forever(timeout=30.0)
        # The daemon has fully drained; a late submit on a still-open
        # connection gets the draining refusal (connection may also be
        # closed already — both are clean outcomes).
        try:
            resp = c.call(graph_req(99))
        except (AssertionError, OSError):
            return
        assert resp["ok"] is False and resp.get("draining")
    finally:
        c.close()


# ---------------------------------------------------------------------------
# THE subprocess acceptance check: real CLI, real signal, real jax —
# SIGTERM mid-stream must drain cleanly and exit 0, fault plan active.


def test_daemon_sigterm_clean_drain_subprocess(tmp_path):
    sock = str(tmp_path / "d.sock")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CUVITE_FAULT_PLAN="device:transient:n=1")
    proc = subprocess.Popen(
        [sys.executable, "-m", "cuvite_tpu.serve", "daemon",
         "--socket", sock, "--b-max", "2", "--linger-ms", "5",
         "--host-devices", "1", "--max-retries", "2",
         "--retry-base-ms", "1"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO, env=env)
    try:
        ready = json.loads(proc.stdout.readline())
        assert ready["ready"]["socket"] == sock
        assert ready["ready"]["fault_plan"] == "device:transient:n=1"
        c = DaemonClient(sock)
        try:
            acks = [c.call({"op": "submit",
                            "synth": {"edges": 256, "seed": 40 + s},
                            "tenant": f"t{s % 2}"})
                    for s in range(4)]
            assert all(a["ok"] for a in acks), acks
            # SIGTERM with jobs possibly still queued/running: the
            # daemon must drain them and exit 0.
            proc.send_signal(signal.SIGTERM)
            seen = []
            while True:
                msg = c.recv()
                seen.append(msg)
                if "serve_summary" in msg:
                    break
            summary = msg["serve_summary"]
        finally:
            c.close()
        rc = proc.wait(timeout=120)
        assert rc == 0, proc.stderr.read()[-2000:]
        assert summary["jobs_done"] == 4
        assert summary["jobs_failed"] == 0
        assert summary["retries"] >= 1, \
            "the injected transient fault should have retried"
        assert summary["conservation"]["ok"]
        results = [m for m in seen if "result" in m]
        assert len(results) == 4
        # The CLI prints the same summary as its last stdout line.
        out_lines = proc.stdout.read().strip().splitlines()
        assert json.loads(out_lines[-1])["serve_summary"]["jobs_done"] == 4
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)


def test_daemon_config_errors_exit_2():
    out = subprocess.run(
        [sys.executable, "-m", "cuvite_tpu.serve", "daemon",
         "--socket", "/tmp/x.sock", "--port", "7",
         "--host-devices", "1"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert out.returncode == 2
    out = subprocess.run(
        [sys.executable, "-m", "cuvite_tpu.serve", "daemon",
         "--host-devices", "1"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert out.returncode == 2
    out = subprocess.run(
        [sys.executable, "-m", "cuvite_tpu.serve", "daemon",
         "--socket", "/tmp/x.sock", "--fault-plan", "bogus:nope",
         "--host-devices", "1"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert out.returncode == 2
    assert "fault directive" in out.stderr


def test_daemon_wait_helpers(tmp_path):
    """serve_forever times out rather than hanging when no drain was
    requested; a second start() is not required for the drain path."""
    srv = LouvainServer(ServeConfig(b_max=2, linger_s=0.01,
                                    engine="fused"), runner=stub_runner)
    d = ServeDaemon(srv, sock_path=str(tmp_path / "w.sock"), poll_s=0.005)
    d.start()
    with pytest.raises(TimeoutError):
        d.serve_forever(timeout=0.2)
    t0 = time.perf_counter()
    d.request_drain()
    summary = d.serve_forever(timeout=30.0)
    assert summary["jobs_done"] == 0
    assert time.perf_counter() - t0 < 30.0
    with pytest.raises(ValueError):
        ServeDaemon(srv)            # neither socket nor port
    with pytest.raises(ValueError):
        ServeDaemon(srv, sock_path="x", port=5)
