"""Distance-1 coloring: hash parity, conflict-freedom, Louvain integration."""

import numpy as np
import pytest

from cuvite_tpu.core.distgraph import DistGraph
from cuvite_tpu.evaluate.modularity import modularity as mod_oracle
from cuvite_tpu.io.generate import generate_rgg, generate_rmat
from cuvite_tpu.louvain.coloring import (
    count_conflicts,
    jenkins_mix,
    jenkins_mix_host,
    multi_hash_coloring,
)
from cuvite_tpu.louvain.driver import louvain_phases


def test_hash_matches_host_scalar():
    import jax.numpy as jnp

    for a, s in [(0, 0), (1, 1012), (12345, 999), (2**31, 7)]:
        dev = int(jenkins_mix(jnp.asarray([a], dtype=jnp.uint32), s)[0])
        assert dev == jenkins_mix_host(a & 0xFFFFFFFF, s)


def _graph_arrays(g):
    return g.sources().astype(np.int32), g.tails.astype(np.int32)


@pytest.mark.parametrize("maker", [
    lambda: generate_rgg(512, seed=1),
    lambda: generate_rmat(9, edge_factor=8, seed=2),
])
def test_coloring_no_conflicts(maker):
    g = maker()
    src, dst = _graph_arrays(g)
    colors, n_colors = multi_hash_coloring(src, dst, g.num_vertices, n_hash=4)
    assert count_conflicts(src, dst, g.num_vertices, colors) == 0
    # coverage target: >= floor(70% of nv) colored (coloring.cpp:23; the
    # loop's integer target, so exact-hit counts like 358/512 pass)
    assert (colors >= 0).sum() >= (g.num_vertices * 70) // 100
    assert n_colors > 0
    assert colors.max() < n_colors


def test_coloring_single_iteration(karate):
    src, dst = _graph_arrays(karate)
    colors, n_colors = multi_hash_coloring(
        src, dst, karate.num_vertices, n_hash=2, single_iteration=True)
    assert n_colors == 4  # exactly one round of 2*nHash
    assert count_conflicts(src, dst, karate.num_vertices, colors) == 0


def test_louvain_with_coloring_quality(karate):
    res = louvain_phases(karate, coloring=8)
    q = mod_oracle(karate, res.communities)
    assert q >= 0.38
    res2 = louvain_phases(karate, vertex_ordering=8)
    q2 = mod_oracle(karate, res2.communities)
    assert q2 >= 0.38


def test_louvain_coloring_sharded(karate):
    res = louvain_phases(karate, nshards=4, coloring=8)
    q = mod_oracle(karate, res.communities)
    assert q >= 0.38


def test_coloring_improves_or_matches_planted():
    # planted partition where sync Louvain may oscillate; coloring schedule
    # must still converge to a sane modularity
    g = generate_rgg(1024, seed=3)
    r_plain = louvain_phases(g)
    r_color = louvain_phases(g, coloring=8)
    q_plain = mod_oracle(g, r_plain.communities)
    q_color = mod_oracle(g, r_color.communities)
    assert q_color >= 0.8 * q_plain
