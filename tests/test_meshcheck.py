"""Tier-5 mesh audit tests (ISSUE 15): the dynamic M001-M003 gate and
its sabotage fixtures.

The acceptance gate is :func:`test_mesh_audit_green_on_current_tree`:
labels bit-identical across >= 3 virtual mesh shapes for both solo
exchanges and both batched engines, per-shard collective sequences
identical, and the per-device HBM ledger obeying every scaling law in
``tools/replication_budget.json``.  The sabotage tests then prove each
M-rule actually convicts a seeded bug — a gate that cannot fail is not
a gate:

  * a conditional psum (collectives under branch-divergent control
    flow) MUST trip M001;
  * a mesh-shape-forked collective schedule MUST trip M001;
  * shape-divergent labels MUST trip M002;
  * an unsharded table threaded into a sharded entry MUST trip M003
    (driver placements monkeypatched to replicate — the ledger's
    per-device column sees through it);
  * dynamic M00x results are NEVER written to the incremental lint
    cache (the concheck precedent).
"""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from cuvite_tpu.analysis import meshcheck as mc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUDGET = os.path.join(REPO, "tools", "replication_budget.json")


# ---------------------------------------------------------------------------
# THE tier-1 gate: the full audit on the forced-CPU 8-virtual-device
# shape (conftest pins the device count; the same audit tools/
# mesh_audit.py runs standalone and ladder stage I runs on real chips).


def test_mesh_audit_green_on_current_tree():
    findings, reports = mc.run_mesh_audit()
    assert not findings, "\n".join(f.format() for f in findings)
    # Coverage, not vacuity: every entry observed at every shape, the
    # sparse entries exchange via all_to_all, the batched programs are
    # collective-free by design, and the ledger rows are non-trivial.
    assert set(reports) == set(mc.ENTRIES)
    for name, by_shape in reports.items():
        assert len(by_shape) == len(mc.MESH_SHAPES), name
        for rep in by_shape.values():
            assert rep.labels and rep.categories, (name, rep.tag)
    sparse_seq = reports["bucketed_sparse"]["8x1"].seq
    assert any(p == "all_to_all" for p, _ in sparse_seq), \
        "sparse entry must exchange via all_to_all"
    assert reports["bucketed_replicated"]["8x1"].seq != sparse_seq
    assert reports["batched_fused"]["8x1"].seq == (), \
        "the batched program is collective-free by design"
    # Two-level entry: tables gathered on the fast axis, ghosts routed
    # on the slow one, and per-device table bytes shrinking ~1/|dcn|
    # (the tentpole's whole point — 2x at |dcn|=2, 4x at |dcn|=4).
    two_sigs = mc._flat_sigs(reports["bucketed_twolevel"]["4x2"].seq)
    assert any(s == "all_gather(ici)" for s in two_sigs), two_sigs
    assert any(s == "all_to_all(dcn)" for s in two_sigs), two_sigs
    floors = {2: 1.8, 4: 3.5, 8: 7.0}
    for tag, rep in reports["bucketed_twolevel"].items():
        row = rep.categories["exchange_tables"]
        ratio = row["global"] / row["per_device"]
        assert ratio >= floors[rep.axes["dcn"]], (tag, row)


def test_budget_manifest_closed_and_loadable():
    doc = mc.load_budget(BUDGET)
    assert doc["version"] == mc.BUDGET_VERSION
    for cat in ("slab", "tables", "plans", "exchange", "scratch"):
        assert doc["categories"][cat]["law"] in ("sharded", "replicated")
    # v2: the two-level categories carry the per-axis law — tables and
    # grouped routing may reach full extent over |dcn|, never more.
    for cat in ("exchange_tables", "exchange_grouped"):
        assert doc["categories"][cat]["law"] == "ici_replicated"


def test_missing_budget_fails_closed(tmp_path):
    findings, _ = mc.run_mesh_audit(
        entry_names=[], budget_path=str(tmp_path / "nope.json"))
    assert [f.rule for f in findings] == ["M000"]


# ---------------------------------------------------------------------------
# Sabotage: M001 — the conditional psum.


def test_conditional_psum_trips_m001():
    from jax.sharding import PartitionSpec as P

    from cuvite_tpu.comm.mesh import make_mesh, shard_map

    mesh = make_mesh(8)

    def bad(x):
        return jax.lax.cond(
            x[0] > 0.0,
            lambda v: jax.lax.psum(v, "v"),
            lambda v: v,
            x)

    wrapped = jax.jit(shard_map(bad, mesh=mesh, in_specs=P("v"),
                                out_specs=P("v"), check_vma=False))
    jaxpr = jax.make_jaxpr(wrapped)(np.zeros(8, np.float32))
    findings = mc.lint_collective_jaxpr(jaxpr, "sabotage_cond_psum")
    assert any(f.rule == "M001" for f in findings), findings

    def good(x):  # both branches issue the identical sequence
        return jax.lax.cond(
            x[0] > 0.0,
            lambda v: jax.lax.psum(v, "v"),
            lambda v: jax.lax.psum(v * 0.0, "v"),
            x)

    wrapped_ok = jax.jit(shard_map(good, mesh=mesh, in_specs=P("v"),
                                   out_specs=P(), check_vma=False))
    jaxpr_ok = jax.make_jaxpr(wrapped_ok)(np.zeros(8, np.float32))
    assert not mc.lint_collective_jaxpr(jaxpr_ok, "balanced_cond")


def test_sequence_with_empty_cond_branch_flattens_and_convicts():
    """The conditional-psum shape produces a cond with one EMPTY
    branch; flattening and cross-shape comparison must convict, not
    crash (review regression: _flat_names IndexError on ())."""
    forked = {"8x1": (("cond", ((("psum", ("v",)),), ())),),
              "4x2": ()}
    findings = mc.check_sequences("e", forked)
    assert [f.rule for f in findings] == ["M001"]
    assert "psum" in findings[0].message
    # ... and branch flattening keeps EVERY collective, including the
    # first of each branch.
    seq = (("cond", ((("psum", ("v",)), ("all_to_all", ("v",))),
                     (("all_gather", ("v",)),))),)
    assert mc._flat_names(seq) == ["psum", "all_to_all", "all_gather"]


def test_shape_forked_sequence_trips_m001():
    seqs = {"8x1": (("psum", ("v",)), ("all_to_all", ("v",))),
            "4x2": (("psum", ("v",)),)}
    findings = mc.check_sequences("forked", seqs)
    assert [f.rule for f in findings] == ["M001"]
    assert not mc.check_sequences("same", {"8x1": seqs["8x1"],
                                           "4x2": seqs["8x1"]})


def test_axis_renamed_sequence_convicts_with_axes_in_message():
    """Sequences differing ONLY in axis names — the ICI/DCN rename
    class — must convict AND the message must render the axes (review
    regression: names-only rendering read 'psum vs psum')."""
    seqs = {"8x1": (("psum", ("v",)),), "4x2": (("psum", ("ici",)),)}
    findings = mc.check_sequences("renamed", seqs)
    assert [f.rule for f in findings] == ["M001"]
    assert "psum(v)" in findings[0].message
    assert "psum(ici)" in findings[0].message


def test_shape_divergent_labels_trip_m002():
    a = np.arange(16)
    b = a.copy()
    b[3] = 0
    findings = mc.check_labels("lab", {"8x1": [(a, 0.5)],
                                       "4x2": [(b, 0.5)]})
    assert [f.rule for f in findings] == ["M002"]
    findings_q = mc.check_labels("labq", {"8x1": [(a, 0.5)],
                                          "4x2": [(a, 0.5000001)]})
    assert [f.rule for f in findings_q] == ["M002"]
    assert not mc.check_labels("ok", {"8x1": [(a, 0.5)],
                                      "4x2": [(a.copy(), 0.5)]})


# ---------------------------------------------------------------------------
# Sabotage: M003 — an unsharded [nv_pad] table inside a sharded entry.
# driver placements are monkeypatched to REPLICATE; the ledger's
# per-device column must stop scaling and the law check must convict.


def test_unsharded_table_trips_m003(monkeypatch):
    import cuvite_tpu.louvain.driver as drv
    from cuvite_tpu.comm.mesh import make_mesh, shard_1d
    from cuvite_tpu.core.distgraph import DistGraph
    from cuvite_tpu.louvain.driver import PhaseRunner

    monkeypatch.setattr(
        drv, "shard_1d",
        lambda mesh, arr, replicate=False: shard_1d(mesh, arr,
                                                    replicate=True))
    ledgers = {}
    for shape in ((4, 2), (2, 4)):
        dg = DistGraph.build(mc._audit_graph(), shape[0])
        rec, tracer = mc._recorder()
        PhaseRunner(dg, mesh=make_mesh(shape[0]), engine="bucketed",
                    exchange="replicated", tracer=tracer)
        rec.ledger.snapshot(0)
        ledgers[f"{shape[0]}x{shape[1]}"] = {
            "devices": shape[0],
            "categories": mc._ledger_categories(rec.ledger),
        }
    findings = mc.check_replication("sabotage_replicated",
                                    ledgers, mc.load_budget(BUDGET))
    assert any(f.rule == "M003" for f in findings), ledgers
    assert any("tables" in (f.snippet or "") for f in findings
               if f.rule == "M003")


def test_unlisted_category_trips_m003():
    ledgers = {"4x2": {"devices": 4, "categories": {
        "mystery": {"global": 1 << 20, "per_device": 1 << 18}}}}
    findings = mc.check_replication("x", ledgers, mc.load_budget(BUDGET))
    assert [f.rule for f in findings] == ["M003"]
    assert "mystery" in findings[0].message


def test_per_device_nbytes_sees_replication():
    """The ledger export itself: a replicated placement answers full
    bytes per device, a 1-D sharded one 1/S — the measurement M003's
    law check is built on."""
    from cuvite_tpu.comm.mesh import make_mesh, shard_1d
    from cuvite_tpu.obs.memory import per_device_nbytes

    mesh = make_mesh(4)
    host = np.zeros(4096, np.float32)
    sharded = shard_1d(mesh, host)
    replicated = shard_1d(mesh, host, replicate=True)
    assert per_device_nbytes(sharded) == host.nbytes // 4
    assert per_device_nbytes(replicated) == host.nbytes
    assert per_device_nbytes(host) == host.nbytes  # host: conservative


# ---------------------------------------------------------------------------
# Dynamic results are never cached.


def test_mesh_audit_never_touches_lint_cache(tmp_path):
    from cuvite_tpu.analysis.engine import run_paths

    cache = tmp_path / "cache.json"
    src = tmp_path / "m.py"
    src.write_text("x = 1\n")
    run_paths([str(src)], cache=str(cache))
    before = cache.read_bytes()
    findings, _ = mc.run_mesh_audit(
        entry_names=["bucketed_replicated"],
        shapes=((4, 2), (2, 4)))
    assert not findings
    assert cache.read_bytes() == before, \
        "dynamic M00x results must never enter the lint cache"


# ---------------------------------------------------------------------------
# The shared neutrality helper (what test_batched/test_pallas_spmd use).


def test_assert_mesh_neutral_helper():
    good = {"a": [(np.arange(4), 0.1)], "b": [(np.arange(4), 0.1)]}
    mc.assert_mesh_neutral(lambda cfg: good[cfg], ["a", "b"])
    bad = {"a": [(np.arange(4), 0.1)], "b": [(np.arange(4) * 2, 0.1)]}
    with pytest.raises(AssertionError, match="M002"):
        mc.assert_mesh_neutral(lambda cfg: bad[cfg], ["a", "b"])


# ---------------------------------------------------------------------------
# CLI: the static --inventory path stays runnable without the audit
# (subprocess; the full-audit CLI is exercised in-process above).


def test_mesh_audit_cli_write_budget(tmp_path):
    """The M000 remediation path is real: --write-budget regenerates
    the manifest, preserving existing category laws."""
    budget = tmp_path / "budget.json"
    budget.write_text(json.dumps({
        "version": 1, "env": {},
        "categories": {"slab": {"law": "sharded", "reason": "seeded"}},
    }))
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "mesh_audit.py"),
         "--write-budget", "--entries", "bucketed_replicated",
         "--shapes", "2x1", "--budget", str(budget)],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr
    doc = json.loads(budget.read_text())
    assert doc["version"] == mc.BUDGET_VERSION
    assert doc["categories"]["slab"]["reason"] == "seeded"
    # observed-but-unlisted categories land with the failing-closed
    # 'sharded' default law.
    assert any(v["law"] == "sharded" and "autogenerated" in v["reason"]
               for k, v in doc["categories"].items() if k != "slab")


def test_mesh_audit_cli_inventory_subprocess():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "mesh_audit.py"),
         "--inventory", "--json"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr
    inv = json.loads(out.stdout)
    rels = {e["rel"] for e in inv}
    # The replicated community tables are in the closed inventory.
    assert "cuvite_tpu/louvain/bucketed.py" in rels
    assert "cuvite_tpu/ops/segment.py" in rels
    assert all(e["reason"] for e in inv)
