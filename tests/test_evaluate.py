"""Ground-truth comparison (F-score/Gini) + ET modes + CLI."""

import json
import subprocess
import sys

import numpy as np
import pytest

from cuvite_tpu.evaluate.compare import (
    compare_communities,
    gini_coefficient,
    load_ground_truth,
    write_communities,
)
from cuvite_tpu.louvain.driver import louvain_phases


def test_compare_identical_partitions():
    c = np.array([0, 0, 1, 1, 2])
    r = compare_communities(c, c)
    assert r.precision == 1.0 and r.recall == 1.0 and r.f_score == 1.0
    assert r.false_negative == 0 and r.false_positive == 0
    # pairs: C(2,2)+C(2,2)+C(1,2) = 1+1+0
    assert r.true_positive == 2


def test_compare_against_brute_force():
    rng = np.random.default_rng(0)
    truth = rng.integers(0, 4, size=30)
    out = rng.integers(0, 5, size=30)
    r = compare_communities(truth, out)
    tp = fn = fp = 0
    for i in range(30):
        for j in range(i + 1, 30):
            st, so = truth[i] == truth[j], out[i] == out[j]
            tp += st and so
            fn += st and not so
            fp += so and not st
    assert (r.true_positive, r.false_negative, r.false_positive) == (tp, fn, fp)
    assert r.precision == pytest.approx(tp / (tp + fp))
    assert r.recall == pytest.approx(tp / (tp + fn))


def test_gini_uniform_is_zero():
    assert gini_coefficient(np.array([5, 5, 5, 5])) == pytest.approx(0.0)


def test_gini_concentrated_is_high():
    g = gini_coefficient(np.array([1, 1, 1, 97]))
    assert g > 0.7


def test_ground_truth_roundtrip(tmp_path):
    p = tmp_path / "truth.dat"
    p.write_text("0 1\n1 1\n2 2\n3 2\n")
    c = load_ground_truth(str(p))  # 1-based by default
    np.testing.assert_array_equal(c, [0, 0, 1, 1])
    out = tmp_path / "out.communities"
    write_communities(str(out), c)
    np.testing.assert_array_equal(np.loadtxt(out, dtype=np.int64), c)


def test_compare_report_format():
    c = np.array([0, 0, 1, 1])
    rep = compare_communities(c, c).report()
    assert "F-score" in rep and "Gini" in rep and "True positive" in rep


@pytest.mark.parametrize("mode", [1, 2, 3, 4])
def test_et_modes_converge(karate, mode):
    res = louvain_phases(karate, et_mode=mode, et_delta=0.25)
    from cuvite_tpu.evaluate.modularity import modularity
    q = modularity(karate, res.communities)
    assert q >= 0.35, f"ET mode {mode} degraded quality: Q={q}"


def test_cli_end_to_end(tmp_path, karate):
    from cuvite_tpu.io.vite import write_vite

    binp = tmp_path / "karate.bin"
    write_vite(str(binp), karate, bits64=True)
    cmd = [
        sys.executable, "-m", "cuvite_tpu.cli",
        "--file", str(binp), "--bits64", "--output", "--json", "--quiet",
    ]
    proc = subprocess.run(
        cmd, capture_output=True, text=True, cwd=str(tmp_path),
        env={"PYTHONPATH": "/root/repo", "PATH": "/usr/local/bin:/usr/bin:/bin",
             "JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=1"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
    summary = json.loads(line)
    assert summary["modularity"] > 0.38
    assert (tmp_path / "karate.bin.communities").exists()


def test_cli_validation_errors(tmp_path):
    from cuvite_tpu.cli import build_parser, validate

    with pytest.raises(SystemExit):
        validate(build_parser().parse_args([]))  # no input
    with pytest.raises(SystemExit):
        validate(build_parser().parse_args(
            ["--generate", "64", "--one-phase", "--threshold-cycling"]))
    with pytest.raises(SystemExit):
        validate(build_parser().parse_args(
            ["--generate", "64", "--coloring", "4", "--vertex-ordering", "4"]))
    with pytest.raises(SystemExit):
        validate(build_parser().parse_args(
            ["--file", "x", "--random-edges", "5"]))
