"""End-to-end multi-phase Louvain: golden results on known graphs."""

import numpy as np
import pytest

from cuvite_tpu.core.graph import Graph
from cuvite_tpu.evaluate.modularity import modularity as modularity_oracle
from cuvite_tpu.louvain.driver import louvain_phases, threshold_for_phase


def test_threshold_schedule():
    assert threshold_for_phase(0) == 1e-3
    assert threshold_for_phase(3) == 1e-4
    assert threshold_for_phase(7) == 1e-5
    assert threshold_for_phase(10) == 1e-6
    assert threshold_for_phase(13) == 1e-3  # cycle wraps


def test_two_cliques_exact(two_cliques):
    res = louvain_phases(two_cliques)
    assert res.num_communities == 2
    # K5+K5+bridge: Q = 2*(10/21 - (21/42)^2) with both-direction counting
    q = modularity_oracle(two_cliques, res.communities)
    assert res.modularity == pytest.approx(q, abs=1e-5)
    assert q > 0.45


def test_karate_golden(karate):
    """Louvain on Zachary's karate club reaches Q ~ 0.40-0.42
    (the well-known value; reference uses karate.bin as its smoke test,
    /root/reference/README:53)."""
    res = louvain_phases(karate)
    q = modularity_oracle(karate, res.communities)
    assert q >= 0.38, f"karate modularity too low: {q}"
    assert 2 <= res.num_communities <= 8
    # device-reported modularity consistent with the host oracle
    assert res.modularity == pytest.approx(q, abs=1e-4)


def test_karate_sharded_runs(karate):
    res = louvain_phases(karate, nshards=8)
    q = modularity_oracle(karate, res.communities)
    assert q >= 0.38
    # Deterministic: sharded must equal single-shard exactly.
    res1 = louvain_phases(karate, nshards=1)
    np.testing.assert_array_equal(res.communities, res1.communities)


def test_threshold_cycling_converges(karate):
    res = louvain_phases(karate, threshold_cycling=True)
    q = modularity_oracle(karate, res.communities)
    assert q >= 0.38


def test_one_phase(karate):
    res = louvain_phases(karate, one_phase=True)
    assert len(res.phases) <= 1


def test_modularity_monotone_over_phases(karate):
    res = louvain_phases(karate)
    mods = [p.modularity for p in res.phases]
    assert all(b >= a - 1e-9 for a, b in zip(mods, mods[1:]))


def test_star_graph_collapses():
    """A star collapses into a single community -> Q = 0 at best."""
    n = 9
    s = np.zeros(n - 1, dtype=np.int64)
    d = np.arange(1, n, dtype=np.int64)
    g = Graph.from_edges(n, s, d)
    res = louvain_phases(g)
    assert res.num_communities <= n
    assert res.modularity <= 0.5
