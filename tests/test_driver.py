"""End-to-end multi-phase Louvain: golden results on known graphs."""

import numpy as np
import pytest

from cuvite_tpu.core.graph import Graph
from cuvite_tpu.evaluate.modularity import modularity as modularity_oracle
from cuvite_tpu.louvain.driver import louvain_phases, threshold_for_phase


def test_threshold_schedule():
    assert threshold_for_phase(0) == 1e-3
    assert threshold_for_phase(3) == 1e-4
    assert threshold_for_phase(7) == 1e-5
    assert threshold_for_phase(10) == 1e-6
    assert threshold_for_phase(13) == 1e-3  # cycle wraps


def test_two_cliques_exact(two_cliques):
    res = louvain_phases(two_cliques)
    assert res.num_communities == 2
    # K5+K5+bridge: Q = 2*(10/21 - (21/42)^2) with both-direction counting
    q = modularity_oracle(two_cliques, res.communities)
    assert res.modularity == pytest.approx(q, abs=1e-5)
    assert q > 0.45


def test_karate_golden(karate):
    """Louvain on Zachary's karate club reaches Q ~ 0.40-0.42
    (the well-known value; reference uses karate.bin as its smoke test,
    /root/reference/README:53)."""
    res = louvain_phases(karate)
    q = modularity_oracle(karate, res.communities)
    assert q >= 0.38, f"karate modularity too low: {q}"
    assert 2 <= res.num_communities <= 8
    # device-reported modularity consistent with the host oracle
    assert res.modularity == pytest.approx(q, abs=1e-4)


def test_karate_sharded_runs(karate):
    res = louvain_phases(karate, nshards=8)
    q = modularity_oracle(karate, res.communities)
    assert q >= 0.38
    # Deterministic: sharded must equal single-shard exactly.
    res1 = louvain_phases(karate, nshards=1)
    np.testing.assert_array_equal(res.communities, res1.communities)


def test_threshold_cycling_converges(karate):
    res = louvain_phases(karate, threshold_cycling=True)
    q = modularity_oracle(karate, res.communities)
    assert q >= 0.38


def test_one_phase(karate):
    res = louvain_phases(karate, one_phase=True)
    assert len(res.phases) <= 1


def test_modularity_monotone_over_phases(karate):
    res = louvain_phases(karate)
    mods = [p.modularity for p in res.phases]
    assert all(b >= a - 1e-9 for a, b in zip(mods, mods[1:]))


def test_star_graph_collapses():
    """A star collapses into a single community -> Q = 0 at best."""
    n = 9
    s = np.zeros(n - 1, dtype=np.int64)
    d = np.arange(1, n, dtype=np.int64)
    g = Graph.from_edges(n, s, d)
    res = louvain_phases(g)
    assert res.num_communities <= n
    assert res.modularity <= 0.5


# ---------------------------------------------------------------------------
# CUVITE_EXCHANGE_CUTOVER (the exchange='auto' sparse cutover, env-tunable)


def test_exchange_cutover_env_override(monkeypatch):
    from cuvite_tpu.louvain.driver import (
        AUTO_SPARSE_MIN_VERTICES, exchange_cutover,
    )

    monkeypatch.delenv("CUVITE_EXCHANGE_CUTOVER", raising=False)
    assert exchange_cutover() == AUTO_SPARSE_MIN_VERTICES
    monkeypatch.setenv("CUVITE_EXCHANGE_CUTOVER", "1024")
    assert exchange_cutover() == 1024
    monkeypatch.setenv("CUVITE_EXCHANGE_CUTOVER", "0x100")
    assert exchange_cutover() == 256
    for bogus in ("zero", "-5", "0", ""):
        monkeypatch.setenv("CUVITE_EXCHANGE_CUTOVER", bogus)
        if bogus == "":
            assert exchange_cutover() == AUTO_SPARSE_MIN_VERTICES
        else:
            with pytest.warns(UserWarning, match="CUVITE_EXCHANGE_CUTOVER"):
                assert exchange_cutover() == AUTO_SPARSE_MIN_VERTICES


def test_exchange_cutover_is_honored_by_auto(karate, monkeypatch):
    """exchange='auto' on a mesh: with the cutover forced to 1 every phase
    resolves to the sparse plan (observable at the ExchangePlan.build
    chokepoint); with the default cutover (2^26) none does."""
    from cuvite_tpu.comm.exchange import ExchangePlan

    calls = []
    orig = ExchangePlan.build

    def counting(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    monkeypatch.setattr(ExchangePlan, "build", staticmethod(counting))
    monkeypatch.setenv("CUVITE_EXCHANGE_CUTOVER", "1")
    r_sparse = louvain_phases(karate, nshards=2, exchange="auto")
    assert calls, "cutover=1 must route exchange='auto' to the sparse plan"
    n_sparse = len(calls)
    calls.clear()
    monkeypatch.delenv("CUVITE_EXCHANGE_CUTOVER")
    r_repl = louvain_phases(karate, nshards=2, exchange="auto")
    assert not calls, "below the default cutover 'auto' stays replicated"
    # Exchange choice must not change the clustering.
    np.testing.assert_array_equal(r_sparse.communities, r_repl.communities)
    assert n_sparse >= 1


# ---------------------------------------------------------------------------
# sort-engine x coloring: auto-switch to the class-capable bucketed engine


def test_sort_coloring_auto_switches_to_bucketed(karate):
    with pytest.warns(UserWarning, match="auto-switching"):
        r = louvain_phases(karate, engine="sort", coloring=4)
    r_ref = louvain_phases(karate, engine="bucketed", coloring=4)
    np.testing.assert_array_equal(r.communities, r_ref.communities)
    assert r.modularity == r_ref.modularity


def test_sort_coloring_opt_out_keeps_legacy_schedule(karate, monkeypatch):
    monkeypatch.setenv("CUVITE_KEEP_SORT_COLORING", "1")
    with pytest.warns(UserWarning, match="legacy schedule"):
        res = louvain_phases(karate, engine="sort", coloring=4)
    q = modularity_oracle(karate, res.communities)
    assert q >= 0.38
