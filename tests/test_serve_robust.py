"""Robustness-layer tests (ISSUE 11): fault injection + retry,
admission control, per-tenant fairness, deadline shedding, the
open-loop load generator, and the chaos gate (job conservation under a
seeded randomized fault plan over hundreds of jobs).

Everything except the explicitly-real-jax tests runs a STUB runner on
a fake clock/sleep pair: the invariants under test (conservation,
retry accounting, fairness, admission projections) live entirely in
the queue, so hundreds of chaos jobs cost milliseconds and zero
sleeps.  The real-jax tests then pin the one property the stub cannot:
surviving tenants' labels/Q bit-identical to a fault-free run through
the real batched driver.
"""

import types

import numpy as np
import pytest

from cuvite_tpu.core.graph import Graph
from cuvite_tpu.serve import (
    AdmissionConfig,
    AdmissionReject,
    FaultPlan,
    InjectedFault,
    LouvainServer,
    ServeConfig,
)
from cuvite_tpu.serve.faults import FaultRule
from cuvite_tpu.serve.loadgen import run_open_loop, saturation_sweep
from cuvite_tpu.serve.queue import _ClassBin, Job
from cuvite_tpu.workloads.synth import many_seed, synthesize_graph


class FakeClock:
    """Injectable clock + sleep pair: sleep advances virtual time."""

    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def sleep(self, s: float) -> None:
        self.t += s


def make_graph(seed: int, nv: int = 16, ne: int = 32) -> Graph:
    rng = np.random.default_rng(seed)
    return Graph.from_edges(nv, rng.integers(0, nv, ne),
                            rng.integers(0, nv, ne))


def stub_result(g):
    """Deterministic pure function of the graph — the identity anchor
    for chaos runs (a fault that perturbed a surviving job's inputs
    would change this)."""
    nv = g.num_vertices
    key = int(np.sum(g.tails)) % 997
    return types.SimpleNamespace(
        communities=(np.arange(nv) + key) % max(nv, 1),
        modularity=key / 997.0,
        phases=[1], total_iterations=3, num_communities=nv)


def make_stub_runner(clock=None, service_s: float = 0.0, calls=None):
    """cluster_many-shaped stub; optionally consumes virtual service
    time per batch (what makes queueing/admission observable on the
    fake clock)."""

    def runner(graphs, **kw):
        if calls is not None:
            calls.append(len(graphs))
        if clock is not None and service_s:
            clock.sleep(service_s)
        results = [stub_result(g) for g in graphs]
        return types.SimpleNamespace(results=results, n_phases=1)

    return runner


def make_server(clock, *, runner=None, faults=None, **cfg_kw):
    cfg_kw.setdefault("engine", "fused")  # stub path: skip plan shapes
    cfg_kw.setdefault("b_max", 4)
    cfg_kw.setdefault("linger_s", 0.0)
    return LouvainServer(ServeConfig(**cfg_kw), clock=clock,
                         sleep=clock.sleep, faults=faults,
                         runner=runner or make_stub_runner(clock))


# ---------------------------------------------------------------------------
# Fault-plan grammar


def test_fault_plan_grammar_round_trip():
    plan = FaultPlan.parse(
        "dispatch:raise:every=7; device:transient:n=2;"
        "pack:transient:p=0.1,seed=42")
    assert len(plan.rules) == 3
    assert plan.rules[0].permanent and plan.rules[0].every == 7
    assert not plan.rules[1].permanent and plan.rules[1].n == 2
    assert plan.rules[2].p == 0.1 and plan.rules[2].seed == 42
    assert FaultPlan.parse(plan.spec()).spec() == plan.spec()
    assert not FaultPlan.parse("")       # empty plan is falsy
    assert not FaultPlan.parse(None)


@pytest.mark.parametrize("bad", [
    "dispatch:raise",                    # no params
    "teleport:raise:n=1",                # unknown site
    "dispatch:crash:n=1",                # unknown kind
    "dispatch:raise:n=0",                # selector out of range
    "dispatch:raise:p=1.5",
    "dispatch:raise:every=7,n=2",        # two selectors
    "dispatch:raise:seed=3",             # no selector
    "dispatch:raise:bogus=1",
])
def test_fault_plan_rejects_malformed(bad):
    with pytest.raises(ValueError):
        FaultPlan.parse(bad)


def test_fault_plan_deterministic():
    def fire_seq(plan, n=64):
        out = []
        for _ in range(n):
            try:
                plan.check("device")
                out.append(0)
            except InjectedFault:
                out.append(1)
        return out

    spec = "device:transient:p=0.3,seed=9"
    a = fire_seq(FaultPlan.parse(spec))
    b = fire_seq(FaultPlan.parse(spec))
    assert a == b and sum(a) > 0
    every = fire_seq(FaultPlan.parse("device:raise:every=4"), 12)
    assert every == [0, 0, 0, 1] * 3
    first_n = fire_seq(FaultPlan.parse("device:transient:n=2"), 5)
    assert first_n == [1, 1, 0, 0, 0]


# ---------------------------------------------------------------------------
# Transient retry + permanent isolation


def test_transient_fault_retries_with_backoff():
    clock = FakeClock()
    calls = []
    srv = make_server(clock, runner=make_stub_runner(clock, calls=calls),
                      faults=FaultPlan.parse("device:transient:n=2"),
                      max_retries=3, retry_base_s=0.1)
    from cuvite_tpu.obs import FlightRecorder, MemoryTraceSink
    from cuvite_tpu.utils.trace import Tracer

    sink = MemoryTraceSink()
    srv.tracer = Tracer(recorder=FlightRecorder(sink, watch_compiles=False))
    jid = srv.submit(make_graph(0))
    t_before = clock.t
    done = srv.step(force=True)
    assert [j for j, _ in done] == [jid]
    assert srv.stats.retries == 2
    # Exponential backoff on the injectable sleep: 0.1 + 0.2.
    assert clock.t - t_before == pytest.approx(0.1 + 0.2)
    retries = [r for r in sink.records
               if r.get("t") == "event" and r.get("name") == "retry"]
    assert [r["attrs"]["attempt"] for r in retries] == [1, 2]
    assert retries[0]["attrs"]["site"] == "device"
    assert srv.conservation()["ok"]


def test_transient_exhausted_flows_to_failure():
    clock = FakeClock()
    srv = make_server(clock,
                      faults=FaultPlan.parse("device:transient:n=99"),
                      max_retries=1, retry_base_s=0.01)
    srv.submit(make_graph(1))
    assert srv.step(force=True) == []
    assert srv.stats.retries == 1
    assert srv.stats.jobs_failed == 1 and len(srv.failures) == 1
    assert "transient" in srv.failures[0][1]
    assert srv.conservation()["ok"]


def test_permanent_batch_fault_isolates_batchmates():
    """A permanent fault hitting a BATCH dispatch must not kill the
    jobs: the batch splits and each isolated single-job dispatch (a
    fresh passage through the fault sites) completes."""
    clock = FakeClock()
    srv = make_server(clock, faults=FaultPlan.parse("dispatch:raise:n=1"))
    ids = [srv.submit(make_graph(s)) for s in range(3)]
    done = dict(srv.step(force=True))
    assert set(done) == set(ids)
    assert srv.stats.jobs_failed == 0 and not srv.failures
    assert srv.stats.jobs_done == 3
    assert srv.conservation()["ok"]


def test_submit_fault_counts_as_rejection():
    clock = FakeClock()
    srv = make_server(clock, faults=FaultPlan.parse("submit:raise:n=1"))
    with pytest.raises(InjectedFault):
        srv.submit(make_graph(0))
    jid = srv.submit(make_graph(1))  # passage 2: admitted
    assert srv.stats.jobs_rejected == 1 and srv.stats.jobs_submitted == 1
    done = srv.step(force=True)
    assert [j for j, _ in done] == [jid]
    assert srv.conservation()["ok"]


# ---------------------------------------------------------------------------
# Admission control


def test_admission_cold_start_admits():
    clock = FakeClock()
    srv = make_server(clock,
                      admission=AdmissionConfig(wait_slo_s=0.001))
    for s in range(8):
        srv.submit(make_graph(s))   # no estimate yet: everything admits
    assert srv.stats.jobs_rejected == 0 and srv.pending() == 8


def test_admission_empty_bin_always_admits():
    """A class whose batch service EXCEEDS slo/headroom must still
    admit into an empty (sub-one-batch) bin: the job dispatches within
    the linger window; its own batch service is not queue wait.
    (Counting it would lock the class out at depth 0 forever.)"""
    clock = FakeClock()
    srv = make_server(clock,
                      runner=make_stub_runner(clock, service_s=2.0),
                      b_max=4, admission=AdmissionConfig(wait_slo_s=0.5))
    srv.submit(make_graph(0))
    srv.step(force=True)             # est ~2 s >> slo 0.5 s
    assert srv.admission.estimate(next(iter(srv.admission._obs))) \
        == pytest.approx(2.0)
    for s in range(3):               # depths 0..2 < b_max: all admit
        srv.submit(make_graph(10 + s))
    assert srv.stats.jobs_rejected == 0 and srv.pending() == 3
    with pytest.raises(AdmissionReject):
        for s in range(8):           # one full batch queued: reject
            srv.submit(make_graph(20 + s))
    srv.drain()
    assert srv.conservation()["ok"]


def test_admission_rejects_with_retry_after():
    """Once the measured service time projects a new job's wait past
    the SLO, submit rejects with a structured retry_after_s."""
    clock = FakeClock()
    srv = make_server(clock,
                      runner=make_stub_runner(clock, service_s=0.3),
                      b_max=2, admission=AdmissionConfig(wait_slo_s=0.5))
    from cuvite_tpu.obs import FlightRecorder, MemoryTraceSink
    from cuvite_tpu.utils.trace import Tracer

    sink = MemoryTraceSink()
    srv.tracer = Tracer(recorder=FlightRecorder(sink, watch_compiles=False))
    srv.submit(make_graph(0))
    srv.submit(make_graph(1))
    srv.step()                       # observes busy ~0.3 s per batch
    est = srv.admission.estimate(next(iter(srv.admission._obs)))
    assert est == pytest.approx(0.3)
    # floor(depth/b_max) full batches stand between a new job and its
    # own dispatch: depths 0-1 project 0 (admit — an empty-ish bin
    # serves within linger regardless of batch service time), depths
    # 2-3 project 1 * 0.3 * 1.25 = 0.375 <= 0.5 (admit), depth 4
    # projects 0.75 s past the 0.5 s SLO: reject from there.
    admitted = []
    rejections = []
    for s in range(6):
        try:
            admitted.append(srv.submit(make_graph(10 + s)))
        except AdmissionReject as e:
            rejections.append(e)
    assert len(admitted) == 4
    assert rejections, "overload must reject"
    assert all(e.retry_after_s > 0 for e in rejections)
    assert srv.stats.jobs_rejected == len(rejections)
    rej_events = [r for r in sink.records
                  if r.get("t") == "event" and r.get("name") == "reject"]
    assert len(rej_events) == len(rejections)
    assert rej_events[0]["attrs"]["retry_after_s"] > 0
    srv.drain()
    assert srv.conservation()["ok"]


# ---------------------------------------------------------------------------
# Per-tenant fairness


def test_class_bin_round_robin():
    b = _ClassBin()
    for k in range(4):
        b.push(Job(f"a{k}", None, (0, 0), t_submit=float(k), tenant="A"))
    b.push(Job("b0", None, (0, 0), t_submit=10.0, tenant="B"))
    b.push(Job("c0", None, (0, 0), t_submit=11.0, tenant="C"))
    assert b.depth() == 6
    assert b.oldest_t_submit() == 0.0
    order = [b.pop_rr().job_id for _ in range(6)]
    assert order == ["a0", "b0", "c0", "a1", "a2", "a3"]
    assert b.pop_rr() is None and b.depth() == 0


def test_firehose_tenant_cannot_monopolize_batch():
    """Tenant A floods the bin; tenant B's two jobs still ride the
    FIRST batch (round-robin pop), not batch 4."""
    clock = FakeClock()
    srv = make_server(clock, b_max=4)
    a_ids = [srv.submit(make_graph(s), tenant="firehose")
             for s in range(6)]
    b_ids = [srv.submit(make_graph(100 + s), tenant="small")
             for s in range(2)]
    first = [j for j, _ in srv.step()]     # full bin -> one batch of 4
    assert first == [a_ids[0], b_ids[0], a_ids[1], b_ids[1]]
    rest = [j for j, _ in srv.drain()]
    assert rest == a_ids[2:]
    assert srv.conservation()["ok"]


def test_linger_reads_oldest_across_tenants():
    """The firehose cannot hold the linger clock hostage: the deadline
    runs from the OLDEST job in the bin even when a flood of newer
    jobs arrives after it."""
    clock = FakeClock()
    srv = make_server(clock, b_max=64, linger_s=0.5)
    old = srv.submit(make_graph(0), tenant="small")
    clock.t += 0.4
    for s in range(5):
        srv.submit(make_graph(10 + s), tenant="firehose")
    clock.t += 0.15                  # old job is 0.55 s old, flood 0.15 s
    done = [j for j, _ in srv.step()]
    assert old in done and len(done) == 6
    assert srv.stats.linger_dispatches == 1


# ---------------------------------------------------------------------------
# Deadline shedding


def test_expired_jobs_shed_not_packed():
    clock = FakeClock()
    calls = []
    srv = make_server(clock, runner=make_stub_runner(clock, calls=calls))
    from cuvite_tpu.obs import FlightRecorder, MemoryTraceSink
    from cuvite_tpu.utils.trace import Tracer

    sink = MemoryTraceSink()
    srv.tracer = Tracer(recorder=FlightRecorder(sink, watch_compiles=False))
    doomed = srv.submit(make_graph(0), deadline_s=0.1)
    alive = srv.submit(make_graph(1), deadline_s=10.0)
    clock.t += 0.2                   # doomed expires; alive does not
    done = srv.step(force=True)
    assert [j for j, _ in done] == [alive]
    assert [j for j, _ in srv.shed] == [doomed]
    assert srv.stats.jobs_shed == 1
    assert calls == [1], "the shed job must never reach the runner"
    shed_events = [r for r in sink.records
                   if r.get("t") == "event" and r.get("name") == "shed"]
    assert len(shed_events) == 1
    assert shed_events[0]["attrs"]["job_id"] == doomed
    assert shed_events[0]["attrs"]["late_s"] == pytest.approx(0.1)
    assert srv.conservation()["ok"]


def test_linger_fires_for_second_bin_after_long_dispatch():
    """ISSUE 11 satellite: a bin whose linger deadline passes WHILE
    another bin's batch is mid-dispatch is picked up by the next step
    — the due-scan is a snapshot, not a lost wakeup."""
    from cuvite_tpu.io.generate import generate_rmat

    clock = FakeClock()
    srv = make_server(clock, runner=make_stub_runner(clock, service_s=0.6),
                      b_max=4, linger_s=0.5)
    small = srv.submit(make_graph(0))
    clock.t += 0.55                  # small class now past linger
    big = srv.submit(generate_rmat(13, edge_factor=8, seed=1))
    first = [j for j, _ in srv.step()]
    # Only the small-class bin was due at the scan; its 0.6 s dispatch
    # pushed the clock past the big job's linger deadline.
    assert first == [small]
    second = [j for j, _ in srv.step()]
    assert second == [big]
    assert srv.stats.linger_dispatches == 2
    assert srv.conservation()["ok"]


# ---------------------------------------------------------------------------
# Stats thread-safety (ISSUE 11 satellite)


def test_stats_snapshot_race_free():
    """to_dict()/percentiles snapshot wait_samples under the lock: a
    reader hammering them while a writer appends must never see a
    mutating deque (pre-fix: sorted() over a deque being appended
    raises RuntimeError)."""
    import threading

    from cuvite_tpu.serve import ServeStats

    stats = ServeStats()
    stop = threading.Event()
    errors = []

    def reader():
        try:
            while not stop.is_set():
                stats.to_dict()
                _ = stats.wait_p95_s
        except Exception as e:  # pragma: no cover - the failure signal
            errors.append(e)

    t = threading.Thread(target=reader)
    t.start()
    for i in range(20000):
        with stats.lock:
            stats.wait_samples.append(i * 1e-6)
            stats.jobs_done += 1
    stop.set()
    t.join(timeout=30)
    assert not errors
    assert stats.to_dict()["jobs_done"] == 20000


# ---------------------------------------------------------------------------
# Open-loop load generator (stub runner, fake clock)


def _loadgen_server(clock, *, service_s, admission=None, b_max=4):
    return make_server(clock,
                       runner=make_stub_runner(clock, service_s=service_s),
                       b_max=b_max, linger_s=0.05, admission=admission)


def test_open_loop_sustainable_rate():
    clock = FakeClock()
    srv = _loadgen_server(clock, service_s=0.05)  # ~80 jobs/s capacity
    graphs = [make_graph(s) for s in range(32)]
    rep = run_open_loop(srv, graphs, rate=20.0)
    assert rep.done == 32 and rep.rejected == 0 and rep.shed == 0
    assert rep.goodput_jobs_per_s == pytest.approx(20.0, rel=0.3)
    assert rep.wait_p95_s < 0.5
    assert rep.conservation["ok"]


def test_open_loop_overload_without_admission_grows_unbounded():
    """The failure mode admission exists for: at ~3x capacity with no
    intake bound, every job completes eventually but queue waits grow
    with the backlog — wait_p95 far past any reasonable SLO."""
    clock = FakeClock()
    srv = _loadgen_server(clock, service_s=0.4, b_max=2)  # ~5 jobs/s
    graphs = [make_graph(s) for s in range(48)]
    rep = run_open_loop(srv, graphs, rate=15.0)
    assert rep.done == 48 and rep.rejected == 0
    assert rep.wait_p95_s > 2.0, \
        f"overload should blow the queue wait, got {rep.wait_p95_s}"
    assert rep.conservation["ok"]


def test_open_loop_overload_with_admission_holds_slo():
    """Same overload with admission on: excess jobs are rejected with
    retry_after_s and the ADMITTED jobs' wait p95 stays within the
    SLO the controller defends."""
    slo_s = 1.0
    clock = FakeClock()
    srv = _loadgen_server(clock, service_s=0.4, b_max=2,
                          admission=AdmissionConfig(wait_slo_s=slo_s))
    graphs = [make_graph(s) for s in range(48)]
    rep = run_open_loop(srv, graphs, rate=15.0)
    assert rep.rejected > 0, "overload must shed load at intake"
    assert rep.done == 48 - rep.rejected
    assert rep.wait_p95_s <= slo_s * 1.5, \
        (f"admission should bound waits near the SLO, got "
         f"{rep.wait_p95_s}")
    assert rep.conservation["ok"]


def test_saturation_sweep_finds_knee():
    def mk_server():
        clock = FakeClock()
        return _loadgen_server(clock, service_s=0.5, b_max=2,
                               admission=AdmissionConfig(wait_slo_s=1.0))

    graphs = [make_graph(s) for s in range(24)]
    reports, best = saturation_sweep(
        mk_server, lambda: graphs, start_rate=1.0, slo_s=1.0,
        growth=2.0, max_rounds=6)
    assert best is not None
    assert len(reports) > 1
    last = reports[-1]
    # The ramp stopped because the last rate was unsustainable.
    assert (last.rejected > 0 or last.wait_p95_s > 1.0
            or last.goodput_jobs_per_s < 0.9 * last.rate)
    assert best.rate < last.rate


# ---------------------------------------------------------------------------
# THE chaos gate (tier-1 acceptance): seeded randomized fault plan over
# hundreds of jobs -> conservation + surviving-result identity.

CHAOS_PLAN = (
    "submit:transient:p=0.02,seed=11;"
    "pack:transient:p=0.05,seed=12;"
    "dispatch:raise:p=0.03,seed=13;"
    "device:transient:p=0.08,seed=14;"
    "device:raise:p=0.02,seed=15;"
    "unpack:transient:p=0.04,seed=16"
)


def _chaos_run(n_jobs=240, faults=None, admission=None):
    clock = FakeClock()
    srv = make_server(
        clock, runner=make_stub_runner(clock, service_s=0.05),
        b_max=8, linger_s=0.1, max_retries=2, retry_base_s=0.01,
        faults=faults, admission=admission)
    if admission is not None:
        # Seed the service-time estimate so intake pressure rejects
        # deterministically from the first burst.
        srv.submit(make_graph(10**6), job_id="warm")
        srv.step(force=True)
    outcomes = {}
    results = {}
    submitted = []
    k = 0
    while k < n_jobs:
        for _ in range(6):           # burst arrivals between steps
            if k >= n_jobs:
                break
            jid = f"j{k}"
            deadline = 0.12 if k % 5 == 0 else None
            try:
                srv.submit(make_graph(k), job_id=jid,
                           tenant=f"t{k % 7}", deadline_s=deadline)
                submitted.append(jid)
            except (AdmissionReject, InjectedFault):
                outcomes[jid] = "rejected"
            k += 1
        for jid, res in srv.step():
            assert jid not in outcomes, f"{jid} terminated twice"
            outcomes[jid] = "done"
            results[jid] = res
        clock.t += 0.05
    for jid, res in srv.drain():
        assert jid not in outcomes, f"{jid} terminated twice"
        outcomes[jid] = "done"
        results[jid] = res
    for jid, _err in srv.failures:
        assert outcomes.setdefault(jid, "failed") == "failed", \
            f"{jid} terminated twice"
    for jid, _late in srv.shed:
        assert outcomes.setdefault(jid, "shed") == "shed", \
            f"{jid} terminated twice"
    return srv, outcomes, results, submitted


def test_chaos_conservation_and_identity():
    faults = FaultPlan.parse(CHAOS_PLAN)
    srv, outcomes, results, submitted = _chaos_run(
        n_jobs=240, faults=faults,
        admission=AdmissionConfig(wait_slo_s=0.6))
    # Every injection site actually fired at least once — the plan
    # covers the whole dispatch path, not a corner of it.
    fired_sites = {r.site for r in faults.rules if r.fired}
    assert fired_sites == {"submit", "pack", "dispatch", "device",
                           "unpack"}, fired_sites
    # Job conservation: every job terminated exactly once (the double-
    # termination asserts live in _chaos_run) and the ledger balances.
    cons = srv.conservation()
    assert cons["ok"], cons
    assert cons["pending"] == 0
    n_jobs = 240
    assert len(outcomes) == n_jobs, \
        f"{n_jobs - len(outcomes)} jobs vanished"
    by_kind = {k: sum(1 for v in outcomes.values() if v == k)
               for k in ("done", "failed", "rejected", "shed")}
    assert sum(by_kind.values()) == n_jobs
    # The chaos actually exercised every terminal path.
    assert all(by_kind[k] > 0 for k in by_kind), by_kind
    assert srv.stats.retries > 0
    # Surviving tenants bit-identical to a fault-free run: the same
    # submissions through a no-fault no-admission server.
    _, _, clean_results, _ = _chaos_run(n_jobs=240)
    for jid, res in results.items():
        ref = clean_results[jid]
        assert res.modularity == ref.modularity
        assert np.array_equal(res.communities, ref.communities), jid


# ---------------------------------------------------------------------------
# Real-jax fault runs: the stub cannot pin label/Q bit-identity through
# the actual batched driver, so a small chaos run does.


@pytest.fixture(scope="module")
def real_graphs():
    return [synthesize_graph(512, seed=many_seed(21, k)) for k in range(6)]


def test_real_jax_faults_bit_identical_survivors(real_graphs):
    """Transient + permanent faults through the REAL driver: retried /
    isolated jobs return exactly the labels and Q of a fault-free
    serve (the retry re-runs the same deterministic program)."""
    clean = LouvainServer(ServeConfig(b_max=4, linger_s=0.0),
                          clock=FakeClock())
    clean_ids = [clean.submit(g) for g in real_graphs]
    clean_done = dict(clean.drain())

    clock = FakeClock()
    faults = FaultPlan.parse(
        "device:transient:n=1;dispatch:raise:every=3")
    srv = LouvainServer(ServeConfig(b_max=4, linger_s=0.0,
                                    max_retries=2, retry_base_s=0.01),
                        clock=clock, sleep=clock.sleep, faults=faults)
    ids = [srv.submit(g) for g in real_graphs]
    done = dict(srv.drain())
    assert srv.stats.retries >= 1
    assert sum(r.fired for r in faults.rules) >= 2
    assert srv.conservation()["ok"]
    for cid, jid in zip(clean_ids, ids):
        if jid not in done:
            continue  # permanently failed by injection: terminal, fine
        ref = clean_done[cid]
        assert done[jid].modularity == ref.modularity
        assert np.array_equal(done[jid].communities, ref.communities)
    # At least most jobs survive this plan (every=3 fires on batch
    # passages; isolation saves the members).
    assert len(done) >= 4


def test_poison_mid_drain_terminates(real_graphs):
    """ISSUE 11 satellite: a poison job sitting in the queue when
    drain() is called must not wedge the drain — the drain terminates,
    batchmates complete, done+failed == submitted."""
    poison = Graph.from_edges(4, np.array([0]), np.array([1]),
                              weights=np.array([0.0]))  # 2m == 0
    from cuvite_tpu.obs import FlightRecorder, MemoryTraceSink, spans_of
    from cuvite_tpu.utils.trace import Tracer

    sink = MemoryTraceSink()
    rec = FlightRecorder(sink, watch_compiles=False)
    srv = LouvainServer(ServeConfig(b_max=4, linger_s=60.0),
                        clock=FakeClock(), tracer=Tracer(recorder=rec))
    with rec:
        good = [srv.submit(g) for g in real_graphs[:2]]
        bad = srv.submit(poison)
        done = dict(srv.drain())   # linger never fires: pure drain path
    assert set(done) == set(good)
    assert [j for j, _ in srv.failures] == [bad]
    assert srv.pending() == 0
    # The satellite's conservation form:
    assert srv.stats.jobs_done + srv.stats.jobs_failed \
        == srv.stats.jobs_submitted
    drains = spans_of(sink.records, "drain")
    assert len(drains) == 1 and drains[0]["end"] is not None
