"""engine='pallas' as a first-class SPMD citizen (ISSUE 4).

The dryrun_multichip-style gate for the kernelized sharded step: on an
8-virtual-device CPU mesh (conftest forces
--xla_force_host_platform_device_count=8) the Pallas row-argmax kernel
runs in interpret mode INSIDE the shard_map body, under both exchanges,
and must be indistinguishable from the XLA bucketed step:

  * labels bit-identical to bucketed-SPMD on R-MAT 12 (both exchanges),
    with NO downgrade warning (the historical "engine='pallas' is
    single-shard only" fallback is deleted, not routed around);
  * the kernel really is on the traced path (spied call, transposed
    [D, Nb] blocks) — not silently skipped by all-False flags;
  * zero fresh XLA compiles on the second identical run (the bench
    compile-guard precondition: per-phase plan rebuilds must land in the
    same compiled executables).
"""

import logging
import warnings

import jax
import numpy as np
import pytest

from cuvite_tpu.io.generate import generate_rmat
from cuvite_tpu.louvain.driver import louvain_phases


@pytest.fixture(scope="module")
def rmat12():
    return generate_rmat(12, edge_factor=8, seed=3)


# sparse is the production SPMD default and stays tier-1; the replicated
# arm (~25 s) rides tier-2.
@pytest.mark.parametrize(
    "exchange",
    [pytest.param("replicated", marks=pytest.mark.slow), "sparse"])
def test_pallas_spmd_bit_identical_to_bucketed(rmat12, exchange):
    from cuvite_tpu.analysis.meshcheck import assert_mesh_neutral

    ref = louvain_phases(rmat12, nshards=8, engine="bucketed",
                         exchange=exchange)
    with warnings.catch_warnings():
        # The deleted mesh downgrade warned; ANY warning from the pallas
        # run now fails the test (coverage warnings included — rmat-12's
        # degree classes are all kernel-covered).
        warnings.simplefilter("error")
        res = louvain_phases(rmat12, nshards=8, engine="pallas",
                             exchange=exchange)
    # Bit-identity via the ONE shared meshcheck implementation (tier-5
    # M002): identical labels -> the per-phase precise recompute sees
    # identical inputs -> Q exactly equal, not merely close.
    by_engine = {"bucketed": ref, "pallas": res}
    assert_mesh_neutral(
        lambda eng: [(by_engine[eng].communities,
                      by_engine[eng].modularity)],
        ["bucketed", "pallas"], entry=f"pallas_spmd_{exchange}")
    # Coverage accounting rides the result: every rmat-12 degree class
    # fits the kernel ladder (<= PALLAS_MAX_WIDTH).
    assert res.pallas_coverage == 1.0
    assert res.pallas_width_hits
    assert all(n > 0 for n in res.pallas_width_hits.values())
    assert ref.pallas_coverage is None  # bucketed runs carry no coverage


def test_pallas_spmd_routes_rows_through_kernel(monkeypatch):
    """The flags really reach the shard_map body: spy on the kernel entry
    (resolved at trace time from the module attribute) and require the
    transposed [D, Nb] block layout.  A distinct sparse budget keys a
    fresh compiled step, so the spy cannot be bypassed by an executable
    cached from another test."""
    import cuvite_tpu.kernels.row_argmax as rk

    calls = []
    orig = rk.row_argmax_pallas

    def spy(cT, *args, **kw):
        calls.append(tuple(cT.shape))
        return orig(cT, *args, **kw)

    monkeypatch.setattr(rk, "row_argmax_pallas", spy)
    g = generate_rmat(10, edge_factor=8, seed=5)
    res = louvain_phases(g, nshards=4, engine="pallas", exchange="sparse",
                         exchange_budget=333)
    assert calls, "row_argmax_pallas never reached the SPMD step's trace"
    for shape in calls:
        assert len(shape) == 2 and shape[1] % 128 == 0, \
            f"kernel block not in transposed [D, Nb>=128k] layout: {shape}"
    ref = louvain_phases(g, nshards=4, engine="bucketed", exchange="sparse",
                         exchange_budget=333)
    assert np.array_equal(res.communities, ref.communities)


def test_pallas_coloring_counts_class_phases_as_xla():
    """Class-scheduled phases sweep the XLA per-class plans, never the
    kernel — their traversed mass must count as NON-kernelized in the
    run-level coverage (a colored phase 0 carries most of the run's edge
    mass; reporting only the later plain phases would overstate the
    'honesty label' the bench records carry)."""
    g = generate_rmat(10, edge_factor=8, seed=5)
    res = louvain_phases(g, engine="pallas", coloring=4)
    ref = louvain_phases(g, engine="bucketed", coloring=4)
    assert np.array_equal(res.communities, ref.communities)
    assert res.pallas_coverage is not None
    assert res.pallas_coverage < 1.0, \
        "colored phase-0 mass not counted as XLA"


def test_stacked_plan_counts_width_edges_without_kernel_widths():
    """count_width_edges must populate the accounting even when NO width
    qualifies for the kernel (CUVITE_PALLAS_MAX below the smallest bucket
    width) — the driver indexes width_edges whenever engine='pallas', and
    the honest report there is coverage 0, not a crash."""
    from cuvite_tpu.core.distgraph import DistGraph
    from cuvite_tpu.louvain.bucketed import build_stacked_plans

    g = generate_rmat(9, edge_factor=8, seed=7)
    dg = DistGraph.build(g, 2)
    plan = build_stacked_plans(dg, pallas_widths=(),
                               count_width_edges=True)
    assert plan.width_edges is not None
    assert int(plan.width_edges.sum()) == int(g.degrees().sum())
    assert not any(plan.pallas_flags)


@pytest.mark.slow
def test_pallas_spmd_no_recompile_on_second_run(rmat12, caplog):
    """Zero fresh compiles on the second identical pallas-SPMD clustering
    (phases 2+ of run 1 already prove in-run reuse; run 2 pins the
    cross-run cache the bench compile guard relies on).

    Tier-2 (slow): two full rmat-12 pallas-SPMD clusterings (~36 s on the
    tier-1 host). Tier-1 siblings keep the load-bearing coverage:
    test_pallas_spmd_bit_identical_to_bucketed[sparse] runs the same
    compiled program set, and the compile-budget audit (tools/
    compile_audit.py pallas entries) pins the cross-run compile count."""
    louvain_phases(rmat12, nshards=8, engine="pallas", exchange="sparse")
    jax.config.update("jax_log_compiles", True)
    try:
        with caplog.at_level(logging.WARNING, logger="jax"):
            louvain_phases(rmat12, nshards=8, engine="pallas",
                           exchange="sparse")
        compiles = [r for r in caplog.records
                    if "Compiling" in r.getMessage()]
        assert not compiles, (
            f"second pallas-SPMD run recompiled {len(compiles)} "
            "executables: "
            + "; ".join(r.getMessage()[:120] for r in compiles[:4]))
    finally:
        jax.config.update("jax_log_compiles", False)
