"""Sparse ghost exchange: plan correctness, equality with the replicated
exchange, and the budget-overflow retry path.

The sparse path is the analog of the reference's exchangeVertexReqs /
fillRemoteCommunities / updateRemoteCommunities protocol
(/root/reference/louvain.cpp:3118-3264, :2588-2959, :2983-3116); these tests
pin (a) the phase-static routing plan against a numpy oracle, (b) trajectory
equality sparse == replicated == single-shard, and (c) that an undersized
per-peer budget is detected and the driver's retry converges to the same
answer.
"""

import numpy as np
import pytest

from cuvite_tpu.comm.exchange import ExchangePlan
from cuvite_tpu.comm.mesh import make_mesh
from cuvite_tpu.core.distgraph import DistGraph
from cuvite_tpu.io.generate import generate_rgg, generate_rmat
from cuvite_tpu.louvain.driver import PhaseRunner, louvain_phases


@pytest.fixture(scope="module")
def rmat9():
    return generate_rmat(9, edge_factor=8, seed=2)


def test_plan_ghosts_match_oracle(rmat9):
    dg = DistGraph.build(rmat9, 4)
    plan = ExchangePlan.build(dg)
    nvp = dg.nv_pad
    for s, sh in enumerate(dg.shards):
        src = np.asarray(sh.src)
        dst = np.asarray(sh.dst).astype(np.int64)
        real = src < nvp
        d = dst[real]
        expect = np.unique(d[(d < s * nvp) | (d >= (s + 1) * nvp)])
        np.testing.assert_array_equal(plan.ghost_ids[s], expect)
    # send_idx consistency: shard t's row for requester s lists exactly the
    # local indices of s's ghosts owned by t, in ghost order.
    for s in range(dg.nshards):
        gids = plan.ghost_ids[s]
        for t in range(dg.nshards):
            mine = gids[(gids >= t * nvp) & (gids < (t + 1) * nvp)]
            row = plan.send_idx[t, s]
            row = row[row < nvp]
            np.testing.assert_array_equal(row, mine - t * nvp)


def test_remap_preserves_community_lookup(rmat9):
    """comm_ext[dst_remapped] must equal comm_full[dst_global] for every
    real edge — the invariant the whole exchange relies on."""
    dg = DistGraph.build(rmat9, 4)
    plan = ExchangePlan.build(dg)
    nvp = dg.nv_pad
    rng = np.random.default_rng(3)
    comm_full = rng.integers(0, dg.total_padded_vertices,
                             size=dg.total_padded_vertices)
    for s, sh in enumerate(dg.shards):
        src = np.asarray(sh.src)
        dst = np.asarray(sh.dst).astype(np.int64)
        ext = plan.remap_dst(s, src, dst)
        gids = plan.ghost_ids[s]
        ghost_vals = comm_full[gids] if len(gids) else np.zeros(0, np.int64)
        table = np.concatenate([
            comm_full[s * nvp:(s + 1) * nvp],
            ghost_vals,
            np.zeros(plan.ghost_pad - len(gids), dtype=np.int64),
        ])
        real = src < nvp
        np.testing.assert_array_equal(table[ext[real]], comm_full[dst[real]])


@pytest.mark.parametrize("nshards", [2, 8])
def test_sparse_equals_replicated_trajectory(rmat9, nshards):
    mesh = make_mesh(nshards)
    outs = {}
    for exchange in ("replicated", "sparse"):
        dg = DistGraph.build(rmat9, nshards)
        r = PhaseRunner(dg, mesh=mesh, engine="bucketed", exchange=exchange)
        comm = r.comm0
        trace = []
        for _ in range(4):
            out = r._step(None, None, None, comm, r.vdeg, r.constant)
            if len(out) > 3:
                assert not bool(out[3])
            trace.append((np.asarray(out[0]), float(out[1]), int(out[2])))
            comm = out[0]
        outs[exchange] = trace
    for it, ((t1, q1, m1), (t2, q2, m2)) in enumerate(
            zip(outs["replicated"], outs["sparse"])):
        np.testing.assert_array_equal(t1, t2, err_msg=f"iter {it}")
        assert q2 == pytest.approx(q1, abs=1e-5)
        assert m1 == m2


def test_tiny_budget_overflows_and_driver_retries(rmat9):
    nshards = 4
    mesh = make_mesh(nshards)
    dg = DistGraph.build(rmat9, nshards)
    r = PhaseRunner(dg, mesh=mesh, engine="bucketed", budget=1)
    comm = r.comm0
    ovf_seen = False
    # Iteration 1 references no remote communities (comm[v] == v), so sweep
    # a few iterations until cross-shard merges need more than one entry.
    for _ in range(4):
        out = r._step(None, None, None, comm, r.vdeg, r.constant)
        ovf_seen |= bool(out[3])
        comm = out[0]
    assert ovf_seen, "budget=1 should overflow once communities span shards"

    # The driver retries with a grown budget and must land on the same
    # communities as the single-shard run.
    r1 = louvain_phases(rmat9, engine="bucketed")
    rN = louvain_phases(rmat9, nshards=nshards, engine="bucketed",
                        exchange="sparse", exchange_budget=1)
    assert rN.modularity == pytest.approx(r1.modularity, abs=1e-4)


def test_full_run_sparse_rgg_matches_single():
    g = generate_rgg(512, seed=5)
    r1 = louvain_phases(g, engine="bucketed")
    rN = louvain_phases(g, nshards=8, engine="bucketed", exchange="sparse")
    assert rN.modularity == pytest.approx(r1.modularity, abs=1e-4)
    assert rN.num_communities == r1.num_communities


def test_exchange_auto_cutover(monkeypatch):
    """exchange='auto' resolves per phase by graph size: both resolutions
    must produce the same clustering, and the cutover constant must
    actually switch the path — observed by spying on ExchangePlan.build
    (only the sparse path constructs a ghost plan)."""
    from cuvite_tpu.louvain import driver as drv

    plan_builds = []
    orig_build = ExchangePlan.build
    monkeypatch.setattr(
        ExchangePlan, "build",
        staticmethod(lambda dg: (plan_builds.append(1), orig_build(dg))[1]))

    g = generate_rgg(256, seed=3)
    monkeypatch.setattr(drv, "AUTO_SPARSE_MIN_VERTICES", 1)
    r_sparse = louvain_phases(g, nshards=4)      # auto -> sparse everywhere
    assert plan_builds, "auto below the cutover must build ghost plans"
    n_sparse_builds = len(plan_builds)
    monkeypatch.setattr(drv, "AUTO_SPARSE_MIN_VERTICES", 1 << 30)
    r_repl = louvain_phases(g, nshards=4)        # auto -> replicated
    assert len(plan_builds) == n_sparse_builds, \
        "auto above the cutover must not build ghost plans"
    assert np.array_equal(r_sparse.communities, r_repl.communities)
    assert r_sparse.modularity == pytest.approx(r_repl.modularity, abs=1e-6)


def test_sparse_step_lowers_to_three_all_to_all():
    """The packed exchange (VERDICT r2 item 5) must keep the per-iteration
    collective count at 3 — owner-route, reply, ghost pull — not the
    pre-packing 7.  Counted in the jax lowering, where each lax.all_to_all
    appears exactly once (launch count is what ICI latency charges for;
    a CPU-mesh wall clock cannot see it)."""
    import re

    import jax

    g = generate_rmat(10, edge_factor=8, seed=1)
    dg = DistGraph.build(g, 8)
    runner = PhaseRunner(dg, mesh=make_mesh(8), engine="bucketed",
                         exchange="sparse")
    txt = jax.jit(runner._step).lower(
        None, None, None, runner.comm0, runner.vdeg, runner.constant
    ).as_text()
    assert len(re.findall("all_to_all", txt)) == 3
