"""Streaming subsystem tests (ISSUE 17): delta-vs-rebuild bit
equality through the ``apply_delta_slab`` chokepoint (insert-only,
delete-only, mixed — including the delete-miss path and a forced slab
spill), warm-vs-cold label quality inside the golden envelope, stale
warm-start refusal, zero-fresh-compiles on a second same-class delta,
churn generator determinism + provenance round-trip, StreamPool
LRU/ledger accounting (stub sessions, no jax), and the daemon
``delta`` verb over a unix socket.

The rebuild oracle is the canonical-form contract itself: maintain the
undirected pair -> weight dict on the host, rebuild a fresh
``DistGraph`` slab from it, and demand the resident session's
(src, dst, w) arrays are BIT-equal — same class, same row order, same
f32 weights.  Churn weights are small dyadic integers (1..8) so f32
coalescing is exact on both sides.
"""

import json
import os
import types

import numpy as np
import pytest

from cuvite_tpu.core.distgraph import DistGraph
from cuvite_tpu.core.graph import Graph
from cuvite_tpu.obs.compile_watch import CompileWatcher
from cuvite_tpu.serve import LouvainServer, ServeConfig, ServeDaemon
from cuvite_tpu.serve.queue import StreamPool
from cuvite_tpu.stream import DeltaBatch, StreamSession
from cuvite_tpu.workloads.golden import (
    check_envelope,
    envelope_from_measurement,
)
from cuvite_tpu.workloads.synth import (
    churn_batches,
    load_churn,
    synthesize_graph,
    write_churn,
)

from test_serve_daemon import DaemonClient, stub_runner

NV = 300


def _draw_edges(seed: int, n: int, nv: int = NV) -> dict:
    """Undirected pair -> summed weight dict, dyadic int weights."""
    rng = np.random.default_rng(seed)
    src = rng.integers(0, nv, 2 * n)
    dst = rng.integers(0, nv, 2 * n)
    w = rng.integers(1, 8, 2 * n).astype(np.float64)
    edges: dict = {}
    for u, v, ww in zip(src, dst, w):
        if u == v:
            continue
        k = (min(u, v), max(u, v))
        edges[k] = edges.get(k, 0.0) + ww
        if len(edges) >= n:
            break
    return edges


def _graph_from(edges: dict, nv: int = NV) -> Graph:
    ks = sorted(edges)
    src = np.array([k[0] for k in ks], dtype=np.int64)
    dst = np.array([k[1] for k in ks], dtype=np.int64)
    w = np.array([edges[k] for k in ks], dtype=np.float64)
    return Graph.from_edges(nv, src, dst, w)


def _oracle_apply(edges: dict, *, dels=(), ins=()) -> dict:
    """The host-side twin of apply_delta: retire deleted pairs, then
    coalesce inserted pairs by weight sum (misses tolerated)."""
    out = dict(edges)
    for u, v in dels:
        out.pop((min(u, v), max(u, v)), None)
    for u, v, ww in ins:
        k = (min(u, v), max(u, v))
        out[k] = out.get(k, 0.0) + ww
    return out


def _assert_slab_equals_rebuild(sess: StreamSession, edges: dict,
                                nv: int = NV) -> None:
    """Bit-equality of the resident slab against a cold rebuild of the
    same edge set (class, row order, values)."""
    g2 = _graph_from(edges, nv)
    dg2 = DistGraph.build(g2, 1, min_nv_pad=4096, min_ne_pad=16384)
    sh = dg2.shards[0]
    assert (dg2.nv_pad, dg2.ne_pad) == (sess.nv_pad, sess.ne_pad)
    assert sh.n_real_edges == sess.ne
    assert np.array_equal(np.asarray(sess.src),
                          np.asarray(sh.src).astype(np.int32))
    assert np.array_equal(np.asarray(sess.dst),
                          np.asarray(sh.dst).astype(np.int32))
    assert np.array_equal(np.asarray(sess.w),
                          np.asarray(sh.w).astype(np.float32))
    assert abs(sess.tw2 - g2.total_edge_weight_twice()) < 1e-6


@pytest.fixture(scope="module")
def base_edges() -> dict:
    return _draw_edges(7, 1200)


@pytest.fixture
def session(base_edges):
    return StreamSession.from_graph(_graph_from(base_edges))


# ---------------------------------------------------------------------------
# delta-vs-rebuild bit equality (the acceptance contract)
# ---------------------------------------------------------------------------

def test_delta_insert_only_bit_equal(session, base_edges):
    rng = np.random.default_rng(11)
    iu = rng.integers(0, NV, 50)
    iv = rng.integers(0, NV, 50)
    iw = rng.integers(1, 8, 50).astype(np.float64)
    keep = iu != iv
    iu, iv, iw = iu[keep], iv[keep], iw[keep]
    batch = DeltaBatch.from_edits(NV, ins_src=iu, ins_dst=iv, ins_w=iw)
    info = session.apply_delta(batch)
    assert info["n_del"] == 0 and info["n_ins"] == 2 * len(iu)
    _assert_slab_equals_rebuild(
        session, _oracle_apply(base_edges, ins=zip(iu, iv, iw)))


def test_delta_delete_only_bit_equal(session, base_edges):
    rng = np.random.default_rng(13)
    keys = sorted(base_edges)
    dels = [keys[i] for i in rng.choice(len(keys), 40, replace=False)]
    batch = DeltaBatch.from_edits(NV, del_src=[k[0] for k in dels],
                                  del_dst=[k[1] for k in dels])
    info = session.apply_delta(batch)
    assert info["n_ins"] == 0
    assert info["n_del_hit"] == info["n_del"] == 2 * len(dels)
    _assert_slab_equals_rebuild(session, _oracle_apply(base_edges,
                                                       dels=dels))


def test_delta_mixed_bit_equal_and_label_determinism(session, base_edges):
    """Mixed batch with (a) an insert that coalesces onto a resident
    pair, (b) a delete of a pair that does not exist (miss path), and
    (c) fresh inserts — then the cold labels on the delta'd slab must
    equal the cold labels on a rebuilt-from-scratch session."""
    rng = np.random.default_rng(17)
    keys = sorted(base_edges)
    dels = [keys[i] for i in rng.choice(len(keys), 25, replace=False)]
    missing = next((u, v) for u in range(NV) for v in range(u + 1, NV)
                   if (u, v) not in base_edges and (u, v) not in dels)
    dels_req = dels + [missing]
    resident = keys[3]  # coalesce target: already in the slab
    iu = np.concatenate([rng.integers(0, NV, 30), [resident[0]]])
    iv = np.concatenate([rng.integers(0, NV, 30), [resident[1]]])
    iw = np.concatenate([rng.integers(1, 8, 30).astype(np.float64),
                         [2.0]])
    keep = iu != iv
    iu, iv, iw = iu[keep], iv[keep], iw[keep]
    batch = DeltaBatch.from_edits(
        NV, ins_src=iu, ins_dst=iv, ins_w=iw,
        del_src=[k[0] for k in dels_req],
        del_dst=[k[1] for k in dels_req])
    info = session.apply_delta(batch)
    # the phantom delete misses; the real ones all hit (mirrored count)
    assert info["n_del"] == 2 * len(dels_req)
    assert info["n_del_hit"] == 2 * len(dels)
    after = _oracle_apply(base_edges, dels=dels_req,
                          ins=zip(iu, iv, iw))
    _assert_slab_equals_rebuild(session, after)
    # identical slabs => identical cold clustering, bit for bit
    r_delta = session.recluster(warm="cold")
    r_rebuild = StreamSession.from_graph(
        _graph_from(after)).recluster(warm="cold")
    assert np.array_equal(np.asarray(r_delta.communities),
                          np.asarray(r_rebuild.communities))
    assert abs(r_delta.modularity - r_rebuild.modularity) < 1e-9


def _session_at_class(graph, min_ne_pad):
    """from_graph at an explicit ne_pad floor (a small class keeps the
    spill test off the expensive 16k/32k-row compiles)."""
    import jax.numpy as jnp

    from cuvite_tpu.utils.checkpoint import graph_fingerprint

    dg = DistGraph.build(graph, 1, min_nv_pad=4096,
                         min_ne_pad=min_ne_pad)
    sh = dg.shards[0]
    return StreamSession(
        nv=graph.num_vertices, nv_pad=dg.nv_pad, ne_pad=dg.ne_pad,
        ne=sh.n_real_edges,
        src=jnp.asarray(np.asarray(sh.src).astype(np.int32)),
        dst=jnp.asarray(np.asarray(sh.dst).astype(np.int32)),
        w=jnp.asarray(np.asarray(sh.w).astype(np.float32)),
        tw2=graph.total_edge_weight_twice(), policy=graph.policy,
        fingerprint=graph_fingerprint(graph))


def test_delta_spill_grows_class_and_stays_bit_equal():
    """A batch overflowing the padding headroom must reshape to the
    next pow2 class (grow_slab path) and still match the rebuild."""
    edges = _draw_edges(23, 2040)
    assert 4000 < 2 * len(edges) <= 4096
    sess = _session_at_class(_graph_from(edges), min_ne_pad=4096)
    assert sess.ne_pad == 4096
    rng = np.random.default_rng(29)
    fresh = []
    while len(fresh) < 60:
        u, v = (int(x) for x in rng.integers(0, NV, 2))
        k = (min(u, v), max(u, v))
        if u != v and k not in edges and k not in dict(fresh):
            fresh.append((k, float(rng.integers(1, 8))))
    iu = [k[0] for k, _ in fresh]
    iv = [k[1] for k, _ in fresh]
    iw = [w for _, w in fresh]
    info = sess.apply_delta(
        DeltaBatch.from_edits(NV, ins_src=iu, ins_dst=iv, ins_w=iw))
    assert sess.ne_pad == 8192, "spill must grow the slab class"
    assert info["ne"] == sess.ne
    after = _oracle_apply(edges, ins=zip(iu, iv, iw))
    g2 = _graph_from(after)
    dg2 = DistGraph.build(g2, 1, min_nv_pad=4096, min_ne_pad=4096)
    sh = dg2.shards[0]
    assert dg2.ne_pad == sess.ne_pad and sh.n_real_edges == sess.ne
    assert np.array_equal(np.asarray(sess.src),
                          np.asarray(sh.src).astype(np.int32))
    assert np.array_equal(np.asarray(sess.dst),
                          np.asarray(sh.dst).astype(np.int32))
    assert np.array_equal(np.asarray(sess.w),
                          np.asarray(sh.w).astype(np.float32))


# ---------------------------------------------------------------------------
# warm-start re-clustering
# ---------------------------------------------------------------------------

def test_warm_start_within_golden_envelope_then_zero_compiles():
    """On a planted-community graph, warm-start labels after churn must
    land inside the golden envelope derived from the cold full re-run
    of the SAME post-churn graph (Q_TOL/PHASE_SLACK/COMM_REL) — and the
    NEXT same-class delta batch must then run entirely on cached
    executables (the steady-state zero-fresh-compiles contract)."""
    g = synthesize_graph(6000, seed=3, mu=0.12)
    b0, b1 = churn_batches(g, frac=0.01, seed=5, batches=2)

    def to_batch(arrs):
        return DeltaBatch.from_edits(
            g.num_vertices,
            ins_src=arrs["ins_src"], ins_dst=arrs["ins_dst"],
            ins_w=arrs["ins_w"], del_src=arrs["del_src"],
            del_dst=arrs["del_dst"])

    batch = to_batch(b0)
    warm_sess = StreamSession.from_graph(g)
    warm_sess.recluster(warm="cold")          # seed resident labels
    info = warm_sess.apply_delta(batch)
    assert 0.0 < info["frontier_frac"] <= 1.0
    warm = warm_sess.recluster(warm="labels")

    cold_sess = StreamSession.from_graph(g)
    cold_sess.apply_delta(batch)
    cold = cold_sess.recluster(warm="cold")

    env = envelope_from_measurement({
        "modularity": cold.modularity, "phases": len(cold.phases),
        "communities": cold.num_communities})

    def degradations(res):
        # The envelope guards against DEGRADATION; a warm start that
        # lands in a better optimum (Q above the band) is not a
        # regression, so the Q check is one-sided here.
        problems = check_envelope(env, {
            "modularity": res.modularity, "phases": len(res.phases),
            "communities": res.num_communities})
        return [p for p in problems
                if not (p.startswith("Q=")
                        and res.modularity >= cold.modularity)]

    assert not degradations(warm), degradations(warm)
    plp = warm_sess.recluster(warm="plp")
    assert not degradations(plp), degradations(plp)

    # one cycle warmed every executable: the second same-class batch
    # (same pow2 slot class by construction) compiles NOTHING
    with CompileWatcher() as w:
        warm_sess.apply_delta(to_batch(b1))
    assert not w.compiles, w.compiles
    with CompileWatcher() as w:
        warm_sess.recluster(warm="labels")
    assert not w.compiles, w.compiles


def test_stale_warm_start_refused(session, base_edges):
    with pytest.raises(ValueError, match="needs resident labels"):
        session.recluster(warm="labels")
    res = session.recluster(warm="cold")
    fp_before = session.fingerprint
    session.apply_delta(DeltaBatch.from_edits(
        NV, ins_src=[1], ins_dst=[2], ins_w=[1.0]))
    # labels stamped with a fingerprint from another lineage: refuse
    with pytest.raises(ValueError, match="stale warm-start refused"):
        session.recluster(warm="labels",
                          warm_labels=np.asarray(res.communities),
                          warm_fingerprint=0xDEAD)
    # the true pre-delta lineage fingerprint is accepted
    ok = session.recluster(warm="labels",
                           warm_labels=np.asarray(res.communities),
                           warm_fingerprint=fp_before)
    assert ok.num_communities >= 1


# ---------------------------------------------------------------------------
# churn generator (workloads satellite)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def churn_graph():
    return synthesize_graph(2000, seed=3)


def test_churn_deterministic_and_disjoint_deletes(churn_graph):
    g = churn_graph
    a = churn_batches(g, frac=0.05, seed=9, batches=2)
    b = churn_batches(g, frac=0.05, seed=9, batches=2)
    for ba, bb in zip(a, b):
        for k in ba:
            assert np.array_equal(ba[k], bb[k]), k
    c = churn_batches(g, frac=0.05, seed=10, batches=2)[0]
    assert not np.array_equal(a[0]["ins_src"], c["ins_src"])
    # deletes sampled without replacement ACROSS batches
    d0 = set(zip(a[0]["del_src"], a[0]["del_dst"]))
    d1 = set(zip(a[1]["del_src"], a[1]["del_dst"]))
    assert not d0 & d1
    for ba in a:
        assert np.all((ba["ins_w"] >= 1.0) & (ba["ins_w"] <= 8.0))
        assert np.all(ba["ins_w"] == np.round(ba["ins_w"]))
        assert np.all(ba["ins_src"] != ba["ins_dst"])


def test_churn_provenance_round_trip(tmp_path, churn_graph):
    g = churn_graph
    out = str(tmp_path / "g")
    payload = write_churn(out, g, frac=0.05, seed=9, batches=2)
    assert payload["source"] == "churn" and payload["sha256"]
    with open(out + ".churn.provenance.json", encoding="utf-8") as f:
        on_disk = json.load(f)
    assert on_disk["churn_seed"] == 9 and on_disk["batches"] == 2
    loaded = load_churn(out)
    fresh = churn_batches(g, frac=0.05, seed=9, batches=2)
    assert len(loaded) == 2
    for bl, bf in zip(loaded, fresh):
        for k in bf:
            assert np.array_equal(bl[k], bf[k]), k


# ---------------------------------------------------------------------------
# StreamPool (serve satellite) — stub sessions, no jax
# ---------------------------------------------------------------------------

class _StubSess:
    def __init__(self, graph, tracer=None, nbytes=1000):
        self.nbytes = nbytes
        self.dropped = 0

    def hbm_bytes(self):
        return self.nbytes

    def drop(self):
        self.dropped += 1


def _pool(budget, nbytes=1000):
    made = []

    def factory(graph, tracer=None):
        s = _StubSess(graph, tracer, nbytes=nbytes)
        made.append(s)
        return s

    return StreamPool(budget, factory=factory), made


def test_pool_lru_eviction_and_conservation():
    pool, made = _pool(2500)
    sa = pool.admit("a", None)
    sb = pool.admit("b", None)
    assert pool.conservation()["ok"]
    pool.admit("c", None)               # 3000 > 2500: evict oldest (a)
    assert pool.get("a") is None and sa.dropped == 1
    assert pool.get("b") is sb          # touch: b is now newest
    pool.admit("d", None)               # evicts c, not the touched b
    assert pool.get("c") is None and pool.get("b") is sb
    cons = pool.conservation()
    assert cons["ok"] and cons["resident"] == 2 and cons["evicted"] == 2
    assert cons["bytes_resident"] == 2000
    pool.clear()
    cons = pool.conservation()
    assert cons["ok"] and cons["resident"] == 0
    assert cons["bytes_resident"] == 0
    assert all(s.dropped == 1 for s in made)


def test_pool_replace_and_oversized_sole_tenant():
    pool, _ = _pool(2500)
    s1 = pool.admit("t", None)
    s2 = pool.admit("t", None)          # replace, not a second resident
    assert s2 is not s1 and s1.dropped == 1
    cons = pool.conservation()
    assert cons["ok"] and cons["resident"] == 1 and cons["evicted"] == 1
    # a session larger than the whole budget stays resident when alone
    big_pool, _ = _pool(500, nbytes=1000)
    sb = big_pool.admit("big", None)
    assert big_pool.get("big") is sb
    assert big_pool.conservation()["ok"]


def test_pool_reledger_after_spill_evicts_to_fit():
    pool, _ = _pool(2500)
    pool.admit("a", None)
    sb = pool.admit("b", None)
    sb.nbytes = 2000                    # b's slab class grew (spill)
    pool.reledger("b")
    assert pool.get("a") is None and pool.get("b") is sb
    cons = pool.conservation()
    assert cons["ok"] and cons["bytes_resident"] == 2000
    pool.reledger("ghost")              # evicted-mid-op tenants: no-op
    assert pool.conservation()["ok"]


# ---------------------------------------------------------------------------
# daemon `delta` verb (wire protocol)
# ---------------------------------------------------------------------------

@pytest.fixture
def stream_daemon(tmp_path):
    srv = LouvainServer(
        ServeConfig(b_max=2, linger_s=0.01, engine="fused",
                    stream_budget_bytes=10_000),
        runner=stub_runner,
        stream_factory=lambda graph, tracer=None: _StreamStub(graph))
    d = ServeDaemon(srv, sock_path=str(tmp_path / "serve.sock"),
                    poll_s=0.005)
    d.start()
    yield d
    if not d._done.is_set():
        d.request_drain()
        d.serve_forever(timeout=30.0)


class _StreamStub:
    """Daemon-facing stub: real DeltaBatch in, canned info out."""

    def __init__(self, graph):
        self.nv = graph.num_vertices
        self.ne = graph.num_edges
        self._labels = None

    def hbm_bytes(self):
        return 1000

    def labels(self):
        return self._labels

    def apply_delta(self, batch):
        self.ne = self.ne + batch.n_ins
        return {"n_ins": batch.n_ins, "n_del": batch.n_del,
                "n_del_hit": 0, "ne": self.ne, "frontier_frac": 0.25,
                "wall_s": 0.0}

    def recluster(self, warm="labels", **kw):
        self._labels = np.zeros(self.nv, dtype=np.int64)
        return types.SimpleNamespace(
            modularity=0.5, num_communities=2, phases=[1],
            total_iterations=3, communities=self._labels)


def test_daemon_delta_verb(stream_daemon, tmp_path):
    c = DaemonClient(str(tmp_path / "serve.sock"))
    gspec = {"nv": 8, "src": [0, 1, 2, 3], "dst": [1, 2, 3, 4]}
    try:
        # first contact with no resident session and no graph: refused
        r = c.call({"op": "delta", "tenant": "t0", "ins": [[0, 1]]})
        assert not r["ok"] and r["resident"] is False
        assert "upload" in r["error"]
        # upload + delta in one request ("resident" reports the
        # pre-request state: this admit is a fresh upload)
        r = c.call({"op": "delta", "tenant": "t0", "graph": gspec,
                    "ins": [[0, 5], [1, 6, 2.0]], "del": [[0, 1]]})
        assert r["ok"] and r["resident"] is False
        assert r["delta"]["n_ins"] == 4 and r["delta"]["n_del"] == 2
        assert r["delta"]["frontier_frac"] == 0.25
        # resident now: a bare delta needs no graph spec
        r = c.call({"op": "delta", "tenant": "t0", "ins": [[2, 7]],
                    "recluster": True, "warm": "labels"})
        assert r["ok"] and r["resident"] is True and "recluster" in r
        # no resident labels yet: warm request downgrades loudly
        assert r["recluster"]["warm"] == "cold"
        r = c.call({"op": "delta", "tenant": "t0", "ins": [[3, 7]],
                    "recluster": True, "warm": "labels",
                    "labels": True})
        assert r["recluster"]["warm"] == "labels"
        assert len(r["recluster"]["labels"]) == 8
        # a second tenant gets its own session
        r = c.call({"op": "delta", "tenant": "t1", "graph": gspec,
                    "ins": [[0, 2]]})
        assert r["ok"] and r["resident"] is False
        assert stream_daemon.server.streams.to_dict()["resident"] == 2
        # a draining daemon admits no further deltas: either an
        # explicit refusal or (when the idle drain wins the race and
        # closes the socket first) a dropped connection
        stream_daemon.request_drain()
        try:
            r = c.call({"op": "delta", "tenant": "t0", "ins": [[4, 7]]})
            refused = (not r["ok"]) and bool(r.get("draining"))
        except (ConnectionResetError, BrokenPipeError, AssertionError):
            refused = True
        assert refused
    finally:
        c.close()
    stream_daemon.serve_forever(timeout=30.0)
    # shutdown released every resident session (conservation holds)
    cons = stream_daemon.server.streams.conservation()
    assert cons["ok"] and cons["resident"] == 0
