"""Multi-host launch path: 2 processes x 4 virtual CPU devices each.

The TPU analog of the reference's "multi-node without a cluster" practice
(oversubscribed MPI ranks on one node, README:48-53): two OS processes
connect through jax.distributed.initialize over localhost, form one global
8-device mesh, and must produce communities bit-identical to a
single-process run of the same graph.
"""

import ast
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import os, sys
proc = int(sys.argv[1]); n = int(sys.argv[2]); port = sys.argv[3]
out_dir = sys.argv[4]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
from cuvite_tpu.comm.multihost import initialize, is_distributed
initialize(coordinator=f"127.0.0.1:{port}", num_processes=n, process_id=proc)
assert is_distributed()
assert len(jax.devices()) == 4 * n, jax.devices()

import numpy as np
from cuvite_tpu.core.graph import Graph
from cuvite_tpu.comm.mesh import make_mesh
from cuvite_tpu.louvain.driver import louvain_phases

edges = np.load(os.path.join(out_dir, "edges.npy"))
g = Graph.from_edges(int(edges.max()) + 1, edges[:, 0], edges[:, 1])
mesh = make_mesh(4 * n)
res = louvain_phases(g, nshards=4 * n, mesh=mesh)
# Every process holds the full gathered labels; each writes its own copy so
# the parent can assert cross-process agreement.
np.save(os.path.join(out_dir, f"comm.{proc}.npy"), res.communities)
with open(os.path.join(out_dir, f"mod.{proc}"), "w") as f:
    f.write(repr(float(res.modularity)))
print(f"proc {proc}: OK Q={res.modularity:.6f}")
"""


DV_WORKER = r"""
import os, sys
proc = int(sys.argv[1]); n = int(sys.argv[2]); port = sys.argv[3]
out_dir = sys.argv[4]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
from cuvite_tpu.comm.multihost import initialize
initialize(coordinator=f"127.0.0.1:{port}", num_processes=n, process_id=proc)

import numpy as np
from cuvite_tpu.io.dist_ingest import DistVite
from cuvite_tpu.louvain.driver import louvain_phases

path = os.path.join(out_dir, "g.bin")
dv = DistVite.load(path, 4 * n)
# Per-host ingest really was partial: remote shards hold no edge arrays.
remote = [s for s in range(4 * n) if not (dv.local_lo <= s < dv.local_hi)]
assert remote and all(dv.shards[s].src is None for s in remote)
res = louvain_phases(dv)
np.save(os.path.join(out_dir, f"dvcomm.{proc}.npy"), res.communities)
with open(os.path.join(out_dir, f"dvmod.{proc}"), "w") as f:
    f.write(repr(float(res.modularity)))
# Distributed coloring on the per-host partition (VERDICT r4 item 7):
# per-round owned-slice allgather + per-class stacked plans.
resc = louvain_phases(dv, coloring=2)
np.save(os.path.join(out_dir, f"dvcomm_c.{proc}.npy"), resc.communities)
print(f"proc {proc}: OK Q={res.modularity:.6f} Qc={resc.modularity:.6f}")
"""


DV4_WORKER = r"""
import os, sys
proc = int(sys.argv[1]); n = int(sys.argv[2]); port = sys.argv[3]
out_dir = sys.argv[4]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
from cuvite_tpu.utils.compile_cache import enable_compile_cache
enable_compile_cache()  # 4 processes share one content-addressed cache
from cuvite_tpu.comm.multihost import initialize
initialize(coordinator=f"127.0.0.1:{port}", num_processes=n, process_id=proc)

import numpy as np
from cuvite_tpu.io.dist_ingest import DistVite
from cuvite_tpu.louvain.driver import louvain_phases

nsh = 2 * n
dv = DistVite.load(os.path.join(out_dir, "g.bin"), nsh)
# Per-process ghost-count sanity at a scale where routing is non-trivial:
# every local shard must reference ghosts (rmat-15 is far from block
# diagonal), and remote shards must hold no edge arrays at all.
ghost_counts = {}
for s in range(dv.local_lo, dv.local_hi):
    sh = dv.shards[s]
    real = np.asarray(sh.src) < dv.nv_pad
    d = np.asarray(sh.dst)[real].astype(np.int64)
    owned = (d >= s * dv.nv_pad) & (d < (s + 1) * dv.nv_pad)
    ghost_counts[s] = int(len(np.unique(d[~owned])))
    assert 0 < ghost_counts[s] < dv.total_padded_vertices, ghost_counts
remote = [s for s in range(nsh) if not (dv.local_lo <= s < dv.local_hi)]
assert remote and all(dv.shards[s].src is None for s in remote)

res = louvain_phases(dv)
np.save(os.path.join(out_dir, f"dv4comm.{proc}.npy"), res.communities)
with open(os.path.join(out_dir, f"dv4info.{proc}"), "w") as f:
    f.write(repr((float(res.modularity), ghost_counts)))
print(f"proc {proc}: OK Q={res.modularity:.6f} ghosts={ghost_counts}")
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_run_matches_single(tmp_path):
    from conftest import karate_edges

    _, s, d = karate_edges()
    np.save(tmp_path / "edges.npy", np.stack([s, d], axis=1))
    (tmp_path / "worker.py").write_text(WORKER)
    port = _free_port()
    env = dict(os.environ, PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(tmp_path / "worker.py"), str(i), "2",
             str(port), str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    for p, o in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{o[-3000:]}"

    c0 = np.load(tmp_path / "comm.0.npy")
    c1 = np.load(tmp_path / "comm.1.npy")
    assert np.array_equal(c0, c1), "processes disagree on communities"

    # Single-process oracle on the same 8-device virtual mesh.
    from cuvite_tpu.core.graph import Graph
    from cuvite_tpu.louvain.driver import louvain_phases

    edges = np.load(tmp_path / "edges.npy")
    g = Graph.from_edges(int(edges.max()) + 1, edges[:, 0], edges[:, 1])
    ref = louvain_phases(g, nshards=8)
    assert np.array_equal(c0, ref.communities), \
        "2-process run differs from single-process 8-shard run"
    q0 = float(open(tmp_path / "mod.0").read())
    assert abs(q0 - ref.modularity) < 1e-6


def test_four_process_dist_ingest_rmat15(tmp_path):
    """4 processes x 2 virtual devices, per-host sharded ingest of R-MAT 15
    (~1M directed edges): ghost routing is non-trivial on every shard
    (asserted per process), each process range-reads only its 2 shards,
    and the 8-shard distributed clustering is bit-identical to the
    single-process full-ingest run — the reference's oversubscribed-ranks
    practice at benchmark-family scale (/root/reference/README:48-53)."""
    ncpu = os.cpu_count() or 1
    if ncpu < 4:
        # Gloo's kv-store wait and the coordination-service shutdown
        # barrier have fixed ~30 s deadlines with no knob; with fewer
        # cores than workers one process is starved past them whenever a
        # compile burst hits, and retrying only tunes around the symptom
        # (VERDICT r5 weak #1).  The 2-process variants below still cover
        # the dist-ingest path on this host.
        pytest.skip(f"needs >=4 cores for 4 concurrent workers (host has "
                    f"{ncpu}); scheduler starvation trips the fixed ~30s "
                    "coordination barriers")
    from cuvite_tpu.io.generate import generate_rmat
    from cuvite_tpu.io.vite import write_vite
    from cuvite_tpu.louvain.driver import louvain_phases

    g = generate_rmat(15, edge_factor=16, seed=1)
    write_vite(str(tmp_path / "g.bin"), g)
    # Pre-warm IN-PROCESS before spawning workers: the single-process
    # 8-shard reference run below populates the shared persistent
    # compile cache (conftest enabled it), so the 4 cold workers spend
    # their barrier deadlines loading cached executables, not compiling.
    ref = louvain_phases(g, nshards=8)
    (tmp_path / "worker.py").write_text(DV4_WORKER)
    env = dict(os.environ, PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)
    nproc = 4

    def launch():
        port = _free_port()
        procs = [
            subprocess.Popen(
                [sys.executable, str(tmp_path / "worker.py"), str(i),
                 str(nproc), str(port), str(tmp_path)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True,
            )
            for i in range(nproc)
        ]
        try:
            return procs, [p.communicate(timeout=840)[0] for p in procs]
        except subprocess.TimeoutExpired:
            # Kill the whole team: a leaked worker would burn the 1-core
            # host for the rest of the suite.
            for p in procs:
                if p.poll() is None:
                    p.kill()
            raise

    def results_complete():
        return all((tmp_path / f"dv4comm.{i}.npy").exists()
                   and (tmp_path / f"dv4info.{i}").exists()
                   for i in range(nproc))

    procs, outs = launch()
    # Up to 2 retries (the pre-r5 count: the extra retry was tuning
    # around the cold-cache symptom the in-process pre-warm above now
    # removes — VERDICT r5 weak #1): the remaining retry covers genuine
    # scheduler noise, not systematic compile-burst starvation.
    for _retry in range(2):
        if results_complete() or not any(
                "DEADLINE_EXCEEDED" in o for o in outs):
            break
        # Gloo's kv-store wait and the coordination-service shutdown
        # barrier have fixed ~30 s deadlines with no knob; on this
        # 1-core host a full-suite run (other xdist workers compiling)
        # can starve one of the 4 processes past them.  Scheduler
        # artifact, not a correctness signal — retry (bounded by the
        # loop above) on the specific signature, after letting the
        # compile burst pass.  A genuine failure (assertion, crash)
        # does not match and still fails below.
        time.sleep(45)
        for i in range(nproc):
            (tmp_path / f"dv4comm.{i}.npy").unlink(missing_ok=True)
            (tmp_path / f"dv4info.{i}").unlink(missing_ok=True)
        procs, outs = launch()
    if not results_complete():
        # Returncodes only matter when a worker ALSO failed to deliver
        # results (same leniency on every attempt).
        for p, o in zip(procs, outs):
            assert p.returncode == 0, f"worker failed:\n{o[-3000:]}"
    # Every worker wrote its results BEFORE jax shutdown, so a nonzero
    # exit from a contention-starved shutdown barrier after that point
    # does not invalidate the run — the bit-identity assertions below
    # are the test, and they run against complete result sets only.
    # Keep such teardown crashes VISIBLE in CI output though (ADVICE r4).
    for i, (p, o) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            print(f"# worker {i} exited rc={p.returncode} after writing "
                  f"results (teardown crash?):\n{o[-1500:]}")
    assert results_complete(), (
        "workers exited without writing results:\n"
        + "\n---\n".join(o[-1200:] for o in outs))

    comms = [np.load(tmp_path / f"dv4comm.{i}.npy") for i in range(nproc)]
    for c in comms[1:]:
        assert np.array_equal(comms[0], c), "processes disagree"
    infos = [ast.literal_eval(open(tmp_path / f"dv4info.{i}").read())
             for i in range(nproc)]
    shards_seen = sorted(s for _, gc in infos for s in gc)
    assert shards_seen == list(range(8)), shards_seen

    # ref was computed up front (it doubles as the compile-cache pre-warm).
    assert np.array_equal(comms[0], ref.communities), \
        "4-process dist-ingest differs from single-process full ingest"
    assert abs(infos[0][0] - ref.modularity) < 1e-6


@pytest.mark.slow
def test_two_process_dist_ingest(tmp_path):
    """2-process per-host sharded ingest: each process range-reads only its
    4 shards' edges (remote shards carry no arrays), yet the clustering
    matches the single-process full-ingest run.

    slow: ~50 s — the two-process protocol itself stays tier-1 via
    test_two_process_run_matches_single."""
    from conftest import karate_edges

    from cuvite_tpu.core.graph import Graph
    from cuvite_tpu.io.vite import write_vite
    from cuvite_tpu.louvain.driver import louvain_phases

    _, s, d = karate_edges()
    g = Graph.from_edges(34, s, d)
    write_vite(str(tmp_path / "g.bin"), g)
    (tmp_path / "worker.py").write_text(DV_WORKER)
    port = _free_port()
    env = dict(os.environ, PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(tmp_path / "worker.py"), str(i), "2",
             str(port), str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    for p, o in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{o[-3000:]}"

    c0 = np.load(tmp_path / "dvcomm.0.npy")
    c1 = np.load(tmp_path / "dvcomm.1.npy")
    assert np.array_equal(c0, c1)
    ref = louvain_phases(g, nshards=8)
    assert np.array_equal(c0, ref.communities)
    q0 = float(open(tmp_path / "dvmod.0").read())
    assert abs(q0 - ref.modularity) < 1e-6
    # Distributed-coloring run: processes agree and match full ingest.
    cc0 = np.load(tmp_path / "dvcomm_c.0.npy")
    cc1 = np.load(tmp_path / "dvcomm_c.1.npy")
    assert np.array_equal(cc0, cc1)
    refc = louvain_phases(g, nshards=8, coloring=2)
    assert np.array_equal(cc0, refc.communities)
