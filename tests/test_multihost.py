"""Multi-host launch path: 2 processes x 4 virtual CPU devices each.

The TPU analog of the reference's "multi-node without a cluster" practice
(oversubscribed MPI ranks on one node, README:48-53): two OS processes
connect through jax.distributed.initialize over localhost, form one global
8-device mesh, and must produce communities bit-identical to a
single-process run of the same graph.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import os, sys
proc = int(sys.argv[1]); n = int(sys.argv[2]); port = sys.argv[3]
out_dir = sys.argv[4]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
from cuvite_tpu.comm.multihost import initialize, is_distributed
initialize(coordinator=f"127.0.0.1:{port}", num_processes=n, process_id=proc)
assert is_distributed()
assert len(jax.devices()) == 4 * n, jax.devices()

import numpy as np
from cuvite_tpu.core.graph import Graph
from cuvite_tpu.comm.mesh import make_mesh
from cuvite_tpu.louvain.driver import louvain_phases

edges = np.load(os.path.join(out_dir, "edges.npy"))
g = Graph.from_edges(int(edges.max()) + 1, edges[:, 0], edges[:, 1])
mesh = make_mesh(4 * n)
res = louvain_phases(g, nshards=4 * n, mesh=mesh)
# Every process holds the full gathered labels; each writes its own copy so
# the parent can assert cross-process agreement.
np.save(os.path.join(out_dir, f"comm.{proc}.npy"), res.communities)
with open(os.path.join(out_dir, f"mod.{proc}"), "w") as f:
    f.write(repr(float(res.modularity)))
print(f"proc {proc}: OK Q={res.modularity:.6f}")
"""


DV_WORKER = r"""
import os, sys
proc = int(sys.argv[1]); n = int(sys.argv[2]); port = sys.argv[3]
out_dir = sys.argv[4]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
from cuvite_tpu.comm.multihost import initialize
initialize(coordinator=f"127.0.0.1:{port}", num_processes=n, process_id=proc)

import numpy as np
from cuvite_tpu.io.dist_ingest import DistVite
from cuvite_tpu.louvain.driver import louvain_phases

path = os.path.join(out_dir, "g.bin")
dv = DistVite.load(path, 4 * n)
# Per-host ingest really was partial: remote shards hold no edge arrays.
remote = [s for s in range(4 * n) if not (dv.local_lo <= s < dv.local_hi)]
assert remote and all(dv.shards[s].src is None for s in remote)
res = louvain_phases(dv)
np.save(os.path.join(out_dir, f"dvcomm.{proc}.npy"), res.communities)
with open(os.path.join(out_dir, f"dvmod.{proc}"), "w") as f:
    f.write(repr(float(res.modularity)))
print(f"proc {proc}: OK Q={res.modularity:.6f}")
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_run_matches_single(tmp_path):
    from conftest import karate_edges

    _, s, d = karate_edges()
    np.save(tmp_path / "edges.npy", np.stack([s, d], axis=1))
    (tmp_path / "worker.py").write_text(WORKER)
    port = _free_port()
    env = dict(os.environ, PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(tmp_path / "worker.py"), str(i), "2",
             str(port), str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    for p, o in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{o[-3000:]}"

    c0 = np.load(tmp_path / "comm.0.npy")
    c1 = np.load(tmp_path / "comm.1.npy")
    assert np.array_equal(c0, c1), "processes disagree on communities"

    # Single-process oracle on the same 8-device virtual mesh.
    from cuvite_tpu.core.graph import Graph
    from cuvite_tpu.louvain.driver import louvain_phases

    edges = np.load(tmp_path / "edges.npy")
    g = Graph.from_edges(int(edges.max()) + 1, edges[:, 0], edges[:, 1])
    ref = louvain_phases(g, nshards=8)
    assert np.array_equal(c0, ref.communities), \
        "2-process run differs from single-process 8-shard run"
    q0 = float(open(tmp_path / "mod.0").read())
    assert abs(q0 - ref.modularity) < 1e-6


def test_two_process_dist_ingest(tmp_path):
    """2-process per-host sharded ingest: each process range-reads only its
    4 shards' edges (remote shards carry no arrays), yet the clustering
    matches the single-process full-ingest run."""
    from conftest import karate_edges

    from cuvite_tpu.core.graph import Graph
    from cuvite_tpu.io.vite import write_vite
    from cuvite_tpu.louvain.driver import louvain_phases

    _, s, d = karate_edges()
    g = Graph.from_edges(34, s, d)
    write_vite(str(tmp_path / "g.bin"), g)
    (tmp_path / "worker.py").write_text(DV_WORKER)
    port = _free_port()
    env = dict(os.environ, PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(tmp_path / "worker.py"), str(i), "2",
             str(port), str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    for p, o in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{o[-3000:]}"

    c0 = np.load(tmp_path / "dvcomm.0.npy")
    c1 = np.load(tmp_path / "dvcomm.1.npy")
    assert np.array_equal(c0, c1)
    ref = louvain_phases(g, nshards=8)
    assert np.array_equal(c0, ref.communities)
    q0 = float(open(tmp_path / "dvmod.0").read())
    assert abs(q0 - ref.modularity) < 1e-6
