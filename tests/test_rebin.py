"""Device re-binning (ISSUE 19): coarsen/rebin.py + the driver/batched
integration, and the msd/hash big-class coalesce engines.

The host ``BucketPlan.build`` is the bit-identity oracle: the device
plan builder must reproduce its buckets (verts/dst/w prefix per kept
width), self-loop vector and assemble permutation exactly, on gapped
label spaces and across every ladder width the class admits.  The
integration half pins the serving properties the tentpole claims: full
sort/bucketed/batched runs label-identical with device re-binning
forced on and off, zero fresh compiles on phases >= 2 of an unchanged
class, the one-sync-per-phase discipline intact on re-binned phases,
and NO host BucketPlan.build call after phase 0.
"""

import logging

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cuvite_tpu.coarsen.rebin import (
    device_rebin_plan,
    rebin_eligible,
    rebin_geometry,
)
from cuvite_tpu.io.generate import generate_rmat
from cuvite_tpu.louvain.bucketed import DEFAULT_BUCKETS, BucketPlan
from cuvite_tpu.louvain.driver import louvain_phases
from cuvite_tpu.ops.segment import coalesced_runs

# ---------------------------------------------------------------------------
# Plan bit-identity vs the host oracle


def _coalesced_slab(rng, nv_pad, ne_pad, *, base=0, gapped=False,
                    hubs=0, hub_deg=None, max_deg=8):
    """A slab honoring the rebin_plan contract: sorted by src, distinct
    (src, dst) pairs, real rows compacted into the prefix, padding
    (src == nv_pad, w == 0) after; dyadic weights (exactness domain).
    ``gapped``: only a sparse subset of the label space has edges.
    ``hubs``: that many vertices get degree ``hub_deg`` (default
    nv_pad, the widest class) — the all-eligible-widths lever."""
    deg = rng.integers(0, max_deg + 1, nv_pad)
    if gapped:
        dead = rng.choice(nv_pad, size=nv_pad - nv_pad // 7, replace=False)
        deg[dead] = 0
    if hubs:
        hub_ids = rng.choice(np.flatnonzero(deg >= 0), size=hubs,
                             replace=False)
        deg[hub_ids] = nv_pad if hub_deg is None else hub_deg
    assert int(deg.sum()) <= ne_pad, "slab budget"
    src_l, dst_l = [], []
    for v in range(nv_pad):
        d = int(deg[v])
        if not d:
            continue
        nbrs = np.sort(rng.permutation(nv_pad)[:d])
        src_l.append(np.full(d, v, np.int64))
        dst_l.append(nbrs + base)
    n = int(deg.sum())
    src = np.full(ne_pad, nv_pad, np.int32)
    dst = np.zeros(ne_pad, np.int32)
    w = np.zeros(ne_pad, np.float32)
    if n:
        src[:n] = np.concatenate(src_l)
        dst[:n] = np.concatenate(dst_l)
        w[:n] = rng.integers(1, 64, n) / 8.0
    return src, dst, w


@pytest.mark.parametrize("nv_pad,ne_pad,kw", [
    (8, 64, {}),
    (64, 1024, {"gapped": True}),
    (256, 8192, {"base": 1024, "max_deg": 40}),
    (1024, 32768, {"hubs": 4, "max_deg": 40}),        # widths up to 1024
    (8192, 1 << 17, {"hubs": 3, "gapped": True,
                     "max_deg": 12}),                 # full ladder to 8192
], ids=["tiny", "gapped", "based", "hubby", "ladder-top"])
def test_device_plan_matches_host(nv_pad, ne_pad, kw):
    rng = np.random.default_rng(nv_pad + ne_pad)
    base = kw.get("base", 0)
    src, dst, w = _coalesced_slab(rng, nv_pad, ne_pad, **kw)
    assert rebin_eligible(nv_pad, ne_pad)
    geom = rebin_geometry(nv_pad, ne_pad)
    plan = BucketPlan.build(src, dst, w, nv_local=nv_pad, base=base)
    assert not plan.has_heavy
    bks, heavy, self_loop, perm = jax.device_get(device_rebin_plan(
        jnp.asarray(src), jnp.asarray(dst), jnp.asarray(w),
        nv_pad=nv_pad, base=base, geometry=geom))

    host = {b.width: b for b in plan.buckets}
    for (width, rows), (verts, dmat, wmat) in zip(geom, bks):
        hb = host.get(width)
        n = 0 if hb is None else int((np.asarray(hb.verts) < nv_pad).sum())
        if n:
            # The host bucket embeds as the device bucket's prefix:
            # same ascending-id row order, same gather content, same
            # own-id/zero column padding.
            assert np.array_equal(verts[:n], np.asarray(hb.verts)[:n])
            assert np.array_equal(dmat[:n], np.asarray(hb.dst)[:n])
            assert np.array_equal(wmat[:n], np.asarray(hb.w)[:n])
        assert rows >= n
        assert (verts[n:] == nv_pad).all()
        assert (wmat[n:] == 0).all()
        assert (dmat[n:] == 0).all()
    # Every host bucket width is a kept geometry width (truncated
    # ladder covers the class).
    assert set(host) <= {wd for wd, _ in geom}
    assert np.array_equal(self_loop,
                          np.asarray(plan.self_loop, self_loop.dtype))
    assert (np.asarray(heavy[0]) == nv_pad).all()  # static empty residual

    # Assemble-perm consistency: deg>0 vertices point at their own row
    # in the concatenated bucket space, deg==0 at the trailing default.
    total = sum(r for _, r in geom)
    allverts = np.concatenate([np.asarray(b[0]) for b in bks])
    deg = np.bincount(src[src < nv_pad], minlength=nv_pad)
    assert (perm[deg == 0] == total).all()
    touched = np.flatnonzero(deg > 0)
    assert np.array_equal(allverts[perm[touched]], touched)


def test_rebin_geometry_static_and_truncated():
    """Geometry is class-derived only: ladder truncates once a width
    covers nv_pad, rows are pow2 occupancy ceilings, and the SAME class
    always yields the SAME tuple (the compile-key contract)."""
    geom = rebin_geometry(16, 64)
    assert [wd for wd, _ in geom] == [8, 16]
    for wd, rows in geom:
        assert rows & (rows - 1) == 0
    assert geom == rebin_geometry(16, 64)
    widths = [wd for wd, _ in rebin_geometry(4096, 16384)]
    assert widths == [wd for wd in DEFAULT_BUCKETS if wd <= 4096]


def test_rebin_eligibility_bounds(monkeypatch):
    """Past the ladder top a heavy residual could exist (host oracle
    path); past the element budget the plan is too big.  The env knob
    is read per call."""
    assert rebin_eligible(1024, 16384)
    assert not rebin_eligible(DEFAULT_BUCKETS[-1] * 2, 1 << 16)
    monkeypatch.setenv("CUVITE_REBIN_MAX_ELEMS", "1024")
    assert not rebin_eligible(1024, 16384)


# ---------------------------------------------------------------------------
# Driver integration


@pytest.fixture(scope="module")
def rmat10():
    g = generate_rmat(10, edge_factor=8, seed=3)
    assert g.num_vertices <= 4096 and g.num_edges <= 16384
    return g


def test_full_runs_identical_rebin_on_off(rmat10, monkeypatch):
    """Device re-binning never changes results: bucketed runs with the
    re-binner on (default) and pinned off produce identical labels, Q
    and iteration counts.  (The sort-engine arm rides the slow
    sibling, test_full_runs_identical_rebin_vs_sort.)"""
    monkeypatch.delenv("CUVITE_DEVICE_REBIN", raising=False)
    r_on = louvain_phases(rmat10, engine="bucketed")
    monkeypatch.setenv("CUVITE_DEVICE_REBIN", "0")
    r_off = louvain_phases(rmat10, engine="bucketed")
    assert len(r_on.phases) == len(r_off.phases) >= 3
    assert r_on.total_iterations == r_off.total_iterations
    assert r_on.modularity == r_off.modularity
    assert np.array_equal(r_on.communities, r_off.communities)


@pytest.mark.slow
def test_full_runs_identical_rebin_vs_sort(rmat10, monkeypatch):
    """The cross-engine arm of the on/off identity: the re-binned
    bucketed run also matches the sort engine's labels."""
    monkeypatch.delenv("CUVITE_DEVICE_REBIN", raising=False)
    r_on = louvain_phases(rmat10, engine="bucketed")
    r_sort = louvain_phases(rmat10, engine="sort")
    assert np.array_equal(r_on.communities, r_sort.communities)
    assert r_on.modularity == r_sort.modularity


def test_no_host_plan_build_after_phase0(rmat10, monkeypatch):
    """The acceptance spy: with device re-binning on, the ONLY host
    BucketPlan.build of a multi-phase bucketed run is phase 0's."""
    calls = []
    orig = BucketPlan.build

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    monkeypatch.setattr(BucketPlan, "build", staticmethod(spy))
    res = louvain_phases(rmat10, engine="bucketed")
    assert len(res.phases) >= 3
    assert len(calls) == 1, \
        f"{len(calls)} host BucketPlan.build calls (want phase 0 only)"


def test_rebin_zero_fresh_compiles_after_phase1(rmat10, monkeypatch):
    """Static geometry holds the compile-key contract: same pow2 class
    across coarse phases => all compiles in phases 0-1 (phase 1 traces
    the re-binned program), none after."""
    monkeypatch.delenv("CUVITE_DEVICE_REBIN", raising=False)
    from cuvite_tpu.utils.trace import Tracer

    compiles = []

    class _Grab(logging.Handler):
        def emit(self, record):
            if "Compiling" in record.getMessage():
                compiles.append(record.getMessage())

    import contextlib

    class _Probe(Tracer):
        def __init__(self):
            super().__init__(enabled=True)
            self.marks = []

        @contextlib.contextmanager
        def stage(self, name):
            if name == "iterate":
                self.marks.append(len(compiles))
            with super().stage(name):
                yield

    probe = _Probe()
    handler = _Grab(level=logging.WARNING)
    logger = logging.getLogger("jax")
    logger.addHandler(handler)
    jax.config.update("jax_log_compiles", True)
    try:
        res = louvain_phases(rmat10, engine="bucketed", tracer=probe)
    finally:
        jax.config.update("jax_log_compiles", False)
        logger.removeHandler(handler)
    assert len(res.phases) >= 3 and len(probe.marks) >= 3
    fresh_after_phase1 = len(compiles) - probe.marks[2]
    assert fresh_after_phase1 == 0, compiles[probe.marks[2]:][:4]


def test_rebin_adds_no_device_syncs(rmat10, monkeypatch):
    """One sync per phase stays one sync per phase: the re-binned
    coarse phases must not change the run's jax.device_get count."""
    def run_counting():
        calls = []
        orig = jax.device_get

        def spy(x):
            calls.append(1)
            return orig(x)

        monkeypatch.setattr(jax, "device_get", spy)
        try:
            res = louvain_phases(rmat10, engine="bucketed")
        finally:
            monkeypatch.setattr(jax, "device_get", orig)
        return res, len(calls)

    monkeypatch.delenv("CUVITE_DEVICE_REBIN", raising=False)
    r_on, n_on = run_counting()
    monkeypatch.setenv("CUVITE_DEVICE_REBIN", "0")
    r_off, n_off = run_counting()
    assert np.array_equal(r_on.communities, r_off.communities)
    assert n_on == n_off, \
        f"device re-binning changed sync count: {n_on} vs {n_off}"


def test_rebin_device_fraction_in_tracer(rmat10):
    """The bench telemetry counters: every eligible coarse phase of a
    bucketed run re-bins on device when the knob is on."""
    from cuvite_tpu.utils.trace import Tracer

    tr = Tracer(enabled=True)
    res = louvain_phases(rmat10, engine="bucketed", tracer=tr)
    total = tr.counters.get("rebin_phases", 0)
    dev = tr.counters.get("rebin_device_phases", 0)
    assert len(res.phases) >= 3
    # Every coarse-phase runner counts itself (the terminating
    # no-improvement attempt included, so >= recorded phases - 1).
    assert total >= len(res.phases) - 1
    assert dev == total  # the floor class is rebin-eligible


# ---------------------------------------------------------------------------
# Batched integration


def test_batched_rebinned_identical_and_spied(monkeypatch):
    """The serving path: a batched bucketed run re-bins its coarse
    phases on device ('rebinned' in phase_engines), produces labels/Q
    bit-identical to the host-plan arm, and makes NO BucketPlan.build
    call after prepare (phase 0).  (The B=1 and per-graph-driver
    cross-checks ride the slow sibling,
    test_batched_rebinned_matches_b1_and_solo.)"""
    from cuvite_tpu.louvain.driver import louvain_many

    gs = [generate_rmat(8, edge_factor=8, seed=s) for s in (1, 2)]
    monkeypatch.delenv("CUVITE_DEVICE_REBIN", raising=False)
    on = louvain_many(gs, engine="bucketed")
    assert on.phase_engines[0] == "bucketed"
    assert all(e == "rebinned" for e in on.phase_engines[1:])
    assert len(on.phase_engines) >= 2

    monkeypatch.setenv("CUVITE_DEVICE_REBIN", "0")
    off = louvain_many(gs, engine="bucketed")
    assert all(e == "fused" for e in off.phase_engines[1:])
    monkeypatch.delenv("CUVITE_DEVICE_REBIN", raising=False)
    for r_on, r_off in zip(on.results, off.results):
        assert r_on.modularity == r_off.modularity
        assert np.array_equal(r_on.communities, r_off.communities)

    # The batched build spy: warm path re-runs prepare (phase 0 builds
    # are legal) but the re-binned EXECUTE phases must build nothing —
    # count builds with the coarse phases forced to fused vs rebinned;
    # the rebinned arm must not add any.
    calls = []
    orig = BucketPlan.build

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    monkeypatch.setattr(BucketPlan, "build", staticmethod(spy))
    louvain_many(gs, engine="bucketed")
    n_rebinned = len(calls)
    calls.clear()
    monkeypatch.setenv("CUVITE_DEVICE_REBIN", "0")
    louvain_many(gs, engine="bucketed")
    assert n_rebinned <= len(calls)  # host arm builds at least as many
    # and the rebinned arm's builds are all phase-0 (prepare) builds:
    # re-running prepare alone accounts for every one of them.
    monkeypatch.delenv("CUVITE_DEVICE_REBIN", raising=False)
    from cuvite_tpu.core.batch import batch_slabs
    from cuvite_tpu.louvain.batched import prepare_batch

    calls.clear()
    prepare_batch(batch_slabs(gs), engine="bucketed")
    assert len(calls) == n_rebinned


@pytest.mark.slow
def test_batched_rebinned_matches_b1_and_solo(monkeypatch):
    """Cross-arm identity of the re-binned serving path: every tenant
    of a B>1 re-binned batch matches its own B=1 batch AND the
    per-graph bucketed driver bit-for-bit."""
    from cuvite_tpu.louvain.driver import louvain_many

    monkeypatch.delenv("CUVITE_DEVICE_REBIN", raising=False)
    gs = [generate_rmat(8, edge_factor=8, seed=s) for s in (1, 2)]
    on = louvain_many(gs, engine="bucketed")
    assert all(e == "rebinned" for e in on.phase_engines[1:])
    for g, r_on in zip(gs, on.results):
        b1 = louvain_many([g], engine="bucketed")
        solo = louvain_phases(g, engine="bucketed")
        assert np.array_equal(r_on.communities, b1.results[0].communities)
        assert np.array_equal(r_on.communities, solo.communities)


def test_batched_second_batch_zero_fresh_compiles(monkeypatch):
    """Serving amortization with device re-binning ON: a second batch
    of different same-class graphs compiles nothing — including the
    re-binned coarse phases."""
    from cuvite_tpu.core.batch import bucket_shape_for
    from cuvite_tpu.louvain.driver import louvain_many
    from cuvite_tpu.obs import CompileWatcher

    monkeypatch.delenv("CUVITE_DEVICE_REBIN", raising=False)
    gs = [generate_rmat(8, edge_factor=8, seed=s) for s in (5, 6)]
    fresh = [generate_rmat(8, edge_factor=8, seed=s) for s in (7, 8)]
    shape = bucket_shape_for(gs + fresh)
    louvain_many(gs, engine="bucketed", bucket_shape=shape)  # warm
    with CompileWatcher() as watch:
        br = louvain_many(fresh, engine="bucketed", bucket_shape=shape)
    assert watch.compiles == [], \
        f"second same-class batch recompiled: {watch.compiles}"
    assert all(e == "rebinned" for e in br.phase_engines[1:])


# ---------------------------------------------------------------------------
# msd / hash coalesce engines vs the float64 oracle (tentpole b)


def _chokepoint_slab(nv_pad, ne_pad, seed):
    rng = np.random.default_rng(seed)
    n_real = ne_pad - ne_pad // 7
    src = np.full(ne_pad, nv_pad, np.int32)
    dst = np.zeros(ne_pad, np.int32)
    w = np.zeros(ne_pad, np.float32)
    src[:n_real] = rng.integers(0, nv_pad, n_real)
    dst[:n_real] = rng.integers(0, nv_pad, n_real)
    src[:4] = [nv_pad - 1, nv_pad - 1, 0, 0]
    dst[:4] = [nv_pad - 1, nv_pad - 1, nv_pad - 1, 0]
    w[:n_real] = rng.integers(1, 64, n_real) / 8.0
    return src, dst, w


def _oracle(src, ckey, w, nv_pad):
    """Sorted-unique real (src, ckey) pairs, weights summed in float64
    (dyadic inputs: every f32 partial sum is exact, so engines must
    match BIT-for-bit after the cast)."""
    real = src < nv_pad
    keys = src[real].astype(np.int64) * (nv_pad + 1) + ckey[real]
    order = np.argsort(keys, kind="stable")
    ks, ws = keys[order], w[real][order].astype(np.float64)
    uniq, start = np.unique(ks, return_index=True)
    sums = np.add.reduceat(ws, start) if len(ws) else ws
    return (uniq // (nv_pad + 1)).astype(src.dtype), \
        (uniq % (nv_pad + 1)).astype(ckey.dtype), \
        sums.astype(w.dtype)


def _assert_matches_oracle(out, src, dst, w, nv_pad):
    s_ref, c_ref, w_ref = _oracle(src, dst, w, nv_pad)
    src_c, ckey_c, w_c, n = (np.asarray(x) for x in jax.device_get(out))
    n = int(n)
    assert n == len(s_ref)
    assert np.array_equal(src_c[:n], s_ref)
    assert np.array_equal(ckey_c[:n], c_ref)
    assert np.array_equal(w_c[:n], w_ref)
    assert (src_c[n:] == nv_pad).all()


@pytest.mark.parametrize("engine", ["msd", "hash"])
@pytest.mark.parametrize("nv_pad", [1 << 15, 1 << 16],
                         ids=["widest-legal-pack", "first-ineligible"])
def test_bigclass_engines_match_oracle(engine, nv_pad):
    """The parity pair at the packing boundary: nv_pad = 2^15 is the
    widest legal 31-bit pack (msd delegates to it), 2^16 the first
    class past it (msd runs its two passes; the sort arm degrades to
    the variadic comparator)."""
    ne_pad = 8192
    src, dst, w = _chokepoint_slab(nv_pad, ne_pad, seed=nv_pad)
    arrs = tuple(jnp.asarray(x) for x in (src, dst, w))
    out = coalesced_runs(*arrs, nv_pad=nv_pad, engine=engine)
    _assert_matches_oracle(out, src, dst, w, nv_pad)
    ref = jax.device_get(coalesced_runs(*arrs, nv_pad=nv_pad,
                                        engine="sort"))
    got = jax.device_get(out)
    for r, g, name in zip(ref, got, ("src", "ckey", "w", "n")):
        assert np.array_equal(np.asarray(r), np.asarray(g)), name


@pytest.mark.parametrize("engine", ["msd", "hash"])
def test_bigclass_engines_forced_x64_identical(engine):
    """Under jax_enable_x64 the sort arm packs one int64 key; msd/hash
    keep their int32 formulations — all three must agree bit-for-bit
    at the first ineligible width."""
    nv_pad, ne_pad = 1 << 16, 8192
    src, dst, w = _chokepoint_slab(nv_pad, ne_pad, seed=97)
    arrs = tuple(jnp.asarray(x) for x in (src, dst, w))
    base = jax.device_get(coalesced_runs(*arrs, nv_pad=nv_pad,
                                         engine=engine))
    prior = jax.config.jax_enable_x64
    try:
        jax.config.update("jax_enable_x64", True)
        forced = jax.device_get(coalesced_runs(*arrs, nv_pad=nv_pad,
                                               engine="sort"))
    finally:
        jax.config.update("jax_enable_x64", prior)
    for b, f, name in zip(base, forced, ("src", "ckey", "w", "n")):
        assert np.array_equal(np.asarray(b), np.asarray(f)), name


def test_hash_collision_retry_path():
    """A deliberately tiny table forces collisions: the device-side
    detector must fire and the sorted retry must still produce the
    exact coalesce."""
    nv_pad, ne_pad = 1 << 16, 4096
    src, dst, w = _chokepoint_slab(nv_pad, ne_pad, seed=5)
    import os

    os.environ["CUVITE_HASH_SLOTS"] = "2"
    try:
        out = coalesced_runs(jnp.asarray(src), jnp.asarray(dst),
                             jnp.asarray(w), nv_pad=nv_pad,
                             engine="hash")
        _assert_matches_oracle(out, src, dst, w, nv_pad)
    finally:
        del os.environ["CUVITE_HASH_SLOTS"]
