"""Unit tests: CSR construction, invariants, Vite I/O round-trip."""

import numpy as np

from cuvite_tpu.core.distgraph import DistGraph, balanced_parts, uniform_parts
from cuvite_tpu.core.graph import Graph
from cuvite_tpu.io.vite import read_vite, write_vite


def test_from_edges_symmetrize(two_cliques):
    g = two_cliques
    assert g.num_vertices == 10
    # 2*K5 (10 undirected each) + bridge = 21 undirected -> 42 directed slots
    assert g.num_edges == 42
    # Sum of weighted degrees = 2m
    assert g.total_edge_weight_twice() == 42.0
    np.testing.assert_array_equal(
        g.degrees(), np.array([5, 4, 4, 4, 4, 5, 4, 4, 4, 4])
    )


def test_weighted_degrees_match_manual(karate):
    g = karate
    wd = g.weighted_degrees()
    manual = np.zeros(g.num_vertices)
    for v in range(g.num_vertices):
        e0, e1 = g.offsets[v], g.offsets[v + 1]
        manual[v] = g.weights[e0:e1].sum()
    np.testing.assert_allclose(wd, manual, rtol=1e-6)
    assert wd.sum() == g.total_edge_weight_twice()


def test_self_loop_single_insertion():
    g = Graph.from_edges(3, [0, 1, 1], [1, 2, 1])
    # self loop (1,1) inserted once; (0,1) and (1,2) symmetrized
    assert g.num_edges == 5
    assert g.weighted_degrees()[1] == 3.0


def test_duplicate_edges_coalesce():
    g = Graph.from_edges(2, [0, 0], [1, 1])
    assert g.num_edges == 2  # one per direction
    np.testing.assert_allclose(g.weights, [2.0, 2.0])


def test_vite_roundtrip(tmp_path, karate):
    for bits64 in (True, False):
        p = str(tmp_path / f"karate{bits64}.bin")
        write_vite(p, karate, bits64=bits64)
        g2 = read_vite(p, bits64=bits64)
        assert g2.num_vertices == karate.num_vertices
        assert g2.num_edges == karate.num_edges
        np.testing.assert_array_equal(g2.offsets, karate.offsets)
        np.testing.assert_array_equal(g2.tails, karate.tails)
        np.testing.assert_allclose(g2.weights, karate.weights)


def test_vite_sliced_read(tmp_path, karate):
    p = str(tmp_path / "karate.bin")
    write_vite(p, karate, bits64=True)
    lo, hi = 10, 20
    g2 = read_vite(p, bits64=True, vertex_range=(lo, hi))
    assert g2.num_vertices == hi - lo
    assert g2.offsets[0] == 0
    e0, e1 = karate.offsets[lo], karate.offsets[hi]
    np.testing.assert_array_equal(g2.tails, karate.tails[e0:e1])


def test_uniform_parts():
    p = uniform_parts(10, 4)
    np.testing.assert_array_equal(p, [0, 3, 6, 8, 10])


def test_balanced_parts_cover(karate):
    p = balanced_parts(karate, 4)
    assert p[0] == 0 and p[-1] == karate.num_vertices
    assert np.all(np.diff(p) >= 0)


def test_distgraph_shards_cover_all_edges(karate):
    for nshards in (1, 2, 4):
        dg = DistGraph.build(karate, nshards)
        total_real = sum(sh.n_real_edges for sh in dg.shards)
        assert total_real == karate.num_edges
        # Padding has zero weight; real weights survive intact.
        src, dst, w = dg.stacked_edges()
        assert w.astype(np.float64).sum() == karate.total_edge_weight_twice()
        # Padded id round trip.
        assert np.all(dg.pad_to_old[dg.old_to_pad] == np.arange(34))
        # vdeg preserved in padded space
        np.testing.assert_allclose(
            dg.padded_weighted_degrees()[dg.old_to_pad],
            karate.weighted_degrees(), rtol=1e-6,
        )
