"""Cross-feature combinations the per-feature suites don't pair up.

The reference exercises its flags jointly (e.g. `-b -t 2 -i` in one run,
README:54-102); these tests pin the interaction matrix: early termination
on the sharded engines under both exchanges, the 64-bit policy end to end,
per-host ingest with ET and balanced cuts, weighted graphs through the
fused engine, and threshold cycling on a mesh.
"""

import numpy as np
import pytest

from cuvite_tpu.core.graph import Graph
from cuvite_tpu.evaluate.modularity import modularity
from cuvite_tpu.io.generate import generate_rgg
from cuvite_tpu.louvain.driver import louvain_phases


@pytest.fixture(scope="module")
def rgg384():
    return generate_rgg(384, seed=11)


@pytest.fixture(scope="module", params=["sparse", "replicated"])
def plain_by_exchange(request, rgg384):
    """One plain 4-shard baseline per exchange mode, shared by the ET
    parametrizations (it only depends on the exchange)."""
    return request.param, louvain_phases(rgg384, nshards=4,
                                         exchange=request.param)


@pytest.mark.parametrize("et_mode", [1, 2])
def test_et_multishard_both_exchanges(rgg384, et_mode, plain_by_exchange):
    """ET freeze/decay masks ride the on-device loop on the SPMD engines
    under either exchange; quality must stay near the plain run."""
    exchange, plain = plain_by_exchange
    r = louvain_phases(rgg384, nshards=4, et_mode=et_mode,
                       exchange=exchange)
    assert r.modularity > 0.9 * plain.modularity
    assert modularity(rgg384, r.communities) == pytest.approx(
        r.modularity, abs=1e-4)


def test_bits64_policy_end_to_end(tmp_path):
    """wide_policy (int64 ids / f64 weights on host) through write, ranged
    read, and a sharded run — the USE_32_BIT_GRAPH switch's other half."""
    from cuvite_tpu.core.types import wide_policy
    from cuvite_tpu.io.vite import read_vite, write_vite

    g32 = generate_rgg(256, seed=7)
    g = Graph(offsets=g32.offsets,
              tails=g32.tails.astype(np.int64),
              weights=g32.weights.astype(np.float64),
              policy=wide_policy())
    p = str(tmp_path / "wide.bin")
    write_vite(p, g, bits64=True)
    g2 = read_vite(p, bits64=True)
    assert g2.policy.vertex_dtype == np.int64
    r = louvain_phases(g2, nshards=4)
    r32 = louvain_phases(g32, nshards=4)
    assert np.array_equal(r.communities, r32.communities)


def test_dist_ingest_with_et_and_balanced(tmp_path):
    from cuvite_tpu.io.dist_ingest import DistVite
    from cuvite_tpu.io.vite import write_vite

    g = generate_rgg(384, seed=11)
    p = str(tmp_path / "g.bin")
    write_vite(p, g)
    dv = DistVite.load(p, 8, balanced=True)
    r = louvain_phases(dv, balanced=True, et_mode=1)
    full = louvain_phases(g, nshards=8, balanced=True, et_mode=1,
                          exchange="sparse")
    assert np.array_equal(r.communities, full.communities)


def test_fused_weighted_graph(rgg384):
    """RGG weights are real distances — the fused engine must agree with
    bucketed on a genuinely weighted graph."""
    rf = louvain_phases(rgg384, engine="fused")
    rb = louvain_phases(rgg384, engine="bucketed")
    assert np.array_equal(rf.communities, rb.communities)
    assert rf.modularity == pytest.approx(rb.modularity, abs=1e-5)


def test_threshold_cycling_multishard(rgg384):
    r = louvain_phases(rgg384, nshards=8, threshold_cycling=True)
    r1 = louvain_phases(rgg384, threshold_cycling=True)
    assert np.array_equal(r.communities, r1.communities)


@pytest.mark.parametrize("et_mode", [1, 2])
def test_coloring_with_early_termination(rgg384, et_mode):
    """Coloring x ET — the reference's distLouvainMethodWithColoring ET
    variants (/root/reference/louvain.cpp:951-1431): the freeze mask must
    actually bite inside the per-class commits (the run must differ from
    coloring without ET — falsifiable if the mask is dropped), and quality
    must hold."""
    kw = dict(et_delta=0.9) if et_mode == 2 else {}
    r = louvain_phases(rgg384, coloring=6, et_mode=et_mode, **kw)
    rc = louvain_phases(rgg384, coloring=6)
    r0 = louvain_phases(rgg384)
    assert modularity(rgg384, r.communities) >= \
        0.8 * modularity(rgg384, r0.communities)
    if et_mode == 1:
        # Falsifiable mask check (mode 1 only): dropping the frozen mask
        # inside the class commits reverts the run to plain coloring.  The
        # mask plumbing is shared by all modes; mode 2's freeze criterion
        # ("stable for 2 iterations") happens to freeze only vertices that
        # would not have moved again on this graph, so its run can
        # legitimately equal the no-ET run.
        traj = [(p.iterations, p.num_vertices) for p in r.phases]
        traj_c = [(p.iterations, p.num_vertices) for p in rc.phases]
        assert (traj != traj_c
                or not np.array_equal(r.communities, rc.communities)), \
            "ET changed nothing under coloring (freeze mask dropped?)"


@pytest.mark.parametrize("et_mode", [1, 2])
def test_vertex_ordering_with_early_termination(rgg384, et_mode):
    """Ordering x ET — the reference's VertexOrder ET variants
    (/root/reference/louvain.cpp:1627-2102); same falsifiability bar as
    the coloring x ET test (mode 1 = the freeze-mask mode)."""
    kw = dict(et_delta=0.9) if et_mode == 2 else {}
    r = louvain_phases(rgg384, vertex_ordering=6, et_mode=et_mode, **kw)
    ro = louvain_phases(rgg384, vertex_ordering=6)
    r0 = louvain_phases(rgg384)
    assert modularity(rgg384, r.communities) >= \
        0.8 * modularity(rgg384, r0.communities)
    if et_mode == 1:
        traj = [(p.iterations, p.num_vertices) for p in r.phases]
        traj_o = [(p.iterations, p.num_vertices) for p in ro.phases]
        assert (traj != traj_o
                or not np.array_equal(r.communities, ro.communities)), \
            "ET changed nothing under vertex ordering (freeze mask dropped?)"
