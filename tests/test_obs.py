"""Flight-recorder tests (ISSUE 6): the observability acceptance gates.

The three properties that make telemetry trustworthy enough to leave on:

  * free when off, cheap when on — labels are BIT-IDENTICAL with and
    without a recorder attached (both engines, both exchanges, rmat-14),
    and the device phase loops still sync exactly once per phase (a
    host-sync spy counts jax.device_get calls — per-iteration syncs are
    the thing the on-device loop exists to avoid);
  * the trace round-trips — every span closes, phase spans nest the
    iterate stage and the convergence/exchange events, the per-iteration
    Q rows in the trace match ``LouvainResult.convergence``, and a cold
    run records at least one XLA compile event;
  * the regression gate bites — ``tools/perf_regress.py`` flags an
    injected 30% TEPS drop against the checked-in BENCH trajectory,
    passes on the real trajectory, and its ``--self-check`` (run here,
    in tier-1) refuses a malformed checked-in record.
"""

import contextlib
import json
import logging
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from cuvite_tpu.io.generate import generate_rmat
from cuvite_tpu.louvain.driver import louvain_phases
from cuvite_tpu.obs import (
    CompileWatcher,
    DeviceMemoryLedger,
    FlightRecorder,
    JsonlTraceSink,
    MemoryTraceSink,
    MOVED_UNTRACKED,
    SpanEmitter,
    convergence_summary,
    decode_phase_conv,
    read_trace,
    spans_of,
    validate_trace,
)
from cuvite_tpu.utils.trace import Tracer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PERF_REGRESS = os.path.join(REPO, "tools", "perf_regress.py")


@pytest.fixture(scope="module")
def rmat14():
    return generate_rmat(14, edge_factor=8, seed=3)


# ---------------------------------------------------------------------------
# Trace round-trip (FIRST in the module: this run owns the cold compiles
# for its unique graph shape, so the compile-event assertion is sound in
# a single-process tier-1 run).


def test_trace_round_trip_cold_run(tmp_path):
    g = generate_rmat(13, edge_factor=12, seed=7)  # shape unique to this test
    path = str(tmp_path / "run.jsonl")
    with FlightRecorder(JsonlTraceSink(path)) as rec:
        res = louvain_phases(g, tracer=Tracer(recorder=rec))
    records = read_trace(path)
    assert validate_trace(records) == [], validate_trace(records)[:5]
    assert records[0]["t"] == "run_begin" and records[-1]["t"] == "run_end"

    # Phase spans nest the iterate stage and the telemetry events.
    phase_spans = spans_of(records, "phase")
    assert len(phase_spans) == len(res.convergence) >= 2
    for span in phase_spans:
        assert span["end"] is not None
        assert "iterate" in span["child_names"]
        names = {e["name"] for e in span["events"]}
        assert {"convergence", "exchange"} <= names, names

    # Per-iteration Q rows in the trace match LouvainResult.convergence.
    conv_events = [r for r in records if r.get("t") == "event"
                   and r.get("name") == "convergence"]
    assert len(conv_events) == len(res.convergence)
    for ev, pc in zip(conv_events, res.convergence):
        assert ev["attrs"]["phase"] == pc.phase
        assert ev["attrs"]["iterations"] == pc.iterations
        assert ev["attrs"]["rows"] == [r.to_dict() for r in pc.rows]
        qs = [row["q"] for row in ev["attrs"]["rows"]]
        assert qs == [r.q for r in pc.rows]
        # The curve is non-decreasing over the ACCEPTED iterations; the
        # final row is the attempt that failed the threshold and may dip.
        assert all(b >= a - 1e-6 for a, b in zip(qs[:-1], qs[1:-1]))

    # Cold run: the compile watcher recorded the fresh XLA compiles.
    compile_events = [r for r in records if r.get("t") == "event"
                      and r.get("name") == "compile"]
    assert compile_events, "cold run must record at least one compile"
    assert all("module" in e["attrs"] for e in compile_events)

    # HBM ledger snapshots rode along.
    hbm = [r for r in records if r.get("t") == "event"
           and r.get("name") == "hbm"]
    assert len(hbm) >= len(res.phases)
    assert all(isinstance(e["attrs"]["by_buffer"], dict) for e in hbm)


# ---------------------------------------------------------------------------
# Telemetry is free: bit-identical labels, both engines, both exchanges.


@pytest.mark.parametrize("engine,exchange,nshards", [
    ("bucketed", "sparse", 2),
    ("bucketed", "replicated", 2),
    ("fused", "auto", 1),
], ids=["bucketed-sparse", "bucketed-replicated", "fused"])
def test_labels_bit_identical_with_telemetry(rmat14, tmp_path, engine,
                                             exchange, nshards):
    kw = dict(engine=engine, exchange=exchange, nshards=nshards,
              verbose=False)
    res0 = louvain_phases(rmat14, **kw)
    path = str(tmp_path / "t.jsonl")
    with FlightRecorder(JsonlTraceSink(path)) as rec:
        res1 = louvain_phases(rmat14, tracer=Tracer(recorder=rec), **kw)
    assert np.array_equal(res0.communities, res1.communities), \
        "telemetry changed the clustering"
    assert res0.modularity == res1.modularity
    assert validate_trace(read_trace(path)) == []
    # The telemetry run carries per-phase convergence; the off run too
    # (the buffers ride the existing sync whether or not anyone listens).
    assert len(res1.convergence) >= len(res1.phases)
    assert [pc.iterations for pc in res0.convergence] \
        == [pc.iterations for pc in res1.convergence]


def test_convergence_rows_without_recorder(rmat14):
    """LouvainResult.convergence is populated on a PLAIN run — the
    device buffers ride the existing per-phase sync unconditionally."""
    res = louvain_phases(rmat14, verbose=False)
    assert res.convergence and len(res.convergence) >= len(res.phases)
    for pc in res.convergence:
        assert pc.iterations == len(pc.rows)  # far below CONV_ROWS_CAP
        assert not pc.truncated
        assert all(r.moved >= 0 for r in pc.rows)  # device loop tracks moved
    gained = [pc for pc in res.convergence if pc.gained]
    assert len(gained) == len(res.phases)
    # Digests agree with the rows (the bench's convergence_summary path).
    digests = convergence_summary(res.convergence)
    for d, pc in zip(digests, res.convergence):
        assert d["q_last"] == pc.rows[-1].q
        assert d["moved_total"] == sum(r.moved for r in pc.rows)


# ---------------------------------------------------------------------------
# Cheap when on: exactly one device sync per phase, zero fresh compiles
# on phases 2+.


def test_one_device_sync_per_phase(rmat14, monkeypatch):
    """The telemetry buffers ride THE existing per-phase sync: a spy on
    jax.device_get sees exactly one call per phase attempt (the
    _phase_sync chokepoint), never a per-iteration fetch."""
    import cuvite_tpu.louvain.driver as drv

    louvain_phases(rmat14, verbose=False)  # eat compiles outside the spy

    gets = []
    orig_get = jax.device_get

    def spy(x):
        gets.append(x)
        return orig_get(x)

    syncs = []
    orig_sync = drv._phase_sync

    def sync_spy(*a, **kw):
        syncs.append(len(gets))
        return orig_sync(*a, **kw)

    monkeypatch.setattr(jax, "device_get", spy)
    monkeypatch.setattr(drv, "_phase_sync", sync_spy)
    with FlightRecorder() as rec:
        res = louvain_phases(rmat14, tracer=Tracer(recorder=rec),
                             verbose=False)
    attempts = len(res.convergence)
    total_iters = sum(pc.iterations for pc in res.convergence)
    assert total_iters > attempts  # a per-iteration sync would be visible
    assert len(syncs) == attempts
    assert len(gets) == attempts, (
        f"{len(gets)} device_get calls for {attempts} phase attempts "
        f"({total_iters} iterations): telemetry added host syncs")


class _PhaseProbe(Tracer):
    """Recorder-attached tracer marking the compile-log length at each
    iterate stage (the per-phase boundary)."""

    def __init__(self, recorder, compile_log):
        super().__init__(recorder=recorder)
        self._log = compile_log
        self.marks = []

    @contextlib.contextmanager
    def stage(self, name):
        if name == "iterate":
            self.marks.append(len(self._log))
        with super().stage(name):
            yield


def test_zero_fresh_compiles_phases2plus_with_telemetry(rmat14):
    """Telemetry must not break the compiled-step cache: with a recorder
    attached, phases 2+ of an unchanged slab class compile nothing."""
    compiles = []

    class _Grab(logging.Handler):
        def emit(self, record):
            if "Compiling" in record.getMessage():
                compiles.append(record.getMessage())

    handler = _Grab(level=logging.WARNING)
    logger = logging.getLogger("jax")
    logger.addHandler(handler)
    jax.config.update("jax_log_compiles", True)
    try:
        with FlightRecorder() as rec:
            probe = _PhaseProbe(rec, compiles)
            res = louvain_phases(rmat14, tracer=probe, verbose=False)
    finally:
        jax.config.update("jax_log_compiles", False)
        logger.removeHandler(handler)
    assert len(res.phases) >= 3 and len(probe.marks) >= 3
    fresh = len(compiles) - probe.marks[2]
    assert fresh == 0, (
        f"phases 2+ compiled {fresh}x under telemetry: "
        f"{compiles[probe.marks[2]:][:4]}")


# ---------------------------------------------------------------------------
# CLI export flags.


def test_cli_trace_and_metrics_out(tmp_path, karate):
    from cuvite_tpu.cli import main
    from cuvite_tpu.io.vite import write_vite

    p = str(tmp_path / "k.bin")
    write_vite(p, karate)
    trace = str(tmp_path / "k.jsonl")
    metrics = str(tmp_path / "k.json")
    rc = main(["--file", p, "--bits64", "--trace-out", trace,
               "--metrics-out", metrics, "--quiet"])
    assert rc == 0
    records = read_trace(trace)
    assert validate_trace(records) == []
    assert spans_of(records, "phase")
    m = json.load(open(metrics))
    assert m["modularity"] > 0.40
    assert m["convergence"] and m["convergence"][0]["rows"]
    assert "hbm_peak_by_buffer" in m and "stages" in m
    assert m["stages"]["iterate_s"] > 0


# ---------------------------------------------------------------------------
# obs unit surface: emitter nesting, ledger, convergence decode, watcher.


def test_span_emitter_nesting_and_leak_unwind():
    sink = MemoryTraceSink()
    em = SpanEmitter(sink)
    outer = em.begin("outer")
    inner = em.begin("inner")
    em.event("ping", k=1)
    # Ending the OUTER span with the inner still open unwinds the leak.
    em.end(outer)
    em.close()
    recs = sink.records
    assert validate_trace(recs) == []
    ev = next(r for r in recs if r.get("t") == "event")
    assert ev["parent"] == inner and ev["attrs"] == {"k": 1}
    leak = next(r for r in recs
                if r.get("t") == "span_end" and r.get("id") == inner)
    assert leak.get("leaked") is True


def test_validate_trace_catches_violations():
    base = {"wall": 0.0, "mono": 0.0, "host": 0}
    unclosed = [dict(base, t="span_begin", id=1, parent=None, name="x")]
    assert any("never closed" in p for p in validate_trace(unclosed))
    orphan_parent = [dict(base, t="span_begin", id=2, parent=9, name="x")]
    assert any("not open" in p for p in validate_trace(orphan_parent))
    bad_end = [dict(base, t="span_end", id=3)]
    assert any("unknown" in p for p in validate_trace(bad_end))
    backwards = [dict(base, t="event", name="a", mono=2.0),
                 dict(base, t="event", name="b", mono=1.0)]
    assert any("backwards" in p for p in validate_trace(backwards))


def test_memory_ledger_peaks_and_phases():
    class Arr:
        def __init__(self, nbytes):
            self.nbytes = nbytes

    led = DeviceMemoryLedger()
    led.begin_phase()
    led.track("slab", Arr(100), Arr(50), None)
    led.track("tables", Arr(10))
    snap = led.snapshot(0)
    assert snap["by_buffer"] == {"slab": 150, "tables": 10}
    assert snap["total"] == 160 and snap["rss_mb"] > 0
    led.begin_phase()  # new phase replaces the live set
    led.track("slab", Arr(80))
    led.track("scratch", Arr(999))
    led.snapshot(1)
    assert led.peak_by_buffer == {"slab": 150, "tables": 10, "scratch": 999}
    assert len(led.snapshots) == 2


def test_decode_phase_conv_truncation():
    q = [0.1, 0.2, 0.3, 0.3]
    moved = [40, 20, 5, 0]
    pc = decode_phase_conv(2, 3, q, moved)
    assert pc.phase == 2 and not pc.truncated
    assert [r.q for r in pc.rows] == [0.1, 0.2, 0.3]
    assert pc.moved_total() == 65
    assert pc.dq() == [None, pytest.approx(0.1), pytest.approx(0.1)]
    # More iterations than the buffer holds: rows clamp, flag set.
    pc = decode_phase_conv(0, 9, q, moved)
    assert pc.truncated and pc.iterations == 9 and len(pc.rows) == 4
    # Untracked moved counts (host color loops) poison the total.
    pc = decode_phase_conv(0, 2, q)
    assert pc.rows[0].moved == MOVED_UNTRACKED
    assert pc.moved_total() is None
    assert pc.summary()["moved_total"] is None


def test_compile_watcher_nesting_restores_flag():
    prior = bool(jax.config.jax_log_compiles)
    events = []
    with CompileWatcher(on_event=events.append) as outer:
        assert bool(jax.config.jax_log_compiles) is True
        with CompileWatcher():
            pass
        # The inner watcher restored the flag to the OUTER True state.
        assert bool(jax.config.jax_log_compiles) is True
        assert outer in logging.getLogger("jax").handlers
    assert bool(jax.config.jax_log_compiles) is prior
    assert outer not in logging.getLogger("jax").handlers


def test_compile_watcher_nesting_outer_still_records():
    """The OUTER watcher keeps receiving compile events during a nested
    watcher's window — the inner one mutes jax's stream handler, never
    another watcher (a muted outer guard would let a mid-measurement
    recompile pass undetected)."""
    @jax.jit
    def nested_fresh(x):
        return x - 12

    with CompileWatcher() as outer:
        with CompileWatcher() as inner:
            nested_fresh(np.arange(23))  # unique shape: fresh compile
        assert inner.compiles
        assert outer.compiles, \
            "outer watcher lost compiles inside the nested window"
    assert len(outer.compiles) == len(inner.compiles)


def test_flight_recorder_no_trace_skips_emitter():
    """NO_TRACE: a recorder attached for its compile watcher / HBM
    ledger only (the bench; --metrics-out without --trace-out) builds no
    span records at all — and still collects compile events."""
    from cuvite_tpu.obs import NO_TRACE

    @jax.jit
    def fresh_fn2(x):
        return x * 5 - 3

    with FlightRecorder(NO_TRACE) as rec:
        assert rec.emitter is None and rec.sink is None
        tr = Tracer(recorder=rec)
        with tr.stage("iterate"):
            fresh_fn2(np.arange(29))  # unique shape: fresh compile
        tr.event("convergence", rows=[])  # facade no-ops, must not raise
    assert rec.compile_events, "NO_TRACE must not disable the watcher"
    assert tr.times.get("iterate", 0) > 0  # stage timing still works


class _FakeLogRecord:
    def __init__(self, msg):
        self._msg = msg

    def getMessage(self):
        return self._msg


def test_compile_watcher_prefix_names_pair_correctly():
    """A module whose name prefixes another ('step' vs 'step2') must not
    steal the other's completion: out-of-order completions pair with the
    right pending compile and no phantom dur_s=None event remains."""
    w = CompileWatcher()
    w.emit(_FakeLogRecord("Compiling step with global shapes and types"))
    w.emit(_FakeLogRecord("Compiling step2 with global shapes and types"))
    w.emit(_FakeLogRecord("Finished XLA compilation of jit(step2) in 0.2 sec"))
    w.emit(_FakeLogRecord("Finished XLA compilation of jit(step) in 0.1 sec"))
    assert w._pending == []
    assert [(e["module"], e["dur_s"]) for e in w.events] \
        == [("jit(step2)", 0.2), ("jit(step)", 0.1)]


def test_flight_recorder_records_compiles():
    @jax.jit
    def fresh_fn(x):
        return x * 3 + 41

    with FlightRecorder() as rec:
        fresh_fn(np.arange(17))  # unique shape: guaranteed fresh compile
    assert rec.compile_log, "watcher missed the fresh compile"
    assert rec.compile_events and "module" in rec.compile_events[0]
    names = [r.get("name") for r in rec.records if r.get("t") == "event"]
    assert "compile" in names


# ---------------------------------------------------------------------------
# tools/perf_regress.py: the regression gate (tier-1 runs the self-check
# so a malformed checked-in bench record can never land silently).


def test_perf_regress_self_check_tier1():
    out = subprocess.run(
        [sys.executable, PERF_REGRESS, "--self-check"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "self-check ok" in out.stdout


def _fresh_v4_record():
    """The r05 trajectory record upgraded to a self-describing v4 fresh
    record (what today's run_bench emits): perf_regress refuses to gate
    anything less."""
    with open(os.path.join(REPO, "BENCH_r05.json")) as f:
        rec = json.load(f)["parsed"]
    rec.update(
        schema=4, engine="bucketed", vs_baseline=None,
        graph=rec.get("graph", "rmat-18"),
        modularity=rec.get("modularity", 0.5),
        phases=rec.get("phases", 3),
        compile_guard={"checked": True, "new_compiles": 0},
        stages={"coarsen_s": 0.0, "coalesce_s": 0.0, "rebin_s": 0.0,
                "upload_s": 0.0, "iterate_s": 0.0},
        convergence_summary=[{"iterations": 1}],
        compile_events=[], hbm_peak_by_buffer={})
    return rec


def test_perf_regress_passes_real_trajectory(tmp_path):
    p = tmp_path / "fresh.json"
    p.write_text(json.dumps(_fresh_v4_record()))
    out = subprocess.run(
        [sys.executable, PERF_REGRESS, "--record", str(p)],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr


def test_perf_regress_flags_30pct_teps_drop(tmp_path):
    fresh = _fresh_v4_record()
    fresh["value"] = round(fresh["value"] * 0.65, 1)  # 35% drop
    p = tmp_path / "bad.json"
    p.write_text(json.dumps(fresh))
    out = subprocess.run(
        [sys.executable, PERF_REGRESS, "--record", str(p)],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 1
    assert "REGRESSION" in out.stderr and "TEPS" in out.stderr


def test_perf_regress_refuses_schemaless_fresh_record(tmp_path):
    """A fresh record with no int 'schema' must be refused (rc 2), not
    gated leniently: run_bench always stamps schema=4, so a missing
    field means record emission itself regressed."""
    fresh = _fresh_v4_record()
    del fresh["schema"]
    p = tmp_path / "schemaless.json"
    p.write_text(json.dumps(fresh))
    out = subprocess.run(
        [sys.executable, PERF_REGRESS, "--record", str(p)],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 2
    assert "SCHEMA FAIL" in out.stderr and "schema" in out.stderr


def test_perf_regress_stage_growth_and_floor():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from perf_regress import check_regression
    finally:
        sys.path.pop(0)
    traj = [("BENCH_rX.json", 9, {
        "metric": "louvain_teps_per_chip", "value": 100.0, "unit": "t/s",
        "platform": "cpu", "scale": 18,
        "stages": {"coarsen_s": 2.0, "upload_s": 0.01, "iterate_s": 10.0},
    })]
    fresh = {"metric": "louvain_teps_per_chip", "value": 98.0,
             "unit": "t/s", "platform": "cpu", "scale": 18,
             "stages": {"coarsen_s": 3.0, "upload_s": 0.4,
                        "iterate_s": 10.0}}
    probs = check_regression(fresh, traj, 0.30)
    assert any("coarsen_s" in p for p in probs)       # 50% growth trips
    assert not any("upload_s" in p for p in probs)    # sub-floor: noise
    assert not any("TEPS" in p for p in probs)        # 2% drop is fine
    # A different platform is a new baseline, not a regression.
    assert check_regression(dict(fresh, platform="tpu"), traj, 0.30) == []
    # A different input graph (both sides identified) is incomparable:
    # a road network's intrinsic TEPS is not an rmat regression.
    traj_g = [(p, n, dict(rec, graph="rmat-18")) for p, n, rec in traj]
    slow_other = dict(fresh, graph="road-usa", value=10.0)
    assert check_regression(slow_other, traj_g, 0.30) == []
    # Same for engine: a bucketed run is not gated against a pallas
    # ceiling (and a pallas regression is not hidden under bucketed's).
    traj_e = [(p, n, dict(rec, engine="pallas")) for p, n, rec in traj]
    slow_engine = dict(fresh, engine="bucketed", value=10.0)
    assert check_regression(slow_engine, traj_e, 0.30) == []
    # ISSUE 18: flat and two-level exchanges are separate arms — a
    # two-level record never gates against the flat trajectory, and
    # within the two-level arm the (dcn, ici) factorization must match
    # (2x4 and 4x2 pay different ICI/DCN splits by design).
    xb = {"mode": "twolevel", "dcn": 2, "ici": 4,
          "table_bytes_per_device": 1024, "ghost_bytes": 512}
    slow_two = dict(fresh, value=10.0, exchange=xb)
    assert check_regression(slow_two, traj, 0.30) == []
    traj_42 = [(p, n, dict(rec, exchange=dict(xb, dcn=4, ici=2)))
               for p, n, rec in traj]
    assert check_regression(slow_two, traj_42, 0.30) == []
    traj_24 = [(p, n, dict(rec, exchange=dict(xb)))
               for p, n, rec in traj]
    assert any("TEPS" in p
               for p in check_regression(slow_two, traj_24, 0.30))


def test_perf_regress_self_check_catches_malformed(tmp_path):
    good = {"n": 9, "cmd": "x", "rc": 0,
            "parsed": {"metric": "louvain_teps_per_chip", "value": 1.0,
                       "unit": "t/s"}}
    (tmp_path / "BENCH_r09.json").write_text(json.dumps(good))
    bad = dict(good, parsed={"metric": "louvain_teps_per_chip",
                             "value": -3.0, "unit": "t/s"})
    (tmp_path / "BENCH_r10.json").write_text(json.dumps(bad))
    out = subprocess.run(
        [sys.executable, PERF_REGRESS, "--self-check",
         "--bench-glob", str(tmp_path / "BENCH_*.json")],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 1
    assert "non-positive" in out.stderr
