"""Tier-1 gate for graftlint tier 6: widthcheck (R026-R028 static) +
the width audit (W001-W003 dynamic).

Four layers:

  * the audit itself must be green on the current tree — the scale-28
    zero-allocation certification IS a tier-1 test;
  * sabotage fixtures prove every rule convicts a seeded overflow
    (a gate that cannot fail is not a gate);
  * the width summaries ride the tier-2 lint cache bit-identically
    warm vs cold, while dynamic W00x results never enter it;
  * the single-source pins: widthcheck.MAX_WORKLOAD ==
    registry.max_workload(), BATCH_MAX == max(BATCH_SIZES), the
    width-ok inventory closed, R026-R028 present in SARIF.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuvite_tpu.analysis import widthaudit as wa
from cuvite_tpu.analysis import widthcheck as wc
from cuvite_tpu.analysis.callgraph import run_project_sources

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(findings):
    return sorted(f.rule for f in findings)


# ---------------------------------------------------------------------------
# The audit on the current tree (the certification gate).


def test_width_audit_green_on_current_tree():
    findings, reports = wa.run_width_audit()
    assert not findings, "\n".join(f.format() for f in findings)
    # Both certification workloads traced every entry.
    for wname in ("friendster", "rmat_s28"):
        assert set(reports[wname]) == set(wa.ENTRIES)
    # The zero-allocation pin: tracing the billion-edge path touched
    # NO device memory.
    assert reports["spy"]["delta_bytes"] == 0


def test_audit_workloads_derive_from_registry():
    from cuvite_tpu.workloads import registry

    wl = wa.audit_workloads()
    s28 = wl[f"rmat_s{registry.RMAT_SCALE_MAX}"]
    nv, ne = registry.rmat_scale_law(registry.RMAT_SCALE_MAX)
    assert s28["nv_pad"] == nv and s28["ne_pad"] == ne  # pow2 already
    # Every per-shard slab is admissible under the raise-guard.
    from cuvite_tpu.ops.segment import SLAB_NE_MAX

    for shapes in wl.values():
        assert shapes["ne_shard"] <= SLAB_NE_MAX
        assert shapes["ne_shard"] * shapes["shards"] == shapes["ne_pad"]


def test_max_workload_single_source():
    from cuvite_tpu.core.batch import BATCH_SIZES
    from cuvite_tpu.workloads import registry

    assert registry.max_workload() == wc.MAX_WORKLOAD
    assert registry.BATCH_MAX == max(BATCH_SIZES)


# ---------------------------------------------------------------------------
# Static sabotage: R026/R027/R028 each convict a seeded overflow.


def _lint(src: str, rel: str = "cuvite_tpu/ops/sab.py"):
    return run_project_sources({rel: src})


def test_r026_convicts_int32_slab_domain():
    findings = _lint(
        "import jax.numpy as jnp\n"
        "def flat(ne_pad):\n"
        "    idx = jnp.arange(ne_pad * ne_pad, dtype=jnp.int32)\n"
        "    return idx\n")
    assert "R026" in _rules(findings)


def test_r026_skips_raise_guarded_site():
    findings = _lint(
        "import jax.numpy as jnp\n"
        "CEIL = 1 << 30\n"
        "def flat(src):\n"
        "    ne_pad = src.shape[0]\n"
        "    if ne_pad > CEIL:\n"
        "        raise ValueError('shard the slab first')\n"
        "    idx = jnp.arange(ne_pad, dtype=jnp.int32)\n"
        "    brk = (idx != 0).astype(jnp.int32)\n"
        "    rid = jnp.cumsum(brk)\n"
        "    return rid\n")
    assert not findings, "\n".join(f.format() for f in findings)


def test_r027_convicts_untied_pack():
    findings = _lint(
        "def pack(src, ckey, kbits):\n"
        "    return (src << kbits) | ckey\n")
    assert "R027" in _rules(findings)


def test_r027_skips_pack_tied_to_guard():
    # The segment.py contract shape: the pack sits under a predicate
    # derived from the shift amount's own bit budget.
    findings = _lint(
        "def pack(src, ckey, key_bound, src_bound):\n"
        "    kbits = max(key_bound - 1, 1).bit_length()\n"
        "    sbits = max(src_bound - 1, 1).bit_length()\n"
        "    fits32 = kbits + sbits <= 31\n"
        "    if fits32:\n"
        "        return (src << kbits) | ckey\n"
        "    return None\n")
    assert "R027" not in _rules(findings)


def test_bare_pow2_shift_is_not_a_pack():
    # `1 << bit_length()` pow2 padding (next_pow2, pow2_floor, tree-sum
    # padding) must not read as a bit-pack.
    findings = _lint(
        "def next_pow2(n):\n"
        "    if n <= 1:\n"
        "        return 1\n"
        "    return 1 << (int(n - 1).bit_length())\n")
    assert not findings


def test_r028_convicts_int32_slab_reduction():
    findings = _lint(
        "import jax.numpy as jnp\n"
        "def run_ids(src):\n"
        "    brk = (src[1:] != src[:-1]).astype(jnp.int32)\n"
        "    return jnp.cumsum(brk)\n")
    assert "R028" in _rules(findings)


def test_width_ok_annotation_suppresses_and_feeds_inventory():
    src = ("import jax.numpy as jnp\n"
           "def flat(ne_pad):\n"
           "    return jnp.arange(ne_pad * ne_pad, dtype=jnp.int32)"
           "  # graftlint: width-ok=test reason\n")
    assert not _lint(src)
    from cuvite_tpu.analysis.callgraph import summarize
    from cuvite_tpu.analysis.engine import SourceFile

    sf = SourceFile(src, path="sab.py", rel="cuvite_tpu/ops/sab.py")
    inv = wc.width_inventory([summarize(sf)])
    assert len(inv) == 1 and inv[0]["reason"] == "test reason"


def test_non_device_path_files_carry_no_sites():
    # serve/ and obs/ hold no slab-extent index arithmetic by scope.
    from cuvite_tpu.analysis.engine import SourceFile

    sf = SourceFile("import jax.numpy as jnp\n"
                    "def f(ne_pad):\n"
                    "    return jnp.arange(ne_pad * ne_pad, "
                    "dtype=jnp.int32)\n",
                    path="d.py", rel="cuvite_tpu/serve/d.py")
    assert wc.width_summary(sf)["sites"] == []


# ---------------------------------------------------------------------------
# Dynamic sabotage: W001/W002 convict seeded overflows.


def test_w001_convicts_narrow_cumsum_over_wide_slab():
    def entry(mask):
        return jnp.cumsum(mask.astype(jnp.int32))

    jaxpr = jax.make_jaxpr(entry)(
        jax.ShapeDtypeStruct(((1 << 31) + 8,), jnp.bool_))
    findings = wa.index_width_findings(jaxpr, "sabotage", 32)
    assert findings and all(f.rule == "W001" for f in findings)
    assert findings[0].path == "<width:sabotage>"


def test_w001_passes_widest_legal_slab():
    from cuvite_tpu.ops.segment import SLAB_NE_MAX

    def entry(mask):
        return jnp.cumsum(mask.astype(jnp.int32))

    jaxpr = jax.make_jaxpr(entry)(
        jax.ShapeDtypeStruct((SLAB_NE_MAX,), jnp.bool_))
    assert not wa.index_width_findings(jaxpr, "ok", 32)


def test_w002_boundary_probes_green_under_code_laws():
    findings, facts = wa.boundary_probes(wa.code_laws())
    assert not findings, "\n".join(f.format() for f in findings)
    assert (1, "int32", 1) in facts["sort_widest_legal"]
    assert any(nk == 2 for nk, _dt, _nd in facts["sort_one_past"])
    assert (1, "int64", 1) in facts["sort_forced_64"]
    assert facts["slab_one_past"] == "raised"
    assert facts["flat_one_past"] == "raised"
    assert facts["accum"] == {"below": "float32", "at": "ds32",
                              "by_addends": "ds32"}


def test_w002_convicts_when_law_disagrees_with_code():
    # A manifest claiming a 30-bit pack budget makes the real 31-bit
    # packing look one-past — the probe must convict, proving W002 has
    # teeth when predicate and law drift apart.
    laws = dict(wa.code_laws(), pack_bits=30)
    findings, _facts = wa.boundary_probes(laws)
    assert any(f.rule == "W002" for f in findings)


def test_w003_fails_closed_on_missing_manifest(tmp_path):
    findings, _reports = wa.run_width_audit(
        entry_names=[], budget_path=str(tmp_path / "nope.json"),
        probes=False)
    assert _rules(findings) == ["W003"]
    assert "unreadable" in findings[0].message


def test_w003_convicts_drifted_manifest_law():
    manifest = {"version": wa.BUDGET_VERSION,
                "laws": dict(wa.code_laws(), slab_ne_max=1 << 20),
                "max_workload": wc.MAX_WORKLOAD}
    findings = wa.manifest_crosscheck(manifest)
    assert any(f.rule == "W003" and "slab_ne_max" in f.message
               for f in findings)


def test_w003_convicts_crashing_entry(monkeypatch):
    def boom(nv, ne):
        raise RuntimeError("seeded crash")

    monkeypatch.setitem(wa.ENTRIES, "solo_sort_step", (boom, True))
    findings, _ = wa.run_width_audit(
        entry_names=["solo_sort_step"], workloads=["rmat_s28"],
        probes=False)
    assert any(f.rule == "W003" and "seeded crash" in f.message
               for f in findings)


# ---------------------------------------------------------------------------
# Cache discipline: static width facts ride the summary cache
# bit-identically; dynamic W00x results never touch it.


def test_width_summary_rides_cache_warm_equals_cold(tmp_path):
    from cuvite_tpu.analysis.engine import run_paths

    # Lay the file out so its repo-relative rel lands under the
    # device-path prefix the interpreter scopes to.
    src_dir = tmp_path / "cuvite_tpu" / "ops"
    src_dir.mkdir(parents=True)
    src = src_dir / "sab.py"
    src.write_text("import jax.numpy as jnp\n"
                   "def flat(ne_pad):\n"
                   "    return jnp.arange(ne_pad * ne_pad, "
                   "dtype=jnp.int32)\n")
    cache = tmp_path / "cache.json"
    root = str(tmp_path / "cuvite_tpu")
    cold = run_paths([root], cache=str(cache))
    warm = run_paths([root], cache=str(cache))
    assert [f.to_dict() for f in cold] == [f.to_dict() for f in warm]
    assert "R026" in _rules(cold)
    doc = json.loads(cache.read_text())
    summaries = [e.get("summary") for e in doc.get("entries", {}).values()]
    assert any((s or {}).get("width", {}).get("sites")
               for s in summaries), \
        "width facts must ride the tier-2 summary cache"


@pytest.mark.slow
def test_width_audit_never_touches_lint_cache(tmp_path):
    """Tier-2 (slow): pays a full ~9 s dynamic width audit to pin a
    one-time layering invariant (dynamic W00x results never enter the
    lint cache). The audit's tier-1 sibling is
    test_width_audit_green_on_current_tree; the cache's byte-stability
    pins live in the tier-1 cache tests above."""
    from cuvite_tpu.analysis.engine import run_paths

    cache = tmp_path / "cache.json"
    src = tmp_path / "m.py"
    src.write_text("x = 1\n")
    run_paths([str(src)], cache=str(cache))
    before = cache.read_bytes()
    findings, _ = wa.run_width_audit(
        entry_names=["solo_sort_step"], workloads=["rmat_s28"],
        probes=False)
    assert not findings
    assert cache.read_bytes() == before, \
        "dynamic W00x results must never enter the lint cache"


# ---------------------------------------------------------------------------
# SARIF + CLI surfaces.


def test_sarif_roundtrip_includes_width_rules():
    from cuvite_tpu.analysis.__main__ import to_sarif

    findings = _lint(
        "import jax.numpy as jnp\n"
        "def flat(ne_pad):\n"
        "    return jnp.arange(ne_pad * ne_pad, dtype=jnp.int32)\n")
    doc = json.loads(json.dumps(to_sarif(findings)))
    rule_ids = {r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]}
    assert {"R026", "R027", "R028"} <= rule_ids
    results = doc["runs"][0]["results"]
    assert any(r["ruleId"] == "R026" for r in results)
    assert all(r["partialFingerprints"]["graftlintFingerprint/v1"]
               for r in results)


def test_width_audit_cli_inventory_subprocess():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "width_audit.py"),
         "--inventory", "--json"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr
    inv = json.loads(out.stdout)
    assert all(e["reason"] for e in inv)
    # The two deliberate 32-bit sites of this tree are in the closed
    # inventory: the dense flat-key domain and the per-vertex n_moved.
    rels = {e["rel"] for e in inv}
    assert "cuvite_tpu/kernels/seg_coalesce.py" in rels
    assert "cuvite_tpu/louvain/step.py" in rels


def test_width_audit_cli_write_budget(tmp_path):
    budget = tmp_path / "budget.json"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "width_audit.py"),
         "--write-budget", "--budget", str(budget)],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr
    doc = json.loads(budget.read_text())
    assert doc["version"] == wa.BUDGET_VERSION
    assert doc["laws"] == wa.code_laws()
    # The regenerated manifest is exactly the checked-in one: the
    # committed artifact cannot drift from the generator.
    committed = json.loads(
        open(os.path.join(REPO, "tools", "width_budget.json")).read())
    assert doc == committed
