"""Tracer / diagnostics tests."""

import numpy as np

from cuvite_tpu.louvain.driver import louvain_phases
from cuvite_tpu.utils.trace import NullTracer, Tracer, rss_high_water_mb


def test_tracer_stages_and_counters():
    tr = Tracer()
    with tr.stage("load"):
        pass
    with tr.stage("iterate"):
        pass
    with tr.stage("iterate"):
        pass
    tr.count("traversed_edges", 1000)
    assert tr.calls["iterate"] == 2
    assert tr.counters["traversed_edges"] == 1000
    rep = tr.report()
    assert "iterate" in rep and "TEPS" in rep and "rss high-water" in rep


def test_null_tracer_is_free():
    tr = NullTracer()
    with tr.stage("x"):
        pass
    tr.count("y")
    assert tr.times == {} and tr.counters == {}


def test_rss_positive():
    assert rss_high_water_mb() > 1.0


def test_driver_fills_tracer(karate):
    for engine in ("bucketed", "fused"):
        tr = Tracer()
        res = louvain_phases(karate, engine=engine, tracer=tr)
        assert res.modularity > 0.40
        assert tr.times.get("iterate", 0) > 0
        assert tr.counters["traversed_edges"] >= karate.num_edges
        assert tr.teps() > 0


def test_cli_trace_flag(tmp_path, karate, capsys):
    from cuvite_tpu.cli import main
    from cuvite_tpu.io.vite import write_vite

    p = str(tmp_path / "k.bin")
    write_vite(p, karate)
    main(["--file", p, "--bits64", "--trace", "--quiet"])
    out = capsys.readouterr().out
    assert "stage breakdown" in out and "TEPS" in out
