"""Tracer / diagnostics tests."""

import os

import numpy as np

from cuvite_tpu.louvain.driver import louvain_phases
from cuvite_tpu.utils.trace import NullTracer, Tracer, rss_high_water_mb


def test_tracer_stages_and_counters():
    tr = Tracer()
    with tr.stage("load"):
        pass
    with tr.stage("iterate"):
        pass
    with tr.stage("iterate"):
        pass
    tr.count("traversed_edges", 1000)
    assert tr.calls["iterate"] == 2
    assert tr.counters["traversed_edges"] == 1000
    rep = tr.report()
    assert "iterate" in rep and "TEPS" in rep and "rss high-water" in rep


def test_null_tracer_is_free():
    tr = NullTracer()
    with tr.stage("x"):
        pass
    tr.count("y")
    assert tr.times == {} and tr.counters == {}


def test_rss_positive():
    assert rss_high_water_mb() > 1.0


def test_driver_fills_tracer(karate):
    for engine in ("bucketed", "fused"):
        tr = Tracer()
        res = louvain_phases(karate, engine=engine, tracer=tr)
        assert res.modularity > 0.40
        assert tr.times.get("iterate", 0) > 0
        assert tr.counters["traversed_edges"] >= karate.num_edges
        assert tr.teps() > 0


def test_cli_trace_flag(tmp_path, karate, capsys):
    from cuvite_tpu.cli import main
    from cuvite_tpu.io.vite import write_vite

    p = str(tmp_path / "k.bin")
    write_vite(p, karate)
    main(["--file", p, "--bits64", "--trace", "--quiet"])
    out = capsys.readouterr().out
    assert "stage breakdown" in out and "TEPS" in out


def test_dist_stats_report(karate):
    from cuvite_tpu.core.distgraph import DistGraph
    from cuvite_tpu.utils.trace import dist_stats_report

    dg = DistGraph.build(karate, 4)
    rep = dist_stats_report(dg, ghost_counts=[3, 1, 2, 0])
    assert f"Number of vertices: {karate.num_vertices}" in rep
    assert f"Number of edges: {karate.num_edges}" in rep
    assert "Standard deviation:" in rep
    assert "Ghost vertices per shard: max 3" in rep
    counts = [sh.n_real_edges for sh in dg.shards]
    assert f"Maximum number of edges: {max(counts)}" in rep


def test_shard_diag_files(tmp_path, karate):
    """--diag-prefix writes one file per shard, a line per phase (the
    reference's dat.out.<rank>, main.cpp:101-110)."""
    prefix = str(tmp_path / "diag" / "dat.out")
    # exchange='sparse' explicitly: ghost counts only exist on the sparse
    # plan, and 'auto' routes a karate-sized graph to the replicated
    # exchange (no ghost plan to report).
    res = louvain_phases(karate, nshards=4, diag_prefix=prefix,
                         exchange="sparse")
    assert res.modularity > 0.40
    for s in range(4):
        lines = open(f"{prefix}.{s}").read().splitlines()
        # One line per phase ATTEMPT: the final no-gain phase writes its
        # line too but is not appended to res.phases.
        assert len(lines) >= len(res.phases)
        assert lines[0].startswith("phase 0: owned=")
        assert "ghosts=" in lines[0] and "Q=" in lines[0]


def test_shard_diag_lazy_file_creation(tmp_path):
    """No file exists until the first write for that shard (a 64-shard
    run that only diagnoses shard 3 creates ONE file)."""
    from cuvite_tpu.utils.trace import ShardDiag

    prefix = str(tmp_path / "sub" / "dat.out")
    with ShardDiag(prefix, nshards=4) as diag:
        assert not os.path.exists(os.path.dirname(prefix))
        diag.write(2, "hello")
        assert os.path.exists(f"{prefix}.2")
        assert not os.path.exists(f"{prefix}.0")
        assert not os.path.exists(f"{prefix}.1")


def test_shard_diag_truncates_on_reopen(tmp_path):
    """A rerun with the same prefix REPLACES each shard file (the
    reference's per-rank ofstreams truncate too): stale lines from a
    previous run never mix into a fresh diagnosis."""
    from cuvite_tpu.utils.trace import ShardDiag

    prefix = str(tmp_path / "dat.out")
    with ShardDiag(prefix, nshards=2) as diag:
        diag.write(0, "old run line 1")
        diag.write(0, "old run line 2")
        diag.write(1, "old shard-1 line")
    with ShardDiag(prefix, nshards=2) as diag:
        diag.write(0, "new run line")
        # Shard 1 never written this run: its file keeps the OLD content
        # (truncation is per-file on first write, not a prefix sweep).
    assert open(f"{prefix}.0").read().splitlines() == ["new run line"]
    assert open(f"{prefix}.1").read().splitlines() == ["old shard-1 line"]


def test_tracer_stage_reentrancy():
    """Nested stage() of the SAME name: the outer window CONTAINS the
    inner one, so the accumulated time double-counts the inner span by
    design (calls tells the reader how many windows there were), and a
    recorder sees properly nested spans."""
    import time

    from cuvite_tpu.obs import FlightRecorder, spans_of, validate_trace

    with FlightRecorder() as rec:
        tr = Tracer(recorder=rec)
        with tr.stage("iterate"):
            with tr.stage("iterate"):
                time.sleep(0.002)
    assert tr.calls["iterate"] == 2
    assert tr.times["iterate"] >= 2 * 0.002  # outer contains inner
    assert validate_trace(rec.records) == []
    spans = spans_of(rec.records, "iterate")
    assert len(spans) == 2
    outer = next(s for s in spans if s["begin"]["parent"] is None)
    inner = next(s for s in spans if s is not outer)
    assert inner["begin"]["parent"] == outer["id"]


def test_breakdown_keeps_sub_millisecond_stages():
    """ISSUE 6 satellite: breakdown() must NOT round — the historical
    round(v, 3) reported a 0.4 ms upload as 0.0, making real-vs-absent
    indistinguishable to the regression gate.  report() still rounds for
    humans."""
    tr = Tracer()
    tr.times["upload"] = 4.2e-4
    tr.times["iterate"] = 1.23456789
    tr.calls = {"upload": 1, "iterate": 1}
    bd = tr.breakdown()
    assert bd["upload_s"] == 4.2e-4      # full precision survives
    assert bd["iterate_s"] == 1.23456789
    assert bd["coarsen_s"] == 0.0        # canonical stages always present


def test_cli_dist_stats_flag(tmp_path, karate, capsys):
    from cuvite_tpu.cli import main
    from cuvite_tpu.io.vite import write_vite

    p = str(tmp_path / "k.bin")
    write_vite(p, karate)
    main(["--file", p, "--bits64", "--dist-stats", "--shards", "2",
          "--quiet"])
    out = capsys.readouterr().out
    assert "Graph edge distribution characteristics" in out
