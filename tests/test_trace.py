"""Tracer / diagnostics tests."""

import numpy as np

from cuvite_tpu.louvain.driver import louvain_phases
from cuvite_tpu.utils.trace import NullTracer, Tracer, rss_high_water_mb


def test_tracer_stages_and_counters():
    tr = Tracer()
    with tr.stage("load"):
        pass
    with tr.stage("iterate"):
        pass
    with tr.stage("iterate"):
        pass
    tr.count("traversed_edges", 1000)
    assert tr.calls["iterate"] == 2
    assert tr.counters["traversed_edges"] == 1000
    rep = tr.report()
    assert "iterate" in rep and "TEPS" in rep and "rss high-water" in rep


def test_null_tracer_is_free():
    tr = NullTracer()
    with tr.stage("x"):
        pass
    tr.count("y")
    assert tr.times == {} and tr.counters == {}


def test_rss_positive():
    assert rss_high_water_mb() > 1.0


def test_driver_fills_tracer(karate):
    for engine in ("bucketed", "fused"):
        tr = Tracer()
        res = louvain_phases(karate, engine=engine, tracer=tr)
        assert res.modularity > 0.40
        assert tr.times.get("iterate", 0) > 0
        assert tr.counters["traversed_edges"] >= karate.num_edges
        assert tr.teps() > 0


def test_cli_trace_flag(tmp_path, karate, capsys):
    from cuvite_tpu.cli import main
    from cuvite_tpu.io.vite import write_vite

    p = str(tmp_path / "k.bin")
    write_vite(p, karate)
    main(["--file", p, "--bits64", "--trace", "--quiet"])
    out = capsys.readouterr().out
    assert "stage breakdown" in out and "TEPS" in out


def test_dist_stats_report(karate):
    from cuvite_tpu.core.distgraph import DistGraph
    from cuvite_tpu.utils.trace import dist_stats_report

    dg = DistGraph.build(karate, 4)
    rep = dist_stats_report(dg, ghost_counts=[3, 1, 2, 0])
    assert f"Number of vertices: {karate.num_vertices}" in rep
    assert f"Number of edges: {karate.num_edges}" in rep
    assert "Standard deviation:" in rep
    assert "Ghost vertices per shard: max 3" in rep
    counts = [sh.n_real_edges for sh in dg.shards]
    assert f"Maximum number of edges: {max(counts)}" in rep


def test_shard_diag_files(tmp_path, karate):
    """--diag-prefix writes one file per shard, a line per phase (the
    reference's dat.out.<rank>, main.cpp:101-110)."""
    prefix = str(tmp_path / "diag" / "dat.out")
    # exchange='sparse' explicitly: ghost counts only exist on the sparse
    # plan, and 'auto' routes a karate-sized graph to the replicated
    # exchange (no ghost plan to report).
    res = louvain_phases(karate, nshards=4, diag_prefix=prefix,
                         exchange="sparse")
    assert res.modularity > 0.40
    for s in range(4):
        lines = open(f"{prefix}.{s}").read().splitlines()
        # One line per phase ATTEMPT: the final no-gain phase writes its
        # line too but is not appended to res.phases.
        assert len(lines) >= len(res.phases)
        assert lines[0].startswith("phase 0: owned=")
        assert "ghosts=" in lines[0] and "Q=" in lines[0]


def test_cli_dist_stats_flag(tmp_path, karate, capsys):
    from cuvite_tpu.cli import main
    from cuvite_tpu.io.vite import write_vite

    p = str(tmp_path / "k.bin")
    write_vite(p, karate)
    main(["--file", p, "--bits64", "--dist-stats", "--shards", "2",
          "--quiet"])
    out = capsys.readouterr().out
    assert "Graph edge distribution characteristics" in out
