"""Scheduling variants: class-restricted coloring sweeps, vertex-ordering
(frozen community info), and the on-device ET loop."""

import numpy as np
import pytest

import jax.numpy as jnp

from cuvite_tpu.core.distgraph import DistGraph
from cuvite_tpu.evaluate.modularity import modularity as mod_oracle
from cuvite_tpu.io.generate import generate_rgg, generate_rmat
from cuvite_tpu.louvain.driver import PhaseRunner, louvain_phases


def test_class_restricted_sweep_matches_full_sweep_masked():
    """A class's restricted-plan step must decide exactly what the full
    sweep decides for that class's vertices (same state, same formulas) —
    the optimization changes cost, not semantics."""
    from cuvite_tpu.louvain.bucketed import BucketPlan
    from cuvite_tpu.louvain.driver import _bucketed_class_jit, _bucketed_jit

    g = generate_rmat(9, edge_factor=8, seed=2)
    dg = DistGraph.build(g, 1)
    sh = dg.shards[0]
    nvp = dg.nv_pad
    nvt = dg.total_padded_vertices
    vdt, wdt = np.int32, np.float32
    sentinel = np.iinfo(vdt).max
    rng = np.random.default_rng(0)
    cls = rng.integers(0, 4, nvp).astype(np.int32)

    src_np = np.asarray(sh.src)
    full_plan = BucketPlan.build(src_np, np.asarray(sh.dst),
                                 np.asarray(sh.w), nv_local=nvp, base=0)

    def upload(plan):
        bk = tuple((jnp.asarray(b.verts.astype(vdt)),
                    jnp.asarray(b.dst.astype(vdt)),
                    jnp.asarray(b.w.astype(wdt))) for b in plan.buckets)
        hv = (jnp.asarray(plan.heavy_src.astype(vdt)),
              jnp.asarray(plan.heavy_dst.astype(vdt)),
              jnp.asarray(plan.heavy_w.astype(wdt)))
        return bk, hv, jnp.asarray(plan.self_loop.astype(wdt))

    fb, fh, fs = upload(full_plan)
    comm = jnp.arange(nvt, dtype=vdt)
    vdeg = jnp.asarray(dg.padded_weighted_degrees().astype(wdt))
    const = jnp.asarray(1.0 / g.total_edge_weight_twice(), dtype=wdt)

    # advance two plain iterations for a non-trivial state
    for _ in range(2):
        comm = _bucketed_jit(fb, fh, fs, comm, vdeg, const, nv_total=nvt,
                             sentinel=sentinel, accum_dtype="float32")[0]

    full_tgt = _bucketed_jit(fb, fh, fs, comm, vdeg, const, nv_total=nvt,
                             sentinel=sentinel, accum_dtype="float32")[0]
    for c in range(4):
        src_c = np.where(
            (src_np < nvp) & (cls[np.minimum(src_np, nvp - 1)] == c),
            src_np, nvp).astype(src_np.dtype)
        pc = BucketPlan.build(src_c, np.asarray(sh.dst), np.asarray(sh.w),
                              nv_local=nvp, base=0)
        cb, ch, cs = upload(pc)
        tgt_c = _bucketed_class_jit(cb, ch, cs, comm, comm, vdeg, const,
                                    nv_total=nvt, sentinel=sentinel,
                                    accum_dtype="float32")[0]
        in_c = cls == c
        np.testing.assert_array_equal(
            np.asarray(tgt_c)[in_c], np.asarray(full_tgt)[in_c],
            err_msg=f"class {c} decisions differ from full sweep")
        # vertices outside the class never move in the class step
        np.testing.assert_array_equal(
            np.asarray(tgt_c)[~in_c], np.asarray(comm)[~in_c])


def test_vertex_ordering_uses_frozen_info():
    """info_comm must change decisions once comm has drifted from the
    snapshot — the mechanism that makes -d a real variant."""
    from cuvite_tpu.louvain.bucketed import BucketPlan
    from cuvite_tpu.louvain.driver import _bucketed_class_jit, _bucketed_jit

    g = generate_rmat(9, edge_factor=8, seed=2)
    dg = DistGraph.build(g, 1)
    sh = dg.shards[0]
    nvt = dg.total_padded_vertices
    vdt, wdt = np.int32, np.float32
    sentinel = np.iinfo(vdt).max
    plan = BucketPlan.build(np.asarray(sh.src), np.asarray(sh.dst),
                            np.asarray(sh.w), nv_local=dg.nv_pad, base=0)
    bk = tuple((jnp.asarray(b.verts.astype(vdt)),
                jnp.asarray(b.dst.astype(vdt)),
                jnp.asarray(b.w.astype(wdt))) for b in plan.buckets)
    hv = (jnp.asarray(plan.heavy_src.astype(vdt)),
          jnp.asarray(plan.heavy_dst.astype(vdt)),
          jnp.asarray(plan.heavy_w.astype(wdt)))
    sl = jnp.asarray(plan.self_loop.astype(wdt))
    comm0 = jnp.arange(nvt, dtype=vdt)
    vdeg = jnp.asarray(dg.padded_weighted_degrees().astype(wdt))
    const = jnp.asarray(1.0 / g.total_edge_weight_twice(), dtype=wdt)

    comm1 = _bucketed_jit(bk, hv, sl, comm0, vdeg, const, nv_total=nvt,
                          sentinel=sentinel, accum_dtype="float32")[0]
    assert not np.array_equal(np.asarray(comm0), np.asarray(comm1))
    fresh = _bucketed_class_jit(bk, hv, sl, comm1, comm1, vdeg, const,
                                nv_total=nvt, sentinel=sentinel,
                                accum_dtype="float32")[0]
    frozen = _bucketed_class_jit(bk, hv, sl, comm1, comm0, vdeg, const,
                                 nv_total=nvt, sentinel=sentinel,
                                 accum_dtype="float32")[0]
    assert not np.array_equal(np.asarray(fresh), np.asarray(frozen)), \
        "frozen community info produced identical decisions (no-op -d)"


def test_vertex_ordering_end_to_end_quality_and_difference():
    g = generate_rgg(512, seed=9)
    r_plain = louvain_phases(g)
    r_order = louvain_phases(g, vertex_ordering=8)
    q_plain = mod_oracle(g, r_plain.communities)
    q_order = mod_oracle(g, r_order.communities)
    assert q_order >= 0.8 * q_plain
    # -d must actually change the run (iteration trajectory or result)
    traj_plain = [(p.iterations, p.num_vertices) for p in r_plain.phases]
    traj_order = [(p.iterations, p.num_vertices) for p in r_order.phases]
    assert (traj_plain != traj_order
            or not np.array_equal(r_plain.communities, r_order.communities))


@pytest.mark.parametrize("et_mode", [1, 2, 3, 4])
def test_et_device_loop_converges(karate, et_mode):
    res = louvain_phases(karate, et_mode=et_mode, et_delta=0.25)
    q = mod_oracle(karate, res.communities)
    assert q >= 0.3
    assert res.modularity == pytest.approx(q, abs=1e-6)


def test_et_freeze_reduces_or_keeps_work():
    g = generate_rgg(512, seed=9)
    r0 = louvain_phases(g)
    r3 = louvain_phases(g, et_mode=3)
    assert mod_oracle(g, r3.communities) >= 0.8 * mod_oracle(g, r0.communities)


def test_coloring_multishard_still_works(karate):
    res = louvain_phases(karate, nshards=4, coloring=8)
    assert mod_oracle(karate, res.communities) >= 0.38


def test_coloring_sort_engine_warns(karate):
    """Degradations must be loud (VERDICT r2 weak #8): coloring on the sort
    engine runs the legacy n_classes-full-sweeps schedule and says so.
    (Multi-shard bucketed+replicated coloring is now class-restricted and
    must NOT warn — see test_coloring_multishard_matches_single.)"""
    with pytest.warns(UserWarning, match="full sweeps"):
        louvain_phases(karate, nshards=4, engine="sort", coloring=8)


def test_vertex_ordering_sparse_exchange_supported(karate):
    """Sparse-exchange ordering is a supported config since r4 (class plans
    stacked over the ghost routing) — it must NOT degrade or warn.  The
    former plain-schedule fallback warning is pinned gone here; trajectory
    equality is pinned by test_ordering_multishard_sparse_matches_single."""
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")
        res = louvain_phases(karate, nshards=4, vertex_ordering=8,
                             exchange="sparse")
    assert res.modularity > 0.40


def test_coloring_multishard_matches_single(karate):
    """Distributed class-restricted coloring (VERDICT r2 item 8): the
    8-shard schedule must reproduce the single-shard class-restricted
    trajectory exactly (unit weights: every reduction is fp-exact)."""
    import warnings as _w

    r1 = louvain_phases(karate, coloring=8)
    with _w.catch_warnings():
        _w.simplefilter("error")  # supported config: no degradation warning
        r8 = louvain_phases(karate, nshards=8, coloring=8,
                            exchange="replicated")
    assert np.array_equal(r8.communities, r1.communities)
    assert r8.modularity == pytest.approx(r1.modularity, abs=1e-6)


def test_ordering_multishard_matches_single():
    g = generate_rmat(10, edge_factor=8, seed=4)
    r1 = louvain_phases(g, vertex_ordering=8)
    r4 = louvain_phases(g, nshards=4, vertex_ordering=8,
                        exchange="replicated")
    assert np.array_equal(r4.communities, r1.communities)


def test_vertex_ordering_sort_engine_auto_switches(karate):
    """sort x ordering now auto-switches to the class-capable bucketed
    engine (VERDICT r5 weak #4) instead of silently degrading to the
    plain schedule; the degradation warning survives only under the
    explicit CUVITE_KEEP_SORT_COLORING opt-out, where the sort engine
    genuinely cannot run the ordered schedule."""
    with pytest.warns(UserWarning, match="auto-switching"):
        r = louvain_phases(karate, engine="sort", vertex_ordering=8)
    r_ref = louvain_phases(karate, engine="bucketed", vertex_ordering=8)
    np.testing.assert_array_equal(r.communities, r_ref.communities)


def test_vertex_ordering_sort_engine_opt_out_warns_plain_fallback(
        karate, monkeypatch):
    monkeypatch.setenv("CUVITE_KEEP_SORT_COLORING", "1")
    with pytest.warns(UserWarning, match="PLAIN schedule"):
        louvain_phases(karate, engine="sort", vertex_ordering=8)


def test_sparse_exchange_sort_engine_warns(karate):
    """exchange='sparse' on the sort engine must not be silently ignored."""
    with pytest.warns(UserWarning, match="sort engine"):
        louvain_phases(karate, nshards=4, engine="sort", exchange="sparse")


def test_env_int_malformed_warns(monkeypatch):
    from cuvite_tpu.louvain.bucketed import _env_int

    monkeypatch.setenv("CUVITE_TEST_KNOB", "25x6")
    with pytest.warns(UserWarning, match="CUVITE_TEST_KNOB"):
        assert _env_int("CUVITE_TEST_KNOB", 7) == 7
    monkeypatch.setenv("CUVITE_TEST_KNOB", "256")
    assert _env_int("CUVITE_TEST_KNOB", 7) == 256


def test_coloring_multishard_sparse_matches_single(karate):
    """Class-restricted coloring ON THE SPARSE EXCHANGE (VERDICT r3 item
    5): per-class plans stacked over the phase ghost routing must
    reproduce the single-shard class-restricted trajectory exactly, with
    no degradation warning."""
    import warnings as _w

    r1 = louvain_phases(karate, coloring=8)
    with _w.catch_warnings():
        _w.simplefilter("error")  # supported config: no degradation warning
        r8 = louvain_phases(karate, nshards=8, coloring=8,
                            exchange="sparse")
    assert np.array_equal(r8.communities, r1.communities)
    assert r8.modularity == pytest.approx(r1.modularity, abs=1e-6)


@pytest.mark.slow
def test_ordering_multishard_sparse_matches_single():
    """Vertex ordering on the sparse exchange: the frozen community-info
    tables ride the exchange's separate info grouping.

    slow: ~23 s — ordering×multishard stays tier-1 on the replicated
    exchange (test_ordering_multishard_matches_single) and
    coloring×sparse via test_coloring_multishard_sparse_matches_single.

    Runs in a FRESH subprocess: this test owns the single largest compile
    in the suite (sharded per-class sparse steps), and an xdist worker
    that reaches it with a long compile history segfaults inside that
    XLA:CPU LLVM compile (the cumulative-state crash pytest.ini
    documents; reproduced at -n 2 and -n 3, never in a fresh process).
    Isolation also lets the compile FINISH once, after which the
    persistent cache serves it everywhere."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = r"""
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
from cuvite_tpu.utils.compile_cache import enable_compile_cache
enable_compile_cache()
import numpy as np
from cuvite_tpu.io.generate import generate_rmat
from cuvite_tpu.louvain.driver import louvain_phases
g = generate_rmat(10, edge_factor=8, seed=4)
r1 = louvain_phases(g, vertex_ordering=8)
r4 = louvain_phases(g, nshards=4, vertex_ordering=8, exchange="sparse")
assert np.array_equal(r4.communities, r1.communities), "mismatch"
print("OK", r1.modularity)
"""
    env = dict(os.environ, PYTHONPATH=repo)
    env.pop("XLA_FLAGS", None)
    out = subprocess.run([sys.executable, "-c", script], env=env,
                         capture_output=True, text=True, timeout=840)
    assert out.returncode == 0, f"subprocess failed:\n{out.stdout[-2000:]}\n{out.stderr[-2000:]}"
    assert "OK" in out.stdout
