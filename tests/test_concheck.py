"""Tier-4 concurrency checker tests (ISSUE 13): the cooperative
scheduler seam, vector-clock race detection, the resurrected PR-12
``_routes`` race (true positive) against the fixed daemon (true
negative), the no-lock-across-send pin, drain racing a transient-retry
backoff, the R020/R021 static rules, cache bit-identity for the static
lock summaries, and the SARIF/env-knob plumbing.

The dynamic tests run the REAL ServeDaemon code (handle/_dispatch_loop/
request_drain) with the stub runner on the virtual clock — hundreds of
distinct interleavings cost seconds and zero real sleeps; every failing
schedule is replayable from its (strategy, seed) pair.
"""

import json
import os
import subprocess
import sys

import pytest

from cuvite_tpu.analysis import concheck, run_paths, run_source
from cuvite_tpu.analysis.callgraph import run_project_sources
from cuvite_tpu.serve import sync

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# One serve_inventory() parse shared by every dynamic test in the file.
INVENTORY = concheck.serve_inventory()


def scenario(name: str) -> concheck.DaemonScenario:
    s = concheck.builtin_scenarios()[name][0]()
    s.inventory = INVENTORY
    return s


# ---------------------------------------------------------------------------
# Inventory: seeded from the R019 lockset summaries


def test_inventory_seeded_from_lockset_summaries():
    fields = {(e["class"], e["field"]) for e in INVENTORY}
    # the PR-12 race field and the declared ServeStats counters
    assert ("ServeDaemon", "_routes") in fields
    assert ("ServeStats", "jobs_done") in fields
    assert ("ServeStats", "wait_samples") in fields
    declared = {(e["class"], e["field"]) for e in INVENTORY
                if e["declared"]}
    assert ("ServeStats", "jobs_done") in declared
    # inference-only entries carry declared=False
    routes = [e for e in INVENTORY
              if (e["class"], e["field"]) == ("ServeDaemon", "_routes")]
    assert routes and not routes[0]["declared"]
    assert routes[0]["locks"] == ["self.lock"]


# ---------------------------------------------------------------------------
# THE regression pin: the PR-12 _routes race, resurrected


def test_routes_race_detected_within_default_budget():
    """True positive: the lock-free _route_results pop racing intake's
    locked check-then-insert MUST be convicted — and the failing
    schedule must replay from its seed."""
    rep = concheck.explore(scenario("racy-routes"), budget=32, seed=0,
                           stop_on_failure=True)
    assert not rep.clean, "the resurrected _routes race went undetected"
    races = rep.races()
    assert any(r["field"] == "ServeDaemon._routes" for r in races), races
    race = next(r for r in races if r["field"] == "ServeDaemon._routes")
    # both access stacks are reported, anchored in daemon code
    for side in ("first", "second"):
        assert race[side]["stack"], race
        assert any("daemon.py" in frame[0]
                   for frame in race[side]["stack"]), race[side]
    # replay-from-seed: the SAME (strategy, seed) convicts again,
    # deterministically, on a fresh scenario instance
    failing = rep.failing[0]
    replay = concheck.run_schedule(scenario("racy-routes"),
                                   seed=failing.seed,
                                   strategy=failing.strategy)
    assert any(r["field"] == "ServeDaemon._routes" for r in replay.races)
    assert replay.signature == failing.signature


def test_fixed_daemon_clean_on_the_convicting_seeds():
    """True negative: the shipped daemon (locked pops) explores clean
    on the exact seeds that convict the racy variant."""
    racy = concheck.explore(scenario("racy-routes"), budget=32, seed=0,
                            stop_on_failure=True)
    assert racy.failing
    for failing in racy.failing[:2]:
        rep = concheck.run_schedule(scenario("clean"), seed=failing.seed,
                                    strategy=failing.strategy)
        assert rep.clean, (rep.failures, rep.races)


def test_clean_tree_conservation_across_200_interleavings():
    """The acceptance gate: the current serve/ tree explores clean —
    zero races, zero deadlocks, zero assertion failures — and job
    conservation + exactly-once delivery hold across >= 200 DISTINCT
    interleavings (every schedule's post-run check asserts them)."""
    budget = max(concheck.schedule_budget(), 200)
    rep = concheck.explore(scenario("clean"), budget=budget, seed=7)
    assert rep.clean, (rep.failures()[:3], rep.races()[:3])
    assert rep.schedules == budget
    assert rep.distinct >= 200, \
        f"only {rep.distinct} distinct interleavings explored"
    assert not rep.warnings, rep.warnings


def test_conservation_check_has_teeth():
    """The per-schedule invariant check must actually convict a broken
    ledger — tamper with a counter after a clean run and re-check."""
    scen = scenario("clean")
    det = concheck.RaceDetector()
    sched = sync.Scheduler(seed=3, strategy="random", detector=det)
    with sync.activated(sched):
        ctx = scen.setup(sched)
    sched.run()
    scen.check(sched, ctx)
    assert not sched.failures
    with ctx["server"].stats.lock:
        ctx["server"].stats.jobs_done += 1      # break the ledger
    scen.check(sched, ctx)
    assert any(f["kind"] == "conservation" for f in sched.failures)


# ---------------------------------------------------------------------------
# No lock held across a socket send (the PR-12 claim, pinned)


def test_send_under_lock_is_convicted():
    rep = concheck.explore(scenario("send-under-lock"), budget=16,
                           seed=0, stop_on_failure=True)
    assert not rep.clean
    kinds = {f["kind"] for f in rep.failures()}
    assert "lock-across-send" in kinds, kinds
    msg = next(f for f in rep.failures()
               if f["kind"] == "lock-across-send")["message"]
    assert "ServeDaemon.lock" in msg


def test_shipped_daemon_never_sends_under_a_lock():
    rep = concheck.explore(scenario("clean"), budget=24, seed=11)
    assert not any(f["kind"] == "lock-across-send"
                   for f in rep.failures()), rep.failures()


# ---------------------------------------------------------------------------
# SIGTERM drain racing a pending transient-retry backoff


def test_drain_races_retry_backoff_terminates_exactly_once():
    """device:transient:n=1 puts the dispatcher into a virtual-time
    retry backoff; the drainer requests drain at an arbitrary point of
    every schedule.  The retrying job must terminate exactly once,
    conservation must hold, and the daemon must complete the drain —
    all asserted per schedule by DaemonScenario.check.  At least one
    explored schedule must interleave the drain REQUEST inside the
    pending backoff window (the satellite's target interleaving)."""
    scen = scenario("drain-vs-retry")
    drain_during_backoff = 0
    for i in range(24):
        rep = concheck.run_schedule(scen, seed=900 + i,
                                    strategy=("random", "pct")[i % 2])
        assert rep.clean, (rep.seed, rep.failures, rep.races)
        dispatch_sleep = drain_set = None
        for step, (tname, op, detail) in enumerate(rep.trace):
            if tname == "dispatch" and op == "sleep" \
                    and dispatch_sleep is None:
                dispatch_sleep = step
            if tname == "drainer" and op == "set" \
                    and "drain_req" in detail:
                drain_set = step
        if dispatch_sleep is not None and drain_set is not None \
                and drain_set > dispatch_sleep:
            drain_during_backoff += 1
    assert drain_during_backoff >= 1, \
        "no schedule interleaved the drain request with the retry " \
        "backoff — the scenario lost its targeting"


# ---------------------------------------------------------------------------
# The pipelined dispatcher (ISSUE 14): packer/executor/intake/drainer


def test_pipeline_clean_across_200_interleavings():
    """The ISSUE-14 acceptance gate: the PIPELINED daemon — packer +
    executor seam-threads, intake, poller, drainer — explores clean
    (0 races / deadlocks / assertion failures) and job conservation +
    wire-level exactly-once hold across >= 200 DISTINCT interleavings
    (asserted per schedule by DaemonScenario.check)."""
    budget = max(concheck.schedule_budget(), 200)
    rep = concheck.explore(scenario("pipeline-clean"), budget=budget,
                           seed=17)
    assert rep.clean, (rep.failures()[:3], rep.races()[:3])
    assert rep.schedules == budget
    assert rep.distinct >= 200, \
        f"only {rep.distinct} distinct interleavings explored"
    assert not rep.warnings, rep.warnings


def test_delta_vs_drain_across_200_interleavings():
    """The ISSUE-17 acceptance gate: two tenants' delta streams (every
    request re-uploads, so a 1500-byte budget over 1000-byte stub
    sessions keeps the StreamPool admitting/evicting) racing intake and
    a mid-run drain explore clean, with delta exactly-once, StreamPool
    session/byte conservation, and zero residents after the drain
    epilogue asserted per schedule (DaemonScenario.check)."""
    budget = max(concheck.schedule_budget(), 200)
    rep = concheck.explore(scenario("delta-vs-drain"), budget=budget,
                           seed=29)
    assert rep.clean, (rep.failures()[:3], rep.races()[:3])
    assert rep.schedules == budget
    assert rep.distinct >= 200, \
        f"only {rep.distinct} distinct interleavings explored"
    assert not rep.warnings, rep.warnings


def test_pipeline_faulty_explores_clean():
    """Transient pack + device faults through the pipelined dispatcher:
    retries fire in their home stages (pack on the packer, device on
    the executor) and every schedule still conserves + delivers
    exactly once."""
    rep = concheck.explore(scenario("pipeline-faulty"), budget=24,
                           seed=23)
    assert rep.clean, (rep.failures()[:3], rep.races()[:3])


def test_drain_vs_inflight_pack_flushes_handoff_exactly_once():
    """A drain requested MID-PACK (the packer parked at its in-pack
    schedule point) must flush the in-flight PackedBatch through the
    handoff slot exactly once, then the bins — asserted per schedule
    by the exactly-once check.  The trace scan proves >= 1 schedule
    actually interleaved the drain request inside the pack window
    (between the packer's in-pack sleep and its handoff acquisition),
    so the scenario targets what it claims to."""
    scen = scenario("drain-vs-inflight-pack")
    drain_mid_pack = 0
    for i in range(24):
        rep = concheck.run_schedule(scen, seed=700 + i,
                                    strategy=("random", "pct")[i % 2])
        assert rep.clean, (rep.seed, rep.failures, rep.races)
        pack_sleep = None
        handoff_after = None
        drain_set = None
        for step, (tname, op, detail) in enumerate(rep.trace):
            if tname == "packer" and op == "sleep" and pack_sleep is None:
                pack_sleep = step
            if tname == "packer" and op == "acquire" \
                    and detail == "Handoff.lock" and pack_sleep is not None \
                    and handoff_after is None:
                handoff_after = step
            if tname == "drainer" and op == "set" \
                    and "drain_req" in detail:
                drain_set = step
        if pack_sleep is not None and handoff_after is not None \
                and drain_set is not None \
                and pack_sleep < drain_set < handoff_after:
            drain_mid_pack += 1
    assert drain_mid_pack >= 1, \
        "no schedule interleaved the drain request inside an " \
        "in-flight pack — the scenario lost its targeting"


def test_routes_race_still_convicted_with_pipeline_scenarios_present():
    """The resurrected PR-12 fixtures keep convicting after the
    scenario registry grew the pipeline entries (a checker that stops
    seeing known bugs is broken)."""
    names = set(concheck.builtin_scenarios())
    assert {"pipeline-clean", "pipeline-faulty",
            "drain-vs-inflight-pack"} <= names
    rep = concheck.explore(scenario("racy-routes"), budget=32, seed=1,
                           stop_on_failure=True)
    assert not rep.clean
    rep = concheck.explore(scenario("send-under-lock"), budget=16,
                           seed=1, stop_on_failure=True)
    assert not rep.clean


# ---------------------------------------------------------------------------
# Vector-clock semantics (unit level)


def _two_thread_run(body1, body2, *, seed=0, inventory=None):
    det = concheck.RaceDetector()
    sched = sync.Scheduler(seed=seed, strategy="random", detector=det)

    class Shared:
        def __init__(self):
            self.lock = sync.Lock()
            self.other_lock = sync.Lock()
            self.ev = sync.Event()
            self.x = 0

    with sync.activated(sched):
        obj = Shared()
        obj.lock.name = "Shared.lock"
        inv = inventory if inventory is not None else [
            {"class": "Shared", "owner": "self", "field": "x",
             "locks": ["self.lock"], "declared": False}]
        concheck.instrument(sched, [obj], inv)
        sched.spawn(body1, name="w1", args=(obj,))
        sched.spawn(body2, name="w2", args=(obj,))
    sched.run()
    return sched, det, obj


def test_vc_locked_increments_are_not_a_race():
    def w(obj):
        with obj.lock:
            obj.x += 1

    for seed in range(6):
        sched, det, obj = _two_thread_run(w, w, seed=seed)
        assert not det.races, det.races
        assert not sched.failures
        assert obj.x == 2


def test_vc_unlocked_write_write_is_a_race():
    def w(obj):
        obj.x += 1

    convicted = 0
    for seed in range(6):
        _sched, det, _obj = _two_thread_run(w, w, seed=seed)
        convicted += bool(det.races)
    # happens-before conviction does not depend on hitting the bad
    # interleaving: EVERY schedule convicts
    assert convicted == 6


def test_vc_event_set_wait_orders_the_handoff():
    """set() -> observed wait() is a happens-before edge: publish via
    event, consume after wait — no race, in every schedule."""

    def producer(obj):
        obj.x = 41
        obj.ev.set()

    def consumer(obj):
        if obj.ev.wait(timeout=10.0):
            obj.x += 1

    for seed in range(6):
        _sched, det, obj = _two_thread_run(producer, consumer, seed=seed)
        assert not det.races, (seed, det.races)
        assert obj.x == 42


def test_vc_mixed_lock_is_still_a_race():
    """One side under lock A, the other under lock B: mutual exclusion
    in name only — still unordered, still convicted."""

    def w1(obj):
        with obj.lock:
            obj.x += 1

    def w2(obj):
        with obj.other_lock:
            obj.x += 1

    convicted = 0
    for seed in range(6):
        _sched, det, _obj = _two_thread_run(w1, w2, seed=seed)
        convicted += bool(det.races)
    assert convicted == 6


def test_vc_event_clear_resets_the_hb_edge():
    """Soundness: after clear(), a wait released by a LATER set must
    join only that setter's clock — a stale event clock would fabricate
    happens-before with the ORIGINAL setter and mask its race.  Virtual
    sleeps pin the order: A writes+sets at t0, B (never synced with A)
    clears at t0+1 and re-sets at t0+2, C waits at t0+3 and reads."""
    det = concheck.RaceDetector()
    sched = sync.Scheduler(seed=0, strategy="random", detector=det)

    class Shared:
        def __init__(self):
            self.lock = sync.Lock()
            self.ev = sync.Event()
            self.x = 0

    def a(obj):
        obj.x = 1
        obj.ev.set()

    def b(obj):
        sched.sleep(1.0)
        obj.ev.clear()
        sched.sleep(1.0)
        obj.ev.set()

    def c(obj):
        sched.sleep(3.0)
        if obj.ev.wait(timeout=10.0):
            _ = obj.x

    with sync.activated(sched):
        obj = Shared()
        concheck.instrument(sched, [obj], [
            {"class": "Shared", "owner": "self", "field": "x",
             "locks": ["self.lock"], "declared": False}])
        sched.spawn(a, name="a", args=(obj,))
        sched.spawn(b, name="b", args=(obj,))
        sched.spawn(c, name="c", args=(obj,))
    sched.run()
    assert any(r["field"] == "Shared.x"
               and {r["first"]["thread"], r["second"]["thread"]}
               == {"a", "c"}
               for r in det.races), det.races


def test_lock_acquire_timeout_is_virtual():
    """A timed acquire on a contended lock must expire on the VIRTUAL
    clock and return False — not park forever (which would convict a
    spurious deadlock on correct code)."""
    sched = sync.Scheduler(seed=0, strategy="random")
    out = {}

    class Shared:
        def __init__(self):
            self.lock = sync.Lock()

    def holder(obj):
        with obj.lock:
            sched.sleep(5.0)

    def trier(obj):
        sched.sleep(0.5)        # let the holder win the lock
        out["got"] = obj.lock.acquire(timeout=1.0)

    with sync.activated(sched):
        obj = Shared()
        sched.spawn(holder, name="holder", args=(obj,))
        sched.spawn(trier, name="trier", args=(obj,))
    sched.run()
    assert out["got"] is False
    assert not any(f["kind"] == "deadlock" for f in sched.failures), \
        sched.failures


def test_send_to_other_client_under_a_wlock_is_convicted():
    """Cross-client head-of-line stall: holding client A's wlock across
    a send to client B must be convicted — only the DESTINATION
    client's own lock is exempt."""
    sched = sync.Scheduler(seed=0, strategy="random")
    with sync.activated(sched):
        c0 = concheck.FakeClient(sched, 0)
        c1 = concheck.FakeClient(sched, 1)

        def broadcaster():
            with c0.wlock:
                c1.send({"result": {"job_id": "j0"}})

        sched.spawn(broadcaster, name="bcast")
    sched.run()
    hits = [f for f in sched.failures if f["kind"] == "lock-across-send"]
    assert hits and "_Client.wlock#0" in hits[0]["message"]


def test_stale_guarded_by_annotation_warns():
    """A declared guard the schedules never observe held is a stale
    annotation: the static tier is being lied to."""

    def w(obj):
        with obj.other_lock:        # guards with the WRONG lock
            obj.x += 1

    inv = [{"class": "Shared", "owner": "self", "field": "x",
            "locks": ["self.lock"], "declared": True}]
    _sched, det, _obj = _two_thread_run(w, w, seed=0, inventory=inv)
    warnings = det.warnings()
    assert warnings and "stale guarded-by" in warnings[0]
    assert "Shared.x" in warnings[0]


def test_scheduler_detects_lock_order_deadlock():
    """Opposite-order acquisition must be driven INTO the deadlock by
    some schedule and reported with both threads' wait reasons."""

    def w1(obj):
        with obj.lock:
            with obj.other_lock:
                obj.x += 1

    def w2(obj):
        with obj.other_lock:
            with obj.lock:
                obj.x += 1

    deadlocked = 0
    for seed in range(24):
        sched, _det, _obj = _two_thread_run(w1, w2, seed=seed)
        deadlocked += any(f["kind"] == "deadlock"
                          for f in sched.failures)
    assert deadlocked >= 1, "no schedule drove the AB/BA deadlock"


def test_replay_same_seed_same_schedule():
    rep1 = concheck.run_schedule(scenario("clean"), seed=123,
                                 strategy="pct")
    rep2 = concheck.run_schedule(scenario("clean"), seed=123,
                                 strategy="pct")
    assert rep1.signature == rep2.signature
    assert rep1.steps == rep2.steps
    assert [t[:2] for t in rep1.trace] == [t[:2] for t in rep2.trace]


def test_pct_strategy_explores_clean():
    rep = concheck.explore(scenario("clean"), budget=8, seed=5,
                           strategies=("pct",))
    assert rep.clean, (rep.failures()[:3], rep.races()[:3])


# ---------------------------------------------------------------------------
# R021 — check-then-act atomicity (per-file static)


R021_BAD = '''
import threading

class D:
    def __init__(self):
        self.lock = threading.Lock()
        self._routes = {}

    def submit(self, rid, client):
        if rid in self._routes:
            return False
        with self.lock:
            self._routes[rid] = client
        return True
'''

R021_GOOD = '''
import threading

class D:
    def __init__(self):
        self.lock = threading.Lock()
        self._routes = {}

    def submit(self, rid, client):
        with self.lock:
            if rid in self._routes:
                return False
            self._routes[rid] = client
        return True
'''


def rules_of(findings):
    return {f.rule for f in findings}


def test_r021_check_then_act_fires():
    fs = [f for f in run_source(R021_BAD, rel="cuvite_tpu/serve/x.py")
          if f.rule == "R021"]
    assert len(fs) == 1
    assert "check-then-act" in fs[0].message
    assert fs[0].severity == "high"


def test_r021_recheck_under_lock_is_clean():
    assert "R021" not in rules_of(
        run_source(R021_GOOD, rel="cuvite_tpu/serve/x.py"))


def test_r021_scope_is_serve_only():
    assert "R021" not in rules_of(
        run_source(R021_BAD, rel="cuvite_tpu/louvain/x.py"))


def test_r021_read_in_other_function_is_clean():
    """The check-then-act shape needs the mutation in the SAME function
    — a read-only helper deciding nothing it mutates is not a finding."""
    src = R021_BAD.replace(
        "        if rid in self._routes:\n            return False\n",
        "")
    src += '''
    def peek(self, rid):
        if rid in self._routes:
            return True
        return False
'''
    assert "R021" not in rules_of(
        run_source(src, rel="cuvite_tpu/serve/x.py"))


def test_r021_inline_suppression():
    src = R021_BAD.replace(
        "if rid in self._routes:",
        "if rid in self._routes:  # graftlint: disable=R021")
    assert "R021" not in rules_of(
        run_source(src, rel="cuvite_tpu/serve/x.py"))


# ---------------------------------------------------------------------------
# R020 — lock-order cycles (project tier)


R020_A = '''
import threading

class A:
    def __init__(self, b: "B"):
        self.lock = threading.Lock()
        self.b = b

    def m(self):
        with self.lock:
            self.b.poke()

    def kick(self):
        with self.lock:
            pass
'''

R020_B = '''
import threading

class B:
    def __init__(self, a: "A"):
        self.lock = threading.Lock()
        self.a = a

    def poke(self):
        with self.lock:
            self.a.kick()
'''


def test_r020_cross_class_cycle_fires():
    fs = run_project_sources({"cuvite_tpu/serve/a.py": R020_A,
                              "cuvite_tpu/serve/b.py": R020_B})
    hits = [f for f in fs if f.rule == "R020"]
    assert hits, fs
    assert any("A.lock" in f.message and "B.lock" in f.message
               for f in hits) or any("re-acquired" in f.message
                                     for f in hits)


def test_r020_nested_with_cycle_and_consistent_order():
    nest = '''
class C:
    def m1(self):
        with self.a_lock:
            with self.b_lock:
                pass

    def m2(self):
        with self.b_lock:
            with self.a_lock:
                pass
'''
    fs = run_project_sources({"cuvite_tpu/serve/c.py": nest})
    assert "R020" in rules_of(fs)
    consistent = nest.replace(
        "with self.b_lock:\n            with self.a_lock:",
        "with self.a_lock:\n            with self.b_lock:")
    fs = run_project_sources({"cuvite_tpu/serve/c.py": consistent})
    assert "R020" not in rules_of(fs)


def test_r020_nonreentrant_self_deadlock_vs_rlock():
    src = '''
import threading

class S:
    def __init__(self):
        self.lock = threading.Lock()

    def outer(self):
        with self.lock:
            self.inner()

    def inner(self):
        with self.lock:
            pass
'''
    fs = run_project_sources({"cuvite_tpu/serve/s.py": src})
    hits = [f for f in fs if f.rule == "R020"]
    assert hits and "self-deadlock" in hits[0].message
    fs = run_project_sources({
        "cuvite_tpu/serve/s.py": src.replace("threading.Lock()",
                                             "threading.RLock()")})
    assert "R020" not in rules_of(fs)


def test_r020_scope_is_serve_only():
    fs = run_project_sources({"cuvite_tpu/louvain/a.py": R020_A,
                              "cuvite_tpu/louvain/b.py": R020_B})
    assert "R020" not in rules_of(fs)


def test_r020_r021_self_lint_current_serve_tree_is_clean():
    """The acceptance pin: zero R020/R021 findings on the shipped
    serve/ package (the daemon's lock order is acyclic, every guarded
    check re-checks under the lock)."""
    fs = run_paths([os.path.join(REPO, "cuvite_tpu", "serve")])
    assert not [f for f in fs if f.rule in ("R020", "R021")], \
        [f.format() for f in fs if f.rule in ("R020", "R021")]


# ---------------------------------------------------------------------------
# Cache: static tier-4 outputs ride it; dynamic results never do


def _serve_fixture_tree(tmp_path):
    tree = tmp_path / "cuvite_tpu" / "serve"
    tree.mkdir(parents=True)
    (tree / "a.py").write_text(R020_A)
    (tree / "b.py").write_text(R020_B)
    (tree / "x.py").write_text(R021_BAD)
    return tmp_path / "cuvite_tpu"


def test_lock_summaries_ride_cache_warm_equals_cold(tmp_path):
    tree = _serve_fixture_tree(tmp_path)
    cache = str(tmp_path / "cache.json")
    cold = run_paths([str(tree)])
    assert {"R020", "R021"} <= rules_of(cold)
    warm0 = run_paths([str(tree)], cache=cache)
    warm1 = run_paths([str(tree)], cache=cache)   # pure hits
    assert cold == warm0 == warm1                 # bit-identical
    with open(cache, encoding="utf-8") as fh:
        data = json.load(fh)
    ent = data["entries"]["cuvite_tpu/serve/a.py"]
    locks = ent["summary"]["locks"]
    assert locks["classes"]["A"]["methods"]["m"]["acquires"]
    # R020 findings are PROJECT findings rebuilt from the cached
    # summaries — they are not stored per file
    assert "R020" not in {f["rule"] for f in ent["findings"]}


def test_dynamic_exploration_never_touches_the_cache(tmp_path):
    tree = _serve_fixture_tree(tmp_path)
    cache = str(tmp_path / "cache.json")
    run_paths([str(tree)], cache=cache)
    with open(cache, "rb") as fh:
        before = fh.read()
    rep = concheck.explore(scenario("clean"), budget=2, seed=0)
    assert rep.clean
    with open(cache, "rb") as fh:
        assert fh.read() == before
    # and nothing concheck-shaped leaked into the cache schema
    assert b"races" not in before and b"schedules" not in before


# ---------------------------------------------------------------------------
# SARIF + CLI + env knob


def test_r020_r021_emit_through_sarif(tmp_path, capsys):
    from cuvite_tpu.analysis.__main__ import main

    tree = _serve_fixture_tree(tmp_path)
    rc = main([str(tree), "--format", "sarif"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    run = doc["runs"][0]
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"R020", "R021"} <= rule_ids
    hit_ids = {r["ruleId"] for r in run["results"]}
    assert {"R020", "R021"} <= hit_ids
    for res in run["results"]:
        assert res["partialFingerprints"]["graftlintFingerprint/v1"]
        assert res["locations"][0]["physicalLocation"]["region"][
            "startLine"] >= 1


def test_sched_budget_env_knob(monkeypatch):
    monkeypatch.setenv(concheck.BUDGET_ENV, "7")
    assert concheck.schedule_budget() == 7
    monkeypatch.setenv(concheck.BUDGET_ENV, "not-a-number")
    with pytest.warns(UserWarning, match=concheck.BUDGET_ENV):
        assert concheck.schedule_budget() == concheck.DEFAULT_BUDGET
    monkeypatch.setenv(concheck.BUDGET_ENV, "0")
    with pytest.warns(UserWarning):
        assert concheck.schedule_budget() == concheck.DEFAULT_BUDGET
    monkeypatch.delenv(concheck.BUDGET_ENV)
    assert concheck.schedule_budget() == concheck.DEFAULT_BUDGET


def test_concheck_cli_main_inprocess():
    rc = concheck.main(["--budget", "2", "--seed", "0",
                        "--scenario", "racy-routes", "--format", "json"])
    assert rc == 0      # expect=detect and it WAS detected


def test_concheck_cli_rejects_unknown_scenario():
    with pytest.raises(SystemExit):
        concheck.main(["--scenario", "bogus"])


def test_concheck_cli_replay_reproduces_a_conviction():
    """The CLI's printed replay handle must actually reproduce: replay
    the racy fixture from the (strategy, seed) pair explore found."""
    rep = concheck.explore(scenario("racy-routes"), budget=16, seed=0,
                           stop_on_failure=True)
    failing = rep.failing[0]
    rc = concheck.main(["--scenario", "racy-routes", "--replay",
                        f"{failing.strategy}:{failing.seed}"])
    assert rc == 1      # the replayed schedule convicts again
    # and a clean scenario replays clean on the same handle
    rc = concheck.main(["--scenario", "clean", "--replay",
                        f"{failing.strategy}:{failing.seed}"])
    assert rc == 0


@pytest.mark.slow
def test_concheck_cli_subprocess_smoke():
    """The lint.sh --sched-smoke entry: real child process, fixed seed,
    tiny budget — clean scenarios clean, bug fixtures convicted."""
    out = subprocess.run(
        [sys.executable, "-m", "cuvite_tpu.analysis.concheck",
         "--budget", "3", "--seed", "0"],
        capture_output=True, text=True, cwd=REPO, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0, out.stdout + out.stderr
    assert "concheck: ok" in out.stdout
