"""Correctness of the jitted Louvain step against an independent oracle.

The oracle re-implements, with plain Python dicts, the per-vertex semantics of
distExecuteLouvainIteration / distGetMaxIndex
(/root/reference/louvain.cpp:2185-2382): gain formula, strictly-positive-gain
moves, tie-break to the smaller community id, and the singleton-swap guard.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from cuvite_tpu.comm.mesh import make_mesh, shard_1d
from cuvite_tpu.core.distgraph import DistGraph
from cuvite_tpu.core.graph import Graph
from cuvite_tpu.evaluate.modularity import modularity as modularity_oracle
from cuvite_tpu.louvain.step import make_single_step, make_sharded_step
from cuvite_tpu.comm.mesh import VERTEX_AXIS


def oracle_step(graph: Graph, comm: np.ndarray):
    """One synchronous sweep; returns (target, modularity_of_input)."""
    nv = graph.num_vertices
    vdeg = graph.weighted_degrees().astype(np.float64)
    two_m = graph.total_edge_weight_twice()
    const = 1.0 / two_m
    comm_deg = np.zeros(nv)
    comm_size = np.zeros(nv, dtype=np.int64)
    for v in range(nv):
        comm_deg[comm[v]] += vdeg[v]
        comm_size[comm[v]] += 1

    target = comm.copy()
    le_xx = 0.0
    for v in range(nv):
        e0, e1 = graph.offsets[v], graph.offsets[v + 1]
        if e0 == e1:
            continue
        weights_to = {}
        self_loop = 0.0
        for k in range(e0, e1):
            t = int(graph.tails[k])
            w = float(graph.weights[k])
            if t == v:
                self_loop += w
            c = int(comm[t])
            weights_to[c] = weights_to.get(c, 0.0) + w
        cc = int(comm[v])
        counter0 = weights_to.get(cc, 0.0)
        le_xx += counter0
        eix = counter0 - self_loop
        ax = comm_deg[cc] - vdeg[v]
        max_gain, max_idx, max_size = 0.0, cc, comm_size[cc]
        for c, eiy in weights_to.items():
            if c == cc:
                continue
            ay = comm_deg[c]
            gain = 2.0 * (eiy - eix) - 2.0 * vdeg[v] * (ay - ax) * const
            if gain > max_gain or (
                gain == max_gain and gain != 0.0 and c < max_idx
            ):
                max_gain, max_idx, max_size = gain, c, comm_size[c]
        if max_size == 1 and comm_size[cc] == 1 and max_idx > cc:
            max_idx = cc
        target[v] = max_idx
    q = le_xx * const - np.square(comm_deg * const).sum()
    return target, q


def run_device_step(graph: Graph, comm: np.ndarray, nshards: int = 1):
    dg = DistGraph.build(graph, nshards)
    src, dst, w = dg.stacked_edges()
    vdeg = dg.padded_weighted_degrees()
    nvt = dg.total_padded_vertices
    comm_pad = np.arange(nvt, dtype=dg.graph.policy.vertex_dtype)
    comm_pad[dg.old_to_pad] = dg.old_to_pad[comm]  # labels in padded space
    const = jnp.asarray(
        1.0 / graph.total_edge_weight_twice(), dtype=graph.policy.weight_dtype
    )
    if nshards == 1:
        step = make_single_step(nvt)
        t, q, n, _ = step(src, dst, w, comm_pad, vdeg, const)
    else:
        mesh = make_mesh(nshards)
        step = make_sharded_step(mesh, VERTEX_AXIS, nvt)
        t, q, n, _ = step(
            shard_1d(mesh, src), shard_1d(mesh, dst), shard_1d(mesh, w),
            shard_1d(mesh, comm_pad), shard_1d(mesh, vdeg), const,
        )
    t = np.asarray(t)
    # back to original-id labels
    target_old = dg.pad_to_old[t[dg.old_to_pad]]
    return target_old, float(q), int(n)


@pytest.mark.parametrize("fixture", ["karate", "two_cliques", "ring8"])
def test_step_matches_oracle(fixture, request):
    graph = request.getfixturevalue(fixture)
    comm = np.arange(graph.num_vertices, dtype=np.int64)
    for it in range(4):
        expected, q_exp = oracle_step(graph, comm)
        got, q_got, _ = run_device_step(graph, comm)
        np.testing.assert_array_equal(
            got, expected, err_msg=f"iteration {it} targets diverge"
        )
        assert q_got == pytest.approx(q_exp, abs=1e-5)
        comm = expected


def test_modularity_identity_assignment(karate):
    """Identity assignment: e_in = self-loops (none) -> Q = -sum (k_i/2m)^2."""
    comm = np.arange(karate.num_vertices, dtype=np.int64)
    _, q, _ = run_device_step(karate, comm)
    assert q == pytest.approx(modularity_oracle(karate, comm), abs=1e-6)


@pytest.mark.parametrize("nshards", [2, 4, 8])
def test_sharded_step_matches_single(karate, nshards):
    comm = np.arange(karate.num_vertices, dtype=np.int64)
    for it in range(3):
        t1, q1, n1 = run_device_step(karate, comm, nshards=1)
        tn, qn, nn = run_device_step(karate, comm, nshards=nshards)
        np.testing.assert_array_equal(t1, tn)
        assert qn == pytest.approx(q1, abs=1e-5)
        assert nn == n1
        comm = t1


def test_first_step_two_cliques(two_cliques):
    """After convergence each K5 collapses to one community."""
    comm = np.arange(10, dtype=np.int64)
    for _ in range(6):
        comm, _ = oracle_step(two_cliques, comm)
    assert len(set(comm[:5])) == 1
    assert len(set(comm[5:])) == 1
    assert comm[0] != comm[5]


def test_packed_sort_debug_bounds_guard(monkeypatch):
    """CUVITE_DEBUG_BOUNDS=1 turns packed-key bound violations into hard
    errors instead of silent key corruption (advisor r2 finding).

    The env var is read once at module import (advisor r3: a trace-time
    read could never take effect after the step cache warms), so the test
    toggles the module attribute directly."""
    import jax.numpy as jnp

    from cuvite_tpu.ops import segment
    from cuvite_tpu.ops.segment import sort_edges_by_vertex_comm

    src = jnp.array([0, 1, 2], dtype=jnp.int32)
    ckey = jnp.array([0, 1, 9], dtype=jnp.int32)  # >= key_bound
    w = jnp.ones(3, dtype=jnp.float32)
    monkeypatch.setattr(segment, "DEBUG_BOUNDS", True)
    with pytest.raises(AssertionError, match="bound violation"):
        sort_edges_by_vertex_comm(src, ckey, w, src_bound=4, key_bound=4)
    # In-bounds input passes and round-trips exactly.
    out = sort_edges_by_vertex_comm(
        src, jnp.array([2, 1, 0], dtype=jnp.int32), w,
        src_bound=4, key_bound=4)
    assert [int(x) for x in out[0]] == [0, 1, 2]
    assert [int(x) for x in out[1]] == [2, 1, 0]
