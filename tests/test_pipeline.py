"""Pipelined-dispatch tests (ISSUE 14): the pack/execute split, the
two-seam-thread dispatcher, measured-service b_max autotuning, the
overlap telemetry, the serve-record pipeline tagging, and the chaos
gate through the pipelined dispatcher.

Real-thread tests use the stub runner (instant, pure function of the
graph) so hundreds of jobs cost milliseconds; the real-jax tests pin
the one property the stub cannot — per-tenant labels/Q bit-identical
across serial dispatch, pipelined dispatch, and B=1.
"""

import json
import subprocess
import sys
import time
import types

import numpy as np
import pytest

from cuvite_tpu.core.graph import Graph
from cuvite_tpu.serve import (
    AdmissionConfig,
    BmaxAutotuner,
    FaultPlan,
    InjectedFault,
    LouvainServer,
    PipelinedDispatcher,
    ServeConfig,
)
from cuvite_tpu.serve.loadgen import run_open_loop
from cuvite_tpu.workloads.bench import validate_record
from cuvite_tpu.workloads.synth import many_seed, synthesize_graph

from tests.test_serve import REPO, PERF_REGRESS  # noqa: F401


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def sleep(self, s: float) -> None:
        self.t += s


def make_graph(seed: int, nv: int = 16, ne: int = 32) -> Graph:
    rng = np.random.default_rng(seed)
    return Graph.from_edges(nv, rng.integers(0, nv, ne),
                            rng.integers(0, nv, ne))


def stub_result(g):
    nv = g.num_vertices
    key = int(np.sum(g.tails)) % 997
    return types.SimpleNamespace(
        communities=(np.arange(nv) + key) % max(nv, 1),
        modularity=key / 997.0,
        phases=[1], total_iterations=3, num_communities=nv)


def make_stub_runner(clock=None, service_of=None):
    """cluster_many-shaped stub; ``service_of(n_graphs)`` consumes that
    much virtual time per batch (the rung-dependent service curve the
    autotune tests drive)."""

    def runner(graphs, **kw):
        if clock is not None and service_of is not None:
            clock.sleep(service_of(len(graphs)))
        return types.SimpleNamespace(
            results=[stub_result(g) for g in graphs], n_phases=1)

    return runner


# ---------------------------------------------------------------------------
# BmaxAutotuner (unit)


KEY = ((4096, 16384), "float32")


def test_autotuner_picks_goodput_optimal_feasible_rung():
    """THE acceptance curve: the default b_max=64 rung is
    SLO-infeasible (batch service >> SLO), a smaller measured rung
    wins on projected goodput."""
    at = BmaxAutotuner(AdmissionConfig(wait_slo_s=0.5, headroom=1.0))
    for _ in range(3):
        at.observe(KEY, 64, 10.0)    # infeasible: 10 s >> 0.5 s SLO
        at.observe(KEY, 8, 0.2)      # feasible, goodput 40 jobs/s
        at.observe(KEY, 16, 0.45)    # feasible, goodput 35.6 jobs/s
    assert at.pick(KEY, 64) == 8
    # The cap clamps the candidate set (a rung above it never wins).
    assert at.pick(KEY, 8) == 8


def test_autotuner_never_picks_an_unmeasured_rung():
    """The compile clamp: a rung below its warm window (== a rung whose
    program may not be compiled) is not a candidate, however good its
    projected goodput would be."""
    at = BmaxAutotuner(AdmissionConfig(wait_slo_s=0.5))
    at.observe(KEY, 8, 0.01)
    at.observe(KEY, 8, 0.01)         # 2 obs < min_obs=3: not warm
    assert at.pick(KEY, 64) is None
    at.observe(KEY, 8, 0.01)         # warm now
    at.observe(KEY, 64, 0.001)       # 1 obs: tempting but NOT warm
    assert at.pick(KEY, 64) == 8
    assert 64 not in at.curve(KEY)


def test_autotuner_infeasible_curve_falls_back_to_fastest():
    at = BmaxAutotuner(AdmissionConfig(wait_slo_s=0.01, headroom=1.0))
    for _ in range(3):
        at.observe(KEY, 8, 0.8)
        at.observe(KEY, 2, 0.3)      # nothing feasible: least-bad wins
    assert at.pick(KEY, 64) == 2


def test_autotune_config_validates():
    from cuvite_tpu.serve import AutotuneConfig

    with pytest.raises(ValueError, match="min_obs"):
        AutotuneConfig(min_obs=0)
    with pytest.raises(ValueError, match="window"):
        AutotuneConfig(min_obs=8, window=4)
    with pytest.raises(ValueError, match="autotune_b_max"):
        ServeConfig(autotune_b_max=True)   # needs admission


# ---------------------------------------------------------------------------
# Server-level autotune (fake clock, rung-dependent service curve)


def test_server_autotunes_b_max_and_emits_event():
    """Affine service 0.1 + 0.05*n: rung 8 breaches the 0.5 s SLO
    (0.5 * 1.25 headroom > 0.5), rung 4 is the goodput-optimal
    feasible rung — after the warm window the class serves at 4 and an
    ``autotune`` event records the change."""
    from cuvite_tpu.obs import FlightRecorder, MemoryTraceSink
    from cuvite_tpu.utils.trace import Tracer

    clock = FakeClock()
    sink = MemoryTraceSink()
    srv = LouvainServer(
        ServeConfig(b_max=8, linger_s=0.0, engine="fused",
                    admission=AdmissionConfig(wait_slo_s=0.5),
                    autotune_b_max=True),
        clock=clock, sleep=clock.sleep,
        tracer=Tracer(recorder=FlightRecorder(sink, watch_compiles=False)),
        runner=make_stub_runner(clock, lambda n: 0.1 + 0.05 * n))
    key = None
    # Warm rungs 8, 4, 2 (3 dispatches each — exact-size batches).
    for rung in (8, 8, 8, 4, 4, 4, 2, 2, 2):
        for s in range(rung):
            srv.submit(make_graph(1000 + s))
        done = srv.step(force=True)
        assert len(done) == rung
    key = next(iter(srv.autotuned()), None)
    assert key is not None, "autotune never moved the class"
    assert srv.autotuned()[key] == 4
    assert srv.b_max_for(key) == 4
    events = [r for r in sink.records
              if r.get("t") == "event" and r.get("name") == "autotune"]
    assert events, "no autotune event emitted"
    assert events[-1]["attrs"]["b_max_new"] == 4
    assert "curve" in events[-1]["attrs"]
    # The retuned rung now caps dispatch: 8 queued jobs pop as 4+4.
    for s in range(8):
        srv.submit(make_graph(2000 + s))
    srv.drain()
    assert srv.conservation()["ok"]
    with srv.stats.lock:
        assert srv.stats.inflight == 0


# ---------------------------------------------------------------------------
# Pack/execute split telemetry (serial path)


def test_pack_and_execute_spans_split(tmp_path):
    from cuvite_tpu.obs import FlightRecorder, MemoryTraceSink, spans_of
    from cuvite_tpu.utils.trace import Tracer

    clock = FakeClock()
    sink = MemoryTraceSink()
    srv = LouvainServer(
        ServeConfig(b_max=2, linger_s=0.0, engine="fused"),
        clock=clock, tracer=Tracer(
            recorder=FlightRecorder(sink, watch_compiles=False)),
        runner=make_stub_runner(clock, lambda n: 0.1))
    srv.submit(make_graph(1))
    srv.submit(make_graph(2))
    srv.step()
    packs = spans_of(sink.records, "pack")
    execs = spans_of(sink.records, "execute")
    assert len(packs) == 1 and len(execs) == 1
    assert packs[0]["begin"]["attrs"]["trigger"] == "full"
    assert "wall_s" in packs[0]["end"]["attrs"]
    assert execs[0]["begin"]["attrs"]["b_pad"] == 2
    assert execs[0]["end"]["attrs"]["phases"] == 1
    # The stub consumed 0.1 s inside execute on the injectable clock.
    st = srv.stats.to_dict()
    assert st["device_s"] == pytest.approx(0.1)
    assert st["pipeline_depth"] == 1 and st["overlap_frac"] == 0.0


# ---------------------------------------------------------------------------
# The pipelined dispatcher: bit-identity + overlap (real jax)


@pytest.fixture(scope="module")
def pipe_graphs():
    return [synthesize_graph(512, seed=many_seed(31, k)) for k in range(8)]


@pytest.fixture(scope="module")
def pipe_vs_serial(pipe_graphs):
    srv_p = LouvainServer(ServeConfig(b_max=4, linger_s=0.02))
    rep_p = run_open_loop(srv_p, pipe_graphs, rate=500.0, pipelined=True)
    srv_s = LouvainServer(ServeConfig(b_max=4, linger_s=0.02))
    rep_s = run_open_loop(srv_s, pipe_graphs, rate=500.0)
    return srv_p, rep_p, srv_s, rep_s


def test_pipelined_results_bit_identical_to_serial_and_b1(
        pipe_graphs, pipe_vs_serial):
    from cuvite_tpu.louvain.batched import cluster_many

    _srv_p, rep_p, _srv_s, rep_s = pipe_vs_serial
    assert rep_p.conservation["ok"] and rep_s.conservation["ok"]
    assert rep_p.done == rep_s.done == len(pipe_graphs)
    dp, ds = dict(rep_p.results), dict(rep_s.results)
    assert set(dp) == set(ds)
    for k in dp:
        assert dp[k].modularity == ds[k].modularity
        assert np.array_equal(dp[k].communities, ds[k].communities)
    # ... and to B=1 solo runs through the same batched driver.
    by_submit = [jid for jid, _ in sorted(
        dp.items(), key=lambda kv: int(kv[0].split("-")[1]))]
    for jid, g in zip(by_submit, pipe_graphs):
        solo = cluster_many([g], engine="bucketed").results[0]
        assert dp[jid].modularity == solo.modularity
        assert np.array_equal(dp[jid].communities, solo.communities)


def test_pipelined_overlap_telemetry(pipe_vs_serial):
    srv_p, _rep_p, srv_s, _rep_s = pipe_vs_serial
    stp = srv_p.stats.to_dict()
    sts = srv_s.stats.to_dict()
    assert stp["pipeline_depth"] == 2 and sts["pipeline_depth"] == 1
    assert stp["pack_s"] > 0 and stp["device_s"] > 0
    assert 0.0 <= stp["overlap_frac"] <= 1.0
    # The serial dispatcher can never overlap by construction.
    assert sts["overlap_frac"] == 0.0
    assert stp["inflight"] == 0 and sts["inflight"] == 0


# ---------------------------------------------------------------------------
# Chaos gate through the PIPELINED dispatcher (acceptance): seeded fault
# plan at all five sites over >= 240 jobs — conservation holds,
# survivors bit-identical to fault-free.

CHAOS_PLAN = (
    "submit:transient:p=0.02,seed=11;"
    "pack:transient:p=0.05,seed=12;"
    "dispatch:raise:p=0.03,seed=13;"
    "device:transient:p=0.08,seed=14;"
    "device:raise:p=0.02,seed=15;"
    "unpack:transient:p=0.04,seed=16"
)


def test_pipelined_chaos_conservation_and_identity():
    n_jobs = 240
    faults = FaultPlan.parse(CHAOS_PLAN)
    srv = LouvainServer(
        ServeConfig(b_max=8, linger_s=0.002, engine="fused",
                    max_retries=2, retry_base_s=0.001),
        faults=faults, runner=make_stub_runner())
    pipe = PipelinedDispatcher(srv, poll_s=0.002)
    pipe.start()
    outcomes = {}
    graphs = {}
    for k in range(n_jobs):
        jid = f"j{k}"
        g = make_graph(k)
        graphs[jid] = g
        # Every 11th job arrives already expired: the deterministic
        # shed path (real clock: a future deadline would usually be
        # met by the instant stub).
        deadline = -0.001 if k % 11 == 0 else None
        try:
            pipe.submit(g, job_id=jid, tenant=f"t{k % 7}",
                        deadline_s=deadline)
        except InjectedFault:
            outcomes[jid] = "rejected"
        if k % 40 == 39:
            time.sleep(0.005)        # bursty arrivals
    pipe.request_drain()
    assert pipe.wait_done(timeout=120.0), "pipelined drain wedged"
    for jid, res in pipe.results:
        assert jid not in outcomes, f"{jid} terminated twice"
        outcomes[jid] = ("done", res)
    for jid, _err in pipe.fails:
        assert jid not in outcomes, f"{jid} terminated twice"
        outcomes[jid] = "failed"
    for jid, _late in pipe.sheds:
        assert jid not in outcomes, f"{jid} terminated twice"
        outcomes[jid] = "shed"
    cons = srv.conservation()
    assert cons["ok"], cons
    assert cons["pending"] == 0 and cons["inflight"] == 0
    assert len(outcomes) == n_jobs, f"{n_jobs - len(outcomes)} vanished"
    fired_sites = {r.site for r in faults.rules if r.fired}
    assert fired_sites == {"submit", "pack", "dispatch", "device",
                           "unpack"}, fired_sites
    kinds = {"done": 0, "failed": 0, "shed": 0, "rejected": 0}
    for v in outcomes.values():
        kinds[v[0] if isinstance(v, tuple) else v] += 1
    assert kinds["done"] > 0 and kinds["shed"] > 0 \
        and kinds["rejected"] > 0
    assert srv.stats.retries > 0
    # Survivors bit-identical to fault-free: the stub is a pure
    # function of the graph, so the expected result is exact.
    for jid, v in outcomes.items():
        if not isinstance(v, tuple):
            continue
        ref = stub_result(graphs[jid])
        assert v[1].modularity == ref.modularity
        assert np.array_equal(v[1].communities, ref.communities), jid


def test_sticky_shape_union_survives_out_of_order_recording():
    """The pipelined interleaving: batch B packs (reading the sticky
    union) BEFORE batch A's execute records its geometry.  Recording
    must UNION with the current state, not overwrite — a grow-only
    geometry can never shrink, whatever order the executes land in."""
    from cuvite_tpu.core.batch import bucket_shape_for
    from cuvite_tpu.io.generate import generate_rmat

    rmats = [generate_rmat(8, edge_factor=8, seed=s) for s in (41, 42)]
    synths = [synthesize_graph(1024, seed=many_seed(5, k))
              for k in range(2)]
    srv = LouvainServer(ServeConfig(b_max=2, linger_s=0.0),
                        clock=FakeClock())
    for g in rmats + synths:           # one tenant: FIFO pop order
        srv.submit(g)
    pa = srv.pack_batch(*srv.pop_due(force=True))   # the rmat pair
    pb = srv.pack_batch(*srv.pop_due(force=True))   # the synth pair
    # Execute OUT of pack order: A's (larger) geometry records last-
    # but-one; B's must not erase it.
    srv.execute_batch(pa)
    srv.execute_batch(pb)
    cls = next(iter(srv._shapes))
    final = srv._shapes[cls]
    assert final.fits(bucket_shape_for(rmats)), \
        "out-of-order recording shrank the sticky union"
    assert final.fits(bucket_shape_for(synths))
    assert srv.conservation()["ok"]


def test_exec_window_envelope_under_nested_isolation():
    """Overlap bookkeeping: a nested execute window (poison isolation
    on the other thread) must not close the envelope the outer window
    opened — last_exec spans [outer start, last end]."""
    from cuvite_tpu.serve import ServeStats

    st = ServeStats()
    st.exec_begins(10.0)
    st.exec_begins(11.0)               # nested (isolation)
    st.exec_ends(11.0, 12.0)
    with st.lock:
        assert st.exec_since == 10.0   # outer window still open
    st.exec_ends(10.0, 15.0)
    with st.lock:
        assert st.last_exec == (10.0, 15.0)
        assert st.exec_depth == 0 and st.exec_since is None
        assert st.device_s == pytest.approx(6.0)  # both windows' busy


def test_pipelined_daemon_honors_route_variant(tmp_path):
    """The concheck seeded-bug contract: replacing _route_results on
    the daemon INSTANCE must reach the pipelined path too (the serial
    loop looks it up dynamically; the pipe's route must as well)."""
    from cuvite_tpu.serve import ServeDaemon

    srv = LouvainServer(ServeConfig(b_max=2, linger_s=0.01,
                                    engine="fused"),
                        runner=make_stub_runner())
    d = ServeDaemon(srv, sock_path=str(tmp_path / "v.sock"))
    seen = []
    d._route_results = lambda finished, fails, sheds: seen.append(
        (list(finished), list(fails), list(sheds)))
    d.pipe._route([("job-0", object())], [], [])
    assert seen and seen[0][0][0][0] == "job-0", \
        "pipelined route ignored the instance-level variant"


def test_pipelined_daemon_flag_wiring(tmp_path):
    from cuvite_tpu.serve import ServeDaemon

    srv = LouvainServer(ServeConfig(b_max=2, linger_s=0.01,
                                    engine="fused"),
                        runner=make_stub_runner())
    d = ServeDaemon(srv, sock_path=str(tmp_path / "p.sock"))
    assert d.pipelined and d.pipe is not None
    srv2 = LouvainServer(ServeConfig(b_max=2, linger_s=0.01,
                                     engine="fused"),
                         runner=make_stub_runner())
    d2 = ServeDaemon(srv2, sock_path=str(tmp_path / "s.sock"),
                     pipelined=False)
    assert not d2.pipelined and d2.pipe is None
    # The serial daemon still drains cleanly through the old loop.
    d2.start()
    d2.request_drain()
    summary = d2.serve_forever(timeout=30.0)
    assert summary["conservation"]["ok"]
    assert summary["pipeline_depth"] == 1
    d.start()
    d.request_drain()
    summary = d.serve_forever(timeout=30.0)
    assert summary["conservation"]["ok"]
    assert summary["pipeline_depth"] == 2


# ---------------------------------------------------------------------------
# serve record schema: pipelined REQUIRED, autotuned_b_max optional


@pytest.fixture(scope="module")
def pipelined_serve_record():
    from cuvite_tpu.workloads.bench import run_serve_bench

    return run_serve_bench(
        rate=200.0, b_max=2, edges=512, n_jobs=4, slo_ms=60000.0,
        admission=True, linger_ms=1.0, budget_s=600.0, platform="cpu",
        pipelined=True, t_start=time.perf_counter())


def test_pipelined_serve_record_schema(pipelined_serve_record):
    assert validate_record(pipelined_serve_record) == []
    blk = pipelined_serve_record["serve"]
    assert blk["pipelined"] is True
    assert blk["done"] == 4
    assert "overlap_frac" in blk and "pack_s" in blk
    # pipelined is REQUIRED on every serve record now
    rec = json.loads(json.dumps(pipelined_serve_record))
    del rec["serve"]["pipelined"]
    assert any("pipelined" in p for p in validate_record(rec))
    rec = json.loads(json.dumps(pipelined_serve_record))
    rec["serve"]["pipelined"] = "yes"
    assert any("pipelined" in p for p in validate_record(rec))
    rec = json.loads(json.dumps(pipelined_serve_record))
    rec["serve"]["autotuned_b_max"] = 0
    assert any("autotuned_b_max" in p for p in validate_record(rec))
    rec["serve"]["autotuned_b_max"] = 4
    assert validate_record(rec) == []


def _round_log(path, rec):
    with open(path, "w") as f:
        json.dump({"n": 98, "cmd": "test", "rc": 0, "tail": "",
                   "parsed": rec}, f)


def test_perf_regress_separates_pipeline_modes(tmp_path,
                                               pipelined_serve_record):
    """Serial and pipelined serve records never gate each other: a
    pipelined trajectory far above the serial one must not flag a
    fresh serial record (and vice versa)."""
    fresh = json.loads(json.dumps(pipelined_serve_record))
    fresh["serve"]["pipelined"] = False          # a serial record
    peer = json.loads(json.dumps(pipelined_serve_record))
    peer["serve"]["goodput_jobs_per_s"] = \
        pipelined_serve_record["serve"]["goodput_jobs_per_s"] * 100
    fresh_p = tmp_path / "fresh.json"
    fresh_p.write_text(json.dumps(fresh))
    _round_log(tmp_path / "BENCH_r98.json", peer)
    out = subprocess.run(
        [sys.executable, PERF_REGRESS, "--record", str(fresh_p),
         "--bench-glob", str(tmp_path / "BENCH_r9*.json")],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 comparable" in out.stdout
    # Same mode still gates.
    _round_log(tmp_path / "BENCH_r98.json",
               json.loads(json.dumps(fresh)))
    out = subprocess.run(
        [sys.executable, PERF_REGRESS, "--record", str(fresh_p),
         "--bench-glob", str(tmp_path / "BENCH_r9*.json")],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "1 comparable" in out.stdout
