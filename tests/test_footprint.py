"""Benchmark-scale footprint machinery: slab-free DistGraph, slab release,
zero-copy uploads, and the dense/radix coarsen path equivalence.

These paths exist so single-host clustering fits R-MAT 26 (the reference's
distributed benchmark config 3 minus the mesh; tools/scale_model.md) — but
every one of them must be bit-identical to the padded/copying baseline,
which is what this file pins at small scale.
"""

import os

import numpy as np
import pytest

from cuvite_tpu.core.distgraph import DistGraph
from cuvite_tpu.core.graph import Graph
from cuvite_tpu.io.generate import generate_rmat
from cuvite_tpu.louvain.driver import PhaseRunner, louvain_phases


@pytest.fixture(scope="module")
def rmat10():
    return generate_rmat(10, edge_factor=8, seed=3)


def test_pad_edges_false_aliases_csr(rmat10):
    dg = DistGraph.build(rmat10, 1, pad_edges=False)
    sh = dg.shards[0]
    assert dg.ne_pad == rmat10.num_edges
    assert sh.n_real_edges == rmat10.num_edges
    # dst/w alias the CSR arrays: zero extra edge bytes.
    assert sh.dst is rmat10.tails
    assert sh.w is rmat10.weights
    # src is the expanded CSR row ids.
    assert np.array_equal(
        np.asarray(sh.src),
        np.repeat(np.arange(rmat10.num_vertices), rmat10.degrees()))
    # Vertex-side padding is unchanged.
    dg_pad = DistGraph.build(rmat10, 1)
    assert dg.nv_pad == dg_pad.nv_pad
    assert np.array_equal(dg.old_to_pad, dg_pad.old_to_pad)


def test_pad_edges_false_step_matches_padded(rmat10):
    """One bucketed phase on the slab-free layout == the padded layout."""
    out = []
    for pad in (True, False):
        dg = DistGraph.build(rmat10, 1, pad_edges=pad)
        runner = PhaseRunner(dg, engine="bucketed")
        comm, mod, iters, _ = runner.run(1e-6, lower=-1.0)
        out.append((np.asarray(comm), float(mod), int(iters)))
    (c0, m0, i0), (c1, m1, i1) = out
    assert i0 == i1
    assert m0 == m1
    assert np.array_equal(c0, c1)


def test_release_slabs_keeps_metadata_and_results(rmat10):
    dg = DistGraph.build(rmat10, 1, pad_edges=False)
    base = PhaseRunner(DistGraph.build(rmat10, 1), engine="bucketed")
    rel = PhaseRunner(dg, engine="bucketed", release_slabs=True)
    sh = dg.shards[0]
    assert sh.src is None and sh.dst is None and sh.w is None
    assert sh.n_real_edges == rmat10.num_edges  # metadata survives
    c0, m0, i0, _ = base.run(1e-6, lower=-1.0)
    c1, m1, i1, _ = rel.run(1e-6, lower=-1.0)
    assert i0 == i1 and float(m0) == float(m1)
    assert np.array_equal(np.asarray(c0), np.asarray(c1))


def test_louvain_phases_slabless_matches_sort_engine(rmat10):
    """End-to-end: the slab-free bucketed run equals the slab-resident
    sort engine (the cross-engine equivalence the suite already pins,
    re-asserted over the new footprint path)."""
    rb = louvain_phases(rmat10, engine="bucketed")
    rs = louvain_phases(rmat10, engine="sort")
    assert rb.total_iterations == rs.total_iterations
    assert rb.modularity == pytest.approx(rs.modularity, abs=1e-12)
    assert np.array_equal(rb.communities, rs.communities)


def test_to_device_zero_copy_on_cpu():
    import jax

    from cuvite_tpu.utils.upload import (
        ALIGN, aligned_empty, aligned_zeros, to_device,
    )

    # XLA:CPU only aliases 64-byte-aligned imports (unaligned ones copy
    # silently) — which is why the plan builders use the aligned
    # allocators.  Pin both the allocator guarantee and the aliasing.
    x = aligned_empty(1024, np.int32)
    assert x.ctypes.data % ALIGN == 0
    x[:] = np.arange(1024)
    src_ptr = x.ctypes.data
    y = to_device(x)
    assert y.dtype == np.int32
    assert np.array_equal(np.asarray(y), np.arange(1024))
    if jax.default_backend() == "cpu":
        # Aliasing is observable: the device buffer IS the numpy buffer,
        # and the numpy side is frozen so host writes raise instead of
        # silently corrupting device state (ADVICE r4).
        assert y.unsafe_buffer_pointer() == src_ptr
        assert not x.flags.writeable
        with pytest.raises(ValueError):
            x[0] = 12345
    # dtype-changing uploads still copy (and must not alias the source;
    # note jax canonicalizes int64 to int32 when x64 is off).
    x2 = aligned_empty(8, np.int32)
    x2[:] = 1
    z = to_device(x2, np.int64)
    assert x2.flags.writeable  # astype copied: source stays mutable
    x2[1] = -7
    assert int(z[1]) == 1
    # 2-D aligned_zeros views are C-contiguous and aligned.
    m = aligned_zeros((16, 128), np.uint8)
    assert m.flags.c_contiguous and m.ctypes.data % ALIGN == 0


def test_to_device_always_committed():
    """Every to_device return is COMMITTED (explicit sharding), whether or
    not the source won the 64-byte-alignment lottery.  jit's lowering
    cache keys on each argument's committed-vs-unspecified sharding, so a
    mixed pattern across a run's uploads means a fresh XLA compile of the
    ~50-operand phase loop per phase per run — the round-4 7x bench
    regression (VERDICT r4 weak #1)."""
    from cuvite_tpu.utils.upload import aligned_empty, to_device

    aligned = aligned_empty(256, np.int32)
    aligned[:] = 3
    buf = np.zeros(256 * 4 + 4, dtype=np.int8)
    off = 4 if buf.ctypes.data % 64 == 0 else 0
    misaligned = buf[off:off + 256 * 4].view(np.int32)
    assert misaligned.ctypes.data % 64 != 0
    for src in (aligned, misaligned):
        out = to_device(src)
        assert out.committed, "to_device must always commit (cache-key "\
            "stability; VERDICT r4 weak #1)"


def test_no_recompile_on_second_run(caplog):
    """A repeat louvain_phases run on the same graph must not trigger ANY
    new jit compilation: the bench's timed runs rely on the warm-up having
    eaten every compile (bench.py), and the round-4 regression was exactly
    this property breaking via unstable upload shardings."""
    import logging

    import jax

    from cuvite_tpu.io.generate import generate_rmat
    from cuvite_tpu.louvain.driver import louvain_phases

    g = generate_rmat(10, edge_factor=8, seed=3)
    louvain_phases(g)  # warm-up eats all compiles
    jax.config.update("jax_log_compiles", True)
    try:
        with caplog.at_level(logging.WARNING, logger="jax"):
            res = louvain_phases(g)
        compiles = [r for r in caplog.records
                    if "Compiling" in r.getMessage()]
        assert not compiles, (
            f"second run recompiled {len(compiles)} executables: "
            + "; ".join(r.getMessage()[:120] for r in compiles[:4]))
    finally:
        jax.config.update("jax_log_compiles", False)
    assert res.phases


def test_coarsen_dense_radix_bit_identical_large_nc(monkeypatch):
    """nc > 2^22 exercises the radix path; force_dense must reproduce it
    bit-for-bit (same accumulation order by the stability argument in
    native/cuvite_native.cpp)."""
    from cuvite_tpu import native

    if not native.available():
        pytest.skip("native library unavailable")
    g = generate_rmat(9, edge_factor=8, seed=5)
    rng = np.random.default_rng(0)
    nc = (1 << 22) + 1000
    labels = rng.integers(0, nc, size=g.num_vertices).astype(np.int32)
    outs = []
    for mode in ("radix", "dense"):
        monkeypatch.setenv("CUVITE_COARSEN_FORCE", mode)
        outs.append(native.coarsen_csr(
            g.offsets, g.tails, g.weights, labels, nc))
    (o0, t0, w0), (o1, t1, w1) = outs
    assert np.array_equal(o0, o1)
    assert np.array_equal(t0, t1)
    assert np.array_equal(w0, w1)


def test_coarsen_memavailable_heuristic_reads():
    from cuvite_tpu.native import _mem_available_bytes

    avail = _mem_available_bytes()
    # On this Linux host the probe must work and be sane.
    if os.path.exists("/proc/meminfo"):
        assert avail is not None and avail > (1 << 20)
