"""Scale-exercising tests (VERDICT round-1 weak #8).

The small golden graphs never reach the paths that matter at benchmark
scale: ROW_CHUNK-sized lax.map chunking (a bucket with more rows than one
chunk), the heavy class on a genuinely skewed graph, several width classes
populated at once, and the sparse exchange's O(owned + ghosts) footprint.
These tests build graphs big/skewed enough to hit each, while staying
CPU-test-sized; a scale-20 smoke is env-gated (CUVITE_SLOW_TESTS=1).
"""

import os

import numpy as np
import pytest

from cuvite_tpu.core.distgraph import DistGraph
from cuvite_tpu.core.graph import Graph
from cuvite_tpu.evaluate.modularity import modularity
from cuvite_tpu.io.generate import generate_rmat
from cuvite_tpu.louvain.bucketed import ROW_CHUNK, BucketPlan
from cuvite_tpu.louvain.driver import louvain_phases


@pytest.fixture(scope="module")
def rmat15():
    return generate_rmat(15, edge_factor=16, seed=5)


def test_rmat15_overflows_row_chunk(rmat15):
    """A scale-15 R-MAT's narrow buckets hold more rows than ROW_CHUNK, so
    the lax.map chunking path actually executes (no prior test reached
    it)."""
    g = rmat15
    dg = DistGraph.build(g, 1)
    sh = dg.shards[0]
    plan = BucketPlan.build(np.asarray(sh.src), np.asarray(sh.dst),
                            np.asarray(sh.w), nv_local=dg.nv_pad, base=0)
    rows = {b.width: len(b.verts) for b in plan.buckets}
    assert any(n > ROW_CHUNK for n in rows.values()), rows
    # Multiple width classes populated at once.
    assert len([n for n in rows.values() if n > 0]) >= 4, rows


@pytest.mark.slow
def test_rmat15_bucketed_matches_sort_engine(rmat15):
    """Full-run equality of the two engines on a graph big enough to
    exercise chunking and several buckets at once.

    slow: ~19 s — rmat15 chunk-overflow and exchange-footprint coverage
    stays tier-1 in this file; engine equality at smaller scales rides
    test_bucketed.py."""
    rb = louvain_phases(rmat15, engine="bucketed")
    rs = louvain_phases(rmat15, engine="sort")
    assert rb.modularity == pytest.approx(rs.modularity, abs=5e-4)
    q = modularity(rmat15, rb.communities)
    assert q == pytest.approx(rb.modularity, abs=1e-4)
    assert q > 0.05  # R-MATs are weakly modular but not structureless


@pytest.fixture(scope="module")
def hub_graph():
    """Deterministic skewed graph: a hub of degree > 8192 (the heavy
    class threshold DEFAULT_BUCKETS[-1]) over a ring of cliques."""
    edges = []
    nv = 40 * 256 + 1  # 40 cliques of 256 + hub
    hub = nv - 1
    for c in range(40):
        base = c * 256
        for i in range(256):
            edges.append((base + i, base + (i + 1) % 256))
            edges.append((base + i, base + (i + 7) % 256))
            edges.append((base + i, base + (i + 31) % 256))
    for v in range(hub):  # hub sees every vertex: degree 10240 > 8192
        edges.append((hub, v))
    e = np.array(edges, dtype=np.int64)
    return Graph.from_edges(nv, e[:, 0], e[:, 1])


def test_heavy_class_on_skewed_graph(hub_graph):
    g = hub_graph
    assert int(g.degrees().max()) > 8192
    dg = DistGraph.build(g, 1)
    sh = dg.shards[0]
    plan = BucketPlan.build(np.asarray(sh.src), np.asarray(sh.dst),
                            np.asarray(sh.w), nv_local=dg.nv_pad, base=0)
    assert plan.has_heavy
    rb = louvain_phases(g, engine="bucketed")
    rs = louvain_phases(g, engine="sort")
    assert rb.modularity == pytest.approx(rs.modularity, abs=5e-4)
    assert rb.modularity > 0.5  # cliques must be recovered despite the hub


def test_heavy_class_multishard(hub_graph):
    """The heavy path under SPMD + sparse exchange (the hub's edges land in
    one shard's heavy slab; its tails are ghosts of every other shard)."""
    r8 = louvain_phases(hub_graph, nshards=8, exchange="sparse")
    r1 = louvain_phases(hub_graph, nshards=1)
    assert np.array_equal(r8.communities, r1.communities)


def test_sparse_exchange_footprint_rmat15():
    """Per-chip sparse-exchange state is O(owned + ghosts), not
    O(nv_total): the extended table of every shard must stay well below
    the replicated-exchange footprint."""
    from cuvite_tpu.comm.exchange import ExchangePlan

    g = generate_rmat(14, edge_factor=8, seed=9)
    dg = DistGraph.build(g, 8)
    xplan = ExchangePlan.build(dg)
    nv_total = dg.total_padded_vertices
    # Ghost tables are padded to pow2 of the max shard's ghost count; even
    # so, owned + ghosts must undercut the full vertex space.
    assert dg.nv_pad + xplan.ghost_pad < nv_total
    for gids in xplan.ghost_ids:
        assert len(gids) < nv_total - dg.nv_pad


@pytest.mark.skipif(not os.environ.get("CUVITE_SLOW_TESTS"),
                    reason="scale-20 smoke: set CUVITE_SLOW_TESTS=1")
def test_scale20_smoke():
    g = generate_rmat(20, edge_factor=16, seed=1)
    res = louvain_phases(g, engine="bucketed")
    assert res.modularity > 0.01
    assert len(res.phases) >= 2


def test_chunk_for_width_stays_pow2():
    """Pow2-padded row counts divide evenly only by pow2 chunks; a non-pow2
    chunk (e.g. from the 384/768 widths) would silently disable chunking
    and blow the transient-memory bound."""
    from cuvite_tpu.louvain.bucketed import DEFAULT_BUCKETS, chunk_for_width

    for w in DEFAULT_BUCKETS:
        c = chunk_for_width(w)
        assert c > 0 and (c & (c - 1)) == 0, (w, c)
