"""Pallas kernel vs XLA-fallback parity (interpret mode on CPU)."""

import numpy as np
import pytest

import jax.numpy as jnp

from cuvite_tpu.kernels.row_argmax import row_argmax_pallas
from cuvite_tpu.louvain.bucketed import _row_argmax

SENTINEL = np.iinfo(np.int32).max


def _bucket_case(n_rows, width, nv, seed):
    rng = np.random.default_rng(seed)
    cmat = rng.integers(0, nv, size=(n_rows, width)).astype(np.int32)
    # Multiples of 1/16: float sums are exact in any order, so the kernel
    # and the XLA path must agree bit-for-bit.
    wmat = (rng.integers(1, 32, size=(n_rows, width)) / 16.0).astype(
        np.float32)
    curr = rng.integers(0, nv, size=n_rows).astype(np.int32)
    # Some rows keep slots in the current community (the is_cc mask path).
    cmat[: n_rows // 2, 0] = curr[: n_rows // 2]
    vdeg = (rng.integers(1, 64, size=n_rows) / 4.0).astype(np.float32)
    # Self-loop weight <= the row's weight into its current community.
    sl = np.where(cmat[:, 0] == curr, wmat[:, 0] / 2.0, 0.0).astype(
        np.float32)
    comm_deg = (rng.integers(1, 256, size=nv) / 8.0).astype(np.float32)
    constant = np.float32(1.0 / 64.0)
    return cmat, wmat, curr, vdeg, sl, comm_deg, constant


@pytest.mark.parametrize("width", [8, 32, 64, 256])
@pytest.mark.parametrize("seed", [0, 3])
@pytest.mark.parametrize("constant", [None, np.float32(0.3)])
def test_row_argmax_pallas_matches_xla(width, seed, constant):
    """Widths 8/32 exercise the unrolled candidate loop; 64/256 the
    fori_loop form added for the wide classes (VERDICT r3 item 4).
    constant=0.3 (non-dyadic) pins the gain's operand ASSOCIATION to the
    XLA path's — with the default dyadic 1/64 every association is exact
    and a reassociation regression would be invisible."""
    n_rows, nv = 256, 500
    cmat, wmat, curr, vdeg, sl, comm_deg, _const_dyadic = _bucket_case(
        n_rows, width, nv, seed)
    constant = _const_dyadic if constant is None else constant

    # Reference path mirrors bucketed_step: both kernels take the self-loop
    # weight and derive eix = counter0 - sl row-locally.
    is_cc = cmat == curr[:, None]
    counter0 = np.sum(np.where(is_cc, wmat, 0.0), axis=1).astype(np.float32)
    ay = comm_deg[cmat]                     # pre-gathered outside the kernel
    ax = comm_deg[curr] - vdeg
    ref = _row_argmax(
        jnp.asarray(cmat), jnp.asarray(wmat), jnp.asarray(ay), None,
        jnp.asarray(curr), jnp.asarray(vdeg), jnp.asarray(sl),
        jnp.asarray(ax), jnp.asarray(constant), SENTINEL,
    )
    bc, bg, c0 = row_argmax_pallas(
        jnp.asarray(np.ascontiguousarray(cmat.T)),
        jnp.asarray(np.ascontiguousarray(wmat.T)),
        jnp.asarray(np.ascontiguousarray(ay.T)),
        jnp.asarray(curr), jnp.asarray(vdeg), jnp.asarray(sl),
        jnp.asarray(ax), jnp.asarray(constant),
        sentinel=SENTINEL, tile_n=128, interpret=True,
    )
    assert np.array_equal(np.asarray(c0), counter0)
    assert np.array_equal(np.asarray(bg), np.asarray(ref.best_gain))
    assert np.array_equal(np.asarray(bc), np.asarray(ref.best_c))


def test_row_argmax_pallas_no_candidates():
    """Rows whose every slot sits in the current community -> sentinel."""
    n_rows, width, nv = 128, 8, 50
    rng = np.random.default_rng(1)
    curr = rng.integers(0, nv, size=n_rows).astype(np.int32)
    cmat = np.repeat(curr[:, None], width, axis=1)
    wmat = np.ones((n_rows, width), dtype=np.float32)
    vdeg = np.ones(n_rows, dtype=np.float32)
    sl = np.zeros(n_rows, dtype=np.float32)
    comm_deg = np.ones(nv, dtype=np.float32)
    ay = comm_deg[cmat]
    ax = comm_deg[curr] - vdeg
    bc, bg, c0 = row_argmax_pallas(
        jnp.asarray(np.ascontiguousarray(cmat.T)),
        jnp.asarray(np.ascontiguousarray(wmat.T)),
        jnp.asarray(np.ascontiguousarray(ay.T)),
        jnp.asarray(curr), jnp.asarray(vdeg), jnp.asarray(sl),
        jnp.asarray(ax), jnp.asarray(np.float32(0.01)),
        sentinel=SENTINEL, tile_n=128, interpret=True,
    )
    assert np.all(np.asarray(bc) == SENTINEL)
    assert np.all(np.isneginf(np.asarray(bg)))
    assert np.allclose(np.asarray(c0), width)


@pytest.mark.parametrize("seed", [0, 5])
@pytest.mark.parametrize("constant", [None, np.float32(0.3)])
def test_heavy_bincount_matches_quadratic_oracle(seed, constant):
    """Heavy-class community-range-tile kernel (heavy_bincount.py) vs the
    quadratic XLA fallback on the same rows: identical best_c/best_gain/
    counter0 bit-for-bit (1/16-multiple weights make f32 sums exact in any
    order, so the matmul-bincount and the all-pairs aggregation agree;
    the non-dyadic constant=0.3 case additionally pins the gain's operand
    association to the XLA path's)."""
    from cuvite_tpu.kernels.heavy_bincount import heavy_argmax_pallas

    n_rows, width, nv = 64, 512, 500
    nv_ceil, c_tile, d_chunk = 512, 128, 128
    cmat, wmat, curr, vdeg, sl, comm_deg, _const_dyadic = _bucket_case(
        n_rows, width, nv, seed)
    constant = _const_dyadic if constant is None else constant
    is_cc = cmat == curr[:, None]
    counter0 = np.sum(np.where(is_cc, wmat, 0.0), axis=1).astype(np.float32)
    ay = comm_deg[cmat]
    ax = comm_deg[curr] - vdeg
    ref = _row_argmax(
        jnp.asarray(cmat), jnp.asarray(wmat), jnp.asarray(ay), None,
        jnp.asarray(curr), jnp.asarray(vdeg), jnp.asarray(sl),
        jnp.asarray(ax), jnp.asarray(constant), SENTINEL,
    )
    comm_deg_pad = np.zeros(nv_ceil, dtype=np.float32)
    comm_deg_pad[:nv] = comm_deg
    bc, bg, c0 = heavy_argmax_pallas(
        jnp.asarray(np.ascontiguousarray(cmat.T)),
        jnp.asarray(np.ascontiguousarray(wmat.T)),
        jnp.asarray(comm_deg_pad),
        jnp.asarray(curr), jnp.asarray(vdeg), jnp.asarray(sl),
        jnp.asarray(ax), jnp.asarray(constant),
        c_tile=c_tile, d_chunk=d_chunk, interpret=True,
    )
    assert np.array_equal(np.asarray(c0), counter0)
    assert np.array_equal(np.asarray(bg), np.asarray(ref.best_gain))
    assert np.array_equal(np.asarray(bc), np.asarray(ref.best_c))


def test_heavy_bincount_zero_weight_edges_are_candidates():
    """A community reached only by a w=0 edge is still a valid move target
    (same invariant as the XLA paths: 'No w>0 filter').  Its gain
    -2*eix - 2*vdeg*const*(ay-ax) can win when ay < ax."""
    from cuvite_tpu.kernels.heavy_bincount import heavy_argmax_pallas

    n_rows, width, nv = 16, 128, 120
    nv_ceil, c_tile, d_chunk = 128, 128, 128
    rng = np.random.default_rng(9)
    cmat = rng.integers(0, nv, size=(n_rows, width)).astype(np.int32)
    wmat = (rng.integers(0, 4, size=(n_rows, width)) / 16.0).astype(
        np.float32)  # ~1/4 of edges have weight 0
    curr = rng.integers(0, nv, size=n_rows).astype(np.int32)
    vdeg = np.maximum(wmat.sum(axis=1), 0.25).astype(np.float32)
    sl = np.zeros(n_rows, dtype=np.float32)
    comm_deg = (rng.integers(1, 64, size=nv) / 8.0).astype(np.float32)
    ay = comm_deg[cmat]
    ax = comm_deg[curr] - vdeg
    constant = np.float32(1.0 / 16.0)
    ref = _row_argmax(
        jnp.asarray(cmat), jnp.asarray(wmat), jnp.asarray(ay), None,
        jnp.asarray(curr), jnp.asarray(vdeg), jnp.asarray(sl),
        jnp.asarray(ax), jnp.asarray(constant), SENTINEL,
    )
    cdp = np.zeros(nv_ceil, dtype=np.float32)
    cdp[:nv] = comm_deg
    bc, bg, c0 = heavy_argmax_pallas(
        jnp.asarray(np.ascontiguousarray(cmat.T)),
        jnp.asarray(np.ascontiguousarray(wmat.T)),
        jnp.asarray(cdp),
        jnp.asarray(curr), jnp.asarray(vdeg), jnp.asarray(sl),
        jnp.asarray(ax), jnp.asarray(constant),
        c_tile=c_tile, d_chunk=d_chunk, interpret=True,
    )
    assert np.array_equal(np.asarray(bg), np.asarray(ref.best_gain))
    assert np.array_equal(np.asarray(bc), np.asarray(ref.best_c))

    # Constructed row where a community reached ONLY by a w=0 edge WINS:
    # pins valid = (cnt > 0), not (wagg > 0) — the old rule returns
    # community 2 here.  curr=0, no edges into it (eix=0); community 1
    # via w=0 (tiny comm_deg -> positive gain), community 2 via w=0.5
    # (huge comm_deg -> negative gain).
    one = np.full((1, 128), nv_ceil, dtype=np.int32)
    onew = np.zeros((1, 128), dtype=np.float32)
    one[0, 0], onew[0, 0] = 1, 0.0
    one[0, 1], onew[0, 1] = 2, 0.5
    cd1 = np.ones(nv_ceil, dtype=np.float32)
    cd1[1], cd1[2] = 0.125, 40.0
    bc1, bg1, c01 = heavy_argmax_pallas(
        jnp.asarray(one.T.copy()), jnp.asarray(onew.T.copy()),
        jnp.asarray(cd1),
        jnp.asarray(np.array([0], np.int32)),
        jnp.asarray(np.array([0.5], np.float32)),
        jnp.asarray(np.array([0.0], np.float32)),
        jnp.asarray(np.array([0.5], np.float32)),  # ax = cd[0] - vdeg
        jnp.asarray(np.float32(1 / 16)),
        c_tile=c_tile, d_chunk=d_chunk, interpret=True,
    )
    assert int(bc1[0]) == 1, "w=0-only community must be the argmax"
    assert float(bg1[0]) == 2 * 0.5 * (1 / 16) * (0.5 - 0.125)
    assert float(c01[0]) == 0.0


def test_heavy_bincount_padding_and_no_candidates():
    """Padded slots (c = nv_ceil, w = 0) never contribute; rows whose
    neighbors all sit in the current community return the sentinel."""
    from cuvite_tpu.kernels.heavy_bincount import heavy_argmax_pallas

    n_rows, width = 8, 256
    nv, nv_ceil, c_tile, d_chunk = 100, 128, 128, 128
    rng = np.random.default_rng(2)
    curr = rng.integers(0, nv, size=n_rows).astype(np.int32)
    cmat = np.full((n_rows, width), nv_ceil, dtype=np.int32)  # all padding
    wmat = np.zeros((n_rows, width), dtype=np.float32)
    # First half of the slots: real edges into the CURRENT community only.
    cmat[:, : width // 2] = curr[:, None]
    wmat[:, : width // 2] = 0.5
    vdeg = np.ones(n_rows, dtype=np.float32)
    sl = np.zeros(n_rows, dtype=np.float32)
    comm_deg = np.ones(nv_ceil, dtype=np.float32)
    ax = comm_deg[curr] - vdeg
    bc, bg, c0 = heavy_argmax_pallas(
        jnp.asarray(np.ascontiguousarray(cmat.T)),
        jnp.asarray(np.ascontiguousarray(wmat.T)),
        jnp.asarray(comm_deg),
        jnp.asarray(curr), jnp.asarray(vdeg), jnp.asarray(sl),
        jnp.asarray(ax), jnp.asarray(np.float32(0.01)),
        c_tile=c_tile, d_chunk=d_chunk, interpret=True,
    )
    assert np.all(np.asarray(bc) == SENTINEL)
    assert np.all(np.isneginf(np.asarray(bg)))
    assert np.allclose(np.asarray(c0), 0.5 * (width // 2))


def test_pallas_engine_end_to_end(karate):
    """engine='pallas' must produce the same result as engine='bucketed'
    through the full multi-phase driver (interpret mode on CPU)."""
    from cuvite_tpu.louvain.driver import louvain_phases

    res_b = louvain_phases(karate, engine="bucketed")
    res_p = louvain_phases(karate, engine="pallas")
    assert res_p.modularity == pytest.approx(res_b.modularity, abs=1e-6)
    assert np.array_equal(res_p.communities, res_b.communities)


def test_pallas_engine_rmat():
    from cuvite_tpu.io.generate import generate_rmat
    from cuvite_tpu.louvain.driver import louvain_phases

    g = generate_rmat(10, edge_factor=8, seed=4)
    res_b = louvain_phases(g, engine="bucketed")
    res_p = louvain_phases(g, engine="pallas")
    assert res_p.modularity == pytest.approx(res_b.modularity, abs=1e-5)


# ---------------------------------------------------------------------------
# ISSUE 8: the heavy-class kernel promotion — layout builder, policy, and
# the compiled-path (jitted driver, interpret kernel) parity pin.


def test_build_heavy_layout_contract():
    from cuvite_tpu.kernels.heavy_bincount import build_heavy_layout

    nv_local, pad_id = 64, 4096
    # CSR-ordered padded triples: vertex 3 (4 edges), vertex 7 (2 edges).
    hs = np.array([3, 3, 3, 3, 7, 7, 64, 64], np.int64)
    hd = np.array([10, 11, 12, 13, 20, 21, 0, 0], np.int64)
    hw = np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 0, 0], np.float32)
    verts, dT, wT = build_heavy_layout(hs, hd, hw, nv_local=nv_local,
                                       pad_id=pad_id, d_chunk=8)
    assert verts.shape == (8,) and dT.shape == wT.shape == (8, 8)
    assert list(verts[:2]) == [3, 7] and (verts[2:] == nv_local).all()
    assert list(dT[:4, 0]) == [10, 11, 12, 13]
    assert list(wT[:2, 1]) == [5.0, 6.0]
    # Padding slots: dst == pad_id (never a candidate), w == 0.
    assert (dT[4:, 0] == pad_id).all() and (wT[2:, 1] == 0).all()
    assert (dT[:, 2:] == pad_id).all()
    # Element budget: an over-budget hub set degrades to None.
    assert build_heavy_layout(hs, hd, hw, nv_local=nv_local,
                              pad_id=pad_id, d_chunk=8,
                              max_elems=16) is None
    # No heavy edges at all -> None.
    empty = np.full(8, nv_local, np.int64)
    assert build_heavy_layout(empty, hd, hw, nv_local=nv_local,
                              pad_id=pad_id) is None


def test_heavy_kernel_policy(monkeypatch):
    import jax

    from cuvite_tpu.kernels.heavy_bincount import heavy_kernel_enabled

    monkeypatch.delenv("CUVITE_HEAVY_KERNEL", raising=False)
    # tier-1 runs on CPU: the default engages on the TPU backend only.
    assert heavy_kernel_enabled() == (jax.default_backend() == "tpu")
    monkeypatch.setenv("CUVITE_HEAVY_KERNEL", "0")   # kill switch
    assert heavy_kernel_enabled() is False
    monkeypatch.setenv("CUVITE_HEAVY_KERNEL", "1")   # forced (interpret)
    assert heavy_kernel_enabled() is True


@pytest.fixture(scope="module")
def hub_graph():
    """A graph with one genuinely heavy vertex (> 8192 neighbors, the
    widths[-1] residual) plus background structure."""
    from cuvite_tpu.core.graph import Graph

    rng = np.random.default_rng(0)
    nv = 9000
    hub_dst = rng.choice(np.arange(1, nv), size=8400, replace=False)
    src = np.concatenate([np.zeros(8400, np.int64),
                          rng.integers(1, nv, 12000)])
    dst = np.concatenate([hub_dst, rng.integers(1, nv, 12000)])
    return Graph.from_edges(nv, src, dst)


# pallas arm ~29 s under the CPU interpreter; the kernel's bit-identity
# stays tier-1 through the bucketed arm + the unit-level kernel tests.
@pytest.mark.parametrize(
    "engine",
    ["bucketed", pytest.param("pallas", marks=pytest.mark.slow)])
def test_heavy_kernel_full_run_bit_identical(hub_graph, engine,
                                             monkeypatch):
    """The promoted heavy path (CUVITE_HEAVY_KERNEL=1 forces the kernel
    in interpret mode on CPU — the same jitted driver path the TPU
    default runs) must cluster bit-identically to the sorted heavy
    path it replaces."""
    from cuvite_tpu.louvain.driver import louvain_phases

    monkeypatch.setenv("CUVITE_HEAVY_KERNEL", "0")
    r0 = louvain_phases(hub_graph, engine=engine)
    monkeypatch.setenv("CUVITE_HEAVY_KERNEL", "1")
    r1 = louvain_phases(hub_graph, engine=engine)
    assert len(r0.phases) == len(r1.phases) >= 2
    assert r0.total_iterations == r1.total_iterations
    assert r0.modularity == r1.modularity
    assert np.array_equal(r0.communities, r1.communities)
    if engine == "pallas":
        # Coverage honesty: with the heavy kernel engaged the heavy
        # residual (width 0) counts as kernelized; without it, not.
        assert r1.pallas_coverage > r0.pallas_coverage
        assert 0 in r1.pallas_width_hits


def test_heavy_kernel_budget_degrade_keeps_sorted_path(hub_graph,
                                                       monkeypatch):
    """An over-budget hub layout must degrade loudly to the sorted path
    and still produce the identical clustering (the PALLAS_MAX_WIDTH
    degrade-with-coverage pattern)."""
    from cuvite_tpu.louvain.driver import louvain_phases

    monkeypatch.setenv("CUVITE_HEAVY_KERNEL", "0")
    r0 = louvain_phases(hub_graph, engine="bucketed")
    monkeypatch.setenv("CUVITE_HEAVY_KERNEL", "1")
    monkeypatch.setenv("CUVITE_HEAVY_ELEMS", "64")
    with pytest.warns(UserWarning, match="CUVITE_HEAVY_ELEMS"):
        r1 = louvain_phases(hub_graph, engine="bucketed")
    assert np.array_equal(r0.communities, r1.communities)
