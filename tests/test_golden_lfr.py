"""End-to-end clustering-quality golden gate (SURVEY §4(b)).

The reference's de-facto correctness oracle is the -g ground-truth
comparison against LFR benchmark graphs (/root/reference/main.cpp:553-559,
compare.cpp:8-256): run the full pipeline, compare the produced communities
to the planted ones, and demand a high F-score.  This test reproduces that
gate with a planted-partition graph (the LFR degenerate case with flat
community sizes): if clustering QUALITY regresses — not just modularity
self-consistency — this fails.
"""

import numpy as np
import pytest

from cuvite_tpu.core.graph import Graph
from cuvite_tpu.evaluate.compare import compare_communities
from cuvite_tpu.louvain.driver import louvain_phases


def planted_partition(n_comms: int, comm_size: int, p_in: float,
                      p_out: float, seed: int):
    """Planted-partition graph + ground-truth labels (numpy, no nx dep)."""
    rng = np.random.default_rng(seed)
    nv = n_comms * comm_size
    truth = np.repeat(np.arange(n_comms), comm_size)
    # candidate pairs i<j via block sampling: full O(nv^2) mask is fine at
    # test scale (nv <= ~1k)
    iu, ju = np.triu_indices(nv, k=1)
    same = truth[iu] == truth[ju]
    p = np.where(same, p_in, p_out)
    keep = rng.random(len(iu)) < p
    src, dst = iu[keep], ju[keep]
    return Graph.from_edges(nv, src, dst), truth


@pytest.fixture(scope="module")
def planted():
    return planted_partition(n_comms=16, comm_size=32, p_in=0.4,
                             p_out=0.004, seed=7)


def test_full_pipeline_recovers_planted_partition(planted):
    g, truth = planted
    res = louvain_phases(g)
    r = compare_communities(truth, res.communities)
    assert r.f_score >= 0.95, r.report()
    assert res.modularity > 0.5


def test_multishard_pipeline_recovers_planted_partition(planted):
    g, truth = planted
    res = louvain_phases(g, nshards=8)
    r = compare_communities(truth, res.communities)
    assert r.f_score >= 0.95, r.report()


def test_threshold_cycling_keeps_quality(planted):
    g, truth = planted
    res = louvain_phases(g, threshold_cycling=True)
    r = compare_communities(truth, res.communities)
    assert r.f_score >= 0.95, r.report()
