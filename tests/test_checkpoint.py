"""Checkpoint/resume: a run interrupted at any phase boundary resumes to
the same final clustering as an uninterrupted run."""

import numpy as np
import pytest

from cuvite_tpu.louvain.driver import louvain_phases
from cuvite_tpu.utils.checkpoint import load_latest


def test_resume_matches_uninterrupted(karate, tmp_path):
    ckpt = str(tmp_path / "ck")
    full = louvain_phases(karate)

    # "Crash" after phase 0: limit to one phase but write checkpoints.
    part = louvain_phases(karate, checkpoint_dir=ckpt, max_phases=1)
    ck = load_latest(ckpt)
    assert ck is not None and ck.phase == 1
    assert part.modularity < full.modularity  # genuinely interrupted

    res = louvain_phases(karate, checkpoint_dir=ckpt, resume=True)
    assert res.modularity == full.modularity
    assert np.array_equal(res.communities, full.communities)
    assert res.total_iterations == full.total_iterations
    assert [p.modularity for p in res.phases] == \
        [p.modularity for p in full.phases]


def test_resume_without_checkpoint_is_fresh(karate, tmp_path):
    ckpt = str(tmp_path / "empty")
    res = louvain_phases(karate, checkpoint_dir=ckpt, resume=True)
    full = louvain_phases(karate)
    assert np.array_equal(res.communities, full.communities)


def test_checkpoint_mismatched_graph_raises(karate, ring8, tmp_path):
    """Resuming in a directory written for a DIFFERENT graph must surface
    the mismatch (content fingerprint), not silently compose wrong labels
    or silently restart."""
    ckpt = str(tmp_path / "ck")
    louvain_phases(karate, checkpoint_dir=ckpt, max_phases=1)
    with pytest.raises(ValueError, match="fingerprint"):
        louvain_phases(ring8, checkpoint_dir=ckpt, resume=True)


def test_checkpoint_same_shape_different_content_raises(karate, tmp_path):
    """Same (nv, ne) but different weights — the silent-wrong-resume case
    the counts-only fingerprint missed — must also raise."""
    ckpt = str(tmp_path / "ck")
    louvain_phases(karate, checkpoint_dir=ckpt, max_phases=1)
    from cuvite_tpu.core.graph import Graph

    other = Graph(offsets=karate.offsets.copy(), tails=karate.tails.copy(),
                  weights=karate.weights * 2.0, policy=karate.policy)
    with pytest.raises(ValueError, match="fingerprint"):
        louvain_phases(other, checkpoint_dir=ckpt, resume=True)


def test_corrupt_checkpoint_falls_back(karate, tmp_path):
    ckpt = tmp_path / "ck"
    louvain_phases(karate, checkpoint_dir=str(ckpt), max_phases=1)
    # Corrupt a later (higher-numbered) file; loader must skip it.
    bad = ckpt / "phase_0099.npz"
    bad.write_bytes(b"not a zip")
    ck = load_latest(str(ckpt))
    assert ck is not None and ck.phase == 1


def test_resume_at_max_phases_runs_nothing_more(karate, tmp_path):
    ckpt = str(tmp_path / "ck")
    part = louvain_phases(karate, checkpoint_dir=ckpt, max_phases=1)
    res = louvain_phases(karate, checkpoint_dir=ckpt, resume=True,
                         max_phases=1)
    assert len(res.phases) == 1
    assert res.modularity == part.modularity


def test_stale_higher_checkpoints_cleared(karate, tmp_path):
    """A fresh (non-resume) run in a reused directory must not leave a
    previous run's later phases to hijack a subsequent --resume."""
    ckpt = str(tmp_path / "ck")
    full = louvain_phases(karate, checkpoint_dir=ckpt)      # run A: N phases
    louvain_phases(karate, checkpoint_dir=ckpt, max_phases=1)  # run B killed
    ck = load_latest(ckpt)
    assert ck is not None and ck.phase == 1                 # run A's cleared
    res = louvain_phases(karate, checkpoint_dir=ckpt, resume=True)
    assert res.modularity == full.modularity


def test_one_phase_with_checkpoint_rejected(karate, tmp_path):
    import pytest

    with pytest.raises(ValueError):
        louvain_phases(karate, checkpoint_dir=str(tmp_path), one_phase=True)
