"""Two-level ICI/DCN exchange tests (ISSUE 18).

The tentpole contract, pinned three ways:

  * **Bit-identity.**  The two-level exchange changes WHERE community
    tables live (replicated only inside the fast ICI submesh), never
    what is computed: labels and modularity are bit-identical to the
    flat sparse exchange across every hybrid factorization of the
    8-device pool — through :func:`meshcheck.assert_mesh_neutral`, the
    one shared implementation.

  * **Plan structure.**  ``ExchangePlan.build_grouped`` degenerates to
    the flat plan at ici=1, remaps dst ids into group-local space, and
    reports per-axis stats (table_bytes_per_device, ghost_bytes).

  * **Sabotage.**  Re-widening one table's gather to the global axis
    MUST be convicted by M003's per-axis ``ici_replicated`` budget —
    measured on the traced step jaxpr at nv=8192, where the |dcn|-fold
    per-device inflation clears the law's tolerance-plus-floor
    allowance (at the 2048-vertex audit graph the gap hides under the
    4 KiB floor; a gate that cannot fail is not a gate).
"""

import os

import jax
import numpy as np
import pytest

from cuvite_tpu.analysis import meshcheck as mc
from cuvite_tpu.comm import exchange as xch
from cuvite_tpu.comm.mesh import make_hybrid_mesh
from cuvite_tpu.core.distgraph import DistGraph
from cuvite_tpu.io.generate import generate_rmat
from cuvite_tpu.louvain import driver as drv
from cuvite_tpu.louvain.driver import PhaseRunner, louvain_phases

HYBRID_SHAPES = ((8, 1), (4, 2), (2, 4), (1, 8))
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUDGET = os.path.join(REPO, mc.DEFAULT_BUDGET_REL)


def _labels(g, **kw):
    res = louvain_phases(g, max_phases=2, verbose=False, **kw)
    return [(np.asarray(res.communities), float(res.modularity))]


def _run_cfg(g):
    def run(cfg):
        if cfg == "flat":
            return _labels(g, nshards=8, engine="bucketed",
                           exchange="sparse")
        # exchange='auto' resolves to 'twolevel' when |dcn| > 1 and to
        # the flat sparse program at |dcn| == 1 — both paths covered.
        return _labels(g, nshards=8, engine="bucketed", exchange="auto",
                       mesh_shape=cfg)
    return run


def test_twolevel_bit_identical_to_flat():
    g = generate_rmat(10, edge_factor=8, seed=3)
    mc.assert_mesh_neutral(_run_cfg(g), ["flat", *HYBRID_SHAPES],
                           entry="twolevel_vs_flat")


@pytest.mark.slow
def test_twolevel_bit_identical_to_flat_rmat14():
    # The acceptance-scale pin: rmat-14 across every hybrid shape.
    g = generate_rmat(14, edge_factor=8, seed=3)
    mc.assert_mesh_neutral(_run_cfg(g), ["flat", *HYBRID_SHAPES],
                           entry="twolevel_vs_flat_rmat14")


# ---------------------------------------------------------------------------
# Grouped plan structure.


def test_grouped_plan_degenerates_to_flat_at_ici1():
    g = generate_rmat(8, edge_factor=8, seed=1)
    dg = DistGraph.build(g, 4)
    flat = xch.ExchangePlan.build(dg)
    grouped = xch.ExchangePlan.build_grouped(dg, 4)
    assert grouped.ici == 1
    assert grouped.nv_pad == flat.nv_pad
    for gg, gf in zip(grouped.ghost_ids, flat.ghost_ids):
        np.testing.assert_array_equal(gg, gf)
    np.testing.assert_array_equal(grouped.send_idx, flat.send_idx)
    # and remap_dst is the flat remap bit-for-bit
    s = 1
    src = np.asarray(dg.shards[s].src)
    dst = np.asarray(dg.shards[s].dst)
    np.testing.assert_array_equal(grouped.remap_dst(s, src, dst),
                                  flat.remap_dst(s, src, dst))


def test_grouped_plan_group_local_remap():
    g = generate_rmat(8, edge_factor=8, seed=1)
    dg = DistGraph.build(g, 8)
    plan = xch.ExchangePlan.build_grouped(dg, 2)  # ici = 4
    assert plan.ici == 4 and plan.nshards == 2
    nvp = dg.nv_pad
    nv_grp = plan.nv_pad
    assert nv_grp == 4 * nvp and plan.shard_nv_pad == nvp
    for s in range(8):
        grp = s // 4
        src = np.asarray(dg.shards[s].src)
        dst = np.asarray(dg.shards[s].dst)
        rd = np.asarray(plan.remap_dst(s, src, dst))
        real = src < nvp
        owned = real & (dst >= grp * nv_grp) & (dst < (grp + 1) * nv_grp)
        # owned dsts land at their group-local index; ghosts beyond
        np.testing.assert_array_equal(rd[owned],
                                      dst[owned] - grp * nv_grp)
        assert (rd[real & ~owned] >= nv_grp).all()
        # a shard's self edge remaps to (s % ici) * nvp + src — the
        # base build_stacked_plans must use for self-loop detection
        self_e = real & (dst == s * nvp + src)
        if self_e.any():
            np.testing.assert_array_equal(
                rd[self_e], (s % 4) * nvp + src[self_e])


def test_grouped_stats_report_per_axis_bytes():
    g = generate_rmat(8, edge_factor=8, seed=1)
    dg = DistGraph.build(g, 8)
    flat = xch.ExchangePlan.build(dg).stats()
    two = xch.ExchangePlan.build_grouped(dg, 2).stats()
    assert flat["mode"] == "sparse" and "dcn" not in flat
    assert two["mode"] == "twolevel"
    assert (two["dcn"], two["ici"]) == (2, 4)
    # group table window = nv_total / |dcn| per device, two tables wide
    assert two["table_bytes_per_device"] == \
        2 * dg.total_padded_vertices // 2 * 4
    assert two["ghost_bytes"] > 0


def test_result_carries_exchange_stats():
    # The bench/CLI `exchange` block's source (ISSUE 18 satellite): an
    # SPMD run's result carries the phase-1 plan digest; single-shard
    # runs carry None.
    g = generate_rmat(8, edge_factor=8, seed=1)
    two = louvain_phases(g, mesh_shape=(2, 4), engine="bucketed",
                         max_phases=1, verbose=False)
    xs = two.exchange_stats
    assert xs["mode"] == "twolevel"
    assert (xs["dcn"], xs["ici"]) == (2, 4)
    assert xs["table_bytes_per_device"] > 0 and xs["ghost_bytes"] > 0
    flat = louvain_phases(g, nshards=8, engine="bucketed",
                          exchange="sparse", max_phases=1, verbose=False)
    assert flat.exchange_stats["mode"] == "sparse"
    solo = louvain_phases(g, engine="bucketed", max_phases=1,
                          verbose=False)
    assert solo.exchange_stats is None


def test_twolevel_validation_errors():
    g = generate_rmat(8, edge_factor=8, seed=1)
    with pytest.raises(ValueError, match="mesh_shape"):
        louvain_phases(g, nshards=4, mesh_shape=(2, 4))
    with pytest.raises(ValueError, match="twolevel"):
        louvain_phases(g, nshards=8, exchange="twolevel")
    with pytest.raises(ValueError, match="replicated"):
        louvain_phases(g, mesh_shape=(2, 4), exchange="replicated")
    with pytest.raises(ValueError, match="coloring"):
        louvain_phases(g, mesh_shape=(2, 4), coloring=2)


# ---------------------------------------------------------------------------
# The M003 per-axis sabotage: one table re-widened to the global axis.


def _trace_table_row(nv, shape):
    """exchange_tables ledger row of the step jaxpr traced at ``shape``
    on a ``nv``-vertex audit-style graph (trace only — no execution)."""
    from cuvite_tpu.analysis.jaxpr_audit import tiny_graphs

    n_dcn, n_ici = shape
    g = tiny_graphs(b=1, nv=nv, ne=4 * nv)[0]
    dg = DistGraph.build(g, n_dcn * n_ici)
    runner = PhaseRunner(dg, mesh=make_hybrid_mesh(n_dcn, n_ici),
                         engine="bucketed", exchange="twolevel")
    jaxpr = jax.make_jaxpr(
        lambda c: runner._call(c, runner._extra))(runner.comm0)
    return mc.exchange_table_bytes(jaxpr, {"dcn": n_dcn, "ici": n_ici})


def test_global_axis_table_convicted_by_per_axis_budget(monkeypatch):
    nv, shape = 8192, (4, 2)
    honest = _trace_table_row(nv, shape)
    # honest: two group tables (comm + vdeg) at nv/|dcn| each
    assert honest["per_device"] == 2 * nv // 4 * 4
    assert honest["global"] == 2 * nv * 4

    real = xch.twolevel_env

    def widened(comm, vdeg, send_idx, ghost_sel, dcn_axis, ici_axis,
                **kw):
        env = real(comm, vdeg, send_idx, ghost_sel, dcn_axis, ici_axis,
                   **kw)
        # the sabotage: one community table gathered over BOTH axes —
        # O(nv_total) per device again, exactly what two-level removed.
        wide = jax.lax.all_gather(comm, (dcn_axis, ici_axis), tiled=True)
        n = env.cdeg_v.shape[0]
        return env._replace(
            cdeg_v=env.cdeg_v + 0 * wide[:n].astype(env.cdeg_v.dtype))

    monkeypatch.setattr(xch, "twolevel_env", widened)
    drv._STEP_CACHE.clear()
    try:
        sabotaged = _trace_table_row(nv, shape)
    finally:
        drv._STEP_CACHE.clear()
    assert sabotaged["per_device"] == honest["per_device"] + nv * 4

    manifest = mc.load_budget(BUDGET)
    axes = {"dcn": shape[0], "ici": shape[1]}

    def row(r):
        return {"4x2": {"devices": 8, "axes": axes,
                        "categories": {"exchange_tables": r}}}

    assert mc.check_replication("twolevel", row(honest), manifest) == []
    findings = mc.check_replication("twolevel", row(sabotaged), manifest)
    assert [f.rule for f in findings] == ["M003"], findings
    assert "ici_replicated" in findings[0].message


def test_exchange_table_bytes_counts_replicating_collectives_only():
    """The metric's ground rules on a hand-built jaxpr: all_gather and
    non-scalar psum count; all_to_all (distinct data per device) and
    scalar psums do not."""
    from functools import partial

    from cuvite_tpu.comm.mesh import make_mesh, shard_map
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(8)

    @partial(shard_map, mesh=mesh, in_specs=P("v"), out_specs=P(),
             check_vma=False)
    def body(x):
        g = jax.lax.all_gather(x, "v", tiled=True)      # 8*16*4 = 512 B  # graftlint: disable=R025 — hand-built fixture exercising the exchange_table_bytes metric, not a product table
        t = jax.lax.psum(x, "v")                        # 16*4 = 64 B
        s = jax.lax.psum(jax.numpy.sum(x), "v")         # scalar: 0
        a = jax.lax.all_to_all(x.reshape(8, 2), "v", 0, 0)  # moved: 0
        return g.sum() + t.sum() + s + a.sum()

    jaxpr = jax.make_jaxpr(body)(np.zeros(128, np.float32))
    row = mc.exchange_table_bytes(jaxpr, {"v": 8})
    assert row["per_device"] == 512 + 64
    # the gather spans the whole axis (1 distinct copy); the psum'd
    # table is replicated 8-fold but covers its extent once
    assert row["global"] == 512 + 64
