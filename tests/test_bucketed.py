"""The bucketed engine must be step-for-step identical to the sort engine
(and therefore to the reference-semantics oracle)."""

import numpy as np
import pytest

from cuvite_tpu.core.distgraph import DistGraph
from cuvite_tpu.core.graph import Graph
from cuvite_tpu.evaluate.modularity import modularity as mod_oracle
from cuvite_tpu.io.generate import generate_rgg, generate_rmat
from cuvite_tpu.louvain.bucketed import BucketPlan
from cuvite_tpu.louvain.driver import PhaseRunner, louvain_phases


def _run_engines_one_phase(graph, iters=4):
    outs = []
    for engine in ("sort", "bucketed"):
        dg = DistGraph.build(graph, 1)
        r = PhaseRunner(dg, engine=engine)
        comm = r.comm0
        trace = []
        for _ in range(iters):
            target, q, moved, _ = r._step(r.src, r.dst, r.w, comm, r.vdeg,
                                          r.constant)
            trace.append((np.asarray(target), float(q), int(moved)))
            comm = target
        outs.append(trace)
    return outs


@pytest.mark.parametrize("maker", [
    lambda: generate_rgg(256, seed=1),
    lambda: generate_rmat(9, edge_factor=8, seed=2),   # has heavy vertices
])
def test_engines_identical_trajectories(maker):
    graph = maker()
    sort_trace, bucket_trace = _run_engines_one_phase(graph)
    for it, ((t1, q1, m1), (t2, q2, m2)) in enumerate(
            zip(sort_trace, bucket_trace)):
        np.testing.assert_array_equal(
            t1, t2, err_msg=f"engines diverge at iteration {it}")
        assert q2 == pytest.approx(q1, abs=1e-5)
        assert m1 == m2


def test_engines_identical_on_karate(karate):
    sort_trace, bucket_trace = _run_engines_one_phase(karate, iters=5)
    for (t1, q1, _), (t2, q2, _) in zip(sort_trace, bucket_trace):
        np.testing.assert_array_equal(t1, t2)


def test_bucket_plan_partitions_all_edges():
    g = generate_rmat(9, edge_factor=8, seed=2)
    dg = DistGraph.build(g, 1)
    sh = dg.shards[0]
    plan = BucketPlan.build(np.asarray(sh.src), np.asarray(sh.dst),
                            np.asarray(sh.w), nv_local=dg.nv_pad, base=0)
    # every real edge is represented exactly once: bucket row weights +
    # heavy weights sum to the total
    total = sum(float(b.w.sum()) for b in plan.buckets) \
        + float(plan.heavy_w.sum())
    assert total == pytest.approx(float(np.asarray(sh.w).sum()), rel=1e-6)
    # vertex coverage: every real vertex with degree > 0 appears in exactly
    # one bucket or the heavy set
    deg = np.bincount(np.asarray(sh.src)[np.asarray(sh.src) < dg.nv_pad],
                      minlength=dg.nv_pad)
    in_bucket = np.zeros(dg.nv_pad, dtype=int)
    for b in plan.buckets:
        real = b.verts[b.verts < dg.nv_pad]
        in_bucket[real] += 1
    heavy_real = np.unique(
        np.asarray(plan.heavy_src)[np.asarray(plan.heavy_src) < dg.nv_pad])
    in_bucket[heavy_real] += 1
    assert np.all(in_bucket[deg > 0] == 1)
    assert np.all(in_bucket[deg == 0] == 0)


def test_full_run_bucketed_matches_sort(karate):
    r1 = louvain_phases(karate, engine="sort")
    r2 = louvain_phases(karate, engine="bucketed")
    np.testing.assert_array_equal(r1.communities, r2.communities)
    assert r2.modularity == pytest.approx(r1.modularity, abs=1e-5)


def test_bucketed_weighted_selfloops():
    g = Graph.from_edges(6, [0, 1, 2, 3, 0, 4], [1, 2, 0, 3, 0, 5],
                         weights=[2.0, 1.0, 3.0, 5.0, 4.0, 1.0])
    sort_trace, bucket_trace = _run_engines_one_phase(g, iters=3)
    for (t1, q1, _), (t2, q2, _) in zip(sort_trace, bucket_trace):
        np.testing.assert_array_equal(t1, t2)
        assert q2 == pytest.approx(q1, abs=1e-6)


def test_heavy_path_and_chunking_with_small_widths():
    """Exercise the heavy fallback and lax.map chunked rows explicitly by
    shrinking the bucket widths (default widths leave rmat(9) heavy-free)."""
    import jax.numpy as jnp
    import cuvite_tpu.louvain.bucketed as bk
    from cuvite_tpu.louvain.bucketed import BucketPlan, bucketed_step
    from cuvite_tpu.louvain.step import make_single_step

    g = generate_rmat(9, edge_factor=8, seed=2)
    dg = DistGraph.build(g, 1)
    sh = dg.shards[0]
    plan = BucketPlan.build(np.asarray(sh.src), np.asarray(sh.dst),
                            np.asarray(sh.w), nv_local=dg.nv_pad, base=0,
                            widths=(4, 8))  # most vertices become heavy
    assert plan.has_heavy
    vdt, wdt = np.int32, np.float32
    buckets = tuple(
        (jnp.asarray(b.verts.astype(vdt)), jnp.asarray(b.dst.astype(vdt)),
         jnp.asarray(b.w.astype(wdt))) for b in plan.buckets)
    heavy = (jnp.asarray(plan.heavy_src.astype(vdt)),
             jnp.asarray(plan.heavy_dst.astype(vdt)),
             jnp.asarray(plan.heavy_w.astype(wdt)))
    sl = jnp.asarray(plan.self_loop.astype(wdt))
    nvt = dg.total_padded_vertices
    comm = jnp.arange(nvt, dtype=vdt)
    vdeg = jnp.asarray(dg.padded_weighted_degrees().astype(wdt))
    const = jnp.asarray(1.0 / g.total_edge_weight_twice(), dtype=wdt)

    ref_step = make_single_step(nvt)
    src, dst, w = dg.stacked_edges()
    for it in range(3):
        t1, q1, m1, _ = ref_step(jnp.asarray(src), jnp.asarray(dst),
                              jnp.asarray(w), comm, vdeg, const)
        t2, q2, m2, _ = bucketed_step(buckets, heavy, sl, comm, vdeg, const,
                                   nv_total=nvt, sentinel=np.iinfo(vdt).max)
        np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2),
                                      err_msg=f"iter {it}")
        assert float(q2) == pytest.approx(float(q1), abs=1e-5)
        comm = t1

    # chunked path: force a tiny chunk so lax.map runs with many chunks
    old = bk.ROW_ELEMS_CHUNK
    try:
        bk.ROW_ELEMS_CHUNK = 1 << 10
        plan2 = BucketPlan.build(np.asarray(sh.src), np.asarray(sh.dst),
                                 np.asarray(sh.w), nv_local=dg.nv_pad,
                                 base=0, widths=(4, 64, 256))
        buckets2 = tuple(
            (jnp.asarray(b.verts.astype(vdt)),
             jnp.asarray(b.dst.astype(vdt)),
             jnp.asarray(b.w.astype(wdt))) for b in plan2.buckets)
        heavy2 = (jnp.asarray(plan2.heavy_src.astype(vdt)),
                  jnp.asarray(plan2.heavy_dst.astype(vdt)),
                  jnp.asarray(plan2.heavy_w.astype(wdt)))
        comm = jnp.arange(nvt, dtype=vdt)
        t3, q3, _, _ = bucketed_step(buckets2, heavy2, sl, comm, vdeg, const,
                                  nv_total=nvt, sentinel=np.iinfo(vdt).max)
        t0, q0, _, _ = ref_step(jnp.asarray(src), jnp.asarray(dst),
                             jnp.asarray(w), comm, vdeg, const)
        np.testing.assert_array_equal(np.asarray(t0), np.asarray(t3))
    finally:
        bk.ROW_ELEMS_CHUNK = old


@pytest.mark.parametrize("nshards", [2, 8])
def test_multishard_bucketed_matches_single(nshards):
    """The sharded bucketed step (shard_map + all_gather/psum) must produce
    the same trajectory as the single-shard engines."""
    g = generate_rmat(9, edge_factor=8, seed=2)
    single = _run_engines_one_phase(g)[1]

    from cuvite_tpu.comm.mesh import make_mesh

    dg1 = DistGraph.build(g, 1)
    dg = DistGraph.build(g, nshards)
    mesh = make_mesh(nshards)
    r = PhaseRunner(dg, mesh=mesh, engine="bucketed")
    comm = r.comm0
    for it, (t1, q1, m1) in enumerate(single):
        target, q, moved, ovf = r._step(None, None, None, comm, r.vdeg,
                                        r.constant)
        assert not bool(ovf), "sparse budget overflow in test"
        # Labels are padded-space vertex ids and the padded layouts differ
        # per nshards: map each to original-id space, compare as partitions.
        lab1 = dg1.pad_to_old[t1[dg1.old_to_pad]]
        labN = dg.pad_to_old[np.asarray(target)[dg.old_to_pad]]
        assert _partition_signature(lab1) == _partition_signature(labN), \
            f"diverged at iteration {it}"
        assert float(q) == pytest.approx(q1, abs=1e-5)
        assert int(moved) == m1
        comm = target


def _partition_signature(labels):
    """Canonical form of a partition: tuple of frozensets of members."""
    import collections

    groups = collections.defaultdict(list)
    for v, c in enumerate(np.asarray(labels)):
        groups[int(c)].append(v)
    return frozenset(frozenset(m) for m in groups.values())


@pytest.mark.parametrize("nshards", [4])
def test_full_run_multishard_bucketed(karate, nshards):
    r1 = louvain_phases(karate, engine="bucketed")
    rN = louvain_phases(karate, nshards=nshards, engine="bucketed")
    assert rN.modularity == pytest.approx(r1.modularity, abs=1e-4)
    np.testing.assert_array_equal(
        _np_canon(r1.communities), _np_canon(rN.communities))


def _np_canon(labels):
    """Renumber labels by first appearance so partitions compare equal."""
    labels = np.asarray(labels)
    _, first = np.unique(labels, return_index=True)
    order = np.argsort(first)
    remap = np.empty(len(order), dtype=np.int64)
    remap[order] = np.arange(len(order))
    return remap[np.searchsorted(np.unique(labels), labels)]


def test_zero_weight_edges_engines_agree():
    """Zero-weight real edges must be candidates in both engines."""
    rng = np.random.default_rng(7)
    g0 = generate_rgg(128, seed=1)
    w = np.asarray(g0.weights).copy()
    # zero out ~20% of undirected edges symmetrically: rebuild from edges
    src, dst = g0.sources(), g0.tails
    keep_mask = src < dst
    es, ed = src[keep_mask], dst[keep_mask]
    ew = w[keep_mask]
    ew[rng.random(len(ew)) < 0.2] = 0.0
    g = Graph.from_edges(128, es, ed, weights=ew)
    sort_trace, bucket_trace = _run_engines_one_phase(g, iters=4)
    for it, ((t1, q1, m1), (t2, q2, m2)) in enumerate(
            zip(sort_trace, bucket_trace)):
        np.testing.assert_array_equal(t1, t2, err_msg=f"iter {it}")
        assert m1 == m2


def test_build_assemble_perm_properties():
    """Direct pin of the scatter-free assembly map: bucket vertices map to
    their own row in the concatenated space, everyone else (heavy /
    degree-0 / padding) to the trailing default slot."""
    from cuvite_tpu.louvain.bucketed import build_assemble_perm

    nv = 10
    verts_a = np.array([3, 7, nv, nv])     # padded bucket: rows 0..3
    verts_b = np.array([1, 2, 5])          # second bucket: rows 4..6
    perm = build_assemble_perm([verts_a, verts_b], nv)
    total = len(verts_a) + len(verts_b)
    assert perm.dtype == np.int32 and perm.shape == (nv,)
    assert perm[3] == 0 and perm[7] == 1          # bucket a rows
    assert perm[1] == 4 and perm[2] == 5 and perm[5] == 6
    # not in any bucket -> default slot
    for v in (0, 4, 6, 8, 9):
        assert perm[v] == total, (v, perm[v])
