"""The bucketed engine must be step-for-step identical to the sort engine
(and therefore to the reference-semantics oracle)."""

import numpy as np
import pytest

from cuvite_tpu.core.distgraph import DistGraph
from cuvite_tpu.core.graph import Graph
from cuvite_tpu.evaluate.modularity import modularity as mod_oracle
from cuvite_tpu.io.generate import generate_rgg, generate_rmat
from cuvite_tpu.louvain.bucketed import BucketPlan
from cuvite_tpu.louvain.driver import PhaseRunner, louvain_phases


def _run_engines_one_phase(graph, iters=4):
    outs = []
    for engine in ("sort", "bucketed"):
        dg = DistGraph.build(graph, 1)
        r = PhaseRunner(dg, engine=engine)
        comm = r.comm0
        trace = []
        for _ in range(iters):
            target, q, moved = r._step(r.src, r.dst, r.w, comm, r.vdeg,
                                       r.constant)
            trace.append((np.asarray(target), float(q), int(moved)))
            comm = target
        outs.append(trace)
    return outs


@pytest.mark.parametrize("maker", [
    lambda: generate_rgg(256, seed=1),
    lambda: generate_rmat(9, edge_factor=8, seed=2),   # has heavy vertices
])
def test_engines_identical_trajectories(maker):
    graph = maker()
    sort_trace, bucket_trace = _run_engines_one_phase(graph)
    for it, ((t1, q1, m1), (t2, q2, m2)) in enumerate(
            zip(sort_trace, bucket_trace)):
        np.testing.assert_array_equal(
            t1, t2, err_msg=f"engines diverge at iteration {it}")
        assert q2 == pytest.approx(q1, abs=1e-5)
        assert m1 == m2


def test_engines_identical_on_karate(karate):
    sort_trace, bucket_trace = _run_engines_one_phase(karate, iters=5)
    for (t1, q1, _), (t2, q2, _) in zip(sort_trace, bucket_trace):
        np.testing.assert_array_equal(t1, t2)


def test_bucket_plan_partitions_all_edges():
    g = generate_rmat(9, edge_factor=8, seed=2)
    dg = DistGraph.build(g, 1)
    sh = dg.shards[0]
    plan = BucketPlan.build(np.asarray(sh.src), np.asarray(sh.dst),
                            np.asarray(sh.w), nv_local=dg.nv_pad, base=0)
    # every real edge is represented exactly once: bucket row weights +
    # heavy weights sum to the total
    total = sum(float(b.w.sum()) for b in plan.buckets) \
        + float(plan.heavy_w.sum())
    assert total == pytest.approx(float(np.asarray(sh.w).sum()), rel=1e-6)
    # vertex coverage: every real vertex with degree > 0 appears in exactly
    # one bucket or the heavy set
    deg = np.bincount(np.asarray(sh.src)[np.asarray(sh.src) < dg.nv_pad],
                      minlength=dg.nv_pad)
    in_bucket = np.zeros(dg.nv_pad, dtype=int)
    for b in plan.buckets:
        real = b.verts[b.verts < dg.nv_pad]
        in_bucket[real] += 1
    heavy_real = np.unique(
        np.asarray(plan.heavy_src)[np.asarray(plan.heavy_src) < dg.nv_pad])
    in_bucket[heavy_real] += 1
    assert np.all(in_bucket[deg > 0] == 1)
    assert np.all(in_bucket[deg == 0] == 0)


def test_full_run_bucketed_matches_sort(karate):
    r1 = louvain_phases(karate, engine="sort")
    r2 = louvain_phases(karate, engine="bucketed")
    np.testing.assert_array_equal(r1.communities, r2.communities)
    assert r2.modularity == pytest.approx(r1.modularity, abs=1e-5)


def test_bucketed_weighted_selfloops():
    g = Graph.from_edges(6, [0, 1, 2, 3, 0, 4], [1, 2, 0, 3, 0, 5],
                         weights=[2.0, 1.0, 3.0, 5.0, 4.0, 1.0])
    sort_trace, bucket_trace = _run_engines_one_phase(g, iters=3)
    for (t1, q1, _), (t2, q2, _) in zip(sort_trace, bucket_trace):
        np.testing.assert_array_equal(t1, t2)
        assert q2 == pytest.approx(q1, abs=1e-6)
