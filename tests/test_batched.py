"""Batched multi-tenant driver tests (ISSUE 9): the properties that make
batch serving trustworthy.

  * packing: slab-class binning keys, batch-pow2 padding, pad-row
    invariants, mixed classes refused;
  * bit-identity: every tenant of a B>1 batch gets labels AND Q
    bit-equal to its own B=1 run — batching must never change results,
    including batches whose rows converge at different phase counts;
  * amortization evidence: a second batch of the same (class, B)
    compiles NOTHING, and the whole batch syncs the host exactly once
    per phase plus one final label gather;
  * sharding neutrality: the batch-axis mesh changes which device runs
    which rows, never what any row computes.
"""

import jax
import numpy as np
import pytest

from cuvite_tpu.core.batch import (
    BATCH_SIZES,
    MIN_NE_PAD,
    MIN_NV_PAD,
    batch_pad,
    batch_slabs,
    slab_class_of,
)
from cuvite_tpu.core.graph import Graph
from cuvite_tpu.io.generate import generate_rmat
from cuvite_tpu.louvain.driver import louvain_many, louvain_phases
from cuvite_tpu.obs import CompileWatcher
from cuvite_tpu.workloads.synth import many_seed, synthesize_graph


@pytest.fixture(scope="module")
def jobs():
    """Mixed sizes AND convergence lengths, one slab class: two R-MAT
    graphs (little community structure, several phases) and two synth
    power-law graphs (planted communities, fewer phases)."""
    gs = [generate_rmat(8, edge_factor=8, seed=s) for s in (1, 2)]
    gs += [synthesize_graph(2048, seed=many_seed(7, k)) for k in (0, 1)]
    assert len({slab_class_of(g) for g in gs}) == 1
    return gs


@pytest.fixture(scope="module")
def batch_result(jobs):
    """One warm batched run shared by the read-only assertions."""
    louvain_many(jobs)  # eat compiles for later cache/sync spies
    return louvain_many(jobs)


# ---------------------------------------------------------------------------
# Packing


def test_slab_class_floors():
    g = generate_rmat(8, edge_factor=8, seed=1)
    assert slab_class_of(g) == (MIN_NV_PAD, MIN_NE_PAD)
    big = generate_rmat(13, edge_factor=8, seed=1)
    cls = slab_class_of(big)
    assert cls[0] == 1 << 13 and cls[0] > MIN_NV_PAD


def test_batch_pad_ladder():
    assert [batch_pad(n) for n in (1, 2, 3, 5, 8, 9, 64)] == \
        [1, 2, 4, 8, 8, 16, 64]
    assert batch_pad(65) == 128  # beyond the ladder: plain pow2
    with pytest.raises(ValueError):
        batch_pad(0)


def test_batch_slabs_layout(jobs):
    b = batch_slabs(jobs)
    assert b.n_jobs == 4 and b.b_pad == 4
    assert b.slab_class == (MIN_NV_PAD, MIN_NE_PAD)
    assert b.src.shape == (4, MIN_NE_PAD)
    # Row 0 is a real slab: padding tail carries the src sentinel.
    ne0 = int(b.ne_real[0])
    assert (b.src[0, ne0:] == MIN_NV_PAD).all()
    assert (b.w[0, ne0:] == 0).all()
    assert b.row_valid.all() and (b.constant > 0).all()


def test_batch_slabs_pad_rows(jobs):
    b = batch_slabs(jobs[:3])  # 3 jobs pad to the 4-rung
    assert b.n_jobs == 3 and b.b_pad == 4 and b.pack_util == 0.75
    assert not b.row_valid[3]
    assert (b.src[3] == MIN_NV_PAD).all()
    assert not b.real_mask[3].any()
    assert b.constant[3] == 0.0


def test_batch_slabs_refuses_mixed_classes(jobs):
    big = generate_rmat(13, edge_factor=8, seed=1)
    with pytest.raises(ValueError, match="mixed slab classes"):
        batch_slabs([jobs[0], big])


def test_batch_sizes_are_pow2_ladder():
    assert all(b & (b - 1) == 0 for b in BATCH_SIZES)
    assert list(BATCH_SIZES) == sorted(BATCH_SIZES)


# ---------------------------------------------------------------------------
# Bit-identity and per-row semantics


def test_batched_bit_identical_to_b1(jobs, batch_result):
    """THE serving contract: every tenant's labels and Q from a B=4
    batch equal its own B=1 run bit-for-bit — with the batch holding
    rows of different phase/iteration counts (masked exit, not split)."""
    singles = [louvain_many([g]).results[0] for g in jobs]
    phase_counts = {len(r.phases) for r in batch_result.results}
    assert len(phase_counts) > 1, \
        "fixture must mix convergence lengths to exercise masking"
    for rb, r1 in zip(batch_result.results, singles):
        assert r1.modularity == rb.modularity
        assert np.array_equal(r1.communities, rb.communities)
        assert r1.total_iterations == rb.total_iterations
        assert len(r1.phases) == len(rb.phases)


def test_batched_matches_pergraph_driver_quality(jobs, batch_result):
    """Per-tenant Q tracks the per-graph bucketed driver (the batched
    loop's in-loop f32 vs the driver's precise recompute — equal on
    these exactly-representable graphs up to f32 noise)."""
    for g, rb in zip(jobs, batch_result.results):
        ref = louvain_phases(g, verbose=False)
        assert abs(ref.modularity - rb.modularity) < 5e-5
        assert ref.num_communities == rb.num_communities


def test_batched_convergence_telemetry(batch_result):
    for res in batch_result.results:
        assert res.convergence, "batched rows must carry telemetry"
        gained = [pc for pc in res.convergence if pc.gained]
        assert len(gained) == len(res.phases)
        # Rows carry real per-iteration Q curves (input-assignment
        # semantics: the phase's own scalar is the driver's).
        assert all(len(pc.rows) == min(pc.iterations, len(pc.rows))
                   for pc in res.convergence)


def test_edgeless_rows_short_circuit(jobs):
    empty = Graph.from_edges(5, np.zeros(0, np.int64),
                             np.zeros(0, np.int64))
    br = louvain_many([jobs[0], empty, jobs[1]])
    assert len(br.results) == 3
    mid = br.results[1]
    assert mid.modularity == 0.0
    assert np.array_equal(mid.communities, np.arange(5))
    # Neighbors still bit-match their solo runs (ordering preserved).
    solo = louvain_many([jobs[1]]).results[0]
    assert np.array_equal(br.results[2].communities, solo.communities)


# ---------------------------------------------------------------------------
# Amortization evidence


def test_zero_fresh_compiles_on_second_batch(jobs, batch_result):
    """One compile per (class, B): a second batch of DIFFERENT graphs
    in the same class at the same B traces nothing new."""
    fresh = [generate_rmat(8, edge_factor=8, seed=s) for s in (11, 12)]
    fresh += [synthesize_graph(2048, seed=many_seed(7, k)) for k in (2, 3)]
    with CompileWatcher() as watch:
        br = louvain_many(fresh)
    assert watch.compiles == [], \
        f"second (class, B) batch recompiled: {watch.compiles}"
    assert len(br.results) == 4


def test_one_device_sync_per_phase_batched(jobs, batch_result, monkeypatch):
    """The whole batch syncs once per phase (driver._phase_sync) plus
    exactly one final label gather — the per-graph driver's sync
    discipline, extended to B tenants."""
    import cuvite_tpu.louvain.driver as drv

    orig_get = jax.device_get
    gets = []

    def spy(x):
        gets.append(x)
        return orig_get(x)

    monkeypatch.setattr(jax, "device_get", spy)
    br = louvain_many(jobs)
    assert len(gets) == br.n_phases + 1, \
        f"{len(gets)} device_get calls for {br.n_phases} batch phases " \
        "(want one per phase + the final label gather)"
    assert drv is not None  # keep the import for the sync chokepoint ref


def test_sharding_never_changes_results(jobs, batch_result):
    """mesh=None (single-device program) and mesh='auto' (batch axis
    sharded over the virtual-device mesh) produce identical tenants —
    asserted through the ONE shared meshcheck implementation of
    "bit-identical across mesh shapes" (the tier-5 M002 check)."""
    from cuvite_tpu.analysis.meshcheck import assert_mesh_neutral

    def run(mesh):
        br = batch_result if mesh == "auto" \
            else louvain_many(jobs, mesh=mesh)
        return [(r.communities, r.modularity) for r in br.results]

    assert_mesh_neutral(run, ["auto", None], entry="batched_fused")


def test_explicit_b_pad_validhalf(jobs):
    with pytest.raises(ValueError, match="b_pad"):
        louvain_many(jobs, b_pad=2)  # 4 jobs cannot pack into 2 rows


# ---------------------------------------------------------------------------
# Batched BUCKETED engine (ISSUE 10): the sort-free phase-0 sweep over
# cross-graph-padded plans, phases >= 1 fused at the serving-coarse
# class.  Same trust properties as the fused engine, plus the plan
# packing/geometry contracts.


@pytest.fixture(scope="module")
def bucketed_result(jobs):
    """One warm batched-bucketed run shared by read-only assertions."""
    louvain_many(jobs, engine="bucketed")  # eat compiles for the spies
    return louvain_many(jobs, engine="bucketed")


def test_bucketed_rejects_unknown_engine(jobs):
    with pytest.raises(ValueError, match="engine"):
        louvain_many(jobs, engine="sorted")


def test_bucketed_bit_identical_to_b1(jobs, bucketed_result):
    """THE serving contract, bucketed edition: every tenant of a B=4
    bucketed batch equals its own B=1 bucketed run bit-for-bit, with
    the batch mixing convergence lengths (masked exit, not split)."""
    singles = [louvain_many([g], engine="bucketed").results[0]
               for g in jobs]
    phase_counts = {len(r.phases) for r in bucketed_result.results}
    assert len(phase_counts) > 1, \
        "fixture must mix convergence lengths to exercise masking"
    for rb, r1 in zip(bucketed_result.results, singles):
        assert r1.modularity == rb.modularity
        assert np.array_equal(r1.communities, rb.communities)
        assert r1.total_iterations == rb.total_iterations
        assert len(r1.phases) == len(rb.phases)


def test_bucketed_matches_pergraph_bucketed_driver(jobs, bucketed_result):
    """Per-tenant LABELS are bit-identical to the per-graph bucketed
    driver (louvain_phases engine='auto' -> bucketed): the batched
    sweep runs the same _run_phase_loop over the same _bucketed_call.
    Q agrees up to the in-loop-f32 vs precise-recompute gap."""
    for g, rb in zip(jobs, bucketed_result.results):
        ref = louvain_phases(g, verbose=False)
        assert np.array_equal(ref.communities, rb.communities)
        assert abs(ref.modularity - rb.modularity) < 5e-5
        assert ref.num_communities == rb.num_communities


def test_bucketed_matches_fused_engine(jobs, bucketed_result):
    """Engine choice never changes results: fused and bucketed batches
    agree bit-for-bit per tenant."""
    fused = louvain_many(jobs, engine="fused")
    for rb, rf in zip(bucketed_result.results, fused.results):
        assert rb.modularity == rf.modularity
        assert np.array_equal(rb.communities, rf.communities)
        assert rb.total_iterations == rf.total_iterations


def test_bucketed_phase_engine_telemetry(bucketed_result, monkeypatch):
    """Phase 0 records the bucketed engine, coarse phases the device
    re-binned bucketed loop (ISSUE 19 — the serving class is
    rebin-eligible, so no coarse phase falls back to fused), and the
    one-notch serving-coarse shrink is reported.  Pinning
    CUVITE_DEVICE_REBIN=0 restores the fused downgrade."""
    eng = bucketed_result.phase_engines
    assert eng[0] == "bucketed"
    assert all(e == "rebinned" for e in eng[1:]) and len(eng) >= 2
    assert bucketed_result.coarse_class == (1024, 4096)
    fused = louvain_many([generate_rmat(8, edge_factor=8, seed=1)])
    assert all(e == "fused" for e in fused.phase_engines)
    assert fused.coarse_class is None
    monkeypatch.setenv("CUVITE_DEVICE_REBIN", "0")
    gs = [generate_rmat(8, edge_factor=8, seed=s) for s in (1, 2)]
    off = louvain_many(gs, engine="bucketed")
    assert off.phase_engines[0] == "bucketed"
    assert all(e == "fused" for e in off.phase_engines[1:])


def test_batch_bucket_plans_geometry(jobs):
    """Cross-graph padding: kept widths = union over the batch, row
    counts = pow2 batch max, [B, rows, width] stacking, absent rows
    flag-masked with the verts == nv_pad sentinel."""
    from cuvite_tpu.core.batch import (
        batch_bucket_plans,
        batch_slabs,
        bucket_shape_for,
    )

    batch = batch_slabs(jobs)
    plan = batch_bucket_plans(batch)
    nv = batch.nv_pad
    # The slab-derived geometry equals the degree-derived one (the
    # shape-pinning path must agree with the packing path).
    assert plan.shape == bucket_shape_for(jobs)
    assert list(plan.shape.widths) == sorted(plan.shape.widths)
    for (verts, dmat, wmat), width, rows in zip(
            plan.buckets, plan.shape.widths, plan.shape.rows):
        assert rows & (rows - 1) == 0, "row counts must be pow2"
        assert verts.shape == (batch.b_pad, rows)
        assert dmat.shape == wmat.shape == (batch.b_pad, rows, width)
        assert wmat.dtype == np.float32  # stable-compile-key contract
        # Per-row padding tails are pure sentinel rows.
        for i in range(batch.b_pad):
            pad_rows = verts[i] >= nv
            assert (wmat[i][pad_rows] == 0).all()
    assert plan.perm.shape == (batch.b_pad, nv)
    assert plan.self_loop.shape == (batch.b_pad, nv)


def test_bucketed_pad_rows_carry_empty_plans(jobs):
    """A 3-job batch pads to the 4-rung: the pad row's plan is pure
    sentinel (it traces, costs two masked sweeps, and leaks no NaN into
    real tenants, which stay bit-identical to their solo runs)."""
    from cuvite_tpu.core.batch import batch_bucket_plans, batch_slabs

    batch = batch_slabs(jobs[:3])
    assert batch.b_pad == 4 and not batch.row_valid[3]
    plan = batch_bucket_plans(batch)
    for verts, dmat, wmat in plan.buckets:
        assert (verts[3] == batch.nv_pad).all()
        assert (wmat[3] == 0).all()
    hs, _hd, hw = plan.heavy
    assert (hs[3] == batch.nv_pad).all() and (hw[3] == 0).all()
    assert (plan.self_loop[3] == 0).all()

    br = louvain_many(jobs[:3], engine="bucketed")
    for g, rb in zip(jobs[:3], br.results):
        assert np.isfinite(rb.modularity)
        assert all(np.isfinite(row.q) for pc in rb.convergence
                   for row in pc.rows)
        solo = louvain_many([g], engine="bucketed").results[0]
        assert solo.modularity == rb.modularity
        assert np.array_equal(solo.communities, rb.communities)


def test_bucket_shape_pin_and_refusal(jobs):
    """A pinned geometry must cover the batch: pinning the job-set
    union works (and keeps results bit-identical); a too-small shape
    refuses loudly instead of truncating plans."""
    from cuvite_tpu.core.batch import (
        BucketShape,
        batch_bucket_plans,
        batch_slabs,
        bucket_shape_for,
    )

    shape = bucket_shape_for(jobs)
    br = louvain_many(jobs, engine="bucketed", bucket_shape=shape)
    solo = louvain_many([jobs[0]], engine="bucketed").results[0]
    assert solo.modularity == br.results[0].modularity
    assert np.array_equal(solo.communities, br.results[0].communities)
    tiny = BucketShape(widths=(8,), rows=(1,), heavy_pad=8)
    with pytest.raises(ValueError, match="does not fit"):
        batch_bucket_plans(batch_slabs(jobs), shape=tiny)


def test_bucketed_zero_fresh_compiles_on_second_batch(jobs,
                                                      bucketed_result):
    """One compile per (class, B, engine): a second bucketed batch of
    DIFFERENT graphs at the same B with the job-set-union geometry
    pinned (the bench's discipline) traces nothing new — including the
    serving-coarse fused phases."""
    from cuvite_tpu.core.batch import bucket_shape_for

    fresh = [generate_rmat(8, edge_factor=8, seed=s) for s in (11, 12)]
    fresh += [synthesize_graph(2048, seed=many_seed(7, k)) for k in (2, 3)]
    shape = bucket_shape_for(list(jobs) + fresh)
    louvain_many(jobs, engine="bucketed", bucket_shape=shape)  # warm pin
    with CompileWatcher() as watch:
        br = louvain_many(fresh, engine="bucketed", bucket_shape=shape)
    assert watch.compiles == [], \
        f"second (class, B, engine) batch recompiled: {watch.compiles}"
    assert len(br.results) == 4


def test_one_device_sync_per_phase_bucketed(jobs, bucketed_result,
                                            monkeypatch):
    """The bucketed batched path keeps the sync discipline: one
    driver._phase_sync per phase (bucketed phase 0 included) plus
    exactly one final label gather."""
    orig_get = jax.device_get
    gets = []

    def spy(x):
        gets.append(x)
        return orig_get(x)

    monkeypatch.setattr(jax, "device_get", spy)
    br = louvain_many(jobs, engine="bucketed")
    assert len(gets) == br.n_phases + 1, \
        f"{len(gets)} device_get calls for {br.n_phases} batch phases " \
        "(want one per phase + the final label gather)"


def test_bucketed_sharding_never_changes_results(jobs, bucketed_result):
    """The batch-axis mesh split changes which device runs which rows,
    never what a bucketed row computes (the shared meshcheck M002
    helper — one implementation across test files and the audit)."""
    from cuvite_tpu.analysis.meshcheck import assert_mesh_neutral

    def run(mesh):
        br = bucketed_result if mesh == "auto" \
            else louvain_many(jobs, engine="bucketed", mesh=mesh)
        return [(r.communities, r.modularity) for r in br.results]

    assert_mesh_neutral(run, ["auto", None], entry="batched_bucketed")
