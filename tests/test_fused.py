"""Fused on-device multi-phase driver vs the per-phase host driver.

The two execution strategies must produce identical clusterings: the fused
program's relabel-only coarsening is an order-preserving relabeling of the
host driver's dense renumber + aggregate, and every id comparison the
algorithm makes is order-invariant.
"""

import numpy as np
import pytest

from cuvite_tpu.evaluate.modularity import modularity as mod_oracle
from cuvite_tpu.io.generate import generate_rgg, generate_rmat
from cuvite_tpu.louvain.driver import louvain_phases


def test_fused_karate_identical(karate):
    rb = louvain_phases(karate, engine="bucketed")
    rf = louvain_phases(karate, engine="fused")
    assert rf.modularity == pytest.approx(rb.modularity, abs=1e-6)
    assert np.array_equal(rf.communities, rb.communities)
    # Per-phase history survives the fused run.
    assert [p.iterations for p in rf.phases] == \
        [p.iterations for p in rb.phases]
    assert [p.modularity for p in rf.phases] == pytest.approx(
        [p.modularity for p in rb.phases], abs=1e-6)
    # nc trajectory: phase p+1's vertex count = phase p's community count.
    assert [p.num_vertices for p in rf.phases] == \
        [p.num_vertices for p in rb.phases]


def test_fused_two_cliques(two_cliques):
    rf = louvain_phases(two_cliques, engine="fused")
    assert rf.num_communities == 2
    # Q = 2*(10/21 - (21/42)^2) = 0.452381 for two K5s + one bridge edge.
    assert rf.modularity == pytest.approx(0.452381, abs=1e-4)


@pytest.mark.parametrize("maker", [
    lambda: generate_rmat(10, edge_factor=8, seed=4),
    lambda: generate_rgg(1024, seed=1),
])
def test_fused_matches_host_driver(maker):
    g = maker()
    rb = louvain_phases(g, engine="bucketed")
    rf = louvain_phases(g, engine="fused")
    assert rf.modularity == pytest.approx(rb.modularity, abs=1e-5)
    assert np.array_equal(rf.communities, rb.communities)
    assert rf.total_iterations == rb.total_iterations


def test_fused_one_phase(karate):
    rb = louvain_phases(karate, engine="bucketed", one_phase=True)
    rf = louvain_phases(karate, engine="fused", one_phase=True)
    assert rf.modularity == pytest.approx(rb.modularity, abs=1e-6)
    assert len(rf.phases) == 1


def test_fused_threshold_cycling(karate):
    rb = louvain_phases(karate, engine="bucketed", threshold_cycling=True)
    rf = louvain_phases(karate, engine="fused", threshold_cycling=True)
    assert rf.modularity == pytest.approx(rb.modularity, abs=1e-6)
    assert np.array_equal(rf.communities, rb.communities)


def test_fused_modularity_oracle(karate):
    rf = louvain_phases(karate, engine="fused")
    q = mod_oracle(karate, rf.communities)
    assert q == pytest.approx(rf.modularity, abs=1e-4)


def test_fused_falls_back_for_variants(karate):
    """ET / coloring / SPMD requests silently use the per-phase driver."""
    r = louvain_phases(karate, engine="fused", et_mode=1)
    assert r.modularity > 0.38
    r8 = louvain_phases(karate, engine="fused", nshards=8)
    r1 = louvain_phases(karate, engine="fused")
    assert np.array_equal(r8.communities, r1.communities)


def test_fused_multilevel_shrink(monkeypatch):
    """Above FUSED_SHRINK_EDGES the fused driver compacts the graph between
    device calls: later phases must report (and traverse) the SHRUNKEN
    edge counts, and the result must match both the single-call fused run
    and the bucketed engine."""
    from cuvite_tpu.io.generate import generate_rgg
    from cuvite_tpu.louvain import driver

    g = generate_rgg(1024, seed=1)
    monkeypatch.setattr(driver, "FUSED_SHRINK_EDGES", 64)
    rf = louvain_phases(g, engine="fused")
    monkeypatch.setattr(driver, "FUSED_SHRINK_EDGES", 1 << 20)
    r1 = louvain_phases(g, engine="fused")
    rb = louvain_phases(g, engine="bucketed")
    assert rf.modularity == pytest.approx(rb.modularity, abs=1e-5)
    assert np.array_equal(rf.communities, rb.communities)
    assert np.array_equal(rf.communities, r1.communities)
    # The whole point: phase p runs on the COARSENED slab, not the original.
    ne_hist = [p.num_edges for p in rf.phases]
    assert len(ne_hist) >= 2 and ne_hist[1] < ne_hist[0]
    # Single-call fused reports the full slab every phase.
    assert all(p.num_edges == g.num_edges for p in r1.phases)


def test_fused_multilevel_cycling_safety_net(monkeypatch):
    """FUSED_SHRINK_EDGES=1 makes EVERY call an intermediate (cycling=False)
    one-phase call, so convergence is always detected on an intermediate
    call — the safety-net 1e-6 pass must still run (via the forced final
    cycling call) to match the bucketed cycling schedule."""
    from cuvite_tpu.io.generate import generate_rgg
    from cuvite_tpu.louvain import driver

    g = generate_rgg(1024, seed=1)
    monkeypatch.setattr(driver, "FUSED_SHRINK_EDGES", 1)
    rf = louvain_phases(g, engine="fused", threshold_cycling=True)
    rb = louvain_phases(g, engine="bucketed", threshold_cycling=True)
    assert rf.modularity == pytest.approx(rb.modularity, abs=1e-5)
    assert np.array_equal(rf.communities, rb.communities)
