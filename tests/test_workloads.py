"""Workload subsystem tests: converter round-trips (SNAP / MTX / METIS,
32- and 64-bit), synthesizer determinism + the golden-envelope gate, the
registry's offline fallback, and the bench harness's compile guard
(which must ABORT, emitting nothing, when a timed run recompiles).
"""

import gzip
import json
import os
import time

import numpy as np
import pytest

from cuvite_tpu.core.graph import Graph
from cuvite_tpu.core.types import default_policy, wide_policy
from cuvite_tpu.io.vite import read_vite, write_vite
from cuvite_tpu.workloads.convert import convert, edges_to_vite
from cuvite_tpu.workloads.synth import synthesize

# A small weighted graph with GAPS in the id space (relabel exercised)
# and no duplicate edges, so Graph.from_edges is a bit-exact oracle for
# the converter's canonical (row-sorted) output.
EDGES = [(1, 4, 0.5), (1, 7, 2.0), (4, 7, 1.5), (7, 13, 1.0),
         (13, 22, 0.25), (4, 22, 3.0), (22, 31, 1.25), (1, 31, 0.75)]
IDS = sorted({v for e in EDGES for v in e[:2]})
REMAP = {v: i for i, v in enumerate(IDS)}


def expected_graph(policy, weights=True):
    src = np.array([REMAP[u] for u, v, w in EDGES])
    dst = np.array([REMAP[v] for u, v, w in EDGES])
    w = np.array([w for u, v, w in EDGES]) if weights else None
    return Graph.from_edges(len(IDS), src, dst, weights=w, policy=policy)


def assert_csr_equal(got: Graph, exp: Graph):
    assert np.array_equal(got.offsets, exp.offsets)
    assert np.array_equal(got.tails, exp.tails)
    assert np.array_equal(got.weights, exp.weights)


@pytest.mark.parametrize("bits64", [False, True], ids=["32bit", "64bit"])
def test_snap_roundtrip_bit_equality(tmp_path, bits64):
    path = tmp_path / "g.txt"
    lines = ["# SNAP-style comment"]
    lines += [f"{u}\t{v}\t{w}" for u, v, w in EDGES]
    path.write_text("\n".join(lines) + "\n")
    out = str(tmp_path / "g.vite")
    stats = convert(str(path), out, fmt="snap", bits64=bits64)
    assert stats.relabeled and stats.num_vertices == len(IDS)
    assert stats.num_edges == 2 * len(EDGES)
    policy = wide_policy() if bits64 else default_policy()
    g = read_vite(out, bits64=bits64)
    assert_csr_equal(g, expected_graph(policy))
    # write_vite of the read-back graph reproduces the file byte-for-byte
    # (converter output is io/vite.py-compatible, not merely readable).
    out2 = str(tmp_path / "g2.vite")
    write_vite(out2, g, bits64=bits64)
    assert open(out, "rb").read() == open(out2, "rb").read()


def test_snap_gz_output_is_byte_identical(tmp_path):
    plain = tmp_path / "g.txt"
    plain.write_text("\n".join(f"{u} {v} {w}" for u, v, w in EDGES) + "\n")
    gzp = tmp_path / "g.txt.gz"
    with gzip.open(gzp, "wb") as f:
        f.write(plain.read_bytes())
    convert(str(plain), str(tmp_path / "a.vite"), fmt="snap")
    convert(str(gzp), str(tmp_path / "b.vite"), fmt="snap")
    assert (tmp_path / "a.vite").read_bytes() \
        == (tmp_path / "b.vite").read_bytes()


@pytest.mark.parametrize("bits64", [False, True], ids=["32bit", "64bit"])
def test_mtx_symmetric_roundtrip(tmp_path, bits64):
    # 1-based dense ids, lower-triangle storage, real field.
    n = len(IDS)
    path = tmp_path / "g.mtx"
    lines = ["%%MatrixMarket matrix coordinate real symmetric",
             "% comment", f"{n} {n} {len(EDGES)}"]
    for u, v, w in EDGES:
        i, j = REMAP[u] + 1, REMAP[v] + 1
        lines.append(f"{max(i, j)} {min(i, j)} {w}")
    path.write_text("\n".join(lines) + "\n")
    out = str(tmp_path / "g.vite")
    stats = convert(str(path), out, fmt="mtx", bits64=bits64)
    assert not stats.relabeled and stats.symmetrized
    policy = wide_policy() if bits64 else default_policy()
    assert_csr_equal(read_vite(out, bits64=bits64), expected_graph(policy))


def test_mtx_general_not_symmetrized(tmp_path):
    # 'general' adjacency already lists both directions: converting must
    # NOT double it.
    n = len(IDS)
    both = [(REMAP[u], REMAP[v], w) for u, v, w in EDGES]
    both += [(v, u, w) for u, v, w in both]
    path = tmp_path / "g.mtx"
    lines = ["%%MatrixMarket matrix coordinate real general",
             f"{n} {n} {len(both)}"]
    lines += [f"{i + 1} {j + 1} {w}" for i, j, w in both]
    path.write_text("\n".join(lines) + "\n")
    out = str(tmp_path / "g.vite")
    stats = convert(str(path), out, fmt="mtx")
    assert not stats.symmetrized and stats.num_edges == len(both)
    assert_csr_equal(read_vite(out, bits64=False),
                     expected_graph(default_policy()))


@pytest.mark.parametrize("bits64", [False, True], ids=["32bit", "64bit"])
def test_metis_roundtrip_with_edge_weights(tmp_path, bits64):
    # METIS fmt=001 (edge weights), 1-based, both directions listed,
    # one isolated vertex appended (blank adjacency line).
    n = len(IDS)
    adj = [[] for _ in range(n + 1)]
    for u, v, w in EDGES:
        adj[REMAP[u]].append((REMAP[v] + 1, w))
        adj[REMAP[v]].append((REMAP[u] + 1, w))
    lines = ["% comment", f"{n + 1} {len(EDGES)} 001"]
    for nbrs in adj:
        lines.append(" ".join(f"{t} {w:g}" for t, w in nbrs))
    (tmp_path / "g.graph").write_text("\n".join(lines) + "\n")
    out = str(tmp_path / "g.vite")
    stats = convert(str(tmp_path / "g.graph"), out, bits64=bits64)
    assert stats.fmt == "metis" and not stats.symmetrized
    assert stats.num_vertices == n + 1  # the isolated vertex survives
    policy = wide_policy() if bits64 else default_policy()
    g = read_vite(out, bits64=bits64)
    exp = expected_graph(policy)
    assert np.array_equal(g.offsets[: n + 1], exp.offsets)
    assert int(g.offsets[n + 1]) == int(exp.offsets[n])  # degree-0 tail
    assert np.array_equal(g.tails, exp.tails)
    assert np.array_equal(g.weights, exp.weights)


def test_metis_unweighted(tmp_path):
    n = len(IDS)
    adj = [[] for _ in range(n)]
    for u, v, _ in EDGES:
        adj[REMAP[u]].append(REMAP[v] + 1)
        adj[REMAP[v]].append(REMAP[u] + 1)
    lines = [f"{n} {len(EDGES)}"]
    lines += [" ".join(str(t) for t in nbrs) for nbrs in adj]
    (tmp_path / "g.metis").write_text("\n".join(lines) + "\n")
    out = str(tmp_path / "g.vite")
    convert(str(tmp_path / "g.metis"), out)
    assert_csr_equal(read_vite(out, bits64=False),
                     expected_graph(default_policy(), weights=False))


def test_metis_parse_spans_text_blocks(tmp_path):
    """A METIS file larger than one reader block must parse identically:
    the block-final newline is a boundary artifact, NOT an isolated
    vertex's blank adjacency line (regression: block-size-dependent
    'more adjacency lines than nv' / silently shifted adjacency)."""
    from cuvite_tpu.workloads.convert import metis_edge_chunks

    n = len(IDS)
    adj = [[] for _ in range(n)]
    for u, v, _ in EDGES:
        adj[REMAP[u]].append(REMAP[v] + 1)
        adj[REMAP[v]].append(REMAP[u] + 1)
    lines = [f"{n} {len(EDGES)}"]
    lines += [" ".join(str(t) for t in nbrs) for nbrs in adj]
    path = tmp_path / "g.graph"
    path.write_text("\n".join(lines) + "\n")

    def collect(block_bytes):
        chunks = list(metis_edge_chunks(str(path), block_bytes=block_bytes))
        s = np.concatenate([c[0] for c in chunks])
        d = np.concatenate([c[1] for c in chunks])
        return s, d

    s_big, d_big = collect(8 << 20)
    s_tiny, d_tiny = collect(4)  # every line its own block
    assert np.array_equal(s_big, s_tiny)
    assert np.array_equal(d_big, d_tiny)


def test_chunking_does_not_change_output(tmp_path):
    """The same edge stream through 1-edge chunks and one big chunk must
    produce byte-identical files (the canonicalization pass's job)."""
    src = np.array([REMAP[u] for u, v, w in EDGES])
    dst = np.array([REMAP[v] for u, v, w in EDGES])
    w = np.array([w for u, v, w in EDGES])
    one = [(src, dst, w)]
    tiny = [(src[i:i + 1], dst[i:i + 1], w[i:i + 1])
            for i in np.random.default_rng(0).permutation(len(src))]
    a, b = str(tmp_path / "a.vite"), str(tmp_path / "b.vite")
    edges_to_vite(iter(one), a, num_vertices=len(IDS), relabel="none")
    edges_to_vite(iter(tiny), b, num_vertices=len(IDS), relabel="none",
                  chunk_edges=2)
    assert open(a, "rb").read() == open(b, "rb").read()


# ---------------------------------------------------------------------------
# Synthesizer + golden envelope (the tier-1 verify-golden run)

SYNTH_EDGES = 40_000
SYNTH_SEED = 7


@pytest.fixture(scope="module")
def synth_workload(tmp_path_factory):
    d = tmp_path_factory.mktemp("synth")
    out = str(d / "pl.vite")
    payload = synthesize(out, edges=SYNTH_EDGES, seed=SYNTH_SEED)
    return out, payload


def test_synth_is_deterministic(tmp_path, synth_workload):
    _, payload = synth_workload
    p2 = synthesize(str(tmp_path / "pl2.vite"), edges=SYNTH_EDGES,
                    seed=SYNTH_SEED)
    assert p2["sha256"] == payload["sha256"]
    assert p2["result"]["num_edges"] == payload["result"]["num_edges"]
    # A different seed must actually change the graph.
    p3 = synthesize(str(tmp_path / "pl3.vite"), edges=SYNTH_EDGES,
                    seed=SYNTH_SEED + 1)
    assert p3["sha256"] != payload["sha256"]


def test_synth_provenance_and_truth(synth_workload):
    out, payload = synth_workload
    assert payload["source"] == "synthesized"
    assert os.path.exists(out + ".provenance.json")
    assert os.path.exists(payload["truth_path"])
    ne = payload["result"]["num_edges"]
    assert 0.9 * SYNTH_EDGES <= ne <= SYNTH_EDGES  # self-draws dropped


def test_synth_golden_envelope_verify(synth_workload):
    """End-to-end golden gate on the synthesized power-law graph: the
    checked-in envelope (workloads/golden.json, powerlaw-test/default)
    must admit a fresh clustering run, F-score included."""
    from cuvite_tpu.louvain.driver import louvain_phases
    from cuvite_tpu.workloads.golden import measure_run, verify

    out, payload = synth_workload
    g = read_vite(out, bits64=False)
    res = louvain_phases(g, verbose=False)
    measured = measure_run(res.communities, res,
                           truth_path=payload["truth_path"],
                           provenance="synthesized")
    ok, problems = verify("powerlaw-test", "default", measured)
    assert ok, problems
    assert measured["f_score"] > 0.85  # planted structure is recovered


def test_golden_envelope_catches_regression(tmp_path, synth_workload):
    from cuvite_tpu.workloads.golden import (
        envelope_from_measurement, check_envelope,
    )

    measured = {"modularity": 0.69, "phases": 2, "communities": 23,
                "f_score": 0.92}
    entry = envelope_from_measurement(measured)
    assert check_envelope(entry, measured) == []
    worse = dict(measured, modularity=0.60)
    assert any("Q=" in p for p in check_envelope(entry, worse))
    split = dict(measured, communities=230)
    assert any("communities" in p for p in check_envelope(entry, split))
    bad_f = dict(measured, f_score=0.5)
    assert any("f_score" in p for p in check_envelope(entry, bad_f))
    # A better-than-golden F-score never fails (one-sided).
    better = dict(measured, f_score=0.99)
    assert not any("f_score" in p for p in check_envelope(entry, better))


def test_verify_golden_missing_entry_fails(synth_workload, tmp_path):
    from cuvite_tpu.workloads.golden import verify

    measured = {"modularity": 0.5, "phases": 2, "communities": 10}
    ok, problems = verify("no-such-dataset", "default", measured,
                          path=str(tmp_path / "empty.json"))
    assert not ok and "no golden entry" in problems[0]


def test_workloads_cli_synth_convert_verify(tmp_path):
    """The CLI wiring end-to-end, in-process: synth -> verify-golden
    --update-golden -> verify-golden (pass)."""
    from cuvite_tpu.workloads.__main__ import main

    out = str(tmp_path / "cli.vite")
    golden = str(tmp_path / "golden.json")
    assert main(["synth", "--edges", "20000", "--seed", "11",
                 "--out", out]) == 0
    assert main(["verify-golden", "--dataset", "cli-test", "--file", out,
                 "--golden", golden, "--update-golden"]) == 0
    assert main(["verify-golden", "--dataset", "cli-test", "--file", out,
                 "--golden", golden]) == 0
    data = json.load(open(golden))
    assert "cli-test/default" in data["entries"]


# ---------------------------------------------------------------------------
# Registry: offline fallback (no network on this rig)


def test_registry_offline_fallback(tmp_path, monkeypatch):
    import cuvite_tpu.workloads.registry as reg

    fake = reg.Dataset(
        name="fake-tiny", url="http://127.0.0.1:9/nothing.txt.gz",
        fmt="snap", num_vertices=1000, num_edges_undirected=10_000,
        synth_edges=20_000)
    monkeypatch.setitem(reg.DATASETS, "fake-tiny", fake)
    payload = reg.fetch("fake-tiny", str(tmp_path), timeout=2)
    assert payload["source"] == "offline-synthesized"
    assert payload["stands_in_for"] == "fake-tiny"
    out = str(tmp_path / "fake-tiny.vite")
    g = read_vite(out, bits64=False)
    assert g.num_edges == payload["result"]["num_edges"]
    prov = reg.load_provenance(out)
    assert prov["source"] == "offline-synthesized"
    assert "fetch_error" in prov


def test_registry_no_offline_fallback_raises(tmp_path, monkeypatch):
    import cuvite_tpu.workloads.registry as reg

    fake = reg.Dataset(
        name="fake-tiny2", url="http://127.0.0.1:9/nothing.txt.gz",
        fmt="snap", num_vertices=10, num_edges_undirected=10)
    monkeypatch.setitem(reg.DATASETS, "fake-tiny2", fake)
    with pytest.raises(Exception):
        reg.fetch("fake-tiny2", str(tmp_path), offline_fallback=False,
                  timeout=2)


# ---------------------------------------------------------------------------
# Bench harness: record schema + THE compile-guard abort


def test_bench_record_schema_and_guard_pass():
    from cuvite_tpu.io.generate import generate_rmat
    from cuvite_tpu.workloads.bench import run_bench, validate_record

    # edge_factor=10 is used NOWHERE else in the suite: the cold-run
    # asserts below (compile_events non-empty, guard checked) need this
    # graph's compiled programs to be absent from the in-process jit
    # cache, and a shared shape lets an earlier test warm them (the
    # bucketed plan geometry collapses to the same pow2 ladder for
    # same-(scale, edge_factor) rmats — same idiom as test_obs.py's
    # shape-unique graph).
    g = generate_rmat(9, edge_factor=10, seed=3)
    # t_start pinned HERE: the default anchors at bench-module import,
    # and this test runs near the end of a long tier-1 — the suite's
    # elapsed wall must not eat the budget (the budget path has its own
    # assertions; this test targets the guarded steady-state path).
    rec = run_bench(g, repeats=2, budget_s=600, platform="cpu",
                    graph_label="rmat9", scale=9,
                    t_start=time.perf_counter())
    assert validate_record(rec) == []
    assert rec["compile_guard"] == {"checked": True, "new_compiles": 0}
    assert rec["runs"] == 2 and len(rec["teps_runs"]) == 2
    assert rec["platform"] == "cpu" and rec["value"] > 0
    # Schema v2: per-stage breakdown of the recorded run (ISSUE 3;
    # coalesce_s — the device relabel+coalesce slice — since ISSUE 8).
    for k in ("coarsen_s", "coalesce_s", "upload_s", "iterate_s"):
        assert k in rec["stages"] and rec["stages"][k] >= 0
    assert rec["stages"]["iterate_s"] > 0  # the phase loops always run
    # Schema v5 (ISSUE 20: optional `mix` block; v4 added the ISSUE-6
    # self-describing telemetry fields asserted below).
    assert rec["schema"] == 5
    assert rec["convergence_summary"], "recorded run must carry digests"
    assert all(d["iterations"] >= 1 for d in rec["convergence_summary"])
    # The warm-up compiles under the recorder: cold cost is on record.
    assert rec["compile_events"], "cold run must record compile events"
    assert all("module" in e for e in rec["compile_events"])
    assert isinstance(rec["hbm_peak_by_buffer"], dict)


def test_bench_aborts_on_injected_recompile():
    """Inject a recompile into the first timed run (the warm-up sees a
    DIFFERENT graph shape) and assert the harness refuses to produce a
    record — the acceptance gate for VERDICT r5 weak #6."""
    from cuvite_tpu.io.generate import generate_rmat
    from cuvite_tpu.workloads.bench import (
        BenchCompileGuardError, run_bench,
    )

    # Suite-unique edge_factor=10 shapes (see the schema test above):
    # the injected SECOND shape must be guaranteed-cold in the
    # in-process jit cache, or the guard legitimately sees zero fresh
    # compiles and this test misfires on suite order.
    shapes = iter([generate_rmat(9, edge_factor=10, seed=3),
                   generate_rmat(8, edge_factor=10, seed=4)])
    with pytest.raises(BenchCompileGuardError) as exc:
        run_bench(lambda: next(shapes), repeats=1, budget_s=600,
                  platform="cpu", graph_label="sabotage",
                  t_start=time.perf_counter())
    assert exc.value.compile_log  # the abort carries the compile list


def test_bench_main_emits_no_json_on_guard_trip(monkeypatch, capsys):
    import cuvite_tpu.workloads.bench as wb

    def boom(*a, **k):
        raise wb.BenchCompileGuardError(["Compiling sabotage"])

    monkeypatch.setattr(wb, "run_bench", boom)
    monkeypatch.setattr(wb, "_init_backend", lambda: "cpu")
    rc = wb.main(["--scale", "6", "--repeats", "1"])
    out = capsys.readouterr().out
    assert rc == 3
    assert not out.strip(), f"guard trip must emit NO json, got: {out!r}"


def test_validate_record_rejects_unchecked_nonzero_compiles():
    from cuvite_tpu.workloads.bench import validate_record

    rec = {"metric": "louvain_teps_per_chip", "value": 1.0,
           "unit": "traversed_edges/sec", "vs_baseline": 0.1,
           "platform": "cpu", "graph": "x", "modularity": 0.1,
           "phases": 1, "compile_guard": {"checked": True,
                                          "new_compiles": 2},
           "stages": {"coarsen_s": 0.0, "coalesce_s": 0.0,
                      "rebin_s": 0.0, "upload_s": 0.0, "iterate_s": 1.0},
           "engine": "bucketed", "schema": 4,
           "convergence_summary": [{"phase": 0, "iterations": 3}],
           "compile_events": [{"module": "jit(f)", "dur_s": 0.5}],
           "hbm_peak_by_buffer": {"slab": 1024}}
    assert any("new_compiles" in p for p in validate_record(rec))
    # Schema v2: a record without the stage breakdown (or with a bogus
    # one) is rejected.
    old = dict(rec, compile_guard={"checked": True, "new_compiles": 0})
    del old["stages"]
    assert any("stages" in p for p in validate_record(old))
    bad = dict(rec, compile_guard={"checked": True, "new_compiles": 0},
               stages={"coarsen_s": -1.0, "upload_s": 0.0,
                       "iterate_s": 1.0})
    assert any("coarsen_s" in p for p in validate_record(bad))
    # ISSUE 8: coalesce_s is a required stage key; the optional
    # coalesce_kernel coverage must be a fraction when present.
    noco = dict(rec, compile_guard={"checked": True, "new_compiles": 0},
                stages={"coarsen_s": 0.0, "upload_s": 0.0,
                        "iterate_s": 1.0})
    assert any("coalesce_s" in p for p in validate_record(noco))
    # Schema v3: an engine-less record is rejected, and a pallas record
    # must carry the kernel-coverage fields (honest TEPS labeling).
    ok = dict(rec, compile_guard={"checked": True, "new_compiles": 0})
    noeng = dict(ok)
    del noeng["engine"]
    assert any("engine" in p for p in validate_record(noeng))
    pal = dict(ok, engine="pallas")
    assert any("pallas_coverage" in p for p in validate_record(pal))
    assert any("pallas_width_hits" in p for p in validate_record(pal))
    pal_ok = dict(pal, pallas_coverage=0.93,
                  pallas_width_hits={"8": 1000, "32": 500})
    assert validate_record(pal_ok) == []
    pal_bad = dict(pal_ok, pallas_coverage=1.7)
    assert any("pallas_coverage" in p for p in validate_record(pal_bad))
    ck_bad = dict(ok, coalesce_kernel=2.0)
    assert any("coalesce_kernel" in p for p in validate_record(ck_bad))
    assert validate_record(dict(ok, coalesce_kernel=0.0)) == []
    # Schema v4: the telemetry fields are REQUIRED and type-checked; a
    # pre-v4 record (no schema field) is rejected outright.
    v3 = dict(ok)
    del v3["schema"]
    assert any("schema" in p for p in validate_record(v3))
    for key, bad_val in (("convergence_summary", "nope"),
                         ("compile_events", [{"dur_s": 1.0}]),
                         ("hbm_peak_by_buffer", [1, 2])):
        assert any(key in p for p in validate_record(dict(ok, **{key: bad_val}))), key
    # ISSUE 18: the optional exchange block — a two-level record must
    # carry its (dcn, ici) factorization and per-device table/ghost
    # bytes; a flat SPMD record carries only the mode.
    probs = validate_record(dict(ok, exchange={"mode": "twolevel"}))
    for k in ("dcn", "ici", "table_bytes_per_device", "ghost_bytes"):
        assert any(k in p for p in probs), (k, probs)
    assert validate_record(dict(ok, exchange={
        "mode": "twolevel", "dcn": 2, "ici": 4,
        "table_bytes_per_device": 16384, "ghost_bytes": 6144})) == []
    assert validate_record(dict(ok, exchange={"mode": "sparse"})) == []
    assert any("mode" in p for p in validate_record(
        dict(ok, exchange={"mode": "dense"})))
    assert any("dcn" in p for p in validate_record(dict(ok, exchange={
        "mode": "twolevel", "dcn": 0, "ici": 4,
        "table_bytes_per_device": 16384, "ghost_bytes": 6144})))


# ---------------------------------------------------------------------------
# Modularity oracle size gate (VERDICT r5 weak #7)


def test_modularity_gate(karate, monkeypatch):
    from cuvite_tpu.evaluate.modularity import (
        host_oracle_max_edges, modularity, modularity_gated,
    )

    labels = np.zeros(karate.num_vertices, dtype=np.int64)
    q_oracle = modularity(karate, labels)
    q, used = modularity_gated(karate, labels, fallback=-123.0)
    assert used and q == q_oracle
    q, used = modularity_gated(karate, labels, fallback=-123.0,
                               max_edges=0)
    assert not used and q == -123.0
    monkeypatch.setenv("CUVITE_HOST_ORACLE_MAX_EDGES", "1e3")
    assert host_oracle_max_edges() == 1000
    monkeypatch.setenv("CUVITE_HOST_ORACLE_MAX_EDGES", "bogus")
    with pytest.warns(UserWarning):
        assert host_oracle_max_edges() > 0
