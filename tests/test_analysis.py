"""graftlint tests: per-rule positive/negative fixtures, suppression
handling, baseline round-trip, and the repo self-lint gate.

The self-lint gate (test_selflint_no_new_high_findings) is the tier-1
enforcement the subsystem exists for: a PR introducing a new
high-severity hazard anywhere in cuvite_tpu/, tools/, or tests/ fails
the suite, with the checked-in baseline (tools/graftlint_baseline.json)
grandfathering whatever was already there when the rule landed.

All fixtures are tiny inline source STRINGS — never repo files — so a
rule's semantics are pinned independently of the codebase's current
state.  ``rel`` paths on fixtures exercise the directory scoping rules
(R003 device-path modules, R007 tools/, R008 tests/).
"""

import json
import os
import subprocess
import sys

import pytest

from cuvite_tpu.analysis import (
    all_rules,
    apply_baseline,
    load_baseline,
    run_paths,
    run_source,
    write_baseline,
)
from cuvite_tpu.analysis.engine import Finding, gate_failures

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "tools", "graftlint_baseline.json")

SCAN_PATHS = ("cuvite_tpu", "tools", "tests")


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# Per-rule fixtures: (rule id, triggering source, clean source, rel path).
# The clean variant stays as close to the bad one as the rule allows, so
# each pair pins the rule's discriminating feature, not its surface syntax.

RULE_CASES = [
    (
        "R001",
        """
import jax
import numpy as np

@jax.jit
def step(x):
    return _helper(x)

def _helper(x):
    x.block_until_ready()
    v = float(x.sum())
    return np.asarray(v), x.item()
""",
        """
import jax
import numpy as np

@jax.jit
def step(x):
    return x * 2

def _host_report(x):
    # identical host-sync calls, but NOT reachable from any jitted
    # function in this module
    x.block_until_ready()
    v = float(x.sum())
    return np.asarray(v), x.item()
""",
        "cuvite_tpu/fake_r001.py",
    ),
    (
        "R002",
        """
import functools
import jax

@functools.partial(jax.jit, static_argnums=tuple(range(2)))
def f(a, b, x):
    if x > 0:
        return x
    return -x
""",
        """
import functools
import jax

@functools.partial(jax.jit, static_argnums=(0, 1), static_argnames=("m",))
def f(a, b, x, *, m=4):
    if a > 0:          # static: branch is resolved at trace time
        return x * m
    if x is None:      # structural dispatch, not data-dependent
        return b
    return -x
""",
        "cuvite_tpu/fake_r002.py",
    ),
    (
        "R003",
        """
import jax.numpy as jnp
import numpy as np

def device_ids(n):
    pad = jnp.zeros(n, dtype="int64")
    wide = jnp.full(n, 0, dtype=np.int64)
    cast = jnp.arange(n).astype("int64")
    return pad.astype(jnp.float64), wide, cast
""",
        """
import jax.numpy as jnp
import numpy as np

def device_ids(n):
    # np 64-bit HOST arrays are fine (plan building); only jnp device
    # constructions defeat the 32-bit graph mode
    host = np.zeros(n, dtype=np.int64)
    return jnp.asarray(host, dtype=jnp.int32)
""",
        "cuvite_tpu/louvain/fake_r003.py",
    ),
    (
        "R004",
        """
import jax
from cuvite_tpu.comm.multihost import allgather_varlen, gather_global

def resume(path, arr):
    try:
        state = allgather_varlen(arr)
    except ValueError:
        state = None
    if jax.process_index() == 0:
        return gather_global(arr)
    if _load(path):
        return gather_global(arr)
    return state

def _load(path):
    return None
""",
        """
from cuvite_tpu.comm.multihost import allgather_varlen, gather_global, \\
    is_distributed

def resume(dist_ingest, arr):
    if dist_ingest:          # replicated plain value: uniform by contract
        state = allgather_varlen(arr)
    if is_distributed():     # known-uniform predicate
        return gather_global(arr)
    return state
""",
        "cuvite_tpu/fake_r004.py",
    ),
    (
        "R005",
        """
import numpy as np

def freeze(x, out, acc):
    x.flags.writeable = False
    out[:10] = 0
    np.copyto(out, x)
    acc.fill(0)
    acc += 1 if False else 0
""",
        """
import numpy as np

def freeze(x_ref, o_ref):
    # pallas kernel convention: *_ref params are output Refs
    o_ref[...] = x_ref[...]

def local_only(x):
    out = np.empty_like(x)
    out[:10] = 0          # local allocation: ours to mutate
    out.flags.writeable = False
    np.copyto(out, out)
    return out
""",
        "cuvite_tpu/fake_r005.py",
    ),
    (
        "R006",
        """
import jax.numpy as jnp
from jax.ops import segment_sum

def phase_q(e_c, a_c, seg, n):
    mod = jnp.sum(e_c) - segment_sum(a_c, seg, num_segments=n).sum()
    return mod
""",
        """
import jax.numpy as jnp
from cuvite_tpu.ops.exactsum import ds_tree_sum, ds_to_f64

def phase_q(e_c, a_c):
    mod = ds_tree_sum(e_c - a_c ** 2)
    return mod

def stepped_q(e_c, accum_dtype):
    # dtype-policy-aware: the caller chose the accumulation width
    mod = jnp.sum(e_c.astype(accum_dtype))
    return mod
""",
        "cuvite_tpu/louvain/fake_r006.py",
    ),
    (
        "R007",
        """
import subprocess
import sys

def bench(cmd):
    return subprocess.run([sys.executable] + cmd, capture_output=True)
""",
        """
import subprocess
import sys

def bench(cmd):
    return subprocess.run([sys.executable] + cmd, capture_output=True,
                          timeout=7200)
""",
        "tools/fake_r007.py",
    ),
    (
        "R008",
        """
import os

if not os.environ.get("NO_SYSCTL"):   # opt-OUT: fires by default
    with open("/proc/sys/vm/max_map_count", "w") as f:
        f.write("1048576")
""",
        """
import os

if os.environ.get("RAISE_SYSCTL"):    # opt-IN: off by default
    with open("/proc/sys/vm/max_map_count", "w") as f:
        f.write("1048576")
with open("/proc/sys/vm/max_map_count") as f:   # read-only: fine
    cur = int(f.read())
""",
        "tests/fake_r008.py",
    ),
    (
        "R009",
        """
import urllib.request

def fetch(url, dest):
    with urllib.request.urlopen(url, timeout=60) as resp, \\
            open(dest, "wb") as out:
        out.write(resp.read())
    return dest
""",
        """
import hashlib
import urllib.request

def fetch(url, dest, expected):
    h = hashlib.sha256()
    with urllib.request.urlopen(url, timeout=60) as resp, \\
            open(dest, "wb") as out:
        buf = resp.read()
        h.update(buf)
        out.write(buf)
    _verify_checksum(h.hexdigest(), expected, dest)
    return dest

def _verify_checksum(digest, expected, path):
    if expected is not None and digest != expected:
        raise ValueError(path)
""",
        "cuvite_tpu/workloads/registry.py",
    ),
    (
        "R010",
        """
import jax
import numpy as np

def phase_transition(src_d, labels_d, stats):
    host_slab = jax.device_get(src_d)
    lab = np.asarray(labels_d)
    return host_slab, lab
""",
        """
import numpy as np

def build_plan(plan, comm_pad):
    # host plan arrays: attribute access and non-device-suggestive names
    # are out of scope by design (near-zero false positives)
    src_np = np.asarray(plan.src)
    comm = np.asarray(comm_pad)
    final = np.asarray(labels_d)  # graftlint: disable=R010 — the final label gather
    return src_np, comm, final
""",
        "cuvite_tpu/coarsen/fake_r010.py",
    ),
    (
        "R011",
        """
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

def launch(kernel, cT):
    spec = pl.BlockSpec((8, 512), lambda i: (0, i),
                        memory_space=pltpu.VMEM)
    return pl.pallas_call(kernel, grid=(4,), in_specs=[spec],
                          out_specs=spec, out_shape=None)(cT)
""",
        """
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128

def launch(kernel, cT, tile):
    D, N = cT.shape
    # dims derived from the ladder-bound shapes; unit dims are layout
    # plumbing, not a tile-size decision
    mat = pl.BlockSpec((D, tile), lambda i: (0, i),
                       memory_space=pltpu.VMEM)
    vec = pl.BlockSpec((1, tile), lambda i: (0, i),
                       memory_space=pltpu.VMEM)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    return pl.pallas_call(kernel, grid=(N // tile,),
                          in_specs=[smem, mat, vec],
                          out_specs=vec, out_shape=None)(cT)
""",
        "cuvite_tpu/kernels/fake_r011.py",
    ),
    (
        "R012",
        """
import time
import jax
import jax.numpy as jnp

@jax.jit
def step(x):
    return x * 2.0

def bench(x):
    t0 = time.perf_counter()
    y = step(jnp.asarray(x))
    dt = time.perf_counter() - t0  # async dispatch: times the launch
    return y, dt
""",
        """
import time
import jax
import jax.numpy as jnp

@jax.jit
def step(x):
    return x * 2.0

def bench(x, opaque_fn):
    t0 = time.perf_counter()
    y = jax.block_until_ready(step(jnp.asarray(x)))
    dt = time.perf_counter() - t0
    # Opaque callables are out of scope: they may sync internally
    # (louvain_phases does), and flagging them would bury the signal.
    t0 = time.perf_counter()
    opaque_fn()
    dt2 = time.perf_counter() - t0
    return y, dt, dt2
""",
        "tools/fake_r012.py",
    ),
    (
        "R013",
        """
import jax
import jax.numpy as jnp

def coalesce(src, dst, w):
    # full-slab sort outside the sanctioned chokepoint: the round-7 tax
    src_s, dst_s, w_s = jax.lax.sort((src, dst, w), num_keys=2)
    order = jnp.argsort(src, stable=True)
    return src_s, dst_s, w_s, order
""",
        """
import jax
import jax.numpy as jnp

from cuvite_tpu.ops import segment as seg

def coalesce(src, dst, w, nv_pad):
    # routed through the sanctioned fallback chokepoint
    return seg.coalesced_runs(src, dst, w, nv_pad=nv_pad, engine="sort")

def tiny_row_sort(row):
    # a genuinely non-slab sort, justified inline
    return jax.lax.sort((row,), num_keys=1)  # graftlint: disable=R013 — O(D) per-row sort, not a slab

def rebin_degrees(src, real, nv_pad):
    # the ISSUE-19 re-binner idiom: histogram + prefix, NO sort —
    # exactly what this rule's scope exists to keep sort-free
    deg = jax.ops.segment_sum(real.astype(jnp.int32), src,
                              num_segments=nv_pad)
    return deg, jnp.cumsum(deg) - deg
""",
        "cuvite_tpu/coarsen/fake_r013.py",
    ),
    (
        "R014",
        """
import jax

def serve_loop(queue):
    results = []
    while queue:
        job = queue.pop()
        step = jax.jit(lambda s, d, w: s)   # fresh callable: compile per job
        src = jax.device_put(job.src)       # upload per job
        results.append(step(src, job.dst, job.w))
    return results
""",
        """
from cuvite_tpu.louvain.batched import cluster_many

def serve_loop(queue, b_max):
    results = []
    while queue:
        jobs = [queue.pop() for _ in range(min(len(queue), b_max))]
        # one module-scope compiled program, one placement per batch
        br = cluster_many([j.graph for j in jobs])
        results.extend(br.results)
    return results
""",
        "cuvite_tpu/serve/fake_r014.py",
    ),
    (
        "R015",
        """
from cuvite_tpu.louvain.bucketed import BucketPlan

def dispatch(jobs, nv_pad):
    plans = []
    for job in jobs:
        # plan-per-job trap: O(E) gather matrices rebuilt per tenant
        plans.append(BucketPlan.build(job.src, job.dst, job.w,
                                      nv_local=nv_pad, base=0))
    return plans
""",
        """
from cuvite_tpu.core.batch import batch_bucket_plans, batch_slabs
from cuvite_tpu.louvain.bucketed import BucketPlan

def dispatch(jobs, nv_pad):
    # planning at pack time: ONE call covers every row of the batch
    batch = batch_slabs([j.graph for j in jobs])
    return batch_bucket_plans(batch)

def one_off(job, nv_pad):
    # outside any dispatch loop: a single job's plan is fine
    return BucketPlan.build(job.src, job.dst, job.w,
                            nv_local=nv_pad, base=0)

def justified(jobs, nv_pad):
    for job in jobs:
        yield BucketPlan.build(job.src, job.dst, job.w, nv_local=nv_pad, base=0)  # graftlint: disable=R015 — diagnostic path, not dispatch

def coarse_dispatch(batches, nv_pad, geometry):
    from cuvite_tpu.coarsen.rebin import device_rebin_plan

    # the sanctioned in-loop planner (ISSUE 19): coarse phases re-bin
    # ON DEVICE inside the compiled program — not a host plan per job
    for b in batches:
        yield device_rebin_plan(b.src, b.dst, b.w, nv_pad=nv_pad,
                                base=0, geometry=geometry)
""",
        "cuvite_tpu/serve/fake_r015.py",
    ),
    (
        "R016",
        """
import time

def due(queue, linger_s):
    now = time.monotonic()        # untestable-deadline trap
    stamp = time.time()           # ditto (wall time)
    return [j for j in queue if now - j.t_submit >= linger_s], stamp
""",
        """
import time

from cuvite_tpu.serve import clock as serve_clock

def due(queue, linger_s, clock=serve_clock.monotonic):
    # deadlines run on the INJECTED clock; a bare default REFERENCE to
    # time.monotonic is not a call and stays legal
    t0 = time.perf_counter()      # busy timing: allowlisted
    out = [j for j in queue if clock() - j.t_submit >= linger_s]
    busy = time.perf_counter() - t0
    return out, busy

def injected_default(clock=time.monotonic):
    return [clock()]
""",
        "cuvite_tpu/serve/fake_r016.py",
    ),
    (
        "R022",
        """
import threading
from threading import Event, Thread


def start(daemon):
    # direct construction EXITS the sync seam: invisible to every
    # concheck tier-4 schedule
    daemon.lock = threading.Lock()
    daemon.wake = Event()
    t = Thread(target=daemon.run)
    t.start()
    return t
""",
        """
import threading

from cuvite_tpu.serve import sync


def start(daemon):
    # the seam factories: plain threading in production,
    # scheduler-backed twins under concheck
    daemon.lock = sync.Lock()
    daemon.wake = sync.Event()
    t = sync.Thread(target=daemon.run, name="d")
    t.start()
    return t


def annotate(x: threading.RLock) -> None:
    # a bare TYPE reference is not a construction
    pass


def justified():
    return threading.Barrier(2)  # graftlint: disable=R022 — test-harness barrier, never under the scheduler
""",
        "cuvite_tpu/serve/fake_r022.py",
    ),
    (
        "R019",
        """
import threading


class Stats:
    def __init__(self):
        self.lock = threading.RLock()
        self.jobs_done = 0
        self.samples = []

    def record(self, wait):
        with self.lock:
            self.jobs_done += 1
            self.samples.append(wait)

    def racy(self, wait):
        # the PR-11 shape: same fields, no lock — lost updates under the
        # daemon's reader/dispatcher concurrency
        self.jobs_done += 1
        self.samples.append(wait)
""",
        """
import threading


class Stats:
    def __init__(self):
        self.lock = threading.RLock()
        self.jobs_done = 0
        self.samples = []
        self.jobs_done = 0       # ctor re-init: construction, not a race

    def record(self, wait):
        with self.lock:
            self.jobs_done += 1
            self.samples.append(wait)


class SingleThreaded:
    def __init__(self):
        self.count = 0

    def bump(self):
        self.count += 1          # no lock discipline anywhere: unflagged
""",
        "cuvite_tpu/serve/fake_r019.py",
    ),
    (
        "R029",
        """
import jax
import jax.numpy as jnp


def hot_patch(sess, i, weight):
    # direct slab edit outside the apply_delta_slab chokepoint: forks
    # the canonical form the bit-equality tests pin
    sess.w = sess.w.at[i].set(weight)
    sess.src = sess.src.at[i].add(0)
    return sess

_step = jax.jit(lambda s, d, w: (s, d, w), donate_argnums=(2,))
""",
        """
import jax
from cuvite_tpu.stream.delta import apply_delta_slab


def hot_patch(sess, batch, nv_pad, adt):
    # every slab edit routed through the ONE jitted chokepoint
    i_s, i_d, i_w, d_s, d_d = batch.padded(256)
    return apply_delta_slab(sess.src, sess.dst, sess.w,
                            i_s, i_d, i_w, d_s, d_d, sess.ne,
                            nv_pad=nv_pad, accum_dtype=adt)

_step = jax.jit(lambda s, d, w: (s, d, w))


def scratch(mask, idx):
    # a genuinely non-slab update, justified inline
    return mask.at[idx].set(True)  # graftlint: disable=R029 — local scratch mask, never a resident slab
""",
        "cuvite_tpu/stream/fake_r029.py",
    ),
]

RULE_IDS = [c[0] for c in RULE_CASES]


@pytest.mark.parametrize("rule_id,bad,good,rel", RULE_CASES, ids=RULE_IDS)
def test_rule_positive(rule_id, bad, good, rel):
    findings = run_source(bad, rel=rel)
    assert rule_id in rules_of(findings), \
        f"{rule_id} did not fire on its positive fixture: {findings}"


@pytest.mark.parametrize("rule_id,bad,good,rel", RULE_CASES, ids=RULE_IDS)
def test_rule_negative(rule_id, bad, good, rel):
    findings = run_source(good, rel=rel)
    assert rule_id not in rules_of(findings), \
        f"{rule_id} false-positive on its clean fixture: " \
        f"{[f.format() for f in findings if f.rule == rule_id]}"


def test_r014_r015_cover_the_packer_path():
    """ISSUE 20: the per-batch amortization rules extend beyond serve/
    to the PACKER path — pack_*/prepare_*/unpack_* functions in
    louvain/batched.py and core/batch.py hold the same contract (one
    upload, one plan build, zero jit construction per batch, however
    many tenants a merged sub-row batch carries).  Scope stays
    per-function: the phase loops in the same modules legitimately run
    jax calls per iteration."""
    bad = """
import jax

from cuvite_tpu.louvain.bucketed import BucketPlan

def pack_subrow_many(graphs):
    out = []
    for g in graphs:
        buf = jax.device_put(g.src)      # upload per TENANT, not per batch
        plan = BucketPlan.build(g.src, g.dst, g.w, nv_local=4096, base=0)
        out.append((buf, plan))
    return out

def _run_phase_loop(xs):
    # NOT a packer function: in-loop jax here is the phase loop's job
    for x in xs:
        x = jax.device_put(x)
    return xs
"""
    for rel in ("cuvite_tpu/louvain/batched.py",
                "cuvite_tpu/core/batch.py"):
        found = rules_of(run_source(bad, rel=rel))
        assert "R014" in found and "R015" in found, (rel, found)
        # only the packer function's loop fires, not the phase loop's
        lines = [f.line for f in run_source(bad, rel=rel)
                 if f.rule == "R014"]
        assert len(lines) == 1, lines
    # The same source OUTSIDE the packer scope stays silent.
    clean = rules_of(run_source(bad, rel="cuvite_tpu/louvain/fused.py"))
    assert "R014" not in clean and "R015" not in clean


def test_registry_ships_at_least_eight_rules():
    rules = all_rules()
    assert len(rules) >= 8
    assert {r.id for r in rules} >= set(RULE_IDS) | {"R017", "R018"}
    for r in rules:
        assert r.severity in ("high", "medium", "low")
        assert r.title


# ---------------------------------------------------------------------------
# Severity / finding counts on the positive fixtures


def test_positive_fixture_severities_match_registry():
    sev = {r.id: r.severity for r in all_rules()}
    for rule_id, bad, _good, rel in RULE_CASES:
        for f in run_source(bad, rel=rel):
            if f.rule == rule_id:
                assert f.severity == sev[rule_id]


def test_r001_flags_each_sync_call_site():
    bad = RULE_CASES[0][1]
    hits = [f for f in run_source(bad, rel="cuvite_tpu/x.py")
            if f.rule == "R001"]
    # block_until_ready, float, np.asarray, .item
    assert len(hits) == 4


def test_r003_scope_is_device_path_only():
    bad = RULE_CASES[2][1]  # the R003-triggering source
    assert any(f.rule == "R003"
               for f in run_source(bad, rel="cuvite_tpu/ops/x.py"))
    # the SAME source outside louvain/kernels/ops is out of scope
    assert not any(f.rule == "R003"
                   for f in run_source(bad, rel="cuvite_tpu/io/x.py"))


def test_r012_sync_before_dispatch_is_not_evidence():
    # A host-value int() BEFORE the dispatch forces nothing: the window
    # still times only the async launch and must be flagged.
    bad = """
import time
import jax.numpy as jnp

def bench(a, b, nv):
    t0 = time.perf_counter()
    n = int(nv)
    y = jnp.dot(a, b)
    dt = time.perf_counter() - t0
    return y, n, dt
"""
    assert any(f.rule == "R012"
               for f in run_source(bad, rel="tools/x.py"))
    # Same-line wrapping IS evidence: float(jnp.dot(...)) blocks on the
    # result before the window closes.
    good = """
import time
import jax.numpy as jnp

def bench(a, b):
    t0 = time.perf_counter()
    y = float(jnp.dot(a, b))
    dt = time.perf_counter() - t0
    return y, dt
"""
    assert not any(f.rule == "R012"
                   for f in run_source(good, rel="tools/x.py"))
    # A wrapped readback whose argument spans lines still forces the
    # dispatch it encloses (normal 79-char wrapping must not flag).
    wrapped = """
import time
import jax
import jax.numpy as jnp

def bench(a, b):
    t0 = time.perf_counter()
    y = jax.block_until_ready(
        jnp.dot(a, b))
    dt = time.perf_counter() - t0
    return y, dt
"""
    assert not any(f.rule == "R012"
                   for f in run_source(wrapped, rel="tools/x.py"))


R008_GUARD = """
import os

if %s:
    with open("/proc/sys/vm/max_map_count", "w") as f:
        f.write("1048576")
"""


@pytest.mark.parametrize("guard,fires", [
    ("os.environ.get('X')", False),               # opt-in
    ("os.environ.get('X') == '1'", False),        # opt-in, explicit value
    ("os.environ.get('X') is not None", False),   # opt-in
    ("os.environ.get('X', '') != ''", False),     # opt-in
    ("not (os.environ.get('X') is None)", False),  # opt-in, double flip
    ("FLAG and os.environ.get('X')", False),      # conjunction still gates
    ("not os.environ.get('NO_X')", True),         # opt-out
    ("os.environ.get('NO_X') is None", True),     # opt-out, rephrased
    ("os.environ.get('NO_X') == ''", True),       # opt-out, rephrased
    ("os.environ.get('NO_X') != '1'", True),      # opt-out, rephrased
    ("FLAG or os.environ.get('X')", True),        # or-arm bypasses the gate
    ("os.environ.get('X', '1')", True),           # truthy default: not a gate
    ("os.environ.get('X', default='1')", True),   # keyword default, same
])
def test_r008_gate_polarity(guard, fires):
    findings = run_source(R008_GUARD % guard, rel="tests/x.py")
    assert ("R008" in rules_of(findings)) == fires, (guard, findings)


R008_ELSE = """
import os

if %s:
    pass
else:
    with open("/proc/sys/vm/max_map_count", "w") as f:
        f.write("1048576")
"""


@pytest.mark.parametrize("guard,fires", [
    # else of an opt-IN check runs by default when the var is UNSET
    ("os.environ.get('RAISE_X')", True),
    # else of an opt-OUT check runs only when the var IS set: genuine gate
    ("not os.environ.get('NO_X')", False),
    # unprovable polarity must not gate the else branch either
    ("FLAG or os.environ.get('X')", True),
])
def test_r008_else_branch_polarity(guard, fires):
    findings = run_source(R008_ELSE % guard, rel="tests/x.py")
    assert ("R008" in rules_of(findings)) == fires, (guard, findings)


def test_r010_scope_and_name_heuristic():
    bad = RULE_CASES[9][1]
    # In scope under BOTH phase-transition prefixes...
    for rel in ("cuvite_tpu/louvain/x.py", "cuvite_tpu/coarsen/x.py"):
        hits = [f for f in run_source(bad, rel=rel) if f.rule == "R010"]
        # jax.device_get + np.asarray(labels_d): two findings
        assert len(hits) == 2, (rel, hits)
    # ...and silent everywhere else (the same pulls are legitimate on
    # ingest/eval paths where no device-resident slab exists).
    for rel in ("cuvite_tpu/io/x.py", "cuvite_tpu/workloads/x.py",
                "tools/x.py"):
        assert "R010" not in rules_of(run_source(bad, rel=rel)), rel


def test_r010_inline_disable_is_the_allowlist():
    src = """
import jax

def finalize(labels_d):
    return jax.device_get(labels_d)  # graftlint: disable=R010 — final label gather
"""
    assert run_source(src, rel="cuvite_tpu/louvain/x.py") == []


def test_r007_scope_is_tools_only():
    bad = RULE_CASES[6][1]
    assert not any(f.rule == "R007"
                   for f in run_source(bad, rel="cuvite_tpu/x.py"))


def test_r009_network_outside_registry_fires_even_with_checksum():
    # The GOOD registry fixture (checksum-verified download) is still a
    # violation anywhere else: the allowed file is part of the contract.
    good_registry = RULE_CASES[8][2]
    for rel in ("cuvite_tpu/io/vite.py", "tools/grab.py", "tests/x.py"):
        assert "R009" in rules_of(run_source(good_registry, rel=rel)), rel


R009_SUBPROCESS = """
import subprocess

def grab(url, dest):
    subprocess.run(%s, timeout=600, check=True)
"""


@pytest.mark.parametrize("argv,fires", [
    ("['curl', '-o', dest, url]", True),
    ("['wget', '-O', dest, url]", True),
    ("'wget ' + url", False),            # non-constant: cannot prove
    ("['/usr/bin/curl', url]", True),    # path-qualified downloader
    ("['python', '-m', 'x']", False),    # not a downloader
])
def test_r009_subprocess_downloaders(argv, fires):
    findings = run_source(R009_SUBPROCESS % argv,
                          rel="cuvite_tpu/workloads/registry.py")
    assert ("R009" in rules_of(findings)) == fires, (argv, findings)


# ---------------------------------------------------------------------------
# Suppressions

SUPPRESSIBLE = """
import subprocess

def bench(cmd):
    return subprocess.run(cmd)%s
"""


def test_line_suppression():
    dirty = run_source(SUPPRESSIBLE % "", rel="tools/x.py")
    assert rules_of(dirty) == {"R007"}
    clean = run_source(SUPPRESSIBLE % "  # graftlint: disable=R007",
                       rel="tools/x.py")
    assert clean == []


def test_line_suppression_is_rule_specific():
    still = run_source(SUPPRESSIBLE % "  # graftlint: disable=R001",
                       rel="tools/x.py")
    assert rules_of(still) == {"R007"}


def test_line_suppression_all():
    clean = run_source(SUPPRESSIBLE % "  # graftlint: disable=all",
                       rel="tools/x.py")
    assert clean == []


def test_file_suppression_within_pragma_window():
    src = "# graftlint: disable-file=R007\n" + SUPPRESSIBLE % ""
    assert run_source(src, rel="tools/x.py") == []


def test_file_suppression_ignored_past_pragma_window():
    pad = "\n" * 40
    src = SUPPRESSIBLE % "" + pad + "# graftlint: disable-file=R007\n"
    assert rules_of(run_source(src, rel="tools/x.py")) == {"R007"}


# ---------------------------------------------------------------------------
# Baseline round-trip


def _dirty_findings():
    return run_source(SUPPRESSIBLE % "", rel="tools/x.py")


def test_baseline_roundtrip(tmp_path):
    findings = _dirty_findings()
    assert findings
    bl_path = str(tmp_path / "baseline.json")
    write_baseline(bl_path, findings)

    baseline = load_baseline(bl_path)
    new, grandfathered = apply_baseline(_dirty_findings(), baseline)
    assert new == []
    assert len(grandfathered) == len(findings)
    assert gate_failures(new) == []


def test_baseline_survives_line_drift(tmp_path):
    bl_path = str(tmp_path / "baseline.json")
    write_baseline(bl_path, _dirty_findings())
    # Same violation, shifted down by unrelated edits above it: the
    # fingerprint is (path, rule, stripped line), so it stays baselined.
    drifted = run_source("\n# a new comment\n\n" + SUPPRESSIBLE % "",
                         rel="tools/x.py")
    new, old = apply_baseline(drifted, load_baseline(bl_path))
    assert new == [] and len(old) == 1


def test_baseline_does_not_mask_new_findings(tmp_path):
    bl_path = str(tmp_path / "baseline.json")
    write_baseline(bl_path, _dirty_findings())
    two = SUPPRESSIBLE % "" + """
def bench2(cmd):
    return subprocess.run(cmd, check=True)
"""
    new, old = apply_baseline(run_source(two, rel="tools/x.py"),
                              load_baseline(bl_path))
    assert len(old) == 1  # the grandfathered original
    assert len(new) == 1 and new[0].rule == "R007"
    assert gate_failures(new)


def test_e000_is_never_baselineable(tmp_path):
    """A grandfathered parse error must not permanently un-lint a file:
    E000 findings are excluded from write_baseline AND never match a
    (possibly hand-edited) baseline entry."""
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    findings = run_paths([str(bad)])
    assert [f.rule for f in findings] == ["E000"]
    bl_path = str(tmp_path / "baseline.json")
    write_baseline(bl_path, findings)
    assert load_baseline(bl_path) == {}  # not written...
    forged = {findings[0].fingerprint(): 1}
    new, old = apply_baseline(findings, forged)  # ...and never matched
    assert old == [] and new == findings


def test_pragma_inside_string_literal_is_ignored():
    """A docstring QUOTING the suppression syntax must not disable the
    gate for the file that quotes it."""
    src = '''"""Docs.

The suppression syntax is:
# graftlint: disable-file=all
"""
import subprocess

def bench(cmd):
    return subprocess.run(cmd)
'''
    assert rules_of(run_source(src, rel="tools/x.py")) == {"R007"}
    # ...while a REAL comment pragma still works
    real = "# graftlint: disable-file=R007\n" + src
    assert run_source(real, rel="tools/x.py") == []


def test_baseline_missing_file_is_empty():
    assert load_baseline("/nonexistent/baseline.json") == {}


def test_baseline_counts_duplicates(tmp_path):
    f = Finding(rule="R007", severity="high", path="tools/x.py", line=4,
                message="m", snippet="subprocess.run(cmd)")
    g = Finding(rule="R007", severity="high", path="tools/x.py", line=9,
                message="m", snippet="subprocess.run(cmd)")
    bl_path = str(tmp_path / "baseline.json")
    write_baseline(bl_path, [f])  # ONE slot for this fingerprint
    new, old = apply_baseline([f, g], load_baseline(bl_path))
    assert len(old) == 1 and len(new) == 1


# ---------------------------------------------------------------------------
# Engine behaviour


def test_syntax_error_yields_gateable_finding(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    findings = run_paths([str(p)])
    assert len(findings) == 1
    assert findings[0].rule == "E000" and findings[0].severity == "high"
    assert gate_failures(findings)


def test_unreadable_sources_fail_closed(tmp_path):
    """Non-UTF8 bytes and null bytes must become E000 findings, not an
    uncaught exception that discards every other file's findings."""
    latin = tmp_path / "latin.py"
    latin.write_bytes(b"# caf\xe9\n")
    nul = tmp_path / "nul.py"
    nul.write_bytes(b"x = 1\x00\n")
    findings = run_paths([str(latin), str(nul)])
    assert [f.rule for f in findings] == ["E000", "E000"]
    assert gate_failures(findings)


def test_barren_path_fails_closed(tmp_path):
    """A typo'd / renamed input directory must NOT report a green gate."""
    empty = tmp_path / "empty"
    empty.mkdir()
    for bad in ("/nonexistent/tree", str(empty)):
        findings = run_paths([bad])
        assert [f.rule for f in findings] == ["E000"]
        assert gate_failures(findings)


def test_run_paths_walks_directories(tmp_path):
    sub = tmp_path / "tools"
    sub.mkdir()
    (sub / "a.py").write_text(SUPPRESSIBLE % "")
    (sub / "skip.txt").write_text("subprocess.run(x)")
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        findings = run_paths(["tools"])
    finally:
        os.chdir(cwd)
    assert rules_of(findings) == {"R007"}
    assert findings[0].path == "tools/a.py"


# ---------------------------------------------------------------------------
# The gate itself


def test_selflint_no_new_high_findings(monkeypatch):
    """THE tier-1 gate: zero non-baselined high-severity findings across
    the repo's source, tools, and tests — ALL tiers (per-file rules,
    the cross-module R017/R018 pass, the serve/ lockset R019).  Runs
    through the incremental cache (the same one tools/lint.sh warms; a
    hit is pinned bit-identical to cold by
    test_cache_hit_bit_identical)."""
    import warnings as _warnings

    from cuvite_tpu.analysis.engine import stale_baseline_entries

    monkeypatch.chdir(REPO)
    findings = run_paths(SCAN_PATHS, cache=os.path.join(
        REPO, "tools", ".graftlint_cache.json"))
    baseline = load_baseline(BASELINE)
    new, _ = apply_baseline(findings, baseline)
    failures = gate_failures(new, "high")
    assert not failures, \
        "new high-severity graftlint findings (fix, suppress with a " \
        "justified '# graftlint: disable=R###', or re-baseline " \
        "deliberately via tools/lint.sh --write-baseline):\n" + \
        "\n".join(f.format() for f in failures)
    # Baseline hygiene rides along as a WARNING, not a failure: a dead
    # entry silently admits one future regression at its fingerprint.
    stale = stale_baseline_entries(findings, baseline)
    if stale:
        _warnings.warn(
            "graftlint baseline has stale entries (run tools/lint.sh "
            f"--prune-baseline): {stale}")


def test_gate_is_cwd_independent(tmp_path, monkeypatch):
    """Paths are anchored to the REPO ROOT, not the CWD: linting the
    repo by absolute path from elsewhere must keep the scoped rules on
    and the baseline matching."""
    from cuvite_tpu.analysis.engine import _relpath

    monkeypatch.chdir(tmp_path)
    assert _relpath(os.path.join(REPO, "tools", "lint.sh")) \
        == "tools/lint.sh"
    findings = run_paths([os.path.join(REPO, p) for p in SCAN_PATHS])
    assert all(not f.path.startswith(("/", "..")) for f in findings)
    new, _ = apply_baseline(findings, load_baseline(BASELINE))
    assert not gate_failures(new, "high")
    # ...while trees OUTSIDE the repo resolve against the scan-root
    # anchor, so scoped rules work on them from ANY CWD
    sub = tmp_path / "deep" / "nested" / "tools"
    sub.mkdir(parents=True)
    (sub / "a.py").write_text(SUPPRESSIBLE % "")
    assert rules_of(run_paths(["deep/nested/tools"])) == {"R007"}
    monkeypatch.chdir("/")  # ancestor CWD: anchor must still win
    assert rules_of(run_paths([str(sub)])) == {"R007"}
    # a single FILE under a scoped dir keeps the scoping component too
    assert rules_of(run_paths([str(sub / "a.py")])) == {"R007"}


def test_write_baseline_cli_reports_e000(tmp_path, capsys):
    """--write-baseline must not claim it captured unparsable files, and
    must exit nonzero so a rebaseline doesn't green-wash an E000."""
    from cuvite_tpu.analysis.__main__ import main

    tree = tmp_path / "tools"
    tree.mkdir()
    (tree / "bad.py").write_text(SUPPRESSIBLE % "")
    (tree / "broken.py").write_text("def f(:\n")
    bl = str(tmp_path / "bl.json")
    rc = main([str(tree), "--baseline", bl, "--write-baseline"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "wrote 1 finding(s)" in out and "NOT baselined" in out
    assert len(load_baseline(bl)) == 1


@pytest.mark.slow
def test_cli_gate_matches_library(monkeypatch, capsys):
    """Tier-2 (slow): this is a second ~13 s full-repo gate scan whose
    tier-1 coverage lives in test_gate_is_cwd_independent (same
    run_paths + baseline + gate over SCAN_PATHS) and, for the real CLI
    surface, test_cli_subprocess_entrypoint."""
    from cuvite_tpu.analysis.__main__ import main

    monkeypatch.chdir(REPO)
    rc = main(list(SCAN_PATHS) + ["--baseline", BASELINE,
                                  "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["gate"]["failures"] == 0


def test_cli_list_rules(capsys):
    from cuvite_tpu.analysis.__main__ import main

    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in RULE_IDS:
        assert rid in out


@pytest.mark.slow
def test_cli_subprocess_entrypoint():
    """`python -m cuvite_tpu.analysis` works as a real child process
    (what tools/lint.sh and CI invoke)."""
    out = subprocess.run(
        [sys.executable, "-m", "cuvite_tpu.analysis", *SCAN_PATHS,
         "--baseline", BASELINE],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr


# ---------------------------------------------------------------------------
# Tier 2: cross-module jit-reachability (R017/R018).  Fixtures are
# multi-file {rel: source} projects linted through run_project_sources —
# the same path run_paths takes for a tree on disk.

from cuvite_tpu.analysis import run_project_sources  # noqa: E402

R017_DEEP = {
    # jit root -> mid helper (module 2) -> device_get (module 3): the
    # exact false negative ANALYSIS.md used to document as out of scope.
    "cuvite_tpu/louvain/fake_root.py": """
import jax

from cuvite_tpu.fake_mid import mid_helper

@jax.jit
def step(x):
    return mid_helper(x)
""",
    "cuvite_tpu/fake_mid.py": """
from cuvite_tpu.fake_deep import deep_pull

def mid_helper(x):
    return deep_pull(x) + 1
""",
    "cuvite_tpu/fake_deep.py": """
import jax

def deep_pull(x):
    return jax.device_get(x)
""",
}


def test_r017_transitive_device_get_two_modules_deep():
    findings = run_project_sources(R017_DEEP)
    hits = [f for f in findings if f.rule == "R017"]
    assert len(hits) == 1, findings
    assert hits[0].path == "cuvite_tpu/fake_deep.py"
    assert "fake_root.py::step" in hits[0].message  # the reach chain
    assert hits[0].severity == "high"


def test_r017_negative_without_entry_point():
    # Identical modules, no @jax.jit: plain host code, nothing fires.
    clean = dict(R017_DEEP)
    clean["cuvite_tpu/louvain/fake_root.py"] = \
        clean["cuvite_tpu/louvain/fake_root.py"].replace("@jax.jit\n", "")
    assert not any(f.rule == "R017"
                   for f in run_project_sources(clean))


def test_r017_defers_to_r001_in_module():
    # A same-module reachable sync is R001's finding; R017 must not
    # double-report it.
    src = {
        "cuvite_tpu/fake_one.py": """
import jax

@jax.jit
def step(x):
    return helper(x)

def helper(x):
    return jax.device_get(x)
""",
    }
    rules = {f.rule for f in run_project_sources(src)}
    assert "R001" in rules and "R017" not in rules


def test_r017_factory_partial_shard_map_idiom():
    """The louvain/batched.py shape: the traced body reaches jit only
    through a functools.partial assigned to a local, wrapped in
    shard_map — the per-file engine misses it, tier 2 must not."""
    src = {
        "cuvite_tpu/fake_factory.py": """
import functools
import jax

from cuvite_tpu.fake_body import phase_body

def get_phase(mesh, nv_pad):
    body = functools.partial(phase_body, nv_pad=nv_pad)
    return jax.jit(shard_map(body, mesh=mesh))

def shard_map(f, mesh):
    return f
""",
        "cuvite_tpu/fake_body.py": """
import numpy as np

def phase_body(x, *, nv_pad):
    return np.asarray(x)
""",
    }
    hits = [f for f in run_project_sources(src) if f.rule == "R017"]
    assert len(hits) == 1 and hits[0].path == "cuvite_tpu/fake_body.py"


def test_r017_inline_suppression():
    src = dict(R017_DEEP)
    src["cuvite_tpu/fake_deep.py"] = """
import jax

def deep_pull(x):
    return jax.device_get(x)  # graftlint: disable=R017 — final gather
"""
    assert not any(f.rule == "R017" for f in run_project_sources(src))


R018_PROJECT = {
    "cuvite_tpu/coarsen/fake_phase.py": """
from cuvite_tpu.utils.fake_pull import pull_stats

def phase_transition(slab_d):
    return pull_stats(slab_d)
""",
    "cuvite_tpu/utils/fake_pull.py": """
import jax

def pull_stats(slab_d):
    return jax.device_get(slab_d)
""",
}


def test_r018_pull_in_helper_reached_from_coarsen():
    findings = run_project_sources(R018_PROJECT)
    hits = [f for f in findings if f.rule == "R018"]
    assert len(hits) == 1, findings
    assert hits[0].path == "cuvite_tpu/utils/fake_pull.py"
    assert "fake_phase.py::phase_transition" in hits[0].message


def test_r018_negative_unreached_helper():
    # The same helper reached only from tools/: no phase-transition
    # caller, no finding (and R010 stays silent outside its scope).
    src = {
        "tools/fake_bench.py": R018_PROJECT[
            "cuvite_tpu/coarsen/fake_phase.py"],
        "cuvite_tpu/utils/fake_pull.py": R018_PROJECT[
            "cuvite_tpu/utils/fake_pull.py"],
    }
    assert not any(f.rule in ("R018", "R010")
                   for f in run_project_sources(src))


def test_r018_in_scope_modules_stay_r010():
    # A pull INSIDE louvain//coarsen/ is R010's (baselined, medium)
    # finding; R018 covers only the helpers those modules reach.
    src = {"cuvite_tpu/coarsen/fake_self.py": """
import jax

def phase_transition(slab_d):
    return jax.device_get(slab_d)
"""}
    rules = {f.rule for f in run_project_sources(src)}
    assert "R010" in rules and "R018" not in rules


# ---------------------------------------------------------------------------
# Tier 2b: lockset checker details beyond the RULE_CASES pair.


R019_SEEDED_PR11 = """
import threading


class ServeStats:
    def __init__(self):
        self.lock = threading.RLock()
        self.jobs_done = 0
        self.wait_samples = []


class Dispatcher:
    def __init__(self, stats):
        self.stats = stats

    def locked_path(self, wait):
        with self.stats.lock:
            self.stats.jobs_done += 1
            self.stats.wait_samples.append(wait)

    def drain_recheck(self, wait):
        # the PR-11 drain-recheck bug shape: the happy path takes the
        # lock, the drain path forgot it
        self.stats.jobs_done += 1
        self.stats.wait_samples.append(wait)
"""


def test_r019_seeded_pr11_unguarded_mutation():
    hits = [f for f in run_source(R019_SEEDED_PR11,
                                  rel="cuvite_tpu/serve/fake_seed.py")
            if f.rule == "R019"]
    assert len(hits) == 2, hits          # jobs_done += and .append
    assert all("self.stats.lock" in f.message for f in hits)
    assert all(f.severity == "high" for f in hits)


def test_r019_scope_is_serve_only():
    assert not any(
        f.rule == "R019"
        for f in run_source(R019_SEEDED_PR11,
                            rel="cuvite_tpu/louvain/fake_seed.py"))


def test_r019_guarded_by_annotation():
    """The explicit annotation establishes the discipline when NO
    in-class mutation ever takes the lock (inference has nothing to
    infer from)."""
    src = """
import threading


class Stats:
    lock: object = None
    jobs_done: int = 0  # graftlint: guarded-by=self.lock

    def racy(self):
        self.jobs_done += 1
"""
    hits = [f for f in run_source(src, rel="cuvite_tpu/serve/fake.py")
            if f.rule == "R019"]
    assert len(hits) == 1 and "self.lock" in hits[0].message
    # ...and holding the annotated lock satisfies it.
    good = src.replace("        self.jobs_done += 1",
                       "        with self.lock:\n"
                       "            self.jobs_done += 1")
    assert not any(f.rule == "R019"
                   for f in run_source(good,
                                       rel="cuvite_tpu/serve/fake.py"))


def test_r019_nested_class_does_not_cross_pollute():
    """An inner class's mutations must not inherit (or feed) the outer
    class's inferred guards."""
    src = """
import threading


class Outer:
    def __init__(self):
        self.lock = threading.RLock()
        self.count = 0

    def locked(self):
        with self.lock:
            self.count += 1

    class Inner:
        def bump(self):
            self.count += 1   # Inner has no lock discipline of its own
"""
    assert not any(f.rule == "R019"
                   for f in run_source(src,
                                       rel="cuvite_tpu/serve/fake.py"))


def test_r019_inline_suppression():
    suffix = "  # graftlint: disable=R019 — single-threaded teardown"
    lines = R019_SEEDED_PR11.splitlines()
    # Suppress the two drain_recheck mutations (the last two statements).
    drain_at = lines.index("    def drain_recheck(self, wait):")
    out = [ln + suffix
           if i > drain_at and ln.strip().startswith("self.stats.")
           else ln
           for i, ln in enumerate(lines)]
    hits = [f for f in run_source("\n".join(out),
                                  rel="cuvite_tpu/serve/fake.py")
            if f.rule == "R019"]
    assert hits == [], hits


def test_r019_real_serve_package_self_lints_clean(monkeypatch):
    """The acceptance pin: the REAL serve/ package carries no unguarded
    mutation of an inferred/annotated guarded field."""
    monkeypatch.chdir(REPO)
    findings = run_paths(["cuvite_tpu/serve"], project=False)
    assert not [f for f in findings if f.rule == "R019"], findings


# ---------------------------------------------------------------------------
# Tier 5 (static): SPMD mesh/collective rules R023-R025.  Fixtures are
# multi-file projects through run_project_sources, like tier 2's.

MESH5_MESH = """
import numpy as np
from jax.sharding import Mesh

VERTEX_AXIS = "v"
BATCH_AXIS = "b"

def make(devs):
    return Mesh(np.array(devs), (VERTEX_AXIS,))

def make_batch(devs):
    return Mesh(np.array(devs), (BATCH_AXIS,))
"""

MESH5_STEP = """
import jax
from cuvite_tpu.fake_mesh5 import VERTEX_AXIS
from cuvite_tpu.fake_helper5 import tail_sum

def make_step(mesh):
    def step(x, flag):
        return tail_sum(x, VERTEX_AXIS, flag)
    return jax.jit(shard_map(step, mesh=mesh, in_specs=P(VERTEX_AXIS),
                             out_specs=P(VERTEX_AXIS)))
"""


def _mesh5_project(helper_src):
    return {
        "cuvite_tpu/fake_mesh5.py": MESH5_MESH,
        "cuvite_tpu/fake_step5.py": MESH5_STEP,
        "cuvite_tpu/fake_helper5.py": helper_src,
    }


MESH5_HELPER_DRIFT = """
import jax

def tail_sum(x, axis_name, flag):
    return jax.lax.psum(x, "ici")
"""

MESH5_HELPER_WRONG_AXIS = """
import jax

def tail_sum(x, axis_name, flag):
    return jax.lax.psum(x, "b")
"""

MESH5_HELPER_DIVERGENT = """
import jax

def tail_sum(x, axis_name, flag):
    if flag.any():
        return jax.lax.psum(x, axis_name)
    return x
"""

MESH5_HELPER_CLEAN = """
import jax

def tail_sum(x, axis_name, flag):
    return jax.lax.psum(x, axis_name)
"""


def test_r023_unknown_axis_cross_module():
    findings = run_project_sources(_mesh5_project(MESH5_HELPER_DRIFT))
    hits = [f for f in findings if f.rule == "R023"]
    assert len(hits) == 1, findings
    assert hits[0].path == "cuvite_tpu/fake_helper5.py"
    assert "'ici'" in hits[0].message
    assert "fake_step5.py::step" in hits[0].message  # the reach chain


def test_r023_per_wrap_axis_mismatch():
    # 'b' IS a constructed mesh axis, but every wrap reaching the
    # helper maps only 'v': the two-level-split bug class.
    findings = run_project_sources(
        _mesh5_project(MESH5_HELPER_WRONG_AXIS))
    hits = [f for f in findings if f.rule == "R023"]
    assert len(hits) == 1, findings
    assert "maps only axes ['v']" in hits[0].message


def test_r023_multi_wrap_union_admits_both_axes():
    """A helper reached from BOTH the vertex-sharded and the
    batch-sharded wrap admits the union of their axes: psum over
    either axis is legal, conviction requires disjointness from EVERY
    reaching wrap (the fixpoint over all call edges, not the BFS
    tree)."""
    src = _mesh5_project(MESH5_HELPER_WRONG_AXIS)  # psum over 'b'
    src["cuvite_tpu/fake_bstep5.py"] = """
import jax
from cuvite_tpu.fake_mesh5 import BATCH_AXIS
from cuvite_tpu.fake_helper5 import tail_sum

def make_bstep(mesh):
    def bstep(x, flag):
        return tail_sum(x, BATCH_AXIS, flag)
    return jax.jit(shard_map(bstep, mesh=mesh, in_specs=P(BATCH_AXIS),
                             out_specs=P(BATCH_AXIS)))
"""
    assert not any(f.rule == "R023"
                   for f in run_project_sources(src))


def test_r023_param_axis_resolves_clean():
    # axis_name chases its call-site binding (VERTEX_AXIS -> "v")
    # through the wrap: no finding.
    findings = run_project_sources(_mesh5_project(MESH5_HELPER_CLEAN))
    assert not any(f.rule in ("R023", "R024", "R025") for f in findings)


def test_r023_no_wrap_no_finding():
    src = _mesh5_project(MESH5_HELPER_DRIFT)
    src["cuvite_tpu/fake_step5.py"] = MESH5_STEP.replace(
        "shard_map(step, mesh=mesh, in_specs=P(VERTEX_AXIS),\n"
        "                             out_specs=P(VERTEX_AXIS))", "step")
    assert not any(f.rule == "R023"
                   for f in run_project_sources(src))


def test_r023_axis_index_first_positional_axis():
    # axis_index takes the axis name as its FIRST argument (review
    # regression: the axis-arg reader only looked at position 1).
    findings = run_project_sources(_mesh5_project("""
import jax

def tail_sum(x, axis_name, flag):
    me = jax.lax.axis_index("ici")
    return x + me
"""))
    hits = [f for f in findings if f.rule == "R023"]
    assert len(hits) == 1 and "'ici'" in hits[0].message


def test_r023_inline_suppression():
    src = _mesh5_project(MESH5_HELPER_DRIFT.replace(
        'jax.lax.psum(x, "ici")',
        'jax.lax.psum(x, "ici")  # graftlint: disable=R023 — staged axis'))
    assert not any(f.rule == "R023" for f in run_project_sources(src))


# Hybrid 2-D ('dcn','ici') mesh project: the two-level exchange shape.
MESH5_HYBRID_MESH = """
import numpy as np
from jax.sharding import Mesh

DCN_AXIS = "dcn"
ICI_AXIS = "ici"

def make_hybrid(devs, n_dcn, n_ici):
    return Mesh(np.array(devs).reshape(n_dcn, n_ici),
                (DCN_AXIS, ICI_AXIS))
"""

MESH5_HYBRID_STEP = """
import jax
from cuvite_tpu.fake_hmesh5 import DCN_AXIS, ICI_AXIS
from cuvite_tpu.fake_htable5 import group_tables

def make_step(mesh):
    def step(comm, vdeg):
        return group_tables(comm, vdeg, DCN_AXIS, ICI_AXIS)
    return jax.jit(shard_map(step, mesh=mesh,
                             in_specs=P((DCN_AXIS, ICI_AXIS)),
                             out_specs=P((DCN_AXIS, ICI_AXIS))))
"""

MESH5_HYBRID_TABLE_CLEAN = """
import jax

def group_tables(comm, vdeg, dcn_axis, ici_axis):
    comm_g = jax.lax.all_gather(comm, ici_axis, tiled=True)
    vdeg_g = jax.lax.all_gather(vdeg, ici_axis, tiled=True)
    return comm_g[: comm.shape[0]] + vdeg_g[: vdeg.shape[0]]
"""


def _mesh5_hybrid_project(table_src):
    return {
        "cuvite_tpu/fake_hmesh5.py": MESH5_HYBRID_MESH,
        "cuvite_tpu/fake_hstep5.py": MESH5_HYBRID_STEP,
        "cuvite_tpu/fake_htable5.py": table_src,
    }


def test_r023_hybrid_ici_gather_clean():
    # The narrowed two-level table gather (ICI axis via the wrap's
    # binding) is legal on the 2-D hybrid mesh: no finding.
    findings = run_project_sources(
        _mesh5_hybrid_project(MESH5_HYBRID_TABLE_CLEAN))
    assert not any(f.rule in ("R023", "R024") for f in findings), findings


def test_r023_hybrid_table_rewidened_to_flat_axis_convicted():
    """ISSUE 18 sabotage, static half: re-widening one group table's
    gather from the ICI submesh back to the retired flat global axis
    ('v' — which no mesh in the hybrid project constructs) is exactly
    an axis-name edit, and R023 convicts it cross-module."""
    sab = MESH5_HYBRID_TABLE_CLEAN.replace(
        'jax.lax.all_gather(comm, ici_axis, tiled=True)',
        'jax.lax.all_gather(comm, "v", tiled=True)')
    findings = run_project_sources(_mesh5_hybrid_project(sab))
    hits = [f for f in findings if f.rule == "R023"]
    assert len(hits) == 1, findings
    assert hits[0].path == "cuvite_tpu/fake_htable5.py"
    assert "'v'" in hits[0].message
    assert "fake_hstep5.py::step" in hits[0].message


def test_r024_conditional_collective_cross_module():
    findings = run_project_sources(
        _mesh5_project(MESH5_HELPER_DIVERGENT))
    hits = [f for f in findings if f.rule == "R024"]
    assert len(hits) == 1, findings
    assert hits[0].path == "cuvite_tpu/fake_helper5.py"
    assert "flag.any" in hits[0].message
    assert "fake_step5.py::step" in hits[0].message
    # Unconditional collective in the same shape: clean (pinned above
    # by test_r023_param_axis_resolves_clean).


def test_r024_requires_shard_map_reach():
    # The same divergent helper with NO shard_map anywhere: host-side
    # code, R024 stays silent (R004 covers host collective wrappers).
    src = {"cuvite_tpu/fake_solo5.py": MESH5_HELPER_DIVERGENT}
    assert not any(f.rule == "R024" for f in run_project_sources(src))


def test_r024_leaves_host_wrappers_to_r004():
    src = _mesh5_project("""
from cuvite_tpu.comm.multihost import gather_global

def tail_sum(x, axis_name, flag):
    if flag.any():
        return gather_global(x)
    return x
""")
    rules = {f.rule for f in run_project_sources(src)}
    assert "R004" in rules and "R024" not in rules


R025_TABLE = """
import jax
import jax.numpy as jnp

def make_step(mesh, nv_total):
    def step(vdeg, comm):
        table = jnp.zeros((nv_total,), dtype=vdeg.dtype)%s
        return jax.lax.psum(table, "v")
    return jax.jit(shard_map(step, mesh=mesh, in_specs=P("v"),
                             out_specs=P("v")))
"""


def test_r025_unannotated_nv_total_table():
    src = {"cuvite_tpu/fake_r025.py": R025_TABLE % "",
           "cuvite_tpu/fake_mesh5.py": MESH5_MESH}
    hits = [f for f in run_project_sources(src) if f.rule == "R025"]
    assert len(hits) == 1, hits
    assert "nv_total" in hits[0].message
    assert "replicated-ok" in hits[0].message


def test_r025_replicated_ok_annotation_closes_the_finding():
    src = {"cuvite_tpu/fake_r025.py": R025_TABLE
           % "  # graftlint: replicated-ok=frozen community table",
           "cuvite_tpu/fake_mesh5.py": MESH5_MESH}
    assert not any(f.rule == "R025" for f in run_project_sources(src))
    # ... and the annotated site lands in the closed inventory.
    from cuvite_tpu.analysis.callgraph import summarize
    from cuvite_tpu.analysis.engine import SourceFile
    from cuvite_tpu.analysis.meshspec import replicated_inventory

    rel = "cuvite_tpu/fake_r025.py"
    inv = replicated_inventory(
        [summarize(SourceFile(src[rel], path=rel, rel=rel))])
    assert len(inv) == 1
    assert inv[0]["reason"] == "frozen community table"


def test_r025_positional_and_broadcast_spellings_convict():
    """Review regressions: ``num_segments`` spelled POSITIONALLY
    (segment_sum(data, ids, nv_total)) and ``broadcast_to`` (whose
    shape is the SECOND positional) materialize the same O(nv_total)
    table and must convict like the keyword/zeros spellings."""
    src = {"cuvite_tpu/fake_r025pos.py": """
import jax
import jax.numpy as jnp

def make_step(mesh, nv_total):
    def step(vdeg, comm):
        deg = seg.segment_sum(vdeg, comm, nv_total)
        rep = jnp.broadcast_to(vdeg[:1], (nv_total,))
        return jax.lax.psum(deg + rep, "v")
    return jax.jit(shard_map(step, mesh=mesh, in_specs=P("v"),
                             out_specs=P("v")))
""",
           "cuvite_tpu/fake_mesh5.py": MESH5_MESH}
    hits = [f for f in run_project_sources(src) if f.rule == "R025"]
    assert len(hits) == 2, hits


def test_r025_unreached_table_is_clean():
    # nv_total-sized table in plain host code (no shard_map reach):
    # one copy on one device is not replication.
    src = {"cuvite_tpu/fake_host25.py": """
import jax.numpy as jnp

def table_of(nv_total):
    return jnp.zeros((nv_total,), dtype="int32")
"""}
    assert not any(f.rule == "R025" for f in run_project_sources(src))


def test_tier5_rules_ride_the_cache_warm_equals_cold(tmp_path):
    """R023 findings come from PROJECT-linked mesh facts riding the
    tier-2 summaries: a warm (all-hits) run must reproduce them bit-
    identically from the cache without reparsing."""
    tree = tmp_path / "cuvite_tpu"
    tree.mkdir()
    (tree / "fake_mesh5.py").write_text(MESH5_MESH)
    (tree / "fake_step5.py").write_text(MESH5_STEP)
    (tree / "fake_helper5.py").write_text(MESH5_HELPER_DRIFT)
    cache = str(tmp_path / "cache.json")
    cold = run_paths([str(tree)], cache=cache)
    warm = run_paths([str(tree)], cache=cache)
    assert cold == warm
    assert any(f.rule == "R023" for f in warm)


def test_tier5_sarif_roundtrip():
    from cuvite_tpu.analysis.__main__ import to_sarif

    findings = run_project_sources(_mesh5_project(MESH5_HELPER_DRIFT))
    doc = to_sarif([f for f in findings if f.rule == "R023"])
    run = doc["runs"][0]
    meta_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"R023", "R024", "R025"} <= meta_ids
    assert [r["ruleId"] for r in run["results"]] == ["R023"]
    assert run["results"][0]["level"] == "error"
    assert run["results"][0]["partialFingerprints"]


# ---------------------------------------------------------------------------
# Incremental cache: hit == cold, bit for bit; edits invalidate.


def _mini_tree(tmp_path):
    tree = tmp_path / "tools"
    tree.mkdir()
    (tree / "a.py").write_text(SUPPRESSIBLE % "")
    (tree / "b.py").write_text("def ok():\n    return 1\n")
    return tree


def test_cache_hit_bit_identical(tmp_path):
    tree = _mini_tree(tmp_path)
    cache = str(tmp_path / "cache.json")
    cold = run_paths([str(tree)])                      # no cache at all
    warm0 = run_paths([str(tree)], cache=cache)        # cold, writes
    assert os.path.exists(cache)
    warm1 = run_paths([str(tree)], cache=cache)        # pure hits
    assert cold == warm0 == warm1                      # dataclass equality
    # An edit invalidates exactly that file.
    (tree / "b.py").write_text("import subprocess\n\n"
                               "def bad(cmd):\n"
                               "    return subprocess.run(cmd)\n")
    warm2 = run_paths([str(tree)], cache=cache)
    assert warm2 == run_paths([str(tree)])
    assert {f.path for f in warm2 if f.rule == "R007"} \
        == {"tools/a.py", "tools/b.py"}


def test_cache_rules_version_invalidates(tmp_path, monkeypatch):
    from cuvite_tpu.analysis import cache as cache_mod

    tree = _mini_tree(tmp_path)
    cache = str(tmp_path / "cache.json")
    run_paths([str(tree)], cache=cache)
    with open(cache, encoding="utf-8") as fh:
        data = json.load(fh)
    assert data["rules_version"] == cache_mod.rules_version()
    # A rules-set change (simulated version bump) must cold-start.
    monkeypatch.setattr(cache_mod, "rules_version", lambda: "different")
    lc = cache_mod.LintCache(cache)
    assert lc.entries == {}


def test_cache_corruption_degrades_to_cold(tmp_path):
    tree = _mini_tree(tmp_path)
    cache = str(tmp_path / "cache.json")
    with open(cache, "w", encoding="utf-8") as fh:
        fh.write("{not json")
    assert run_paths([str(tree)], cache=cache) == run_paths([str(tree)])


def test_cache_narrowed_rules_bypass(tmp_path):
    """A rules-subset run must not poison (or be served by) the cache."""
    from cuvite_tpu.analysis.rules import SubprocessNoTimeout

    tree = _mini_tree(tmp_path)
    cache = str(tmp_path / "cache.json")
    run_paths([str(tree)], cache=cache)        # full registry, cached
    only = run_paths([str(tree)], rules=[SubprocessNoTimeout()],
                     cache=cache)
    assert {f.rule for f in only} == {"R007"}
    full = run_paths([str(tree)], cache=cache)
    assert {f.rule for f in full} == {"R007"}


# ---------------------------------------------------------------------------
# Baseline hygiene: staleness report + --prune-baseline.


def test_stale_baseline_entries_and_prune(tmp_path):
    from cuvite_tpu.analysis.engine import (
        prune_baseline,
        stale_baseline_entries,
    )

    tree = _mini_tree(tmp_path)
    bl = str(tmp_path / "bl.json")
    findings = run_paths([str(tree)])
    write_baseline(bl, findings)
    # Fix the violation: the baseline entry goes stale.
    (tree / "a.py").write_text(
        (SUPPRESSIBLE % "").replace("subprocess.run(cmd)",
                                    "subprocess.run(cmd, timeout=60)"))
    now = run_paths([str(tree)])
    stale = stale_baseline_entries(now, load_baseline(bl))
    assert len(stale) == 1 and stale[0][0][1] == "R007"
    dropped = prune_baseline(bl, now)
    assert dropped == 1
    assert load_baseline(bl) == {}
    assert stale_baseline_entries(now, load_baseline(bl)) == []
    assert prune_baseline(bl, now) == 0      # idempotent


def test_prune_baseline_keeps_live_entries(tmp_path):
    from cuvite_tpu.analysis.engine import prune_baseline

    tree = _mini_tree(tmp_path)
    bl = str(tmp_path / "bl.json")
    findings = run_paths([str(tree)])
    write_baseline(bl, findings)
    assert prune_baseline(bl, findings) == 0
    new, old = apply_baseline(run_paths([str(tree)]), load_baseline(bl))
    assert new == [] and len(old) == len(findings)


def test_prune_and_staleness_are_scoped_to_linted_paths(tmp_path):
    """A subset run (lint.sh --changed, explicit paths) must treat
    entries for UNLINTED files as unknown — neither stale-reported nor
    pruned — or every subset run would steer the operator into deleting
    live grandfathered slots."""
    from cuvite_tpu.analysis.engine import (
        linted_rels,
        prune_baseline,
        stale_baseline_entries,
    )

    tree = _mini_tree(tmp_path)
    (tree / "c.py").write_text(SUPPRESSIBLE % "")   # second violation
    bl = str(tmp_path / "bl.json")
    write_baseline(bl, run_paths([str(tree)]))      # a.py + c.py slots
    # Subset run over ONE file: c.py's live entry must survive.
    subset = [str(tree / "a.py")]
    findings = run_paths(subset)
    linted = linted_rels(subset)
    assert linted == {"tools/a.py"}
    assert stale_baseline_entries(findings, load_baseline(bl),
                                  linted=linted) == []
    assert prune_baseline(bl, findings, linted=linted) == 0
    new, old = apply_baseline(run_paths([str(tree)]), load_baseline(bl))
    assert new == [] and len(old) == 2              # both still covered
    # The same subset WITHOUT the scope would have reported/pruned it.
    assert len(stale_baseline_entries(findings, load_baseline(bl))) == 1


def test_prune_baseline_cli_refuses_no_project(tmp_path):
    from cuvite_tpu.analysis.__main__ import main

    tree = _mini_tree(tmp_path)
    bl = str(tmp_path / "bl.json")
    write_baseline(bl, run_paths([str(tree)]))
    with pytest.raises(SystemExit):
        main([str(tree), "--baseline", bl, "--prune-baseline",
              "--no-project"])


def test_prune_baseline_cli(tmp_path, capsys):
    from cuvite_tpu.analysis.__main__ import main

    tree = _mini_tree(tmp_path)
    bl = str(tmp_path / "bl.json")
    write_baseline(bl, run_paths([str(tree)]))
    (tree / "a.py").write_text("x = 1\n")
    rc = main([str(tree), "--baseline", bl, "--prune-baseline"])
    assert rc == 0
    assert "pruned 1 stale baseline slot(s)" in capsys.readouterr().out
    assert load_baseline(bl) == {}


def test_selflint_reports_stale_count_in_text(tmp_path, capsys):
    from cuvite_tpu.analysis.__main__ import main

    tree = _mini_tree(tmp_path)
    bl = str(tmp_path / "bl.json")
    write_baseline(bl, run_paths([str(tree)]))
    (tree / "a.py").write_text("x = 1\n")
    rc = main([str(tree), "--baseline", bl])
    out = capsys.readouterr().out
    assert rc == 0 and "stale baseline slot(s)" in out


# ---------------------------------------------------------------------------
# SARIF output: schema shape + round-trip against the finding list.


def test_sarif_roundtrip(tmp_path, capsys):
    from cuvite_tpu.analysis.__main__ import main, to_sarif

    tree = _mini_tree(tmp_path)
    rc = main([str(tree), "--format", "sarif"])
    assert rc == 1                       # the R007 finding fails the gate
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-2.1.0.json")
    run = doc["runs"][0]
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert rule_ids >= set(RULE_IDS) | {"R017", "R018", "E000"}
    findings = run_paths([str(tree)])
    assert len(run["results"]) == len(findings)
    for res, f in zip(run["results"],
                      sorted(findings,
                             key=lambda f: (f.path, f.line, f.rule))):
        assert res["ruleId"] == f.rule
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == f.path
        assert loc["region"]["startLine"] == f.line
        assert loc["region"]["snippet"]["text"] == f.snippet
        assert res["partialFingerprints"]["graftlintFingerprint/v1"]
    # Fingerprints must be a pure function of (path, rule, snippet):
    # regenerating from the same findings is byte-identical.
    assert to_sarif(findings) == to_sarif(findings)
    # Severity -> SARIF level mapping (R007 is high -> error).
    assert run["results"][0]["level"] == "error"


def test_sarif_baselined_findings_are_excluded(tmp_path, capsys):
    from cuvite_tpu.analysis.__main__ import main

    tree = _mini_tree(tmp_path)
    bl = str(tmp_path / "bl.json")
    write_baseline(bl, run_paths([str(tree)]))
    rc = main([str(tree), "--format", "sarif", "--baseline", bl])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["runs"][0]["results"] == []
    assert doc["runs"][0]["properties"]["baselinedFindings"] >= 1


# ---------------------------------------------------------------------------
# Tier 3: jaxpr lint + compile-budget audit (the dynamic tier).  The
# audit runs the REAL entries at the representative small class — the
# same scenarios tools/compile_audit.py grades — plus the sabotage
# fixture proving B002 actually catches content-in-the-compile-key.

sys.path.insert(0, os.path.join(REPO, "tools"))


def test_compile_budget_audit_tier1(monkeypatch):
    """tools/compile_audit.py must pass on the current repo: observed
    compile set ⊆ the checked-in manifest, nothing recompiles on a
    content-only change, and the traced jaxprs carry no 64-bit ops,
    callbacks, or in-graph transfers."""
    monkeypatch.chdir(REPO)
    import compile_audit

    results, jaxpr_findings = compile_audit.run_audit()
    problems = [f.format() for r in results for f in r.findings]
    problems += [f.format() for f in jaxpr_findings]
    assert not problems, "\n".join(problems)


def test_compile_audit_sabotage_content_in_compile_key():
    """Thread batch content into a compile key (weights as a static
    argument) and assert the budget auditor catches it — the gate that
    replaces PR 10's by-hand measurement."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from cuvite_tpu.analysis.jaxpr_audit import audit_entry

    @functools.partial(jax.jit, static_argnames=("w",))
    def sabotaged(x, *, w):
        # content (the weight tuple) is a STATIC: every distinct batch
        # recompiles — exactly what pinning weights f32 prevents.
        return x * jnp.asarray(w, dtype=jnp.float32)

    def run(seed):
        w = tuple(float(v) for v in
                  np.random.default_rng(seed).uniform(0.5, 2.0, 4))
        sabotaged(np.ones(4, np.float32), w=w)

    res = audit_entry("sabotage", run,
                      {"modules": ["sabotaged"],
                       "content_independent": True})
    assert any(f.rule == "B002" for f in res.findings), res
    assert not res.ok


def test_compile_audit_sabotage_occupancy_in_compile_key():
    """ISSUE 20: sub-row OCCUPANCY (how many tenants landed in a packed
    row — batch content, like the weights) must never become a static.
    A sabotaged packed-run twin that threads the occupancy count into a
    static argument recompiles when the second audit run packs a
    different number of tenants — B002 fires."""
    import functools

    import jax
    import numpy as np

    from cuvite_tpu.analysis.jaxpr_audit import audit_entry

    @functools.partial(jax.jit, static_argnames=("n_occupied",))
    def sabotaged_packed(x, *, n_occupied):
        # occupancy as a STATIC: every distinct fill level recompiles —
        # exactly what pack_subrows' runtime sub_valid mask prevents.
        return x * (x.shape[0] // n_occupied)

    def run(seed):
        # The audit varies only the content seed; occupancy follows it
        # the way a skewed serving mix varies fill level batch to batch.
        n_occupied = 1 + (seed % 2)
        sabotaged_packed(np.ones(4, np.float32), n_occupied=n_occupied)

    res = audit_entry("sabotage-occupancy", run,
                      {"modules": ["sabotaged_packed"],
                       "content_independent": True})
    assert any(f.rule == "B002" for f in res.findings), res
    assert not res.ok


def test_compile_audit_missing_manifest_entry_fails_closed():
    from cuvite_tpu.analysis.jaxpr_audit import audit_entry

    res = audit_entry("ghost_entry", lambda seed: None, None)
    assert [f.rule for f in res.findings] == ["B001"]


def test_compile_audit_union_patterns_cover_shared_programs():
    """Which entry a shared program's compile lands on depends on run
    order (the serve path compiles the batched entries' programs when
    audited alone): matching must accept the UNION of the manifest's
    modules via extra_patterns, not just the entry's own."""
    import jax
    import numpy as np

    from cuvite_tpu.analysis.jaxpr_audit import audit_entry

    def shared_program(x):
        return x - 1

    jitted = jax.jit(shared_program)

    def run(seed):
        jitted(np.full(5, seed, np.float32))

    alone = audit_entry("other_entry", run,
                        {"modules": [], "content_independent": True})
    assert any(f.rule == "B001" for f in alone.findings)
    covered = audit_entry("other_entry", run,
                          {"modules": [], "content_independent": True},
                          extra_patterns=("shared_program",))
    assert not [f for f in covered.findings if f.rule == "B001"]


def test_compile_audit_unexpected_module_is_b001():
    import jax
    import numpy as np

    from cuvite_tpu.analysis.jaxpr_audit import audit_entry

    def interloper_program(x):
        return x + 1

    jitted = jax.jit(interloper_program)

    def run(seed):
        jitted(np.full(3, seed, np.float32))  # same shapes: one compile

    res = audit_entry("closed_set", run,
                      {"modules": ["something_else"],
                       "content_independent": True})
    rules = [f.rule for f in res.findings]
    assert "B001" in rules and "B002" not in rules


def test_jaxpr_lint_flags_wide_dtypes_and_callbacks():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from cuvite_tpu.analysis.jaxpr_audit import lint_jaxpr

    def clean(x):
        return jnp.sum(x * 2)

    jaxpr = jax.make_jaxpr(clean)(np.ones(8, np.float32))
    assert lint_jaxpr(jaxpr, "clean") == []

    def with_callback(x):
        return jax.pure_callback(
            lambda v: np.asarray(v),
            jax.ShapeDtypeStruct(x.shape, x.dtype), x)

    jaxpr = jax.make_jaxpr(with_callback)(np.ones(8, np.float32))
    hits = lint_jaxpr(jaxpr, "with_callback")
    assert [f.rule for f in hits] == ["J002"]
    assert hits[0].severity == "high"
    assert lint_jaxpr(jaxpr, "with_callback", allow=("J002",)) == []


def test_jaxpr_lint_recurses_into_subjaxprs():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from cuvite_tpu.analysis.jaxpr_audit import lint_jaxpr

    def body(c):
        i, x = c
        y = jax.pure_callback(
            lambda v: np.asarray(v),
            jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return i + 1, y

    def looped(x):
        return jax.lax.while_loop(lambda c: c[0] < 3, body, (0, x))

    jaxpr = jax.make_jaxpr(looped)(jnp.ones(4, jnp.float32))
    assert any(f.rule == "J002"
               for f in lint_jaxpr(jaxpr, "looped"))
