"""Serving-layer tests (ISSUE 9): queue semantics, CLI paths, the
``--many`` workload generator, and the ``batch`` bench schema + gate.

The queue tests drive an injected clock, so linger deadlines are
deterministic and no test sleeps.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from cuvite_tpu.core.batch import slab_class_of
from cuvite_tpu.io.generate import generate_rmat
from cuvite_tpu.louvain.driver import louvain_many
from cuvite_tpu.serve import LouvainServer, ServeConfig
from cuvite_tpu.workloads.bench import validate_record
from cuvite_tpu.workloads.synth import (
    many_seed,
    synthesize_graph,
    synthesize_many,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PERF_REGRESS = os.path.join(REPO, "tools", "perf_regress.py")


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


@pytest.fixture(scope="module")
def small_graphs():
    return [synthesize_graph(1024, seed=many_seed(3, k)) for k in range(5)]


# ---------------------------------------------------------------------------
# Queue discipline


def test_full_bin_dispatches_immediately(small_graphs):
    clock = FakeClock()
    srv = LouvainServer(ServeConfig(b_max=2, linger_s=10.0), clock=clock)
    srv.submit(small_graphs[0])
    assert srv.step() == []          # one job, linger not reached
    srv.submit(small_graphs[1])
    done = srv.step()                # bin full at b_max=2
    assert [jid for jid, _ in done] == ["job-0", "job-1"]
    assert srv.pending() == 0
    assert srv.stats.batches == 1 and srv.stats.pack_util == 1.0
    assert srv.stats.linger_dispatches == 0


def test_linger_deadline_dispatches_partial(small_graphs):
    clock = FakeClock()
    srv = LouvainServer(ServeConfig(b_max=8, linger_s=0.5), clock=clock)
    jid = srv.submit(small_graphs[0])
    assert srv.step() == []          # fresh: waits for batch mates
    clock.t += 0.6                   # oldest job passes the deadline
    done = srv.step()
    assert [j for j, _ in done] == [jid]
    assert srv.stats.linger_dispatches == 1
    # A lone job pads to the B=1 rung: no padding tax.
    assert srv.stats.pack_util == 1.0


def test_classes_bin_separately(small_graphs):
    big = generate_rmat(13, edge_factor=8, seed=1)
    assert slab_class_of(big) != slab_class_of(small_graphs[0])
    srv = LouvainServer(ServeConfig(b_max=4, linger_s=0.0),
                        clock=FakeClock())
    srv.submit(small_graphs[0])
    srv.submit(big)
    srv.submit(small_graphs[1])
    done = dict(srv.drain())
    assert len(done) == 3
    assert srv.stats.batches == 2, "two classes -> two batches"


def test_serve_results_match_direct_runs(small_graphs):
    srv = LouvainServer(ServeConfig(b_max=4, linger_s=0.0),
                        clock=FakeClock())
    ids = [srv.submit(g) for g in small_graphs[:4]]
    done = dict(srv.drain())
    for jid, g in zip(ids, small_graphs):
        direct = louvain_many([g]).results[0]
        assert done[jid].modularity == direct.modularity
        assert np.array_equal(done[jid].communities, direct.communities)
    assert srv.stats.jobs_done == 4 and srv.stats.jobs_per_s > 0


def test_pack_span_and_tenant_events(small_graphs):
    from cuvite_tpu.obs import MemoryTraceSink, FlightRecorder, spans_of
    from cuvite_tpu.utils.trace import Tracer

    sink = MemoryTraceSink()
    rec = FlightRecorder(sink, watch_compiles=False)
    srv = LouvainServer(ServeConfig(b_max=2, linger_s=0.0),
                        clock=FakeClock(), tracer=Tracer(recorder=rec))
    with rec:
        srv.submit(small_graphs[0])
        srv.submit(small_graphs[1])
        srv.step()
    packs = spans_of(sink.records, "pack")
    assert len(packs) == 1
    pk = packs[0]
    assert pk["begin"]["attrs"]["jobs"] == 2
    assert pk["begin"]["attrs"]["b_pad"] == 2
    assert pk["begin"]["attrs"]["trigger"] == "full"
    # ISSUE 10: the span says which engine packed the batch and what
    # the batch's jobs waited (per-batch percentiles).
    assert pk["begin"]["attrs"]["engine"] == "bucketed"
    assert {"wait_p50_s", "wait_p95_s"} <= set(pk["begin"]["attrs"])
    assert pk["end"] is not None and "wall_s" in pk["end"]["attrs"]
    tenants = [r for r in sink.records
               if r.get("t") == "event" and r.get("name") == "tenant_result"]
    assert len(tenants) == 2
    assert {"job_id", "q", "phases", "communities",
            "wait_s"} <= set(tenants[0]["attrs"])


def test_queue_wait_percentiles(small_graphs):
    """Queue-wait latency (enqueue -> dispatch) on the injected clock:
    p50/p95 over the dispatched jobs, surfaced in the serve summary."""
    clock = FakeClock()
    srv = LouvainServer(ServeConfig(b_max=4, linger_s=0.5), clock=clock)
    srv.submit(small_graphs[0])   # will wait 0.7 s
    clock.t += 0.4
    srv.submit(small_graphs[1])   # will wait 0.3 s
    clock.t += 0.3                # oldest passes the 0.5 s deadline
    done = srv.step()
    assert len(done) == 2 and srv.stats.linger_dispatches == 1
    waits = sorted(srv.stats.wait_samples)
    assert waits == pytest.approx([0.3, 0.7])
    assert srv.stats.wait_p50_s == pytest.approx(0.3)
    assert srv.stats.wait_p95_s == pytest.approx(0.7)
    summary = srv.stats.to_dict()
    assert summary["wait_p50_ms"] == pytest.approx(300.0)
    assert summary["wait_p95_ms"] == pytest.approx(700.0)


def test_wait_percentile_estimator():
    from cuvite_tpu.serve.queue import percentile

    assert percentile([], 95.0) == 0.0
    assert percentile([5.0], 50.0) == 5.0
    xs = list(range(1, 101))
    assert percentile(xs, 50.0) == 50
    assert percentile(xs, 95.0) == 95
    assert percentile(xs, 100.0) == 100


def test_serve_sticky_bucket_geometry(small_graphs):
    """The queue pins each class's bucket geometry to the grow-only
    union of everything it has served: after two batches of different
    degree mixes, a third batch whose needs fit the union compiles
    NOTHING (no per-batch geometry churn in the serving hot path)."""
    from cuvite_tpu.core.batch import bucket_shape_for
    from cuvite_tpu.obs import CompileWatcher

    rmats = [generate_rmat(8, edge_factor=8, seed=s) for s in (21, 22)]
    clock = FakeClock()
    srv = LouvainServer(ServeConfig(b_max=2, linger_s=0.0), clock=clock)
    for g in small_graphs[:2]:
        srv.submit(g)
    srv.step()
    cls = slab_class_of(small_graphs[0])
    first = srv._shapes[cls]
    for g in rmats:          # same class, different degree histogram
        srv.submit(g)
    srv.step()
    grown = srv._shapes[cls]
    assert grown.fits(first), "sticky shape must only grow"
    assert grown.fits(bucket_shape_for(rmats))
    # A repeat mix inside the union reuses both compiled programs.
    for g in [small_graphs[2], rmats[0]]:
        srv.submit(g)
    with CompileWatcher() as watch:
        done = srv.step()
    assert len(done) == 2
    assert watch.compiles == [], \
        f"geometry inside the sticky union recompiled: {watch.compiles}"


def test_serve_engine_selection(small_graphs):
    """ServeConfig.engine reaches the batched driver (default
    'bucketed'; 'fused' keeps PR 9's program) and bogus engines refuse
    at config time, not mid-dispatch."""
    with pytest.raises(ValueError, match="engine"):
        ServeConfig(engine="sorted")
    srv = LouvainServer(ServeConfig(b_max=2, linger_s=0.0,
                                    engine="fused"), clock=FakeClock())
    ids = [srv.submit(g) for g in small_graphs[:2]]
    done = dict(srv.drain())
    for jid, g in zip(ids, small_graphs):
        direct = louvain_many([g], engine="fused").results[0]
        assert done[jid].modularity == direct.modularity
        assert np.array_equal(done[jid].communities, direct.communities)


def test_poison_job_isolated_not_batch_fatal(small_graphs):
    """A job whose packing/clustering raises must not take its
    batchmates down or vanish: the batch splits, good jobs complete,
    the poison job lands in server.failures."""
    from cuvite_tpu.core.graph import Graph

    poison = Graph.from_edges(4, np.array([0]), np.array([1]),
                              weights=np.array([0.0]))  # 2m == 0
    srv = LouvainServer(ServeConfig(b_max=4, linger_s=0.0),
                        clock=FakeClock())
    good = [srv.submit(g) for g in small_graphs[:2]]
    bad = srv.submit(poison)
    done = dict(srv.drain())
    assert set(done) == set(good), "batchmates must survive"
    assert srv.stats.jobs_failed == 1
    assert [jid for jid, _ in srv.failures] == [bad]
    assert srv.pending() == 0, "a poison job must never re-queue"
    for jid, g in zip(good, small_graphs):
        assert np.array_equal(done[jid].communities,
                              louvain_many([g]).results[0].communities)


def test_accumulator_classes_bin_separately(small_graphs):
    """A ds32-scale tenant must not drag same-shape f32 tenants onto
    the ds32 program (it would silently change their results vs solo):
    the queue bins by accumulator class, and the packer refuses a
    mixed batch outright."""
    from cuvite_tpu.core.graph import Graph
    from cuvite_tpu.louvain.batched import accum_class_of, cluster_many

    heavy = Graph.from_edges(
        8, np.array([0, 1]), np.array([1, 2]),
        weights=np.array([2.0 ** 25, 2.0 ** 25]))
    light = small_graphs[0]
    assert accum_class_of(heavy) == "ds32"
    assert accum_class_of(light) == "float32"
    assert slab_class_of(heavy) == slab_class_of(light)
    with pytest.raises(ValueError, match="mixed accumulator"):
        cluster_many([light, heavy])
    srv = LouvainServer(ServeConfig(b_max=4, linger_s=0.0),
                        clock=FakeClock())
    srv.submit(light)
    srv.submit(heavy)
    done = dict(srv.drain())
    assert len(done) == 2 and srv.stats.batches == 2
    assert srv.stats.jobs_failed == 0


def test_b_max_rounds_to_ladder_rung():
    # ISSUE 11 satellite: the clamp is no longer silent — rounding to a
    # rung warns (a clamped b_max=1000 serving 64-row batches would
    # otherwise mislead capacity planning); exact rungs stay quiet.
    with pytest.warns(UserWarning, match="BATCH_SIZES rung"):
        assert ServeConfig(b_max=10).b_max == 16
    with pytest.warns(UserWarning, match="BATCH_SIZES rung"):
        assert ServeConfig(b_max=1000).b_max == 64
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")
        assert ServeConfig(b_max=64).b_max == 64
    with pytest.raises(ValueError):
        ServeConfig(b_max=0)


def test_config_validates_at_config_time():
    """ISSUE 11 satellite: linger/threshold/retry knobs refuse at
    ServeConfig construction, not deep in the driver mid-dispatch."""
    with pytest.raises(ValueError, match="linger_s"):
        ServeConfig(linger_s=-0.1)
    with pytest.raises(ValueError, match="threshold"):
        ServeConfig(threshold=0.0)
    with pytest.raises(ValueError, match="threshold"):
        ServeConfig(threshold=-1e-6)
    with pytest.raises(ValueError, match="max_retries"):
        ServeConfig(max_retries=-1)
    with pytest.raises(ValueError, match="retry_base_s"):
        ServeConfig(retry_base_s=-0.5)
    with pytest.raises(ValueError, match="admission"):
        ServeConfig(admission="please")


# ---------------------------------------------------------------------------
# CLI paths


def test_cluster_many_cli(tmp_path, capsys):
    from cuvite_tpu.serve.__main__ import main as serve_main

    prefix = str(tmp_path / "set")
    synthesize_many(prefix, 2, 1024, seed=5, write_truth=False)
    files = [f"{prefix}_{k:04d}.vite" for k in range(2)]
    rc = serve_main(["cluster-many", *files, "--output", "--json",
                     "--host-devices", "1", "--b-max", "2",
                     "--linger-ms", "0"])
    assert rc == 0
    lines = [json.loads(x) for x in
             capsys.readouterr().out.strip().splitlines()]
    summary = lines[-1]["summary"]
    assert summary["jobs_done"] == 2 and summary["batches"] == 1
    for f in files:
        out = f + ".communities"
        assert os.path.exists(out)
        labels = np.loadtxt(out, dtype=np.int64)
        assert labels.ndim == 1 and labels.min() >= 0


# ---------------------------------------------------------------------------
# synth --many


def test_synth_many_deterministic_and_distinct(tmp_path):
    p1 = synthesize_many(str(tmp_path / "a"), 3, 1024, seed=9,
                         write_truth=False)
    p2 = synthesize_many(str(tmp_path / "b"), 3, 1024, seed=9,
                         write_truth=False)
    sha1 = [m["sha256"] for m in p1["graphs"]]
    sha2 = [m["sha256"] for m in p2["graphs"]]
    assert sha1 == sha2, "same (seed, index) must be byte-identical"
    assert len(set(sha1)) == 3, "distinct streams per member"
    # ONE provenance file for the set, naming every member.
    setp = json.load(open(str(tmp_path / "a") + ".many.provenance.json"))
    assert setp["source"] == "synthesized-many" and setp["count"] == 3
    assert len(setp["graphs"]) == 3
    # member k is independent of the set size K
    assert many_seed(9, 1) == many_seed(9, 1)
    assert many_seed(9, 1) != many_seed(9, 2)


def test_synthesize_graph_matches_stream(small_graphs):
    g1 = synthesize_graph(1024, seed=many_seed(3, 0))
    assert g1.num_vertices == small_graphs[0].num_vertices
    assert np.array_equal(g1.tails, small_graphs[0].tails)


# ---------------------------------------------------------------------------
# `batch` bench block + perf_regress gate


@pytest.fixture(scope="module")
def batch_record():
    from cuvite_tpu.workloads.bench import run_batch_bench

    return run_batch_bench(B=2, n_jobs=4, edges=1024, repeats=1,
                           budget_s=600.0, platform="cpu")


def test_batch_record_schema_valid(batch_record):
    assert validate_record(batch_record) == []
    blk = batch_record["batch"]
    assert blk["B"] == 2 and blk["n_jobs"] == 4 and blk["batches"] == 2
    assert blk["pack_util"] == 1.0
    assert blk["jobs_per_s"] > 0
    assert blk["class"] == list((4096, 16384))
    assert batch_record["engine"] == "batched"


def test_batch_block_validation_rejects_malformed(batch_record):
    rec = dict(batch_record)
    rec["batch"] = {"B": 2, "jobs_per_s": 5.0}  # pack_util missing
    assert any("pack_util" in p for p in validate_record(rec))
    rec["batch"] = dict(batch_record["batch"], pack_util=1.5)
    assert any("pack_util" in p for p in validate_record(rec))
    rec["batch"] = dict(batch_record["batch"], jobs_per_s=0)
    assert any("jobs_per_s" in p for p in validate_record(rec))
    rec["batch"] = dict(batch_record["batch"], B="two")
    assert any("batch.B" in p for p in validate_record(rec))
    # ISSUE 10: a PRESENT engine tag must be a known batched engine; a
    # MISSING one is tolerated (pre-ISSUE-10 v4 batch records could
    # only be fused, and perf_regress defaults them exactly so — a
    # historical round log must not retroactively fail --self-check).
    rec["batch"] = dict(batch_record["batch"], engine="sorted")
    assert any("batch.engine" in p for p in validate_record(rec))
    noeng = dict(batch_record["batch"])
    del noeng["engine"]
    rec["batch"] = noeng
    assert validate_record(rec) == []


def _round_log(path, rec, n=97):
    with open(path, "w") as f:
        json.dump({"n": n, "cmd": "test", "rc": 0, "tail": "",
                   "parsed": rec}, f)


def _gate(tmp_path, fresh, peer):
    fresh_p = tmp_path / "fresh.json"
    fresh_p.write_text(json.dumps(fresh))
    _round_log(tmp_path / "BENCH_r97.json", peer)
    return subprocess.run(
        [sys.executable, PERF_REGRESS, "--record", str(fresh_p),
         "--bench-glob", str(tmp_path / "BENCH_r9*.json")],
        capture_output=True, text=True, timeout=120)


def test_perf_regress_gates_jobs_per_s(tmp_path, batch_record):
    peer = json.loads(json.dumps(batch_record))
    peer["batch"]["jobs_per_s"] = batch_record["batch"]["jobs_per_s"] * 2
    out = _gate(tmp_path, batch_record, peer)
    assert out.returncode == 1, out.stdout + out.stderr
    assert "batch jobs_per_s" in out.stderr


def test_perf_regress_passes_like_for_like(tmp_path, batch_record):
    out = _gate(tmp_path, batch_record, json.loads(
        json.dumps(batch_record)))
    assert out.returncode == 0, out.stdout + out.stderr


def test_perf_regress_ignores_other_batch_configs(tmp_path, batch_record):
    """A record at a different B (or a non-batch record) is not a peer:
    first record of a new serving config is a baseline."""
    peer = json.loads(json.dumps(batch_record))
    peer["batch"]["B"] = 64
    peer["batch"]["jobs_per_s"] = 1e9
    peer["value"] = batch_record["value"] * 100
    out = _gate(tmp_path, batch_record, peer)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 comparable" in out.stdout


def test_perf_regress_separates_batch_engines(tmp_path, batch_record):
    """ISSUE 10: fused and bucketed serving trajectories never gate
    each other — a bucketed record several-x above the fused one must
    not flag a fresh fused record (same B, same class)."""
    peer = json.loads(json.dumps(batch_record))
    peer["batch"]["engine"] = "bucketed"
    peer["batch"]["jobs_per_s"] = \
        batch_record["batch"]["jobs_per_s"] * 100
    out = _gate(tmp_path, batch_record, peer)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 comparable" in out.stdout


# ---------------------------------------------------------------------------
# `serve` bench block (open-loop load generator) + perf_regress gate


@pytest.fixture(scope="module")
def serve_record():
    import time as _time

    from cuvite_tpu.workloads.bench import run_serve_bench

    return run_serve_bench(
        rate=200.0, b_max=2, edges=512, n_jobs=4, slo_ms=60000.0,
        admission=True, linger_ms=1.0, budget_s=600.0, platform="cpu",
        t_start=_time.perf_counter())


def test_serve_record_schema_valid(serve_record):
    assert validate_record(serve_record) == []
    blk = serve_record["serve"]
    assert blk["b_max"] == 2 and blk["offered"] == 4
    assert blk["done"] == 4 and blk["rejected"] == 0
    assert blk["goodput_jobs_per_s"] > 0
    assert blk["admission"] is True and blk["slo_met"] is True
    assert blk["reject_rate"] == 0.0 and blk["shed_rate"] == 0.0
    assert serve_record["engine"] == "batched"
    assert serve_record["compile_guard"] == {"checked": True,
                                             "new_compiles": 0}


def test_serve_block_validation_rejects_malformed(serve_record):
    rec = json.loads(json.dumps(serve_record))
    rec["serve"] = {"b_max": 2}
    assert any("goodput_jobs_per_s" in p for p in validate_record(rec))
    rec["serve"] = dict(serve_record["serve"], reject_rate=1.5)
    assert any("reject_rate" in p for p in validate_record(rec))
    rec["serve"] = dict(serve_record["serve"], admission="yes")
    assert any("admission" in p for p in validate_record(rec))
    rec["serve"] = dict(serve_record["serve"], goodput_jobs_per_s=0)
    assert any("goodput_jobs_per_s" in p for p in validate_record(rec))
    rec["serve"] = dict(serve_record["serve"], engine="sorted")
    assert any("serve.engine" in p for p in validate_record(rec))


def test_perf_regress_gates_serve_goodput(tmp_path, serve_record):
    peer = json.loads(json.dumps(serve_record))
    peer["serve"]["goodput_jobs_per_s"] = \
        serve_record["serve"]["goodput_jobs_per_s"] * 2
    out = _gate(tmp_path, serve_record, peer)
    assert out.returncode == 1, out.stdout + out.stderr
    assert "serve goodput_jobs_per_s" in out.stderr


def test_perf_regress_serve_like_for_like(tmp_path, serve_record):
    out = _gate(tmp_path, serve_record, json.loads(
        json.dumps(serve_record)))
    assert out.returncode == 0, out.stdout + out.stderr


def test_perf_regress_separates_admission_arms(tmp_path, serve_record):
    """The admission-off overload arm is a DIFFERENT experiment (its
    goodput can be much higher or lower at the same rate); it must
    never gate the admission-on trajectory."""
    peer = json.loads(json.dumps(serve_record))
    peer["serve"]["admission"] = False
    peer["serve"]["goodput_jobs_per_s"] = \
        serve_record["serve"]["goodput_jobs_per_s"] * 100
    out = _gate(tmp_path, serve_record, peer)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 comparable" in out.stdout


def test_perf_regress_ignores_subsaturation_serve_runs(tmp_path,
                                                       serve_record):
    """Below saturation, goodput (and the rate-paced TEPS value) track
    the OFFERED rate, not server capacity: a conservative low-rate run
    must not trip against a saturated round's numbers."""
    fresh = json.loads(json.dumps(serve_record))
    fresh["serve"]["arrival_jobs_per_s"] = 10.0
    fresh["serve"]["goodput_jobs_per_s"] = 9.8   # ~= offered: unsaturated
    fresh["value"] = 1.0                         # rate-paced wall
    peer = json.loads(json.dumps(serve_record))
    peer["serve"]["goodput_jobs_per_s"] = \
        serve_record["serve"]["goodput_jobs_per_s"] * 100
    peer["value"] = serve_record["value"] * 100
    out = _gate(tmp_path, fresh, peer)
    assert out.returncode == 0, out.stdout + out.stderr


def test_perf_regress_serve_vs_batch_never_compare(tmp_path, serve_record,
                                                   batch_record):
    """A serve record and a batch record are different benches: the
    batch trajectory must not gate a fresh serve record."""
    peer = json.loads(json.dumps(batch_record))
    peer["value"] = serve_record["value"] * 100
    out = _gate(tmp_path, serve_record, peer)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 comparable" in out.stdout


def test_perf_regress_legacy_batch_records_gate_as_fused(tmp_path,
                                                         batch_record):
    """A pre-ISSUE-10 trajectory batch record (no engine tag) ran the
    fused loop; it must keep gating fresh FUSED records — a missing tag
    must not silently reset the fused serving baseline."""
    peer = json.loads(json.dumps(batch_record))
    del peer["batch"]["engine"]
    del peer["schema"]   # legacy rounds predate strict v4 validation
    peer["batch"]["jobs_per_s"] = \
        batch_record["batch"]["jobs_per_s"] * 2
    out = _gate(tmp_path, batch_record, peer)
    assert out.returncode == 1, out.stdout + out.stderr
    assert "batch jobs_per_s" in out.stderr
