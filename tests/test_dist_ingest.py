"""Per-host sharded ingest (io/dist_ingest.DistVite).

Single-process, all shards are local, so DistVite must reproduce the
full-ingest DistGraph pipeline exactly: same partition, same slabs, same
final communities.  The 2-process variant lives in test_multihost.py.
"""

import numpy as np
import pytest

from cuvite_tpu.core.distgraph import DistGraph
from cuvite_tpu.io.dist_ingest import DistVite
from cuvite_tpu.io.vite import write_vite
from cuvite_tpu.louvain.driver import louvain_phases


@pytest.fixture(scope="module")
def karate_bin(tmp_path_factory):
    import networkx as nx

    from cuvite_tpu.core.graph import Graph

    e = np.array(nx.karate_club_graph().edges(), dtype=np.int64)
    g = Graph.from_edges(34, e[:, 0], e[:, 1])
    p = str(tmp_path_factory.mktemp("dv") / "karate.bin")
    write_vite(p, g)
    return p, g


def test_distvite_matches_distgraph_layout(karate_bin):
    path, g = karate_bin
    dv = DistVite.load(path, 4, min_nv_pad=1024, min_ne_pad=4096)
    dg = DistGraph.build(g, 4, min_nv_pad=1024, min_ne_pad=4096)
    assert dv.nv_pad == dg.nv_pad and dv.ne_pad == dg.ne_pad
    assert np.array_equal(dv.parts, dg.parts)
    assert np.array_equal(dv.old_to_pad, dg.old_to_pad)
    assert np.array_equal(dv.pad_to_old, dg.pad_to_old)
    assert np.allclose(dv.padded_weighted_degrees(),
                       dg.padded_weighted_degrees())
    assert dv.graph.total_edge_weight_twice() == pytest.approx(
        g.total_edge_weight_twice())
    for s in range(4):
        assert np.array_equal(dv.shards[s].src, dg.shards[s].src)
        assert np.array_equal(dv.shards[s].dst, dg.shards[s].dst)
        assert np.allclose(dv.shards[s].w, dg.shards[s].w)
        assert dv.shards[s].n_real_edges == dg.shards[s].n_real_edges


def test_distvite_run_matches_full_ingest(karate_bin):
    path, g = karate_bin
    dv = DistVite.load(path, 8)
    res_dv = louvain_phases(dv)
    res_full = louvain_phases(g, nshards=8)
    assert np.array_equal(res_dv.communities, res_full.communities)
    assert res_dv.modularity == pytest.approx(res_full.modularity, abs=1e-9)


def test_distvite_balanced_parts(karate_bin):
    path, g = karate_bin
    dv = DistVite.load(path, 4, balanced=True)
    dg = DistGraph.build(g, 4, balanced=True)
    assert np.array_equal(dv.parts, dg.parts)


def test_distvite_modularity_oracle(karate_bin):
    path, g = karate_bin
    from cuvite_tpu.evaluate.modularity import modularity

    dv = DistVite.load(path, 4)
    # identity assignment in padded space
    ident = np.arange(dv.total_padded_vertices, dtype=np.int64)
    q_dv = dv.modularity(ident)
    q_ref = modularity(g, np.arange(g.num_vertices))
    assert q_dv == pytest.approx(q_ref, abs=1e-12)


def test_distvite_rejects_unsupported_modes(karate_bin):
    path, _ = karate_bin
    dv = DistVite.load(path, 8)
    with pytest.raises(ValueError, match="sparse"):
        louvain_phases(dv, exchange="replicated")
    with pytest.raises(ValueError, match="bucketed"):
        louvain_phases(dv, engine="sort")


def test_distvite_coloring_matches_full_ingest(karate_bin):
    """Distributed coloring rounds (multi_hash_coloring_dist) + per-class
    stacked plans on the per-host partition: colors AND the full -c/-d
    clustering are bit-identical to the full-ingest run (VERDICT r4 item
    7; the reference's distributed coloring, coloring.cpp:204-420)."""
    from cuvite_tpu.louvain.coloring import (
        multi_hash_coloring, multi_hash_coloring_dist,
    )

    path, g = karate_bin
    dv = DistVite.load(path, 8)
    colors_dist, nc_dist = multi_hash_coloring_dist(dv, n_hash=2)
    colors_full, nc_full = multi_hash_coloring(
        g.sources().astype(np.int32), g.tails.astype(np.int32),
        g.num_vertices, n_hash=2)
    assert nc_dist == nc_full
    assert np.array_equal(colors_dist, colors_full)

    for kw in ({"coloring": 4}, {"vertex_ordering": 4}):
        res_dv = louvain_phases(dv, **kw)
        res_full = louvain_phases(g, nshards=8, **kw)
        assert np.array_equal(res_dv.communities, res_full.communities), kw
        assert res_dv.modularity == pytest.approx(
            res_full.modularity, abs=1e-9)


def test_distvite_checkpoint_resume(karate_bin, tmp_path):
    """Checkpoint fingerprints from per-shard content hashes: a DistVite
    run checkpoints per phase, resumes to the uninterrupted result, and a
    different graph's checkpoint is rejected (VERDICT r4 item 7)."""
    path, g = karate_bin
    dv = DistVite.load(path, 8)
    full = louvain_phases(dv)
    ckpt = str(tmp_path / "ck")
    part = louvain_phases(dv, checkpoint_dir=ckpt, max_phases=1)
    assert len(part.phases) == 1  # actually stopped early
    res = louvain_phases(dv, checkpoint_dir=ckpt, resume=True)
    assert np.array_equal(res.communities, full.communities)
    assert res.modularity == pytest.approx(full.modularity, abs=1e-12)

    # fingerprint guard: a checkpoint from ANOTHER graph is rejected
    from cuvite_tpu.core.graph import Graph
    from cuvite_tpu.io.vite import write_vite

    ring = np.arange(16, dtype=np.int64)
    other = Graph.from_edges(16, ring, (ring + 1) % 16)
    p2 = str(tmp_path / "ring.bin")
    write_vite(p2, other)
    dv2 = DistVite.load(p2, 8)
    with pytest.raises(ValueError, match="fingerprint"):
        louvain_phases(dv2, checkpoint_dir=ckpt, resume=True)
