"""Segmented-coalesce engines (ISSUE 8): kernels/seg_coalesce.py +
ops/segment.coalesced_runs + the device_coarsen_slab dispatch.

The packed-sort path is the bit-parity oracle: the dense dst-tile
engines (Pallas kernel, interpret mode on CPU, and its XLA scatter
twin) must reproduce its compacted (src, dst, w) prefix BIT-for-bit —
offsets/tails always (run presence is exact in every mode), weights on
the documented exactness domain (unit/dyadic run sums).  The
packed-sort key-width contract of ops/segment.py is pinned at its
edges here too (the widest legal 31-bit packing, the first ineligible
width, and the CUVITE_DEBUG_BOUNDS violation callback).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import cuvite_tpu.ops.segment as seg
from cuvite_tpu.kernels.seg_coalesce import coalesce_engine
from cuvite_tpu.ops.segment import coalesced_runs

def _slab(nv_pad, ne_pad, seed, gapped=False, self_loops=True,
          zero_weight=True):
    """A relabeled-slab-shaped triple: real rows in a prefix, padding
    (src == nv_pad, dst == 0, w == 0) after; dyadic weights (exactness
    domain).  ``gapped``: ids drawn from a sparse subset of the space
    (the renumber's hard case leaves no gaps, but coalesced_runs must
    not assume density)."""
    rng = np.random.default_rng(seed)
    n_real = ne_pad - ne_pad // 5
    pool = (rng.choice(nv_pad, size=max(nv_pad // 11, 2), replace=False)
            if gapped else np.arange(nv_pad))
    src = np.full(ne_pad, nv_pad, np.int32)
    dst = np.zeros(ne_pad, np.int32)
    w = np.zeros(ne_pad, np.float32)
    src[:n_real] = rng.choice(pool, size=n_real)
    dst[:n_real] = rng.choice(pool, size=n_real)
    if self_loops:
        src[: n_real // 8] = dst[: n_real // 8]  # heavy self-loop runs
    w[:n_real] = rng.integers(1, 64, n_real) / 8.0
    if zero_weight:
        w[n_real // 2: n_real // 2 + 37] = 0.0  # real zero-weight edges
    return tuple(jnp.asarray(x) for x in (src, dst, w))


@pytest.mark.parametrize("nv_pad,ne_pad,gapped", [
    # ≥3 slab classes; gapped (sparse) id spaces on the floor class only
    # — id sparsity is engine-invariant, one class covers it.
    # [floor-gapped]/[wide-slab] are tier-2 (slow): the identity they
    # pin is class-shape-invariant and [floor] keeps it in tier-1 at a
    # third of the wall; gapped-id handling stays covered in tier-1 by
    # the sticky-union/concheck gapped scenarios.
    (4096, 16384, False),
    pytest.param(4096, 16384, True, marks=pytest.mark.slow),
    pytest.param(4096, 65536, False, marks=pytest.mark.slow),
    (1024, 16384, False),
], ids=["floor", "floor-gapped", "wide-slab", "narrow-nv"])
def test_dense_engines_bit_identical_to_sort(nv_pad, ne_pad, gapped):
    arrs = _slab(nv_pad, ne_pad, seed=nv_pad + ne_pad, gapped=gapped)
    ref = jax.device_get(coalesced_runs(*arrs, nv_pad=nv_pad,
                                        engine="sort"))
    for engine in ("xla", "pallas"):
        got = jax.device_get(coalesced_runs(*arrs, nv_pad=nv_pad,
                                            engine=engine))
        for r, g, name in zip(ref, got, ("src", "dst", "w", "n")):
            assert np.array_equal(r, g), (engine, name)
    # Tail sentinel contract: padding after the compacted prefix.
    src_c, dst_c, w_c, n = ref
    n = int(n)
    assert (src_c[n:] == nv_pad).all()
    assert (dst_c[n:] == 0).all()
    assert (w_c[n:] == 0).all()
    # The prefix is strictly (src, dst)-sorted: distinct packed keys.
    keys = src_c[:n].astype(np.int64) * nv_pad + dst_c[:n]
    assert (np.diff(keys) > 0).all()


def test_zero_weight_runs_emitted_by_presence():
    """A real zero-weight edge is a run (presence, not weight) in every
    engine — dropping it would change the coarse offsets."""
    nv_pad, ne_pad = 1024, 16384
    src = np.full(ne_pad, nv_pad, np.int32)
    dst = np.zeros(ne_pad, np.int32)
    w = np.zeros(ne_pad, np.float32)
    src[:3] = [5, 7, 9]
    dst[:3] = [6, 8, 10]
    w[:3] = [1.0, 0.0, 2.0]  # the (7, 8) run weighs exactly 0
    arrs = tuple(jnp.asarray(x) for x in (src, dst, w))
    for engine in ("sort", "xla", "pallas"):
        src_c, dst_c, w_c, n = jax.device_get(
            coalesced_runs(*arrs, nv_pad=nv_pad, engine=engine))
        assert int(n) == 3, engine
        assert list(src_c[:3]) == [5, 7, 9] and w_c[1] == 0.0, engine


def test_device_coarsen_slab_dense_vs_sort_bitwise(two_cliques):
    """Through the real consumer: device_coarsen_slab with the dense
    engines produces the identical 6-tuple (slab, dense_map, nc, ne2)."""
    from cuvite_tpu.coarsen.device import device_coarsen_slab
    from cuvite_tpu.core.distgraph import DistGraph

    dg = DistGraph.build(two_cliques, 1)
    sh = dg.shards[0]
    lab = np.arange(dg.nv_pad, dtype=np.int64)
    lab[:5] = 0
    lab[5:10] = 5
    args = (jnp.asarray(np.asarray(sh.src)), jnp.asarray(np.asarray(sh.dst)),
            jnp.asarray(np.asarray(sh.w)),
            jnp.asarray(lab.astype(np.asarray(sh.src).dtype)),
            jnp.asarray(dg.vertex_mask()))
    ref = jax.device_get(device_coarsen_slab(*args, nv_pad=dg.nv_pad,
                                             coalesce="sort"))
    for engine in ("xla", "pallas"):
        got = jax.device_get(device_coarsen_slab(*args, nv_pad=dg.nv_pad,
                                                 coalesce=engine))
        for r, g in zip(ref, got):
            assert np.array_equal(r, g), engine


def test_coalesce_engine_policy(monkeypatch):
    monkeypatch.delenv("CUVITE_SEG_COALESCE", raising=False)
    # Default: the packed sort stays the workhorse until the staged chip
    # A/B promotes a dense engine (measured rationale in the module).
    assert coalesce_engine(4096) == "sort"
    monkeypatch.setenv("CUVITE_SEG_COALESCE", "xla")
    assert coalesce_engine(4096) == "xla"
    # ds32 run sums need the sorted pair arithmetic — degrade in every
    # mode.
    assert coalesce_engine(4096, seg.DS_ACCUM) == "sort"
    # Domain over the accumulator budget (nv_pad > MAX_NV) -> degrade.
    assert coalesce_engine(1 << 16) == "sort"
    monkeypatch.setenv("CUVITE_SEG_COALESCE_MAX_NV", "1024")
    assert coalesce_engine(4096) == "sort"
    assert coalesce_engine(1024) == "xla"
    monkeypatch.delenv("CUVITE_SEG_COALESCE_MAX_NV")
    monkeypatch.setenv("CUVITE_SEG_COALESCE", "pallas")
    assert coalesce_engine(4096) == "pallas"
    monkeypatch.setenv("CUVITE_SEG_COALESCE", "0")
    assert coalesce_engine(1024) == "sort"
    # A typo'd pin warns and keeps the default instead of silently
    # measuring the wrong engine.
    monkeypatch.setenv("CUVITE_SEG_COALESCE", "sorr")
    with pytest.warns(UserWarning, match="unrecognized"):
        assert coalesce_engine(1024) == "sort"


def test_coalesced_runs_rejects_ds32_on_dense():
    arrs = _slab(1024, 16384, seed=1)
    with pytest.raises(AssertionError, match="ds32"):
        coalesced_runs(*arrs, nv_pad=1024, accum_dtype=seg.DS_ACCUM,
                       engine="xla")


def test_ds32_sort_fallback_matches_plain_on_exact_domain():
    """ds32 always rides the sort path; on dyadic weights its collapsed
    run sums equal the plain f32 path bit-for-bit."""
    arrs = _slab(1024, 16384, seed=9)
    a = jax.device_get(coalesced_runs(*arrs, nv_pad=1024, engine="sort"))
    b = jax.device_get(coalesced_runs(*arrs, nv_pad=1024, engine="sort",
                                      accum_dtype=seg.DS_ACCUM))
    for x, y in zip(a, b):
        assert np.array_equal(x, y)


# ---------------------------------------------------------------------------
# Full-run integration: the sort engine's device transition with a dense
# coalesce forced must cluster bit-identically, with zero fresh compiles
# on phases 2+ and the same per-phase sync count as the default path.


@pytest.fixture(scope="module")
def rmat10():
    from cuvite_tpu.io.generate import generate_rmat

    g = generate_rmat(10, edge_factor=8, seed=3)
    assert g.num_vertices <= 4096 and g.num_edges <= 16384  # floor class
    return g


def test_sort_engine_dense_coalesce_full_run_identical(rmat10, monkeypatch):
    from cuvite_tpu.louvain.driver import louvain_phases

    monkeypatch.delenv("CUVITE_SEG_COALESCE", raising=False)
    r0 = louvain_phases(rmat10, engine="sort")
    monkeypatch.setenv("CUVITE_SEG_COALESCE", "xla")
    r1 = louvain_phases(rmat10, engine="sort")
    assert len(r0.phases) == len(r1.phases) >= 3
    assert r0.total_iterations == r1.total_iterations
    assert r0.modularity == r1.modularity
    assert np.array_equal(r0.communities, r1.communities)


def test_fused_dense_coalesce_full_run_identical(rmat10, monkeypatch):
    import cuvite_tpu.louvain.driver as drv
    from cuvite_tpu.louvain.driver import louvain_phases

    # Force the one-call-per-phase multilevel path so device_coarsen_slab
    # actually runs between fused calls.
    monkeypatch.setattr(drv, "FUSED_SHRINK_EDGES", 1 << 10)
    monkeypatch.delenv("CUVITE_SEG_COALESCE", raising=False)
    r0 = louvain_phases(rmat10, engine="fused")
    monkeypatch.setenv("CUVITE_SEG_COALESCE", "xla")
    r1 = louvain_phases(rmat10, engine="fused")
    assert len(r0.phases) == len(r1.phases) >= 3
    assert np.array_equal(r0.communities, r1.communities)


def test_dense_coalesce_zero_fresh_compiles_after_phase1(
        rmat10, monkeypatch):
    """The dense path must keep the tentpole compile contract: same pow2
    class across phases => all compiles in phases 0-1, none after."""
    import logging

    from cuvite_tpu.louvain.driver import louvain_phases
    from cuvite_tpu.utils.trace import Tracer

    monkeypatch.setenv("CUVITE_SEG_COALESCE", "xla")
    compiles = []

    class _Grab(logging.Handler):
        def emit(self, record):
            if "Compiling" in record.getMessage():
                compiles.append(record.getMessage())

    import contextlib

    class _Probe(Tracer):
        def __init__(self):
            super().__init__(enabled=True)
            self.marks = []

        @contextlib.contextmanager
        def stage(self, name):
            if name == "iterate":
                self.marks.append(len(compiles))
            with super().stage(name):
                yield

    probe = _Probe()
    handler = _Grab(level=logging.WARNING)
    logger = logging.getLogger("jax")
    logger.addHandler(handler)
    jax.config.update("jax_log_compiles", True)
    try:
        res = louvain_phases(rmat10, engine="sort", tracer=probe)
    finally:
        jax.config.update("jax_log_compiles", False)
        logger.removeHandler(handler)
    assert len(res.phases) >= 3 and len(probe.marks) >= 3
    fresh_after_phase1 = len(compiles) - probe.marks[2]
    assert fresh_after_phase1 == 0, compiles[probe.marks[2]:][:4]


def test_dense_coalesce_adds_no_device_syncs(rmat10, monkeypatch):
    """One sync per phase stays one sync per phase: forcing the dense
    coalesce must not change the run's jax.device_get call count."""
    from cuvite_tpu.louvain.driver import louvain_phases

    def run_counting():
        calls = []
        orig = jax.device_get

        def spy(x):
            calls.append(1)
            return orig(x)

        monkeypatch.setattr(jax, "device_get", spy)
        try:
            res = louvain_phases(rmat10, engine="sort")
        finally:
            monkeypatch.setattr(jax, "device_get", orig)
        return len(calls), res

    monkeypatch.delenv("CUVITE_SEG_COALESCE", raising=False)
    n0, r0 = run_counting()
    monkeypatch.setenv("CUVITE_SEG_COALESCE", "xla")
    n1, r1 = run_counting()
    assert np.array_equal(r0.communities, r1.communities)
    assert n0 == n1


def test_coalesce_stage_and_coverage_counters(rmat10, monkeypatch):
    """coalesce_s splits out of coarsen_s (schema v4) and the coverage
    counters say which engine ran: 0 dense edges by default, all of
    them with the dense engine forced."""
    from cuvite_tpu.louvain.driver import louvain_phases
    from cuvite_tpu.utils.trace import Tracer

    monkeypatch.delenv("CUVITE_SEG_COALESCE", raising=False)
    tr = Tracer()
    louvain_phases(rmat10, engine="sort", tracer=tr)
    bd = tr.breakdown()
    assert "coalesce_s" in bd and 0 < bd["coalesce_s"] <= bd["coarsen_s"]
    assert tr.counters.get("coalesce_edges", 0) > 0
    assert tr.counters.get("coalesce_dense_edges", 0) == 0
    tr2 = Tracer()
    monkeypatch.setenv("CUVITE_SEG_COALESCE", "xla")
    louvain_phases(rmat10, engine="sort", tracer=tr2)
    assert tr2.counters["coalesce_dense_edges"] \
        == tr2.counters["coalesce_edges"] > 0


# ---------------------------------------------------------------------------
# Packed-sort key-width contract (ops/segment.py): the fallback
# chokepoint's edges, pinned (ISSUE 8 satellite).


def _lex_oracle(src, ckey, w):
    order = np.lexsort((np.asarray(ckey), np.asarray(src)))
    return (np.asarray(src)[order], np.asarray(ckey)[order],
            np.asarray(w)[order])


def test_packed_sort_widest_legal_31bit_packing():
    """kbits + sbits == 31 is the widest int32 packing: the top packed
    key is INT32_MAX and must NOT flip the sign bit (segment.py:120).
    Extreme ids at both bounds pin the boundary."""
    rng = np.random.default_rng(2)
    src_bound, key_bound = 1 << 16, 1 << 15   # sbits 16 + kbits 15 == 31
    n = 4096
    src = rng.integers(0, src_bound, n).astype(np.int32)
    ckey = rng.integers(0, key_bound, n).astype(np.int32)
    # Force the extremes: the (max src, max key) row packs to INT32_MAX.
    src[:4] = [src_bound - 1, src_bound - 1, 0, 0]
    ckey[:4] = [key_bound - 1, 0, key_bound - 1, 0]
    w = rng.random(n).astype(np.float32)
    out = jax.device_get(seg.sort_edges_by_vertex_comm(
        jnp.asarray(src), jnp.asarray(ckey), jnp.asarray(w),
        src_bound=src_bound, key_bound=key_bound))
    s_ref, c_ref, _ = _lex_oracle(src, ckey, w)
    assert np.array_equal(out[0], s_ref)
    assert np.array_equal(out[1], c_ref)
    # The last row really is the INT32_MAX packing.
    assert int(out[0][-1]) == src_bound - 1 \
        and int(out[1][-1]) == key_bound - 1


def test_packed_sort_first_ineligible_width_falls_back_correctly():
    """kbits + sbits == 32: one bit past the int32 packing — without
    x64 the sort must take the lexicographic path and still produce the
    exact (src, ckey) order."""
    rng = np.random.default_rng(3)
    src_bound, key_bound = 1 << 16, 1 << 16   # 16 + 16 == 32
    n = 4096
    src = rng.integers(0, src_bound, n).astype(np.int32)
    ckey = rng.integers(0, key_bound, n).astype(np.int32)
    src[:2] = [src_bound - 1, 0]
    ckey[:2] = [key_bound - 1, key_bound - 1]
    w = rng.random(n).astype(np.float32)
    out = jax.device_get(seg.sort_edges_by_vertex_comm(
        jnp.asarray(src), jnp.asarray(ckey), jnp.asarray(w),
        src_bound=src_bound, key_bound=key_bound))
    s_ref, c_ref, _ = _lex_oracle(src, ckey, w)
    assert np.array_equal(out[0], s_ref)
    assert np.array_equal(out[1], c_ref)


@pytest.mark.parametrize("bad", ["src", "ckey"])
def test_packed_sort_bound_violation_callback(bad, monkeypatch):
    """CUVITE_DEBUG_BOUNDS: an id at or above its declared bound trips
    the host callback loudly (a silently corrupted packing would sort
    rows to the FRONT — segment.py's documented failure mode)."""
    monkeypatch.setattr(seg, "DEBUG_BOUNDS", True)
    src = np.array([1, 2, 3], np.int32)
    ckey = np.array([0, 1, 2], np.int32)
    if bad == "src":
        src[0] = 4       # == src_bound
    else:
        ckey[0] = 5      # > key_bound
    w = np.ones(3, np.float32)
    with pytest.raises(AssertionError, match="bound violation"):
        out = seg.sort_edges_by_vertex_comm(
            jnp.asarray(src), jnp.asarray(ckey), jnp.asarray(w),
            src_bound=4, key_bound=4)
        jax.block_until_ready(out)

# ---------------------------------------------------------------------------
# ISSUE 16: the boundary trio generalized from the bare sort to the
# coalesce CHOKEPOINT (coalesced_runs engine='sort' rides the packed
# sort at src_bound = nv_pad + 1, key_bound = nv_pad, so nv_pad = 2^15
# is the widest int32 packing and 2^16 the first ineligible width),
# plus the heavy-layout elems budget and the tier-6 raise-guards.


def _chokepoint_slab(nv_pad, ne_pad, seed):
    """Slab with the extreme (nv_pad-1, nv_pad-1) packing duplicated so
    coalescing must SUM across the widest key, dyadic weights (exact)."""
    rng = np.random.default_rng(seed)
    n_real = ne_pad - ne_pad // 7
    src = np.full(ne_pad, nv_pad, np.int32)
    dst = np.zeros(ne_pad, np.int32)
    w = np.zeros(ne_pad, np.float32)
    src[:n_real] = rng.integers(0, nv_pad, n_real)
    dst[:n_real] = rng.integers(0, nv_pad, n_real)
    src[:4] = [nv_pad - 1, nv_pad - 1, 0, 0]
    dst[:4] = [nv_pad - 1, nv_pad - 1, nv_pad - 1, 0]
    w[:n_real] = rng.integers(1, 64, n_real) / 8.0
    return src, dst, w


def _coalesce_oracle(src, ckey, w, nv_pad):
    """Sorted-unique real (src, ckey) pairs with summed weights, in
    float64 (the dyadic inputs make every f32 partial sum exact, so the
    engine must match BIT-for-bit after the cast)."""
    src, ckey, w = (np.asarray(x) for x in (src, ckey, w))
    real = src < nv_pad
    keys = src[real].astype(np.int64) * nv_pad + ckey[real]
    order = np.argsort(keys, kind="stable")
    ks, ws = keys[order], w[real][order].astype(np.float64)
    uniq, start = np.unique(ks, return_index=True)
    sums = np.add.reduceat(ws, start)
    return ((uniq // nv_pad).astype(np.int32),
            (uniq % nv_pad).astype(np.int32),
            sums.astype(np.float32))


def _assert_coalesce_matches_oracle(out, src, dst, w, nv_pad):
    s_ref, c_ref, w_ref = _coalesce_oracle(src, dst, w, nv_pad)
    src_c, ckey_c, w_c, n = (np.asarray(x) for x in jax.device_get(out))
    n = int(n)
    assert n == len(s_ref)
    assert np.array_equal(src_c[:n], s_ref)
    assert np.array_equal(ckey_c[:n], c_ref)
    assert np.array_equal(w_c[:n], w_ref)
    assert (src_c[n:] == nv_pad).all()


def test_coalesce_chokepoint_widest_legal_31bit_packing():
    """nv_pad = 2^15: sbits(nv_pad + 1) = 16 + kbits(nv_pad) = 15 == 31,
    the widest int32 packing the chokepoint ever rides — the duplicated
    (nv_pad-1, nv_pad-1) rows pack to the top key and must still
    coalesce to ONE summed run, not sort to the front."""
    nv_pad, ne_pad = 1 << 15, 8192
    src, dst, w = _chokepoint_slab(nv_pad, ne_pad, seed=31)
    out = coalesced_runs(jnp.asarray(src), jnp.asarray(dst),
                         jnp.asarray(w), nv_pad=nv_pad, engine="sort")
    _assert_coalesce_matches_oracle(out, src, dst, w, nv_pad)


def test_coalesce_chokepoint_first_ineligible_width():
    """nv_pad = 2^16: 17 + 16 == 33 bits — the chokepoint must take the
    lexicographic fallback and still produce the exact coalesce."""
    nv_pad, ne_pad = 1 << 16, 8192
    src, dst, w = _chokepoint_slab(nv_pad, ne_pad, seed=32)
    out = coalesced_runs(jnp.asarray(src), jnp.asarray(dst),
                         jnp.asarray(w), nv_pad=nv_pad, engine="sort")
    _assert_coalesce_matches_oracle(out, src, dst, w, nv_pad)


def test_coalesce_chokepoint_forced_64_bit_identical():
    """Under jax_enable_x64 the same ineligible width packs into ONE
    int64 key — and the coalesced result must be bit-identical to the
    lexicographic run (the packed/lex parity contract, at the
    chokepoint rather than the bare sort)."""
    nv_pad, ne_pad = 1 << 16, 8192
    src, dst, w = _chokepoint_slab(nv_pad, ne_pad, seed=33)
    arrs = tuple(jnp.asarray(x) for x in (src, dst, w))
    base = jax.device_get(coalesced_runs(*arrs, nv_pad=nv_pad,
                                         engine="sort"))
    prior = jax.config.jax_enable_x64
    try:
        jax.config.update("jax_enable_x64", True)
        forced = jax.device_get(coalesced_runs(*arrs, nv_pad=nv_pad,
                                               engine="sort"))
    finally:
        jax.config.update("jax_enable_x64", prior)
    for b, f, name in zip(base, forced, ("src", "ckey", "w", "n")):
        assert np.array_equal(np.asarray(b), np.asarray(f)), name


def test_slab_ne_max_raise_guard():
    """The widest legal slab traces; one doubling past SLAB_NE_MAX
    fails LOUD (the int32 run-id cumsums would wrap silently)."""
    def probe(ne):
        jax.eval_shape(
            lambda s, c, w: coalesced_runs(s, c, w, nv_pad=1 << 12,
                                           engine="sort"),
            jax.ShapeDtypeStruct((ne,), jnp.int32),
            jax.ShapeDtypeStruct((ne,), jnp.int32),
            jax.ShapeDtypeStruct((ne,), jnp.float32))

    probe(seg.SLAB_NE_MAX)
    with pytest.raises(ValueError, match="SLAB_NE_MAX"):
        probe(seg.SLAB_NE_MAX * 2)
    with pytest.raises(ValueError, match="SLAB_NE_MAX"):
        jax.eval_shape(
            seg.run_totals,
            jax.ShapeDtypeStruct((seg.SLAB_NE_MAX * 2,), jnp.float32),
            jax.ShapeDtypeStruct((seg.SLAB_NE_MAX * 2,), jnp.bool_))


def test_flat_nv_max_raise_guard():
    """seg_coalesce_xla's flat (src << kbits) | dst key: FLAT_NV_MAX
    traces, one doubling past raises (the key would wrap int32)."""
    from cuvite_tpu.kernels.seg_coalesce import (FLAT_NV_MAX,
                                                 seg_coalesce_xla)

    def probe(nv):
        jax.eval_shape(
            lambda s, d, w: seg_coalesce_xla(s, d, w, nv_pad=nv),
            jax.ShapeDtypeStruct((4096,), jnp.int32),
            jax.ShapeDtypeStruct((4096,), jnp.int32),
            jax.ShapeDtypeStruct((4096,), jnp.float32))

    probe(FLAT_NV_MAX)
    with pytest.raises(ValueError, match="FLAT_NV_MAX"):
        probe(FLAT_NV_MAX * 2)


def test_heavy_layout_elems_budget_boundary():
    """build_heavy_layout's eligibility boundary: a layout landing
    exactly ON max_elems is returned; one element past degrades to None
    (the caller keeps the sorted path, with coverage accounting)."""
    from cuvite_tpu.kernels.heavy_bincount import build_heavy_layout

    nv_local = 16
    src = np.repeat(np.arange(8, dtype=np.int32), 8)   # 8 hubs, deg 8
    dst = np.tile(np.arange(8, dtype=np.int32), 8)
    w = np.ones(64, np.float32)
    # H = 8 -> Hp = 8; counts.max() = 8, d_chunk = 8 -> D = 8: 64 elems.
    at = build_heavy_layout(src, dst, w, nv_local=nv_local,
                            pad_id=nv_local, d_chunk=8, max_elems=64)
    assert at is not None
    verts, dstT, wT = at
    assert verts.shape == (8,) and dstT.shape == (8, 8)
    past = build_heavy_layout(src, dst, w, nv_local=nv_local,
                              pad_id=nv_local, d_chunk=8, max_elems=63)
    assert past is None
