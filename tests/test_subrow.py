"""Sub-row packing tests (ISSUE 20): fence adversarial bit-identity,
the ds32 row-class accumulator re-gate, zero-compile occupancy
invariance, and the serve-side merge-aware packer (overflow merges,
demote-to-plain, poison isolation of a merged batch, sticky-union
non-growth, the new stats counters, and the concheck scenario).

The fence contract under test is exact, not tolerance-based: a packed
sub-row's labels and Q are BIT-identical to the same graph's solo B=1
run through the batched driver, because the sentinel fences make every
per-run float content-local.  The adversarial graphs here aim at the
seams directly — a hub community AT the last sub-row vertex id, a
max-degree star whose edges fill the sub-row edge span to the brink —
where an off-by-one in the offset arithmetic would leak community ids
or edge mass across tenants.
"""

import threading
import types

import numpy as np
import pytest

from cuvite_tpu.core.batch import (
    SubRowLayout,
    batch_pad,
    pack_subrows,
    slab_class_of,
    subrow_layout_for,
    unpack_subrows,
)
from cuvite_tpu.core.graph import Graph
from cuvite_tpu.louvain.batched import (
    accum_class_of,
    cluster_packed,
    pack_subrow_many,
)
from cuvite_tpu.louvain.driver import louvain_many
from cuvite_tpu.serve import LouvainServer, ServeConfig, ServeStats
from cuvite_tpu.workloads.synth import many_seed, synthesize_graph

SMALL = (4096, 16384)
BIG = (8192, 32768)
LAYOUT = subrow_layout_for(SMALL, BIG)


# ---------------------------------------------------------------------------
# Layout geometry (pure numpy)


def test_subrow_layout_for_exact_pow2_ratio_only():
    lay = subrow_layout_for(SMALL, BIG)
    assert lay is not None and lay.n_sub == 2
    assert lay.row_class == BIG
    assert lay.vertex_fences() == (0, 4096, 8192)
    assert lay.vertex_offset(1) == 4096 and lay.edge_offset(1) == 16384
    assert subrow_layout_for(SMALL, (16384, 65536)).n_sub == 4
    # Disagreeing per-dimension ratios cannot fence cleanly.
    assert subrow_layout_for(SMALL, (8192, 16384)) is None
    assert subrow_layout_for(SMALL, (8192, 65536)) is None
    # n_sub must be a pow2 >= 2: same class and 3x are both invalid.
    assert subrow_layout_for(SMALL, SMALL) is None
    assert subrow_layout_for(SMALL, (12288, 49152)) is None
    with pytest.raises(ValueError):
        SubRowLayout(n_sub=3, sub_class=SMALL)


def _ring_graph(nv, seed, extra=0):
    """Connected small graph: an nv-ring plus `extra` random chords."""
    rng = np.random.default_rng(seed)
    src = np.concatenate([np.arange(nv), rng.integers(0, nv, extra)])
    dst = np.concatenate([(np.arange(nv) + 1) % nv,
                          rng.integers(0, nv, extra)])
    keep = src != dst
    return Graph.from_edges(nv, src[keep], dst[keep])


def test_pack_unpack_roundtrip_geometry():
    graphs = [_ring_graph(64, s, extra=32) for s in range(3)]
    packed = pack_subrows(graphs, LAYOUT)
    assert packed.slab_class == BIG
    assert packed.b_pad == batch_pad(2)          # ceil(3/2) rows
    # Row-major occupancy: job j at (j // n_sub, j % n_sub).
    assert packed.sub_valid[0].tolist() == [True, True]
    assert packed.sub_valid[1].tolist() == [True, False]
    assert packed.n_jobs == 3
    assert packed.subrow_util == 3 / (packed.b_pad * 2)
    # Sub-row 1's edges live at the edge offset, shifted by the vertex
    # offset; padding carries the ROW sentinel (src == row nv_pad) so a
    # padded slot can never scatter into a real community.
    eo, vo = LAYOUT.edge_offset(1), LAYOUT.vertex_offset(1)
    g1 = graphs[1]
    seg = packed.src[0, eo:eo + g1.num_edges]
    assert seg.min() >= vo and seg.max() < vo + LAYOUT.nv_sub
    pad = packed.src[0, eo + g1.num_edges:]
    assert (pad == BIG[0]).all()
    # unpack slices per-tenant labels back out of the fenced row,
    # shifted back down by the fence base, with the sub-row's own Q.
    comm = np.broadcast_to(np.arange(BIG[0], dtype=np.int32)[None, :],
                           (packed.b_pad, BIG[0])).copy()
    q = np.arange(packed.b_pad * 2, dtype=np.float64).reshape(
        packed.b_pad, 2)
    out = unpack_subrows(packed, comm, q)
    assert len(out) == 3
    for k, g in enumerate(graphs):
        labels, qk = out[k]
        assert labels.shape == (g.num_vertices,)
        assert np.array_equal(labels, np.arange(g.num_vertices))
        assert qk == float(q[k // 2, k % 2])


# ---------------------------------------------------------------------------
# Fence adversarial bit-identity (real jax, the tentpole contract)


def _hub_graph(nv, hub, seed, extra=64):
    """Ring + a dense hub at vertex id `hub`: the hub's community is an
    attractor whose id sits wherever we aim it — at the seam, in these
    tests."""
    rng = np.random.default_rng(seed)
    spokes = rng.choice(nv - 1, size=nv // 8, replace=False)
    spokes = np.where(spokes >= hub, spokes + 1, spokes) % nv
    src = np.concatenate([np.arange(nv), np.full(spokes.size, hub),
                          rng.integers(0, nv, extra)])
    dst = np.concatenate([(np.arange(nv) + 1) % nv, spokes,
                          rng.integers(0, nv, extra)])
    keep = src != dst
    return Graph.from_edges(nv, src[keep], dst[keep])


def _assert_bit_identical(graphs, layout, **kw):
    res = cluster_packed(graphs, layout, **kw)
    for k, g in enumerate(graphs):
        solo = louvain_many([g], **kw).results[0]
        got = res.results[k]
        assert got.modularity == solo.modularity, (
            f"tenant {k}: packed Q {got.modularity!r} != solo "
            f"{solo.modularity!r} — a fence leaked")
        assert np.array_equal(got.communities, solo.communities), (
            f"tenant {k}: packed labels differ from solo B=1")


def test_fence_community_id_at_the_seam():
    """Tier-1 fence pin: tenant 0's hub community lives AT vertex
    nv_sub-1 (global id 4095) and tenant 1's at vertex 0 (global id
    4096) — adjacent ids across the fence.  Any cross-seam leak in the
    packed program's gather/scatter would merge the two hubs; the
    labels and Q must match each tenant's solo B=1 run bitwise."""
    g_hi = _hub_graph(4096, hub=4095, seed=1)
    g_lo = _hub_graph(4096, hub=0, seed=2)
    assert slab_class_of(g_hi) == SMALL and slab_class_of(g_lo) == SMALL
    _assert_bit_identical([g_hi, g_lo], LAYOUT, max_phases=2)


def test_fence_max_degree_straddles_edge_offset():
    """Each tenant is a max-degree star whose directed edges fill the
    16384-edge sub-row span to 16382/16384 — the last real edge sits
    two slots from the edge offset boundary, so an off-by-one in
    edge_offset arithmetic reads the neighbor tenant's first edges.
    Cheap in tier 1: the packed program is already warm from
    test_fence_community_id_at_the_seam (same row class and B)."""
    def star(nv, seed):
        rng = np.random.default_rng(seed)
        hub = nv - 1
        others = np.arange(nv - 1)
        ex_s = rng.integers(0, nv - 1, 4096)
        ex_d = rng.integers(0, nv - 1, 4096)
        keep = ex_s != ex_d
        g = Graph.from_edges(
            nv, np.concatenate([np.full(nv - 1, hub), ex_s[keep]]),
            np.concatenate([others, ex_d[keep]]))
        assert slab_class_of(g) == SMALL, g.num_edges
        assert g.num_edges > 16000       # near the 16384 boundary
        return g

    _assert_bit_identical([star(4096, 3), star(4096, 4)], LAYOUT,
                          max_phases=2)


def test_ds32_tenant_refused_from_f32_packed_row():
    """A tenant past the ds32 scale gate (tw2 >= 2^24) can never enter
    an f32 packed row: accum_class_of tags it at both classes and
    prepare_packed's backstop raises — louder is better than silently
    flipping every batchmate's accumulator."""
    rng = np.random.default_rng(5)
    heavy = Graph.from_edges(
        256, np.arange(256), (np.arange(256) + 1) % 256,
        weights=np.full(256, 1.0e5))     # tw2 = 2 * 256 * 1e5 >> 2^24
    light = _ring_graph(256, 6, extra=64)
    assert accum_class_of(heavy) == "ds32"
    assert accum_class_of(heavy, BIG[0]) == "ds32"
    assert accum_class_of(light) == "float32"
    assert accum_class_of(light, BIG[0]) == "float32"
    with pytest.raises(ValueError, match="f32-only"):
        pack_subrow_many([light, heavy], LAYOUT)
    del rng


def test_second_packed_batch_of_different_tenants_zero_compiles():
    """The packed compile key is (row class, B, n_sub, engine) — batch
    CONTENT and sub-row OCCUPANCY never enter it.  After one warm
    packed batch, a second batch of DIFFERENT tenants at HALF the
    occupancy (one sub-row empty) reuses the program with zero fresh
    compiles."""
    from cuvite_tpu.obs import CompileWatcher

    warm = [synthesize_graph(1024, seed=many_seed(31, k)) for k in (0, 1)]
    cluster_packed(warm, LAYOUT, max_phases=2)
    fresh = [synthesize_graph(1024, seed=many_seed(32, 9))]
    with CompileWatcher() as w:
        res = cluster_packed(fresh, LAYOUT, max_phases=2)
    assert len(res.results) == 1
    assert not w.compiles, [c for c in w.compiles]


# ---------------------------------------------------------------------------
# Serve-side merge-aware packer (stub runner, fake clock — queue
# discipline only; the real-jax twin below pins the bits)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def sleep(self, s):
        self.t += s


def make_graph(seed, nv=16, ne=32):
    rng = np.random.default_rng(seed)
    return Graph.from_edges(nv, rng.integers(0, nv, ne),
                            rng.integers(0, nv, ne))


def make_big_graph(seed, nv=8192, ne=9000):
    """Stub big-class graph: ~9k arcs symmetrize past the 16384-edge
    floor -> class (8192, 32768), the n_sub=2 merge target of the
    small floor class."""
    g = make_graph(seed, nv=nv, ne=ne)
    assert slab_class_of(g) == BIG
    return g


def stub_result(g):
    nv = g.num_vertices
    key = int(np.sum(g.tails)) % 997
    return types.SimpleNamespace(
        communities=(np.arange(nv) + key) % max(nv, 1),
        modularity=key / 997.0, phases=[1], total_iterations=3,
        num_communities=nv)


def make_stub_runner(clock=None, service_s=0.0, calls=None):
    def runner(graphs, **kw):
        if calls is not None:
            calls.append(len(graphs))
        if clock is not None and service_s:
            clock.sleep(service_s)
        return types.SimpleNamespace(
            results=[stub_result(g) for g in graphs], n_phases=1)

    return runner


def make_server(clock, *, runner=None, faults=None, **cfg_kw):
    cfg_kw.setdefault("engine", "fused")
    cfg_kw.setdefault("b_max", 2)
    cfg_kw.setdefault("linger_s", 0.0)
    cfg_kw.setdefault("merge_packing", True)
    return LouvainServer(ServeConfig(**cfg_kw), clock=clock,
                         sleep=clock.sleep, faults=faults,
                         runner=runner or make_stub_runner(clock))


def _serve_big_then_overflow(srv, *, n_small=3):
    """Certify BIG with a plain batch, then overflow the small bin."""
    for s in (100, 101):
        srv.submit(make_big_graph(s))
    done_big = srv.step()
    assert len(done_big) == 2
    small_ids = [srv.submit(make_graph(s)) for s in range(n_small)]
    return small_ids


def test_overflow_merge_pops_past_b_max_and_conserves():
    clock = FakeClock()
    calls = []
    srv = make_server(clock, runner=make_stub_runner(clock, calls=calls))
    ids = _serve_big_then_overflow(srv)          # 3 smalls vs b_max=2
    done = dict(srv.step())
    assert sorted(done) == sorted(ids)           # ONE merged dispatch
    assert calls == [2, 3]                       # big batch, then 3 > b_max
    s = srv.stats
    assert s.merged_batches == 1 and s.jobs_done == 5
    # Occupancy ledger: big batch b_pad=2 rows of 1 sub-row each; the
    # merged batch ceil(3/2)=2 rows of 2 -> (2+3) / (2+4).
    assert s.graphs_real == 5 and s.subrow_capacity == 6
    assert s.subrow_util == pytest.approx(5 / 6)
    assert srv.conservation()["ok"] and srv.pending() == 0
    # Only PLAIN completions certify a merge target: the merged small
    # batch ran the BIG row program, not the small class's own.
    assert BIG in srv._served_classes and SMALL not in srv._served_classes
    per = s.per_class()
    assert per[SMALL]["done"] == 3 and per[BIG]["done"] == 2


def test_no_merge_without_certified_target():
    """Small jobs overflow but no larger class ever completed a plain
    batch here: the pop stays plain at b_max (merging never invents a
    class — a fresh row class would compile fresh programs mid-serve)."""
    clock = FakeClock()
    calls = []
    srv = make_server(clock, runner=make_stub_runner(clock, calls=calls))
    for s in range(3):
        srv.submit(make_graph(s))
    srv.step()
    srv.step(force=True)
    assert calls == [2, 1]                       # plain cap, then the rest
    assert srv.stats.merged_batches == 0
    assert srv.conservation()["ok"]


def test_merge_demotes_to_plain_on_row_class_accum_flip(monkeypatch):
    """Refusal means serve plain, never fail the job: with the ds32
    gate lowered so the ROW class's padded reduction length (8192)
    crosses but the small class (4096) does not, a merge-triggered pop
    re-gates each tenant at the row class, fails, and packs plain —
    all jobs complete, nothing merged."""
    monkeypatch.setattr("cuvite_tpu.louvain.driver.DS_MIN_TOTAL_WEIGHT",
                        6000.0)
    clock = FakeClock()
    calls = []
    srv = make_server(clock, runner=make_stub_runner(clock, calls=calls))
    ids = _serve_big_then_overflow(srv)
    done = dict(srv.step())
    assert sorted(done) == sorted(ids)
    # The pop still took all 3 (the merge DECISION ran), but the batch
    # demoted: merged_batches stays 0.
    assert calls[-1] == 3
    assert srv.stats.merged_batches == 0
    assert srv.stats.jobs_done == 5 and srv.conservation()["ok"]


def test_poison_in_merged_batch_isolates_batchmates():
    """A poison tenant inside the MERGED dispatch must not take its
    batchmates down: the batch splits, each job re-runs solo (plain, at
    its own class), the poison job fails terminally ALONE and the
    survivors complete — every job terminates exactly once."""
    clock = FakeClock()
    calls = []
    smalls = [make_graph(s) for s in range(3)]
    poison = smalls[1]

    def runner(graphs, **kw):
        calls.append(len(graphs))
        if any(g is poison for g in graphs):
            raise RuntimeError("poison tenant")
        return types.SimpleNamespace(
            results=[stub_result(g) for g in graphs], n_phases=1)

    srv = make_server(clock, runner=runner)
    for s in (100, 101):
        srv.submit(make_big_graph(s))
    assert len(srv.step()) == 2                  # certify BIG plain
    ids = [srv.submit(g) for g in smalls]
    done = dict(srv.step())
    assert calls == [2, 3, 1, 1, 1]              # merged raise -> isolation
    assert sorted(done) == [ids[0], ids[2]]      # batchmates survived
    assert [jid for jid, _ in srv.failures] == [ids[1]]
    s = srv.stats
    assert s.merged_batches == 0 and s.jobs_done == 4 and s.jobs_failed == 1
    assert srv.conservation()["ok"] and srv.pending() == 0


def test_sticky_union_ignores_merged_batches():
    """Merged batches are plan-free: the sticky bucket-shape union
    (engine='bucketed') must not grow — not for the small class, not
    for the row class — when a merged dispatch completes.  The union
    stays grow-only across PLAIN batches exactly as before."""
    clock = FakeClock()
    srv = make_server(clock, engine="bucketed")
    ids = _serve_big_then_overflow(srv)
    with srv.stats.lock:
        before = dict(srv._shapes)
    assert BIG in before                         # plain big batch recorded
    done = dict(srv.step())                      # merged small dispatch
    assert sorted(done) == sorted(ids) and srv.stats.merged_batches == 1
    with srv.stats.lock:
        after = dict(srv._shapes)
    assert after == before                       # merged batch: no growth
    # A further PLAIN small batch still unions in grow-only fashion.
    for s in (50, 51):
        srv.submit(make_graph(s, ne=48))
    srv.step()
    with srv.stats.lock:
        grown = dict(srv._shapes)
    assert SMALL in grown
    assert set(grown) >= set(after)


def test_merged_counters_race_free_under_stats_lock():
    """to_dict()/subrow_util/per_class() snapshot the new ISSUE-20
    counters under the stats lock: a reader hammering them while a
    writer appends must never see a mutating dict/deque."""
    import collections

    stats = ServeStats()
    stop = threading.Event()
    errors = []

    def reader():
        try:
            while not stop.is_set():
                stats.to_dict()
                _ = stats.subrow_util
                stats.per_class()
        except Exception as e:  # pragma: no cover - the failure signal
            errors.append(e)

    t = threading.Thread(target=reader)
    t.start()
    from cuvite_tpu.serve.queue import WAIT_WINDOW
    for i in range(20000):
        cls = (4096 << (i % 3), 16384 << (i % 3))
        with stats.lock:
            stats.merged_batches += 1
            stats.graphs_real += 3
            stats.subrow_capacity += 4
            stats.done_by_class[cls] = stats.done_by_class.get(cls, 0) + 1
            stats.waits_by_class.setdefault(
                cls, collections.deque(maxlen=WAIT_WINDOW)).append(i * 1e-6)
    stop.set()
    t.join(timeout=30)
    assert not errors
    d = stats.to_dict()
    assert d["merged_batches"] == 20000
    assert d["subrow_util"] == pytest.approx(3 / 4)
    assert sum(v["done"] for v in stats.per_class().values()) == 20000


def test_concheck_merge_packer_scenario_clean_with_teeth():
    """The merge-aware packer under the schedule explorer: intake
    overflowing a small bin races the big-class batch that certifies
    the merge target — conservation and exactly-once hold on every
    interleaving, AND at least one explored schedule actually
    dispatched merged (the scenario keeps its teeth)."""
    from cuvite_tpu.analysis import concheck

    fac, expect = concheck.builtin_scenarios()["merge-pack-clean"]
    assert expect == "clean"
    scen = fac()
    rep = concheck.explore(scen, budget=24, seed=3)
    assert rep.clean, [f.failures or f.races for f in rep.failing]
    assert scen.merged_batches_seen > 0, (
        "no explored schedule merged — the scenario lost its targeting")


# ---------------------------------------------------------------------------
# Real-jax merged serving (the bits, end to end)


@pytest.mark.slow
def test_serve_overflow_merge_bit_identical_real_jax():
    """Slow-tier end-to-end pin (tier-1 siblings:
    test_overflow_merge_pops_past_b_max_and_conserves for the queue
    discipline, test_fence_community_id_at_the_seam for the fences):
    a real big-class batch certifies the target, three real small jobs
    overflow-merge into ONE row-class dispatch, and every tenant's
    labels and Q come back bit-identical to its solo B=1 run."""
    from cuvite_tpu.io.generate import generate_rmat

    clock = FakeClock()
    srv = LouvainServer(
        ServeConfig(b_max=2, linger_s=5.0, merge_packing=True),
        clock=clock, sleep=clock.sleep)
    bigs = [generate_rmat(13, edge_factor=2, seed=s) for s in (1, 2)]
    assert slab_class_of(bigs[0]) == BIG
    for g in bigs:
        srv.submit(g)
    assert len(srv.step()) == 2
    smalls = [synthesize_graph(1024, seed=many_seed(3, k))
              for k in range(3)]
    ids = [srv.submit(g) for g in smalls]
    done = dict(srv.step())
    assert sorted(done) == sorted(ids)
    assert srv.stats.merged_batches == 1
    for jid, g in zip(ids, smalls):
        solo = louvain_many([g]).results[0]
        assert done[jid].modularity == solo.modularity
        assert np.array_equal(done[jid].communities, solo.communities)
    assert srv.conservation()["ok"] and srv.pending() == 0
