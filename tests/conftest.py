"""Test configuration: force an 8-device virtual CPU mesh.

The TPU-native analog of the reference's "multi-node without a cluster"
strategy (oversubscribed MPI ranks on one node, /root/reference/README:48-53):
XLA's host-platform device count gives N fake devices so every collective and
sharding path runs exactly as it would on an N-chip mesh.
"""

import os
import sys

# Must precede any jax backend initialization.  Note: the axon TPU plugin in
# this image registers itself from sitecustomize and wins over a
# JAX_PLATFORMS env var, so the platform is forced via jax.config below.
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Stack headroom for XLA's compile worker threads: raise the stack soft
# limit to a large FINITE value before jax loads — glibc sizes new pthread
# stacks from the soft limit (RLIM_INFINITY would fall back to the 8 MiB
# default).  (Historically suspected in the late-run segfault; the real
# cause was the map-count limit above.)
import resource  # noqa: E402

# ROOT CAUSE of the single-process full-suite segfault (round 5,
# tools/segfault_notes.md): XLA:CPU maps each compiled executable's code
# into its own anonymous VMA (plus mprotect splits); a full-suite process
# accumulates ~68k maps and crosses the kernel's vm.max_map_count default
# of 65530, at which point mmap fails inside the executable loader (fresh
# compile or persistent-cache AOT read alike) and it segfaults.  Measured:
# peak 68,415 maps; the suite completes with the limit raised, crashes at
# ~65k without.
# NOTE: this is a HOST-GLOBAL sysctl (no per-process form exists), so the
# raise is strictly OPT-IN — CUVITE_RAISE_SYSCTL=1 — and the prior value
# is restored in pytest_sessionfinish below (graftlint R008 polices this
# pattern).  Without the opt-in, split the suite across processes
# (`pytest -n 3`, where pytest-xdist is installed — it is NOT in this
# image) to keep each process's map count under the kernel default.
_maps_prior = None  # raised from this value iff the opt-in fired
try:
    with open("/proc/sys/vm/max_map_count") as _f:
        _maps_cur = int(_f.read())
except (OSError, ValueError):
    _maps_cur = None
_raise_failed = False  # opt-in was set but the write needed privileges
if os.environ.get("CUVITE_RAISE_SYSCTL"):
    if _maps_cur is not None and _maps_cur < 1 << 20:
        try:
            with open("/proc/sys/vm/max_map_count", "w") as _f:
                _f.write(str(1 << 20))
            _maps_prior = _maps_cur
        except OSError:
            _raise_failed = True


def pytest_configure(config):
    """Warn UP FRONT when no segfault mitigation is active, instead of
    letting a full single-process run segfault at ~95% with no hint (the
    measured peak is ~68,415 maps; 70k adds a little headroom).  Checked
    here rather than at import so an xdist run — controller included —
    is recognized as mitigated; partial runs are fine too, which is why
    this warns rather than fails."""
    if _maps_cur is None:
        if os.environ.get("CUVITE_RAISE_SYSCTL"):
            import warnings

            warnings.warn(
                "CUVITE_RAISE_SYSCTL is set but /proc/sys/vm/"
                "max_map_count is unreadable here, so the raise was "
                "skipped; if a full single-process run segfaults late, "
                "rerun as root, or split it with `pytest -n 3` where "
                "pytest-xdist is installed.", stacklevel=1)
        return
    if _maps_prior is not None or _maps_cur >= 70_000:
        return  # raised via the opt-in, or roomy host
    if os.environ.get("PYTEST_XDIST_WORKER") \
            or getattr(config.option, "numprocesses", None):
        return  # split across processes: per-process map counts stay low
    import warnings

    if _raise_failed:
        # Don't tell the user to set the env var they ALREADY set.
        warnings.warn(
            f"CUVITE_RAISE_SYSCTL was set but raising vm.max_map_count "
            f"(currently {_maps_cur}) failed — the write needs root.  A "
            "full single-process suite run may segfault late in the XLA "
            "executable loader; rerun as root, or split the suite with "
            "`pytest -n 3` where pytest-xdist is installed.",
            stacklevel=1)
        return
    warnings.warn(
        f"vm.max_map_count is {_maps_cur} (< ~70k needed by a full "
        "single-process suite run); a complete run may segfault late in "
        "the XLA executable loader.  Either opt in to the sysctl raise "
        "with CUVITE_RAISE_SYSCTL=1 (root; restored at session finish) "
        "or split the suite with `pytest -n 3` where pytest-xdist is "
        "installed.",
        stacklevel=1)


def pytest_sessionfinish(session, exitstatus):
    """Restore the pre-session vm.max_map_count if the opt-in raised it
    (best-effort: the write needs the same root privilege the raise had)."""
    global _maps_prior
    if _maps_prior is None:
        return
    try:
        # _maps_prior is only ever set under the CUVITE_RAISE_SYSCTL
        # opt-in above; this write UNDOES that raise.
        with open("/proc/sys/vm/max_map_count", "w") as _f:  # graftlint: disable=R008
            _f.write(str(_maps_prior))
    except OSError:
        pass
    _maps_prior = None


_s_soft, _s_hard = resource.getrlimit(resource.RLIMIT_STACK)
_s_want = 512 << 20
# RLIM_INFINITY also needs the finite value: glibc sizes pthread stacks
# from the soft limit only when it is finite (infinity -> 8 MiB default).
if _s_soft == resource.RLIM_INFINITY or _s_soft < _s_want:
    try:
        resource.setrlimit(resource.RLIMIT_STACK, (_s_want, _s_hard))
    except (ValueError, OSError):  # hard limit lower: best effort
        pass

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compile cache for the suite: a full-suite run compiles
# hundreds of programs; the content-addressed disk cache removes most of
# that wall time on warm runs.  (It does NOT remove the map-count growth
# — AOT loads map code pages just like fresh compiles — which is why the
# max_map_count raise above is the actual segfault fix.)
from cuvite_tpu.utils.compile_cache import enable_compile_cache  # noqa: E402

enable_compile_cache()

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from cuvite_tpu.core.graph import Graph  # noqa: E402


def karate_edges():
    """Zachary's karate club (34 vertices, 78 edges) — the reference's
    conventional smoke-test input (/root/reference/README:53)."""
    import networkx as nx

    g = nx.karate_club_graph()
    e = np.array(g.edges(), dtype=np.int64)
    return 34, e[:, 0], e[:, 1]


@pytest.fixture(scope="session")
def karate() -> Graph:
    nv, s, d = karate_edges()
    return Graph.from_edges(nv, s, d)


@pytest.fixture(scope="session")
def ring8() -> Graph:
    """8-cycle: trivial known structure."""
    s = np.arange(8)
    d = (s + 1) % 8
    return Graph.from_edges(8, s, d)


@pytest.fixture(scope="session")
def two_cliques() -> Graph:
    """Two K5 cliques joined by a single bridge edge: unambiguous communities."""
    edges = []
    for b in (0, 5):
        for i in range(5):
            for j in range(i + 1, 5):
                edges.append((b + i, b + j))
    edges.append((0, 5))
    e = np.array(edges, dtype=np.int64)
    return Graph.from_edges(10, e[:, 0], e[:, 1])
