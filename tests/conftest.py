"""Test configuration: force an 8-device virtual CPU mesh.

The TPU-native analog of the reference's "multi-node without a cluster"
strategy (oversubscribed MPI ranks on one node, /root/reference/README:48-53):
XLA's host-platform device count gives N fake devices so every collective and
sharding path runs exactly as it would on an N-chip mesh.
"""

import os
import sys

# Must precede any jax backend initialization.  Note: the axon TPU plugin in
# this image registers itself from sitecustomize and wins over a
# JAX_PLATFORMS env var, so the platform is forced via jax.config below.
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Late in a full-suite run, an XLA:CPU compile can segfault inside LLVM
# (reproduced thrice at the same test when run after the whole suite; never
# in isolation or with half-suite prefixes).  Primary mitigation is process
# splitting (pytest.ini: -n 2).  Belt-and-braces: raise the stack soft
# limit to a large FINITE value before jax loads — glibc sizes new pthread
# stacks from the soft limit (RLIM_INFINITY would fall back to the 8 MiB
# default), so XLA's compile worker threads get headroom too.
import resource  # noqa: E402

_s_soft, _s_hard = resource.getrlimit(resource.RLIMIT_STACK)
_s_want = 512 << 20
# RLIM_INFINITY also needs the finite value: glibc sizes pthread stacks
# from the soft limit only when it is finite (infinity -> 8 MiB default).
if _s_soft == resource.RLIM_INFINITY or _s_soft < _s_want:
    try:
        resource.setrlimit(resource.RLIMIT_STACK, (_s_want, _s_hard))
    except (ValueError, OSError):  # hard limit lower: best effort
        pass

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compile cache for the suite: a full-suite run compiles
# hundreds of programs, and the cumulative LLVM state is what triggers
# the late-run segfault above (the crash site is always inside an
# XLA:CPU compile).  With the content-addressed disk cache, warm runs
# skip LLVM for every previously seen program — removing both most of
# the wall time and most of the crash exposure.
from cuvite_tpu.utils.compile_cache import enable_compile_cache  # noqa: E402

enable_compile_cache()

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from cuvite_tpu.core.graph import Graph  # noqa: E402


def karate_edges():
    """Zachary's karate club (34 vertices, 78 edges) — the reference's
    conventional smoke-test input (/root/reference/README:53)."""
    import networkx as nx

    g = nx.karate_club_graph()
    e = np.array(g.edges(), dtype=np.int64)
    return 34, e[:, 0], e[:, 1]


@pytest.fixture(scope="session")
def karate() -> Graph:
    nv, s, d = karate_edges()
    return Graph.from_edges(nv, s, d)


@pytest.fixture(scope="session")
def ring8() -> Graph:
    """8-cycle: trivial known structure."""
    s = np.arange(8)
    d = (s + 1) % 8
    return Graph.from_edges(8, s, d)


@pytest.fixture(scope="session")
def two_cliques() -> Graph:
    """Two K5 cliques joined by a single bridge edge: unambiguous communities."""
    edges = []
    for b in (0, 5):
        for i in range(5):
            for j in range(i + 1, 5):
                edges.append((b + i, b + j))
    edges.append((0, 5))
    e = np.array(edges, dtype=np.int64)
    return Graph.from_edges(10, e[:, 0], e[:, 1])
