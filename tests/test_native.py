"""Native host-runtime parity tests.

Every native entry point (native/cuvite_native.cpp via cuvite_tpu.native)
must be bit-identical to its pure-numpy fallback — the library is an
accelerator, not a semantic variant.  Skipped wholesale when the library
cannot be built/loaded (e.g. no compiler in the deployment image).
"""

import os

import numpy as np
import pytest

from cuvite_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)


def _random_edges(ne, nv, seed, self_loops=True, dups=True):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, nv, size=ne)
    dst = rng.integers(0, nv, size=ne)
    if not self_loops:
        dst = np.where(src == dst, (dst + 1) % nv, dst)
    if dups:
        src[: ne // 4] = src[ne // 2 : ne // 2 + ne // 4]
        dst[: ne // 4] = dst[ne // 2 : ne // 2 + ne // 4]
    w = rng.random(ne)
    return src, dst, w


@pytest.mark.parametrize("symmetrize", [True, False])
@pytest.mark.parametrize("seed", [0, 7])
def test_build_csr_matches_numpy(symmetrize, seed):
    from cuvite_tpu.core.graph import Graph

    nv, ne = 257, 4096
    src, dst, w = _random_edges(ne, nv, seed)
    off_n, tails_n, w_n = native.build_csr(nv, src, dst, w, symmetrize)
    # Force the numpy path (edge count below the native threshold).
    g = Graph.from_edges(nv, src, dst, weights=w, symmetrize=symmetrize)
    assert np.array_equal(off_n, g.offsets)
    assert np.array_equal(tails_n, g.tails)
    # Weight sums accumulate duplicates in the same (input) order on both
    # paths, so equality after the policy-dtype cast is exact, not
    # approximate (native returns the raw f64 sums).
    assert np.array_equal(w_n.astype(g.weights.dtype), g.weights)


@pytest.mark.parametrize("symmetrize", [True, False])
def test_build_csr_radix_branch_matches_numpy(symmetrize):
    """nv > 2^22 forces the LSD-radix branch (the small-nv dense-accumulator
    fast path covers every other CSR test): its bit-identical-to-numpy
    contract for production-scale graphs must stay pinned.  Edges are
    concentrated on high vertex ids so the sparse offsets array stays
    cheap."""
    from cuvite_tpu.core.graph import Graph

    nv = (1 << 22) + 11
    ne = 4096
    rng = np.random.default_rng(3)
    src = rng.integers(nv - 300, nv, size=ne)
    dst = rng.integers(nv - 300, nv, size=ne)
    src[: ne // 4] = src[ne // 2: ne // 2 + ne // 4]   # duplicates
    dst[: ne // 4] = dst[ne // 2: ne // 2 + ne // 4]
    w = rng.random(ne)
    off_n, tails_n, w_n = native.build_csr(nv, src, dst, w, symmetrize)
    g = Graph.from_edges(nv, src, dst, weights=w, symmetrize=symmetrize)
    assert np.array_equal(off_n, g.offsets)
    assert np.array_equal(tails_n, g.tails)
    assert np.array_equal(w_n.astype(g.weights.dtype), g.weights)


def test_build_csr_rejects_out_of_range():
    with pytest.raises(ValueError):
        native.build_csr(4, np.array([0, 5]), np.array([1, 2]),
                         np.ones(2), True)


def test_from_edges_uses_native_above_threshold():
    """Above the 2^16-edge threshold Graph.from_edges routes through the
    native builder; result must equal the numpy path bit-for-bit."""
    from cuvite_tpu.core.graph import Graph

    nv, ne = 1000, (1 << 16) + 11
    src, dst, w = _random_edges(ne, nv, 3)
    g_native = Graph.from_edges(nv, src, dst, weights=w)
    os.environ["CUVITE_NO_NATIVE"] = "1"
    native._LIB = None
    try:
        g_numpy = Graph.from_edges(nv, src, dst, weights=w)
    finally:
        del os.environ["CUVITE_NO_NATIVE"]
        native._LIB = None
    assert np.array_equal(g_native.offsets, g_numpy.offsets)
    assert np.array_equal(g_native.tails, g_numpy.tails)
    assert np.array_equal(g_native.weights, g_numpy.weights)


@pytest.mark.parametrize("scale,ne", [(8, 1 << 11), (12, 3000)])
def test_rmat_matches_numpy(scale, ne):
    from cuvite_tpu.io.generate import rmat_edges_numpy

    s_n, d_n = native.rmat_edges(scale, ne, 1, 0.57, 0.19, 0.19)
    s_p, d_p = rmat_edges_numpy(scale, ne, 1, 0.57, 0.19, 0.19)
    assert np.array_equal(s_n, s_p)
    assert np.array_equal(d_n, d_p)
    assert s_n.min() >= 0 and s_n.max() < (1 << scale)


def test_rmat_is_skewed():
    """R-MAT must produce a heavy-tailed degree distribution (sanity that
    the quadrant recursion actually biases, not uniform noise)."""
    s, d = native.rmat_edges(12, 1 << 14, 1, 0.57, 0.19, 0.19)
    deg = np.bincount(np.concatenate([s, d]), minlength=1 << 12)
    assert deg.max() > 8 * max(deg.mean(), 1)


@pytest.mark.parametrize("bits64", [True, False])
def test_vite_native_roundtrip(tmp_path, bits64):
    from cuvite_tpu.core.graph import Graph
    from cuvite_tpu.core.types import default_policy, wide_policy
    from cuvite_tpu.io.vite import read_vite, write_vite

    nv, ne = 300, 70000  # above the native read/write threshold
    src, dst, w = _random_edges(ne, nv, 5)
    w = np.round(w * 16) / 16  # exact in float32 for the 32-bit format
    policy = wide_policy() if bits64 else default_policy()
    g = Graph.from_edges(nv, src, dst, weights=w, policy=policy)
    p = str(tmp_path / "g.bin")
    write_vite(p, g, bits64=bits64)  # native write
    g2 = read_vite(p, bits64=bits64)  # native read
    os.environ["CUVITE_NO_NATIVE"] = "1"
    native._LIB = None
    try:
        g3 = read_vite(p, bits64=bits64)  # numpy memmap read
    finally:
        del os.environ["CUVITE_NO_NATIVE"]
        native._LIB = None
    for a, b in ((g2, g) , (g3, g)):
        assert np.array_equal(a.offsets, b.offsets)
        assert np.array_equal(a.tails, b.tails)
        assert np.array_equal(a.weights, b.weights)


def test_vite_native_vertex_range(tmp_path):
    from cuvite_tpu.core.graph import Graph
    from cuvite_tpu.io.vite import read_vite, write_vite

    nv, ne = 128, 70000
    src, dst, w = _random_edges(ne, nv, 9)
    g = Graph.from_edges(nv, src, dst, weights=w)
    p = str(tmp_path / "g.bin")
    write_vite(p, g)
    lo, hi = 32, 96
    part = read_vite(p, vertex_range=(lo, hi))
    assert part.num_vertices == hi - lo
    e0, e1 = int(g.offsets[lo]), int(g.offsets[hi])
    assert np.array_equal(part.offsets, g.offsets[lo : hi + 1] - e0)
    assert np.array_equal(part.tails, g.tails[e0:e1])


def test_balanced_parts_matches_python():
    from cuvite_tpu.core.distgraph import balanced_parts
    from cuvite_tpu.core.graph import Graph

    nv, ne = 500, 120000
    src, dst, w = _random_edges(ne, nv, 11)
    g = Graph.from_edges(nv, src, dst, weights=w)
    for nparts in (2, 4, 7):
        p_py = balanced_parts(g, nparts)
        p_nat = native.balanced_parts(g.offsets, nparts)
        assert np.array_equal(p_py, p_nat)


def test_balanced_parts_tiny_graph_matches_python():
    """ne < nparts drives some edge targets to 0; both paths must agree on
    the degenerate cuts (shard 0 never empty)."""
    from cuvite_tpu.core.distgraph import balanced_parts
    from cuvite_tpu.core.graph import Graph

    g = Graph.from_edges(10, np.array([0, 3]), np.array([1, 4]))
    for nparts in (3, 8):
        assert np.array_equal(balanced_parts(g, nparts),
                              native.balanced_parts(g.offsets, nparts))


def test_coarsen_native_matches_numpy():
    """coarsen_graph must be bit-identical with and without the native
    library (same duplicate-accumulation order), including f64 weights."""
    from cuvite_tpu.coarsen.rebuild import coarsen_graph, renumber_communities
    from cuvite_tpu.core.graph import Graph
    from cuvite_tpu.core.types import wide_policy

    nv, ne = 400, 40000  # slab 2*ne > 2^16 -> native path eligible
    src, dst, w = _random_edges(ne, nv, 13)
    g = Graph.from_edges(nv, src, dst, weights=w, policy=wide_policy())
    comm = (np.arange(nv) * 7919) % 37
    dense, nc = renumber_communities(comm)
    cg_native = coarsen_graph(g, dense, nc)
    os.environ["CUVITE_NO_NATIVE"] = "1"
    native._LIB = None
    try:
        cg_numpy = coarsen_graph(g, dense, nc)
    finally:
        del os.environ["CUVITE_NO_NATIVE"]
        native._LIB = None
    assert np.array_equal(cg_native.offsets, cg_numpy.offsets)
    assert np.array_equal(cg_native.tails, cg_numpy.tails)
    assert np.array_equal(cg_native.weights, cg_numpy.weights)


# ---------------------------------------------------------------------------
# Native bucket-plan builder (cv_plan_scan + cv_bucket_fill): bit-identical
# to the numpy BucketPlan.build, including the heavy class, weighted
# graphs, and the uint8 unit-weight compression.

def _numpy_plan(src, dst, w, nv_local, base):
    from cuvite_tpu.louvain.bucketed import BucketPlan

    old = native._LIB
    native._LIB = False  # force the numpy path
    try:
        return BucketPlan.build(src, dst, w, nv_local=nv_local, base=base)
    finally:
        native._LIB = old


def _assert_plans_equal(pn, pp):
    assert len(pn.buckets) == len(pp.buckets)
    for a, b in zip(pn.buckets, pp.buckets):
        assert a.width == b.width
        assert np.array_equal(a.verts, b.verts)
        assert np.array_equal(a.dst, b.dst)
        assert a.w.dtype == b.w.dtype
        assert np.array_equal(a.w, b.w)
    for f in ("heavy_src", "heavy_dst", "heavy_w", "self_loop"):
        assert np.array_equal(getattr(pn, f), getattr(pp, f)), f
    assert pn.has_heavy == pp.has_heavy


def _slab(g, nsh=1, s=0):
    from cuvite_tpu.core.distgraph import DistGraph

    dg = DistGraph.build(g, nsh)
    sh = dg.shards[s]
    return (np.asarray(sh.src), np.asarray(sh.dst), np.asarray(sh.w),
            dg.nv_pad, s * dg.nv_pad)


def test_bucket_plan_native_matches_numpy_rmat():
    from cuvite_tpu.io.generate import generate_rmat
    from cuvite_tpu.louvain.bucketed import _build_native

    src, dst, w, nvp, base = _slab(generate_rmat(14, edge_factor=16, seed=1))
    pn = _build_native(src, dst, w, nvp, base,
                       widths=__import__("cuvite_tpu.louvain.bucketed",
                                         fromlist=["DEFAULT_BUCKETS"]
                                         ).DEFAULT_BUCKETS)
    assert pn is not None
    # (R-MAT coalesces duplicate edges to weight 2, so the plan is NOT
    # unit-weight — the uint8 path is pinned by the ring test below.)
    _assert_plans_equal(pn, _numpy_plan(src, dst, w, nvp, base))


def test_bucket_plan_native_unit_uint8():
    """A duplicate-free unit-weight graph compresses weights to uint8 on
    both paths."""
    from cuvite_tpu.core.graph import Graph
    from cuvite_tpu.louvain.bucketed import DEFAULT_BUCKETS, _build_native

    n = 1 << 17
    s = np.arange(n, dtype=np.int64)
    g = Graph.from_edges(n, s, (s + 1) % n)
    src, dst, w, nvp, base = _slab(g)
    pn = _build_native(src, dst, w, nvp, base, widths=DEFAULT_BUCKETS)
    assert pn is not None
    assert all(b.w.dtype == np.uint8 for b in pn.buckets)
    _assert_plans_equal(pn, _numpy_plan(src, dst, w, nvp, base))


def test_bucket_plan_native_matches_numpy_weighted():
    from cuvite_tpu.io.generate import generate_rgg
    from cuvite_tpu.louvain.bucketed import DEFAULT_BUCKETS, _build_native

    src, dst, w, nvp, base = _slab(generate_rgg(1 << 15, seed=3))
    pn = _build_native(src, dst, w, nvp, base, widths=DEFAULT_BUCKETS)
    assert pn is not None
    _assert_plans_equal(pn, _numpy_plan(src, dst, w, nvp, base))
    assert all(b.w.dtype == w.dtype for b in pn.buckets)


def test_bucket_plan_native_heavy_class():
    """Hub graph: the degree-10240 vertex goes down the heavy path with
    edges in exactly the numpy order."""
    from cuvite_tpu.core.graph import Graph
    from cuvite_tpu.louvain.bucketed import DEFAULT_BUCKETS, _build_native

    edges = []
    nv = 40 * 256 + 1
    hub = nv - 1
    for c in range(40):
        b0 = c * 256
        for i in range(256):
            edges.append((b0 + i, b0 + (i + 1) % 256))
            edges.append((b0 + i, b0 + (i + 7) % 256))
    for v in range(hub):  # hub degree 10240 > DEFAULT_BUCKETS[-1]
        edges.append((hub, v))
    e = np.array(edges, dtype=np.int64)
    g = Graph.from_edges(nv, e[:, 0], e[:, 1])
    src, dst, w, nvp, base = _slab(g)
    pn = _build_native(src, dst, w, nvp, base, widths=DEFAULT_BUCKETS)
    assert pn is not None and pn.has_heavy
    _assert_plans_equal(pn, _numpy_plan(src, dst, w, nvp, base))


def test_bucket_plan_native_declines_masked_slab():
    """Color-class plans mask src mid-slab (padding not at the tail): the
    native path must decline and the numpy fallback handle it."""
    from cuvite_tpu.io.generate import generate_rmat
    from cuvite_tpu.louvain.bucketed import DEFAULT_BUCKETS, _build_native

    src, dst, w, nvp, base = _slab(generate_rmat(13, edge_factor=16, seed=2))
    src = src.copy()
    src[::3] = nvp  # mask every third edge to padding, mid-slab
    assert _build_native(src, dst, w, nvp, base,
                         widths=DEFAULT_BUCKETS) is None


@pytest.mark.parametrize("symmetrize", [True, False])
def test_build_csr_unit_matches_generic(symmetrize):
    """Unit-weight int32 builder (cv_build_csr_unit): identical CSR to the
    generic path for weights=None, duplicates counted exactly."""
    from cuvite_tpu.core.graph import Graph

    nv, ne = 257, 4096
    src, dst, _ = _random_edges(ne, nv, seed=5)
    o, t, w = native.build_csr_unit(nv, src, dst, symmetrize=symmetrize)
    old = native._LIB
    native._LIB = False
    try:
        g = Graph.from_edges(nv, src, dst, symmetrize=symmetrize)
    finally:
        native._LIB = old
    assert np.array_equal(o, g.offsets)
    assert np.array_equal(t.astype(g.tails.dtype), g.tails)
    assert np.array_equal(w.astype(g.weights.dtype), g.weights)


def test_build_csr_unit_radix_branch():
    nv = (1 << 22) + 11
    ne = 4096
    rng = np.random.default_rng(3)
    src = rng.integers(nv - 300, nv, size=ne)
    dst = rng.integers(nv - 300, nv, size=ne)
    src[: ne // 4] = src[ne // 2: ne // 2 + ne // 4]
    dst[: ne // 4] = dst[ne // 2: ne // 2 + ne // 4]
    from cuvite_tpu.core.graph import Graph

    o, t, w = native.build_csr_unit(nv, src, dst, symmetrize=True)
    old = native._LIB
    native._LIB = False
    try:
        g = Graph.from_edges(nv, src, dst, symmetrize=True)
    finally:
        native._LIB = old
    assert np.array_equal(o, g.offsets)
    assert np.array_equal(t.astype(g.tails.dtype), g.tails)
    assert np.array_equal(w.astype(g.weights.dtype), g.weights)


def test_from_edges_unit_dispatch():
    """weights=None above the size threshold must take the int32 unit path
    and produce the exact same Graph as the generic native path."""
    from cuvite_tpu.core.graph import Graph

    nv = 1 << 12
    ne = native.MIN_NATIVE_EDGES + 17
    rng = np.random.default_rng(9)
    src = rng.integers(0, nv, ne)
    dst = rng.integers(0, nv, ne)
    g_unit = Graph.from_edges(nv, src, dst)                 # unit fast path
    g_gen = Graph.from_edges(nv, src, dst,
                             weights=np.ones(ne, dtype=np.float64))
    assert np.array_equal(g_unit.offsets, g_gen.offsets)
    assert np.array_equal(g_unit.tails, g_gen.tails)
    assert np.array_equal(g_unit.weights, g_gen.weights)


def _coarsen_ref(g, dense, nc):
    """The numpy coarsen route (relabel + generic from_edges), native off."""
    from cuvite_tpu.core.graph import Graph

    old = native._LIB
    native._LIB = False
    try:
        s2 = dense[g.sources()]
        d2 = dense[g.tails.astype(np.int64)]
        return Graph.from_edges(nc, s2, d2,
                                weights=g.weights.astype(np.float64),
                                symmetrize=False)
    finally:
        native._LIB = old


@pytest.mark.parametrize("nc_target", [100, 2500])
def test_coarsen_csr_matches_numpy(nc_target):
    """cv_coarsen (small-nc dense-accumulator path) is bit-identical to
    relabel + Graph.from_edges."""
    from cuvite_tpu.core.graph import Graph
    from cuvite_tpu.coarsen.rebuild import renumber_communities

    rng = np.random.default_rng(3)
    nv, ne = 3000, 20000
    src = rng.integers(0, nv, size=ne)
    dst = rng.integers(0, nv, size=ne)
    w = rng.integers(1, 32, size=ne) / 16.0
    g = Graph.from_edges(nv, src, dst, weights=w, symmetrize=True)
    dense, nc = renumber_communities(rng.integers(0, nc_target, size=nv))
    ref = _coarsen_ref(g, dense, nc)
    off, tails, wout = native.coarsen_csr(
        g.offsets, g.tails, g.weights, dense, nc)
    assert np.array_equal(off, ref.offsets)
    assert np.array_equal(tails, ref.tails)
    assert np.array_equal(wout, ref.weights)


def test_coarsen_csr_radix_branch():
    """nc > 2^22 forces cv_coarsen's LSD-radix branch; bit-identity must
    hold there too (production coarsen of phase-0 benchmark graphs)."""
    from cuvite_tpu.core.graph import Graph
    from cuvite_tpu.coarsen.rebuild import renumber_communities

    rng = np.random.default_rng(4)
    nv, ne = 9_000_000, 120_000
    src = rng.integers(0, nv, size=ne)
    dst = rng.integers(0, nv, size=ne)
    g = Graph.from_edges(nv, src, dst, symmetrize=True)
    dense, nc = renumber_communities(rng.integers(0, 8_500_000, size=nv))
    assert nc > 1 << 22  # radix branch precondition
    ref = _coarsen_ref(g, dense, nc)
    off, tails, wout = native.coarsen_csr(
        g.offsets, g.tails, g.weights, dense, nc)
    assert np.array_equal(off, ref.offsets)
    assert np.array_equal(tails, ref.tails)
    assert np.array_equal(wout, ref.weights)


def test_coarsen_graph_dispatch():
    """coarsen_graph above the size threshold must take the native fused
    path and produce the exact same Graph as the numpy route."""
    from cuvite_tpu.core.graph import Graph
    from cuvite_tpu.coarsen.rebuild import coarsen_graph, renumber_communities

    rng = np.random.default_rng(5)
    nv = 1 << 12
    ne = native.MIN_NATIVE_EDGES + 41
    src = rng.integers(0, nv, ne)
    dst = rng.integers(0, nv, ne)
    g = Graph.from_edges(nv, src, dst)
    assert g.num_edges >= native.MIN_NATIVE_EDGES
    dense, nc = renumber_communities(rng.integers(0, 500, size=nv))
    got = coarsen_graph(g, dense, nc)
    ref = _coarsen_ref(g, dense, nc)
    assert np.array_equal(got.offsets, ref.offsets)
    assert np.array_equal(got.tails, ref.tails)
    assert np.array_equal(got.weights, ref.weights)


def test_weighted_degrees_native_matches_numpy():
    from cuvite_tpu.core.graph import Graph

    rng = np.random.default_rng(6)
    nv, ne = 5000, 70000
    src = rng.integers(0, nv, size=ne)
    dst = rng.integers(0, nv, size=ne)
    w = rng.random(ne)
    g = Graph.from_edges(nv, src, dst, weights=w, symmetrize=True)
    ref = np.bincount(g.sources(), weights=g.weights.astype(np.float64),
                      minlength=nv).astype(g.policy.weight_dtype)
    assert np.array_equal(g.weighted_degrees(), ref)


def test_distgraph_single_shard_fast_path():
    """The nshards=1 identity fast path must produce the same slabs as the
    generic remap route (checked against directly computed expectations)."""
    from cuvite_tpu.core.distgraph import DistGraph
    from cuvite_tpu.core.graph import Graph

    rng = np.random.default_rng(7)
    nv, ne = 1000, 8000
    src = rng.integers(0, nv, size=ne)
    dst = rng.integers(0, nv, size=ne)
    g = Graph.from_edges(nv, src, dst, weights=rng.random(ne))
    dg = DistGraph.build(g, 1)
    sh = dg.shards[0]
    n = g.num_edges
    assert sh.n_real_edges == n
    assert np.array_equal(sh.src[:n],
                          g.sources().astype(sh.src.dtype))
    assert np.array_equal(sh.dst[:n], g.tails.astype(sh.dst.dtype))
    assert np.array_equal(sh.w[:n], g.weights)
    assert np.all(sh.src[n:] == dg.nv_pad)
    assert np.all(sh.w[n:] == 0)
    assert np.array_equal(dg.old_to_pad, np.arange(nv))


@pytest.mark.parametrize("symmetrize", [True, False])
@pytest.mark.parametrize("id_dtype", [np.int32, np.int64])
def test_build_csr_w32_matches_generic(symmetrize, id_dtype):
    """Weighted index-payload builder (cv_build_csr_w32): identical CSR to
    the generic f64-payload path after the f32 policy cast, for both input
    id widths (no width conversion happens natively)."""
    from cuvite_tpu.core.graph import Graph

    nv, ne = 257, 4096
    src, dst, w = _random_edges(ne, nv, seed=11)
    o, t, wf = native.build_csr_w(nv, src.astype(id_dtype),
                                  dst.astype(id_dtype), w,
                                  symmetrize=symmetrize)
    g = Graph.from_edges(nv, src, dst, weights=w, symmetrize=symmetrize)
    assert np.array_equal(o, g.offsets)
    assert np.array_equal(t.astype(g.tails.dtype), g.tails)
    assert np.array_equal(wf, g.weights)


def test_build_csr_w32_radix_branch_large_nv():
    """nv > 2^22 puts the generic path on its radix branch and enables the
    from_edges w32 dispatch gate; both must agree bit-for-bit."""
    from cuvite_tpu.core.graph import Graph

    nv = (1 << 22) + 19
    ne = native.MIN_NATIVE_EDGES + 512  # also crosses the dispatch gate
    rng = np.random.default_rng(13)
    src = rng.integers(nv - 500, nv, size=ne)
    dst = rng.integers(nv - 500, nv, size=ne)
    src[: ne // 4] = src[ne // 2: ne // 2 + ne // 4]
    dst[: ne // 4] = dst[ne // 2: ne // 2 + ne // 4]
    w = rng.random(ne)
    o, t, wf = native.build_csr_w(nv, src, dst, w, symmetrize=True)
    old = native._LIB
    native._LIB = False
    try:
        g = Graph.from_edges(nv, src, dst, weights=w, symmetrize=True)
    finally:
        native._LIB = old
    assert np.array_equal(o, g.offsets)
    assert np.array_equal(t.astype(g.tails.dtype), g.tails)
    assert np.array_equal(wf, g.weights)
    # from_edges with the native lib enabled dispatches to the same path.
    g2 = Graph.from_edges(nv, src, dst, weights=w, symmetrize=True)
    assert np.array_equal(g2.weights, g.weights)
    assert np.array_equal(g2.tails, g.tails)
