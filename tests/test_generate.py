"""Generator + RNG tests (LCG parity values from a compiled C++ oracle
running the reference's reseeder/LCG, utils.hpp:76-271)."""

import numpy as np
import pytest

from cuvite_tpu.io.generate import generate_rgg, generate_rmat, rgg_points, rgg_radius
from cuvite_tpu.louvain.driver import louvain_phases
from cuvite_tpu.utils.rng import MLCG, lcg_jump, lcg_stream, reseeder


def test_reseeder_matches_cpp_seed_seq():
    # std::seed_seq({1u}) / ({42u}) single-word outputs
    assert reseeder(1) == 1967017404
    assert reseeder(42) == 2934951935


def test_lcg_sequence_matches_reference():
    expected = [1967017404, 1298247110, 1205324250, 671427599,
                1804575055, 581402804, 586332978, 1843388810]
    s = lcg_stream(1, 8)
    got = [int(round(v * MLCG)) for v in s]
    assert got == expected


def test_lcg_jump_consistent_with_stream():
    full = lcg_stream(1, 100)
    for lo in (0, 1, 17, 64, 99):
        sliced = lcg_stream(1, 100, lo=lo, hi=100)
        np.testing.assert_allclose(sliced, full[lo:], rtol=0, atol=0)
    assert lcg_jump(reseeder(1), 5) == 581402804


def test_rgg_points_in_strips():
    nv, p = 1024, 4
    x, y = rgg_points(nv, p, seed=1)
    n = nv // p
    assert len(x) == nv
    for s in range(p):
        ys = y[s * n : (s + 1) * n]
        assert np.all(ys >= s / p) and np.all(ys < (s + 1) / p + 1e-12)
    assert np.all((x >= 0) & (x <= 2.0))  # element 0 may exceed 1 (ref quirk)


def test_rgg_shard_count_invariance_of_stream():
    """The same global stream is sliced per shard: x coords of shard s for
    p=4 equal stream slice [s*2n, s*2n+n)."""
    nv = 256
    x4, _ = rgg_points(nv, 4, seed=1)
    full = lcg_stream(1, 2 * nv)
    n = nv // 4
    np.testing.assert_allclose(x4[:n], full[:n])
    np.testing.assert_allclose(x4[n : 2 * n], full[2 * n : 3 * n])


def test_rgg_graph_properties():
    g = generate_rgg(512, nshards=2, seed=1)
    assert g.num_vertices == 512
    assert g.num_edges > 0
    # weights are euclidean distances <= rn
    assert g.weights.max() <= rgg_radius(512) + 1e-6
    # symmetric: both directions present
    assert g.num_edges % 2 == 0


def test_rgg_strip_too_narrow_raises():
    with pytest.raises(ValueError):
        generate_rgg(128, nshards=64)


def test_rgg_louvain_finds_structure():
    g = generate_rgg(512, seed=1)
    res = louvain_phases(g)
    assert res.modularity > 0.5  # RGGs are strongly modular


def test_rmat_shape_and_degree_skew():
    g = generate_rmat(10, edge_factor=8, seed=3)
    assert g.num_vertices == 1024
    deg = g.degrees()
    # power-lawish: max degree far above mean
    assert deg.max() > 4 * deg.mean()


def test_rmat_deterministic():
    g1 = generate_rmat(8, seed=7)
    g2 = generate_rmat(8, seed=7)
    np.testing.assert_array_equal(g1.tails, g2.tails)


def test_minstd0_weight_matches_libstdcxx_oracle(tmp_path):
    """The far-target extra-edge weight must be bit-identical to the
    reference's actual C++ expression (distgraph.cpp:755-757): an
    identity-hash-seeded minstd_rand0 driving
    uniform_real_distribution<double>(0.01, 1.0).  Oracle: compile and run
    that exact standard-library expression with the system g++."""
    import subprocess
    import sys

    from cuvite_tpu.utils.rng import minstd0_uniform_real

    src = tmp_path / "oracle.cpp"
    src.write_text(
        "#include <cstdint>\n#include <cstdio>\n#include <random>\n"
        "#include <functional>\n"
        "int main(int argc, char** argv) {\n"
        "  for (int k = 1; k < argc; ++k) {\n"
        "    long long key = atoll(argv[k]);\n"
        "    std::hash<long long> reh;\n"
        "    unsigned seed = (unsigned)reh(key);\n"
        "    std::default_random_engine re(seed);\n"
        "    std::uniform_real_distribution<double> d;\n"
        "    double w = d(re, std::uniform_real_distribution<double>::"
        "param_type{0.01, 1.0});\n"
        "    printf(\"%.17g\\n\", w);\n"
        "  }\n  return 0;\n}\n"
    )
    exe = tmp_path / "oracle"
    subprocess.run(["g++", "-O2", "-o", str(exe), str(src)], check=True)
    keys = np.array([0, 1, 7, 2147483646, 2147483647, 123456789012345,
                     34 * 34 + 5, 2**31, 2**32 - 1, 2**32, 987654321],
                    dtype=np.int64)
    out = subprocess.run([str(exe)] + [str(k) for k in keys],
                         capture_output=True, text=True, check=True)
    oracle = np.array([float(x) for x in out.stdout.split()])
    ours = minstd0_uniform_real(keys.astype(np.uint64), 0.01, 1.0)
    np.testing.assert_array_equal(ours, oracle)


def test_rgg_extra_edges_deterministic_and_weighted():
    g1 = generate_rgg(512, nshards=4, random_edge_percent=20, seed=1)
    g2 = generate_rgg(512, nshards=4, random_edge_percent=20, seed=1)
    np.testing.assert_array_equal(g1.tails, g2.tails)
    np.testing.assert_array_equal(g1.weights, g2.weights)
    base = generate_rgg(512, nshards=4, seed=1)
    # ~20% extra undirected edges, minus self/duplicate forfeits
    extra = (g1.num_edges - base.num_edges) // 2
    target = (20 * (base.num_edges // 2)) // 100
    assert 0 < extra <= target
    assert extra > target // 2
    g3 = generate_rgg(512, nshards=4, random_edge_percent=20, seed=2)
    assert not np.array_equal(g1.tails, g3.tails)


def test_rgg_extra_far_weights_in_range():
    """Far-target extra edges carry the hash-seeded uniform[0.01, 1.0)
    weight; near (strip-neighbor) targets carry the true distance."""
    from cuvite_tpu.io.generate import _rgg_extra_edges, rgg_points

    nv, p = 512, 4
    n = nv // p
    x, y = rgg_points(nv, p, 1)
    pts = np.stack([x, y], axis=1)
    gi, gj, w = _rgg_extra_edges(pts, p, n, nv, 50, 1000,
                                 np.zeros((0, 2), dtype=np.int64), 1)
    far = np.abs(gi // n - gj // n) > 1
    assert far.any() and (~far).any()
    assert np.all(w[far] >= 0.01) and np.all(w[far] < 1.0)
    d = np.sqrt(((pts[gi[~far]] - pts[gj[~far]]) ** 2).sum(axis=1))
    np.testing.assert_allclose(w[~far], d)
