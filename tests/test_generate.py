"""Generator + RNG tests (LCG parity values from a compiled C++ oracle
running the reference's reseeder/LCG, utils.hpp:76-271)."""

import numpy as np
import pytest

from cuvite_tpu.io.generate import generate_rgg, generate_rmat, rgg_points, rgg_radius
from cuvite_tpu.louvain.driver import louvain_phases
from cuvite_tpu.utils.rng import MLCG, lcg_jump, lcg_stream, reseeder


def test_reseeder_matches_cpp_seed_seq():
    # std::seed_seq({1u}) / ({42u}) single-word outputs
    assert reseeder(1) == 1967017404
    assert reseeder(42) == 2934951935


def test_lcg_sequence_matches_reference():
    expected = [1967017404, 1298247110, 1205324250, 671427599,
                1804575055, 581402804, 586332978, 1843388810]
    s = lcg_stream(1, 8)
    got = [int(round(v * MLCG)) for v in s]
    assert got == expected


def test_lcg_jump_consistent_with_stream():
    full = lcg_stream(1, 100)
    for lo in (0, 1, 17, 64, 99):
        sliced = lcg_stream(1, 100, lo=lo, hi=100)
        np.testing.assert_allclose(sliced, full[lo:], rtol=0, atol=0)
    assert lcg_jump(reseeder(1), 5) == 581402804


def test_rgg_points_in_strips():
    nv, p = 1024, 4
    x, y = rgg_points(nv, p, seed=1)
    n = nv // p
    assert len(x) == nv
    for s in range(p):
        ys = y[s * n : (s + 1) * n]
        assert np.all(ys >= s / p) and np.all(ys < (s + 1) / p + 1e-12)
    assert np.all((x >= 0) & (x <= 2.0))  # element 0 may exceed 1 (ref quirk)


def test_rgg_shard_count_invariance_of_stream():
    """The same global stream is sliced per shard: x coords of shard s for
    p=4 equal stream slice [s*2n, s*2n+n)."""
    nv = 256
    x4, _ = rgg_points(nv, 4, seed=1)
    full = lcg_stream(1, 2 * nv)
    n = nv // 4
    np.testing.assert_allclose(x4[:n], full[:n])
    np.testing.assert_allclose(x4[n : 2 * n], full[2 * n : 3 * n])


def test_rgg_graph_properties():
    g = generate_rgg(512, nshards=2, seed=1)
    assert g.num_vertices == 512
    assert g.num_edges > 0
    # weights are euclidean distances <= rn
    assert g.weights.max() <= rgg_radius(512) + 1e-6
    # symmetric: both directions present
    assert g.num_edges % 2 == 0


def test_rgg_strip_too_narrow_raises():
    with pytest.raises(ValueError):
        generate_rgg(128, nshards=64)


def test_rgg_louvain_finds_structure():
    g = generate_rgg(512, seed=1)
    res = louvain_phases(g)
    assert res.modularity > 0.5  # RGGs are strongly modular


def test_rmat_shape_and_degree_skew():
    g = generate_rmat(10, edge_factor=8, seed=3)
    assert g.num_vertices == 1024
    deg = g.degrees()
    # power-lawish: max degree far above mean
    assert deg.max() > 4 * deg.mean()


def test_rmat_deterministic():
    g1 = generate_rmat(8, seed=7)
    g2 = generate_rmat(8, seed=7)
    np.testing.assert_array_equal(g1.tails, g2.tails)
