"""Multi-host bootstrap and host-level collectives.

The reference bootstraps with MPI_Init + per-rank collective MPI-IO ingest
(/root/reference/main.cpp:67-70, distgraph.cpp:69-203) and binds GPUs via a
shared-memory sub-communicator (louvain_cuda.cu:1634-1669).  The TPU-native
analog: `jax.distributed.initialize` connects the processes of a multi-host
run (one process per host, e.g. 8 hosts x 8 chips on a v5p-64), after which
`jax.devices()` is the GLOBAL device list and a 1-D mesh over it spans the
pod slice.  Collectives then ride ICI within a host's chips and DCN across
hosts — XLA schedules them from the sharding, no transport code here.

Launch recipe (every host runs the same command):

    CUVITE_COORDINATOR=<host0-ip>:8476 \
    CUVITE_NUM_PROCESSES=8 CUVITE_PROCESS_ID=<0..7> \
    python -m cuvite_tpu.cli --file big.bin --shards 64 --distributed ...

On Cloud TPU the three env vars can be omitted entirely:
`jax.distributed.initialize()` auto-discovers the slice topology from the
TPU metadata server.

Design note: host-side planning (partitioning, bucket plans, ghost routing,
coarsening) is REPLICATED — every process computes the identical plan
deterministically from the same graph metadata, the way every MPI rank holds
the same `parts[]` table.  Device state is what is sharded.  Per-host ingest
can still read only the edge ranges this host's shards own
(`read_vite(vertex_range=...)`); the remaining host arrays are O(nv), not
O(ne).
"""

from __future__ import annotations

import os

import jax
import numpy as np

_INITIALIZED = False


def _enable_cpu_collectives() -> None:
    """Switch the CPU backend's cross-process collectives on (gloo).

    This image's jax (0.4.x) defaults ``jax_cpu_collectives_implementation``
    to ``'none'``, so a multi-process CPU run fails its FIRST collective with
    "Multiprocess computations aren't implemented on the CPU backend" — the
    historical tier-1 multihost failures.  Newer jax releases default to
    gloo and (eventually) drop the flag, hence the defensive lookup.  An
    explicit JAX_CPU_COLLECTIVES_IMPLEMENTATION (e.g. 'mpi') always wins;
    TPU runs are unaffected (the flag only configures the CPU client).
    """
    if os.environ.get("JAX_CPU_COLLECTIVES_IMPLEMENTATION"):
        return
    try:
        holder = jax.config._value_holders[
            "jax_cpu_collectives_implementation"]
    except (AttributeError, KeyError):
        return  # flag absent: this jax already defaults to a working impl
    if holder.value in (None, "none"):
        jax.config.update("jax_cpu_collectives_implementation", "gloo")


def initialize(coordinator: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None,
               local_device_ids=None) -> None:
    """Connect this process to a multi-host run (MPI_Init analog).

    Arguments fall back to CUVITE_COORDINATOR / CUVITE_NUM_PROCESSES /
    CUVITE_PROCESS_ID, then to JAX's own auto-detection (which knows Cloud
    TPU, SLURM and OpenMPI environments).  Must run before the first
    device/backend touch.  Safe to call once per process.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    coordinator = coordinator or os.environ.get("CUVITE_COORDINATOR")
    if num_processes is None and os.environ.get("CUVITE_NUM_PROCESSES"):
        num_processes = int(os.environ["CUVITE_NUM_PROCESSES"])
    if process_id is None and os.environ.get("CUVITE_PROCESS_ID"):
        process_id = int(os.environ["CUVITE_PROCESS_ID"])
    # Must happen before the backend exists: the collectives implementation
    # is baked into the CPU client at creation.
    _enable_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _INITIALIZED = True


def is_distributed() -> bool:
    return jax.process_count() > 1


def local_shard_range(nshards: int) -> tuple[int, int]:
    """Contiguous [lo, hi) range of shard indices owned by this process when
    ``nshards`` vertex shards are laid over the global device list (device
    order groups each process's devices contiguously)."""
    per = nshards // jax.process_count()
    rem = nshards % jax.process_count()
    p = jax.process_index()
    lo = p * per + min(p, rem)
    return lo, lo + per + (1 if p < rem else 0)


def place(mesh, arr, spec):
    """Create a GLOBAL array on ``mesh`` with PartitionSpec ``spec`` from a
    host array that every process holds in full.

    Single-process: plain `jax.device_put`.  Multi-process: each process
    contributes only its addressable block via
    `jax.make_array_from_process_local_data` — the multi-host form of the
    same placement (device_put cannot target non-addressable devices).
    """
    from jax.sharding import NamedSharding

    sh = NamedSharding(mesh, spec)
    if not is_distributed():
        return jax.device_put(arr, sh)
    arr = np.asarray(arr)
    idx_map = sh.addressable_devices_indices_map(arr.shape)
    if not sh.is_fully_addressable:
        spans = [(0 if s[0].start is None else int(s[0].start),
                  arr.shape[0] if s[0].stop is None else int(s[0].stop))
                 for s in idx_map.values() if s]
        if spans and len(arr.shape) >= 1:
            lo = min(s[0] for s in spans)
            hi = max(s[1] for s in spans)
            if (lo, hi) != (0, arr.shape[0]):
                # Contiguous process-local block of a 1-D sharded axis.
                return jax.make_array_from_process_local_data(
                    sh, np.ascontiguousarray(arr[lo:hi]), arr.shape)
    # Replicated (or fully-local) value: local data IS the global value.
    return jax.make_array_from_process_local_data(sh, arr, arr.shape)


def place_block(mesh, local_rows: np.ndarray, global_rows: int, spec):
    """Create a global array whose axis-0 rows are sharded over ``mesh``
    from ONLY this process's contiguous row block (per-host ingest path:
    edge-sized arrays never exist in full on any host).  Single-process,
    the local block IS the global array."""
    from jax.sharding import NamedSharding

    sh = NamedSharding(mesh, spec)
    if not is_distributed():
        return jax.device_put(local_rows, sh)
    shape = (global_rows,) + tuple(local_rows.shape[1:])
    return jax.make_array_from_process_local_data(
        sh, np.ascontiguousarray(local_rows), shape)


def allreduce_sum_host(x):
    """Sum a small host value (scalar or ndarray) across processes."""
    if not is_distributed():
        return x
    from jax.experimental import multihost_utils

    parts = multihost_utils.process_allgather(np.asarray(x))
    return parts.sum(axis=0)


def allreduce_max_host(x: np.ndarray) -> np.ndarray:
    """Element-wise max of a small host array across processes (used to
    agree on padded plan shapes, which must be identical on every process
    for the SPMD step to compile to one program)."""
    if not is_distributed():
        return np.asarray(x)
    from jax.experimental import multihost_utils

    parts = multihost_utils.process_allgather(np.asarray(x))
    return np.asarray(parts).max(axis=0)


def allgather_varlen(arr: np.ndarray) -> list:
    """All-gather one variable-length 1-D array per process; returns the
    list of every process's array (the host analog of the reference's
    Alltoall size exchange + Isend/Irecv id lists in exchangeVertexReqs,
    /root/reference/louvain.cpp:3118-3264)."""
    if not is_distributed():
        return [np.asarray(arr)]
    from jax.experimental import multihost_utils

    arr = np.asarray(arr)
    lens = multihost_utils.process_allgather(
        np.array([len(arr)], dtype=np.int64))
    lens = np.asarray(lens).reshape(-1)
    m = max(int(lens.max()), 1)
    # arr.dtype is valid even for empty arrays; every process MUST present
    # the same dtype or the collective is malformed.
    buf = np.zeros(m, dtype=arr.dtype)
    buf[: len(arr)] = arr
    allb = np.asarray(multihost_utils.process_allgather(buf))
    return [allb[p, : int(lens[p])] for p in range(len(lens))]


def gather_global(arr) -> np.ndarray:
    """Fetch a (possibly multi-host sharded) global jax array to a full host
    numpy array on EVERY process — the `MPI_Allgatherv` of the output path
    (cf. gatherAllComm, /root/reference/louvain.cpp:3306-3347)."""
    if not is_distributed():
        return np.asarray(jax.device_get(arr))  # graftlint: disable=R018 — gather_global IS the sanctioned host gather; phase-transition callers opt in per site (R010 disables at _phase_sync / the final label gather)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))
