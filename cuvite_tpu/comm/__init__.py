"""cuvite_tpu.comm"""
