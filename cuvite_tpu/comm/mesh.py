"""Device mesh helpers.

The reference bootstraps distribution with MPI_Init + a shared-memory
sub-communicator for GPU binding (/root/reference/main.cpp:67-74,
louvain_cuda.cu:1634-1669).  The TPU-native analog is a 1-D
`jax.sharding.Mesh` over all addressable devices; multi-host deployments call
`jax.distributed.initialize` before building it.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

VERTEX_AXIS = "v"


def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` across the jax versions this repo meets.

    jax >= 0.6 ships the top-level API with the replication check named
    ``check_vma``; 0.4.x only has ``jax.experimental.shard_map.shard_map``
    with the same semantics under ``check_rep``.  Every SPMD factory
    (louvain/step.py, louvain/bucketed.py) routes through this wrapper so
    the engines run on both — the image's TPU toolchain pins one version,
    CI containers another.

    ``check_vma`` defaults to True to MATCH jax's own default (losing
    the replication check silently would let a dropped psum ship
    per-shard-divergent "replicated" outputs); the engine factories all
    opt out explicitly, as they did against the raw API.

    Like the new API, callable with or without ``f``: omitting it returns
    a decorator (``@shard_map(mesh=..., in_specs=..., out_specs=...)``).
    """
    if f is None:
        return lambda fn: shard_map(fn, mesh=mesh, in_specs=in_specs,
                                    out_specs=out_specs,
                                    check_vma=check_vma)
    if hasattr(jax, "shard_map"):
        impl = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as impl
    # The check kwarg is detected from the signature, not from which
    # module served the function: releases between the top-level export
    # and the check_rep->check_vma rename ship jax.shard_map with the
    # OLD kwarg, so keying the name on attribute presence alone would
    # TypeError exactly in that window.
    import inspect

    try:
        has_vma = "check_vma" in inspect.signature(impl).parameters
    except (TypeError, ValueError):  # builtins/odd wrappers: assume new
        has_vma = True
    kw = {"check_vma" if has_vma else "check_rep": check_vma}
    return impl(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"requested a {n_devices}-device mesh but only "
                    f"{len(devices)} jax device(s) are visible; for a "
                    f"virtual CPU mesh set XLA_FLAGS="
                    f"--xla_force_host_platform_device_count={n_devices} "
                    f"before jax initializes"
                )
            if jax.process_count() > 1 and n_devices != len(devices):
                # Slicing jax.devices()[:n] would keep only the lowest
                # ranks' devices, leaving other processes with no
                # addressable mesh entry — a deadlock, not a smaller run.
                raise ValueError(
                    f"a multi-process mesh must span all "
                    f"{len(devices)} global devices; got n_devices="
                    f"{n_devices} (launch fewer processes/devices instead)"
                )
            devices = devices[:n_devices]
    return Mesh(np.array(devices), (VERTEX_AXIS,))


def shard_1d(mesh: Mesh, arr, replicate: bool = False):
    """Place an array on the mesh, sharded along axis 0 (or replicated).
    Works on single-process and multi-host meshes alike (the latter via
    per-process local blocks, comm/multihost.py)."""
    from cuvite_tpu.comm.multihost import place

    spec = P() if replicate else P(VERTEX_AXIS)
    return place(mesh, arr, spec)
