"""Device mesh helpers.

The reference bootstraps distribution with MPI_Init + a shared-memory
sub-communicator for GPU binding (/root/reference/main.cpp:67-74,
louvain_cuda.cu:1634-1669).  The TPU-native analog is a 1-D
`jax.sharding.Mesh` over all addressable devices; multi-host deployments call
`jax.distributed.initialize` before building it.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

VERTEX_AXIS = "v"

# Two-level exchange axes (ISSUE 18): the hybrid mesh factors the flat
# vertex axis into a slow outer axis (DCN / data-center network, or
# host-to-host) and a fast inner axis (ICI / the chip interconnect of
# one slice).  Community tables replicate only inside the ICI submesh;
# cross-group traffic rides the sparse ghost protocol on the DCN axis.
DCN_AXIS = "dcn"
ICI_AXIS = "ici"


def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` across the jax versions this repo meets.

    jax >= 0.6 ships the top-level API with the replication check named
    ``check_vma``; 0.4.x only has ``jax.experimental.shard_map.shard_map``
    with the same semantics under ``check_rep``.  Every SPMD factory
    (louvain/step.py, louvain/bucketed.py) routes through this wrapper so
    the engines run on both — the image's TPU toolchain pins one version,
    CI containers another.

    ``check_vma`` defaults to True to MATCH jax's own default (losing
    the replication check silently would let a dropped psum ship
    per-shard-divergent "replicated" outputs); the engine factories all
    opt out explicitly, as they did against the raw API.

    Like the new API, callable with or without ``f``: omitting it returns
    a decorator (``@shard_map(mesh=..., in_specs=..., out_specs=...)``).
    """
    if f is None:
        return lambda fn: shard_map(fn, mesh=mesh, in_specs=in_specs,
                                    out_specs=out_specs,
                                    check_vma=check_vma)
    if hasattr(jax, "shard_map"):
        impl = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as impl
    # The check kwarg is detected from the signature, not from which
    # module served the function: releases between the top-level export
    # and the check_rep->check_vma rename ship jax.shard_map with the
    # OLD kwarg, so keying the name on attribute presence alone would
    # TypeError exactly in that window.
    import inspect

    try:
        has_vma = "check_vma" in inspect.signature(impl).parameters
    except (TypeError, ValueError):  # builtins/odd wrappers: assume new
        has_vma = True
    kw = {"check_vma" if has_vma else "check_rep": check_vma}
    return impl(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"requested a {n_devices}-device mesh but only "
                    f"{len(devices)} jax device(s) are visible; for a "
                    f"virtual CPU mesh set XLA_FLAGS="
                    f"--xla_force_host_platform_device_count={n_devices} "
                    f"before jax initializes"
                )
            if jax.process_count() > 1 and n_devices != len(devices):
                # Slicing jax.devices()[:n] would keep only the lowest
                # ranks' devices, leaving other processes with no
                # addressable mesh entry — a deadlock, not a smaller run.
                raise ValueError(
                    f"a multi-process mesh must span all "
                    f"{len(devices)} global devices; got n_devices="
                    f"{n_devices} (launch fewer processes/devices instead)"
                )
            devices = devices[:n_devices]
    return Mesh(np.array(devices), (VERTEX_AXIS,))


def make_hybrid_mesh(dcn: int, ici: int, devices=None) -> Mesh:
    """2-D ``('dcn', 'ici')`` mesh for the two-level exchange.

    On a multi-slice TPU deployment this prefers
    ``mesh_utils.create_hybrid_device_mesh`` (SNIPPETS.md [1]) so the
    outer axis really maps to the slow inter-slice network.  Everywhere
    else — single slice, CPU virtual devices, tier-1 — it falls back to
    a factored reshape of the flat device list into ``[dcn, ici]`` with
    the ICI axis innermost (consecutive devices, which on a real slice
    are the physically adjacent ones).  The factored fallback exercises
    the REAL 2-axis collectives, so the CPU test tier covers the same
    program a hybrid deployment compiles.

    The flattened device order equals ``make_mesh(dcn * ici)``'s order,
    which is what makes the two-level shard numbering (shard
    ``g * ici + i`` owns ``[s*nv_pad, (s+1)*nv_pad)``) line up with the
    flat exchange's contiguous ownership map bit-for-bit.
    """
    if dcn < 1 or ici < 1:
        raise ValueError(f"mesh factors must be >= 1, got {dcn}x{ici}")
    n = dcn * ici
    if devices is None:
        flat = make_mesh(n).devices.reshape(-1)
    else:
        flat = np.asarray(devices).reshape(-1)
        if flat.size != n:
            raise ValueError(
                f"hybrid mesh {dcn}x{ici} needs {n} devices, got {flat.size}")
    if dcn > 1 and len({getattr(d, "slice_index", 0) for d in flat}) == dcn:
        # Real multi-slice topology: let jax group by slice so the DCN
        # axis crosses slices and the ICI axis stays inside one.
        try:
            from jax.experimental import mesh_utils

            arr = mesh_utils.create_hybrid_device_mesh(
                (ici,), (dcn,), devices=list(flat)).reshape(dcn, ici)
            return Mesh(arr, (DCN_AXIS, ICI_AXIS))
        except Exception:
            pass  # fall through to the factored reshape
    return Mesh(flat.reshape(dcn, ici), (DCN_AXIS, ICI_AXIS))


def hybrid_shape(mesh: Mesh) -> tuple[int, int]:
    """(n_dcn, n_ici) of a hybrid mesh; (1, n) for a flat 1-D mesh."""
    if mesh.axis_names == (DCN_AXIS, ICI_AXIS):
        return (mesh.devices.shape[0], mesh.devices.shape[1])
    return (1, int(np.prod(mesh.devices.shape)))


def vertex_spec(mesh: Mesh) -> P:
    """PartitionSpec sharding axis 0 across EVERY mesh axis — the vertex
    layout.  ``P('v')`` on the flat mesh, ``P(('dcn','ici'))`` on the
    hybrid one (dcn-major, matching the flat device order)."""
    if mesh.axis_names == (DCN_AXIS, ICI_AXIS):
        return P((DCN_AXIS, ICI_AXIS))
    return P(VERTEX_AXIS)


def shard_1d(mesh: Mesh, arr, replicate: bool = False):
    """Place an array on the mesh, sharded along axis 0 (or replicated).
    On a hybrid mesh axis 0 shards across both axes dcn-major, so the
    per-device blocks are identical to the flat mesh's.  Works on
    single-process and multi-host meshes alike (the latter via
    per-process local blocks, comm/multihost.py)."""
    from cuvite_tpu.comm.multihost import place

    spec = P() if replicate else vertex_spec(mesh)
    return place(mesh, arr, spec)


def shard_outer(mesh: Mesh, arr):
    """Place an array sharded along axis 0 over the OUTER (dcn) axis
    only — replicated inside each ICI group.  The layout of the grouped
    exchange-plan arrays: every ici sibling drives the same group-scale
    sparse protocol, so each needs the whole group's plan rows."""
    from cuvite_tpu.comm.multihost import place

    if mesh.axis_names != (DCN_AXIS, ICI_AXIS):
        return place(mesh, arr, P(VERTEX_AXIS))
    return place(mesh, arr, P(DCN_AXIS))
