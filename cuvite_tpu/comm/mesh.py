"""Device mesh helpers.

The reference bootstraps distribution with MPI_Init + a shared-memory
sub-communicator for GPU binding (/root/reference/main.cpp:67-74,
louvain_cuda.cu:1634-1669).  The TPU-native analog is a 1-D
`jax.sharding.Mesh` over all addressable devices; multi-host deployments call
`jax.distributed.initialize` before building it.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

VERTEX_AXIS = "v"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"requested a {n_devices}-device mesh but only "
                    f"{len(devices)} jax device(s) are visible; for a "
                    f"virtual CPU mesh set XLA_FLAGS="
                    f"--xla_force_host_platform_device_count={n_devices} "
                    f"before jax initializes"
                )
            if jax.process_count() > 1 and n_devices != len(devices):
                # Slicing jax.devices()[:n] would keep only the lowest
                # ranks' devices, leaving other processes with no
                # addressable mesh entry — a deadlock, not a smaller run.
                raise ValueError(
                    f"a multi-process mesh must span all "
                    f"{len(devices)} global devices; got n_devices="
                    f"{n_devices} (launch fewer processes/devices instead)"
                )
            devices = devices[:n_devices]
    return Mesh(np.array(devices), (VERTEX_AXIS,))


def shard_1d(mesh: Mesh, arr, replicate: bool = False):
    """Place an array on the mesh, sharded along axis 0 (or replicated).
    Works on single-process and multi-host meshes alike (the latter via
    per-process local blocks, comm/multihost.py)."""
    from cuvite_tpu.comm.multihost import place

    spec = P() if replicate else P(VERTEX_AXIS)
    return place(mesh, arr, spec)
