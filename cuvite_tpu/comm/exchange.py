"""Sparse ghost exchange: per-phase static routing + O(ghosts) per-iteration
communication.

This is the TPU-native analog of the reference's three-part protocol:

  exchangeVertexReqs   (/root/reference/louvain.cpp:3118-3264) — once per
      phase, discover which non-owned vertices each rank references and who
      must send them.  Here: ``ExchangePlan`` built on host from the shard
      edge slabs — ghost lists, per-peer send indices, and a static
      all_to_all block layout (counts known per phase, so the exchange
      compiles to fixed ICI schedules).
  fillRemoteCommunities (/root/reference/louvain.cpp:2588-2959) — per
      iteration, pull communities of referenced boundary vertices and the
      Comm{size,degree} of referenced remote communities.  Here:
      ``sparse_env`` — one dense all_to_all over the phase-static ghost plan
      pulls per-vertex attached values (community id, community degree,
      community size); community info itself is resolved by a budgeted
      owner-reduce (below).
  updateRemoteCommunities (/root/reference/louvain.cpp:2983-3116) — per
      iteration, push community size/degree deltas to owner ranks.  Here:
      community degree/size are *recomputed* each iteration (drift-free) but
      kept SHARDED BY OWNER: each shard reduces its owned vertices'
      contributions by community, short-circuits self-owned communities, and
      routes remote-owned unique (community, partial) entries to the
      community's owner through a fixed per-peer budget; owners reduce and
      reply with totals over the transposed routing.

Why vertex-attached values: the gain kernel needs ``comm_deg[comm[u]]`` and
``comm_size[comm[u]]`` for every referenced vertex u.  Attaching those values
to u at its owner means they ride the SAME static ghost routing as ``comm``
itself — no dynamic-shape exchange anywhere.  Per-chip per-iteration traffic
is O(ghosts + remote-referenced communities), not O(total vertices), and the
only replicated arrays are scalars.

The per-peer budget is the one place the worst case exceeds the static
shape: a shard may reference more remote communities of one peer than the
budget covers.  The step then raises an ``overflow`` flag (results of that
sweep are invalid) and the driver re-runs the phase with a doubled budget —
the analog of the reference growing its send buffers, amortized to at most
log(nv) recompiles.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from cuvite_tpu.core.types import next_pow2
from cuvite_tpu.ops import segment as seg


@dataclasses.dataclass
class ExchangePlan:
    """Phase-static ghost routing for a DistGraph partition.

    Shapes (S = nshards, B = max per-pair request count padded,
    G = max ghost count padded):

    ``send_idx[t, s, b]`` — local vertex index (at shard t) of the b-th value
        shard t must send to shard s each iteration; ``nv_pad`` marks padding.
    ``ghost_sel[s, g]`` — flat index into shard s's received [S, B] block
        (peer-major) holding ghost g's value; ghosts are sorted by global id,
        hence grouped by owner, so the selection is a pure permutation.
    ``ghost_ids[s]`` — sorted global (padded-space) ids of shard s's ghosts.

    Two-level mode (:meth:`build_grouped`): the "shards" of the plan are
    DCN GROUPS of ``ici`` consecutive device shards each — ``nv_pad`` is
    then the GROUP window ``ici * shard_nv_pad``, routing runs on the
    slow outer axis only, and ghosts are vertices referenced outside the
    whole group (intra-group references are satisfied by the ICI-local
    all_gather instead).
    """

    nshards: int
    nv_pad: int
    block: int                 # B: per-pair all_to_all block size
    ghost_pad: int             # G: padded ghost-table length
    send_idx: np.ndarray       # [S, S, B] int32
    ghost_sel: np.ndarray      # [S, G] int32
    ghost_ids: list            # list[np.ndarray] per shard
    max_ghosts: int
    ici: int = 1               # device shards per plan shard (dcn group)
    shard_nv_pad: int = 0      # per-device owned window (0 -> nv_pad)

    @staticmethod
    def build(dg) -> "ExchangePlan":
        """Full plan from an all-shards-resident DistGraph.  Per-host-ingest
        partitions (``dg.local_only``) discover ghosts from LOCAL shards and
        allgather the per-shard ghost id lists so every process can build
        its rows of the routing — the literal exchangeVertexReqs flow
        (scan local edges -> exchange referenced-vertex lists,
        /root/reference/louvain.cpp:3118-3264); ``send_idx`` / ``ghost_sel``
        then hold only this process's shard rows (place with place_block)."""
        S, nvp = dg.nshards, dg.nv_pad
        local_only = getattr(dg, "local_only", False)
        lo, hi = (dg.local_lo, dg.local_hi) if local_only else (0, S)
        ghost_local = []
        for s in range(lo, hi):
            sh = dg.shards[s]
            real = np.asarray(sh.src) < nvp
            d = np.asarray(sh.dst)[real].astype(np.int64)
            owned = (d >= s * nvp) & (d < (s + 1) * nvp)
            ghost_local.append(np.unique(d[~owned]))
        if local_only:
            # Host allgather of every shard's referenced-ghost list (the
            # Alltoall sizes + id exchange of exchangeVertexReqs).
            from cuvite_tpu.comm.multihost import allgather_varlen

            lens = np.array([len(g) for g in ghost_local], dtype=np.int64)
            flat = (np.concatenate(ghost_local) if ghost_local
                    else np.zeros(0, dtype=np.int64))
            lens_all = allgather_varlen(lens)
            flat_all = allgather_varlen(flat)
            ghost_ids = []
            for ls, fl in zip(lens_all, flat_all):
                off = 0
                for n in ls:
                    ghost_ids.append(fl[off: off + int(n)])
                    off += int(n)
            assert len(ghost_ids) == S
        else:
            ghost_ids = ghost_local
        bounds = [np.searchsorted(g, np.arange(S + 1) * nvp)
                  for g in ghost_ids]
        max_g = max((len(g) for g in ghost_ids), default=0)
        G = next_pow2(max(max_g, 1))
        B = 1
        for s in range(S):
            if len(ghost_ids[s]):
                B = max(B, int(np.max(np.diff(bounds[s]))))
        B = next_pow2(B)
        # Rows this process materializes: all shards when fully resident,
        # the local range under per-host ingest.  Per shard the routing is
        # one vectorized pass over its ghost list (owner = id // nv_pad,
        # rank = position within the owner group): O(S + G_s) — the former
        # S x S python loop cost S^2 small slice ops, minutes at S = 64
        # (VERDICT r2 item 3).
        n_rows = hi - lo
        send_idx = np.full((n_rows, S, B), nvp, dtype=np.int32)
        ghost_sel = np.zeros((n_rows, G), dtype=np.int32)
        for s in range(S):
            gids, bnd = ghost_ids[s], bounds[s]
            if not len(gids):
                continue
            owner = gids // nvp                       # sorted, group-major
            rank = np.arange(len(gids), dtype=np.int64) - bnd[owner]
            if lo <= s < hi:
                ghost_sel[s - lo, : len(gids)] = (
                    owner * B + rank).astype(np.int32)
            m = (owner >= lo) & (owner < hi)
            if m.any():
                send_idx[owner[m] - lo, s, rank[m]] = (
                    gids[m] - owner[m] * nvp).astype(np.int32)
        return ExchangePlan(
            nshards=S, nv_pad=nvp, block=B, ghost_pad=G,
            send_idx=send_idx, ghost_sel=ghost_sel, ghost_ids=ghost_ids,
            max_ghosts=max_g,
        )

    @staticmethod
    def build_grouped(dg, n_dcn: int) -> "ExchangePlan":
        """Two-level plan: route on the slow DCN axis between GROUPS of
        ``dg.nshards // n_dcn`` consecutive shards.  Each group's window
        is ``nv_grp = ici * nv_pad`` padded-global ids (dcn-major shard
        order, so group g owns exactly the flat shards
        ``[g*ici, (g+1)*ici)``); ghosts are ids referenced by ANY member
        shard outside the group.  Intra-group references need no routing
        — the per-iteration ICI all_gather covers them."""
        S, nvp = dg.nshards, dg.nv_pad
        if getattr(dg, "local_only", False):
            raise NotImplementedError(
                "two-level exchange does not support per-host ingest yet")
        if n_dcn < 1 or S % n_dcn:
            raise ValueError(
                f"dcn={n_dcn} must divide nshards={S}")
        ici = S // n_dcn
        nv_grp = ici * nvp
        ghost_ids = []
        for g in range(n_dcn):
            refs = []
            for sh in dg.shards[g * ici:(g + 1) * ici]:
                real = np.asarray(sh.src) < nvp
                d = np.asarray(sh.dst)[real].astype(np.int64)
                owned = (d >= g * nv_grp) & (d < (g + 1) * nv_grp)
                refs.append(d[~owned])
            ghost_ids.append(np.unique(np.concatenate(refs)) if refs
                             else np.zeros(0, dtype=np.int64))
        bounds = [np.searchsorted(gi, np.arange(n_dcn + 1) * nv_grp)
                  for gi in ghost_ids]
        max_g = max((len(gi) for gi in ghost_ids), default=0)
        G = next_pow2(max(max_g, 1))
        B = 1
        for g in range(n_dcn):
            if len(ghost_ids[g]):
                B = max(B, int(np.max(np.diff(bounds[g]))))
        B = next_pow2(B)
        send_idx = np.full((n_dcn, n_dcn, B), nv_grp, dtype=np.int32)
        ghost_sel = np.zeros((n_dcn, G), dtype=np.int32)
        for g in range(n_dcn):
            gids, bnd = ghost_ids[g], bounds[g]
            if not len(gids):
                continue
            owner = gids // nv_grp
            rank = np.arange(len(gids), dtype=np.int64) - bnd[owner]
            ghost_sel[g, : len(gids)] = (owner * B + rank).astype(np.int32)
            send_idx[owner, g, rank] = (gids - owner * nv_grp).astype(np.int32)
        return ExchangePlan(
            nshards=n_dcn, nv_pad=nv_grp, block=B, ghost_pad=G,
            send_idx=send_idx, ghost_sel=ghost_sel, ghost_ids=ghost_ids,
            max_ghosts=max_g, ici=ici, shard_nv_pad=nvp,
        )

    def stats(self, itemsize: int = 4) -> dict:
        """Plan-shape digest for the flight recorder's ``exchange`` event
        (obs/events.py): the numbers that decide per-iteration comm volume
        — O(S*B) sent per shard, G-table ghost reads — and the padding
        waste (max_ghosts vs ghost_pad).  ``ghost_bytes`` is the 3-channel
        ghost-pull payload per device per iteration; on a two-level plan
        ``table_bytes_per_device`` is the per-device cost of the
        ICI-gathered group tables (comm + vdeg at the GROUP window — the
        O(nv_total / n_dcn) figure the per-axis budget law checks)."""
        out = {
            "mode": "twolevel" if self.ici > 1 else "sparse",
            "nshards": self.nshards,
            "block": self.block,
            "ghost_pad": self.ghost_pad,
            "max_ghosts": self.max_ghosts,
            "ghosts_per_shard": [len(g) for g in self.ghost_ids],
            "ghost_bytes": 3 * self.nshards * self.block * itemsize,
        }
        if self.ici > 1:
            out["dcn"] = self.nshards
            out["ici"] = self.ici
            out["table_bytes_per_device"] = 2 * self.nv_pad * itemsize
        return out

    def remap_dst(self, s: int, src: np.ndarray, dst: np.ndarray) -> np.ndarray:
        """Rewrite shard s's global-padded dst ids into the (group-)extended
        local space [0, nv_pad + ghost_pad): owned -> local index, ghost ->
        nv_pad + position in the sorted ghost table (the dense-remap trick of
        the reference GPU path, /root/reference/louvain_cuda.cu:2244-2378,
        as a phase-static host transform).  Padding edges map to 0.

        On a two-level plan ``s`` is still the DEVICE shard index; its
        group ``s // ici`` picks the window, so owned means
        owned-by-group and the result indexes the group-extended arrays
        every ICI sibling materializes."""
        nvp = self.nv_pad
        svp = self.shard_nv_pad or nvp
        g = s // self.ici
        d = dst.astype(np.int64)
        out = np.zeros(len(d), dtype=np.int64)
        real = src < svp
        owned = real & (d >= g * nvp) & (d < (g + 1) * nvp)
        out[owned] = d[owned] - g * nvp
        ghost = real & ~owned
        out[ghost] = nvp + np.searchsorted(self.ghost_ids[g], d[ghost])
        return out


class SparseEnv(NamedTuple):
    """Per-iteration community state under the sparse exchange (all arrays
    shard-local)."""

    comm_ext: jax.Array    # [nv_pad + G] community of owned + ghost vertices
    cdeg_ext: jax.Array    # [nv_pad + G] comm_deg[comm[u]] per owned/ghost u
    csize_ext: jax.Array   # [nv_pad + G] comm_size[comm[u]] likewise
    cdeg_v: jax.Array      # [nv_pad] owned-vertex slice of cdeg_ext
    csize_v: jax.Array     # [nv_pad] owned-vertex slice of csize_ext
    deg_local: jax.Array   # [nv_pad] comm_deg of communities OWNED by shard
    overflow: jax.Array    # bool: budget exceeded, sweep results invalid


def _pull_ghosts(vals, send_idx, ghost_sel, axis_name):
    """One static all_to_all: every shard sends the requested owned values,
    receives its ghosts' values (peer-major blocks -> ghost order)."""
    nv_pad = vals.shape[0]
    sv = jnp.take(vals, jnp.minimum(send_idx, nv_pad - 1))   # [S, B]
    rv = jax.lax.all_to_all(sv, axis_name, 0, 0, tiled=True)
    ghost = jnp.take(rv.reshape(-1), ghost_sel)              # [G]
    return jnp.concatenate([vals, ghost])


def _pull_ghosts2(vals_a, vals_b, send_idx, ghost_sel, axis_name):
    """Ghost pull of TWO same-dtype channels in one collective: the per-peer
    blocks are stacked [S, 2, B] so a single all_to_all moves both (halving
    the per-iteration collective launches on the hot path)."""
    nv_pad = vals_a.shape[0]
    idx = jnp.minimum(send_idx, nv_pad - 1)
    sv = jnp.stack([jnp.take(vals_a, idx), jnp.take(vals_b, idx)], axis=1)
    rv = jax.lax.all_to_all(sv, axis_name, 0, 0, tiled=True)  # [S, 2, B]
    ga = jnp.take(rv[:, 0, :].reshape(-1), ghost_sel)
    gb = jnp.take(rv[:, 1, :].reshape(-1), ghost_sel)
    return (jnp.concatenate([vals_a, ga]), jnp.concatenate([vals_b, gb]))


def _pull_ghosts3(vals_a, vals_b, vals_c, send_idx, ghost_sel, axis_name):
    """Ghost pull of three channels — two of the vertex dtype plus one
    weight-typed — in ONE collective: the weight channel rides bitcast to
    the (equal-width) vertex dtype, so all three stack [S, 3, B].  Bitcast
    round-trips bits exactly; results are bit-identical to three separate
    pulls."""
    vdt = vals_a.dtype
    nv_pad = vals_a.shape[0]
    idx = jnp.minimum(send_idx, nv_pad - 1)
    cbits = jax.lax.bitcast_convert_type(vals_c, vdt)
    sv = jnp.stack([jnp.take(vals_a, idx), jnp.take(vals_b, idx),
                    jnp.take(cbits, idx)], axis=1)
    rv = jax.lax.all_to_all(sv, axis_name, 0, 0, tiled=True)  # [S, 3, B]
    ga = jnp.take(rv[:, 0, :].reshape(-1), ghost_sel)
    gb = jnp.take(rv[:, 1, :].reshape(-1), ghost_sel)
    gc = jax.lax.bitcast_convert_type(
        jnp.take(rv[:, 2, :].reshape(-1), ghost_sel), vals_c.dtype)
    return (jnp.concatenate([vals_a, ga]), jnp.concatenate([vals_b, gb]),
            jnp.concatenate([vals_c, gc]))


def _group_by_community(vec, nv_pad, S, budget, base, sentinel):
    """Sort-group a shard's owned community vector: returns the grouping
    state shared by the accumulate and request flows (unique keys padded
    with sentinel, run ids, inverse order, owner-route slots + overflow)."""
    vdt = vec.dtype
    idt = jnp.int32
    iota = jnp.arange(nv_pad, dtype=vdt)
    ck, order = jax.lax.sort((vec, iota), num_keys=1)
    lead = jnp.concatenate(
        [jnp.ones((1,), bool), ck[1:] != ck[:-1]])
    run_id = jnp.cumsum(lead.astype(idt)) - 1            # [nv_pad]
    uk = jnp.full((nv_pad,), sentinel, dtype=vdt).at[run_id].set(ck)
    valid = uk != sentinel
    is_self = valid & (uk >= base) & (uk < base + nv_pad)
    is_remote = valid & ~is_self
    # uk is sorted, so owner groups are contiguous; rank within group gives
    # the slot in the per-peer block.
    bnd = jnp.searchsorted(
        uk, (jnp.arange(S + 1, dtype=vdt) * nv_pad)).astype(idt)  # [S+1]
    o_j = jnp.clip(uk // nv_pad, 0, S - 1).astype(idt)
    rank = jnp.arange(nv_pad, dtype=idt) - jnp.take(bnd, o_j)
    slot = o_j * budget + rank
    ok = is_remote & (rank < budget)
    overflow = jnp.any(is_remote & (rank >= budget))
    return uk, run_id, order, is_self, is_remote, slot, ok, overflow


def sparse_env(comm, vdeg, send_idx, ghost_sel, axis_name, *,
               nshards: int, budget: int, info=None) -> SparseEnv:
    """Build the iteration's community state with sparse communication.

    ``comm``/``vdeg`` are the shard's owned slices; ``send_idx`` [S, B] and
    ``ghost_sel`` [G] come from the phase ExchangePlan.  Runs inside
    shard_map over ``axis_name``.

    ``info`` (optional FROZEN assignment, the vertex-ordering schedule):
    community degree/size TABLES are accumulated by grouping ``info``,
    while requests/attachment still follow ``comm`` — the sparse analog of
    bucketed_step's replicated ``info_comm`` contract (tables frozen at
    iteration start, /root/reference/louvain.cpp:1535-1562).  Costs one
    extra owner-route collective over the fused info-is-comm flow.
    """
    S = nshards
    nv_pad = comm.shape[0]
    vdt = comm.dtype
    wdt = vdeg.dtype
    idt = jnp.int32
    sentinel = jnp.iinfo(vdt).max
    me = jax.lax.axis_index(axis_name).astype(vdt)
    base = me * nv_pad
    same_width_dt = jnp.dtype(vdt).itemsize == jnp.dtype(wdt).itemsize

    # --- owner-grouped unique communities of owned vertices ----------------
    (uk, run_id, order, is_self, is_remote, slot, ok,
     overflow) = _group_by_community(comm, nv_pad, S, budget, base, sentinel)

    if info is None:
        acc_uk, acc_is_self, acc_slot, acc_ok = uk, is_self, slot, ok
        acc_run_id, acc_order = run_id, order
    else:
        # Ordering: the deg/size tables come from the FROZEN assignment's
        # grouping; the request grouping above stays on ``comm``.
        (acc_uk, acc_run_id, acc_order, acc_is_self, _acc_rem, acc_slot,
         acc_ok, ovf_i) = _group_by_community(
            info, nv_pad, S, budget, base, sentinel)
        overflow = overflow | ovf_i
    pdeg = seg.segment_sum(jnp.take(vdeg, acc_order), acc_run_id,
                           num_segments=nv_pad, sorted_ids=True)
    psize = seg.segment_sum(jnp.ones((nv_pad,), dtype=vdt), acc_run_id,
                            num_segments=nv_pad, sorted_ids=True)

    # --- self-owned communities: accumulate locally, no communication ------
    self_idx = jnp.where(acc_is_self, (acc_uk - base).astype(idt), nv_pad)
    deg_local = jnp.zeros((nv_pad,), dtype=wdt).at[self_idx].add(
        jnp.where(acc_is_self, pdeg, 0), mode="drop")
    size_local = jnp.zeros((nv_pad,), dtype=vdt).at[self_idx].add(
        jnp.where(acc_is_self, psize, 0), mode="drop")

    # --- remote-owned: budgeted owner-route of (key, pdeg, psize) ----------
    oob = S * budget
    acc_sslot = jnp.where(acc_ok, acc_slot, oob)
    send_key = jnp.full((S * budget,), sentinel, dtype=vdt).at[acc_sslot].set(
        acc_uk, mode="drop")
    send_deg = jnp.zeros((S * budget,), dtype=wdt).at[acc_sslot].set(
        pdeg, mode="drop")
    send_size = jnp.zeros((S * budget,), dtype=vdt).at[acc_sslot].set(
        psize, mode="drop")

    # One collective for the 3-channel owner-route: key/size share the
    # vertex dtype, the weight-typed partial degree rides bitcast to the
    # equal-width vertex dtype (both Policy configurations pair id and
    # weight widths: int32/f32, int64/f64).  Bit-exact vs separate sends;
    # with the packed reply and 3-channel ghost pull this cuts the sparse
    # exchange from 7 all_to_all launches per iteration to 3
    # (VERDICT r2 item 5; cf. fillRemoteCommunities' single aggregated
    # protocol, /root/reference/louvain.cpp:2588-2959).
    same_width = same_width_dt
    if same_width:
        fwd = jnp.stack([send_key.reshape(S, budget),
                         send_size.reshape(S, budget),
                         jax.lax.bitcast_convert_type(
                             send_deg, vdt).reshape(S, budget)], axis=1)
        rfwd = jax.lax.all_to_all(fwd, axis_name, 0, 0, tiled=True)
        recv_key = rfwd[:, 0, :]
        recv_size = rfwd[:, 1, :]
        recv_deg = jax.lax.bitcast_convert_type(rfwd[:, 2, :], wdt)
    else:
        a2a = lambda x: jax.lax.all_to_all(  # noqa: E731
            x.reshape(S, budget), axis_name, 0, 0, tiled=True)
        recv_key = a2a(send_key)  # [S, budget] keys owned by me, from peers
        recv_deg = a2a(send_deg)
        recv_size = a2a(send_size)

    lk = (recv_key.reshape(-1) - base).astype(idt)  # sentinel -> OOB, dropped
    deg_local = deg_local.at[lk].add(recv_deg.reshape(-1), mode="drop")
    size_local = size_local.at[lk].add(recv_size.reshape(-1), mode="drop")

    if info is not None:
        # Separate request route: the accumulate collective above moved the
        # FROZEN grouping's partials; the reply must answer the ``comm``
        # grouping's keys (one extra key-only collective).
        oob = S * budget
        req_sslot = jnp.where(ok, slot, oob)
        send_req = jnp.full((S * budget,), sentinel, dtype=vdt).at[
            req_sslot].set(uk, mode="drop")
        recv_req = jax.lax.all_to_all(
            send_req.reshape(S, budget), axis_name, 0, 0, tiled=True)
        lk = (recv_req.reshape(-1) - base).astype(idt)

    # --- reply with totals over the transposed routing ---------------------
    lk_safe = jnp.clip(lk, 0, nv_pad - 1)
    rdeg = jnp.take(deg_local, lk_safe).reshape(S, budget)
    rsize = jnp.take(size_local, lk_safe).reshape(S, budget)
    if same_width:
        rep = jnp.stack(
            [rsize, jax.lax.bitcast_convert_type(rdeg, vdt)], axis=1)
        back = jax.lax.all_to_all(rep, axis_name, 0, 0, tiled=True)
        back_size = back[:, 0, :]
        back_deg = jax.lax.bitcast_convert_type(back[:, 1, :], wdt)
    else:
        back_deg = jax.lax.all_to_all(rdeg, axis_name, 0, 0, tiled=True)
        back_size = jax.lax.all_to_all(rsize, axis_name, 0, 0, tiled=True)

    flat_slot = jnp.clip(slot, 0, S * budget - 1)
    deg_remote = jnp.take(back_deg.reshape(-1), flat_slot)
    size_remote = jnp.take(back_size.reshape(-1), flat_slot)
    self_safe = jnp.clip((uk - base).astype(idt), 0, nv_pad - 1)
    deg_at_uk = jnp.where(is_self, jnp.take(deg_local, self_safe), deg_remote)
    size_at_uk = jnp.where(is_self, jnp.take(size_local, self_safe),
                           size_remote)

    # --- attach totals to owned vertices (invert the sort) -----------------
    cdeg_v = jnp.zeros((nv_pad,), dtype=wdt).at[order].set(
        jnp.take(deg_at_uk, run_id))
    csize_v = jnp.zeros((nv_pad,), dtype=vdt).at[order].set(
        jnp.take(size_at_uk, run_id))

    # --- ghost pull: comm + attached community values ----------------------
    # All three channels ride ONE collective (weight-typed cdeg bitcast to
    # the vertex width); unequal-width dtype configs fall back to 2+1.
    if same_width:
        comm_ext, csize_ext, cdeg_ext = _pull_ghosts3(
            comm, csize_v, cdeg_v, send_idx, ghost_sel, axis_name)
    else:
        comm_ext, csize_ext = _pull_ghosts2(comm, csize_v, send_idx,
                                            ghost_sel, axis_name)
        cdeg_ext = _pull_ghosts(cdeg_v, send_idx, ghost_sel, axis_name)

    return SparseEnv(
        comm_ext=comm_ext, cdeg_ext=cdeg_ext, csize_ext=csize_ext,
        cdeg_v=cdeg_v, csize_v=csize_v, deg_local=deg_local,
        overflow=overflow,
    )


def twolevel_env(comm, vdeg, send_idx, ghost_sel, dcn_axis, ici_axis, *,
                 n_dcn: int, budget: int, info=None) -> SparseEnv:
    """Two-level community state: tables at GROUP scale, routed on DCN.

    ``comm``/``vdeg`` are the device shard's owned slices [nv_pad].  The
    ICI all_gather materializes the group window [nv_grp = ici * nv_pad]
    — the only O(nv)-scale replication left, and it is 1/n_dcn of the
    flat exchange's — after which the UNCHANGED sparse protocol runs at
    group scale on the slow axis: every ICI sibling holds identical
    group vectors, so the redundant per-column DCN collectives all
    compute the same bits (correctness by replication; the bandwidth
    overlap is accepted — the DCN payload is the small O(ghosts) one).

    Returns a :class:`SparseEnv` whose ``*_ext`` arrays are GROUP-
    extended [nv_grp + G] (edge dst ids are remapped to that space by
    :meth:`ExchangePlan.remap_dst`), ``cdeg_v``/``csize_v`` are sliced
    back to the device's own [nv_pad] window, and ``deg_local`` stays at
    group scale (ICI-replicated, each community counted once per group —
    feed ``deg_axis_name=dcn_axis`` to :func:`sparse_modularity`)."""
    nv_pad = comm.shape[0]
    comm_grp = jax.lax.all_gather(  # graftlint: replicated-ok=scope=ici; group community vector gathered only inside the fast submesh — O(nv_total/n_dcn) per device, the two-level contract M003 budgets
        comm, ici_axis, tiled=True)
    vdeg_grp = jax.lax.all_gather(  # graftlint: replicated-ok=scope=ici; group vertex-degree vector, same 1/n_dcn window as the community gather above
        vdeg, ici_axis, tiled=True)
    info_grp = None
    if info is not None:
        info_grp = jax.lax.all_gather(  # graftlint: replicated-ok=scope=ici; frozen-assignment (vertex-ordering) group vector, same 1/n_dcn window
            info, ici_axis, tiled=True)
    env = sparse_env(comm_grp, vdeg_grp, send_idx, ghost_sel, dcn_axis,
                     nshards=n_dcn, budget=budget, info=info_grp)
    off = jax.lax.axis_index(ici_axis) * nv_pad
    return env._replace(
        cdeg_v=jax.lax.dynamic_slice(env.cdeg_v, (off,), (nv_pad,)),
        csize_v=jax.lax.dynamic_slice(env.csize_v, (off,), (nv_pad,)),
    )


def sparse_modularity(counter0, deg_local, constant, axis_name, accum_dtype,
                      deg_axis_name=None):
    """Q = e·c - a²·c² with comm_deg sharded by owner: the a² term sums each
    shard's OWNED community degrees (every community counted exactly once)
    and psums — per-chip work O(nv_local), not O(nv_total).

    ``deg_axis_name`` narrows the a²-term reduction axis when
    ``deg_local`` is replicated along part of the mesh: under the
    two-level exchange it is group-scale and ICI-replicated, so summing
    over the DCN axis only counts each community exactly once while the
    per-edge e-term still reduces over the full ``axis_name``.

    ``accum_dtype=segment.DS_ACCUM`` runs both reductions in double-single
    f32 pairs with an exact cross-shard pair reduce (see modularity_terms)."""
    deg_axis = axis_name if deg_axis_name is None else deg_axis_name
    if accum_dtype == seg.DS_ACCUM:
        from cuvite_tpu.ops import exactsum as ds

        le = ds.ds_psum(ds.ds_tree_sum(counter0), axis_name)
        p, e = ds.two_prod(deg_local, deg_local)
        la2 = ds.ds_psum(ds.ds_tree_sum(p, e), deg_axis)
        c = ds.ds_from_f32(constant)
        q = ds.ds_add(ds.ds_mul(le, c),
                      ds.ds_neg(ds.ds_mul(la2, ds.ds_mul(c, c))))
        return q[0] + q[1]
    acc = counter0.dtype if accum_dtype is None else accum_dtype
    le_xx = jax.lax.psum(jnp.sum(counter0.astype(acc)), axis_name)
    la2_x = jax.lax.psum(jnp.sum(jnp.square(deg_local.astype(acc))),
                         deg_axis)
    c_acc = constant.astype(acc)
    return le_xx * c_acc - la2_x * c_acc * c_acc
