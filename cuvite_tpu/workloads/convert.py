"""Streaming graph converters: SNAP / Matrix Market / METIS -> Vite.

The reference clusters *real* graphs through external converters that
emit its binary format (`-f` ingest, /root/reference/README:36-82,
distgraph.cpp:99-197).  This module is the equivalent layer for the TPU
framework: each reader streams edges in bounded chunks, and a shared
two-pass pipeline turns any edge-chunk stream into a Vite CSR file with
RSS O(num_vertices + chunk), never O(num_edges):

  pass 0  spool raw (src, dst, w) chunks to a temp binary file while
          tracking id range (and the distinct-id set when relabeling);
  pass 1  re-read the spool, count per-vertex degrees -> CSR offsets;
  pass 2  re-read the spool, scatter edge records into their final file
          positions through per-vertex cursors (ViteStreamWriter);
  pass 3  canonicalize: sort each row's records by tail id, so the same
          logical graph always produces the SAME bytes regardless of
          input edge order or chunking (the round-trip tests pin this).

Formats
-------
* SNAP edge list (``.txt`` / ``.txt.gz``): ``u v [w]`` per line, ``#``
  comments; each undirected edge listed once -> symmetrized on write.
* Matrix Market (``.mtx``): ``coordinate`` ``pattern|real|integer``;
  ``symmetric`` entries are symmetrized, ``general`` is taken as a
  directed adjacency that already contains both directions.
* METIS (``.graph``/``.metis``): header ``nv ne [fmt [ncon]]``; the
  adjacency lists already store both directions -> written as-is.

Self-loops are stored once (the Graph.from_edges convention); duplicate
input edges are preserved as parallel records — the device engines
coalesce neighbor communities per step, so multigraphs are legal input.
"""

from __future__ import annotations

import dataclasses
import gzip
import os
import tempfile
from typing import Iterable, Iterator

import numpy as np

from cuvite_tpu.io.vite import ViteStreamWriter

DEFAULT_CHUNK_EDGES = 1 << 22

_SPOOL_DTYPE = np.dtype([("src", "<i8"), ("dst", "<i8"), ("w", "<f8")])


@dataclasses.dataclass
class ConvertStats:
    """What the conversion did (also the provenance record's payload)."""

    out_path: str
    fmt: str
    num_vertices: int
    num_edges: int          # directed records in the Vite file
    input_edges: int        # edge entries read from the source
    self_loops: int
    relabeled: bool
    bits64: bool
    symmetrized: bool

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ParsedSource:
    """An opened input: its edge-chunk iterator plus the per-format
    conversion policy the pipeline should apply."""

    chunks: Iterable
    fmt: str
    symmetrize: bool
    relabel: str                    # "auto" | "none" | "dense"
    num_vertices: int | None = None  # known from a header, else None


# ---------------------------------------------------------------------------
# Chunked text readers


def _text_blocks(path: str, block_bytes: int = 8 << 20) -> Iterator[bytes]:
    """Newline-aligned byte blocks from a text or gzip file."""
    opener = gzip.open if path.endswith(".gz") else open
    rem = b""
    with opener(path, "rb") as f:
        while True:
            buf = f.read(block_bytes)
            if not buf:
                break
            buf = rem + buf
            nl = buf.rfind(b"\n")
            if nl < 0:
                rem = buf
                continue
            yield buf[: nl + 1]
            rem = buf[nl + 1:]
    if rem:
        yield rem + b"\n"


def _strip_comments(block: bytes, markers: tuple = (b"#", b"%")) -> bytes:
    if not any(m in block for m in markers):
        return block
    keep = [ln for ln in block.split(b"\n")
            if ln and not ln.lstrip().startswith(markers)]
    return b"\n".join(keep)


def snap_edge_chunks(path: str) -> Iterator[tuple]:
    """SNAP edge list: ``u v`` or ``u v w`` per line, '#'/'%' comments."""
    ncols = None
    for block in _text_blocks(path):
        block = _strip_comments(block)
        tokens = block.split()
        if not tokens:
            continue
        if ncols is None:
            first_line = block.lstrip().split(b"\n", 1)[0]
            ncols = len(first_line.split())
            if ncols not in (2, 3):
                raise ValueError(
                    f"{path}: expected 2 or 3 columns, found {ncols}")
        if len(tokens) % ncols:
            raise ValueError(f"{path}: ragged edge line "
                             f"({len(tokens)} tokens % {ncols} columns)")
        arr = np.array(tokens)
        cols = arr.reshape(-1, ncols)
        src = cols[:, 0].astype(np.int64)
        dst = cols[:, 1].astype(np.int64)
        w = cols[:, 2].astype(np.float64) if ncols == 3 else None
        yield src, dst, w


def _mtx_header(path: str) -> tuple:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        header = f.readline().split()
        if len(header) < 5 or header[0] != b"%%MatrixMarket":
            raise ValueError(f"{path}: not a MatrixMarket file")
        obj, fmt, field, symm = (t.decode().lower() for t in header[1:5])
        if obj != "matrix" or fmt != "coordinate":
            raise ValueError(f"{path}: only 'matrix coordinate' supported "
                             f"(got '{obj} {fmt}')")
        if field not in ("pattern", "real", "integer"):
            raise ValueError(f"{path}: unsupported field '{field}'")
        if symm not in ("general", "symmetric"):
            raise ValueError(f"{path}: unsupported symmetry '{symm}'")
        while True:
            line = f.readline()
            if not line:
                raise ValueError(f"{path}: missing size line")
            if line.lstrip().startswith(b"%") or not line.strip():
                continue
            nrows, ncols_, nnz = (int(t) for t in line.split()[:3])
            break
    if nrows != ncols_:
        raise ValueError(f"{path}: adjacency matrix must be square "
                         f"({nrows}x{ncols_})")
    return field, symm, nrows, nnz


def mtx_edge_chunks(path: str) -> Iterator[tuple]:
    """MatrixMarket coordinate entries (1-based ids shifted to 0-based)."""
    field, _symm, _n, _nnz = _mtx_header(path)
    ncols = 2 if field == "pattern" else 3
    past_header = False
    for block in _text_blocks(path):
        lines = [ln for ln in block.split(b"\n")
                 if ln and not ln.lstrip().startswith(b"%")]
        if not past_header and lines:
            lines = lines[1:]  # the size line
            past_header = True
        if not lines:
            continue
        tokens = b" ".join(lines).split()
        if len(tokens) % ncols:
            raise ValueError(f"{path}: ragged coordinate line")
        cols = np.array(tokens).reshape(-1, ncols)
        src = cols[:, 0].astype(np.int64) - 1
        dst = cols[:, 1].astype(np.int64) - 1
        w = cols[:, 2].astype(np.float64) if ncols == 3 else None
        yield src, dst, w


def metis_edge_chunks(path: str,
                      chunk_edges: int = DEFAULT_CHUNK_EDGES,
                      block_bytes: int = 8 << 20) -> Iterator[tuple]:
    """METIS adjacency lines (both directions already present, 1-based)."""
    header = None
    vertex = 0
    srcs: list = []
    dsts: list = []
    ws: list = []
    n_acc = 0

    def flush():
        nonlocal srcs, dsts, ws, n_acc
        out = (np.array(srcs, dtype=np.int64),
               np.array(dsts, dtype=np.int64),
               np.array(ws, dtype=np.float64) if has_ew else None)
        srcs, dsts, ws, n_acc = [], [], [], 0
        return out

    for block in _text_blocks(path, block_bytes):
        # Every block ends with b"\n" (_text_blocks guarantees it), so
        # split() leaves a PHANTOM empty tail that is a block-boundary
        # artifact, not a file line — dropping it matters here because a
        # genuinely blank line IS meaningful (an isolated vertex).
        for raw in block.split(b"\n")[:-1]:
            line = raw.strip()
            if line.startswith(b"%"):
                continue
            if header is None:
                if not line:
                    continue
                toks = line.split()
                nv, _ne = int(toks[0]), int(toks[1])
                fmt = toks[2].decode() if len(toks) > 2 else "0"
                ncon = int(toks[3]) if len(toks) > 3 else (
                    1 if len(fmt) >= 2 and fmt[-2] == "1" else 0)
                fmt = fmt.zfill(3)
                has_vsize = fmt[0] == "1"
                has_vw = fmt[1] == "1"
                has_ew = fmt[2] == "1"
                skip = (1 if has_vsize else 0) + (ncon if has_vw else 0)
                header = (nv, skip, has_ew)
                continue
            # Every non-comment line after the header is one vertex's
            # adjacency — INCLUDING blank lines (an isolated vertex).
            if vertex >= header[0]:
                if line:
                    raise ValueError(f"{path}: more adjacency lines than "
                                     f"the header's nv={header[0]}")
                continue
            toks = line.split()[header[1]:]
            if has_ew:
                if len(toks) % 2:
                    raise ValueError(
                        f"{path}: vertex {vertex + 1} has an odd "
                        "neighbor/weight token count")
                nbrs = toks[0::2]
                wts = toks[1::2]
            else:
                nbrs, wts = toks, ()
            for k, t in enumerate(nbrs):
                srcs.append(vertex)
                dsts.append(int(t) - 1)
                if has_ew:
                    ws.append(float(wts[k]))
            n_acc += len(nbrs)
            vertex += 1
            if n_acc >= chunk_edges:
                yield flush()
    if header is None:
        raise ValueError(f"{path}: empty METIS file")
    if vertex != header[0]:
        raise ValueError(f"{path}: {vertex} adjacency lines for "
                         f"nv={header[0]}")
    if n_acc or vertex:
        out = flush()
        if len(out[0]):
            yield out


def _metis_num_vertices(path: str) -> int:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        for raw in f:
            line = raw.strip()
            if line and not line.startswith(b"%"):
                return int(line.split()[0])
    raise ValueError(f"{path}: empty METIS file")


FORMATS = ("snap", "mtx", "metis")


def detect_format(path: str) -> str:
    base = path[:-3] if path.endswith(".gz") else path
    ext = os.path.splitext(base)[1].lower()
    if ext == ".mtx":
        return "mtx"
    if ext in (".graph", ".metis"):
        return "metis"
    return "snap"


def open_source(path: str, fmt: str = "auto") -> ParsedSource:
    """Open an input file as a chunked edge source with its conversion
    policy (symmetrization, relabeling, known vertex count)."""
    if fmt == "auto":
        fmt = detect_format(path)
    if fmt == "snap":
        return ParsedSource(chunks=snap_edge_chunks(path), fmt="snap",
                            symmetrize=True, relabel="auto")
    if fmt == "mtx":
        _field, symm, n, _nnz = _mtx_header(path)
        # 'general' adjacency already carries both directions; writing
        # it symmetrized would double every edge.
        return ParsedSource(chunks=mtx_edge_chunks(path), fmt="mtx",
                            symmetrize=(symm == "symmetric"),
                            relabel="none", num_vertices=n)
    if fmt == "metis":
        return ParsedSource(chunks=metis_edge_chunks(path), fmt="metis",
                            symmetrize=False, relabel="none",
                            num_vertices=_metis_num_vertices(path))
    raise ValueError(f"unknown format {fmt!r} (choose from {FORMATS})")


# ---------------------------------------------------------------------------
# The shared two-pass (spool -> degrees -> scatter -> canonicalize) pipeline


def _spool_chunks(chunks, spool_path: str, collect_ids: bool):
    """Pass 0: write raw records; return (n, max_id, min_id, uniq_ids)."""
    n = 0
    max_id = -1
    min_id = np.iinfo(np.int64).max
    uniq = np.zeros(0, dtype=np.int64)
    with open(spool_path, "wb") as spool:
        for src, dst, w in chunks:
            src = np.asarray(src, dtype=np.int64)
            dst = np.asarray(dst, dtype=np.int64)
            if len(src) != len(dst):
                raise ValueError("src/dst length mismatch")
            if not len(src):
                continue
            rec = np.empty(len(src), dtype=_SPOOL_DTYPE)
            rec["src"] = src
            rec["dst"] = dst
            rec["w"] = 1.0 if w is None else np.asarray(w, dtype=np.float64)
            rec.tofile(spool)
            n += len(src)
            max_id = max(max_id, int(src.max()), int(dst.max()))
            min_id = min(min_id, int(src.min()), int(dst.min()))
            if collect_ids:
                uniq = np.union1d(uniq, np.unique(
                    np.concatenate([src, dst])))
    return n, max_id, min_id, uniq


def _read_spool(spool_path: str, n: int, chunk: int) -> Iterator[np.ndarray]:
    mm = np.memmap(spool_path, dtype=_SPOOL_DTYPE, mode="r", shape=(n,))
    for lo in range(0, n, chunk):
        yield np.array(mm[lo: lo + chunk])
    del mm


def _scatter_positions(rows: np.ndarray, cursor: np.ndarray) -> np.ndarray:
    """Final-file positions for this chunk's rows, advancing ``cursor``
    (each row's records land at consecutive positions, chunk order)."""
    order = np.argsort(rows, kind="stable")
    r_sorted = rows[order]
    # rank of each record within its row-run
    run_start = np.zeros(len(r_sorted), dtype=np.int64)
    new_run = np.ones(len(r_sorted), dtype=bool)
    new_run[1:] = r_sorted[1:] != r_sorted[:-1]
    run_ids = np.cumsum(new_run) - 1
    first_idx = np.flatnonzero(new_run)
    rank = np.arange(len(r_sorted), dtype=np.int64) - first_idx[run_ids]
    pos_sorted = cursor[r_sorted] + rank
    uniq_rows = r_sorted[new_run]
    counts = np.diff(np.append(first_idx, len(r_sorted)))
    # Advancing the caller's cursor IS the contract (docstring): it is
    # the per-row fill state threaded across spool chunks.
    cursor[uniq_rows] += counts  # graftlint: disable=R005
    pos = np.empty(len(rows), dtype=np.int64)
    pos[order] = pos_sorted
    return pos


def _canonicalize_rows(writer: ViteStreamWriter, offsets: np.ndarray,
                       chunk_edges: int) -> None:
    """Pass 3: sort each row's records by tail id, block by block."""
    nv = len(offsets) - 1
    row = 0
    while row < nv:
        end = int(np.searchsorted(offsets, offsets[row] + chunk_edges,
                                  side="left"))
        end = max(end, row + 1)
        end = min(end, nv)
        lo, hi = int(offsets[row]), int(offsets[end])
        if hi > lo:
            rec = writer.read_edges(lo, hi)
            rows = np.repeat(np.arange(row, end, dtype=np.int64),
                             np.diff(offsets[row:end + 1]))
            order = np.lexsort((rec["tail"], rows))
            writer.write_edges(lo, rec["tail"][order], rec["weight"][order])
        row = end


def edges_to_vite(
    chunks: Iterable,
    out_path: str,
    *,
    bits64: bool = False,
    symmetrize: bool = True,
    num_vertices: int | None = None,
    relabel: str = "auto",
    chunk_edges: int = DEFAULT_CHUNK_EDGES,
    tmp_dir: str | None = None,
    fmt: str = "edges",
) -> ConvertStats:
    """Stream an edge-chunk iterable into a canonical Vite CSR file.

    ``relabel``: "none" keeps ids as given (requires them in
    [0, num_vertices)); "dense" always maps distinct ids to [0, n);
    "auto" relabels only when the id space has gaps.
    """
    tmp_dir = tmp_dir or os.path.dirname(os.path.abspath(out_path))
    fd, spool_path = tempfile.mkstemp(suffix=".spool", dir=tmp_dir)
    os.close(fd)
    try:
        collect = relabel in ("auto", "dense")
        n_in, max_id, min_id, uniq = _spool_chunks(chunks, spool_path,
                                                   collect)
        if n_in == 0:
            raise ValueError("input contains no edges")
        if min_id < 0:
            raise ValueError(f"negative vertex id {min_id} in input")
        id_map = None
        if relabel == "dense" or (relabel == "auto"
                                  and max_id + 1 != len(uniq)):
            id_map = uniq  # position = new id, via searchsorted
            nv = len(uniq)
        else:
            nv = max_id + 1
        if num_vertices is not None:
            if id_map is None and num_vertices < nv:
                raise ValueError(
                    f"vertex id {max_id} >= declared count {num_vertices}")
            if id_map is None:
                nv = num_vertices  # headers may declare isolated tail ids

        def mapped(rec):
            s, d = rec["src"], rec["dst"]
            if id_map is not None:
                s = np.searchsorted(id_map, s)
                d = np.searchsorted(id_map, d)
            return s, d, rec["w"]

        # Pass 1: degrees.
        deg = np.zeros(nv, dtype=np.int64)
        n_self = 0
        for rec in _read_spool(spool_path, n_in, chunk_edges):
            s, d, _ = mapped(rec)
            np.add.at(deg, s, 1)
            if symmetrize:
                fwd = s != d
                np.add.at(deg, d[fwd], 1)
                n_self += int(len(s) - fwd.sum())
            else:
                n_self += int((s == d).sum())
        ne = int(deg.sum())
        offsets = np.zeros(nv + 1, dtype=np.int64)
        np.cumsum(deg, out=offsets[1:])
        del deg

        # Pass 2: scatter records through per-row cursors.
        writer = ViteStreamWriter(out_path, nv, ne, bits64=bits64)
        writer.write_offsets(offsets)
        cursor = offsets[:-1].copy()
        for rec in _read_spool(spool_path, n_in, chunk_edges):
            s, d, w = mapped(rec)
            if symmetrize:
                fwd = s != d
                rows = np.concatenate([s, d[fwd]])
                tails = np.concatenate([d, s[fwd]])
                ws = np.concatenate([w, w[fwd]])
            else:
                rows, tails, ws = s, d, w
            pos = _scatter_positions(rows, cursor)
            writer.write_edges(pos, tails, ws)
        if not np.array_equal(cursor, offsets[1:]):
            raise AssertionError("scatter did not fill every CSR slot")

        # Pass 3: canonical per-row tail order.
        _canonicalize_rows(writer, offsets, chunk_edges)
        writer.close()
        return ConvertStats(
            out_path=out_path, fmt=fmt, num_vertices=nv, num_edges=ne,
            input_edges=n_in, self_loops=n_self,
            relabeled=id_map is not None, bits64=bits64,
            symmetrized=symmetrize,
        )
    finally:
        os.unlink(spool_path)


def convert(path: str, out_path: str, fmt: str = "auto",
            bits64: bool = False, symmetrize: str = "auto",
            relabel: str | None = None,
            chunk_edges: int = DEFAULT_CHUNK_EDGES) -> ConvertStats:
    """Convert a SNAP/MTX/METIS file to Vite binary (see module doc)."""
    src = open_source(path, fmt)
    sym = src.symmetrize if symmetrize == "auto" else (symmetrize == "yes")
    stats = edges_to_vite(
        src.chunks, out_path, bits64=bits64, symmetrize=sym,
        num_vertices=src.num_vertices,
        relabel=relabel if relabel is not None else src.relabel,
        chunk_edges=chunk_edges, fmt=src.fmt,
    )
    return stats
