"""Golden result envelopes for workload runs.

The reference's de-facto correctness oracle is "run the pipeline, compare
against known-good output" (its -g ground-truth path).  This module is
the per-dataset generalization: a checked-in JSON registry maps
``<dataset>/<config>`` keys to expected envelopes for modularity Q,
phase count, community count and (when ground truth exists) F-score.
``verify-golden`` runs fail when a measurement leaves its envelope;
``--update-golden`` re-derives envelopes from a fresh measurement using
the tolerance model below (so updating is one deliberate command, not a
hand-edit).

Tolerance model (envelope = measured value ± slack):
  * Q: ±``q_tol`` absolute (default 0.01 — cross-platform f32 reduction
    order moves Q by ~1e-6; a real quality regression moves it by >0.01);
  * phases: ±``phase_slack`` (count is discrete and stable);
  * communities: ±``comm_rel`` relative (default 10%);
  * F-score: -``f_tol`` one-sided (better-than-golden never fails).
"""

from __future__ import annotations

import json
import os

DEFAULT_GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "golden.json")
GOLDEN_VERSION = 1

Q_TOL = 0.01
PHASE_SLACK = 1
COMM_REL = 0.10
F_TOL = 0.02


def golden_key(dataset: str, config: str = "default") -> str:
    return f"{dataset}/{config}"


def load_golden(path: str = DEFAULT_GOLDEN_PATH) -> dict:
    if not os.path.exists(path):
        return {"version": GOLDEN_VERSION, "entries": {}}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != GOLDEN_VERSION:
        raise ValueError(f"golden registry {path!r}: unsupported version "
                         f"{data.get('version')!r}")
    return data


def save_golden(data: dict, path: str = DEFAULT_GOLDEN_PATH) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def envelope_from_measurement(measured: dict, q_tol: float = Q_TOL,
                              phase_slack: int = PHASE_SLACK,
                              comm_rel: float = COMM_REL,
                              f_tol: float = F_TOL) -> dict:
    """Derive a golden envelope from one measured run (the
    ``--update-golden`` path)."""
    q = float(measured["modularity"])
    phases = int(measured["phases"])
    comms = int(measured["communities"])
    env = {
        "q": [round(q - q_tol, 6), round(q + q_tol, 6)],
        "phases": [max(1, phases - phase_slack), phases + phase_slack],
        "communities": [int(comms * (1 - comm_rel)),
                        int(comms * (1 + comm_rel)) + 1],
        "measured": {"modularity": round(q, 6), "phases": phases,
                     "communities": comms},
    }
    if measured.get("f_score") is not None:
        f = float(measured["f_score"])
        env["f_score_min"] = round(f - f_tol, 6)
        env["measured"]["f_score"] = round(f, 6)
    if measured.get("provenance") is not None:
        env["provenance"] = measured["provenance"]
    return env


def check_envelope(entry: dict, measured: dict) -> list:
    """Violation strings for ``measured`` against golden ``entry``
    (empty list = within envelope)."""
    problems = []
    q = float(measured["modularity"])
    lo, hi = entry["q"]
    if not (lo <= q <= hi):
        problems.append(f"Q={q:.6f} outside [{lo}, {hi}]")
    phases = int(measured["phases"])
    lo, hi = entry["phases"]
    if not (lo <= phases <= hi):
        problems.append(f"phases={phases} outside [{lo}, {hi}]")
    comms = int(measured["communities"])
    lo, hi = entry["communities"]
    if not (lo <= comms <= hi):
        problems.append(f"communities={comms} outside [{lo}, {hi}]")
    f_min = entry.get("f_score_min")
    if f_min is not None:
        f = measured.get("f_score")
        if f is None:
            problems.append("golden pins an F-score but the run has no "
                            "ground truth to compare against")
        elif float(f) < f_min:
            problems.append(f"f_score={float(f):.6f} below {f_min}")
    return problems


def measure_run(communities, res, truth_path: str | None = None,
                zero_based_truth: bool = False,
                provenance: str | None = None) -> dict:
    """Distill a clustering result into the measurement dict the golden
    machinery consumes; wires evaluate.compare when truth exists."""
    measured = {
        "modularity": float(res.modularity),
        "phases": len(res.phases),
        "communities": int(res.num_communities),
        "iterations": int(res.total_iterations),
        "provenance": provenance,
    }
    if truth_path:
        from cuvite_tpu.evaluate.compare import (
            compare_communities, load_ground_truth,
        )

        truth = load_ground_truth(truth_path, zero_based=zero_based_truth)
        cmp_res = compare_communities(truth, communities)
        measured["f_score"] = float(cmp_res.f_score)
        measured["precision"] = float(cmp_res.precision)
        measured["recall"] = float(cmp_res.recall)
    return measured


def verify(dataset: str, config: str, measured: dict,
           path: str = DEFAULT_GOLDEN_PATH,
           update: bool = False) -> tuple:
    """Check (or, with ``update``, record) a measurement.

    Returns ``(ok, problems)``; a missing entry is a failure unless
    updating (a golden gate that silently passes on absent goldens
    would never catch a deleted entry).
    """
    data = load_golden(path)
    key = golden_key(dataset, config)
    if update:
        data["entries"][key] = envelope_from_measurement(measured)
        save_golden(data, path)
        return True, []
    entry = data["entries"].get(key)
    if entry is None:
        return False, [f"no golden entry for {key!r} in {path} "
                       "(run with --update-golden to record one)"]
    problems = check_envelope(entry, measured)
    return not problems, problems
