"""Offline workload synthesizer: power-law degrees + planted overlapping
communities, emitted straight to a Vite file at a requested edge count.

The registry's datasets (com-Orkut / Friendster / uk-2007) need the
network; this generator is the offline fallback that keeps the rig from
ever blocking on it (VERDICT r5 missing #5 explicitly allows "generate a
Vite-format file from a published degree sequence and say so").  It is
fully deterministic — every random draw is a counter-based splitmix64
hash of (seed, index), the same scheme io/generate.py uses for R-MAT —
so a (edges, seed, profile) triple always produces byte-identical output
(the conversion pipeline canonicalizes row order), and golden envelopes
over synthesized graphs are meaningful.

Model (the LFR ingredients, vectorized):
  * vertex degree draws  d_i ~ dmin * u^(-1/(alpha-1)), capped, scaled
    exactly to the requested total;
  * community sizes from a second power law; vertices assigned to
    contiguous ranges; a deterministic ``overlap`` fraction of vertices
    holds a second membership (their edges split between the two);
  * each draw is intra-community with probability 1-mu (uniform member
    of one of the vertex's communities), else a uniform global target;
    self-draws are dropped, parallel edges kept (multigraph-legal).

Ground truth (primary membership, LFR ``vertex community`` 1-based
format — evaluate.compare.load_ground_truth reads it) goes to
``<out>.truth``; full provenance, including the output file's sha256,
to ``<out>.provenance.json``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time

import numpy as np

from cuvite_tpu.utils.rng import splitmix64, u01
from cuvite_tpu.workloads.convert import DEFAULT_CHUNK_EDGES, edges_to_vite

PROFILES = ("powerlaw",)

# Stream tags: every hash stream is splitmix64(seed * STRIDE + tag + index)
# with a distinct tag so streams never collide across uses.
_T_DEGREE = 0x01 << 56
_T_CSIZE = 0x02 << 56
_T_OVERLAP = 0x03 << 56
_T_ALT = 0x04 << 56
_T_MIX = 0x05 << 56
_T_PICK = 0x06 << 56
_T_INTRA = 0x07 << 56
_T_INTER = 0x08 << 56
_T_MANY = 0x09 << 56
# Churn streams (ISSUE 17): delete ranks, insert endpoints, insert
# weights — distinct tags so a churn stream never collides with the
# base synthesis draws of the same seed.
_T_CHURN_DEL = 0x0A << 56
_T_CHURN_INS = 0x0B << 56
_T_CHURN_W = 0x0C << 56
_STRIDE = 0x9E3779B97F4A7C15
_MASK64 = (1 << 64) - 1


def _stream_base(tag: int, seed: int) -> np.uint64:
    """Per-(seed, tag) stream offset; the multiply wraps mod 2^64 by
    design (computed in Python ints so numpy stays warning-free)."""
    return np.uint64((seed * _STRIDE + tag) & _MASK64)


def _hash_u01(tag: int, idx: np.ndarray, seed: int) -> np.ndarray:
    return u01(splitmix64(_stream_base(tag, seed) + idx.astype(np.uint64)))


def _exact_counts(weights: np.ndarray, total: int) -> np.ndarray:
    """Integer counts proportional to ``weights`` summing to exactly
    ``total`` (cumulative rounding: deterministic, order-stable)."""
    cum = np.cumsum(weights, dtype=np.float64)
    cum *= total / cum[-1]
    bounds = np.floor(cum + 0.5).astype(np.int64)
    counts = np.diff(np.concatenate([[0], bounds]))
    counts[-1] += total - bounds[-1]
    return counts


@dataclasses.dataclass
class SynthSpec:
    """Resolved synthesizer parameters (recorded in provenance)."""

    profile: str
    edges: int           # target directed records in the Vite file
    seed: int
    alpha: float         # degree power-law exponent
    mu: float            # inter-community mixing fraction
    dmin: int
    edge_factor: int     # mean directed degree -> nv = edges / edge_factor
    comm_min: int
    comm_beta: float     # community-size power-law exponent
    overlap: float       # fraction of vertices with a second membership
    bits64: bool

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _community_layout(nv: int, spec: SynthSpec):
    """Community sizes from a power law covering exactly nv vertices.
    Returns (bounds[nc+1], sizes[nc])."""
    cmax = max(spec.comm_min + 1, nv // 16 or 1)
    sizes = []
    covered = 0
    batch = 0
    while covered < nv:
        idx = np.arange(batch * 4096, (batch + 1) * 4096, dtype=np.int64)
        u = _hash_u01(_T_CSIZE, idx, spec.seed)
        s = np.minimum(
            (spec.comm_min * np.power(1.0 - u, -1.0 / (spec.comm_beta - 1.0))
             ).astype(np.int64), cmax)
        sizes.append(s)
        covered += int(s.sum())
        batch += 1
    sizes = np.concatenate(sizes)
    cut = int(np.searchsorted(np.cumsum(sizes), nv, side="left")) + 1
    sizes = sizes[:cut]
    sizes[-1] -= int(sizes.sum()) - nv  # trim the last community to fit
    if sizes[-1] <= 0:  # merge a degenerate tail into its neighbor
        sizes = sizes[:-1]
        sizes[-1] += nv - int(sizes.sum())
    bounds = np.zeros(len(sizes) + 1, dtype=np.int64)
    np.cumsum(sizes, out=bounds[1:])
    return bounds, sizes


def _edge_chunk_stream(nv: int, draws: np.ndarray, bounds: np.ndarray,
                       spec: SynthSpec, chunk_edges: int):
    """Yield (src, dst, None) chunks; every value is a pure hash of its
    global draw index, so chunking never changes the edge set."""
    nc = len(bounds) - 1
    comm_of = np.empty(nv, dtype=np.int64)
    for c in range(nc):
        comm_of[bounds[c]:bounds[c + 1]] = c
    # Second membership for a deterministic `overlap` fraction.
    vidx = np.arange(nv, dtype=np.int64)
    has_alt = _hash_u01(_T_OVERLAP, vidx, spec.seed) < spec.overlap
    alt_pick = splitmix64(_stream_base(_T_ALT, spec.seed)
                          + vidx.astype(np.uint64))
    alt_of = ((comm_of + 1 + (alt_pick % np.uint64(max(nc - 1, 1)))
               .astype(np.int64)) % nc) if nc > 1 else comm_of.copy()
    alt_of = np.where(has_alt, alt_of, comm_of)

    cum = np.zeros(nv + 1, dtype=np.int64)
    np.cumsum(draws, out=cum[1:])
    lo_v = 0
    while lo_v < nv:
        hi_v = int(np.searchsorted(cum, cum[lo_v] + chunk_edges,
                                   side="left"))
        hi_v = min(max(hi_v, lo_v + 1), nv)
        src = np.repeat(np.arange(lo_v, hi_v, dtype=np.int64),
                        draws[lo_v:hi_v])
        if not len(src):
            lo_v = hi_v
            continue
        gidx = np.arange(int(cum[lo_v]), int(cum[hi_v]), dtype=np.int64)
        intra = _hash_u01(_T_MIX, gidx, spec.seed) >= spec.mu
        use_alt = _hash_u01(_T_PICK, gidx, spec.seed) < 0.5
        comm = np.where(use_alt, alt_of[src], comm_of[src])
        clo = bounds[comm]
        csz = (bounds[comm + 1] - clo).astype(np.uint64)
        h_in = splitmix64(_stream_base(_T_INTRA, spec.seed)
                          + gidx.astype(np.uint64))
        t_in = clo + (h_in % np.maximum(csz, 1)).astype(np.int64)
        h_out = splitmix64(_stream_base(_T_INTER, spec.seed)
                           + gidx.astype(np.uint64))
        t_out = (h_out % np.uint64(nv)).astype(np.int64)
        dst = np.where(intra, t_in, t_out)
        keep = src != dst
        yield src[keep], dst[keep], None
        lo_v = hi_v


def _sha256_file(path: str, block: int = 8 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            buf = f.read(block)
            if not buf:
                break
            h.update(buf)
    return h.hexdigest()


def write_provenance(out_path: str, payload: dict) -> str:
    path = out_path + ".provenance.json"
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def many_seed(seed: int, index: int) -> int:
    """Per-graph seed of a ``--many`` set: splitmix64 of (seed, index)
    on its own stream tag, so graph k is deterministic, independent of
    the set size K, and never collides with the base generator's
    streams (two members of one set share no draw)."""
    return int(splitmix64(_stream_base(_T_MANY, seed)
                          + np.uint64(index))) & ((1 << 62) - 1)


def _layout(edges: int, spec: SynthSpec, seed: int):
    """Shared degree/community layout of one synthesized graph."""
    n_pairs = edges // 2
    nv = max(64, edges // spec.edge_factor)
    dmax = max(spec.dmin * 4, int(np.sqrt(nv) * 4))
    vidx = np.arange(nv, dtype=np.int64)
    u = _hash_u01(_T_DEGREE, vidx, seed)
    wdeg = spec.dmin * np.power(1.0 - u, -1.0 / (spec.alpha - 1.0))
    wdeg = np.minimum(wdeg, dmax)
    draws = _exact_counts(wdeg, n_pairs)
    bounds, sizes = _community_layout(nv, spec)
    return nv, draws, bounds, sizes


def synthesize_graph(edges: int, seed: int = 1, profile: str = "powerlaw",
                     alpha: float = 2.3, mu: float = 0.25, dmin: int = 2,
                     edge_factor: int = 16, comm_min: int = 16,
                     comm_beta: float = 1.8, overlap: float = 0.05):
    """In-memory variant of :func:`synthesize`: same deterministic draw
    streams, returned as a built ``core.graph.Graph`` instead of a Vite
    file — the shape serving benches and queue tests consume (ISSUE 9:
    K small graphs per process, no filesystem round-trip).  The edge
    SET matches what ``synthesize(...)`` would write for the same
    parameters (symmetrized, duplicates coalesced by Graph.from_edges).
    """
    from cuvite_tpu.core.graph import Graph

    if profile not in PROFILES:
        raise ValueError(f"unknown profile {profile!r} "
                         f"(choose from {PROFILES})")
    edges = int(edges)
    if edges < 4:
        raise ValueError("need at least 4 directed edges")
    spec = SynthSpec(profile=profile, edges=edges, seed=seed, alpha=alpha,
                     mu=mu, dmin=dmin, edge_factor=edge_factor,
                     comm_min=comm_min, comm_beta=comm_beta,
                     overlap=overlap, bits64=False)
    nv, draws, bounds, _sizes = _layout(edges, spec, seed)
    srcs, dsts = [], []
    for s, d, _w in _edge_chunk_stream(nv, draws, bounds, spec,
                                       DEFAULT_CHUNK_EDGES):
        srcs.append(s)
        dsts.append(d)
    src = np.concatenate(srcs) if srcs else np.zeros(0, dtype=np.int64)
    dst = np.concatenate(dsts) if dsts else np.zeros(0, dtype=np.int64)
    return Graph.from_edges(nv, src, dst, symmetrize=True)


def churn_batches(graph, *, frac: float, seed: int = 1,
                  batches: int = 1) -> list:
    """Deterministic insert/delete churn stream against a base graph
    (ISSUE 17: the offline workload behind the warm-start A/B).

    Each batch deletes ``frac`` of the base graph's undirected pairs
    and inserts an equal count of fresh hash-drawn pairs with small
    dyadic integer weights (1..8 — inside the device coalesce's
    exactness domain, so delta-vs-rebuild stays bit-equal).  Every draw
    is a splitmix64 hash of (seed, index) on churn-only stream tags:
    the batch list is a pure function of (graph, frac, seed, batches).
    Deletes are sampled without replacement ACROSS batches (rank order
    of one hash stream over the base pairs), so batch k's deletes still
    exist when it is applied; inserts may touch any pair, including one
    another's — duplicate inserts coalesce by weight sum, exactly like
    the rebuild oracle.

    Returns a list of ``batches`` dicts with int64/f64 numpy arrays
    ``{ins_src, ins_dst, ins_w, del_src, del_dst}`` (one undirected
    record per pair; stream/DeltaBatch.from_edits symmetrizes).
    """
    frac = float(frac)
    batches = int(batches)
    if not 0.0 < frac < 1.0:
        raise ValueError("--churn fraction must be in (0, 1)")
    if batches < 1:
        raise ValueError("churn needs at least one batch")
    nv = graph.num_vertices
    deg = np.diff(graph.offsets)
    src_all = np.repeat(np.arange(nv, dtype=np.int64), deg)
    dst_all = np.asarray(graph.tails, dtype=np.int64)
    canon = src_all <= dst_all  # one record per undirected pair
    psrc, pdst = src_all[canon], dst_all[canon]
    n_pairs = len(psrc)
    n_churn = max(1, int(round(frac * n_pairs)))
    if batches * n_churn > n_pairs:
        raise ValueError(
            f"churn of {batches} x {n_churn} pairs exceeds the base "
            f"graph's {n_pairs} undirected pairs; lower --churn or "
            "--churn-batches")
    pidx = np.arange(n_pairs, dtype=np.int64)
    rank = np.argsort(splitmix64(_stream_base(_T_CHURN_DEL, seed)
                                 + pidx.astype(np.uint64)),
                      kind="stable")
    out = []
    for b in range(batches):
        dsel = rank[b * n_churn:(b + 1) * n_churn]
        # Fresh endpoints: oversample, drop self-draws, keep the first
        # n_churn — deterministic in the draw index.
        need, have, lo = n_churn, [], 0
        while need > 0:
            gidx = np.arange(lo, lo + 2 * need + 4, dtype=np.int64) \
                + np.int64(b) * np.int64(8 * (n_churn + 1))
            hu = splitmix64(_stream_base(_T_CHURN_INS, seed)
                            + (2 * gidx).astype(np.uint64))
            hv = splitmix64(_stream_base(_T_CHURN_INS, seed)
                            + (2 * gidx + 1).astype(np.uint64))
            iu = (hu % np.uint64(nv)).astype(np.int64)
            iv = (hv % np.uint64(nv)).astype(np.int64)
            keep = iu != iv
            have.append(np.stack([iu[keep], iv[keep],
                                  gidx[keep]], axis=1))
            need = n_churn - sum(len(h) for h in have)
            lo += len(gidx)
        ins = np.concatenate(have)[:n_churn]
        hw = splitmix64(_stream_base(_T_CHURN_W, seed)
                        + ins[:, 2].astype(np.uint64))
        ins_w = 1.0 + (hw % np.uint64(8)).astype(np.float64)
        out.append({
            "ins_src": ins[:, 0].copy(), "ins_dst": ins[:, 1].copy(),
            "ins_w": ins_w,
            "del_src": psrc[dsel].copy(), "del_dst": pdst[dsel].copy(),
        })
    return out


def write_churn(out_path: str, graph, *, frac: float, seed: int = 1,
                batches: int = 1) -> dict:
    """Materialize :func:`churn_batches` next to a synthesized Vite
    artifact: ``<out>.churn.npz`` holds the batch arrays
    (``{ins_src,ins_dst,ins_w,del_src,del_dst}_<k>``);
    ``<out>.churn.provenance.json`` records the churn seed/fraction and
    the npz sha256, so the acceptance A/B is reproducible offline."""
    bs = churn_batches(graph, frac=frac, seed=seed, batches=batches)
    npz_path = out_path + ".churn.npz"
    arrays = {}
    for k, b in enumerate(bs):
        for key, arr in b.items():
            arrays[f"{key}_{k}"] = arr
    np.savez(npz_path, **arrays)
    payload = {
        "source": "churn",
        "base": out_path,
        "churn_seed": int(seed),
        "churn_frac": float(frac),
        "batches": int(batches),
        "pairs_deleted_each": int(len(bs[0]["del_src"])),
        "pairs_inserted_each": int(len(bs[0]["ins_src"])),
        "sha256": _sha256_file(npz_path),
        "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    write_provenance(out_path + ".churn", payload)
    return payload


def load_churn(out_path: str) -> list:
    """Read ``<out>.churn.npz`` back into the churn_batches shape."""
    keys = ("ins_src", "ins_dst", "ins_w", "del_src", "del_dst")
    with np.load(out_path + ".churn.npz") as z:
        n = max(int(name.rsplit("_", 1)[1]) for name in z.files) + 1
        return [{k: z[f"{k}_{b}"] for k in keys} for b in range(n)]


def synthesize_many(
    out_prefix: str,
    count: int,
    edges: int,
    seed: int = 1,
    write_truth: bool = True,
    **kw,
) -> dict:
    """K small deterministic power-law graphs in one call (the serving
    bench/test workload): graph k is ``synthesize(...)`` under the
    distinct :func:`many_seed` stream k, written to
    ``<out_prefix>_<k>.vite``; ONE provenance file for the whole set at
    ``<out_prefix>.many.provenance.json`` (each member still gets its
    own, as every Vite artifact does)."""
    count = int(count)
    if count < 1:
        raise ValueError("--many needs a positive graph count")
    members = []
    for k in range(count):
        sk = many_seed(seed, k)
        path = f"{out_prefix}_{k:04d}.vite"
        payload = synthesize(
            path, edges, seed=sk, write_truth=write_truth,
            provenance_extra={"many": {"base_seed": seed, "index": k,
                                       "count": count}},
            **kw)
        members.append({"path": path, "seed": sk,
                        "sha256": payload["sha256"],
                        "result": payload["result"]})
    set_payload = {
        "source": "synthesized-many",
        "count": count,
        "base_seed": seed,
        "edges_each": int(edges),
        "graphs": members,
        "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    write_provenance(out_prefix + ".many", set_payload)
    return set_payload


def synthesize(
    out_path: str,
    edges: int,
    profile: str = "powerlaw",
    seed: int = 1,
    alpha: float = 2.3,
    mu: float = 0.25,
    dmin: int = 2,
    edge_factor: int = 16,
    comm_min: int = 16,
    comm_beta: float = 1.8,
    overlap: float = 0.05,
    bits64: bool = False,
    chunk_edges: int = DEFAULT_CHUNK_EDGES,
    write_truth: bool = True,
    provenance_extra: dict | None = None,
) -> dict:
    """Synthesize a power-law community graph as a Vite file.

    ``edges`` is the target number of DIRECTED records in the file
    (~matching a real dataset's 2x undirected edge count); the realized
    count is slightly lower (self-draws dropped).  Returns the
    provenance payload (also written to ``<out>.provenance.json``).
    """
    if profile not in PROFILES:
        raise ValueError(f"unknown profile {profile!r} "
                         f"(choose from {PROFILES})")
    edges = int(edges)
    if edges < 4:
        raise ValueError("need at least 4 directed edges")
    spec = SynthSpec(profile=profile, edges=edges, seed=seed, alpha=alpha,
                     mu=mu, dmin=dmin, edge_factor=edge_factor,
                     comm_min=comm_min, comm_beta=comm_beta,
                     overlap=overlap, bits64=bits64)
    nv, draws, bounds, sizes = _layout(edges, spec, seed)
    vidx = np.arange(nv, dtype=np.int64)

    stats = edges_to_vite(
        _edge_chunk_stream(nv, draws, bounds, spec, chunk_edges),
        out_path, bits64=bits64, symmetrize=True, num_vertices=nv,
        relabel="none", chunk_edges=chunk_edges, fmt=f"synth:{profile}",
    )

    truth_path = None
    if write_truth:
        truth_path = out_path + ".truth"
        comm_of = np.searchsorted(bounds, vidx, side="right") - 1
        cols = np.stack([vidx + 1, comm_of + 1], axis=1)
        np.savetxt(truth_path, cols, fmt="%d")

    payload = {
        "source": "synthesized",
        "spec": spec.to_dict(),
        "result": stats.to_dict(),
        "num_communities_planted": int(len(sizes)),
        "degree_draw_total": int(draws.sum()),
        "sha256": _sha256_file(out_path),
        "truth_path": truth_path,
        "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if provenance_extra:
        payload.update(provenance_extra)
    write_provenance(out_path, payload)
    return payload
